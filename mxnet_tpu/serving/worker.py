"""Replica worker — one :class:`~.server.Server` behind a socket front.

``python -m mxnet_tpu.serving worker --replica-id r0 --hb-dir POOL/hb``
runs a full serving replica as its own PROCESS: its own bounded queue,
its own PredictorCache, its own hot-reload ``ParamStore`` — the unit the
replica pool (serving/pool.py) multiplexes and the chaos tests SIGKILL.

Contract with the pool (docs/serving.md):

- the worker binds a loopback TCP socket (``--port 0`` picks a free
  one) and publishes the bound port in its heartbeat payload — the
  readiness beacon (``elastic.membership.Heartbeat``) is the ONE
  discovery channel, so the router's view of a replica is exactly what
  the ledger says, uniform across router threads;
- the beacon carries ``ready`` (started, not draining), ``queue_depth``,
  ``params_step`` (current commit root), ``last_batch_age_s``, ``pid``;
- requests arrive as wire frames (serving/wire.py); failures map onto
  the structured serving errors with a ``retryable`` verdict the router
  honors;
- ``drain`` closes admission at the front door (beacon flips to
  not-ready), lets the queue empty under a bounded deadline, and
  reports the residual; ``stop`` shuts the server down and exits 0.

Chaos seam: ``MXNET_TPU_TESTING_SLOW_PREDICT_S=<s>`` installs a
``faults.slow_call("serving_predict", s)`` plan at startup — the
slow-replica shape for hedging/breaker drills, injected in the worker
process where a real slow device would live.

Fleet mode: ``--tenants "a=scale,b=mlp@/ckpt/b"`` runs a multi-tenant
:class:`~.fleet.Fleet` behind the same socket — predict frames carry a
``tenant`` header, failures come back tenant-labeled, and the beacon
advertises the served tenants + their quarantine state so a
tenant-aware router places around a quarantined tenant without ever
touching this process.
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import sys
import threading

import numpy as np

from ..diagnostics.journal import get_journal
from . import wire

__all__ = ["add_worker_args", "cmd_worker"]


def _build_block(model: str, dim: int):
    from ..gluon import nn
    from ..gluon.block import HybridBlock

    if model == "scale":
        class Scale(HybridBlock):
            """y = x * w, scalar weight: shape-agnostic (one program per
            bucket), padding-exact, and the weight VALUE doubles as the
            served checkpoint's fingerprint in chaos tests."""

            def __init__(self, **kwargs):
                super().__init__(**kwargs)
                with self.name_scope():
                    self.w = self.params.get("w", shape=(1,), init="ones")

            def hybrid_forward(self, F, x, w):
                return x * w

        net = Scale()
    elif model == "mlp":
        net = nn.HybridSequential()
        with net.name_scope():
            net.add(nn.Dense(32, activation="relu", in_units=dim))
            net.add(nn.Dense(8, in_units=32))
    else:
        raise ValueError(f"unknown worker model {model!r} "
                         "(scale|mlp)")
    net.initialize()
    return net


def _error_doc(exc, request_header=None) -> dict:
    doc = {"ok": False, "v": wire.PROTOCOL_VERSION,
           "error": type(exc).__name__,
           "retryable": bool(getattr(exc, "retryable", True)),
           "detail": str(exc)[:300]}
    for attr in ("stage", "late_ms", "depth", "limit", "tier",
                 "tenant", "reason", "slots", "queued"):
        v = getattr(exc, attr, None)
        if v is not None:
            doc[attr] = v
    # error frames echo the request's propagated trace context so the
    # router side can correlate a remote failure against its own
    # request root (docs/observability.md distributed tracing)
    trace_ctx = (request_header or {}).get("trace")
    if isinstance(trace_ctx, dict):
        doc["trace"] = trace_ctx
    return doc


def _parse_tenants(spec: str) -> list:
    """``--tenants "a=scale,b=mlp@/ckpt/b"`` → [(name, model, root)].
    ``@root`` is optional; the model is one of the worker models."""
    out = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, rest = part.partition("=")
        if not rest:
            raise ValueError(f"tenant spec {part!r} is not "
                             "name=model[@ckpt_root]")
        model, _, root = rest.partition("@")
        out.append((name.strip(), model.strip(), root.strip() or None))
    if not out:
        raise ValueError(f"--tenants {spec!r} names no tenants")
    return out


class _Front:
    """The socket front door: accept loop + per-connection handlers,
    every wait bounded (accept timeout, per-socket recv timeouts)."""

    def __init__(self, server, args):
        self.server = server
        self.args = args
        self.stop_evt = threading.Event()
        self.draining = False
        self.sock = socket.create_server(("127.0.0.1", args.port))
        self.port = self.sock.getsockname()[1]
        self.sock.settimeout(0.25)

    def beacon(self) -> dict:
        doc = self.server.beacon()
        doc["port"] = self.port
        doc["draining"] = self.draining
        doc["ready"] = bool(doc["ready"]) and not self.draining \
            and not self.stop_evt.is_set()
        return doc

    def run(self):
        while not self.stop_evt.is_set():
            try:
                conn, _addr = self.sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            t = threading.Thread(target=self._handle, args=(conn,),
                                 daemon=True)
            t.start()
        self.sock.close()

    # -- per-connection --------------------------------------------------
    def _handle(self, conn):
        with conn:
            conn.settimeout(10.0)          # header must arrive promptly
            try:
                header, payload = wire.recv_frame(conn)
            except (OSError, wire.WireError):
                return                     # peer vanished: nothing to say
            try:
                self._dispatch(conn, header, payload)
            except (OSError, wire.WireError):
                pass                       # reply path gone: request ends
            except Exception as exc:       # defect, not traffic: journal
                get_journal().crash(exc, where="replica_worker")
                try:
                    wire.send_frame(conn, _error_doc(exc, header))
                except OSError:
                    pass

    def _dispatch(self, conn, header, payload):
        from .batcher import RequestError
        cmd = header.get("cmd")
        if cmd == "predict":
            self._predict(conn, header, payload)
        elif cmd == "decode":
            self._decode(conn, header, payload)
        elif cmd == "drain":
            self.draining = True
            deadline = float(header.get("deadline_s", 20.0))
            residual = _wait_queue_empty(self.server, deadline)
            wire.send_frame(conn, {"ok": True, "residual": residual})
        elif cmd == "resume":
            self.draining = False
            wire.send_frame(conn, {"ok": True})
        elif cmd == "stats":
            st = json.loads(json.dumps(self.server.stats(), default=str))
            wire.send_frame(conn, {"ok": True, "stats": st})
        elif cmd == "ping":
            wire.send_frame(conn, {"ok": True, "pid": os.getpid()})
        elif cmd == "pin":
            # deploy-controller lever: pin/unpin the ParamStore to one
            # step; the worker thread converges the live version at its
            # next loop turn (Server.pin_params)
            step = header.get("step")
            pin = getattr(self.server, "pin_params", None)
            took = bool(pin(step)) if pin is not None else False
            wire.send_frame(conn, {"ok": True, "pinned": took,
                                   "step": step})
        elif cmd == "stop":
            wire.send_frame(conn, {"ok": True})
            self.stop_evt.set()
        else:
            wire.send_frame(conn, _error_doc(
                RequestError(f"unknown command {cmd!r}"), header))

    def _predict(self, conn, header, payload):
        from .batcher import RequestError, ServerStopped
        if self.draining or self.stop_evt.is_set():
            err = ServerStopped("replica draining")
            wire.send_frame(conn, _error_doc(err, header))
            return
        x = np.frombuffer(payload, dtype=header["dtype"]).reshape(
            header["shape"])
        deadline_ms = header.get("deadline_ms")
        budget_s = (deadline_ms / 1000.0 if deadline_ms
                    else self.server.config.result_timeout_s)
        conn.settimeout(budget_s + 10.0)
        # the frame's propagated trace context re-anchors this replica's
        # serving_request root under the router's request span — ONE
        # trace_id across both processes' journals
        parent = wire.extract_parent(header)
        try:
            resp = self.server.submit(x, deadline_ms=deadline_ms,
                                      tenant=header.get("tenant"),
                                      parent=parent)
            out = np.asarray(resp.result(timeout_s=budget_s + 5.0))
        except RequestError as exc:
            wire.send_frame(conn, _error_doc(exc, header))
            return
        if not isinstance(out, np.ndarray):
            err = RequestError("replica model returned a non-array tree; "
                               "the wire protocol ships single arrays")
            err.retryable = False
            wire.send_frame(conn, _error_doc(err, header))
            return
        wire.send_frame(
            conn,
            {"ok": True, "v": wire.PROTOCOL_VERSION,
             "shape": list(out.shape), "dtype": str(out.dtype),
             "params_step": resp.params_step},
            np.ascontiguousarray(out).tobytes())

    def _decode(self, conn, header, payload):
        from .batcher import RequestError, ServerStopped
        if self.draining or self.stop_evt.is_set():
            wire.send_frame(conn, _error_doc(
                ServerStopped("replica draining"), header))
            return
        prompt = np.frombuffer(payload, dtype=np.int32)
        deadline_ms = header.get("deadline_ms")
        budget_s = (deadline_ms / 1000.0 if deadline_ms
                    else self.server.config.result_timeout_s)
        conn.settimeout(budget_s + 10.0)
        try:
            stream = self.server.decode_submit(
                prompt, max_new_tokens=header.get("max_new"),
                deadline_ms=deadline_ms, tenant=header.get("tenant"))
            toks = stream.result(timeout_s=budget_s + 5.0)
        except RequestError as exc:
            wire.send_frame(conn, _error_doc(exc, header))
            return
        out = np.asarray(toks, dtype=np.int32)
        wire.send_frame(
            conn,
            {"ok": True, "v": wire.PROTOCOL_VERSION,
             "generated": int(out.size)},
            np.ascontiguousarray(out).tobytes())


def _wait_queue_empty(server, deadline_s, poll_s=0.02) -> int:
    """Bounded drain wait: poll until the admission queue is empty or
    the deadline expires.  Returns the residual depth (0 = clean)."""
    from .pool import _wait_for
    _wait_for(lambda: server.queue_depth() == 0, deadline_s, poll_s)
    return server.queue_depth()


def add_worker_args(parser) -> None:
    parser.add_argument("--replica-id", required=True)
    parser.add_argument("--hb-dir", required=True,
                        help="pool heartbeat ledger directory")
    parser.add_argument("--heartbeat-s", type=float, default=0.5)
    parser.add_argument("--port", type=int, default=0,
                        help="0 = ephemeral; the bound port is published "
                             "in the heartbeat beacon")
    parser.add_argument("--model", default="scale", help="scale|mlp")
    parser.add_argument("--dim", type=int, default=16)
    parser.add_argument("--ckpt-root", default=None,
                        help="resilience.commit root for hot-reload")
    parser.add_argument("--tenants", default=None,
                        help="run a multi-tenant Fleet instead of a "
                             "single-tenant Server: comma list of "
                             "name=model[@ckpt_root]; requests then "
                             "carry a tenant header and the beacon "
                             "advertises the served tenants")
    parser.add_argument("--max-batch", type=int, default=8)
    parser.add_argument("--window-ms", type=float, default=2.0)
    parser.add_argument("--max-queue", type=int, default=64)
    parser.add_argument("--deadline-ms", type=float, default=2000.0)
    parser.add_argument("--reload-poll-s", type=float, default=0.5)
    parser.add_argument("--pin-step", type=int, default=None,
                        help="pin the ParamStore to this committed step "
                             "at startup (deploy canary/rollback: the "
                             "worker neither advances past nor drifts "
                             "off its assigned version until unpinned)")
    parser.add_argument("--aot-dir", default=None,
                        help="persistent AOT executable-cache root "
                             "(default MXNET_TPU_AOT_CACHE_DIR — the "
                             "pool stamps it into the worker env so "
                             "restarts start warm; docs/serving.md)")
    parser.add_argument("--mesh-axes", default=None,
                        help="tensor-parallel serving mesh axes, e.g. "
                             "'model=-1' or 'batch=2,model=4' (default "
                             "MXNET_TPU_SERVING_MESH; unset = "
                             "single-device)")
    parser.add_argument("--decode-slots", type=int, default=0,
                        help="run a continuous-batching decode engine "
                             "with this many KV slots beside the "
                             "one-shot batcher (0 = off; the engine "
                             "serves the deterministic TinyLM toy)")
    parser.add_argument("--decode-max-len", type=int, default=256,
                        help="decode engine per-slot capacity "
                             "(prompt + generated tokens)")


def cmd_worker(args) -> int:
    from ..elastic.membership import Heartbeat
    from ..observability import flight
    from .reload import ParamStore
    from .server import Server, ServerConfig

    # pod attribution: every span/anchor/flight record this process
    # writes names the replica, even when the worker is launched by
    # hand rather than through ReplicaPool's env stamping
    os.environ.setdefault("MXNET_TPU_REPLICA_ID", str(args.replica_id))
    j = get_journal()
    j.set_phase("replica_worker_setup")
    # flight recorder (MXNET_TPU_TRACE_DIR): bounded span/journal ring
    # dumped on SIGTERM/crash/wedge + flushed periodically, so even a
    # SIGKILLed worker leaves its last-N spans for the postmortem
    recorder = flight.install_from_env()

    slow_s = os.environ.get("MXNET_TPU_TESTING_SLOW_PREDICT_S")
    if slow_s:
        from ..resilience import atomic
        from ..testing import faults
        atomic.set_fault_hook(faults.FaultPlan(
            faults.slow_call("serving_predict", float(slow_s))))

    # --aot-dir beats the inherited env; both default through the
    # ServerConfig field (MXNET_TPU_AOT_CACHE_DIR)
    aot_kw = {"aot_dir": args.aot_dir} if getattr(args, "aot_dir", None) \
        else {}
    # --mesh-axes beats MXNET_TPU_SERVING_MESH (which ServerConfig
    # consults when shard_plan stays None); a bare axes string is
    # promoted to a ShardPlan by the Server
    if getattr(args, "mesh_axes", None):
        aot_kw["shard_plan"] = args.mesh_axes
    if getattr(args, "decode_slots", 0):
        from .decode import DecodeConfig, TinyLM
        aot_kw["decode_model"] = TinyLM(max_len=args.decode_max_len)
        aot_kw["decode"] = DecodeConfig(slots=args.decode_slots)
    if getattr(args, "tenants", None):
        from .fleet import Fleet, FleetConfig
        cfg = FleetConfig(max_batch=args.max_batch,
                          window_ms=args.window_ms,
                          max_queue=args.max_queue,
                          default_deadline_ms=args.deadline_ms,
                          reload_poll_s=args.reload_poll_s, **aot_kw)
        server = Fleet(config=cfg)
        for name, model, root in _parse_tenants(args.tenants):
            server.add_tenant(
                name,
                factory=(lambda m=model: _build_block(m, args.dim)),
                ckpt_root=root)
        server.start()
    else:
        net = _build_block(args.model, args.dim)
        cfg = ServerConfig(max_batch=args.max_batch,
                           window_ms=args.window_ms,
                           max_queue=args.max_queue,
                           default_deadline_ms=args.deadline_ms,
                           reload_poll_s=args.reload_poll_s, **aot_kw)
        store = ParamStore(args.ckpt_root) if args.ckpt_root else None
        if store is not None and getattr(args, "pin_step", None) is not None:
            store.pin_step(args.pin_step)   # before start(): the initial
                                            # force-reload lands on the pin
        server = Server(net, config=cfg, param_store=store).start()

    front = _Front(server, args)
    hb = Heartbeat(args.hb_dir, args.replica_id, args.heartbeat_s,
                   payload=front.beacon, prefix="replica").start()
    j.event("replica_worker_start", replica=args.replica_id,
            port=front.port, model=args.model, pid=os.getpid())

    # a pool-side terminate (restart fallback) should still drain:
    # flip the stop event and let the main loop run the clean shutdown
    signal.signal(signal.SIGTERM,
                  lambda signum, frame: front.stop_evt.set())

    j.set_phase("replica_worker_serve")
    try:
        front.run()
    finally:
        j.set_phase("replica_worker_stop")
        try:
            server.stop(timeout_s=30.0)
        finally:
            hb.stop(resign=True)
        if recorder is not None:
            recorder.stop(dump=True)       # the clean-exit flight dump
        j.event("replica_worker_stop", replica=args.replica_id)
    return 0


if __name__ == "__main__":          # direct module run (pool uses -m ..serving)
    ap = argparse.ArgumentParser()
    add_worker_args(ap)
    sys.exit(cmd_worker(ap.parse_args()))

"""Serving bench CLI: ``python -m mxnet_tpu.serving bench``.

Closed-loop load generator against a small Gluon MLP behind the full
serving stack (bounded admission, dynamic batching, compiled-predictor
cache, deadlines).  Each client thread submits a request, waits for the
response, and immediately submits the next — the closed loop measures
end-to-end capacity, not queue theatre.

Artifact contract (same as bench.py): exactly ONE JSON line on stdout —
``{"metric": "serving_requests_per_sec", "value": ...}`` with latency
percentiles, shed/deadline counters, and the compile-count-vs-grid-bound
proof — plus the same document written atomically to ``--out``
(default ``BENCH_serving.json``).  Failures emit a structured error
line, never a hang: journal breadcrumbs + SIGTERM finalizer ride the
diagnostics journal exactly like bench.py.
"""
from __future__ import annotations

import argparse
import json
import sys
import threading
import time

METRIC = "serving_requests_per_sec"


def _emit(obj: dict) -> None:
    print(json.dumps(obj), flush=True)


def _diagnostic(error: str, detail: str) -> dict:
    return {"metric": METRIC, "value": None, "unit": "req/s",
            "error": error, "detail": detail}


def _build_model(dim):
    from ..gluon import nn
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(32, activation="relu", in_units=dim))
        net.add(nn.Dense(8, in_units=32))
    net.initialize()
    return net


def cmd_bench(args) -> int:
    import numpy as np

    from ..diagnostics import get_journal
    from ..metric import LatencySummary
    from ..resilience.atomic import atomic_write
    from .server import Server, ServerConfig

    j = get_journal()
    j.install_handlers(final_cb=lambda: _emit(_diagnostic(
        "bench_killed", f"killed at phase {j.last_phase!r} before "
        "completion; see stderr journal for breadcrumbs")))
    j.set_phase("serving_bench_setup")
    net = _build_model(args.dim)
    cfg = ServerConfig(max_batch=args.max_batch, max_queue=args.queue,
                       window_ms=args.window_ms,
                       default_deadline_ms=args.deadline_ms)
    server = Server(net, config=cfg)
    server.start()

    client_lat = LatencySummary("client_latency_ms")
    stop_at = time.monotonic() + args.seconds
    ok = [0] * args.clients
    shed = [0] * args.clients
    missed = [0] * args.clients
    errored = [0] * args.clients

    def client(idx):
        from .batcher import (DeadlineExceeded, RequestError,
                              ServerOverloaded)
        rng = np.random.default_rng(idx)
        while time.monotonic() < stop_at:
            x = rng.standard_normal(args.dim).astype(np.float32)
            t0 = time.perf_counter()
            try:
                server.predict(x)
            except ServerOverloaded:
                shed[idx] += 1
                time.sleep(0.002)           # closed-loop backoff
                continue
            except DeadlineExceeded:
                missed[idx] += 1
                continue
            except RequestError as e:
                # predictor failure / stopped server: a dead client
                # thread must show in the artifact, never silently
                # deflate req/s
                errored[idx] += 1
                print(f"serving bench: client {idx}: {e}",
                      file=sys.stderr)
                time.sleep(0.01)
                continue
            client_lat.observe((time.perf_counter() - t0) * 1000.0)
            ok[idx] += 1

    j.set_phase("serving_bench_run")
    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(args.clients)]
    t_start = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=args.seconds + 30)
    elapsed = time.monotonic() - t_start
    j.set_phase("serving_bench_report")
    server.stop(timeout_s=30)

    stats = server.stats()
    total_ok = sum(ok)
    doc = {
        "metric": METRIC,
        "value": round(total_ok / elapsed, 2) if elapsed else None,
        "unit": f"req/s (clients={args.clients}, dim={args.dim}, "
                f"max_batch={args.max_batch})",
        "elapsed_s": round(elapsed, 2),
        "completed": total_ok,
        "client_shed": sum(shed),
        "client_deadline_miss": sum(missed),
        "client_errors": sum(errored),
        "latency_ms": client_lat.summary(),
        "server": stats,
        "compiles": stats["cache"]["misses"],
        "grid_bound": server.grid.grid_bound(),
        "compile_bound_ok":
            stats["cache"]["misses"] <= server.grid.grid_bound(),
    }
    if args.out:
        with atomic_write(args.out, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
        print(f"serving bench: artifact written to {args.out}",
              file=sys.stderr)
    _emit(doc)
    j.mark_clean()
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m mxnet_tpu.serving",
        description="serving subsystem CLI (docs/serving.md)")
    sub = ap.add_subparsers(dest="cmd", required=True)
    b = sub.add_parser("bench", help="closed-loop load generator; ONE "
                                     "JSON line on stdout + --out artifact")
    b.add_argument("--seconds", type=float, default=3.0)
    b.add_argument("--clients", type=int, default=4)
    b.add_argument("--dim", type=int, default=16)
    b.add_argument("--max-batch", type=int, default=8)
    b.add_argument("--queue", type=int, default=64)
    b.add_argument("--window-ms", type=float, default=2.0)
    b.add_argument("--deadline-ms", type=float, default=5000.0)
    b.add_argument("--out", default="BENCH_serving.json",
                   help="artifact path ('' disables the file)")
    b.set_defaults(fn=cmd_bench)
    args = ap.parse_args(argv)
    try:
        return args.fn(args)
    except Exception as e:              # structured line, never a bare crash
        from ..diagnostics import get_journal
        get_journal().crash(e)
        _emit(_diagnostic("bench_crashed", f"{type(e).__name__}: {e}"))
        get_journal().mark_clean()
        return 1


if __name__ == "__main__":
    sys.exit(main())

"""Serving bench CLI: ``python -m mxnet_tpu.serving bench``.

Closed-loop load generator against a small Gluon MLP behind the full
serving stack (bounded admission, dynamic batching, compiled-predictor
cache, deadlines).  Each client thread submits a request, waits for the
response, and immediately submits the next — the closed loop measures
end-to-end capacity, not queue theatre.

Artifact contract (same as bench.py): exactly ONE JSON line on stdout —
``{"metric": "serving_requests_per_sec", "value": ...}`` with latency
percentiles, shed/deadline counters, and the compile-count-vs-grid-bound
proof — plus the same document written atomically to ``--out``
(default ``BENCH_serving.json``).  Failures emit a structured error
line, never a hang: journal breadcrumbs + SIGTERM finalizer ride the
diagnostics journal exactly like bench.py.
"""
from __future__ import annotations

import argparse
import json
import sys
import threading
import time

METRIC = "serving_requests_per_sec"


def _emit(obj: dict) -> None:
    print(json.dumps(obj), flush=True)


def _setup_trace_dir(trace_dir, label):
    """``--trace-dir``: make this bench a traced pod run — journal +
    spans stream to ``<dir>/journal-<label>.jsonl``, the flight
    recorder runs, and subprocess workers (a proc-replica pool) inherit
    the dir through ``MXNET_TPU_TRACE_DIR``.  Returns the recorder (or
    None).  Call BEFORE get_journal() so the handlers bind to the
    run-dir sink."""
    if not trace_dir:
        return None
    import os

    from ..diagnostics.journal import reset_journal
    from ..observability import flight
    from ..observability import trace as obtrace
    os.makedirs(trace_dir, exist_ok=True)
    os.environ["MXNET_TPU_TRACE_DIR"] = str(trace_dir)
    reset_journal(os.path.join(str(trace_dir),
                               f"journal-{label}.jsonl"))
    obtrace.configure(mode="journal")
    return flight.FlightRecorder(str(trace_dir), label=label).install()


def _embed_distributed_trace(doc, trace_dir, recorder):
    """Fold the assembled cross-process snapshot into a BENCH artifact:
    the ``doctor --timeline`` body (per-process span counts, flight
    dumps, the slowest request's cross-process critical path)."""
    if not trace_dir:
        return
    if recorder is not None:
        recorder.stop(dump=True)
    from ..observability import aggregate
    doc["distributed_trace"] = aggregate.timeline_report(str(trace_dir))


def _diagnostic(error: str, detail: str) -> dict:
    return {"metric": METRIC, "value": None, "unit": "req/s",
            "error": error, "detail": detail}


ARRIVAL_FORMAT = "mxtpu-arrival-v1"


def _load_arrival(path):
    """Parse a recorded arrival trace: ``{"format": "mxtpu-arrival-v1",
    "events": [{"dt_ms": float[, "dim": int]}, ...]}``.  Each client
    thread replays the inter-arrival gaps (and per-event feature dims,
    which must match the served model) in order, looping until
    ``--seconds`` expires — the same burst structure every run, so two
    benches under different knobs see identical offered load.  Returns
    ``(events, None)`` or ``(None, reason)`` — a malformed trace is a
    structured bench error, never a crash mid-run."""
    import os
    if not os.path.exists(path):
        return None, f"missing:{path}"
    try:
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return None, f"unparseable:{type(e).__name__}"
    if not isinstance(doc, dict) or doc.get("format") != ARRIVAL_FORMAT:
        return None, f"format:{doc.get('format') if isinstance(doc, dict) else type(doc).__name__}"
    events = doc.get("events")
    if not isinstance(events, list) or not events:
        return None, "no_events"
    out = []
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            return None, f"event:{i}:not_object"
        dt = ev.get("dt_ms")
        if not isinstance(dt, (int, float)) or isinstance(dt, bool) \
                or dt < 0 or dt > 60_000:
            return None, f"event:{i}:dt_ms:{dt!r}"
        dim = ev.get("dim")
        if dim is not None and (not isinstance(dim, int)
                                or isinstance(dim, bool) or dim <= 0):
            return None, f"event:{i}:dim:{dim!r}"
        out.append((float(dt), dim))
    return out, None


def _build_model(dim):
    from ..gluon import nn
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(32, activation="relu", in_units=dim))
        net.add(nn.Dense(8, in_units=32))
    net.initialize()
    return net


def cmd_bench(args) -> int:
    import numpy as np

    from ..diagnostics import get_journal
    from ..metric import LatencySummary
    from ..observability import snapshot
    from ..resilience.atomic import atomic_write
    from .server import Server, ServerConfig

    if getattr(args, "deploy", False):
        return _bench_deploy(args)
    if args.decode > 0:
        return _bench_decode(args)
    if args.tenants > 0:
        return _bench_tenants(args)
    if args.replicas > 1:
        return _bench_pool(args)

    arrival = None
    if args.arrival:
        arrival, why = _load_arrival(args.arrival)
        if arrival is None:
            _emit(_diagnostic("bad_arrival_trace",
                              f"{args.arrival}: {why}"))
            return 1

    recorder = _setup_trace_dir(args.trace_dir, "serving-bench")
    j = get_journal()
    j.install_handlers(final_cb=lambda: _emit(_diagnostic(
        "bench_killed", f"killed at phase {j.last_phase!r} before "
        "completion; see stderr journal for breadcrumbs")))
    j.set_phase("serving_bench_setup")
    net = _build_model(args.dim)
    cfg = ServerConfig(max_batch=args.max_batch, max_queue=args.queue,
                       window_ms=args.window_ms,
                       default_deadline_ms=args.deadline_ms)
    server = Server(net, config=cfg)
    server.start()

    client_lat = LatencySummary("client_latency_ms")
    stop_at = time.monotonic() + args.seconds
    ok = [0] * args.clients
    shed = [0] * args.clients
    missed = [0] * args.clients
    errored = [0] * args.clients

    def client(idx):
        from .batcher import (DeadlineExceeded, RequestError,
                              ServerOverloaded)
        rng = np.random.default_rng(idx)
        pos = idx % len(arrival) if arrival else 0
        while time.monotonic() < stop_at:
            dim = args.dim
            if arrival:
                # replay mode: honor the recorded inter-arrival gap (and
                # per-event dim) instead of the closed loop's immediate
                # resubmit; the trace loops until --seconds expires
                dt_ms, ev_dim = arrival[pos]
                pos = (pos + 1) % len(arrival)
                if ev_dim:
                    dim = ev_dim
                if dt_ms > 0:
                    time.sleep(min(dt_ms / 1000.0,
                                   max(0.0, stop_at - time.monotonic())))
                    if time.monotonic() >= stop_at:
                        break
            x = rng.standard_normal(dim).astype(np.float32)
            t0 = time.perf_counter()
            try:
                server.predict(x)
            except ServerOverloaded:
                shed[idx] += 1
                time.sleep(0.002)           # closed-loop backoff
                continue
            except DeadlineExceeded:
                missed[idx] += 1
                continue
            except RequestError as e:
                # predictor failure / stopped server: a dead client
                # thread must show in the artifact, never silently
                # deflate req/s
                errored[idx] += 1
                print(f"serving bench: client {idx}: {e}",
                      file=sys.stderr)
                time.sleep(0.01)
                continue
            client_lat.observe((time.perf_counter() - t0) * 1000.0)
            ok[idx] += 1

    j.set_phase("serving_bench_run")
    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(args.clients)]
    t_start = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=args.seconds + 30)
    elapsed = time.monotonic() - t_start
    j.set_phase("serving_bench_report")
    server.stop(timeout_s=30)

    stats = server.stats()
    total_ok = sum(ok)
    doc = {
        "metric": METRIC,
        "value": round(total_ok / elapsed, 2) if elapsed else None,
        "unit": f"req/s (clients={args.clients}, dim={args.dim}, "
                f"max_batch={args.max_batch})",
        "elapsed_s": round(elapsed, 2),
        "completed": total_ok,
        "client_shed": sum(shed),
        "client_deadline_miss": sum(missed),
        "client_errors": sum(errored),
        "latency_ms": client_lat.summary(),
        "server": stats,
        "compiles": stats["cache"]["misses"],
        "grid_bound": server.grid.grid_bound(),
        "compile_bound_ok":
            stats["cache"]["misses"] <= server.grid.grid_bound(),
        "observability": snapshot(),
    }
    if arrival:
        doc["arrival"] = {"trace": args.arrival, "events": len(arrival),
                          "mode": "replay"}
    if args.warm_start:
        j.set_phase("serving_bench_warm_start")
        doc["warm_start"] = _warm_start_ab(args)
    _embed_distributed_trace(doc, args.trace_dir, recorder)
    if args.out:
        with atomic_write(args.out, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True, default=str)
        print(f"serving bench: artifact written to {args.out}",
              file=sys.stderr)
    _emit(doc)
    j.mark_clean()
    return 0


def _warm_start_ab(args) -> dict:
    """Cold-vs-warm startup A/B on a fresh AOT cache dir: phase 1
    builds + starts + prewarms a server against an EMPTY store (pays
    the compiles, writes through), phase 2 repeats on the SAME store
    (loads).  Startup ms covers construct → start (incl. prewarm) →
    first response — the operator-visible restart cost; the compile/
    load split comes from ``observability.compile_stats()`` deltas."""
    import os
    import shutil
    import tempfile

    import numpy as np

    from ..observability import compile_stats, reset_metrics
    from .aotcache import AOTCache
    from .server import Server, ServerConfig

    aot_dir = tempfile.mkdtemp(prefix="mxtpu-aot-ab-")
    probe = AOTCache.maybe(aot_dir)
    if probe is None or probe.mode != "rw":
        # the kill switch / ro mode makes the A/B meaningless — report
        # that instead of KeyError-ing mid-phase or measuring a no-op
        shutil.rmtree(aot_dir, ignore_errors=True)
        return {"disabled": True,
                "reason": "MXNET_TPU_AOT_CACHE="
                          f"{os.environ.get('MXNET_TPU_AOT_CACHE')!r} "
                          "(warm-start A/B needs a writable cache)"}
    x = np.ones(args.dim, dtype=np.float32)

    def phase():
        reset_metrics()
        t0 = time.perf_counter()
        net = _build_model(args.dim)
        cfg = ServerConfig(max_batch=args.max_batch,
                           window_ms=args.window_ms,
                           default_deadline_ms=args.deadline_ms,
                           aot_dir=aot_dir,
                           aot_prewarm=((args.dim,),))
        server = Server(net, config=cfg).start()
        server.predict(x)
        ms = round((time.perf_counter() - t0) * 1000.0, 2)
        cs = compile_stats()
        aot = server.stats()["aot"]
        server.stop(timeout_s=30)
        return {"startup_ms": ms, "compiles": cs["compiles"],
                "aot_loads": cs["aot_loads"],
                "aot_load_ms": cs["aot_load_ms"], "cache": aot}

    try:
        cold = phase()
        warm = phase()
    finally:
        shutil.rmtree(aot_dir, ignore_errors=True)
    out = {"cold": cold, "warm": warm,
           "cold_startup_ms": cold["startup_ms"],
           "warm_startup_ms": warm["startup_ms"],
           "warm_zero_compiles": warm["compiles"] == 0}
    if warm["startup_ms"]:
        out["speedup"] = round(cold["startup_ms"] / warm["startup_ms"], 2)
    return out


DECODE_METRIC = "serving_decode_tokens_per_sec"


def _bench_decode(args) -> int:
    """--decode S: closed-loop autoregressive streams against one
    Server's continuous batcher (S decode slots, ``--clients`` stream
    generators, staggered prompt/generation lengths).  The artifact
    (BENCH_serving_decode.json) carries tokens/s, the decode journal
    reduction (steps/s, slot-occupancy histogram) and the zero-mid-run-
    compile proof: after warmup, ``counters["compiles"]`` must not move
    (docs/serving.md continuous batching)."""
    import numpy as np   # noqa: F401  (parity with siblings)

    from ..diagnostics import get_journal
    from ..metric import LatencySummary
    from ..resilience.atomic import atomic_write
    from .batcher import (DeadlineExceeded, RequestError, ServerOverloaded,
                          SlotsExhausted)
    from .decode import DecodeConfig, TinyLM
    from .server import Server, ServerConfig

    j = get_journal()
    j.install_handlers(final_cb=lambda: _emit(
        {"metric": DECODE_METRIC, "value": None, "unit": "tok/s",
         "error": "bench_killed",
         "detail": f"killed at phase {j.last_phase!r}"}))
    j.set_phase("serving_decode_bench_setup")
    model = TinyLM()
    cfg = ServerConfig(
        max_batch=args.max_batch, max_queue=args.queue,
        window_ms=args.window_ms,
        default_deadline_ms=args.deadline_ms,
        decode_model=model,
        decode=DecodeConfig(slots=args.decode,
                            default_deadline_ms=args.deadline_ms))
    server = Server(_build_model(args.dim), config=cfg)
    server.start()
    compiles_at_ready = server.decoder.counters["compiles"]

    stream_lat = LatencySummary("stream_latency_ms")
    stop_at = time.monotonic() + args.seconds
    ok = [0] * args.clients
    toks = [0] * args.clients
    shed = [0] * args.clients
    missed = [0] * args.clients
    errored = [0] * args.clients
    corrupt = []

    def client(idx):
        import numpy as np
        rng = np.random.default_rng(idx)
        while time.monotonic() < stop_at:
            # staggered lengths: prompts 1..16, generations 4..32
            prompt = [int(t) for t in
                      rng.integers(0, model.vocab,
                                   size=int(rng.integers(1, 17)))]
            n = int(rng.integers(4, 33))
            t0 = time.perf_counter()
            try:
                got = server.decode(prompt, max_new_tokens=n)
            except (ServerOverloaded, SlotsExhausted):
                shed[idx] += 1
                time.sleep(0.002)
                continue
            except DeadlineExceeded:
                missed[idx] += 1
                continue
            except RequestError as e:
                errored[idx] += 1
                print(f"decode bench: client {idx}: {e}",
                      file=sys.stderr)
                time.sleep(0.01)
                continue
            if list(got) != model.reference(prompt, n):
                corrupt.append(prompt)    # bit-exactness is the contract
            stream_lat.observe((time.perf_counter() - t0) * 1000.0)
            ok[idx] += 1
            toks[idx] += len(got)

    j.set_phase("serving_decode_bench_run")
    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(args.clients)]
    t_start = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=args.seconds + 30)
    elapsed = time.monotonic() - t_start
    j.set_phase("serving_decode_bench_report")
    dstats = server.decoder.stats()
    server.stop(timeout_s=30)

    total_tok = sum(toks)
    doc = {
        "metric": DECODE_METRIC,
        "value": round(total_tok / elapsed, 2) if elapsed else None,
        "unit": f"tok/s (slots={args.decode}, clients={args.clients})",
        "elapsed_s": round(elapsed, 2),
        "streams_completed": sum(ok),
        "tokens_out": total_tok,
        "client_shed": sum(shed),
        "client_deadline_miss": sum(missed),
        "client_errors": sum(errored),
        "corrupt_streams": len(corrupt),
        "stream_latency_ms": stream_lat.summary(),
        "decode": dstats,
        "compiles_after_warmup":
            dstats["compiles"] - compiles_at_ready,
        "compile_bound_ok": dstats["compiles"] == compiles_at_ready,
    }
    out = args.out or ""
    if out:
        with atomic_write(out, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True, default=str)
        print(f"decode bench: artifact written to {out}",
              file=sys.stderr)
    _emit(doc)
    j.mark_clean()
    # corrupt output or a mid-run compile is a failed bench, not a
    # slower one — the exit code is the gate
    return 0 if not corrupt and doc["compile_bound_ok"] else 1


TENANT_METRIC = "serving_tenant_requests_per_sec"


def _bench_tenants(args) -> int:
    """--tenants N: closed-loop mixed-tenant load against one Fleet —
    N tenants on one worker/queue/cache, clients spread round-robin.
    The artifact (BENCH_serving_tenants.json) carries per-tenant
    p50/p95/p99, shed/quarantine/page-in counters and the observability
    snapshot — the capacity-and-isolation profile of multi-tenant
    serving (docs/serving.md)."""
    import numpy as np

    from ..diagnostics import get_journal
    from ..metric import LatencySummary
    from ..observability import snapshot
    from ..resilience.atomic import atomic_write
    from .batcher import (DeadlineExceeded, RequestError, ServerOverloaded)
    from .fleet import Fleet, FleetConfig

    recorder = _setup_trace_dir(args.trace_dir, "tenant-bench")
    j = get_journal()
    j.install_handlers(final_cb=lambda: _emit(
        {"metric": TENANT_METRIC, "value": None, "unit": "req/s",
         "error": "bench_killed",
         "detail": f"killed at phase {j.last_phase!r}"}))
    j.set_phase("serving_tenant_bench_setup")
    cfg = FleetConfig(max_batch=args.max_batch, max_queue=args.queue,
                      window_ms=args.window_ms,
                      default_deadline_ms=args.deadline_ms)
    fleet = Fleet(cfg)
    names = [f"t{i}" for i in range(args.tenants)]
    for name in names:
        fleet.add_tenant(name,
                         factory=(lambda: _build_model(args.dim)))
    fleet.start()

    client_lat = {n: LatencySummary(f"client_{n}_ms") for n in names}
    stop_at = time.monotonic() + args.seconds
    ok = [0] * args.clients
    shed = [0] * args.clients
    missed = [0] * args.clients
    errored = [0] * args.clients

    def client(idx):
        tenant = names[idx % len(names)]
        rng = np.random.default_rng(idx)
        while time.monotonic() < stop_at:
            x = rng.standard_normal(args.dim).astype(np.float32)
            t0 = time.perf_counter()
            try:
                fleet.predict(x, tenant=tenant)
            except ServerOverloaded:
                shed[idx] += 1
                time.sleep(0.002)
                continue
            except DeadlineExceeded:
                missed[idx] += 1
                continue
            except RequestError as e:
                errored[idx] += 1
                print(f"tenant bench: client {idx} ({tenant}): {e}",
                      file=sys.stderr)
                time.sleep(0.01)
                continue
            client_lat[tenant].observe(
                (time.perf_counter() - t0) * 1000.0)
            ok[idx] += 1

    j.set_phase("serving_tenant_bench_run")
    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(args.clients)]
    t_start = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=args.seconds + 30)
    elapsed = time.monotonic() - t_start
    j.set_phase("serving_tenant_bench_report")
    fleet.stop(timeout_s=30)

    stats = fleet.stats()
    total_ok = sum(ok)
    per_tenant = {}
    for name in names:
        row = stats["tenants"][name]
        per_tenant[name] = {
            "served": row["served"], "shed": row["shed"],
            "quarantines": row["quarantines"],
            "readmissions": row["readmissions"],
            "page_ins": row["page_ins"],
            "p50_ms": row["latency_ms"]["p50"],
            "p95_ms": row["latency_ms"]["p95"],
            "p99_ms": row["latency_ms"]["p99"],
            "client_latency_ms": client_lat[name].summary()}
    doc = {
        "metric": TENANT_METRIC,
        "value": round(total_ok / elapsed, 2) if elapsed else None,
        "unit": f"req/s (tenants={args.tenants}, "
                f"clients={args.clients}, dim={args.dim})",
        "elapsed_s": round(elapsed, 2),
        "completed": total_ok,
        "client_shed": sum(shed),
        "client_deadline_miss": sum(missed),
        "client_errors": sum(errored),
        "tenants": per_tenant,
        "server": {k: v for k, v in stats.items() if k != "tenants"},
        "compiles": stats["cache"]["misses"],
        "observability": snapshot(),
    }
    _embed_distributed_trace(doc, args.trace_dir, recorder)
    out = args.out or ""
    if out:
        with atomic_write(out, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True, default=str)
        print(f"tenant bench: artifact written to {out}",
              file=sys.stderr)
    _emit(doc)
    j.mark_clean()
    return 0


POOL_METRIC = "serving_pool_requests_per_sec"


def _bench_pool(args) -> int:
    """--replicas N: the closed loop runs through the health-routed
    front door (Router over a ReplicaPool of N in-process replicas),
    and the artifact carries the router attempt/hedge/breaker counters
    plus the observability snapshot — BENCH_serving_pool.json."""
    import tempfile

    import numpy as np

    from ..diagnostics import get_journal
    from ..metric import LatencySummary
    from ..observability import snapshot
    from ..resilience.atomic import atomic_write
    from .batcher import (DeadlineExceeded, RequestError, ServerOverloaded)
    from .pool import PoolConfig, ReplicaPool
    from .router import Router, RouterConfig
    from .server import Server, ServerConfig

    recorder = _setup_trace_dir(args.trace_dir, "router-bench")
    j = get_journal()
    j.install_handlers(final_cb=lambda: _emit(
        {"metric": POOL_METRIC, "value": None, "unit": "req/s",
         "error": "bench_killed",
         "detail": f"killed at phase {j.last_phase!r}"}))
    j.set_phase("serving_pool_bench_setup")
    scfg = ServerConfig(max_batch=args.max_batch, max_queue=args.queue,
                        window_ms=args.window_ms,
                        default_deadline_ms=args.deadline_ms)

    def factory():
        return Server(_build_model(args.dim), config=scfg)

    root = tempfile.mkdtemp(prefix="mxtpu-pool-bench-")
    pool = ReplicaPool(root, PoolConfig(heartbeat_s=0.2, deadline_s=1.5))
    for i in range(args.replicas):
        pool.add_local(f"r{i}", factory)
    pool.start()
    router = Router(pool, RouterConfig(
        hedge_ms=args.hedge_ms, default_deadline_ms=args.deadline_ms))

    client_lat = LatencySummary("client_latency_ms")
    stop_at = time.monotonic() + args.seconds
    ok = [0] * args.clients
    shed = [0] * args.clients
    missed = [0] * args.clients
    errored = [0] * args.clients

    def client(idx):
        rng = np.random.default_rng(idx)
        while time.monotonic() < stop_at:
            x = rng.standard_normal(args.dim).astype(np.float32)
            t0 = time.perf_counter()
            try:
                router.predict(x)
            except ServerOverloaded:
                shed[idx] += 1
                time.sleep(0.002)
                continue
            except DeadlineExceeded:
                missed[idx] += 1
                continue
            except RequestError as e:
                errored[idx] += 1
                print(f"pool bench: client {idx}: {e}", file=sys.stderr)
                time.sleep(0.01)
                continue
            client_lat.observe((time.perf_counter() - t0) * 1000.0)
            ok[idx] += 1

    j.set_phase("serving_pool_bench_run")
    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(args.clients)]
    t_start = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=args.seconds + 30)
    elapsed = time.monotonic() - t_start
    j.set_phase("serving_pool_bench_report")
    router_stats = router.stats()
    pool_view = [vars(s) for s in pool.view()]   # BEFORE stop: beacons
    router.stop()                                # resign at shutdown
    pool.stop()

    total_ok = sum(ok)
    doc = {
        "metric": POOL_METRIC,
        "value": round(total_ok / elapsed, 2) if elapsed else None,
        "unit": f"req/s (replicas={args.replicas}, "
                f"clients={args.clients}, dim={args.dim})",
        "elapsed_s": round(elapsed, 2),
        "completed": total_ok,
        "client_shed": sum(shed),
        "client_deadline_miss": sum(missed),
        "client_errors": sum(errored),
        "latency_ms": client_lat.summary(),
        "router": router_stats,
        "pool": pool_view,
        "observability": snapshot(),
    }
    _embed_distributed_trace(doc, args.trace_dir, recorder)
    out = args.out or ""
    if out:
        with atomic_write(out, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True, default=str)
        print(f"pool bench: artifact written to {out}", file=sys.stderr)
    _emit(doc)
    j.mark_clean()
    return 0


DEPLOY_METRIC = "serving_deploy_rollback_ms"


def _bench_deploy(args) -> int:
    """--deploy: canary-gated deployment drill under closed-loop load —
    one GOOD deploy (identical weights recommitted: parity mirrors
    agree, gates pass, promote) and one BAD deploy (regress_params-
    poisoned step: parity gate trips, auto-rollback), with every
    response's version stamp checked against its value.  The artifact
    (BENCH_serving_deploy.json) carries gate-eval and rollback counters;
    the exit code is the gate: nonzero when the good deploy failed to
    promote, the bad deploy failed to roll back, or ANY response's
    value contradicted its stamp."""
    import os
    import tempfile

    import numpy as np

    from .. import nd
    from ..diagnostics import get_journal
    from ..resilience import commit
    from ..resilience.atomic import atomic_write
    from ..testing import faults
    from .batcher import (DeadlineExceeded, RequestError, ServerOverloaded)
    from .deploy import DeployConfig, DeployController
    from .pool import PoolConfig, ReplicaPool
    from .reload import ParamStore
    from .router import Router, RouterConfig
    from .server import Server, ServerConfig

    j = get_journal()
    j.install_handlers(final_cb=lambda: _emit(
        {"metric": DEPLOY_METRIC, "value": None, "unit": "ms",
         "error": "bench_killed",
         "detail": f"killed at phase {j.last_phase!r}"}))
    j.set_phase("serving_deploy_bench_setup")

    from ..gluon.block import HybridBlock

    class Scale(HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.w = self.params.get("w", shape=(1,), init="ones")

        def hybrid_forward(self, F, x, w):
            return x * w

    def commit_scale(root, step, value):
        stage = commit.prepare_stage(root, step)
        nd.save(os.path.join(stage, "net.params"),
                {"w": nd.array(np.asarray([value], np.float32))})
        return commit.finalize(root, step)

    ck = tempfile.mkdtemp(prefix="mxtpu-deploy-bench-ckpt-")
    commit_scale(ck, 1, 3.0)
    scfg = ServerConfig(max_batch=args.max_batch, max_queue=args.queue,
                        window_ms=args.window_ms,
                        default_deadline_ms=args.deadline_ms)

    def factory():
        net = Scale()
        net.initialize()
        return Server(net, config=scfg, param_store=ParamStore(ck))

    n = max(args.replicas, 3)
    root = tempfile.mkdtemp(prefix="mxtpu-deploy-bench-")
    pool = ReplicaPool(root, PoolConfig(heartbeat_s=0.2, deadline_s=1.5))
    for i in range(n):
        pool.add_local(f"r{i}", factory)
    pool.start()
    router = Router(pool, RouterConfig(
        default_deadline_ms=args.deadline_ms))
    base_deadline = time.monotonic() + 30.0
    while time.monotonic() < base_deadline:      # baseline adoption
        if all(s.params_step == 1 for s in pool.view()):
            break
        time.sleep(0.05)
    else:
        _emit({"metric": DEPLOY_METRIC, "value": None, "unit": "ms",
               "error": "baseline_never_adopted",
               "detail": "replicas never converged on step 1"})
        return 1

    # every response's value must match its version stamp's weight —
    # a stamped-3 answer computed with w=3's weights is the one
    # corruption class a canary may NEVER leak
    w_by_step = {None: 1.0, 1: 3.0, 2: 3.0, 3: 30.0}
    stop = threading.Event()
    ok = [0] * args.clients
    shed = [0] * args.clients
    errored = [0] * args.clients
    corrupt = [0] * args.clients
    stamps = [dict() for _ in range(args.clients)]

    def client(idx):
        rng = np.random.default_rng(idx)
        while not stop.is_set():
            x = rng.standard_normal(args.dim).astype(np.float32)
            try:
                resp = router.call(x)     # RouterResponse: value + stamp
            except (ServerOverloaded, DeadlineExceeded):
                shed[idx] += 1
                time.sleep(0.002)
                continue
            except RequestError:
                errored[idx] += 1
                time.sleep(0.01)
                continue
            st = resp.params_step
            want = x * w_by_step.get(st, float("nan"))
            got = resp.value
            got = got.asnumpy() if hasattr(got, "asnumpy") else got
            if not np.allclose(np.asarray(got).ravel(), want,
                               rtol=1e-4, atol=1e-5):
                corrupt[idx] += 1
            stamps[idx][st] = stamps[idx].get(st, 0) + 1
            ok[idx] += 1

    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(args.clients)]
    t_start = time.monotonic()
    for t in threads:
        t.start()

    dcfg = DeployConfig(canary_k=1, window_s=0.4, promote_after=2,
                        min_samples=5, mirror_fraction=0.25,
                        rollback_s=15.0, deadline_s=30.0)
    ctl = DeployController(pool, router, ck, dcfg)

    j.set_phase("serving_deploy_bench_good")
    commit_scale(ck, 2, 3.0)          # same weights: parity must agree
    good = ctl.deploy(2)

    j.set_phase("serving_deploy_bench_bad")
    commit_scale(ck, 3, 3.0)
    faults.regress_params(ck, 3, scale=10.0)   # CRC-valid, wrong answers
    bad = ctl.deploy(3)

    j.set_phase("serving_deploy_bench_report")
    time.sleep(0.5)                   # post-rollback traffic window
    stop.set()
    for t in threads:
        t.join(timeout=30)
    elapsed = time.monotonic() - t_start
    router.stop()
    pool.stop()

    merged = {}
    for d in stamps:
        for k, v in d.items():
            merged[k] = merged.get(k, 0) + v
    total_corrupt = sum(corrupt)
    passed = (good.get("result") == "promoted"
              and bad.get("result") == "rolled_back"
              and total_corrupt == 0)
    doc = {
        "metric": DEPLOY_METRIC,
        "value": bad.get("rollback_ms"),
        "unit": f"ms (replicas={n}, clients={args.clients}, "
                f"canary_k={dcfg.canary_k})",
        "elapsed_s": round(elapsed, 2),
        "completed": sum(ok),
        "client_shed": sum(shed),
        "client_errors": sum(errored),
        "corrupt_responses": total_corrupt,
        "responses_by_step": {str(k): v for k, v in merged.items()},
        "good_deploy": good,
        "bad_deploy": bad,
        "gate_evals": (good.get("gate_evals", 0)
                       + bad.get("gate_evals", 0)),
        "rollbacks": int(bad.get("result") == "rolled_back"),
        "promotions": int(good.get("result") == "promoted"),
        "rollback_reason": bad.get("reason"),
        "passed": passed,
    }
    out = args.out or ""
    if out:
        with atomic_write(out, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True, default=str)
        print(f"deploy bench: artifact written to {out}", file=sys.stderr)
    _emit(doc)
    j.mark_clean()
    return 0 if passed else 1


WARM_METRIC = "aot_warm_entries"


def _parse_shapes(spec: str) -> tuple:
    """``"16"`` / ``"8x128,8x256"`` → feature shapes (no batch axis)."""
    shapes = []
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        try:
            shapes.append(tuple(int(d) for d in part.split("x")))
        except ValueError:
            raise ValueError(f"bad --shapes entry {part!r}: expected "
                             "comma-separated DxDx... ints") from None
    if not shapes:
        raise ValueError(f"--shapes {spec!r} names no shapes")
    return tuple(shapes)


def cmd_warm(args) -> int:
    """``warm --dir ROOT``: offline prewarm — compile + persist a
    model's bucket lattice ahead of deploy, so the FIRST serving start
    on that cache dir is already warm.  Emits one JSON line (entry
    counts, loaded/compiled split, directory audit) and exits 0 on a
    fully-warmed lattice."""
    from ..diagnostics import get_journal
    from . import aot_report
    from .server import Server, ServerConfig
    from .worker import _build_block

    j = get_journal()
    j.install_handlers(final_cb=lambda: _emit(_diagnostic(
        "warm_killed", f"killed at phase {j.last_phase!r}")))
    j.set_phase("aot_warm_setup")
    shapes = _parse_shapes(args.shapes if args.shapes is not None
                           else str(args.dim))
    net = _build_block(args.model, args.dim)
    cfg = ServerConfig(max_batch=args.max_batch, aot_dir=args.dir)
    server = Server(net, config=cfg)     # never started: no worker, no
    # fail BEFORE the lattice compile: warming with the cache switched
    # off (or read-only) would pay every compile and persist nothing —
    # a deploy that trusts the exit code would then start cold
    if server.aot is None or server.aot.mode != "rw":
        mode = None if server.aot is None else server.aot.mode
        _emit(_diagnostic(
            "aot_cache_not_writable",
            f"MXNET_TPU_AOT_CACHE mode {mode!r} — `warm` needs a "
            "writable cache; nothing would be persisted"))
        j.mark_clean()
        return 1
    j.set_phase("aot_warm_run")          # traffic — just the lattice
    res = server.prewarm(shapes)
    j.set_phase("aot_warm_report")
    aot_stats = server.aot.stats()
    doc = {"metric": WARM_METRIC,
           "value": res["warmed"],
           "unit": f"entries (model={args.model}, dim={args.dim}, "
                   f"shapes={[list(s) for s in shapes]})",
           **res,
           "aot": aot_stats,
           "dir_report": aot_report.aot_report(args.dir)}
    _emit(doc)
    j.mark_clean()
    # the exit code is the deploy gate: a backend that cannot serialize
    # its executables compiles the lattice but persists NOTHING
    # (journaled aot_store_failed) — that must not read as warmed
    if aot_stats["store_failures"] > 0:
        print(f"warm: {aot_stats['store_failures']} store(s) failed — "
              "the cache dir is NOT fully seeded (see aot_store_failed "
              "journal records)", file=sys.stderr)
        return 1
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m mxnet_tpu.serving",
        description="serving subsystem CLI (docs/serving.md)")
    sub = ap.add_subparsers(dest="cmd", required=True)
    b = sub.add_parser("bench", help="closed-loop load generator; ONE "
                                     "JSON line on stdout + --out artifact")
    b.add_argument("--seconds", type=float, default=3.0)
    b.add_argument("--clients", type=int, default=4)
    b.add_argument("--dim", type=int, default=16)
    b.add_argument("--max-batch", type=int, default=8)
    b.add_argument("--queue", type=int, default=64)
    b.add_argument("--window-ms", type=float, default=2.0)
    b.add_argument("--deadline-ms", type=float, default=5000.0)
    b.add_argument("--replicas", type=int, default=1,
                   help="> 1 routes the closed loop through a Router "
                        "over N in-process replicas and writes the "
                        "BENCH_serving_pool artifact")
    b.add_argument("--tenants", type=int, default=0,
                   help="> 0 runs the closed loop as mixed-tenant load "
                        "against one Fleet of N tenants and writes the "
                        "BENCH_serving_tenants artifact (per-tenant "
                        "p99/shed/quarantine counters)")
    b.add_argument("--decode", type=int, default=0,
                   help="> 0 runs the closed loop as autoregressive "
                        "decode streams against one Server's continuous "
                        "batcher with N slots and writes the "
                        "BENCH_serving_decode artifact (tokens/s, "
                        "occupancy, zero-mid-run-compile proof)")
    b.add_argument("--deploy", action="store_true",
                   help="run the canary-gated deployment drill instead "
                        "of the raw closed loop: one good deploy "
                        "(promote) + one regress_params-poisoned deploy "
                        "(parity gate trips, auto-rollback) under load, "
                        "with stamp-vs-value corruption checks; writes "
                        "BENCH_serving_deploy.json and exits nonzero "
                        "when any gate outcome or response is wrong")
    b.add_argument("--arrival", default=None,
                   help="replay a recorded arrival trace (JSON: "
                        "{'format': 'mxtpu-arrival-v1', 'events': "
                        "[{'dt_ms': F[, 'dim': N]}, ...]}) instead of "
                        "the closed loop's immediate resubmit: each "
                        "client honors the recorded inter-arrival gaps "
                        "in order, looping until --seconds expires — "
                        "identical offered load across A/B runs "
                        "(benchmarks/arrival_smoke.json)")
    b.add_argument("--hedge-ms", type=float, default=0.0,
                   help="tail-latency hedge delay for --replicas mode "
                        "(0 = off)")
    b.add_argument("--trace-dir", default=None,
                   help="run the bench as a traced pod run: spans + "
                        "journal stream into this directory, the "
                        "flight recorder runs, and the artifact embeds "
                        "the assembled cross-process snapshot "
                        "(doctor --timeline body) under "
                        "'distributed_trace'")
    b.add_argument("--warm-start", action="store_true",
                   help="run a cold-vs-warm startup A/B on a fresh AOT "
                        "cache dir after the closed loop and embed "
                        "cold/warm startup ms + the zero-compile proof "
                        "under 'warm_start' in the artifact "
                        "(docs/serving.md AOT cache)")
    b.add_argument("--out", default=None,
                   help="artifact path ('' disables; default "
                        "BENCH_serving.json, BENCH_serving_pool.json "
                        "with --replicas > 1, or "
                        "BENCH_serving_tenants.json with --tenants)")
    b.set_defaults(fn=cmd_bench)
    wm = sub.add_parser(
        "warm", help="offline prewarm: compile + persist a model's "
                     "bucket lattice into an AOT cache dir ahead of "
                     "deploy; ONE JSON line on stdout (docs/serving.md)")
    wm.add_argument("--dir", required=True,
                    help="AOT cache root (MXNET_TPU_AOT_CACHE_DIR of "
                         "the serving processes that should start warm)")
    wm.add_argument("--model", default="mlp", help="scale|mlp (the "
                    "worker model zoo; serving/worker.py)")
    wm.add_argument("--dim", type=int, default=16)
    wm.add_argument("--max-batch", type=int, default=8)
    wm.add_argument("--shapes", default=None,
                    help="comma-separated feature shapes to warm, each "
                         "DxDx... (no batch axis; default the model "
                         "--dim)")
    wm.set_defaults(fn=cmd_warm)
    w = sub.add_parser("worker", help="replica worker process behind a "
                                      "loopback socket (serving/pool.py "
                                      "spawns these; docs/serving.md)")
    from .worker import add_worker_args, cmd_worker
    add_worker_args(w)
    w.set_defaults(fn=cmd_worker)
    args = ap.parse_args(argv)
    if getattr(args, "out", None) is None and args.cmd == "bench":
        args.out = ("BENCH_serving_deploy.json" if args.deploy
                    else "BENCH_serving_decode.json" if args.decode > 0
                    else "BENCH_serving_tenants.json" if args.tenants > 0
                    else "BENCH_serving_pool.json" if args.replicas > 1
                    else "BENCH_serving.json")
    try:
        return args.fn(args)
    except Exception as e:              # structured line, never a bare crash
        from ..diagnostics import get_journal
        get_journal().crash(e)
        _emit(_diagnostic("bench_crashed", f"{type(e).__name__}: {e}"))
        get_journal().mark_clean()
        return 1


if __name__ == "__main__":
    sys.exit(main())

"""Hot-reload source: newest *valid* committed checkpoint step.

A trainer publishes checkpoints through the directory commit protocol
(``resilience.commit``: stage → CRC manifest → one rename —
docs/checkpointing.md); the server polls the same root from the other
side.  :class:`ParamStore` hands the serving worker a parameter dict
from the newest committed step that passes CRC validation AND loads
cleanly — a producer SIGTERM'd mid-commit leaves either an invisible
``step-N.tmp`` stage or a manifest that fails validation, so a torn
checkpoint can never reach a response.  Every skipped candidate is
journaled (``ckpt_fallback``), and steps that validated but failed to
parse are remembered so one bad step can't wedge the poll loop.

The dict is applied between batches by ``Server._maybe_reload`` via
``Block.load_dict`` — parameters are runtime arguments to the compiled
predictors (serving/cache.py), so a swap retraces nothing and in-flight
requests simply ride whichever version their batch started with.
"""
from __future__ import annotations

import os

from ..base import MXNetError
from ..diagnostics.journal import get_journal
from ..resilience import commit as _commit

__all__ = ["ParamStore"]


class ParamStore:
    """Poll a commit-protocol checkpoint root for fresh parameters.

    ``params_file``: name of the parameter file inside a committed step
    dir; default picks the first ``*.params`` manifest entry (a
    ``Block.save_parameters`` or ``HybridBlock.export`` artifact —
    ``arg:``/``aux:`` prefixes are handled by ``load_dict``).
    """

    def __init__(self, root, params_file=None):
        self.root = root
        self.params_file = params_file
        self.loaded_step = None
        self._bad_steps = set()

    def _pick_file(self, step, manifest):
        if self.params_file is not None:
            if self.params_file not in manifest["files"]:
                raise MXNetError(
                    f"step {step}: manifest has no {self.params_file!r} "
                    f"(files: {sorted(manifest['files'])})")
            return self.params_file
        for name in sorted(manifest["files"]):
            if name.endswith(".params"):
                return name
        raise MXNetError(f"step {step}: no .params file in manifest "
                         f"(files: {sorted(manifest['files'])})")

    def poll(self):
        """Return ``(step, name→NDArray dict)`` when a step newer than
        the loaded one is available and intact, else None.  Corrupt or
        unparseable candidates are journaled and skipped — never served,
        never fatal."""
        from .. import ndarray as nd
        for step in sorted(_commit.committed_steps(self.root), reverse=True):
            if self.loaded_step is not None and step <= self.loaded_step:
                return None          # newest usable is already serving
            if step in self._bad_steps:
                continue
            try:
                manifest = _commit.validate_step(self.root, step)
                fname = self._pick_file(step, manifest)
                loaded = nd.load(
                    os.path.join(_commit.step_dir(self.root, step), fname))
                if not isinstance(loaded, dict):
                    raise MXNetError(f"{fname} is not a parameter dict")
            except (ValueError, MXNetError, OSError) as e:
                # ValueError: torn/corrupt per the manifest CRCs;
                # MXNetError: container-level CRC/truncation from nd.load;
                # OSError: the step dir raced a trainer's keep-last-k GC
                # between listing and read — gone is just another skip
                self._bad_steps.add(step)
                get_journal().event(
                    "ckpt_fallback", root=self.root, step=step,
                    consumer="serving", error=type(e).__name__,
                    detail=str(e)[:300])
                continue
            self.loaded_step = step
            return step, loaded
        return None

    def mark_bad(self, step, revert_to=None):
        """Remember ``step`` as unusable and roll ``loaded_step`` back
        to ``revert_to`` — the server's hook for a checkpoint that
        validated but failed to APPLY (architecture drift), keeping the
        bad-step bookkeeping in one place."""
        self._bad_steps.add(step)
        self.loaded_step = revert_to

"""Hot-reload source: newest *valid* committed checkpoint step.

A trainer publishes checkpoints through the directory commit protocol
(``resilience.commit``: stage → CRC manifest → one rename —
docs/checkpointing.md); the server polls the same root from the other
side.  :class:`ParamStore` hands the serving worker a parameter dict
from the newest committed step that passes CRC validation AND loads
cleanly — a producer SIGTERM'd mid-commit leaves either an invisible
``step-N.tmp`` stage or a manifest that fails validation, so a torn
checkpoint can never reach a response.  Every skipped candidate is
journaled (``ckpt_fallback``), and steps that validated but failed to
parse are remembered so one bad step can't wedge the poll loop.

The dict is applied between batches by ``Server._maybe_reload`` via
``Block.load_dict`` — parameters are runtime arguments to the compiled
predictors (serving/cache.py), so a swap retraces nothing and in-flight
requests simply ride whichever version their batch started with.
"""
from __future__ import annotations

import os
from collections import OrderedDict

from ..base import MXNetError
from ..diagnostics.journal import get_journal
from ..resilience import commit as _commit

__all__ = ["ParamStore"]


def _env_int(name, default):
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


class ParamStore:
    """Poll a commit-protocol checkpoint root for fresh parameters.

    ``params_file``: name of the parameter file inside a committed step
    dir; default picks the first ``*.params`` manifest entry (a
    ``Block.save_parameters`` or ``HybridBlock.export`` artifact —
    ``arg:``/``aux:`` prefixes are handled by ``load_dict``).

    The remembered bad-step set is an LRU bounded by ``max_bad_steps``
    (``MXNET_TPU_SERVING_BAD_STEPS_CAP``, default 64): a long-lived
    server polling a churning commit root must not grow host memory
    one entry per corrupt candidate forever.  Evicting a remembered
    step only costs a re-validation (journaled ``ckpt_fallback`` again)
    if that step ever resurfaces as a candidate."""

    def __init__(self, root, params_file=None, max_bad_steps=None):
        self.root = root
        self.params_file = params_file
        self.loaded_step = None
        self.corrupt_seen = 0          # lifetime count of NEW bad steps
                                       # (the fleet's per-tenant breaker
                                       # reads the delta after poll())
        self.pinned_step = None        # deploy pin: poll() never advances
                                       # past this step while set
        self._bad_steps = OrderedDict()        # step -> None, LRU order
        self._bad_cap = max(int(
            _env_int("MXNET_TPU_SERVING_BAD_STEPS_CAP", 64)
            if max_bad_steps is None else max_bad_steps), 1)

    def _pick_file(self, step, manifest):
        if self.params_file is not None:
            if self.params_file not in manifest["files"]:
                raise MXNetError(
                    f"step {step}: manifest has no {self.params_file!r} "
                    f"(files: {sorted(manifest['files'])})")
            return self.params_file
        for name in sorted(manifest["files"]):
            if name.endswith(".params"):
                return name
        raise MXNetError(f"step {step}: no .params file in manifest "
                         f"(files: {sorted(manifest['files'])})")

    def poll(self):
        """Return ``(step, name→NDArray dict)`` when a step newer than
        the loaded one is available and intact, else None.  Corrupt or
        unparseable candidates are journaled and skipped — never served,
        never fatal."""
        from .. import ndarray as nd
        for step in sorted(_commit.committed_steps(self.root), reverse=True):
            if self.pinned_step is not None and step > self.pinned_step:
                continue             # pinned: newer commits are invisible
            if self.loaded_step is not None and step <= self.loaded_step:
                return None          # newest usable is already serving
            if step in self._bad_steps:
                continue
            try:
                manifest = _commit.validate_step(self.root, step)
                fname = self._pick_file(step, manifest)
                loaded = nd.load(
                    os.path.join(_commit.step_dir(self.root, step), fname))
                if not isinstance(loaded, dict):
                    raise MXNetError(f"{fname} is not a parameter dict")
            except (ValueError, MXNetError, OSError) as e:
                # ValueError: torn/corrupt per the manifest CRCs;
                # MXNetError: container-level CRC/truncation from nd.load;
                # OSError: the step dir raced a trainer's keep-last-k GC
                # between listing and read — gone is just another skip.
                # Only the first two count as CORRUPTION (corrupt_seen,
                # which the fleet feeds to a tenant breaker): a benign
                # GC race must never quarantine a healthy tenant.
                self._remember_bad(step,
                                   corrupt=not isinstance(e, OSError))
                get_journal().event(
                    "ckpt_fallback", root=self.root, step=step,
                    consumer="serving", error=type(e).__name__,
                    detail=str(e)[:300])
                continue
            self.loaded_step = step
            return step, loaded
        return None

    def _remember_bad(self, step, corrupt=True):
        """LRU-insert one bad step under the cap; an eviction is
        journaled once (dedup note) so the operator can see the memory
        is bounded, not leaking skips silently.  ``corrupt=False``
        remembers the skip without counting it as corruption (GC races,
        architecture drift — they feed no breaker)."""
        if step in self._bad_steps:
            self._bad_steps.move_to_end(step)
        else:
            if corrupt:
                self.corrupt_seen += 1
            self._bad_steps[step] = None
        while len(self._bad_steps) > self._bad_cap:
            evicted, _ = self._bad_steps.popitem(last=False)
            get_journal().event(
                "ckpt_fallback", root=self.root, step=evicted,
                consumer="serving", note="bad-step memory evicted "
                "(LRU cap) — re-journals only if it resurfaces",
                cap=self._bad_cap)

    def pin_step(self, step):
        """Freeze the store at ``step``: :meth:`poll` ignores every
        newer commit until ``pin_step(None)`` unpins.  The deploy
        controller's rollback lever — a rolled-back replica pinned to
        the old step cannot silently re-adopt the bad root on its next
        poll (docs/serving.md, canary deployment).  Pinning does NOT
        load anything by itself; pair with :meth:`load_step` (or let
        ``Server.pin_params`` drive the apply) when the live step must
        change."""
        self.pinned_step = None if step is None else int(step)

    def load_step(self, step):
        """Load exactly ``step`` — validated like :meth:`poll`, but an
        explicit target instead of newest-wins, and downgrades are
        allowed (``step`` may be older than ``loaded_step``).  Raises on
        a torn/missing/unparseable step instead of skipping: the caller
        asked for THIS step, so there is no safe substitute.  On success
        ``loaded_step`` moves to ``step``."""
        from .. import ndarray as nd
        step = int(step)
        manifest = _commit.validate_step(self.root, step)   # ValueError on CRC
        fname = self._pick_file(step, manifest)
        loaded = nd.load(
            os.path.join(_commit.step_dir(self.root, step), fname))
        if not isinstance(loaded, dict):
            raise MXNetError(f"{fname} is not a parameter dict")
        self.loaded_step = step
        return step, loaded

    def mark_bad(self, step, revert_to=None):
        """Remember ``step`` as unusable and roll ``loaded_step`` back
        to ``revert_to`` — the server's hook for a checkpoint that
        validated but failed to APPLY (architecture drift), keeping the
        bad-step bookkeeping in one place.  Not a CRC corruption: the
        caller already classified (and breaker-fed) this failure, so it
        must not double-count through ``corrupt_seen``."""
        self._remember_bad(step, corrupt=False)
        self.loaded_step = revert_to

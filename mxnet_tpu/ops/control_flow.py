"""Control-flow operators: foreach / while_loop / cond
(ref: src/operator/control_flow.cc, python/mxnet/ndarray/contrib.py
foreach/while_loop/cond — added in MXNet 1.5).

The reference's imperative versions run the body as a Python loop (each
step's ops recorded on the autograd tape individually) and only the
symbolic versions build a fused subgraph. The TPU build keeps exactly
that split:

- **eager NDArrays**: Python loop — tape-per-step, identical semantics
  to the reference's imperative path;
- **traced NDArrays** (inside ``hybridize()``/``jax.jit``/``vmap``):
  a single ``lax.scan`` — the natural XLA lowering, differentiated by
  the enclosing trace as one unit.

``cond``'s traced path evaluates BOTH branches and selects
(``jnp.where``) instead of ``lax.cond``: on TPU, XLA predicates small
branches anyway, and ``lax.cond`` fails to compile inside differentiated
scanned train steps on some TPU runtimes (documented divergence;
override with MXNET_COND_IMPL=lax_cond).
"""
from __future__ import annotations

import os

import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError

__all__ = ["foreach", "while_loop", "cond"]


def _as_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _is_traced(arrays) -> bool:
    return any(isinstance(getattr(a, "_data", a), jax.core.Tracer)
               for a in arrays)


def _wrap(data):
    from ..ndarray import NDArray
    return NDArray(data, _skip_device_put=True)


def _datas(arrs):
    return [a._data for a in arrs]


def foreach(body, data, init_states, name="foreach"):
    """Scan ``body`` over axis 0 of ``data``
    (ref: python/mxnet/ndarray/contrib.py foreach).

    body(data_slice, states) -> (outputs, new_states); returns
    (outputs stacked along a new axis 0, final states). ``data`` may be
    one NDArray or a list scanned in lockstep; ``init_states`` likewise.
    """
    from ..ndarray import NDArray

    data_list = _as_list(data)
    states = _as_list(init_states)
    single_data = not isinstance(data, (list, tuple))
    single_state = not isinstance(init_states, (list, tuple))
    if not data_list:
        raise MXNetError("foreach: data must hold at least one array")
    length = data_list[0].shape[0]
    for d in data_list:
        if d.shape[0] != length:
            raise MXNetError("foreach: all data arrays must share axis-0 "
                             f"length, got {d.shape[0]} != {length}")

    body_single_out = [True]

    if _is_traced(data_list + states):
        # single fused scan under the enclosing jit/vjp trace
        def step(carry, xs):
            sts = [_wrap(c) for c in carry]
            xs_nd = [_wrap(x) for x in xs]
            outs, new_sts = body(xs_nd[0] if single_data else xs_nd,
                                 sts[0] if single_state else sts)
            body_single_out[0] = not isinstance(outs, (list, tuple))
            outs, new_sts = _as_list(outs), _as_list(new_sts)
            return (tuple(s._data for s in new_sts),
                    tuple(o._data for o in outs))

        final, stacked = lax.scan(step, tuple(_datas(states)),
                                  tuple(_datas(data_list)))
        out_nd = [_wrap(o) for o in stacked]
        st_nd = [_wrap(s) for s in final]
    else:
        # imperative: Python loop, ops tape-recorded step by step
        from .. import ndarray as nd
        out_steps = None
        for i in range(length):
            slices = [d.slice_axis(axis=0, begin=i, end=i + 1)
                      .reshape(d.shape[1:]) for d in data_list]
            outs, states = body(slices[0] if single_data else slices,
                                states[0] if single_state else states)
            body_single_out[0] = not isinstance(outs, (list, tuple))
            outs, states = _as_list(outs), _as_list(states)
            if out_steps is None:
                out_steps = [[] for _ in outs]
            for acc, o in zip(out_steps, outs):
                acc.append(o)
        out_nd = [nd.stack(*acc, axis=0) for acc in (out_steps or [])]
        st_nd = [s if isinstance(s, NDArray) else nd.array(s)
                 for s in states]

    outs_r = out_nd[0] if (body_single_out[0] and len(out_nd) == 1) \
        else out_nd
    sts_r = st_nd[0] if (single_state and len(st_nd) == 1) else st_nd
    return outs_r, sts_r


def while_loop(cond, func, loop_vars, max_iterations=None,
               name="while_loop"):
    """Run ``func`` while ``cond`` holds, at most ``max_iterations`` times
    (ref: python/mxnet/ndarray/contrib.py while_loop).

    cond(*loop_vars) -> scalar; func(*loop_vars) -> (step_outputs,
    new_loop_vars). Returns (outputs stacked with axis-0 length
    ``max_iterations`` — rows past the executed steps are zeros, the
    reference's padding convention — and the final loop_vars).
    """
    from ..ndarray import NDArray

    lvs = _as_list(loop_vars)
    single = not isinstance(loop_vars, (list, tuple))

    if _is_traced(lvs):
        if max_iterations is None:
            raise MXNetError("while_loop: max_iterations is required when "
                             "traced (static shapes under XLA; the "
                             "reference's symbolic mode requires it too)")

        def step(carry, _):
            done, cur = carry
            cur_nd = [_wrap(c) for c in cur]
            keep = jnp.logical_and(
                jnp.logical_not(done),
                jnp.reshape(cond(*cur_nd)._data, ()).astype(bool))
            outs, new = func(*cur_nd)
            outs, new = _as_list(outs), _as_list(new)
            sel = tuple(jnp.where(keep, n._data, c)
                        for n, c in zip(new, cur))
            masked = tuple(jnp.where(keep, o._data,
                                     jnp.zeros_like(o._data))
                           for o in outs)
            return (jnp.logical_not(keep) | done, sel), masked

        (_, final), stacked = lax.scan(
            step, (jnp.bool_(False), tuple(_datas(lvs))),
            None, length=int(max_iterations))
        out_nd = [_wrap(o) for o in stacked]
        st_nd = [_wrap(s) for s in final]
    else:
        from .. import ndarray as nd
        steps = 0
        out_steps = None
        out_avals = None
        while (max_iterations is None or steps < max_iterations) and \
                bool(cond(*lvs).asnumpy()):
            outs, lvs = func(*lvs)
            outs, lvs = _as_list(outs), _as_list(lvs)
            if out_steps is None:
                out_steps = [[] for _ in outs]
                out_avals = [(o.shape, o.dtype) for o in outs]
            for acc, o in zip(out_steps, outs):
                acc.append(o)
            steps += 1
        if out_steps is None:
            # zero executed steps: shapes/dtypes come from abstractly
            # tracing func
            abstract = jax.eval_shape(
                lambda *ds: tuple(o._data for o in
                                  _as_list(func(*[_wrap(d) for d in ds])[0])),
                *_datas(lvs))
            out_avals = [(a.shape, a.dtype) for a in abstract]
            out_steps = [[] for _ in out_avals]
        pad_to = max_iterations if max_iterations is not None else steps
        out_nd = []
        for acc, (shp, dt) in zip(out_steps, out_avals):
            # pad in the OUTPUT dtype — the traced path masks with
            # zeros_like, so eager must not promote int outputs to fp32
            rows = acc + [nd.zeros(shp, dtype=dt)] * (pad_to - len(acc))
            out_nd.append(nd.stack(*rows, axis=0) if rows
                          else nd.zeros((0,) + shp, dtype=dt))
        st_nd = list(lvs)

    outs_r = out_nd[0] if len(out_nd) == 1 else out_nd
    sts_r = st_nd[0] if (single and len(st_nd) == 1) else st_nd
    return outs_r, sts_r


def cond(pred, then_func, else_func, name="cond"):
    """Branch on a scalar predicate
    (ref: python/mxnet/ndarray/contrib.py cond). ``then_func``/
    ``else_func`` are thunks returning an NDArray or list of NDArrays
    with matching shapes."""
    pred_data = getattr(pred, "_data", pred)
    if isinstance(pred_data, jax.core.Tracer):
        then_out = _as_list(then_func())
        else_out = _as_list(else_func())
        if len(then_out) != len(else_out):
            raise MXNetError("cond: branches must return the same number "
                             "of outputs")
        p = jnp.reshape(pred_data, ()).astype(bool)
        if os.environ.get("MXNET_COND_IMPL") == "lax_cond":
            outs = lax.cond(p,
                            lambda: tuple(o._data for o in then_out),
                            lambda: tuple(o._data for o in else_out))
        else:
            # predication: evaluate both branches, select — see module
            # docstring for why this is the TPU default
            outs = tuple(jnp.where(p, t._data, e._data)
                         for t, e in zip(then_out, else_out))
        res = [_wrap(o) for o in outs]
        return res[0] if len(res) == 1 else res
    taken = then_func if bool(jnp.reshape(pred_data, ())) else else_func
    out = taken()
    return out

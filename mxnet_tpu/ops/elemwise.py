"""Elementwise unary/binary/scalar operators.

TPU-native replacement for the reference's mshadow-expression elementwise
kernels and NVRTC pointwise fusion (ref: src/operator/tensor/
elemwise_unary_op_basic.cc, elemwise_binary_broadcast_op_basic.cc,
src/operator/fusion/fused_op.cc). Each op is one jnp/lax call; XLA fuses
chains of them into single TPU kernels, which is exactly the service the
reference needed NVRTC + mshadow templates for.

Ops are registered from tables rather than one file per op — the breadth of
the reference's elementwise surface with none of its boilerplate.
"""
from __future__ import annotations

import jax.numpy as jnp
import jax

from .registry import OpParam, register


def _index_dtype():
    """int64 (reference parity) when jax x64 is enabled, else int32 —
    requested explicitly so jax never warns about truncation."""
    return jnp.int64 if jax.config.x64_enabled else jnp.int32

_f = jnp  # brevity


def _igrad_safe(fn):
    """Wrap comparisons etc. so they are registered non-differentiable."""
    return fn


# ---------------------------------------------------------------------------
# unary ops (ref: src/operator/tensor/elemwise_unary_op_basic.cc + _trig etc.)
# ---------------------------------------------------------------------------
_UNARY = {
    # name: (fn, differentiable)
    "abs": (jnp.abs, True),
    "sign": (jnp.sign, True),
    "ceil": (jnp.ceil, True),
    "floor": (jnp.floor, True),
    "round": (jnp.round, True),
    "rint": (jnp.rint, True),
    "trunc": (jnp.trunc, True),
    "fix": (jnp.trunc, True),
    "exp": (jnp.exp, True),
    "log": (jnp.log, True),
    "log2": (jnp.log2, True),
    "log10": (jnp.log10, True),
    "log1p": (jnp.log1p, True),
    "expm1": (jnp.expm1, True),
    "sqrt": (jnp.sqrt, True),
    "rsqrt": (lambda x: jax.lax.rsqrt(x), True),
    "cbrt": (jnp.cbrt, True),
    "rcbrt": (lambda x: 1.0 / jnp.cbrt(x), True),
    "square": (jnp.square, True),
    "reciprocal": (lambda x: 1.0 / x, True),
    "negative": (jnp.negative, True),
    "relu": (lambda x: jnp.maximum(x, 0), True),
    "sigmoid": (jax.nn.sigmoid, True),
    "softsign": (jax.nn.soft_sign, True),
    "erf": (jax.scipy.special.erf, True),
    "erfinv": (jax.scipy.special.erfinv, True),
    "gamma": (lambda x: jnp.exp(jax.scipy.special.gammaln(x)), True),
    "gammaln": (jax.scipy.special.gammaln, True),
    "sin": (jnp.sin, True), "cos": (jnp.cos, True), "tan": (jnp.tan, True),
    "arcsin": (jnp.arcsin, True), "arccos": (jnp.arccos, True),
    "arctan": (jnp.arctan, True),
    "sinh": (jnp.sinh, True), "cosh": (jnp.cosh, True), "tanh": (jnp.tanh, True),
    "arcsinh": (jnp.arcsinh, True), "arccosh": (jnp.arccosh, True),
    "arctanh": (jnp.arctanh, True),
    "degrees": (jnp.degrees, True),
    "radians": (jnp.radians, True),
    "logical_not": (lambda x: (x == 0).astype(x.dtype), False),
    # int64 like the reference when jax x64 is on, else an EXPLICIT int32
    # request (asking for int64 under default jax emits a truncation
    # UserWarning per call and silently returns int32 anyway)
    "size_array": (lambda x: jnp.asarray(x.size, dtype=_index_dtype()),
                   False),
    "isnan": (jnp.isnan, False),
    "isinf": (jnp.isinf, False),
    "isfinite": (jnp.isfinite, False),
}

for _name, (_fn, _diff) in _UNARY.items():
    register(_name, num_inputs=1, differentiable=_diff,
             doc=f"Elementwise {_name} (ref: src/operator/tensor/elemwise_unary_op*.cc)",
             )(_fn)

register("identity", aliases=["_copy"], doc="Identity / copy op "
         "(ref: elemwise_unary_op_basic.cc _copy)")(lambda x: x + 0)
register("zeros_like", differentiable=False)(jnp.zeros_like)
register("ones_like", differentiable=False)(jnp.ones_like)
register("shape_array", differentiable=False,
         doc="Returns shape as a 1-D index-dtype array (int64 under jax "
             "x64, int32 otherwise; ref: shape_array op)")(
    lambda x: jnp.asarray(x.shape, dtype=_index_dtype()))
register("BlockGrad", aliases=["stop_gradient"],
         doc="Stops gradient flow (ref: src/operator/tensor/"
             "elemwise_unary_op_basic.cc BlockGrad)")(jax.lax.stop_gradient)


def _effective_dtype(dtype):
    """Resolve a requested dtype to what THIS runtime can hold: under
    default jax (x64 off) 64-bit requests already come back 32-bit —
    asking explicitly avoids the per-call truncation UserWarning and
    tracks the live x64 state (covers nd.cast/npx.cast/ONNX Cast alike).
    Matching runs on the NORMALIZED name so alias spellings ('double',
    np.int64) resolve too."""
    if not jax.config.x64_enabled:
        from ..base import _as_np_dtype
        import numpy as _np
        name = _np.dtype(_as_np_dtype(dtype)).name
        return {"int64": "int32", "uint64": "uint32",
                "float64": "float32"}.get(name, dtype)
    return dtype


@register("Cast", aliases=["cast"],
          params=[OpParam("dtype", str, "float32", doc="target dtype")],
          doc="Casts to a new dtype (ref: elemwise_unary_op_basic.cc Cast)")
def _cast(x, dtype="float32"):
    from ..base import _as_np_dtype
    return x.astype(_as_np_dtype(_effective_dtype(dtype)))


@register("amp_cast", params=[OpParam("dtype", str, "float32")],
          doc="AMP cast (ref: src/operator/tensor/amp_cast.cc)")
def _amp_cast(x, dtype="float32"):
    from ..base import _as_np_dtype
    return x.astype(_as_np_dtype(dtype))


# ---------------------------------------------------------------------------
# broadcast binary ops (ref: elemwise_binary_broadcast_op_*.cc). The
# reference distinguishes elemwise_* (no broadcast) from broadcast_*; jnp
# broadcasts natively so both spellings map to one impl.
# ---------------------------------------------------------------------------
def _cmp(fn):
    return lambda a, b: fn(a, b).astype(jnp.result_type(a, b))


_BINARY = {
    "broadcast_add": (jnp.add, True, ["elemwise_add", "_plus"]),
    "broadcast_sub": (jnp.subtract, True, ["elemwise_sub", "_minus"]),
    "broadcast_mul": (jnp.multiply, True, ["elemwise_mul", "_mul"]),
    "broadcast_div": (jnp.divide, True, ["elemwise_div", "_div"]),
    "broadcast_mod": (jnp.mod, True, ["_mod"]),
    "broadcast_power": (jnp.power, True, ["_power", "pow"]),
    "broadcast_maximum": (jnp.maximum, True, ["_maximum"]),
    "broadcast_minimum": (jnp.minimum, True, ["_minimum"]),
    "broadcast_hypot": (jnp.hypot, True, ["_hypot"]),
    "broadcast_equal": (_cmp(jnp.equal), False, ["_equal"]),
    "broadcast_not_equal": (_cmp(jnp.not_equal), False, ["_not_equal"]),
    "broadcast_greater": (_cmp(jnp.greater), False, ["_greater"]),
    "broadcast_greater_equal": (_cmp(jnp.greater_equal), False, ["_greater_equal"]),
    "broadcast_lesser": (_cmp(jnp.less), False, ["_lesser"]),
    "broadcast_lesser_equal": (_cmp(jnp.less_equal), False, ["_lesser_equal"]),
    "broadcast_logical_and": (_cmp(jnp.logical_and), False, ["_logical_and"]),
    "broadcast_logical_or": (_cmp(jnp.logical_or), False, ["_logical_or"]),
    "broadcast_logical_xor": (_cmp(jnp.logical_xor), False, ["_logical_xor"]),
    "arctan2": (jnp.arctan2, True, ["_arctan2"]),
    # reference ldexp is lhs*2^rhs over FLOAT arrays (jnp.ldexp wants an
    # integer exponent, so spell it out)
    "ldexp": (lambda a, b: a * jnp.power(2.0, b).astype(
        jnp.result_type(a, b)), True, ["_ldexp"]),
}

for _name, (_fn, _diff, _aliases) in _BINARY.items():
    register(_name, num_inputs=2, differentiable=_diff, aliases=_aliases,
             doc=f"Broadcasting {_name} "
                 f"(ref: src/operator/tensor/elemwise_binary_broadcast_op*.cc)",
             )(_fn)


# ---------------------------------------------------------------------------
# scalar ops (ref: elemwise_binary_scalar_op_*.cc _plus_scalar etc.) — the
# NDArray operator-overload path lowers `x + 3` onto these.
# ---------------------------------------------------------------------------
_SCALAR = {
    "_plus_scalar": (lambda x, s: x + s, True),
    "_minus_scalar": (lambda x, s: x - s, True),
    "_rminus_scalar": (lambda x, s: s - x, True),
    "_mul_scalar": (lambda x, s: x * s, True),
    "_div_scalar": (lambda x, s: x / s, True),
    "_rdiv_scalar": (lambda x, s: s / x, True),
    "_mod_scalar": (lambda x, s: jnp.mod(x, s), True),
    "_rmod_scalar": (lambda x, s: jnp.mod(s, x), True),
    "_power_scalar": (lambda x, s: jnp.power(x, s), True),
    "_rpower_scalar": (lambda x, s: jnp.power(s, x), True),
    "_maximum_scalar": (lambda x, s: jnp.maximum(x, s), True),
    "_minimum_scalar": (lambda x, s: jnp.minimum(x, s), True),
    "_equal_scalar": (lambda x, s: (x == s).astype(x.dtype), False),
    "_not_equal_scalar": (lambda x, s: (x != s).astype(x.dtype), False),
    "_greater_scalar": (lambda x, s: (x > s).astype(x.dtype), False),
    "_greater_equal_scalar": (lambda x, s: (x >= s).astype(x.dtype), False),
    "_lesser_scalar": (lambda x, s: (x < s).astype(x.dtype), False),
    "_lesser_equal_scalar": (lambda x, s: (x <= s).astype(x.dtype), False),
    "_logical_and_scalar": (lambda x, s: jnp.logical_and(x, s).astype(x.dtype), False),
    "_logical_or_scalar": (lambda x, s: jnp.logical_or(x, s).astype(x.dtype), False),
    "_logical_xor_scalar": (lambda x, s: jnp.logical_xor(x, s).astype(x.dtype), False),
    "_hypot_scalar": (lambda x, s: jnp.hypot(x, s), True),
}

for _name, (_fn, _diff) in _SCALAR.items():
    register(_name, num_inputs=1, differentiable=_diff,
             params=[OpParam("scalar", float, 0.0, doc="scalar operand")],
             doc=f"Scalar op {_name} "
                 f"(ref: src/operator/tensor/elemwise_binary_scalar_op*.cc)",
             )((lambda f: lambda x, scalar=0.0: f(x, scalar))(_fn))

register("add_n", num_inputs=-1, aliases=["ElementWiseSum"],
         doc="Sum of N arrays in one op "
             "(ref: src/operator/tensor/elemwise_sum.cc)")(
    lambda *xs: sum(xs[1:], xs[0]))

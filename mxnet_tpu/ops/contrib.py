"""Contrib ops — detection kernels and misc.

TPU-native equivalent of ``src/operator/contrib/`` (MultiBoxPrior, box_nms,
ROIAlign, BilinearResize2D, ...). The reference hand-writes CUDA for these;
here they are static-shape jnp/lax formulations (greedy NMS as a fori_loop,
ROIAlign as vectorized bilinear gathers) which XLA compiles for the VPU; a
Pallas fast path can slot in later where profiling justifies it.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError
from .registry import OpParam, register


def _box_iou_corner(a, b):
    """IoU between (..., N, 4) and (..., M, 4) corner boxes -> (..., N, M)."""
    tl = jnp.maximum(a[..., :, None, :2], b[..., None, :, :2])
    br = jnp.minimum(a[..., :, None, 2:], b[..., None, :, 2:])
    wh = jnp.maximum(br - tl, 0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = jnp.maximum(a[..., 2] - a[..., 0], 0) * jnp.maximum(a[..., 3] - a[..., 1], 0)
    area_b = jnp.maximum(b[..., 2] - b[..., 0], 0) * jnp.maximum(b[..., 3] - b[..., 1], 0)
    union = area_a[..., :, None] + area_b[..., None, :] - inter
    return jnp.where(union > 0, inter / union, jnp.zeros_like(inter))


@register("_contrib_box_iou", aliases=["box_iou"], num_inputs=2,
          params=[OpParam("format", str, "corner")],
          differentiable=False,
          doc="Pairwise IoU (ref: src/operator/contrib/bounding_box.cc box_iou)")
def _box_iou(lhs, rhs, format="corner"):
    if format == "center":
        def c2c(b):
            xy = b[..., :2]
            wh = b[..., 2:] / 2
            return jnp.concatenate([xy - wh, xy + wh], axis=-1)
        lhs, rhs = c2c(lhs), c2c(rhs)
    return _box_iou_corner(lhs, rhs)


@register("_contrib_box_nms", aliases=["box_nms"],
          params=[OpParam("overlap_thresh", float, 0.5),
                  OpParam("valid_thresh", float, 0.0),
                  OpParam("topk", int, -1),
                  OpParam("coord_start", int, 2),
                  OpParam("score_index", int, 1),
                  OpParam("id_index", int, -1),
                  OpParam("background_id", int, -1),
                  OpParam("force_suppress", bool, False),
                  OpParam("in_format", str, "corner"),
                  OpParam("out_format", str, "corner")],
          differentiable=False,
          doc="Greedy non-max suppression, static shapes: suppressed entries "
              "are filled with -1 like the reference "
              "(ref: src/operator/contrib/bounding_box.cc box_nms)")
def _box_nms(data, overlap_thresh=0.5, valid_thresh=0.0, topk=-1,
             coord_start=2, score_index=1, id_index=-1, background_id=-1,
             force_suppress=False, in_format="corner", out_format="corner"):
    batched = data.ndim == 3
    if not batched:
        data = data[None]

    def nms_one(rows):
        scores = rows[:, score_index]
        boxes = lax.dynamic_slice_in_dim(rows, coord_start, 4, axis=1)
        if in_format == "center":
            xy, wh = boxes[:, :2], boxes[:, 2:] / 2
            boxes = jnp.concatenate([xy - wh, xy + wh], axis=-1)
        valid = scores > valid_thresh
        if id_index >= 0 and background_id >= 0:
            valid &= rows[:, id_index] != background_id
        order = jnp.argsort(-jnp.where(valid, scores, -jnp.inf))
        n = rows.shape[0]
        k = n if topk <= 0 else min(topk, n)
        iou = _box_iou_corner(boxes[order], boxes[order])
        if id_index >= 0 and not force_suppress:
            ids = rows[order, id_index]
            iou = jnp.where(ids[:, None] == ids[None, :], iou, 0.0)
        valid_sorted = valid[order]

        # Greedy NMS as a fixed-point iteration instead of a sequential
        # O(topk) loop: keep_i = valid_i AND no kept higher-ranked j with
        # IoU > t. Each sweep is one n x n matmul (MXU work), and the
        # iteration reaches the greedy fixpoint in suppression-chain-depth
        # sweeps (typically < 10) rather than topk sequential steps —
        # the survey's planned TPU formulation (SURVEY §7: "Pallas for
        # ... NMS"; measured speedup in benchmarks/nms_bench.py).
        ranks = jnp.arange(n)
        adj = (iou > overlap_thresh) & (ranks[None, :] < ranks[:, None]) \
            & (ranks[None, :] < k)          # j can suppress i: j<i, j<topk
        adjf = adj.astype(jnp.float32)

        def fp_cond(state):
            _, changed, it = state
            return changed & (it < n)

        def fp_body(state):
            keep, _, it = state
            suppressed = (adjf @ keep.astype(jnp.float32)) > 0
            new = valid_sorted & ~suppressed
            return new, jnp.any(new != keep), it + 1

        keep, _, _ = lax.while_loop(
            fp_cond, fp_body, (valid_sorted, jnp.bool_(True),
                               jnp.int32(0)))
        keep &= jnp.arange(n) < k
        # compact kept rows to the top (stable), suppressed slots become -1
        perm = jnp.argsort(~keep, stable=True)
        compacted = jnp.where(jnp.sort(~keep, stable=True)[:, None],
                              -jnp.ones_like(rows), rows[order][perm])
        return compacted

    out = jax.vmap(nms_one)(data)
    return out if batched else out[0]


@register("_contrib_BilinearResize2D", aliases=["BilinearResize2D"],
          params=[OpParam("height", int, 0), OpParam("width", int, 0),
                  OpParam("scale_height", float, None),
                  OpParam("scale_width", float, None),
                  OpParam("mode", str, "size"),
                  OpParam("align_corners", bool, True)],
          doc="ref: src/operator/contrib/bilinear_resize.cc")
def _bilinear_resize(x, height=0, width=0, scale_height=None, scale_width=None,
                     mode="size", align_corners=True):
    n, c, h, w = x.shape
    if scale_height is not None:
        height = int(h * scale_height)
        width = int(w * scale_width)
    if align_corners and height > 1 and width > 1:
        ys = jnp.linspace(0.0, h - 1.0, height)
        xs = jnp.linspace(0.0, w - 1.0, width)
        y0 = jnp.clip(jnp.floor(ys).astype(jnp.int32), 0, h - 1)
        x0 = jnp.clip(jnp.floor(xs).astype(jnp.int32), 0, w - 1)
        y1 = jnp.clip(y0 + 1, 0, h - 1)
        x1 = jnp.clip(x0 + 1, 0, w - 1)
        wy = (ys - y0).reshape(1, 1, -1, 1)
        wx = (xs - x0).reshape(1, 1, 1, -1)
        g = lambda yy, xx: x[:, :, yy][:, :, :, xx]
        out = (g(y0, x0) * (1 - wy) * (1 - wx) + g(y1, x0) * wy * (1 - wx)
               + g(y0, x1) * (1 - wy) * wx + g(y1, x1) * wy * wx)
        return out.astype(x.dtype)
    return jax.image.resize(x, (n, c, height, width), method="bilinear").astype(x.dtype)


@register("_contrib_AdaptiveAvgPooling2D", aliases=["AdaptiveAvgPooling2D"],
          params=[OpParam("output_size", tuple, None)],
          doc="ref: src/operator/contrib/adaptive_avg_pooling.cc")
def _adaptive_avg_pool(x, output_size=None):
    n, c, h, w = x.shape
    if not output_size:
        oh = ow = 1
    elif len(output_size) == 1:
        oh = ow = int(output_size[0])
    else:
        oh, ow = int(output_size[0]), int(output_size[1])
    if h % oh == 0 and w % ow == 0:
        x = x.reshape(n, c, oh, h // oh, ow, w // ow)
        return x.mean(axis=(3, 5))
    # general case: average over adaptive windows via interpolation-free loop
    out = jnp.zeros((n, c, oh, ow), dtype=x.dtype)
    rows = [(int(jnp.floor(i * h / oh)), int(-(-((i + 1) * h) // oh))) for i in range(oh)]
    cols = [(int(jnp.floor(j * w / ow)), int(-(-((j + 1) * w) // ow))) for j in range(ow)]
    parts = []
    for (r0, r1) in rows:
        row = [x[:, :, r0:r1, c0:c1].mean(axis=(2, 3)) for (c0, c1) in cols]
        parts.append(jnp.stack(row, axis=-1))
    return jnp.stack(parts, axis=-2)


@register("_contrib_ROIAlign", aliases=["ROIAlign"], num_inputs=2,
          params=[OpParam("pooled_size", tuple, None, required=True),
                  OpParam("spatial_scale", float, 1.0),
                  OpParam("sample_ratio", int, -1),
                  OpParam("position_sensitive", bool, False),
                  OpParam("aligned", bool, False)],
          doc="ROI Align via vectorized bilinear gathers "
              "(ref: src/operator/contrib/roi_align.cc)")
def _roi_align(features, rois, pooled_size=None, spatial_scale=1.0,
               sample_ratio=-1, position_sensitive=False, aligned=False):
    ph, pw = int(pooled_size[0]), int(pooled_size[1])
    n, c, h, w = features.shape
    sr = sample_ratio if sample_ratio > 0 else 2
    offset = 0.5 if aligned else 0.0

    def one_roi(roi):
        batch_idx = roi[0].astype(jnp.int32)
        x1, y1, x2, y2 = (roi[1] * spatial_scale - offset,
                          roi[2] * spatial_scale - offset,
                          roi[3] * spatial_scale - offset,
                          roi[4] * spatial_scale - offset)
        rw = jnp.maximum(x2 - x1, 1.0 if not aligned else 1e-6)
        rh = jnp.maximum(y2 - y1, 1.0 if not aligned else 1e-6)
        bin_h, bin_w = rh / ph, rw / pw
        # sample grid: (ph*sr, pw*sr)
        ys = y1 + (jnp.arange(ph * sr) + 0.5) * bin_h / sr
        xs = x1 + (jnp.arange(pw * sr) + 0.5) * bin_w / sr
        img = lax.dynamic_index_in_dim(features, batch_idx, axis=0, keepdims=False)

        def bilinear(yy, xx):
            y0 = jnp.clip(jnp.floor(yy).astype(jnp.int32), 0, h - 1)
            x0 = jnp.clip(jnp.floor(xx).astype(jnp.int32), 0, w - 1)
            y1i = jnp.clip(y0 + 1, 0, h - 1)
            x1i = jnp.clip(x0 + 1, 0, w - 1)
            wy = jnp.clip(yy - y0, 0, 1).reshape(1, -1, 1)
            wx = jnp.clip(xx - x0, 0, 1).reshape(1, 1, -1)
            g = lambda a, b: img[:, a][:, :, b]
            return (g(y0, x0) * (1 - wy) * (1 - wx) + g(y1i, x0) * wy * (1 - wx)
                    + g(y0, x1i) * (1 - wy) * wx + g(y1i, x1i) * wy * wx)

        samples = bilinear(ys, xs)                       # (c, ph*sr, pw*sr)
        samples = samples.reshape(c, ph, sr, pw, sr)
        return samples.mean(axis=(2, 4))

    return jax.vmap(one_roi)(rois)


@register("_contrib_MultiBoxPrior", aliases=["MultiBoxPrior"],
          params=[OpParam("sizes", tuple, (1.0,)),
                  OpParam("ratios", tuple, (1.0,)),
                  OpParam("clip", bool, False),
                  OpParam("steps", tuple, (-1.0, -1.0)),
                  OpParam("offsets", tuple, (0.5, 0.5))],
          differentiable=False,
          doc="SSD anchor generation (ref: src/operator/contrib/multibox_prior.cc)")
def _multibox_prior(data, sizes=(1.0,), ratios=(1.0,), clip=False,
                    steps=(-1.0, -1.0), offsets=(0.5, 0.5)):
    h, w = data.shape[2], data.shape[3]
    step_y = steps[0] if steps[0] > 0 else 1.0 / h
    step_x = steps[1] if steps[1] > 0 else 1.0 / w
    cy = (jnp.arange(h) + offsets[0]) * step_y
    cx = (jnp.arange(w) + offsets[1]) * step_x
    cy, cx = jnp.meshgrid(cy, cx, indexing="ij")
    centers = jnp.stack([cx.ravel(), cy.ravel()], axis=-1)      # (h*w, 2)
    # reference: num_anchors = len(sizes) + len(ratios) - 1
    whs = []
    for s in sizes:
        whs.append((s * jnp.sqrt(ratios[0]), s / jnp.sqrt(ratios[0])))
    for r in ratios[1:]:
        whs.append((sizes[0] * jnp.sqrt(r), sizes[0] / jnp.sqrt(r)))
    whs = jnp.asarray(whs)                                       # (A, 2)
    half = whs / 2
    boxes = jnp.concatenate([
        centers[:, None, :] - half[None, :, :],
        centers[:, None, :] + half[None, :, :]], axis=-1)        # (h*w, A, 4)
    boxes = boxes.reshape(1, -1, 4)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    return boxes


@register("arange_like", num_inputs=1,
          params=[OpParam("start", float, 0.0), OpParam("step", float, 1.0),
                  OpParam("repeat", int, 1), OpParam("axis", int, None)],
          differentiable=False,
          doc="ref: src/operator/contrib/arange_like op")
def _arange_like(x, start=0.0, step=1.0, repeat=1, axis=None):
    if axis is None:
        n = x.size
        return (start + step * jnp.arange(n)).reshape(x.shape).astype(x.dtype)
    n = x.shape[axis]
    return (start + step * jnp.arange(n)).astype(x.dtype)


@register("_contrib_div_sqrt_dim", aliases=["div_sqrt_dim"],
          doc="x / sqrt(last_dim) — attention scaling helper "
              "(ref: src/operator/contrib/transformer.cc)")
def _div_sqrt_dim(x):
    return x / jnp.sqrt(float(x.shape[-1]))


@register("_contrib_interleaved_matmul_selfatt_qk", num_inputs=1,
          params=[OpParam("heads", int, None, required=True)],
          doc="Transformer fused self-attention QK^T "
              "(ref: src/operator/contrib/transformer.cc). Input (T, N, 3*E) "
              "interleaved qkv projections.")
def _interleaved_qk(qkv, heads=None):
    t, n, e3 = qkv.shape
    e = e3 // 3
    hd = e // heads
    qkv = qkv.reshape(t, n, heads, 3, hd)
    q = qkv[:, :, :, 0]                                  # (T, N, H, D)
    k = qkv[:, :, :, 1]
    q = q.transpose(1, 2, 0, 3).reshape(n * heads, t, hd)
    k = k.transpose(1, 2, 0, 3).reshape(n * heads, t, hd)
    return jnp.matmul(q, k.transpose(0, 2, 1)) / jnp.sqrt(float(hd))


@register("_contrib_interleaved_matmul_selfatt_valatt", num_inputs=2,
          params=[OpParam("heads", int, None, required=True)],
          doc="Transformer fused attention AV (ref: contrib/transformer.cc)")
def _interleaved_valatt(qkv, att, heads=None):
    t, n, e3 = qkv.shape
    e = e3 // 3
    hd = e // heads
    v = qkv.reshape(t, n, heads, 3, hd)[:, :, :, 2]
    v = v.transpose(1, 2, 0, 3).reshape(n * heads, t, hd)
    out = jnp.matmul(att, v)                             # (N*H, T, D)
    out = out.reshape(n, heads, t, hd).transpose(2, 0, 1, 3)
    return out.reshape(t, n, e)


@register("_contrib_flash_attention", num_inputs=3,
          params=[OpParam("block_size", int, 512),
                  OpParam("causal", bool, False),
                  OpParam("sm_scale", float, None)],
          doc="Blockwise online-softmax attention on [B, H, S, D] inputs — "
              "memory-efficient long-context attention (net-new TPU "
              "capability, SURVEY §5.7; no reference analog — MXNet 1.x "
              "used full attention). Sequence-parallel variant: "
              "mxnet_tpu.parallel.ring_attention.")
def _flash_attention(q, k, v, block_size=512, causal=False, sm_scale=None):
    import jax
    from ..parallel.ring_attention import blockwise_attention
    scale = float(q.shape[-1]) ** -0.5 if sm_scale is None else sm_scale
    if k.shape[-2] <= 1024:
        # short KV: one fused softmax(QK^T)V straight on the MXU via the
        # shared dense-attention definition (attention_reference — one
        # mask convention, fp32-accumulated row sums). The s_q x s_kv
        # score tensor is small here, and a single batched matmul pair
        # beats any streaming kernel (measured: the Pallas kernels cost
        # ~20x at S=128 — see docs/perf_notes.md).
        from ..parallel.ring_attention import attention_reference
        return attention_reference(q, k, v, causal=causal, scale=scale)
    # on TPU hardware route to the hand-tiled Pallas kernel (MXU-tiled
    # blocks, VMEM-resident online softmax); the jnp blockwise kernel is
    # the portable fallback and the CPU-test oracle
    from ..pallas import mode as _pallas_mode
    if jax.default_backend() == "tpu" and _pallas_mode() != "off" and \
            q.shape[-2] % 128 == 0 and q.shape[-1] >= 64:
        try:
            from jax.experimental.pallas.ops.tpu.flash_attention import (
                flash_attention as _pallas_fa)
            if q.ndim == 3:
                # the Pallas kernel wants [B, H, S, D]; 3D graphs (e.g.
                # FuseAttention pattern-1 rewrites) ride as H=1
                out = _pallas_fa(q[:, None], k[:, None], v[:, None],
                                 causal=causal, sm_scale=scale)
                return out[:, 0]
            return _pallas_fa(q, k, v, causal=causal, sm_scale=scale)
        except Exception as e:
            # a silent fallback would hide a perf cliff on hardware:
            # surface it once (weak-spot noted in round-1 review)
            import warnings
            if not getattr(_flash_attention, "_warned_fallback", False):
                _flash_attention._warned_fallback = True
                warnings.warn(
                    f"flash_attention: Pallas TPU kernel unavailable "
                    f"({type(e).__name__}: {e}); falling back to the "
                    f"jnp blockwise kernel", RuntimeWarning)
    return blockwise_attention(q, k, v, block_size=block_size,
                               causal=causal, scale=scale)


@register("_contrib_conv_epilogue", num_inputs=2,
          params=[OpParam("act_type", str, "relu")],
          doc="Fused residual epilogue act(x + res) in one VMEM pass — the "
              "RN50 conv-fusion bandwidth lever (docs/pallas.md; promoted "
              "from benchmarks/conv_epilogue_probe.py). Dispatches the "
              "mxnet_tpu.pallas conv_epilogue kernel on TPU; everywhere "
              "else the parity-gated XLA reference runs (journaled "
              "fallback), so numerics are identical across tiers within "
              "the registered tolerance.")
def _conv_epilogue_contrib(x, res, act_type="relu"):
    from ..pallas import fused_conv_epilogue
    return fused_conv_epilogue(x, res=res, act_type=act_type)


@register("_contrib_matmul_epilogue", num_inputs=2, needs_rng=True,
          needs_mode=True,
          params=[OpParam("act_type", str, None),
                  OpParam("p", float, 0.0,
                          doc="inverted-dropout rate folded into the "
                              "epilogue (training only); mask semantics "
                              "bit-identical to Dropout"),
                  OpParam("layer", int, 0),
                  OpParam("tick", int, 0)],
          doc="Fused matmul epilogue dropout(act(y + bias)) in one VMEM "
              "pass over the matmul output — the BERT MFU lever "
              "(docs/pallas.md, docs/roadmap.md items 3-4). Dropout keys "
              "follow the PR-1 (layer, tick, shard) fold discipline. "
              "Dispatches the mxnet_tpu.pallas matmul_epilogue kernel on "
              "TPU with a parity-gated XLA fallback elsewhere.")
def _matmul_epilogue_contrib(y, bias, rng=None, act_type=None, p=0.0,
                             layer=0, tick=0, training=False):
    from ..pallas import fused_matmul_epilogue
    return fused_matmul_epilogue(y, bias, act_type=act_type, p=p, rng=rng,
                                 training=training, layer=layer, tick=tick)


@register("_contrib_ring_attention", num_inputs=3,
          params=[OpParam("axis_name", str, "seq"),
                  OpParam("causal", bool, False),
                  OpParam("batch_axis", str, "data"),
                  OpParam("head_axis", str, None)],
          doc="Sequence-parallel ring attention over the current mesh's "
              "ICI ring (lax.ppermute of K/V shards + online softmax). "
              "Net-new TPU capability (SURVEY §5.7); composes under jit "
              "via shard_map.")
def _ring_attention_op(q, k, v, axis_name="seq", causal=False,
                       batch_axis="data", head_axis=None):
    import jax
    from ..parallel.ring_attention import blockwise_attention, ring_attention
    from ..parallel.mesh import current_mesh
    if not isinstance(q, jax.core.Tracer):
        # eager execution (shape resolution, debugging): same math on one
        # device via the blockwise kernel; the ring engages under jit
        return blockwise_attention(q, k, v, block_size=q.shape[-2],
                                   causal=causal)
    return ring_attention(q, k, v, mesh=current_mesh(),
                          axis_name=axis_name, causal=causal,
                          batch_axis=batch_axis, head_axis=head_axis)


@register("_contrib_MultiBoxTarget", aliases=["MultiBoxTarget"],
          num_inputs=3, num_outputs=3,
          params=[OpParam("overlap_threshold", float, 0.5),
                  OpParam("ignore_label", float, -1.0),
                  OpParam("negative_mining_ratio", float, -1.0),
                  OpParam("negative_mining_thresh", float, 0.5),
                  OpParam("minimum_negative_samples", int, 0),
                  OpParam("variances", tuple, (0.1, 0.1, 0.2, 0.2))],
          differentiable=False,
          doc="SSD training target assignment: anchors x gt labels → "
              "(loc_target, loc_mask, cls_target). Static shapes, vmapped "
              "over the batch (ref: src/operator/contrib/"
              "multibox_target.cc). gt label rows are [cls, x0, y0, x1, "
              "y1], padded with cls=-1. TPU extension over the reference: "
              "anchors may be (N, A, 4) — one anchor set PER IMAGE (the "
              "Faster R-CNN proposal↔gt matching case, ref: "
              "src/operator/contrib/proposal_target.cc) — vmapped over "
              "both, so the whole assignment stays in-graph.")
def _multibox_target(anchors, labels, cls_preds, overlap_threshold=0.5,
                     ignore_label=-1.0, negative_mining_ratio=-1.0,
                     negative_mining_thresh=0.5, minimum_negative_samples=0,
                     variances=(0.1, 0.1, 0.2, 0.2)):
    def one(anc, label, cls_pred):
        anc = anc.reshape(-1, 4)                      # (A, 4) corner
        acx = (anc[:, 0] + anc[:, 2]) / 2
        acy = (anc[:, 1] + anc[:, 3]) / 2
        aw = jnp.maximum(anc[:, 2] - anc[:, 0], 1e-12)
        ah = jnp.maximum(anc[:, 3] - anc[:, 1], 1e-12)
        A = anc.shape[0]
        gt_cls = label[:, 0]
        gt_box = label[:, 1:5]
        valid = gt_cls >= 0                           # (M,)
        iou = _box_iou_corner(anc, gt_box)            # (A, M)
        iou = jnp.where(valid[None, :], iou, -1.0)
        best_gt = jnp.argmax(iou, axis=1)             # (A,)
        best_iou = jnp.max(iou, axis=1)
        # every gt's best anchor is forced positive (reference bipartite
        # matching stage)
        best_anchor = jnp.argmax(iou, axis=0)         # (M,)
        forced = jnp.zeros(A, bool).at[best_anchor].set(valid)
        forced_gt = jnp.zeros(A, jnp.int32).at[best_anchor].set(
            jnp.arange(gt_box.shape[0], dtype=jnp.int32))
        pos = forced | (best_iou >= overlap_threshold)
        gt_idx = jnp.where(forced, forced_gt, best_gt)
        # classification target: 0 = background, cls+1 for positives
        cls_t = jnp.where(pos, gt_cls[gt_idx] + 1.0, 0.0)
        # optional hard-negative mining: keep top-k negatives by max
        # class prob, others → ignore_label
        if negative_mining_ratio > 0:
            prob = jax.nn.softmax(cls_pred, axis=-1)
            neg_score = 1.0 - prob[:, 0]              # objectness-like
            num_pos = jnp.sum(pos)
            max_neg = jnp.maximum(
                (num_pos * negative_mining_ratio).astype(jnp.int32),
                minimum_negative_samples)
            neg_rank = jnp.argsort(jnp.argsort(
                -jnp.where(pos, -jnp.inf, neg_score)))
            keep_neg = (~pos) & (neg_rank < max_neg)
            cls_t = jnp.where(pos | keep_neg, cls_t, ignore_label)
        # localization target: encoded offsets with variances
        g = gt_box[gt_idx]
        gcx = (g[:, 0] + g[:, 2]) / 2
        gcy = (g[:, 1] + g[:, 3]) / 2
        gw = jnp.maximum(g[:, 2] - g[:, 0], 1e-12)
        gh = jnp.maximum(g[:, 3] - g[:, 1], 1e-12)
        loc_t = jnp.stack([
            (gcx - acx) / aw / variances[0],
            (gcy - acy) / ah / variances[1],
            jnp.log(gw / aw) / variances[2],
            jnp.log(gh / ah) / variances[3]], axis=-1)
        loc_t = jnp.where(pos[:, None], loc_t, 0.0)
        loc_m = jnp.broadcast_to(pos[:, None], loc_t.shape).astype(
            loc_t.dtype)
        return (loc_t.reshape(-1), loc_m.reshape(-1), cls_t)

    if anchors.ndim == 3 and anchors.shape[0] == labels.shape[0] \
            and anchors.shape[0] > 1:
        # per-image anchor sets (proposals): vmap over anchors too
        loc_t, loc_m, cls_t = jax.vmap(one)(anchors, labels, cls_preds)
    else:
        anc0 = anchors.reshape(-1, 4)
        loc_t, loc_m, cls_t = jax.vmap(
            lambda lb, cp: one(anc0, lb, cp))(labels, cls_preds)
    return loc_t, loc_m, cls_t


@register("_contrib_MultiBoxDetection", aliases=["MultiBoxDetection"],
          num_inputs=3,
          params=[OpParam("clip", bool, True),
                  OpParam("threshold", float, 0.01),
                  OpParam("background_id", int, 0),
                  OpParam("nms_threshold", float, 0.5),
                  OpParam("force_suppress", bool, False),
                  OpParam("variances", tuple, (0.1, 0.1, 0.2, 0.2)),
                  OpParam("nms_topk", int, -1)],
          differentiable=False,
          doc="SSD inference: decode anchors+offsets, per-class NMS; "
              "output rows [cls_id, score, x0, y0, x1, y1], suppressed "
              "rows -1 (static shape, ref: src/operator/contrib/"
              "multibox_detection.cc)")
def _multibox_detection(cls_prob, loc_pred, anchors, clip=True,
                        threshold=0.01, background_id=0, nms_threshold=0.5,
                        force_suppress=False,
                        variances=(0.1, 0.1, 0.2, 0.2), nms_topk=-1):
    anc = anchors.reshape(-1, 4)
    acx = (anc[:, 0] + anc[:, 2]) / 2
    acy = (anc[:, 1] + anc[:, 3]) / 2
    aw = anc[:, 2] - anc[:, 0]
    ah = anc[:, 3] - anc[:, 1]

    def one(probs, loc):
        # probs: (C, A); loc: (A*4,)
        loc = loc.reshape(-1, 4)
        cx = loc[:, 0] * variances[0] * aw + acx
        cy = loc[:, 1] * variances[1] * ah + acy
        w = jnp.exp(loc[:, 2] * variances[2]) * aw
        h = jnp.exp(loc[:, 3] * variances[3]) * ah
        boxes = jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2,
                           cy + h / 2], axis=-1)
        if clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        # best foreground class per anchor (reference picks argmax)
        fg = jnp.where(jnp.arange(probs.shape[0])[:, None] == background_id,
                       -jnp.inf, probs)
        cls_id = jnp.argmax(fg, axis=0).astype(boxes.dtype)
        score = jnp.max(fg, axis=0)
        keep = score > threshold
        cls_id = jnp.where(keep, cls_id - (background_id == 0), -1.0)
        score = jnp.where(keep, score, -1.0)
        rows = jnp.concatenate([cls_id[:, None], score[:, None], boxes],
                               axis=-1)
        return rows

    rows = jax.vmap(one)(cls_prob, loc_pred)
    return _box_nms(rows, overlap_thresh=nms_threshold, valid_thresh=0.0,
                    topk=nms_topk, coord_start=2, score_index=1,
                    id_index=0, background_id=-1,
                    force_suppress=force_suppress)


# ---------------------------------------------------------------------------
# Binary-network ops — the BMXNet fork delta (SURVEY §2 #23: yanghaojin is
# the BMXNet author; upstream BMXNet adds QConvolution/QFullyConnected/
# QActivation and det_sign with gradient cancellation, smd_hpi/src/).
# TPU design: binarization is sign() with a straight-through estimator;
# the "XNOR-popcount GEMM" becomes a ±1 matmul in bf16 on the MXU — the
# MXU at bf16 rate IS the fast binary GEMM on this hardware (no integer
# popcount unit to beat it).
# ---------------------------------------------------------------------------
def _ste_sign(x, grad_cancel=1.0):
    @jax.custom_vjp
    def core(v):
        return jnp.where(v >= 0, 1.0, -1.0).astype(v.dtype)

    def fwd(v):
        return core(v), v

    def bwd(v, g):
        # straight-through with cancellation: pass grad only where |x|<=t
        return (jnp.where(jnp.abs(v) <= grad_cancel, g,
                          jnp.zeros_like(g)),)

    core.defvjp(fwd, bwd)
    return core(x)


@register("det_sign", params=[OpParam("grad_cancel", float, 1.0)],
          doc="Deterministic sign with straight-through gradient, zeroed "
              "where |x| > grad_cancel (BMXNet det_sign / grad cancellation)")
def _det_sign(x, grad_cancel=1.0):
    return _ste_sign(x, grad_cancel)


@register("approx_sign", params=[],
          doc="ApproxSign (Bi-Real Net): sign forward, piecewise-parabolic "
              "backward (2-2|x| for |x|<=1) — BMXNet approx_sign")
def _approx_sign(x):
    @jax.custom_vjp
    def core(v):
        return jnp.where(v >= 0, 1.0, -1.0).astype(v.dtype)

    def fwd(v):
        return core(v), v

    def bwd(v, g):
        slope = jnp.where(jnp.abs(v) <= 1.0, 2.0 - 2.0 * jnp.abs(v), 0.0)
        return (g * slope,)

    core.defvjp(fwd, bwd)
    return core(x)


@register("QFullyConnected", num_inputs=-1,
          params=[OpParam("num_hidden", int, None, required=True),
                  OpParam("no_bias", bool, False),
                  OpParam("binarize_input", bool, True),
                  OpParam("scaling", bool, True)],
          doc="Binary fully-connected (BMXNet QFullyConnected): ±1 weights "
              "(and optionally inputs), XNOR-Net alpha scaling = mean|W|")
def _q_fully_connected(x, weight, *bias, num_hidden=None, no_bias=False,
                       binarize_input=True, scaling=True):
    xb = _ste_sign(x) if binarize_input else x
    wb = _ste_sign(weight)
    y = jnp.matmul(xb.reshape(xb.shape[0], -1), wb.T)
    if scaling:
        alpha = jnp.mean(jnp.abs(weight))
        y = y * alpha
    if not no_bias and bias:
        y = y + bias[0]
    return y


@register("QConvolution", num_inputs=-1,
          params=[OpParam("kernel", tuple, None, required=True),
                  OpParam("num_filter", int, None, required=True),
                  OpParam("stride", tuple, (1, 1)),
                  OpParam("pad", tuple, (0, 0)),
                  OpParam("dilate", tuple, (1, 1)),
                  OpParam("num_group", int, 1),
                  OpParam("no_bias", bool, True),
                  OpParam("binarize_input", bool, True),
                  OpParam("scaling", bool, True)],
          doc="Binary convolution (BMXNet QConvolution): ±1 weights/input, "
              "per-filter alpha scaling; lowers to a bf16 MXU conv")
def _q_convolution(x, weight, *bias, kernel=None, num_filter=None,
                   stride=(1, 1), pad=(0, 0), dilate=(1, 1), num_group=1,
                   no_bias=True, binarize_input=True, scaling=True):
    xb = _ste_sign(x) if binarize_input else x
    wb = _ste_sign(weight)
    nd_spatial = len(kernel)
    dn = lax.conv_dimension_numbers(
        xb.shape, wb.shape,
        ("NCHW", "OIHW", "NCHW") if nd_spatial == 2 else
        ("NCW", "OIW", "NCW"))
    y = lax.conv_general_dilated(
        xb, wb, window_strides=tuple(stride), padding=[(p, p) for p in pad],
        rhs_dilation=tuple(dilate), dimension_numbers=dn,
        feature_group_count=num_group)
    if scaling:
        alpha = jnp.mean(jnp.abs(weight), axis=tuple(
            range(1, weight.ndim)))                     # per output filter
        y = y * alpha.reshape((1, -1) + (1,) * nd_spatial)
    if not no_bias and bias:
        y = y + bias[0].reshape((1, -1) + (1,) * nd_spatial)
    return y


@register("QActivation", params=[OpParam("act_bit", int, 1),
                                OpParam("backward_only", bool, False)],
          doc="Quantized activation (BMXNet QActivation): 1 bit = STE sign "
              "of clipped input; k bit = uniform quantization of clip(x,0,1)")
def _q_activation(x, act_bit=1, backward_only=False):
    if act_bit == 1:
        return _ste_sign(jnp.clip(x, -1.0, 1.0))
    levels = (1 << act_bit) - 1

    @jax.custom_vjp
    def core(v):
        c = jnp.clip(v, 0.0, 1.0)
        return jnp.round(c * levels) / levels

    def fwd(v):
        return core(v), v

    def bwd(v, g):
        return (jnp.where((v >= 0) & (v <= 1), g, jnp.zeros_like(g)),)

    core.defvjp(fwd, bwd)
    return core(x)


@register("_contrib_ulysses_attention", num_inputs=3,
          params=[OpParam("axis_name", str, "seq"),
                  OpParam("causal", bool, False),
                  OpParam("batch_axis", str, "data")],
          doc="Ulysses all-to-all sequence-parallel attention over the "
              "current mesh (head-scatter alternative to ring attention; "
              "SURVEY §5.7). Eager execution falls back to the blockwise "
              "kernel like _contrib_ring_attention.")
def _ulysses_attention_op(q, k, v, axis_name="seq", causal=False,
                          batch_axis="data"):
    import jax
    from ..parallel.ring_attention import (blockwise_attention,
                                           ulysses_attention)
    from ..parallel.mesh import current_mesh
    if not isinstance(q, jax.core.Tracer):
        return blockwise_attention(q, k, v, block_size=q.shape[-2],
                                   causal=causal)
    return ulysses_attention(q, k, v, mesh=current_mesh(),
                             axis_name=axis_name, causal=causal,
                             batch_axis=batch_axis)


def _proposal_outputs(params):
    return 2 if params.get("output_score") else 1


# shared by Proposal and MultiProposal — MultiProposal forwards **kwargs
# into _proposal, so the two registrations must stay in lockstep
_PROPOSAL_PARAMS = [OpParam("rpn_pre_nms_top_n", int, 6000),
                    OpParam("rpn_post_nms_top_n", int, 300),
                    OpParam("threshold", float, 0.7),
                    OpParam("rpn_min_size", int, 16),
                    OpParam("scales", tuple, (4.0, 8.0, 16.0, 32.0)),
                    OpParam("ratios", tuple, (0.5, 1.0, 2.0)),
                    OpParam("feature_stride", int, 16),
                    OpParam("output_score", bool, False),
                    OpParam("iou_loss", bool, False)]


@register("_contrib_Proposal", aliases=["Proposal"], num_inputs=3,
          num_outputs=_proposal_outputs,
          params=list(_PROPOSAL_PARAMS),
          differentiable=False,
          doc="RPN proposal generation (ref: src/operator/contrib/"
              "proposal.cc): anchors + bbox deltas -> decode, clip, filter "
              "small, NMS, fixed top-N rows [batch_idx, x0, y0, x1, y1] "
              "(padded with -1) — static shapes throughout, vmapped over "
              "the batch.")
def _proposal(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n=6000,
              rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16,
              scales=(4.0, 8.0, 16.0, 32.0), ratios=(0.5, 1.0, 2.0),
              feature_stride=16, output_score=False, iou_loss=False):
    # cls_prob: (N, 2A, H, W) bg/fg per anchor; bbox_pred: (N, 4A, H, W)
    n, c, h, w = cls_prob.shape
    a = len(scales) * len(ratios)
    if c != 2 * a or bbox_pred.shape[1] != 4 * a:
        raise MXNetError(
            f"Proposal: cls_prob needs 2*A={2 * a} channels and bbox_pred "
            f"4*A={4 * a} for {len(scales)} scales x {len(ratios)} ratios; "
            f"got {c} and {bbox_pred.shape[1]}")
    # base anchors centered on each stride cell (reference GenerateAnchors)
    base = []
    cx = cy = (feature_stride - 1) / 2.0
    base_size = float(feature_stride)
    for r in ratios:
        size = base_size * base_size / r
        ws = jnp.sqrt(size)
        hs = ws * r
        for s in scales:
            bw, bh = ws * s, hs * s
            base.append([cx - (bw - 1) / 2, cy - (bh - 1) / 2,
                         cx + (bw - 1) / 2, cy + (bh - 1) / 2])
    base = jnp.asarray(base)                                  # (A, 4)
    sx = jnp.arange(w) * feature_stride
    sy = jnp.arange(h) * feature_stride
    sx, sy = jnp.meshgrid(sx, sy, indexing="xy")
    shifts = jnp.stack([sx.ravel(), sy.ravel(),
                        sx.ravel(), sy.ravel()], axis=1)      # (H*W, 4)
    anchors = (base[None, :, :] + shifts[:, None, :]).reshape(-1, 4)

    def one(scores_map, deltas_map, info):
        im_h, im_w, im_scale = info[0], info[1], info[2]
        scores = scores_map[a:].transpose(1, 2, 0).reshape(-1)  # fg probs
        deltas = deltas_map.transpose(1, 2, 0).reshape(-1, 4)
        if iou_loss:
            # corner-delta decode (reference IoUTransformInv)
            boxes = anchors + deltas
        else:
            # center-offset decode (reference NonLinearTransformInv)
            aw = anchors[:, 2] - anchors[:, 0] + 1.0
            ah = anchors[:, 3] - anchors[:, 1] + 1.0
            acx = anchors[:, 0] + 0.5 * (aw - 1)
            acy = anchors[:, 1] + 0.5 * (ah - 1)
            cx2 = deltas[:, 0] * aw + acx
            cy2 = deltas[:, 1] * ah + acy
            w2 = jnp.exp(jnp.clip(deltas[:, 2], -10, 10)) * aw
            h2 = jnp.exp(jnp.clip(deltas[:, 3], -10, 10)) * ah
            boxes = jnp.stack(
                [cx2 - 0.5 * (w2 - 1), cy2 - 0.5 * (h2 - 1),
                 cx2 + 0.5 * (w2 - 1), cy2 + 0.5 * (h2 - 1)], axis=1)
        boxes = jnp.stack([jnp.clip(boxes[:, 0], 0, im_w - 1),
                           jnp.clip(boxes[:, 1], 0, im_h - 1),
                           jnp.clip(boxes[:, 2], 0, im_w - 1),
                           jnp.clip(boxes[:, 3], 0, im_h - 1)], axis=1)
        # min-size filter in SCALED image pixels (reference: min_size *
        # im_info[2])
        min_sz = rpn_min_size * im_scale
        keep = ((boxes[:, 2] - boxes[:, 0] + 1 >= min_sz)
                & (boxes[:, 3] - boxes[:, 1] + 1 >= min_sz))
        scores = jnp.where(keep, scores, -1.0)
        pre_n = min(rpn_pre_nms_top_n, scores.shape[0])
        top_scores, order = jax.lax.top_k(scores, pre_n)
        rows = jnp.concatenate([top_scores[:, None], boxes[order]], axis=1)
        # NMS over ALL pre_nms candidates, then take the first post_n
        # SURVIVORS (compacted to the top) — the reference keeps scanning
        # past rank post_n until post_n survivors are collected
        nmsed = _box_nms(rows, overlap_thresh=threshold, valid_thresh=0.0,
                         topk=-1, coord_start=1, score_index=0,
                         id_index=-1)
        out_n = rpn_post_nms_top_n
        padded = jnp.full((out_n, 5), -1.0, rows.dtype)
        take = min(out_n, nmsed.shape[0])
        padded = padded.at[:take].set(nmsed[:take])
        return padded

    per_img = jax.vmap(one)(cls_prob, bbox_pred, im_info)   # (N, topN, 5)
    batch_idx = jnp.repeat(jnp.arange(n, dtype=per_img.dtype),
                           rpn_post_nms_top_n).reshape(n, -1, 1)
    valid = per_img[:, :, 0:1] >= 0
    rois = jnp.concatenate(
        [jnp.where(valid, batch_idx, -1.0), per_img[:, :, 1:5]], axis=-1)
    rois = rois.reshape(-1, 5)
    if output_score:
        return rois, per_img[:, :, 0].reshape(-1, 1)
    return rois


@register("_contrib_PSROIPooling", aliases=["PSROIPooling"], num_inputs=2,
          params=[OpParam("spatial_scale", float, None, required=True),
                  OpParam("output_dim", int, None, required=True),
                  OpParam("pooled_size", int, None, required=True),
                  OpParam("group_size", int, 0)],
          doc="Position-sensitive ROI pooling (ref: src/operator/contrib/"
              "psroi_pooling.cc, R-FCN): output channel d, bin (i,j) "
              "average-pools input channel (d*gs+g_i)*gs+g_j over the "
              "bin's integer extent. Formulated as separable row/col bin "
              "masks + ONE einsum per ROI so XLA maps it onto the MXU "
              "instead of the reference's per-bin CUDA loops.")
def _psroi_pooling(data, rois, spatial_scale=None, output_dim=None,
                   pooled_size=None, group_size=0):
    ph = pw = int(pooled_size)
    gs = int(group_size) or ph
    n, c, h, w = data.shape
    if c != output_dim * gs * gs:
        raise MXNetError(
            f"PSROIPooling: data needs output_dim*group_size^2 = "
            f"{output_dim}*{gs}^2 = {output_dim * gs * gs} channels, "
            f"got {c}")
    hs_idx = jnp.arange(h, dtype=jnp.float32)
    ws_idx = jnp.arange(w, dtype=jnp.float32)
    ii = jnp.arange(ph, dtype=jnp.float32)
    jj = jnp.arange(pw, dtype=jnp.float32)
    # bin (i,j) -> position-sensitive channel group (reference: gh =
    # floor(i*gs/ph), identity when gs == pooled_size)
    gh = jnp.clip(jnp.floor(ii * gs / ph), 0, gs - 1).astype(jnp.int32)
    gw = jnp.clip(jnp.floor(jj * gs / pw), 0, gs - 1).astype(jnp.int32)
    cidx = ((jnp.arange(int(output_dim))[:, None, None] * gs
             + gh[None, :, None]) * gs + gw[None, None, :])   # (od, ph, pw)

    def c_round(v):
        # C round(): half AWAY from zero — jnp.round is half-to-even,
        # which shifts bins for .5 coordinates (common after 0.5x scales)
        return jnp.sign(v) * jnp.floor(jnp.abs(v) + 0.5)

    def one_roi(roi):
        batch_idx = roi[0].astype(jnp.int32)
        # reference rounds ROI corners to pixels BEFORE scaling and adds 1
        # to the far edge
        x1 = c_round(roi[1]) * spatial_scale
        y1 = c_round(roi[2]) * spatial_scale
        x2 = c_round(roi[3] + 1.0) * spatial_scale
        y2 = c_round(roi[4] + 1.0) * spatial_scale
        bin_h = jnp.maximum(y2 - y1, 0.1) / ph
        bin_w = jnp.maximum(x2 - x1, 0.1) / pw
        hstart = jnp.clip(jnp.floor(y1 + ii * bin_h), 0, h)
        hend = jnp.clip(jnp.ceil(y1 + (ii + 1) * bin_h), 0, h)
        wstart = jnp.clip(jnp.floor(x1 + jj * bin_w), 0, w)
        wend = jnp.clip(jnp.ceil(x1 + (jj + 1) * bin_w), 0, w)
        row = ((hs_idx[None, :] >= hstart[:, None])
               & (hs_idx[None, :] < hend[:, None]))           # (ph, H)
        col = ((ws_idx[None, :] >= wstart[:, None])
               & (ws_idx[None, :] < wend[:, None]))           # (pw, W)
        img = lax.dynamic_index_in_dim(data, batch_idx, axis=0,
                                       keepdims=False)
        sums = jnp.einsum("ih,chw,jw->cij",
                          row.astype(jnp.float32),
                          img.astype(jnp.float32),
                          col.astype(jnp.float32))
        counts = (row.sum(-1).astype(jnp.float32)[:, None]
                  * col.sum(-1).astype(jnp.float32)[None, :])  # (ph, pw)
        avg = jnp.where(counts > 0, sums / jnp.maximum(counts, 1.0), 0.0)
        out = avg[cidx,
                  jnp.arange(ph)[None, :, None],
                  jnp.arange(pw)[None, None, :]]               # (od, ph, pw)
        return out.astype(data.dtype)

    return jax.vmap(one_roi)(rois)


@register("_contrib_MultiProposal", aliases=["MultiProposal"], num_inputs=3,
          num_outputs=_proposal_outputs,
          params=list(_PROPOSAL_PARAMS),
          differentiable=False,
          doc="Batched RPN proposals (ref: src/operator/contrib/"
              "multi_proposal.cc — upstream Proposal asserts batch 1 and "
              "MultiProposal re-implements it per image; this Proposal is "
              "vmapped over the batch already, so MultiProposal IS "
              "Proposal here).")
def _multi_proposal(cls_prob, bbox_pred, im_info, **kwargs):
    return _proposal(cls_prob, bbox_pred, im_info, **kwargs)


# ---------------------------------------------------------------------------
# Deformable convolution (ref: src/operator/contrib/deformable_convolution.cc
# + ../modulated_deformable_convolution.cc — hand-CUDA deformable_im2col
# there; here a fully vectorized bilinear-gather that XLA fuses, followed by
# one grouped einsum on the MXU. Differentiable in data/offset/mask/weight
# via autodiff (the reference hand-writes all three backward kernels).
# ---------------------------------------------------------------------------
def _deformable_sample(data, offset, mask, kernel, stride, dilate, pad,
                       num_deformable_group):
    """Bilinear-sample data at kernel-tap positions displaced by offset.

    data (N,C,H,W); offset (N, dg*2*kh*kw, oh, ow) with per-dg-block
    channel layout [2*t]=dy, [2*t+1]=dx of tap t (reference
    deformable_im2col channel order); mask (N, dg*kh*kw, oh, ow) or None.
    Returns columns (N, C, kh*kw, oh, ow).
    """
    n, c, h, w = data.shape
    kh, kw = kernel
    dg = num_deformable_group
    oh = (h + 2 * pad[0] - (dilate[0] * (kh - 1) + 1)) // stride[0] + 1
    ow = (w + 2 * pad[1] - (dilate[1] * (kw - 1) + 1)) // stride[1] + 1
    k = kh * kw
    off = offset.reshape(n, dg, k, 2, oh, ow)
    base_y = (jnp.arange(oh) * stride[0] - pad[0])[None, None, None, :,
                                                   None]
    base_x = (jnp.arange(ow) * stride[1] - pad[1])[None, None, None, None,
                                                   :]
    tap_y = jnp.repeat(jnp.arange(kh) * dilate[0],
                       kw).reshape(1, 1, k, 1, 1)
    tap_x = jnp.tile(jnp.arange(kw) * dilate[1],
                     kh).reshape(1, 1, k, 1, 1)
    py = base_y + tap_y + off[:, :, :, 0]           # (N, dg, K, oh, ow)
    px = base_x + tap_x + off[:, :, :, 1]

    y0 = jnp.floor(py)
    x0 = jnp.floor(px)
    wy1 = (py - y0).astype(data.dtype)
    wx1 = (px - x0).astype(data.dtype)
    dataf = data.reshape(n, dg, c // dg, h * w)

    def corner(yi, xi, wgt):
        # reference dmcn_im2col_bilinear: zero contribution outside
        valid = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
        yc = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
        xc = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
        idx = (yc * w + xc).reshape(n, dg, -1)
        gathered = jnp.take_along_axis(
            dataf, jnp.broadcast_to(idx[:, :, None, :],
                                    (n, dg, c // dg, idx.shape[-1])),
            axis=3).reshape(n, dg, c // dg, k, oh, ow)
        wgt = jnp.where(valid, wgt, 0.0).astype(data.dtype)
        return gathered * wgt[:, :, None]

    cols = (corner(y0, x0, (1 - wy1) * (1 - wx1))
            + corner(y0, x0 + 1, (1 - wy1) * wx1)
            + corner(y0 + 1, x0, wy1 * (1 - wx1))
            + corner(y0 + 1, x0 + 1, wy1 * wx1))
    if mask is not None:
        m = mask.reshape(n, dg, 1, k, oh, ow).astype(data.dtype)
        cols = cols * m
    return cols.reshape(n, c, k, oh, ow)


def _deformable_conv_impl(data, offset, mask, weight, bias, kernel, stride,
                          dilate, pad, num_filter, num_group,
                          num_deformable_group):
    n, c, _, _ = data.shape
    kh, kw = kernel
    cols = _deformable_sample(data, offset, mask, kernel, stride, dilate,
                              pad, num_deformable_group)
    _, _, _, oh, ow = cols.shape
    g = num_group
    colsr = cols.reshape(n, g, c // g, kh * kw, oh, ow)
    wr = weight.reshape(g, num_filter // g, c // g, kh * kw)
    out = jnp.einsum("ngckyx,gock->ngoyx", colsr, wr)
    out = out.reshape(n, num_filter, oh, ow)
    if bias is not None:
        out = out + bias.reshape(1, -1, 1, 1)
    return out


def _pairify(v, n=2):
    v = (v,) * n if isinstance(v, int) else tuple(v)
    return v * n if len(v) == 1 else v


@register("_contrib_DeformableConvolution",
          aliases=["DeformableConvolution"], num_inputs=-1,
          params=[OpParam("kernel", tuple, None, required=True),
                  OpParam("stride", tuple, None),
                  OpParam("dilate", tuple, None),
                  OpParam("pad", tuple, None),
                  OpParam("num_filter", int, None, required=True),
                  OpParam("num_group", int, 1),
                  OpParam("num_deformable_group", int, 1),
                  OpParam("no_bias", bool, False),
                  OpParam("layout", str, None),
                  OpParam("workspace", int, 1024)],
          doc="Deformable convolution v1 (ref: src/operator/contrib/"
              "deformable_convolution.cc). Inputs: data, offset "
              "(N, dg*2*kh*kw, oh, ow), weight, [bias]. Completes the "
              "Faster-RCNN/DCN op family.")
def _deformable_convolution(data, offset, weight, *bias, kernel=None,
                            stride=None, dilate=None, pad=None,
                            num_filter=None, num_group=1,
                            num_deformable_group=1, no_bias=False,
                            layout=None, workspace=1024):
    stride = _pairify(stride or 1)
    dilate = _pairify(dilate or 1)
    pad = _pairify(pad or 0)
    return _deformable_conv_impl(
        data, offset, None, weight,
        None if no_bias or not bias else bias[0], tuple(kernel), stride,
        dilate, pad, num_filter, num_group, num_deformable_group)


@register("_contrib_ModulatedDeformableConvolution",
          aliases=["ModulatedDeformableConvolution"], num_inputs=-1,
          params=[OpParam("kernel", tuple, None, required=True),
                  OpParam("stride", tuple, None),
                  OpParam("dilate", tuple, None),
                  OpParam("pad", tuple, None),
                  OpParam("num_filter", int, None, required=True),
                  OpParam("num_group", int, 1),
                  OpParam("num_deformable_group", int, 1),
                  OpParam("no_bias", bool, False),
                  OpParam("layout", str, None),
                  OpParam("workspace", int, 1024)],
          doc="DCNv2: adds a per-tap modulation mask input (ref: "
              "src/operator/contrib/modulated_deformable_convolution.cc). "
              "Inputs: data, offset, mask (N, dg*kh*kw, oh, ow), weight, "
              "[bias].")
def _modulated_deformable_convolution(data, offset, mask, weight, *bias,
                                      kernel=None, stride=None,
                                      dilate=None, pad=None,
                                      num_filter=None, num_group=1,
                                      num_deformable_group=1,
                                      no_bias=False, layout=None,
                                      workspace=1024):
    stride = _pairify(stride or 1)
    dilate = _pairify(dilate or 1)
    pad = _pairify(pad or 0)
    return _deformable_conv_impl(
        data, offset, mask, weight,
        None if no_bias or not bias else bias[0], tuple(kernel), stride,
        dilate, pad, num_filter, num_group, num_deformable_group)


@register("_contrib_count_sketch", aliases=["count_sketch"], num_inputs=3,
          params=[OpParam("out_dim", int, None, required=True),
                  OpParam("processing_batch_size", int, 32)],
          doc="Count sketch projection (ref: src/operator/contrib/"
              "count_sketch.cc, compact bilinear pooling): out[n, h[i]] "
              "+= s[i] * data[n, i]. Linear, so autodiff provides the "
              "reference's hand-written backward.")
def _count_sketch(data, h, s, out_dim=None, processing_batch_size=32):
    n, in_dim = data.shape
    hh = h.reshape(-1).astype(jnp.int32)
    ss = s.reshape(-1).astype(data.dtype)
    out = jnp.zeros((n, out_dim), data.dtype)
    return out.at[:, hh].add(data * ss[None, :])


# ---------------------------------------------------------------------------
# XNOR-popcount packed binary inference (the BMXNet fork's signature
# capability, SURVEY §2 #23: smd_hpi/src xnor GEMM with int32 bit packing).
# Weights/activations store ONE BIT per value (32x memory compression);
# the ±1 dot product is  K - 2*popcount(xor(a, b))  over packed words,
# computed with lax.population_count on the VPU. On TPU the bf16 MXU
# matmul of ±1 values is usually FASTER (docs/divergences.md) — the packed
# path's win is memory/bandwidth (deployment), exactly like the
# reference's mobile targets.
# ---------------------------------------------------------------------------
def _pack_bits_lastdim(x):
    """Sign-bit pack the last dim into uint32 words (bit i of word j =
    sign(x[..., 32j+i]) >= 0). Pad tail bits with +1 (consistent packing
    of both operands makes pads xor to 0 and drop out of the popcount)."""
    k = x.shape[-1]
    words = -(-k // 32)
    pad = words * 32 - k
    bits = (x >= 0)
    if pad:
        bits = jnp.concatenate(
            [bits, jnp.ones(x.shape[:-1] + (pad,), bool)], axis=-1)
    bits = bits.reshape(x.shape[:-1] + (words, 32))
    weights = (jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32))
    return jnp.sum(bits.astype(jnp.uint32) * weights, axis=-1,
                   dtype=jnp.uint32)


@register("_contrib_binary_pack", aliases=["binary_pack"],
          differentiable=False,
          doc="Pack sign bits of the last dim into uint32 words "
              "(BMXNet binary_word packing, 32x weight compression)")
def _binary_pack(x):
    return _pack_bits_lastdim(x)


@register("_contrib_xnor_fully_connected", num_inputs=-1,
          params=[OpParam("in_dim", int, None, required=True)],
          differentiable=False,
          doc="Packed-binary GEMM: y = in_dim - 2*popcount(xor) over "
              "uint32-packed ±1 rows (BMXNet xnor_gemm). Inputs: x_packed "
              "[N, W32], w_packed [num_hidden, W32], (alpha [num_hidden] "
              "fp32 scale), (bias).")
def _xnor_fully_connected(xp, wp, *rest, in_dim=None):
    pc = jnp.sum(lax.population_count(
        jnp.bitwise_xor(xp[:, None, :], wp[None, :, :])).astype(jnp.int32),
        axis=-1)
    y = (in_dim - 2 * pc).astype(jnp.float32)
    if rest:
        y = y * rest[0]      # alpha: scalar or [num_hidden], broadcasts
    if len(rest) > 1:
        y = y + rest[1]
    return y


@register("_contrib_xnor_convolution", num_inputs=-1,
          params=[OpParam("kernel", tuple, None, required=True),
                  OpParam("num_filter", int, None, required=True),
                  OpParam("stride", tuple, (1, 1)),
                  OpParam("pad", tuple, (0, 0))],
          differentiable=False,
          doc="Packed-binary convolution: im2col patches packed to uint32, "
              "then the xnor-popcount GEMM (BMXNet binary conv inference). "
              "Inputs: x fp (binarized+packed internally), w_packed "
              "[num_filter, W32] packed over (C*kh*kw), (alpha), (bias). "
              "Padding uses +1 bits (BMXNet pads with +1, not 0).")
def _xnor_convolution(x, wp, *rest, kernel=None, num_filter=None,
                      stride=(1, 1), pad=(0, 0)):
    kh, kw = kernel
    n = x.shape[0]
    # im2col: [N, C*kh*kw, OH, OW] patches; pad value +1 keeps the ±1
    # algebra exact (sign bit of +1 is 1)
    xpad = jnp.pad(x, ((0, 0), (0, 0), (pad[0], pad[0]),
                       (pad[1], pad[1])), constant_values=1.0)
    patches = lax.conv_general_dilated_patches(
        xpad, filter_shape=(kh, kw), window_strides=tuple(stride),
        padding=[(0, 0), (0, 0)])
    _, ckk, oh, ow = patches.shape
    cols = patches.transpose(0, 2, 3, 1).reshape(n * oh * ow, ckk)
    xp = _pack_bits_lastdim(cols)
    pc = jnp.sum(lax.population_count(
        jnp.bitwise_xor(xp[:, None, :], wp[None, :, :])).astype(jnp.int32),
        axis=-1)
    y = (ckk - 2 * pc).astype(jnp.float32)
    if rest:
        y = y * rest[0]      # alpha: scalar or [num_filter], broadcasts
    if len(rest) > 1:
        y = y + rest[1]
    return y.reshape(n, oh, ow, num_filter).transpose(0, 3, 1, 2)


@register("_contrib_fused_self_attention", num_inputs=1,
          params=[OpParam("heads", int, None, required=True),
                  OpParam("causal", bool, False),
                  OpParam("block_size", int, 512)],
          doc="Self-attention straight off the fused QKV projection "
              "(B, S, 3C), q-major column blocks. Short sequences compute "
              "softmax(QK^T)V with einsums over the (B, S, H, D) layout — "
              "no data-movement transposes, XLA folds the head split into "
              "the matmuls (measured: the (3,B,H,S,D) permute chain cost "
              "~6 GB/step of layout copies in BERT, docs/perf_notes.md). "
              "Long sequences route to the streaming flash path.")
def _fused_self_attention(qkv, heads=None, causal=False, block_size=512):
    b, s, c3 = qkv.shape
    c = c3 // 3
    d = c // heads
    q = qkv[:, :, :c].reshape(b, s, heads, d)
    k = qkv[:, :, c:2 * c].reshape(b, s, heads, d)
    v = qkv[:, :, 2 * c:].reshape(b, s, heads, d)
    if s <= 1024:
        from .tensor import shifted_expsum
        scale = float(d) ** -0.5
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        if causal:
            qi = jnp.arange(s)[:, None]
            ki = jnp.arange(s)[None, :]
            scores = jnp.where(qi >= ki, scores,
                               jnp.finfo(scores.dtype).min)
        _, shifted, se32 = shifted_expsum(scores, axis=-1)
        att = (jnp.exp(shifted).astype(jnp.float32)
               / se32).astype(q.dtype)
        out = jnp.einsum("bhqk,bkhd->bqhd", att, v)
        return out.reshape(b, s, c)
    # long-sequence streaming path wants [B, H, S, D]; the downstream
    # kernels clamp block_size to a divisor of S themselves
    # (blockwise_attention), so callers stay shape-free — required for
    # symbolic export of attention blocks
    qh = q.transpose(0, 2, 1, 3)
    kh = k.transpose(0, 2, 1, 3)
    vh = v.transpose(0, 2, 1, 3)
    out = _flash_attention(qh, kh, vh, block_size=block_size,
                           causal=causal)
    return out.transpose(0, 2, 1, 3).reshape(b, s, c)


@register("_contrib_fused_cross_attention", num_inputs=2,
          params=[OpParam("heads", int, None, required=True),
                  OpParam("block_size", int, 512)],
          doc="Cross-attention off fused projections: q (B, Sq, C) "
              "attends over kv (B, Sk, 2C) — the decoder→encoder shape "
              "of the NMT transformer. Same (B, S, H, D) einsum layout "
              "and fp32-accumulated softmax as "
              "_contrib_fused_self_attention; shape-free for callers so "
              "decoder blocks export symbolically.")
def _fused_cross_attention(q_in, kv, heads=None, block_size=512):
    b, sq, c = q_in.shape
    sk = kv.shape[1]
    d = c // heads
    q = q_in.reshape(b, sq, heads, d)
    k = kv[:, :, :c].reshape(b, sk, heads, d)
    v = kv[:, :, c:].reshape(b, sk, heads, d)
    if sk <= 1024:
        from .tensor import shifted_expsum
        scale = float(d) ** -0.5
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        _, shifted, se32 = shifted_expsum(scores, axis=-1)
        att = (jnp.exp(shifted).astype(jnp.float32)
               / se32).astype(q.dtype)
        out = jnp.einsum("bhqk,bkhd->bqhd", att, v)
        return out.reshape(b, sq, c)
    out = _flash_attention(q.transpose(0, 2, 1, 3),
                           k.transpose(0, 2, 1, 3),
                           v.transpose(0, 2, 1, 3), block_size=block_size)
    return out.transpose(0, 2, 1, 3).reshape(b, sq, c)


# ---------------------------------------------------------------------------
# FFT (ref: src/operator/contrib/fft.cc, ifft.cc)
# ---------------------------------------------------------------------------

@register("_contrib_fft", aliases=["fft"],
          params=[OpParam("compute_size", int, 128)],
          doc="1-D FFT over the last axis; real input (..., d) -> "
              "interleaved real/imag output (..., 2*d), matching the "
              "reference's cuFFT wire format "
              "(ref: src/operator/contrib/fft.cc). compute_size (the "
              "reference's batching knob for cuFFT plans) is accepted "
              "and ignored — XLA plans the whole batch at once.")
def _fft(x, compute_size=128):
    spec = jnp.fft.fft(x.astype(jnp.float32), axis=-1)
    out = jnp.stack([spec.real, spec.imag], axis=-1)
    return out.reshape(x.shape[:-1] + (2 * x.shape[-1],)).astype(jnp.float32)


@register("_contrib_ifft", aliases=["ifft"],
          params=[OpParam("compute_size", int, 128)],
          doc="Inverse of _contrib_fft: interleaved (..., 2*d) -> real "
              "(..., d). Like the reference (cuFFT CUFFT_INVERSE), the "
              "output is UNNORMALIZED: ifft(fft(x)) == d * x "
              "(ref: src/operator/contrib/ifft.cc).")
def _ifft(x, compute_size=128):
    d = x.shape[-1] // 2
    pairs = x.reshape(x.shape[:-1] + (d, 2)).astype(jnp.float32)
    spec = lax.complex(pairs[..., 0], pairs[..., 1])
    # unnormalized inverse = conj(fft(conj(spec))); jnp.fft.ifft divides
    # by d, so scale back up to match the reference wire format
    return (jnp.fft.ifft(spec, axis=-1).real * d).astype(jnp.float32)


# ---------------------------------------------------------------------------
# Spatial sampling trio (ref: src/operator/{grid_generator,
# bilinear_sampler, spatial_transformer}.cc). All three share one
# bilinear-gather core, the same machinery ROIAlign/DeformableConv use,
# but with the reference's zero-padding boundary (outside samples read 0)
# instead of border clamping.
# ---------------------------------------------------------------------------

def _bilinear_sample_zero_pad(img, xf, yf):
    """Sample img (C, H, W) at float pixel coords xf/yf (...,) with
    bilinear interpolation and zero padding outside; differentiable in
    img and coords. Vectorized: one advanced-indexing gather per corner,
    which XLA lowers to a single gather + FMA chain per corner (VPU
    work), the TPU-native shape of the reference's per-pixel CUDA loop."""
    h, w = img.shape[1], img.shape[2]
    x0 = jnp.floor(xf)
    y0 = jnp.floor(yf)
    wx = xf - x0
    wy = yf - y0

    def corner(yi, xi, wgt):
        inb = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
        yc = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
        xc = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
        vals = img[:, yc, xc]                     # (C, ...)
        return jnp.where(inb[None], vals * wgt[None], 0.0)

    return (corner(y0, x0, (1 - wy) * (1 - wx))
            + corner(y0 + 1, x0, wy * (1 - wx))
            + corner(y0, x0 + 1, (1 - wy) * wx)
            + corner(y0 + 1, x0 + 1, wy * wx))


@register("BilinearSampler", num_inputs=2,
          params=[OpParam("cudnn_off", bool, False)],
          doc="Sample data (B, C, H, W) at grid (B, 2, Ho, Wo) of "
              "normalized [-1, 1] (x, y) coords; zero padding outside "
              "(ref: src/operator/bilinear_sampler.cc). x maps to "
              "(x+1)*(W-1)/2 like the reference.")
def _bilinear_sampler(data, grid, cudnn_off=False):
    h, w = data.shape[2], data.shape[3]

    def one(img, g):
        xf = (g[0] + 1.0) * (w - 1.0) / 2.0
        yf = (g[1] + 1.0) * (h - 1.0) / 2.0
        return _bilinear_sample_zero_pad(img, xf, yf)

    return jax.vmap(one)(data, grid)


@register("GridGenerator", num_inputs=1,
          params=[OpParam("transform_type", str, "affine", required=True),
                  OpParam("target_shape", tuple, (0, 0))],
          doc="Generate BilinearSampler grids "
              "(ref: src/operator/grid_generator.cc). 'affine': data "
              "(B, 6) 2x3 matrices over a normalized [-1, 1] target "
              "grid -> (B, 2, H, W). 'warp': data = pixel flow "
              "(B, 2, H, W) added to the identity grid, normalized.")
def _grid_generator(data, transform_type="affine", target_shape=(0, 0)):
    if transform_type == "affine":
        hh, ww = int(target_shape[0]), int(target_shape[1])
        b = data.shape[0]
        ys = jnp.linspace(-1.0, 1.0, hh) if hh > 1 else jnp.zeros((1,))
        xs = jnp.linspace(-1.0, 1.0, ww) if ww > 1 else jnp.zeros((1,))
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        ones = jnp.ones_like(gx)
        src = jnp.stack([gx.ravel(), gy.ravel(), ones.ravel()])  # (3, H*W)
        theta = data.reshape(b, 2, 3).astype(jnp.float32)
        grid = jnp.einsum("bij,jk->bik", theta, src)             # (B, 2, H*W)
        return grid.reshape(b, 2, hh, ww).astype(data.dtype)
    if transform_type == "warp":
        b, _, hh, ww = data.shape
        base_x, base_y = jnp.meshgrid(jnp.arange(ww, dtype=jnp.float32),
                                      jnp.arange(hh, dtype=jnp.float32),
                                      indexing="xy")
        x = data[:, 0] + base_x
        y = data[:, 1] + base_y
        xn = x * (2.0 / max(ww - 1, 1)) - 1.0
        yn = y * (2.0 / max(hh - 1, 1)) - 1.0
        return jnp.stack([xn, yn], axis=1).astype(data.dtype)
    raise MXNetError(f"GridGenerator: unknown transform_type {transform_type!r}")


@register("SpatialTransformer", num_inputs=2,
          params=[OpParam("transform_type", str, "affine", required=True),
                  OpParam("sampler_type", str, "bilinear", required=True),
                  OpParam("target_shape", tuple, (0, 0)),
                  OpParam("cudnn_off", bool, False)],
          doc="Affine spatial transformer = GridGenerator('affine') + "
              "BilinearSampler, fused in one traced graph so XLA shares "
              "the grid across channels "
              "(ref: src/operator/spatial_transformer.cc).")
def _spatial_transformer(data, loc, transform_type="affine",
                         sampler_type="bilinear", target_shape=(0, 0),
                         cudnn_off=False):
    if transform_type != "affine" or sampler_type != "bilinear":
        raise MXNetError("SpatialTransformer supports transform_type='affine'"
                         " sampler_type='bilinear' (like the reference)")
    hh, ww = int(target_shape[0]), int(target_shape[1])
    if hh <= 0 or ww <= 0:
        hh, ww = data.shape[2], data.shape[3]
    grid = _grid_generator(loc, transform_type="affine",
                           target_shape=(hh, ww))
    return _bilinear_sampler(data, grid)


# ---------------------------------------------------------------------------
# round-5 contrib stragglers: the small parity ops reference scripts touch
# ---------------------------------------------------------------------------

@register("_contrib_quadratic", aliases=["quadratic"],
          params=[OpParam("a", float, 0.0), OpParam("b", float, 0.0),
                  OpParam("c", float, 0.0)],
          doc="a*x^2 + b*x + c — the reference's custom-op tutorial op "
              "(ref: src/operator/contrib/quadratic_op.cc)")
def _quadratic(x, a=0.0, b=0.0, c=0.0):
    return a * x * x + b * x + c


@register("_contrib_allclose", aliases=["allclose"], num_inputs=2,
          params=[OpParam("rtol", float, 1e-5), OpParam("atol", float, 1e-8),
                  OpParam("equal_nan", bool, False)],
          differentiable=False,
          doc="Elementwise closeness reduced to one scalar (ref: "
              "src/operator/contrib/allclose_op.cc)")
def _allclose(a, b, rtol=1e-5, atol=1e-8, equal_nan=False):
    return jnp.allclose(a, b, rtol=rtol, atol=atol,
                        equal_nan=equal_nan).astype(jnp.float32)


@register("_contrib_index_copy", aliases=["index_copy"], num_inputs=3,
          doc="Copy rows of new_tensor into old_tensor at index (ref: "
              "src/operator/contrib/index_copy.cc); functional on TPU — "
              "returns the updated array instead of mutating")
def _index_copy(old, index, new):
    if not isinstance(index, jax.core.Tracer):
        idx = jnp.asarray(index)
        n = old.shape[0]
        if idx.size and (int(idx.min()) < 0 or int(idx.max()) >= n):
            raise MXNetError(
                f"index_copy: index out of range for dim-0 size {n} "
                f"(got min {int(idx.min())}, max {int(idx.max())}) — the "
                "reference validates bounds; a silent scatter-drop would "
                "leave rows un-copied")
    return old.at[index.astype(jnp.int32)].set(new)


@register("_contrib_boolean_mask", aliases=["boolean_mask"], num_inputs=2,
          params=[OpParam("axis", int, 0)], differentiable=False,
          doc="Select rows where mask != 0 (ref: src/operator/contrib/"
              "boolean_mask.cc). DATA-DEPENDENT output shape: eager-only "
              "(a jit trace would need static shapes — use `where` with a "
              "neutral fill, or SequenceMask, inside compiled code)")
def _boolean_mask(data, mask, axis=0):
    if isinstance(data, jax.core.Tracer) or isinstance(mask,
                                                       jax.core.Tracer):
        raise MXNetError(
            "boolean_mask has a data-dependent output shape and cannot "
            "run inside jit/hybridize; use where/SequenceMask there")
    import numpy as _onp
    mask_np = _onp.asarray(mask)
    if mask_np.ndim != 1:
        raise MXNetError(
            f"boolean_mask: mask must be 1-D, got shape {mask_np.shape} "
            "(a 2-D mask would index one row per nonzero ELEMENT)")
    if mask_np.shape[0] != data.shape[axis]:
        raise MXNetError(
            f"boolean_mask: mask length {mask_np.shape[0]} != data axis "
            f"{axis} size {data.shape[axis]}")
    keep = _onp.nonzero(mask_np != 0)[0]
    return jnp.take(data, jnp.asarray(keep, jnp.int32), axis=axis)


@register("_contrib_BatchNormWithReLU", aliases=["BatchNormWithReLU"],
          num_inputs=5, num_outputs=3, needs_mode=True,
          params=[OpParam("eps", float, 1e-3),
                  OpParam("momentum", float, 0.9),
                  OpParam("fix_gamma", bool, True),
                  OpParam("use_global_stats", bool, False),
                  OpParam("output_mean_var", bool, False),
                  OpParam("axis", int, 1),
                  OpParam("cudnn_off", bool, False)],
          doc="BatchNorm with fused ReLU epilogue (ref: src/operator/nn/"
              "batch_norm_relu.cc); XLA fuses the max into the normalize")
def _batch_norm_with_relu(x, gamma, beta, moving_mean, moving_var, **kw):
    from .nn import _batch_norm
    out, mean, var = _batch_norm(x, gamma, beta, moving_mean, moving_var,
                                 **kw)
    return jnp.maximum(out, 0.0).astype(out.dtype), mean, var

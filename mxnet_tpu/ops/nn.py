"""Neural-network operators.

TPU-native equivalent of ``src/operator/nn/`` — the reference's cuDNN-backed
Convolution/Pooling/BatchNorm/etc. become ``lax.conv_general_dilated`` /
``lax.reduce_window`` / jnp compositions that XLA tiles onto the MXU. The
fused cuDNN RNN op (ref: src/operator/rnn.cc) becomes a ``lax.scan`` cell;
dropout threads explicit PRNG keys (JAX-idiomatic replacement for the
reference's Resource-managed RNG states, ref: src/resource.cc).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError, _as_np_dtype
from .registry import OpParam, register


def _pair(v, n):
    v = tuple(v) if not isinstance(v, int) else (v,) * n
    if len(v) == 1:
        v = v * n
    return v


# ---------------------------------------------------------------------------
# FullyConnected (ref: src/operator/nn/fully_connected.cc)
# ---------------------------------------------------------------------------
@register("FullyConnected", num_inputs=-1,
          params=[OpParam("num_hidden", int, None, required=True),
                  OpParam("no_bias", bool, False),
                  OpParam("flatten", bool, True)],
          doc="y = x W^T + b (ref: src/operator/nn/fully_connected.cc); the "
              "canonical MXU matmul — keep batched and wide")
def _fully_connected(x, weight, *bias, num_hidden=None, no_bias=False, flatten=True):
    if flatten:
        x = x.reshape(x.shape[0], -1)
    y = jnp.matmul(x, weight.T)
    if not no_bias:
        y = y + bias[0]
    return y


# ---------------------------------------------------------------------------
# Convolution / Deconvolution (ref: src/operator/nn/convolution.cc,
# src/operator/nn/cudnn/cudnn_convolution-inl.h — autotune is XLA's job here)
# ---------------------------------------------------------------------------
def _conv_dims(ndim):
    if ndim == 3:
        return ("NCW", "OIW", "NCW")
    if ndim == 4:
        return ("NCHW", "OIHW", "NCHW")
    if ndim == 5:
        return ("NCDHW", "OIDHW", "NCDHW")
    raise MXNetError(f"Convolution: unsupported input ndim {ndim}")


@register("Convolution", num_inputs=-1,
          params=[OpParam("kernel", tuple, None, required=True),
                  OpParam("stride", tuple, None),
                  OpParam("dilate", tuple, None),
                  OpParam("pad", tuple, None),
                  OpParam("num_filter", int, None, required=True),
                  OpParam("num_group", int, 1),
                  OpParam("no_bias", bool, False),
                  OpParam("layout", str, None),
                  OpParam("cudnn_tune", str, None),
                  OpParam("cudnn_off", bool, False),
                  OpParam("workspace", int, 1024)],
          doc="N-D convolution, NCHW/OIHW layouts "
              "(ref: src/operator/nn/convolution.cc ConvolutionCompute)")
def _convolution(x, weight, *bias, kernel=None, stride=None, dilate=None,
                 pad=None, num_filter=None, num_group=1, no_bias=False,
                 layout=None, cudnn_tune=None, cudnn_off=False, workspace=1024):
    nd = len(kernel)
    stride = _pair(stride or 1, nd)
    dilate = _pair(dilate or 1, nd)
    pad = _pair(pad or 0, nd)
    dn = lax.conv_dimension_numbers(x.shape, weight.shape, _conv_dims(x.ndim))
    out = lax.conv_general_dilated(
        x, weight, window_strides=stride,
        padding=[(p, p) for p in pad], rhs_dilation=dilate,
        dimension_numbers=dn, feature_group_count=num_group)
    if not no_bias:
        out = out + bias[0].reshape((1, -1) + (1,) * nd)
    return out


@register("Deconvolution", num_inputs=-1,
          params=[OpParam("kernel", tuple, None, required=True),
                  OpParam("stride", tuple, None),
                  OpParam("dilate", tuple, None),
                  OpParam("pad", tuple, None),
                  OpParam("adj", tuple, None),
                  OpParam("num_filter", int, None, required=True),
                  OpParam("num_group", int, 1),
                  OpParam("no_bias", bool, True),
                  OpParam("layout", str, None),
                  OpParam("workspace", int, 1024),
                  OpParam("cudnn_tune", str, None),
                  OpParam("cudnn_off", bool, False),
                  OpParam("target_shape", tuple, None)],
          doc="Transposed convolution (ref: src/operator/nn/deconvolution.cc)")
def _deconvolution(x, weight, *bias, kernel=None, stride=None, dilate=None,
                   pad=None, adj=None, num_filter=None, num_group=1,
                   no_bias=True, layout=None, workspace=1024, cudnn_tune=None,
                   cudnn_off=False, target_shape=None):
    nd = len(kernel)
    stride = _pair(stride or 1, nd)
    dilate = _pair(dilate or 1, nd)
    pad = _pair(pad or 0, nd)
    adj = _pair(adj or 0, nd)
    # grad-of-conv formulation: lhs_dilation=stride implements the transpose
    dn = lax.conv_dimension_numbers(x.shape, weight.shape, _conv_dims(x.ndim))
    k_eff = [(kernel[i] - 1) * dilate[i] + 1 for i in range(nd)]
    padding = [(k_eff[i] - 1 - pad[i], k_eff[i] - 1 - pad[i] + adj[i])
               for i in range(nd)]
    # weight layout for deconv in the reference is (in, out/g, *k): swap I/O and
    # flip spatial axes to express as a regular conv
    w = jnp.flip(weight, axis=tuple(range(2, 2 + nd)))
    if num_group > 1:
        ci = w.shape[0]
        w = w.reshape((num_group, ci // num_group) + w.shape[1:])
        w = jnp.swapaxes(w, 1, 2)
        w = w.reshape((w.shape[0] * w.shape[1], ci // num_group) + w.shape[3:])
    else:
        w = jnp.swapaxes(w, 0, 1)
    out = lax.conv_general_dilated(
        x, w, window_strides=(1,) * nd, padding=padding,
        lhs_dilation=stride, rhs_dilation=dilate, dimension_numbers=dn,
        feature_group_count=num_group)
    if not no_bias and bias:
        out = out + bias[0].reshape((1, -1) + (1,) * nd)
    return out


# ---------------------------------------------------------------------------
# Pooling (ref: src/operator/nn/pooling.cc)
# ---------------------------------------------------------------------------
@register("Pooling",
          params=[OpParam("kernel", tuple, ()),
                  OpParam("pool_type", str, "max"),
                  OpParam("global_pool", bool, False),
                  OpParam("stride", tuple, None),
                  OpParam("pad", tuple, None),
                  OpParam("pooling_convention", str, "valid"),
                  OpParam("count_include_pad", bool, True),
                  OpParam("cudnn_off", bool, False),
                  OpParam("layout", str, None)],
          doc="Max/avg/sum/lp pooling via lax.reduce_window "
              "(ref: src/operator/nn/pooling.cc)")
def _pooling(x, kernel=(), pool_type="max", global_pool=False, stride=None,
             pad=None, pooling_convention="valid", count_include_pad=True,
             cudnn_off=False, layout=None):
    nd = x.ndim - 2
    if global_pool:
        axes = tuple(range(2, x.ndim))
        if pool_type == "max":
            return jnp.max(x, axis=axes, keepdims=True)
        return jnp.mean(x, axis=axes, keepdims=True)
    kernel = _pair(kernel, nd)
    stride = _pair(stride or 1, nd)
    pad = _pair(pad or 0, nd)
    window = (1, 1) + kernel
    strides = (1, 1) + stride
    padding = ((0, 0), (0, 0)) + tuple((p, p) for p in pad)
    if pooling_convention == "full":
        # ceil-mode: add extra right-padding so the last window fits
        extra = []
        for i in range(nd):
            size = x.shape[2 + i] + 2 * pad[i] - kernel[i]
            rem = size % stride[i]
            extra.append((stride[i] - rem) % stride[i] if rem else 0)
        padding = ((0, 0), (0, 0)) + tuple(
            (pad[i], pad[i] + extra[i]) for i in range(nd))
    if pool_type == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
        return lax.reduce_window(x, init, lax.max, window, strides, padding)
    if pool_type in ("avg", "sum"):
        summed = lax.reduce_window(x, 0.0, lax.add, window, strides, padding)
        if pool_type == "sum":
            return summed
        if count_include_pad:
            # python-level product: kernel is static, and a jnp.prod here
            # becomes a traced op under jit (float() then fails)
            import math
            return summed / float(math.prod(kernel))
        ones = jnp.ones_like(x)
        counts = lax.reduce_window(ones, 0.0, lax.add, window, strides, padding)
        return summed / counts
    if pool_type == "lp":
        p = 2.0
        s = lax.reduce_window(jnp.abs(x) ** p, 0.0, lax.add, window, strides, padding)
        return s ** (1.0 / p)
    raise MXNetError(f"Pooling: unknown pool_type {pool_type!r}")


# ---------------------------------------------------------------------------
# Activations (ref: src/operator/nn/activation.cc, leaky_relu.cc)
# ---------------------------------------------------------------------------
@register("Activation", params=[OpParam("act_type", str, None, required=True)],
          doc="ref: src/operator/nn/activation.cc")
def _activation(x, act_type=None):
    if act_type == "relu":
        return jnp.maximum(x, 0)
    if act_type == "sigmoid":
        return jax.nn.sigmoid(x)
    if act_type == "tanh":
        return jnp.tanh(x)
    if act_type == "softrelu":
        return jax.nn.softplus(x)
    if act_type == "softsign":
        return jax.nn.soft_sign(x)
    if act_type == "relu6":
        return jnp.clip(x, 0, 6)
    raise MXNetError(f"Activation: unknown act_type {act_type!r}")


@register("LeakyReLU", num_inputs=-1,
          params=[OpParam("act_type", str, "leaky"),
                  OpParam("slope", float, 0.25),
                  OpParam("lower_bound", float, 0.125),
                  OpParam("upper_bound", float, 0.334)],
          doc="leaky/prelu/elu/selu/gelu family (ref: src/operator/leaky_relu.cc)")
def _leaky_relu(x, *gamma, act_type="leaky", slope=0.25, lower_bound=0.125,
                upper_bound=0.334):
    if act_type == "leaky":
        return jnp.where(x >= 0, x, slope * x)
    if act_type == "prelu":
        g = gamma[0]
        if g.ndim == 1 and x.ndim > 1:
            g = g.reshape((1, -1) + (1,) * (x.ndim - 2))
        return jnp.where(x >= 0, x, g * x)
    if act_type == "elu":
        return jnp.where(x >= 0, x, slope * jnp.expm1(x))
    if act_type == "selu":
        return jax.nn.selu(x)
    if act_type == "gelu":
        return jax.nn.gelu(x, approximate=False)
    if act_type == "rrelu":
        mid = (lower_bound + upper_bound) / 2.0
        return jnp.where(x >= 0, x, mid * x)
    raise MXNetError(f"LeakyReLU: unknown act_type {act_type!r}")


@register("softmax", params=[OpParam("axis", int, -1),
                             OpParam("temperature", float, None),
                             OpParam("length", tuple, None),
                             OpParam("dtype", str, None)],
          doc="ref: src/operator/nn/softmax.cc")
def _softmax(x, axis=-1, temperature=None, length=None, dtype=None):
    if temperature:
        x = x / temperature
    out = jax.nn.softmax(x, axis=axis)
    return out.astype(_as_np_dtype(dtype)) if dtype else out


@register("log_softmax", params=[OpParam("axis", int, -1),
                                 OpParam("temperature", float, None)],
          doc="ref: src/operator/nn/softmax.cc log_softmax")
def _log_softmax(x, axis=-1, temperature=None):
    if temperature:
        x = x / temperature
    # max-shifted with fp32-accumulated row sums: under bf16 AMP this is one
    # fused read of x with no fp32 materialization of the full tensor (the
    # [tokens, vocab] MLM-head case is HBM-dominant otherwise)
    from .tensor import shifted_expsum
    _, shifted, se32 = shifted_expsum(x, axis=axis)
    return shifted - jnp.log(se32).astype(x.dtype)


@register("softmin", params=[OpParam("axis", int, -1)])
def _softmin(x, axis=-1):
    return jax.nn.softmax(-x, axis=axis)


@register("SoftmaxActivation", params=[OpParam("mode", str, "instance")])
def _softmax_activation(x, mode="instance"):
    if mode == "channel":
        return jax.nn.softmax(x, axis=1)
    # explicit product, not -1 (ambiguous on zero-size inputs)
    return jax.nn.softmax(x.reshape(x.shape[0], math.prod(x.shape[1:])),
                          axis=-1).reshape(x.shape)


# ---------------------------------------------------------------------------
# Normalization (ref: src/operator/nn/batch_norm.cc, layer_norm.cc,
# group_norm.cc, instance_norm.cc, l2_normalization.cc)
# ---------------------------------------------------------------------------
@register("BatchNorm", num_inputs=5, num_outputs=3, needs_mode=True,
          params=[OpParam("eps", float, 1e-3),
                  OpParam("momentum", float, 0.9),
                  OpParam("fix_gamma", bool, True),
                  OpParam("use_global_stats", bool, False),
                  OpParam("output_mean_var", bool, False),
                  OpParam("axis", int, 1),
                  OpParam("cudnn_off", bool, False),
                  OpParam("act_type", str, None,
                          doc="fuse an activation into the normalize pass "
                              "(the conv-epilogue lever, docs/pallas.md): "
                              "the scale*x+offset multiply-add and the "
                              "activation run as ONE VMEM pass through the "
                              "mxnet_tpu.pallas conv_epilogue kernel on "
                              "TPU, with a parity-gated XLA fallback "
                              "elsewhere")],
          doc="Batch normalization. Inputs: data, gamma, beta, moving_mean, "
              "moving_var. Outputs: (out, batch_mean, batch_var) — like the "
              "reference's three NNVM outputs; running-stat update is done "
              "functionally by the caller (ref: src/operator/nn/batch_norm.cc)")
def _batch_norm(x, gamma, beta, moving_mean, moving_var, eps=1e-3, momentum=0.9,
                fix_gamma=True, use_global_stats=False, output_mean_var=False,
                axis=1, cudnn_off=False, act_type=None, training=False):
    axes = tuple(i for i in range(x.ndim) if i != axis % x.ndim)
    bshape = [1] * x.ndim
    bshape[axis % x.ndim] = x.shape[axis % x.ndim]
    if fix_gamma:
        gamma = jnp.ones_like(gamma)
    if training and not use_global_stats:
        # one-pass batch stats accumulated in fp32: a single fused read of x
        # instead of jnp.var's mean-then-centered-moments passes — this
        # keeps the op HBM-minimal under bf16 AMP, where the step is
        # bandwidth-bound (see docs/perf_notes.md). The raw E[x^2]-E[x]^2
        # form cancels catastrophically when |mean| >> std, so moments are
        # shifted by the running mean — the only shift that is FREE: any
        # same-pass data-derived shift (measured round 3: even one element
        # per channel) breaks XLA's reduce+normalize fusion and costs
        # 11-25% of RN50 throughput, and a lax.cond exact-recompute branch
        # fails to compile inside the differentiated scanned step. Safety
        # instead comes from two sides: (a) the gluon layer adopts the
        # first batch's stats outright at cold start (basic_layers.py), so
        # the shift is within O(std) of the true mean from step 2 on; (b)
        # in-op, channels where cancellation provably destroyed var
        # ((mean-c)² > 4095·var ⇒ >12 bits lost) fall back to e2 = the
        # second moment about c — a bounded, already-computed normalizer
        # (output std ≤ 1) instead of rsqrt(garbage) (the round-2 advisor
        # measured output std 158 at mean=1e4 on zero-init stats).
        c = lax.stop_gradient(moving_mean.astype(jnp.float32))
        cb = c.reshape(bshape)
        xc = x.astype(jnp.float32) - cb
        mean_c = jnp.mean(xc, axis=axes)
        e2 = jnp.mean(jnp.square(xc), axis=axes)
        var_raw = jnp.maximum(e2 - jnp.square(mean_c), 0.0)
        mean = mean_c + c
        suspicious = e2 > 4096.0 * jnp.maximum(var_raw, 1e-30)
        # normalize with the bounded fallback, but REPORT var_raw: the
        # layer detects the cancelled case as mean² >> reported var and
        # refuses to put it into the running stats (reporting e2 would
        # defeat that test — e2 ≈ mean² exactly when suspicious)
        var_norm = jnp.where(suspicious, e2, var_raw)
        var = var_raw
    else:
        mean = moving_mean.astype(jnp.float32)
        var = moving_var.astype(jnp.float32)
        var_norm = var
    # fold (mean, var, gamma, beta) into per-channel scale/offset in fp32,
    # cast once to the compute dtype: the normalize pass over x is then a
    # single fused multiply-add in x's dtype (no fp32 upcast of the tensor)
    inv = lax.rsqrt(var_norm + eps)
    scale = inv * gamma.astype(jnp.float32)
    offset = beta.astype(jnp.float32) - mean * scale
    if act_type is None:
        out = x * scale.astype(x.dtype).reshape(bshape) \
            + offset.astype(x.dtype).reshape(bshape)
    else:
        # BN+activation epilogue through the guarded kernel tier: one
        # VMEM pass on TPU, the numerics-contract XLA reference (same
        # fp32 fold, journaled fallback) everywhere else
        from ..pallas import fused_conv_epilogue
        out = fused_conv_epilogue(
            x, scale=scale.astype(x.dtype), bias=offset.astype(x.dtype),
            channel_axis=axis, act_type=act_type)
    return out, mean.astype(moving_mean.dtype), var.astype(moving_var.dtype)


def _moments_acc(x, axes):
    """Centered two-pass moments with accumulation in at least fp32
    (fp64 stays fp64): safe for |mean| >> std inputs — the raw one-pass
    E[x^2]-E[x]^2 form cancels catastrophically there, and bf16
    accumulation (x's own dtype) loses the variance of wide rows.
    BatchNorm keeps its one-pass form because its running mean provides
    a stable shift (see _batch_norm)."""
    acc = jnp.promote_types(x.dtype, jnp.float32)
    xf = x.astype(acc)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=axes, keepdims=True)
    return mean, var


@register("LayerNorm", num_inputs=3,
          params=[OpParam("axis", int, -1), OpParam("eps", float, 1e-5),
                  OpParam("output_mean_var", bool, False)],
          doc="ref: src/operator/nn/layer_norm.cc")

def _layer_norm(x, gamma, beta, axis=-1, eps=1e-5, output_mean_var=False):
    mean, var = _moments_acc(x, axis)
    inv = lax.rsqrt(var + eps)
    bshape = [1] * x.ndim
    bshape[axis % x.ndim] = x.shape[axis % x.ndim]
    out = (x - mean.astype(x.dtype)) * inv.astype(x.dtype)
    return out * gamma.reshape(bshape) + beta.reshape(bshape)


@register("GroupNorm", num_inputs=3,
          params=[OpParam("num_groups", int, 1), OpParam("eps", float, 1e-5)],
          doc="ref: src/operator/nn/group_norm.cc")
def _group_norm(x, gamma, beta, num_groups=1, eps=1e-5):
    n, c = x.shape[0], x.shape[1]
    g = num_groups
    xg = x.reshape((n, g, c // g) + x.shape[2:])
    axes = tuple(range(2, xg.ndim))
    mean, var = _moments_acc(xg, axes)
    xg = (xg - mean.astype(xg.dtype)) \
        * lax.rsqrt(var + eps).astype(xg.dtype)
    out = xg.reshape(x.shape)
    bshape = (1, c) + (1,) * (x.ndim - 2)
    return out * gamma.reshape(bshape) + beta.reshape(bshape)


@register("InstanceNorm", num_inputs=3, params=[OpParam("eps", float, 1e-3)],
          doc="ref: src/operator/instance_norm.cc")
def _instance_norm(x, gamma, beta, eps=1e-3):
    axes = tuple(range(2, x.ndim))
    mean, var = _moments_acc(x, axes)
    out = (x - mean.astype(x.dtype)) \
        * lax.rsqrt(var + eps).astype(x.dtype)
    bshape = (1, x.shape[1]) + (1,) * (x.ndim - 2)
    return out * gamma.reshape(bshape) + beta.reshape(bshape)


@register("L2Normalization",
          params=[OpParam("eps", float, 1e-10), OpParam("mode", str, "instance")],
          doc="ref: src/operator/l2_normalization.cc")
def _l2_normalization(x, eps=1e-10, mode="instance"):
    if mode == "instance":
        norm = jnp.sqrt(jnp.sum(jnp.square(x.reshape(x.shape[0], -1)), axis=1) + eps)
        return x / norm.reshape((-1,) + (1,) * (x.ndim - 1))
    if mode == "channel":
        norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=1, keepdims=True) + eps)
        return x / norm
    if mode == "spatial":
        axes = tuple(range(2, x.ndim))
        norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axes, keepdims=True) + eps)
        return x / norm
    raise MXNetError(f"L2Normalization: unknown mode {mode!r}")


@register("RMSNorm", num_inputs=2,
          params=[OpParam("axis", int, -1), OpParam("eps", float, 1e-6)],
          doc="RMSNorm (new op — modern LLM parity; no reference analog)")
def _rms_norm(x, gamma, axis=-1, eps=1e-6):
    ms = jnp.mean(jnp.square(x), axis=axis, keepdims=True)
    return x * lax.rsqrt(ms + eps) * gamma


# ---------------------------------------------------------------------------
# Dropout (ref: src/operator/nn/dropout.cc) — explicit PRNG key threading
# ---------------------------------------------------------------------------
@register("Dropout", needs_rng=True, needs_mode=True,
          params=[OpParam("p", float, 0.5),
                  OpParam("mode", str, "training"),
                  OpParam("axes", tuple, ())],
          doc="Inverted dropout; rng key threaded explicitly "
              "(ref: src/operator/nn/dropout.cc)")
def _dropout(x, rng=None, p=0.5, mode="training", axes=(), training=False):
    if p <= 0 or (not training and mode != "always"):
        return x
    shape = list(x.shape)
    for a in axes:
        shape[a] = 1
    # one random BYTE per element, not bernoulli's uint32+float compare:
    # 4x less generator work and mask traffic — dropout-mask generation
    # measured 24% of a BERT step before the rbg+bits treatment
    # (docs/perf_notes.md round 3). Keep-probability granularity is
    # 1/256, immaterial for dropout rates.
    bits = jax.random.bits(rng, tuple(shape), dtype=jnp.uint8)
    # ONE definition of the keep threshold (pallas.keep_threshold): the
    # fused matmul-epilogue's bit-identical-mask contract depends on it
    from ..pallas.kernels import keep_threshold
    keep = bits >= jnp.uint8(keep_threshold(p))
    return jnp.where(keep, x / (1.0 - p), jnp.zeros_like(x))


# ---------------------------------------------------------------------------
# Embedding (ref: src/operator/tensor/indexing_op.cc EmbeddingOpForward)
# ---------------------------------------------------------------------------
@register("Embedding", num_inputs=2,
          params=[OpParam("input_dim", int, None, required=True),
                  OpParam("output_dim", int, None, required=True),
                  OpParam("dtype", str, "float32"),
                  OpParam("sparse_grad", bool, False)],
          doc="Lookup table (ref: indexing_op.cc Embedding)")
def _embedding(indices, weight, input_dim=None, output_dim=None,
               dtype="float32", sparse_grad=False):
    return jnp.take(weight, indices.astype(jnp.int32), axis=0)


# ---------------------------------------------------------------------------
# SoftmaxOutput — softmax forward + CE gradient in backward, the Module-era
# classification head (ref: src/operator/softmax_output.cc)
# ---------------------------------------------------------------------------
def _softmax_output_fwd(data, label, grad_scale, ignore_label, multi_output,
                        use_ignore, normalization, out_grad, smooth_alpha):
    return jax.nn.softmax(data, axis=-1)


@jax.custom_vjp
def _softmax_output_core(data, label, grad_scale, ignore_label, use_ignore):
    return jax.nn.softmax(data, axis=-1)


def _softmax_output_core_fwd(data, label, grad_scale, ignore_label, use_ignore):
    out = jax.nn.softmax(data, axis=-1)
    return out, (out, label, grad_scale, ignore_label, use_ignore)


def _softmax_output_core_bwd(res, g):
    out, label, grad_scale, ignore_label, use_ignore = res
    num_classes = out.shape[-1]
    onehot = jax.nn.one_hot(label.astype(jnp.int32), num_classes, dtype=out.dtype)
    grad = (out - onehot) * grad_scale
    if use_ignore:
        mask = (label != ignore_label).astype(out.dtype)
        grad = grad * mask[..., None]
    # reference ignores incoming head gradient (it's a terminal loss op)
    return grad, jnp.zeros_like(label, dtype=out.dtype), None, None, None


_softmax_output_core.defvjp(_softmax_output_core_fwd, _softmax_output_core_bwd)


@register("SoftmaxOutput", num_inputs=2,
          params=[OpParam("grad_scale", float, 1.0),
                  OpParam("ignore_label", float, -1.0),
                  OpParam("multi_output", bool, False),
                  OpParam("use_ignore", bool, False),
                  OpParam("preserve_shape", bool, False),
                  OpParam("normalization", str, "null"),
                  OpParam("out_grad", bool, False),
                  OpParam("smooth_alpha", float, 0.0)],
          doc="Softmax with cross-entropy backward "
              "(ref: src/operator/softmax_output.cc)")
def _softmax_output(data, label, grad_scale=1.0, ignore_label=-1.0,
                    multi_output=False, use_ignore=False, preserve_shape=False,
                    normalization="null", out_grad=False, smooth_alpha=0.0):
    orig_shape = data.shape
    if multi_output and data.ndim > 2:
        # (N, C, d...) -> softmax over C per spatial position
        data2 = jnp.moveaxis(data, 1, -1)
        out = _softmax_output_core(data2.reshape(-1, data2.shape[-1]),
                                   label.reshape(-1).astype(data.dtype),
                                   grad_scale, ignore_label, use_ignore)
        out = out.reshape(data2.shape)
        return jnp.moveaxis(out, -1, 1)
    if data.ndim > 2 and not preserve_shape:
        data = data.reshape(data.shape[0], -1)
    return _softmax_output_core(data, label.astype(data.dtype), grad_scale,
                                ignore_label, use_ignore).reshape(orig_shape)


def _regression_core(link, grad_fn):
    @jax.custom_vjp
    def core(data, label, grad_scale):
        return link(data)

    def fwd(data, label, grad_scale):
        return link(data), (link(data), label, grad_scale)

    def bwd(res, g):
        out, label, grad_scale = res
        n = out.shape[1] if out.ndim > 1 else 1
        grad = grad_fn(out, label.reshape(out.shape)) * grad_scale / n
        return grad, jnp.zeros_like(out), None

    core.defvjp(fwd, bwd)
    return core


_linear_reg = _regression_core(lambda x: x, lambda o, l: o - l)
_mae_reg = _regression_core(lambda x: x, lambda o, l: jnp.sign(o - l))
_logistic_reg = _regression_core(lambda x: jax.nn.sigmoid(x),
                                 lambda o, l: o - l)


@register("LinearRegressionOutput", num_inputs=2,
          params=[OpParam("grad_scale", float, 1.0)],
          doc="Identity forward, (pred-label) backward "
              "(ref: src/operator/regression_output.cc)")
def _linear_regression_output(data, label, grad_scale=1.0):
    return _linear_reg(data, label.astype(data.dtype), grad_scale)


@register("MAERegressionOutput", num_inputs=2,
          params=[OpParam("grad_scale", float, 1.0)],
          doc="ref: src/operator/regression_output.cc (MAE head)")
def _mae_regression_output(data, label, grad_scale=1.0):
    return _mae_reg(data, label.astype(data.dtype), grad_scale)


@register("LogisticRegressionOutput", num_inputs=2,
          params=[OpParam("grad_scale", float, 1.0)],
          doc="Sigmoid forward, (sigmoid-label) backward "
              "(ref: src/operator/regression_output.cc)")
def _logistic_regression_output(data, label, grad_scale=1.0):
    return _logistic_reg(data, label.astype(data.dtype), grad_scale)


@register("MakeLoss", params=[OpParam("grad_scale", float, 1.0),
                              OpParam("valid_thresh", float, 0.0),
                              OpParam("normalization", str, "null")],
          doc="Marks a symbol as a loss: forward=identity, backward=grad_scale "
              "(ref: src/operator/make_loss.cc)")
def _make_loss(x, grad_scale=1.0, valid_thresh=0.0, normalization="null"):
    @jax.custom_vjp
    def core(v):
        return v

    def fwd(v):
        return v, v.shape

    def bwd(shape, g):
        return (jnp.full(shape, grad_scale),)

    core.defvjp(fwd, bwd)
    return core(x)


@register("smooth_l1", params=[OpParam("scalar", float, 1.0)],
          doc="Huber-like loss elementwise (ref: src/operator/tensor/"
              "elemwise_binary_scalar_op_extended.cc smooth_l1)")
def _smooth_l1(x, scalar=1.0):
    s2 = scalar * scalar
    return jnp.where(jnp.abs(x) < 1.0 / s2, 0.5 * s2 * jnp.square(x),
                     jnp.abs(x) - 0.5 / s2)


# ---------------------------------------------------------------------------
# Fused RNN (ref: src/operator/rnn.cc — cuDNN fused multi-layer RNN).
# Parameters arrive as ONE flat vector in cuDNN layout order, exactly like the
# reference, so checkpoints/scripts port directly. Compute is lax.scan over
# time — XLA compiles to a tight TPU loop.
# ---------------------------------------------------------------------------
def _rnn_gate_count(mode):
    return {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}[mode]


def _rnn_unpack(params, mode, num_layers, input_size, state_size, bidirectional,
                projection_size=None):
    """Slice the flat param vector into per-layer (Wx, Wh, bx, bh[, Wr])
    in the reference's layout: all weights first (layer-major, i2h then
    h2h then the LSTMP projection when present, directions interleaved),
    then all biases (ref: rnn-inl.h GetRnnParamSize incl. LSTMP)."""
    g = _rnn_gate_count(mode)
    d = 2 if bidirectional else 1
    proj = projection_size
    h_out = proj if proj else state_size      # recurrent/output width
    off = 0
    sizes = []
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else h_out * d
        for _dir in range(d):
            sizes.append(("wx", g * state_size, in_sz))
            sizes.append(("wh", g * state_size, h_out))
            if proj:
                sizes.append(("wr", proj, state_size))
    mats = []
    for kind, r, c in sizes:
        mats.append(params[off:off + r * c].reshape(r, c))
        off += r * c
    biases = []
    for layer in range(num_layers):
        for _dir in range(d):
            biases.append(params[off:off + g * state_size]); off += g * state_size
            biases.append(params[off:off + g * state_size]); off += g * state_size
    out = []
    mi = 0
    bi = 0
    per_dir = 3 if proj else 2
    for layer in range(num_layers):
        dirs = []
        for _dir in range(d):
            wx, wh = mats[mi], mats[mi + 1]
            wr = mats[mi + 2] if proj else None
            mi += per_dir
            bx, bh = biases[bi], biases[bi + 1]; bi += 2
            dirs.append((wx, wh, bx, bh, wr))
        out.append(dirs)
    return out


def _rnn_cell_step(mode, carry, x_t, wx, wh, bx, bh, state_size,
                   wr=None):
    if mode == "lstm":
        h, c = carry
        gates = x_t @ wx.T + bx + h @ wh.T + bh
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        c = f * c + i * g
        h = o * jnp.tanh(c)
        if wr is not None:           # LSTMP: project the hidden state
            h = h @ wr.T
        return (h, c), h
    if mode == "gru":
        h = carry[0]
        gx = x_t @ wx.T + bx
        gh = h @ wh.T + bh
        rx, zx, nx = jnp.split(gx, 3, axis=-1)
        rh, zh, nh = jnp.split(gh, 3, axis=-1)
        r = jax.nn.sigmoid(rx + rh)
        z = jax.nn.sigmoid(zx + zh)
        n = jnp.tanh(nx + r * nh)
        h = (1 - z) * n + z * h
        return (h,), h
    act = jnp.tanh if mode == "rnn_tanh" else (lambda v: jnp.maximum(v, 0))
    h = carry[0]
    h = act(x_t @ wx.T + bx + h @ wh.T + bh)
    return (h,), h


def _rnn_layer_scan(mode, x, h0, c0, weights, state_size, reverse=False,
                    seq_len=None):
    wx, wh, bx, bh, wr = weights
    carry0 = (h0, c0) if mode == "lstm" else (h0,)
    T = x.shape[0]

    def step(carry, inp):
        x_t, t = inp
        new_carry, y = _rnn_cell_step(mode, carry, x_t, wx, wh, bx, bh,
                                      state_size, wr=wr)
        if seq_len is not None:
            # cuDNN varlen semantics: beyond a sequence's length the
            # state holds and outputs are zero (ref: rnn.cc
            # use_sequence_length; works for the reverse direction too —
            # the held initial state enters at t = len-1)
            valid = (t < seq_len)[:, None]
            new_carry = tuple(jnp.where(valid, nc, oc)
                              for nc, oc in zip(new_carry, carry))
            y = jnp.where(valid, y, jnp.zeros_like(y))
        return new_carry, y

    carry, ys = lax.scan(step, carry0,
                         (x, jnp.arange(T)), reverse=reverse)
    return carry, ys


def _rnn_outputs(params):
    mode = params.get("mode", "lstm")
    if not params.get("state_outputs", False):
        return 1
    return 3 if mode == "lstm" else 2


@register("RNN", num_inputs=-1, num_outputs=_rnn_outputs, needs_rng=True,
          needs_mode=True,
          params=[OpParam("state_size", int, None, required=True),
                  OpParam("num_layers", int, None, required=True),
                  OpParam("mode", str, "lstm"),
                  OpParam("bidirectional", bool, False),
                  OpParam("p", float, 0.0, doc="dropout between layers"),
                  OpParam("state_outputs", bool, False),
                  OpParam("projection_size", int, None),
                  OpParam("use_sequence_length", bool, False)],
          doc="Fused multi-layer RNN/LSTM/GRU over time via lax.scan "
              "(ref: src/operator/rnn.cc, rnn-inl.h; cuDNN-layout flat params). "
              "Inputs: data (T,N,C), params(flat), state, [state_cell].")
def _rnn(data, params, state, *rest, rng=None, state_size=None, num_layers=None,
         mode="lstm", bidirectional=False, p=0.0, state_outputs=False,
         projection_size=None, use_sequence_length=False, training=False):
    if projection_size is not None and mode != "lstm":
        raise MXNetError("RNN: projection_size is an LSTM(P) feature")
    rest = list(rest)
    state_cell = rest.pop(0) if (mode == "lstm" and rest) else None
    seq_len = None
    if use_sequence_length:
        if not rest:
            raise MXNetError("RNN: use_sequence_length=True needs a "
                             "sequence_length input (N,)")
        seq_len = rest.pop(0).astype(jnp.int32)
    d = 2 if bidirectional else 1
    layers = _rnn_unpack(params, mode, num_layers, data.shape[-1], state_size,
                         bidirectional, projection_size=projection_size)
    x = data
    hs, cs = [], []
    for li, dirs in enumerate(layers):
        outs = []
        for di, weights in enumerate(dirs):
            idx = li * d + di
            h0 = state[idx]
            c0 = state_cell[idx] if state_cell is not None else None
            carry, ys = _rnn_layer_scan(mode, x, h0, c0, weights, state_size,
                                        reverse=(di == 1), seq_len=seq_len)
            if di == 1:
                pass  # lax.scan(reverse=True) already emits outputs in orig order
            outs.append(ys)
            hs.append(carry[0])
            if mode == "lstm":
                cs.append(carry[1])
        x = outs[0] if d == 1 else jnp.concatenate(outs, axis=-1)
        if p > 0 and training and li < len(layers) - 1 and rng is not None:
            keep = jax.random.bernoulli(jax.random.fold_in(rng, li), 1.0 - p, x.shape)
            x = jnp.where(keep, x / (1.0 - p), jnp.zeros_like(x))
    hy = jnp.stack(hs, axis=0)
    if not state_outputs:
        return x
    if mode == "lstm":
        return x, hy, jnp.stack(cs, axis=0)
    return x, hy


# ---------------------------------------------------------------------------
# correlation / upsampling / misc layers used by zoos
# ---------------------------------------------------------------------------
@register("UpSampling", num_inputs=-1,
          params=[OpParam("scale", int, 1, required=True),
                  OpParam("sample_type", str, "nearest"),
                  OpParam("num_args", int, 1),
                  OpParam("num_filter", int, 0),
                  OpParam("multi_input_mode", str, "concat"),
                  OpParam("workspace", int, 512)],
          doc="ref: src/operator/upsampling.cc (nearest mode)")
def _upsampling(*args, scale=1, sample_type="nearest", num_args=1, num_filter=0,
                multi_input_mode="concat", workspace=512):
    x = args[0]
    if sample_type != "nearest":
        raise MXNetError("UpSampling: only nearest supported; use "
                         "contrib.BilinearResize2D for bilinear")
    out = jnp.repeat(jnp.repeat(x, scale, axis=2), scale, axis=3)
    return out


# ---------------------------------------------------------------------------
# CTC loss (ref: src/operator/contrib/ctc_loss.cc / 3rdparty warp-ctc).
# TPU-native design: the alpha recursion is a lax.scan over time — static
# shapes, log-space accumulation, fully fused by XLA.
# ---------------------------------------------------------------------------
def _ctc_alpha_scan(logp, ext_labels, T_mask, S_len):
    """logp: (T, N, C) log-probs; ext_labels: (N, S) blank-interleaved labels;
    T_mask: (T, N) bool valid-time mask; S_len: (N,) valid ext length."""
    T, N, C = logp.shape
    S = ext_labels.shape[1]
    neg_inf = jnp.asarray(-1e30, logp.dtype)
    # emission log-probs per extended label position: (T, N, S)
    emit = jnp.take_along_axis(
        logp, jnp.broadcast_to(ext_labels[None], (T, N, S)), axis=2)

    # allow skip from s-2 when current label != label at s-2 and != blank
    can_skip = jnp.concatenate(
        [jnp.zeros((N, 2), bool),
         (ext_labels[:, 2:] != ext_labels[:, :-2]) &
         (ext_labels[:, 2:] != C - 1)],
        axis=1)

    alpha0 = jnp.full((N, S), neg_inf)
    alpha0 = alpha0.at[:, 0].set(emit[0, :, 0])
    alpha0 = alpha0.at[:, 1].set(jnp.where(S_len > 1, emit[0, :, 1], neg_inf))

    def step(alpha, inputs):
        emit_t, valid_t = inputs
        shift1 = jnp.concatenate([jnp.full((N, 1), neg_inf), alpha[:, :-1]],
                                 axis=1)
        shift2 = jnp.concatenate([jnp.full((N, 2), neg_inf), alpha[:, :-2]],
                                 axis=1)
        shift2 = jnp.where(can_skip, shift2, neg_inf)
        merged = jnp.logaddexp(jnp.logaddexp(alpha, shift1), shift2) + emit_t
        new = jnp.where(valid_t[:, None], merged, alpha)
        return new, None

    alpha, _ = lax.scan(step, alpha0, (emit[1:], T_mask[1:]))
    last = jnp.take_along_axis(alpha, (S_len - 1)[:, None], axis=1)[:, 0]
    last2 = jnp.take_along_axis(
        alpha, jnp.maximum(S_len - 2, 0)[:, None], axis=1)[:, 0]
    return -jnp.logaddexp(last, jnp.where(S_len > 1, last2, neg_inf))


@register("CTCLoss", num_inputs=-1, aliases=["ctc_loss", "_contrib_CTCLoss"],
          params=[OpParam("use_data_lengths", bool, False),
                  OpParam("use_label_lengths", bool, False),
                  OpParam("blank_label", str, "last"),
                  OpParam("data_lengths", None, None),
                  OpParam("label_lengths", None, None)],
          doc="CTC loss, alpha recursion as lax.scan "
              "(ref: src/operator/contrib/ctc_loss.cc). Input (T, N, C) "
              "activations (softmax applied internally), labels (N, L).")
def _ctc_loss(data, labels, *lens, use_data_lengths=False,
              use_label_lengths=False, blank_label="last", data_lengths=None,
              label_lengths=None):
    li = list(lens)
    if use_data_lengths and data_lengths is None:
        data_lengths = li.pop(0)
    if use_label_lengths and label_lengths is None:
        label_lengths = li.pop(0)
    # lengths may arrive as kwargs carrying NDArrays (the reference's calling
    # convention) — unwrap to jax arrays
    if data_lengths is not None:
        data_lengths = jnp.asarray(getattr(data_lengths, "_data", data_lengths))
    if label_lengths is not None:
        label_lengths = jnp.asarray(getattr(label_lengths, "_data",
                                            label_lengths))
    T, N, C = data.shape
    if labels.shape[1] == 0:
        # no labels: the only path is all blanks
        logp0 = jax.nn.log_softmax(data, axis=2)
        blank0 = C - 1 if blank_label == "last" else 0
        t_mask = jnp.arange(T)[:, None] < (
            data_lengths.astype(jnp.int32)[None, :] if data_lengths is not None
            else jnp.full((1, N), T))
        return -jnp.sum(jnp.where(t_mask, logp0[:, :, blank0], 0.0), axis=0)
    logp = jax.nn.log_softmax(data, axis=2)
    labels = labels.astype(jnp.int32)
    L = labels.shape[1]
    if blank_label == "last":
        blank = C - 1
    else:  # 'first': class 0 is blank; shift labels down like the reference
        blank = C - 1
        logp = jnp.concatenate([logp[:, :, 1:], logp[:, :, :1]], axis=2)
        labels = labels - 1
    if label_lengths is None:
        # labels padded with values < 0 (or == blank) don't count
        label_len = jnp.sum((labels >= 0) & (labels < C - 1), axis=1)
    else:
        label_len = label_lengths.astype(jnp.int32)
    if data_lengths is None:
        t_len = jnp.full((N,), T, jnp.int32)
    else:
        t_len = data_lengths.astype(jnp.int32)

    # blank-interleaved extended labels: (N, 2L+1)
    S = 2 * L + 1
    ext = jnp.full((N, S), blank, jnp.int32)
    safe_labels = jnp.clip(labels, 0, C - 1)
    ext = ext.at[:, 1::2].set(safe_labels)
    S_len = 2 * label_len + 1
    T_mask = (jnp.arange(T)[:, None] < t_len[None, :])
    return _ctc_alpha_scan(logp, ext, T_mask, S_len)

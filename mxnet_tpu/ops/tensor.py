"""Tensor ops: reductions, shape manipulation, indexing, ordering, linalg.

TPU-native equivalent of the reference's ``src/operator/tensor/`` (broadcast
reduce ops, matrix ops, indexing, ordering) — each a jnp/lax composition,
shape-static so XLA can tile onto the MXU/VPU.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .registry import OpParam, register

# ---------------------------------------------------------------------------
# reductions (ref: src/operator/tensor/broadcast_reduce_op_value.cc)
# ---------------------------------------------------------------------------


def _norm_axis(axis, ndim, exclude=False):
    if axis is None:
        return None
    if isinstance(axis, int):
        axis = (axis,)
    axis = tuple(a % ndim for a in axis)
    if exclude:
        axis = tuple(a for a in range(ndim) if a not in axis)
    return axis


def _reduce(fn, diff=True, name=None, extra=None, doc=""):
    params = [
        OpParam("axis", tuple, None, doc="axis/axes to reduce over"),
        OpParam("keepdims", bool, False),
        OpParam("exclude", bool, False, doc="reduce over all axes EXCEPT `axis`"),
    ] + (extra or [])

    def impl(x, axis=None, keepdims=False, exclude=False):
        ax = _norm_axis(axis, x.ndim, exclude)
        return fn(x, axis=ax, keepdims=keepdims)

    register(name, params=params, differentiable=diff,
             doc=doc or f"{name} reduction (ref: broadcast_reduce_op_value.cc)")(impl)


_reduce(jnp.sum, name="sum", doc="Sum over axes")
_reduce(jnp.mean, name="mean", doc="Mean over axes")
_reduce(jnp.prod, name="prod", doc="Product over axes")
_reduce(jnp.max, name="max", doc="Max over axes")
_reduce(jnp.min, name="min", doc="Min over axes")
_reduce(jnp.nansum, name="nansum")
_reduce(jnp.nanprod, name="nanprod")


@register("argmax", differentiable=False,
          params=[OpParam("axis", int, None), OpParam("keepdims", bool, False)],
          doc="Index of max along axis (ref: broadcast_reduce_op_index.cc)")
def _argmax(x, axis=None, keepdims=False):
    out = jnp.argmax(x, axis=axis)
    if keepdims and axis is not None:
        out = jnp.expand_dims(out, axis)
    return out.astype(jnp.float32)  # reference returns float indices


@register("argmin", differentiable=False,
          params=[OpParam("axis", int, None), OpParam("keepdims", bool, False)])
def _argmin(x, axis=None, keepdims=False):
    out = jnp.argmin(x, axis=axis)
    if keepdims and axis is not None:
        out = jnp.expand_dims(out, axis)
    return out.astype(jnp.float32)


@register("norm",
          params=[OpParam("ord", int, 2), OpParam("axis", tuple, None),
                  OpParam("keepdims", bool, False)],
          doc="L-p norm (ref: src/operator/tensor/broadcast_reduce_norm_value.cc)")
def _norm(x, ord=2, axis=None, keepdims=False):
    ax = _norm_axis(axis, x.ndim)
    if ord == 1:
        return jnp.sum(jnp.abs(x), axis=ax, keepdims=keepdims)
    return jnp.sqrt(jnp.sum(jnp.square(x), axis=ax, keepdims=keepdims))


# ---------------------------------------------------------------------------
# shape manipulation (ref: src/operator/tensor/matrix_op.cc)
# ---------------------------------------------------------------------------


@register("Reshape", aliases=["reshape"],
          params=[OpParam("shape", tuple, None, required=True),
                  OpParam("reverse", bool, False)],
          doc="Reshape with the reference's special codes 0,-1,-2,-3,-4 "
              "(ref: matrix_op.cc Reshape)")
def _reshape(x, shape=None, reverse=False):
    src = list(x.shape)
    if reverse:
        src = src[::-1]
        shape = tuple(shape)[::-1]
    out = []
    i = 0  # index into src
    j = 0
    shape = list(shape)
    while j < len(shape):
        s = shape[j]
        if s == 0:          # copy dim
            out.append(src[i]); i += 1
        elif s == -1:       # infer
            out.append(-1); i += 1
        elif s == -2:       # copy all remaining
            out.extend(src[i:]); i = len(src)
        elif s == -3:       # merge two dims
            out.append(src[i] * src[i + 1]); i += 2
        elif s == -4:       # split dim into next two numbers
            a, b = shape[j + 1], shape[j + 2]
            d = src[i]
            if a == -1:
                a = d // b
            if b == -1:
                b = d // a
            out.extend([a, b]); i += 1; j += 2
        else:
            out.append(int(s)); i += 1
        j += 1
    if reverse:
        out = out[::-1]
    return jnp.reshape(x, tuple(out))


@register("transpose", params=[OpParam("axes", tuple, None)],
          doc="Permute axes (ref: matrix_op.cc transpose)")
def _transpose(x, axes=None):
    return jnp.transpose(x, axes)


@register("SwapAxis", aliases=["swapaxes"],
          params=[OpParam("dim1", int, 0), OpParam("dim2", int, 0)],
          doc="ref: src/operator/swapaxis.cc")
def _swapaxes(x, dim1=0, dim2=0):
    return jnp.swapaxes(x, dim1, dim2)


@register("moveaxis", params=[OpParam("source", tuple, None, required=True),
                              OpParam("destination", tuple, None, required=True)])
def _moveaxis(x, source=None, destination=None):
    return jnp.moveaxis(x, source, destination)


@register("expand_dims", params=[OpParam("axis", int, 0, required=True)])
def _expand_dims(x, axis=0):
    return jnp.expand_dims(x, axis)


@register("squeeze", params=[OpParam("axis", tuple, None)])
def _squeeze(x, axis=None):
    return jnp.squeeze(x, axis)


@register("Flatten", aliases=["flatten"],
          doc="Collapse all but first axis (ref: matrix_op.cc Flatten)")
def _flatten(x):
    # explicit product, not -1: a zero-size leading axis makes -1
    # ambiguous (jnp raises ZeroDivisionError)
    return jnp.reshape(x, (x.shape[0], math.prod(x.shape[1:])))


@register("reverse", aliases=["flip"], params=[OpParam("axis", tuple, None, required=True)])
def _reverse(x, axis=None):
    return jnp.flip(x, axis)


@register("tile", params=[OpParam("reps", tuple, None, required=True)])
def _tile(x, reps=None):
    return jnp.tile(x, reps)


@register("repeat", params=[OpParam("repeats", int, 1, required=True),
                            OpParam("axis", int, None)])
def _repeat(x, repeats=1, axis=None):
    return jnp.repeat(x, repeats, axis=axis)


@register("Pad", aliases=["pad"],
          params=[OpParam("mode", str, "constant"),
                  OpParam("pad_width", tuple, None, required=True),
                  OpParam("constant_value", float, 0.0)],
          doc="ref: src/operator/pad.cc — pad_width is the reference's flat "
              "2-per-axis tuple")
def _pad(x, mode="constant", pad_width=None, constant_value=0.0):
    pw = [(int(pad_width[2 * i]), int(pad_width[2 * i + 1])) for i in range(x.ndim)]
    if mode == "constant":
        return jnp.pad(x, pw, constant_values=constant_value)
    jmode = {"edge": "edge", "reflect": "reflect"}[mode]
    return jnp.pad(x, pw, mode=jmode)


@register("clip", params=[OpParam("a_min", float, None, required=True),
                          OpParam("a_max", float, None, required=True)])
def _clip(x, a_min=None, a_max=None):
    return jnp.clip(x, a_min, a_max)


@register("broadcast_to", params=[OpParam("shape", tuple, None, required=True)])
def _broadcast_to(x, shape=None):
    shape = tuple(x.shape[i] if s == 0 else int(s) for i, s in enumerate(shape))
    return jnp.broadcast_to(x, shape)


@register("broadcast_like", num_inputs=2)
def _broadcast_like(x, like):
    return jnp.broadcast_to(x, like.shape)


@register("broadcast_axis", aliases=["broadcast_axes"],
          params=[OpParam("axis", tuple, ()), OpParam("size", tuple, ())])
def _broadcast_axis(x, axis=(), size=()):
    shape = list(x.shape)
    for a, s in zip(axis, size):
        shape[a] = int(s)
    return jnp.broadcast_to(x, tuple(shape))


@register("slice", params=[OpParam("begin", tuple, None, required=True),
                           OpParam("end", tuple, None, required=True),
                           OpParam("step", tuple, None)],
          doc="ref: matrix_op.cc slice — begin/end entries may be None")
def _slice(x, begin=None, end=None, step=None):
    step = step or (1,) * len(begin)
    idx = tuple(slice(b, e, s if s else 1) for b, e, s in zip(begin, end, step))
    return x[idx]


@register("slice_axis", params=[OpParam("axis", int, 0, required=True),
                                OpParam("begin", int, 0, required=True),
                                OpParam("end", int, None, required=True)])
def _slice_axis(x, axis=0, begin=0, end=None):
    idx = [slice(None)] * x.ndim
    idx[axis] = slice(begin, end)
    return x[tuple(idx)]


@register("slice_like", num_inputs=2, params=[OpParam("axes", tuple, None)])
def _slice_like(x, like, axes=None):
    axes = axes if axes is not None else tuple(range(x.ndim))
    idx = [slice(None)] * x.ndim
    for a in axes:
        idx[a] = slice(0, like.shape[a])
    return x[tuple(idx)]


def shifted_expsum(x, axis=-1):
    """Shared numerically-stable exp-sum core: returns
    ``(m, shifted, se32)`` where ``m = stop_grad(max(x))``,
    ``shifted = x - m`` (input dtype, elementwise — fuses into consumers)
    and ``se32 = sum(exp(shifted))`` accumulated in fp32 without
    materializing an fp32 tensor of x's shape. One definition backs
    log_softmax, logsumexp and the short-sequence attention softmax so
    their numerics stay consistent."""
    acc = jnp.promote_types(x.dtype, jnp.float32)   # fp64 in stays fp64
    m = jax.lax.stop_gradient(jnp.max(x, axis=axis, keepdims=True))
    shifted = x - m
    se32 = jnp.sum(jnp.exp(shifted).astype(acc), axis=axis,
                   keepdims=True)
    return m, shifted, se32


@register("logsumexp",
          params=[OpParam("axis", int, -1), OpParam("keepdims", bool, False)],
          doc="Numerically-stable log-sum-exp with fp32 accumulation; "
              "gradient is softmax in the input dtype. Backs the fused "
              "sparse softmax-CE loss path (no [.., C] log-prob tensor is "
              "materialized; the reference fuses equivalently in "
              "src/operator/softmax_output.cc)")
def _logsumexp(x, axis=-1, keepdims=False):
    m, _, se32 = shifted_expsum(x, axis=axis)
    out = m.astype(se32.dtype) + jnp.log(se32)
    return out if keepdims else jnp.squeeze(out, axis)


@register("take", num_inputs=2,
          params=[OpParam("axis", int, 0), OpParam("mode", str, "clip")],
          doc="Gather rows by index (ref: src/operator/tensor/indexing_op.cc Take)")
def _take(a, indices, axis=0, mode="clip"):
    jmode = {"clip": "clip", "wrap": "wrap", "raise": "clip"}[mode]
    return jnp.take(a, indices.astype(jnp.int32), axis=axis, mode=jmode)


@register("batch_take", num_inputs=2,
          doc="out[i] = a[i, indices[i]] — one element per leading-axis "
              "row (ref: src/operator/tensor/indexing_op.cc batch_take); "
              "pick with a fixed last axis")
def _batch_take(a, indices):
    return _pick(a.reshape(-1, a.shape[-1]),
                 indices.reshape(-1), axis=-1)


@register("pick", num_inputs=2,
          params=[OpParam("axis", int, -1), OpParam("keepdims", bool, False),
                  OpParam("mode", str, "clip")],
          doc="Pick one element per row by index (ref: indexing_op.cc pick)")
def _pick(x, index, axis=-1, keepdims=False, mode="clip"):
    index = jnp.clip(index.astype(jnp.int32), 0, x.shape[axis] - 1)
    picked = jnp.take_along_axis(x, jnp.expand_dims(index, axis), axis=axis)
    return picked if keepdims else jnp.squeeze(picked, axis)


@register("gather_nd", num_inputs=2,
          doc="ref: indexing_op.cc gather_nd — indices shape (M, ...) leads")
def _gather_nd(data, indices):
    indices = indices.astype(jnp.int32)
    m = indices.shape[0]
    idx = tuple(indices[i] for i in range(m))
    return data[idx]


@register("scatter_nd", num_inputs=2,
          params=[OpParam("shape", tuple, None, required=True)],
          doc="ref: indexing_op.cc scatter_nd")
def _scatter_nd(data, indices, shape=None):
    indices = indices.astype(jnp.int32)
    out = jnp.zeros(shape, dtype=data.dtype)
    idx = tuple(indices[i] for i in range(indices.shape[0]))
    return out.at[idx].set(data)


@register("one_hot",
          params=[OpParam("depth", int, None, required=True),
                  OpParam("on_value", float, 1.0), OpParam("off_value", float, 0.0),
                  OpParam("dtype", str, "float32")],
          differentiable=False, doc="ref: indexing_op.cc one_hot")
def _one_hot(indices, depth=None, on_value=1.0, off_value=0.0, dtype="float32"):
    from ..base import _as_np_dtype
    oh = jax.nn.one_hot(indices.astype(jnp.int32), depth)
    out = oh * (on_value - off_value) + off_value
    return out.astype(_as_np_dtype(dtype))


@register("where", num_inputs=3,
          doc="Elementwise select (ref: src/operator/tensor/control_flow_op.cc)")
def _where(cond, x, y):
    return jnp.where(cond != 0, x, y)


@register("Concat", aliases=["concat"], num_inputs=-1,
          params=[OpParam("dim", int, 1), OpParam("num_args", int, None)],
          doc="ref: src/operator/nn/concat.cc")
def _concat(*args, dim=1, num_args=None):
    return jnp.concatenate(args, axis=dim)


@register("stack", num_inputs=-1,
          params=[OpParam("axis", int, 0), OpParam("num_args", int, None)])
def _stack(*args, axis=0, num_args=None):
    return jnp.stack(args, axis=axis)


def _split_outputs(params):
    return int(params.get("num_outputs", 1))


@register("SliceChannel", aliases=["split"], num_outputs=_split_outputs,
          params=[OpParam("num_outputs", int, 1, required=True),
                  OpParam("axis", int, 1),
                  OpParam("squeeze_axis", bool, False)],
          doc="Split along axis into equal parts (ref: src/operator/slice_channel.cc)")
def _split(x, num_outputs=1, axis=1, squeeze_axis=False):
    parts = jnp.split(x, num_outputs, axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts) if num_outputs > 1 else parts[0]


@register("space_to_depth", params=[OpParam("block_size", int, 1, required=True)])
def _space_to_depth(x, block_size=1):
    n, c, h, w = x.shape
    b = block_size
    x = x.reshape(n, c, h // b, b, w // b, b)
    x = x.transpose(0, 3, 5, 1, 2, 4)
    return x.reshape(n, c * b * b, h // b, w // b)


@register("depth_to_space", params=[OpParam("block_size", int, 1, required=True)])
def _depth_to_space(x, block_size=1):
    n, c, h, w = x.shape
    b = block_size
    x = x.reshape(n, b, b, c // (b * b), h, w)
    x = x.transpose(0, 3, 4, 1, 5, 2)
    return x.reshape(n, c // (b * b), h * b, w * b)


# ---------------------------------------------------------------------------
# ordering (ref: src/operator/tensor/ordering_op.cc)
# ---------------------------------------------------------------------------


@register("sort", params=[OpParam("axis", int, -1), OpParam("is_ascend", bool, True)])
def _sort(x, axis=-1, is_ascend=True):
    out = jnp.sort(x, axis=axis)
    return out if is_ascend else jnp.flip(out, axis=axis)


@register("argsort", differentiable=False,
          params=[OpParam("axis", int, -1), OpParam("is_ascend", bool, True),
                  OpParam("dtype", str, "float32")])
def _argsort(x, axis=-1, is_ascend=True, dtype="float32"):
    from ..base import _as_np_dtype
    out = jnp.argsort(x, axis=axis)
    if not is_ascend:
        out = jnp.flip(out, axis=axis)
    return out.astype(_as_np_dtype(dtype))


def _topk_outputs(params):
    return 2 if params.get("ret_typ", "indices") == "both" else 1


@register("topk", num_outputs=_topk_outputs, differentiable=False,
          params=[OpParam("axis", int, -1), OpParam("k", int, 1),
                  OpParam("ret_typ", str, "indices"),
                  OpParam("is_ascend", bool, False),
                  OpParam("dtype", str, "float32")],
          doc="ref: ordering_op.cc topk")
def _topk(x, axis=-1, k=1, ret_typ="indices", is_ascend=False, dtype="float32"):
    from ..base import _as_np_dtype
    xm = jnp.moveaxis(x, axis, -1)
    vals, idx = jax.lax.top_k(-xm if is_ascend else xm, k)
    if is_ascend:
        vals = -vals
    vals = jnp.moveaxis(vals, -1, axis)
    idx = jnp.moveaxis(idx, -1, axis).astype(_as_np_dtype(dtype))
    if ret_typ == "value":
        return vals
    if ret_typ == "indices":
        return idx
    if ret_typ == "mask":
        # 1 at the top-k positions, 0 elsewhere, in the INPUT dtype
        # (reference: `dtype` governs only the indices output)
        mask = jnp.put_along_axis(
            jnp.zeros(xm.shape, x.dtype),
            jnp.moveaxis(idx, axis, -1).astype(jnp.int32),
            jnp.ones((), x.dtype), axis=-1, inplace=False)
        return jnp.moveaxis(mask, -1, axis)
    return vals, idx  # 'both' returns [values, indices]


# ---------------------------------------------------------------------------
# linalg (ref: src/operator/tensor/dot.cc, la_op.cc)
# ---------------------------------------------------------------------------


@register("dot", num_inputs=2,
          params=[OpParam("transpose_a", bool, False),
                  OpParam("transpose_b", bool, False)],
          doc="Matrix/tensor product onto the MXU "
              "(ref: src/operator/tensor/dot-inl.h DotForward_)")
def _dot(a, b, transpose_a=False, transpose_b=False):
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2) if a.ndim >= 2 else a
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2) if b.ndim >= 2 else b
    if a.ndim == 1 and b.ndim == 1:
        return jnp.dot(a, b)
    # reference semantics: contract last axis of a with first axis of b
    return jnp.tensordot(a, b, axes=([a.ndim - 1], [0]))


@register("batch_dot", num_inputs=2,
          params=[OpParam("transpose_a", bool, False),
                  OpParam("transpose_b", bool, False)],
          doc="Batched matmul (ref: dot-inl.h BatchDotForward_)")
def _batch_dot(a, b, transpose_a=False, transpose_b=False):
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2)
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2)
    return jnp.matmul(a, b)


@register("_linalg_gemm2", aliases=["linalg_gemm2"], num_inputs=2,
          params=[OpParam("transpose_a", bool, False),
                  OpParam("transpose_b", bool, False),
                  OpParam("alpha", float, 1.0)],
          doc="ref: src/operator/tensor/la_op.cc linalg_gemm2")
def _linalg_gemm2(a, b, transpose_a=False, transpose_b=False, alpha=1.0):
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2)
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2)
    return alpha * jnp.matmul(a, b)


@register("_linalg_potrf", aliases=["linalg_potrf"],
          doc="Cholesky factor (ref: la_op.cc linalg_potrf)")
def _potrf(a):
    return jnp.linalg.cholesky(a)


@register("_linalg_trsm", aliases=["linalg_trsm"], num_inputs=2,
          params=[OpParam("transpose", bool, False),
                  OpParam("rightside", bool, False),
                  OpParam("lower", bool, True),
                  OpParam("alpha", float, 1.0)],
          doc="Triangular solve (ref: la_op.cc linalg_trsm)")
def _trsm(a, b, transpose=False, rightside=False, lower=True, alpha=1.0):
    import jax.scipy.linalg as jsl
    if rightside:
        # solve X A = alpha B  <=>  A^T X^T = alpha B^T
        sol = jsl.solve_triangular(jnp.swapaxes(a, -1, -2), jnp.swapaxes(b, -1, -2) * alpha,
                                   lower=not lower, trans=1 if transpose else 0)
        return jnp.swapaxes(sol, -1, -2)
    return jsl.solve_triangular(a, b * alpha, lower=lower, trans=1 if transpose else 0)


@register("_linalg_syrk", aliases=["linalg_syrk"],
          params=[OpParam("transpose", bool, False), OpParam("alpha", float, 1.0)],
          doc="Symmetric rank-k update (ref: la_op.cc linalg_syrk)")
def _syrk(a, transpose=False, alpha=1.0):
    at = jnp.swapaxes(a, -1, -2)
    return alpha * (jnp.matmul(at, a) if transpose else jnp.matmul(a, at))


@register("_linalg_inverse", aliases=["linalg_inverse"],
          doc="ref: la_op.cc linalg_inverse")
def _inverse(a):
    return jnp.linalg.inv(a)


@register("_linalg_det", aliases=["linalg_det"], doc="ref: la_op.cc linalg_det")
def _det(a):
    return jnp.linalg.det(a)


@register("_linalg_slogdet", aliases=["linalg_slogdet"], num_outputs=2,
          doc="Sign and log-abs-determinant (ref: la_op.cc linalg_slogdet)")
def _slogdet(a):
    sign, logabs = jnp.linalg.slogdet(a)
    return sign, logabs


@register("_linalg_trmm", aliases=["linalg_trmm"], num_inputs=2,
          params=[OpParam("transpose", bool, False),
                  OpParam("rightside", bool, False),
                  OpParam("lower", bool, True),
                  OpParam("alpha", float, 1.0)],
          doc="Triangular matrix multiply: alpha * op(tri(A)) @ B, or "
              "B @ op(tri(A)) when rightside — one masked matmul on the "
              "MXU instead of BLAS trmm (ref: la_op.cc linalg_trmm)")
def _trmm(a, b, transpose=False, rightside=False, lower=True, alpha=1.0):
    tri = jnp.tril(a) if lower else jnp.triu(a)
    if transpose:
        tri = jnp.swapaxes(tri, -1, -2)
    out = jnp.matmul(b, tri) if rightside else jnp.matmul(tri, b)
    return alpha * out


@register("_linalg_makediag", aliases=["linalg_makediag"],
          params=[OpParam("offset", int, 0)],
          doc="Vector (..., n) -> matrix (..., n+|o|, n+|o|) with the "
              "vector on diagonal `offset` (ref: la_op.cc linalg_makediag)")
def _makediag(a, offset=0):
    import numpy as _np
    n = a.shape[-1]
    m = n + abs(offset)
    rows = _np.arange(n) + (abs(offset) if offset < 0 else 0)
    cols = _np.arange(n) + (offset if offset > 0 else 0)
    out = jnp.zeros(a.shape[:-1] + (m, m), dtype=a.dtype)
    return out.at[..., rows, cols].set(a)


@register("_linalg_extractdiag", aliases=["linalg_extractdiag"],
          params=[OpParam("offset", int, 0)],
          doc="Matrix (..., n, n) -> diagonal `offset` as a vector "
              "(ref: la_op.cc linalg_extractdiag)")
def _extractdiag(a, offset=0):
    return jnp.diagonal(a, offset=offset, axis1=-2, axis2=-1)


@register("_linalg_maketrian", aliases=["linalg_maketrian"],
          params=[OpParam("offset", int, 0), OpParam("lower", bool, True)],
          doc="Packed vector -> triangular matrix (row-major packing of "
              "the triangle like the reference; ref: la_op.cc "
              "linalg_maketrian)")
def _maketrian(a, offset=0, lower=True):
    import numpy as _np
    m = a.shape[-1]
    # triangle with k rows holds k*(k+1)/2 entries; solve for k
    k = int((_np.sqrt(8 * m + 1) - 1) // 2)
    n = k + abs(offset)
    # the reference keys the triangle on the SIGN of offset and consults
    # `lower` only at offset == 0 (ref: la_op.cc CopyTrians)
    if offset < 0 or (offset == 0 and lower):
        rows, cols = _np.tril_indices(n, offset)
    else:
        rows, cols = _np.triu_indices(n, offset)
    out = jnp.zeros(a.shape[:-1] + (n, n), dtype=a.dtype)
    return out.at[..., rows, cols].set(a)


@register("_linalg_extracttrian", aliases=["linalg_extracttrian"],
          params=[OpParam("offset", int, 0), OpParam("lower", bool, True)],
          doc="Triangular part of (..., n, n) packed row-major into a "
              "vector (ref: la_op.cc linalg_extracttrian)")
def _extracttrian(a, offset=0, lower=True):
    import numpy as _np
    n = a.shape[-1]
    if offset < 0 or (offset == 0 and lower):
        rows, cols = _np.tril_indices(n, offset)
    else:
        rows, cols = _np.triu_indices(n, offset)
    return a[..., rows, cols]


@register("khatri_rao", num_inputs=-1,
          doc="Row-wise Khatri-Rao product (ref: src/operator/contrib/krprod.cc)")
def _khatri_rao(*mats):
    out = mats[0]
    for m in mats[1:]:
        out = (out[:, :, None] * m[:, None, :]).reshape(out.shape[0], -1)
    return out


@register("diag", params=[OpParam("k", int, 0)])
def _diag(x, k=0):
    if x.ndim == 1:
        return jnp.diag(x, k)
    return jnp.diagonal(x, offset=k, axis1=-2, axis2=-1)


@register("embedding_like_dot", num_inputs=2, doc="helper: a @ b.T")
def _dot_t(a, b):
    return jnp.matmul(a, jnp.swapaxes(b, -1, -2))


@register("reshape_like", num_inputs=2,
          doc="Reshape lhs to rhs's shape (ref: src/operator/tensor/"
              "elemwise_unary_op_basic.cc reshape_like)")
def _reshape_like(lhs, rhs):
    return lhs.reshape(rhs.shape)

"""Random samplers.

TPU-native equivalent of ``src/operator/random/`` (sample_op.cc,
multisample_op.cc). The reference draws from per-device stateful RNG resources
(ref: src/resource.cc kRandom); here every sampler takes an explicit JAX PRNG
key threaded by the dispatch layer — stateless, reproducible, shard-friendly.

Two families, like the reference:
- ``_random_*``: fixed distribution params, shape kwarg (creation-style).
- ``_sample_*``: per-element distribution params given as input arrays.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..base import _as_np_dtype
from .registry import OpParam, register


def _shape_dtype_params():
    # ctx passes through uncoerced; the dispatch layer honors it for output
    # placement (ref: sample ops take a Context in the reference too)
    return [OpParam("shape", tuple, None), OpParam("dtype", str, "float32"),
            OpParam("ctx", None, None)]


def _creation(name, draw, extra_params, doc=""):
    params = extra_params + _shape_dtype_params()

    def impl(rng=None, shape=None, dtype="float32", ctx=None, **kw):
        shape = tuple(shape) if shape is not None else (1,)
        return draw(rng, shape, _as_np_dtype(dtype), **kw)

    register(name, num_inputs=0, params=params, differentiable=False,
             needs_rng=True, doc=doc or f"{name} sampler "
             "(ref: src/operator/random/sample_op.cc)")(impl)


_creation("_random_uniform",
          lambda rng, shape, dtype, low=0.0, high=1.0:
          jax.random.uniform(rng, shape, dtype=jnp.float32,
                             minval=low, maxval=high).astype(dtype),
          [OpParam("low", float, 0.0), OpParam("high", float, 1.0)],
          doc="Uniform[low, high) (ref: sample_op.cc _random_uniform)")

_creation("_random_normal",
          lambda rng, shape, dtype, loc=0.0, scale=1.0:
          (jax.random.normal(rng, shape) * scale + loc).astype(dtype),
          [OpParam("loc", float, 0.0), OpParam("scale", float, 1.0)],
          doc="Normal(loc, scale) (ref: sample_op.cc _random_normal)")

_creation("_random_gamma",
          lambda rng, shape, dtype, alpha=1.0, beta=1.0:
          (jax.random.gamma(rng, alpha, shape) * beta).astype(dtype),
          [OpParam("alpha", float, 1.0), OpParam("beta", float, 1.0)])

_creation("_random_exponential",
          lambda rng, shape, dtype, lam=1.0:
          (jax.random.exponential(rng, shape) / lam).astype(dtype),
          [OpParam("lam", float, 1.0)])

_creation("_random_poisson",
          lambda rng, shape, dtype, lam=1.0:
          jax.random.poisson(rng, lam, shape).astype(dtype),
          [OpParam("lam", float, 1.0)])

_creation("_random_randint",
          lambda rng, shape, dtype, low=0, high=1:
          jax.random.randint(rng, shape, int(low), int(high)).astype(dtype),
          [OpParam("low", int, 0), OpParam("high", int, 1)])


@register("_sample_uniform", num_inputs=2, needs_rng=True, differentiable=False,
          params=[OpParam("shape", tuple, None), OpParam("dtype", str, "float32")],
          doc="Per-element uniform (ref: src/operator/random/multisample_op.cc)")
def _sample_uniform(low, high, rng=None, shape=None, dtype="float32"):
    extra = tuple(shape) if shape else ()
    out_shape = low.shape + extra
    u = jax.random.uniform(rng, out_shape)
    low_b = low.reshape(low.shape + (1,) * len(extra))
    high_b = high.reshape(high.shape + (1,) * len(extra))
    return (low_b + u * (high_b - low_b)).astype(_as_np_dtype(dtype))


@register("_sample_normal", num_inputs=2, needs_rng=True, differentiable=False,
          params=[OpParam("shape", tuple, None), OpParam("dtype", str, "float32")],
          doc="Per-element normal (ref: multisample_op.cc)")
def _sample_normal(mu, sigma, rng=None, shape=None, dtype="float32"):
    extra = tuple(shape) if shape else ()
    out_shape = mu.shape + extra
    z = jax.random.normal(rng, out_shape)
    mu_b = mu.reshape(mu.shape + (1,) * len(extra))
    sigma_b = sigma.reshape(sigma.shape + (1,) * len(extra))
    return (mu_b + z * sigma_b).astype(_as_np_dtype(dtype))


@register("_sample_multinomial", num_inputs=1, needs_rng=True, differentiable=False,
          params=[OpParam("shape", tuple, None), OpParam("get_prob", bool, False),
                  OpParam("dtype", str, "int32")],
          doc="Categorical sampling from probability rows "
              "(ref: src/operator/random/sample_multinomial_op.cc)")
def _sample_multinomial(probs, rng=None, shape=None, get_prob=False, dtype="int32"):
    n = int(shape[0]) if shape else 1
    logits = jnp.log(jnp.maximum(probs, 1e-37))
    samples = jax.random.categorical(rng, logits, axis=-1,
                                     shape=(n,) + probs.shape[:-1])
    samples = jnp.moveaxis(samples, 0, -1)
    if not shape:
        samples = samples[..., 0]
    return samples.astype(_as_np_dtype(dtype))


@register("_shuffle", needs_rng=True, differentiable=False,
          doc="Shuffle along first axis (ref: src/operator/random/shuffle_op.cc)")
def _shuffle(x, rng=None):
    return jax.random.permutation(rng, x, axis=0)


@register("_random_bernoulli", needs_rng=True, differentiable=False, num_inputs=0,
          params=[OpParam("p", float, 0.5)] + _shape_dtype_params(),
          doc="Bernoulli(p)")
def _bernoulli(rng=None, p=0.5, shape=None, dtype="float32", ctx=None):
    return jax.random.bernoulli(rng, p, tuple(shape or (1,))).astype(
        _as_np_dtype(dtype))


_creation("_random_negative_binomial",
          lambda rng, shape, dtype, k=1, p=1.0:
          jax.random.poisson(
              rng, jax.random.gamma(jax.random.fold_in(rng, 1), float(k),
                                    shape) * ((1.0 - p) / max(p, 1e-12)),
              shape).astype(dtype),
          [OpParam("k", int, 1), OpParam("p", float, 1.0)],
          doc="NegativeBinomial(k, p) via the gamma-Poisson mixture "
              "(ref: sample_op.cc _random_negative_binomial)")

_creation("_random_generalized_negative_binomial",
          lambda rng, shape, dtype, mu=1.0, alpha=1.0:
          jax.random.poisson(
              rng,
              jax.random.gamma(jax.random.fold_in(rng, 1),
                               1.0 / max(alpha, 1e-12), shape)
              * (mu * alpha) if alpha > 1e-12
              else jnp.full(shape, mu),
              shape).astype(dtype),
          [OpParam("mu", float, 1.0), OpParam("alpha", float, 1.0)],
          doc="GeneralizedNegativeBinomial(mu, alpha): mean mu, dispersion "
              "alpha; alpha->0 degenerates to Poisson(mu) "
              "(ref: sample_op.cc _random_generalized_negative_binomial)")


def _per_elem(name, draw, doc, int_out=False):
    """Per-element samplers (ref: src/operator/random/multisample_op.cc):
    each output row draws from the distribution parameterized by the
    matching element(s) of the input array(s); a trailing ``shape``
    kwarg appends extra draw dims. One vectorized primitive draw — no
    per-element loop (TPU-native shape of the reference's kernels)."""
    n_in = draw.__code__.co_argcount - 2          # params before rng/shape

    def impl(*args, rng=None, shape=None, dtype=None, **_):
        extra = tuple(shape) if shape else ()
        out_shape = args[0].shape + extra
        bargs = [a.reshape(a.shape + (1,) * len(extra)).astype(jnp.float32)
                 for a in args]
        out = draw(*bargs, rng, out_shape)
        dt = _as_np_dtype(dtype or ("int32" if int_out else "float32"))
        return out.astype(dt)

    register(name, num_inputs=n_in, needs_rng=True, differentiable=False,
             params=[OpParam("shape", tuple, None),
                     OpParam("dtype", str, None)],
             doc=doc)(impl)


_per_elem("_sample_gamma",
          lambda alpha, beta, rng, s:
          jax.random.gamma(rng, jnp.broadcast_to(alpha, s)) * beta,
          "Per-element Gamma(alpha, beta) (ref: multisample_op.cc)")

_per_elem("_sample_exponential",
          lambda lam, rng, s: jax.random.exponential(rng, s) / lam,
          "Per-element Exponential(lam) (ref: multisample_op.cc)")

_per_elem("_sample_poisson",
          lambda lam, rng, s:
          jax.random.poisson(rng, jnp.broadcast_to(lam, s), s),
          "Per-element Poisson(lam) (ref: multisample_op.cc)", int_out=False)

_per_elem("_sample_negative_binomial",
          lambda k, p, rng, s: jax.random.poisson(
              rng,
              jax.random.gamma(jax.random.fold_in(rng, 1),
                               jnp.broadcast_to(jnp.maximum(k, 1e-6), s))
              * ((1.0 - p) / jnp.maximum(p, 1e-12)), s),
          "Per-element NegativeBinomial(k, p), gamma-Poisson mixture "
          "(ref: multisample_op.cc)")

_per_elem("_sample_generalized_negative_binomial",
          lambda mu, alpha, rng, s: jax.random.poisson(
              rng,
              jnp.where(
                  alpha > 1e-12,
                  jax.random.gamma(
                      jax.random.fold_in(rng, 1),
                      jnp.broadcast_to(1.0 / jnp.maximum(alpha, 1e-12), s))
                  * (mu * alpha),
                  jnp.broadcast_to(mu, s)), s),
          "Per-element GeneralizedNegativeBinomial(mu, alpha) "
          "(ref: multisample_op.cc)")


@register("_sample_dirichlet", num_inputs=1, needs_rng=True,
          differentiable=False,
          params=[OpParam("shape", tuple, None),
                  OpParam("dtype", str, "float32")],
          doc="Dirichlet(alpha) over the last axis of alpha (..., K): "
              "normalized per-element gamma draws. Extra ``shape`` dims "
              "are inserted before the K axis like the reference's "
              "multisample convention (np.random.dirichlet analog).")
def _sample_dirichlet(alpha, rng=None, shape=None, dtype="float32"):
    extra = tuple(shape) if shape else ()
    out_shape = alpha.shape[:-1] + extra + alpha.shape[-1:]
    a = alpha.reshape(alpha.shape[:-1] + (1,) * len(extra)
                      + alpha.shape[-1:]).astype(jnp.float32)
    g = jax.random.gamma(rng, jnp.broadcast_to(a, out_shape))
    return (g / jnp.sum(g, axis=-1, keepdims=True)).astype(
        _as_np_dtype(dtype))

"""Typed operator registry.

Replaces two reference mechanisms with one TPU-native one:

- the NNVM op registry (``nnvm::Op`` with FCompute/FInferShape/FInferType
  attributes, ref: include/mxnet/op_attr_types.h): here an ``Operator`` holds a
  pure jax function; shape/dtype inference falls out of ``jax.eval_shape`` so
  no per-op inference rules are needed;
- ``dmlc::Parameter`` CRTP hyperparameter structs (ref:
  3rdparty/dmlc-core/include/dmlc/parameter.h), whose introspection the
  reference uses to code-generate Python signatures/docstrings (SURVEY §5.6
  calls this load-bearing): here ``OpParam`` rows serve the same role and
  drive wrapper generation for both ``mx.nd`` and ``mx.sym``.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as _np

from ..base import MXNetError

__all__ = ["OpParam", "Operator", "register", "alias", "get", "list_ops"]

_REGISTRY: Dict[str, "Operator"] = {}


@dataclass
class OpParam:
    """One hyperparameter of an op (dmlc::Parameter field analog)."""
    name: str
    type: Any = None            # python type or callable coercer
    default: Any = None
    required: bool = False
    doc: str = ""

    def coerce(self, value):
        if value is None:
            return None
        typ = self.type
        if typ is None or isinstance(value, bool) and typ is bool:
            return value
        if typ is tuple:
            return _as_tuple(value)
        if typ is bool:
            if isinstance(value, str):
                return value.lower() in ("1", "true", "yes")
            return bool(value)
        if typ in (int, float, str):
            return typ(value)
        if callable(typ):
            return typ(value)
        return value


def _as_tuple(value):
    """Accept tuples, lists, ints, and the reference's string shapes '(2, 2)'."""
    if isinstance(value, str):
        value = ast.literal_eval(value)
    if isinstance(value, (int,)):
        return (value,)
    return tuple(value)


@dataclass
class Operator:
    """A registered operator: a pure function on jax arrays.

    ``fn(*arrays, **params) -> array | tuple`` must be jax-traceable
    (no data-dependent Python control flow), which makes every op usable
    eagerly (mx.nd), under jit (hybridize/CachedOp), and in symbolic graphs
    (mx.sym) from a single definition.
    """
    name: str
    fn: Callable
    num_inputs: int = 1          # -1 = variadic
    num_outputs: int = 1
    params: List[OpParam] = field(default_factory=list)
    doc: str = ""
    differentiable: bool = True
    aliases: List[str] = field(default_factory=list)
    ref: str = ""                # reference file/symbol this op mirrors
    needs_rng: bool = False      # dispatch passes a PRNG key as `rng=` kwarg
                                 # (replaces the reference's ResourceRequest::kRandom)
    needs_mode: bool = False     # dispatch passes `training=` from autograd state
    allow_unknown_params: bool = False   # Custom op forwards user kwargs

    def coerce_params(self, kwargs: dict) -> dict:
        spec = {p.name: p for p in self.params}
        out = {}
        for key, val in kwargs.items():
            if key in spec:
                out[key] = spec[key].coerce(val)
            elif self.allow_unknown_params:
                out[key] = val
            else:
                # tolerate unknown kwargs the way generated wrappers do not:
                # raise, to catch typos early
                raise MXNetError(f"op {self.name!r}: unknown parameter {key!r}. "
                                 f"Known: {sorted(spec)}")
        for p in self.params:
            if p.required and p.name not in out:
                raise MXNetError(f"op {self.name!r}: missing required "
                                 f"parameter {p.name!r}")
            if p.name not in out:
                out[p.name] = p.default
        return out

    def signature_doc(self) -> str:
        lines = [self.doc or self.name, "", "Parameters", "----------"]
        for p in self.params:
            typename = getattr(p.type, "__name__", str(p.type))
            dflt = "required" if p.required else f"default={p.default!r}"
            lines.append(f"{p.name} : {typename}, {dflt}")
            if p.doc:
                lines.append(f"    {p.doc}")
        if self.ref:
            lines += ["", f"Reference: {self.ref}"]
        return "\n".join(lines)


def register(name: str, *, num_inputs: int = 1, num_outputs: int = 1,
             params: Optional[Sequence[OpParam]] = None, doc: str = "",
             differentiable: bool = True, aliases: Sequence[str] = (),
             ref: str = "", needs_rng: bool = False, needs_mode: bool = False):
    """Decorator registering ``fn`` as operator ``name``."""
    def deco(fn):
        op = Operator(name=name, fn=fn, num_inputs=num_inputs,
                      num_outputs=num_outputs, params=list(params or []),
                      doc=doc or (fn.__doc__ or ""), differentiable=differentiable,
                      aliases=list(aliases), ref=ref,
                      needs_rng=needs_rng, needs_mode=needs_mode)
        if name in _REGISTRY:
            raise MXNetError(f"duplicate op registration: {name}")
        _REGISTRY[name] = op
        for a in op.aliases:
            _REGISTRY[a] = op
        return fn
    return deco


def alias(existing: str, *names: str):
    op = get(existing)
    for n in names:
        _REGISTRY[n] = op
        op.aliases.append(n)


def get(name: str) -> Operator:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise MXNetError(f"operator {name!r} is not registered "
                         f"({len(_REGISTRY)} ops known)") from None


def list_ops() -> List[str]:
    """ref: MXListAllOpNames — drives wrapper generation."""
    return sorted(set(_REGISTRY))


# ---------------------------------------------------------------------------
# Public scalar-or-array binary helpers. The reference's python layer
# (ref: python/mxnet/ndarray/ndarray.py maximum/minimum/power/equal/...)
# defines these ABOVE the generated op wrappers: array⊕array dispatches
# to the broadcast op, array⊕scalar to the _*_scalar op, scalar⊕array to
# the reflected scalar op, scalar⊕scalar to plain python. Installed into
# both the nd and sym namespaces by their _expose() calls.
# ---------------------------------------------------------------------------
PUBLIC_BINARY_HELPERS = {
    # public name: (array op, scalar op, reflected scalar op, py fallback)
    "add": ("broadcast_add", "_plus_scalar", "_plus_scalar",
            lambda a, b: a + b),
    "subtract": ("broadcast_sub", "_minus_scalar", "_rminus_scalar",
                 lambda a, b: a - b),
    "multiply": ("broadcast_mul", "_mul_scalar", "_mul_scalar",
                 lambda a, b: a * b),
    "divide": ("broadcast_div", "_div_scalar", "_rdiv_scalar",
               lambda a, b: a / b),
    "modulo": ("broadcast_mod", "_mod_scalar", "_rmod_scalar",
               lambda a, b: a % b),
    "power": ("broadcast_power", "_power_scalar", "_rpower_scalar",
              lambda a, b: a ** b),
    "maximum": ("broadcast_maximum", "_maximum_scalar", "_maximum_scalar",
                max),
    "minimum": ("broadcast_minimum", "_minimum_scalar", "_minimum_scalar",
                min),
    "equal": ("broadcast_equal", "_equal_scalar", "_equal_scalar",
              lambda a, b: float(a == b)),
    "not_equal": ("broadcast_not_equal", "_not_equal_scalar",
                  "_not_equal_scalar", lambda a, b: float(a != b)),
    "greater": ("broadcast_greater", "_greater_scalar", "_lesser_scalar",
                lambda a, b: float(a > b)),
    "greater_equal": ("broadcast_greater_equal", "_greater_equal_scalar",
                      "_lesser_equal_scalar", lambda a, b: float(a >= b)),
    "lesser": ("broadcast_lesser", "_lesser_scalar", "_greater_scalar",
               lambda a, b: float(a < b)),
    "lesser_equal": ("broadcast_lesser_equal", "_lesser_equal_scalar",
                     "_greater_equal_scalar", lambda a, b: float(a <= b)),
    "logical_and": ("broadcast_logical_and", "_logical_and_scalar",
                    "_logical_and_scalar",
                    lambda a, b: float(bool(a) and bool(b))),
    "logical_or": ("broadcast_logical_or", "_logical_or_scalar",
                   "_logical_or_scalar",
                   lambda a, b: float(bool(a) or bool(b))),
    "logical_xor": ("broadcast_logical_xor", "_logical_xor_scalar",
                    "_logical_xor_scalar",
                    lambda a, b: float(bool(a) != bool(b))),
    "hypot": ("broadcast_hypot", "_hypot_scalar", "_hypot_scalar",
              lambda a, b: (a * a + b * b) ** 0.5),
}


def install_binary_helpers(module):
    """Install the public scalar-or-array binary helpers onto a generated
    namespace (nd or sym). ``module`` must already carry the broadcast
    ops and an ``_internal`` submodule with the scalar ops."""
    internal = module._internal

    def make(pub, array_name, scalar_name, rscalar_name, py_fallback):
        arr_fn = getattr(module, array_name)
        sc_fn = getattr(internal, scalar_name)
        rsc_fn = getattr(internal, rscalar_name)

        def helper(lhs, rhs):
            # numeric_types parity: numpy scalars (arr.max(), np.float32)
            # count as scalars, like the reference's numeric_types
            scalar_types = (int, float, bool, _np.generic)
            lhs_scalar = isinstance(lhs, scalar_types)
            rhs_scalar = isinstance(rhs, scalar_types)
            if not lhs_scalar and not rhs_scalar:
                return arr_fn(lhs, rhs)
            if not lhs_scalar:
                return sc_fn(lhs, scalar=float(rhs))
            if not rhs_scalar:
                return rsc_fn(rhs, scalar=float(lhs))
            return py_fallback(lhs, rhs)
        helper.__name__ = pub
        helper.__doc__ = (f"Scalar-or-array {pub} (ref: python/mxnet/"
                          f"ndarray/ndarray.py {pub})")
        return helper

    for pub, (a, s, r, py) in PUBLIC_BINARY_HELPERS.items():
        if not hasattr(module, pub):
            setattr(module, pub, make(pub, a, s, r, py))

"""``mx.np`` — the NumPy-semantics array namespace.

ref: python/mxnet/numpy/ (the 1.6+ `_np_*` op family, SURVEY §2 #16). The
reference re-implements NumPy semantics (zero-dim shapes, broadcasting,
dtype rules) as ~50k LoC of C++ kernels; on TPU **jnp already is that
namespace**, so every function here is the jnp implementation wrapped with
NDArray boxing + autograd-tape capture — same API, compiled by XLA,
differentiable under ``autograd.record()``.
"""
from __future__ import annotations

import builtins
import sys
import types

import jax
import jax.numpy as jnp
import numpy as onp

from .. import _rng, autograd, engine
from ..base import MXNetError, _as_np_dtype
from ..context import current_context
from ..ndarray import NDArray

__all__ = ["ndarray", "array", "zeros", "ones", "empty", "full", "arange",
           "eye", "linspace"]

ndarray = NDArray
# dtype aliases (mx.np.float32 etc.)
float16 = onp.float16
float32 = onp.float32
float64 = onp.float64
int8 = onp.int8
int32 = onp.int32
int64 = onp.int64
uint8 = onp.uint8
bool_ = onp.bool_
pi = onp.pi
inf = onp.inf
nan = onp.nan
newaxis = None


def _unbox(x):
    if isinstance(x, NDArray):
        return x._data
    if isinstance(x, (list, tuple)):
        # sequence-of-arrays numpy signatures (concatenate, stack, ...)
        return [_unbox(e) for e in x]
    return x


def _tracked(x):
    return isinstance(x, NDArray) and (x._tape_node is not None
                                       or x._grad is not None)


def _call(fn, *args, **kwargs):
    """Generic tape-aware dispatch of a jnp function over NDArray args —
    the mx.np analog of _dispatch.invoke (ref: MXImperativeInvokeEx).
    NDArrays are accepted at top level AND one level inside list/tuple
    args (the sequence-of-arrays numpy signatures: concatenate, stack,
    vstack, ...), including on the tape."""
    # index paths of NDArray args: (loc, j) with loc an int positional
    # index or a str kwarg key; j indexes one sequence level (or None).
    # Kwarg arrays participate in the tape exactly like positional ones
    # (np.average's weights= IS differentiable).
    pos = []
    for i, a in enumerate(args):
        if isinstance(a, NDArray):
            pos.append((i, None))
        elif isinstance(a, (list, tuple)):
            for j, e in enumerate(a):
                if isinstance(e, NDArray):
                    pos.append((i, j))
    for k, v in kwargs.items():
        if isinstance(v, NDArray):
            pos.append((k, None))
        elif isinstance(v, (list, tuple)):
            for j, e in enumerate(v):
                if isinstance(e, NDArray):
                    pos.append((k, j))

    def _at(container_args, container_kwargs, loc, j):
        src = container_kwargs[loc] if isinstance(loc, str) \
            else container_args[loc]
        return src if j is None else src[j]

    nd_inputs = [_at(args, kwargs, loc, j) for loc, j in pos]
    datas = tuple(_unbox(a) for a in args)
    kwdatas = {k: _unbox(v) for k, v in kwargs.items()}
    # builtins.any: the generated mx.np.any wrapper shadows the builtin
    # inside this module
    recording = autograd.is_recording() and builtins.any(
        _tracked(a) for a in nd_inputs)
    if recording:
        def wrapped(*tracked_datas):
            full = [list(x) if isinstance(x, list) else x for x in datas]
            fkw = {k: (list(v) if isinstance(v, list) else v)
                   for k, v in kwdatas.items()}
            for (loc, j), d in zip(pos, tracked_datas):
                tgt = fkw if isinstance(loc, str) else full
                if j is None:
                    tgt[loc] = d
                else:
                    tgt[loc][j] = d
            out = fn(*full, **fkw)
            # list outputs (split family) normalize to tuple so the vjp
            # output pytree matches the tuple cotangents at backward
            return tuple(out) if isinstance(out, list) else out
        out_data, vjp_fn = jax.vjp(
            wrapped, *[_at(datas, kwdatas, loc, j) for loc, j in pos])
        outs = list(out_data) if isinstance(out_data, (tuple, list)) \
            else [out_data]
        avals = [jax.ShapeDtypeStruct(o.shape, o.dtype) for o in outs]
        parents = []
        for a in nd_inputs:
            if a._grad is not None:
                parents.append((None, 0, a))
            elif a._tape_node is not None:
                parents.append((a._tape_node, a._tape_out_idx, None))
            else:
                parents.append((None, 0, None))
        node = autograd.TapeNode(vjp_fn, parents, avals, fwd_fn=wrapped,
                                 fwd_inputs=list(nd_inputs))
    else:
        out_data = fn(*datas, **kwdatas)
        outs = list(out_data) if isinstance(out_data, (tuple, list)) \
            else [out_data]
        node = None
    ctx = nd_inputs[0].ctx if nd_inputs else current_context()
    results = []
    for i, o in enumerate(outs):
        if not isinstance(o, jax.Array):
            results.append(o)
            continue
        arr = NDArray(o, ctx=ctx, _skip_device_put=True)
        if node is not None:
            arr._tape_node = node
            arr._tape_out_idx = i
        results.append(arr)
    if len(results) == 1:
        return results[0]
    return tuple(results)


def _make(name, fn):
    def wrapper(*args, **kwargs):
        if "dtype" in kwargs and kwargs["dtype"] is not None:
            kwargs["dtype"] = _as_np_dtype(kwargs["dtype"])
        if "ctx" in kwargs:       # creation ops accept ctx= like the ref
            kwargs.pop("ctx")
        return _call(fn, *args, **kwargs)
    wrapper.__name__ = name
    wrapper.__doc__ = (fn.__doc__ or "").split("\n\n")[0] + \
        f"\n\n(numpy-semantics; jnp.{name} under the hood)"
    return wrapper


# every jnp function exported here keeps exact NumPy semantics
_FUNCS = [
    # creation
    "zeros", "ones", "empty", "full", "arange", "eye", "identity",
    "linspace", "logspace", "meshgrid", "tril", "triu",
    "zeros_like", "ones_like", "full_like", "empty_like",
    # manipulation
    "reshape", "ravel", "transpose", "swapaxes", "moveaxis", "rollaxis",
    "concatenate", "stack", "vstack", "hstack", "dstack", "column_stack",
    "split", "array_split", "hsplit", "vsplit", "dsplit", "tile", "repeat",
    "flip", "fliplr", "flipud", "roll", "rot90", "expand_dims", "squeeze",
    "broadcast_to", "broadcast_arrays", "atleast_1d", "atleast_2d",
    "atleast_3d", "pad", "append", "delete", "insert", "unique",
    # math
    "add", "subtract", "multiply", "divide", "true_divide", "floor_divide",
    "mod", "remainder", "power", "float_power", "negative", "positive",
    "absolute", "abs", "fabs", "sign", "rint", "exp", "expm1", "exp2",
    "log", "log2", "log10", "log1p", "sqrt", "cbrt", "square", "reciprocal",
    "sin", "cos", "tan", "arcsin", "arccos", "arctan", "arctan2", "sinh",
    "cosh", "tanh", "arcsinh", "arccosh", "arctanh", "degrees", "radians",
    "deg2rad", "rad2deg", "hypot", "maximum", "minimum", "fmax", "fmin",
    "clip", "floor", "ceil", "trunc", "around", "round",
    "nan_to_num", "interp", "heaviside", "gcd", "lcm", "ldexp",
    # ("fix" omitted: deprecated in jnp; numpy parity via trunc)
    # reductions
    "sum", "prod", "cumsum", "cumprod", "max", "min", "amax", "amin",
    "nanmax", "nanmin", "nansum", "nanprod", "mean", "std", "var",
    "median", "average", "nanmean", "nanstd", "nanvar", "ptp",
    "percentile", "quantile", "count_nonzero",
    # linalg-ish / products
    "dot", "vdot", "inner", "outer", "matmul", "tensordot", "einsum",
    "kron", "cross", "trace", "diagonal", "diag", "diagflat",
    # comparison / logic
    "equal", "not_equal", "less", "less_equal", "greater", "greater_equal",
    "logical_and", "logical_or", "logical_xor", "logical_not", "isfinite",
    "isinf", "isnan", "isneginf", "isposinf", "isclose", "allclose",
    "array_equal", "where", "all", "any",
    # sorting / searching / counting
    "sort", "argsort", "argmax", "argmin", "nanargmax", "nanargmin",
    "searchsorted", "partition", "argpartition", "nonzero", "flatnonzero",
    "bincount", "digitize", "histogram", "take", "take_along_axis",
    "choose", "compress", "extract", "indices", "unravel_index",
    "ravel_multi_index", "tril_indices", "triu_indices",
    # bit ops
    "bitwise_and", "bitwise_or", "bitwise_xor", "invert", "left_shift",
    "right_shift",
    # misc
    "copysign", "signbit", "frexp", "modf", "divmod", "gradient", "diff",
    "ediff1d", "trapz", "convolve", "correlate", "real", "imag", "conj",
    "angle", "iscomplexobj", "isrealobj", "shape", "size", "ndim",
    "result_type", "can_cast", "promote_types", "vander", "i0", "sinc",
    # round-5 tail: set ops, stats, selection, float-representation
    "unwrap", "cov", "corrcoef", "union1d", "intersect1d", "setdiff1d",
    "setxor1d", "isin", "select", "resize", "trim_zeros", "diag_indices",
    "diag_indices_from", "ix_", "spacing", "nextafter", "fmod",
    "logaddexp", "logaddexp2", "nancumsum", "nancumprod", "nanmedian",
    "nanpercentile", "nanquantile",
]

_this = sys.modules[__name__]
for _name in _FUNCS:
    if hasattr(jnp, _name) and not hasattr(_this, _name):
        setattr(_this, _name, _make(_name, getattr(jnp, _name)))
        __all__.append(_name)


def _boxing_callback(fn):
    """Adapt a user callback written against mx.np (NDArray in, NDArray
    out) to the raw-jnp calling convention jnp's higher-order functions
    use internally — the tracer must never end up inside an NDArray that
    escapes the trace."""
    def adapted(*arrays):
        out = fn(*[NDArray(a, _skip_device_put=True) for a in arrays])
        return out._data if isinstance(out, NDArray) else out
    return adapted


def apply_along_axis(func1d, axis, arr, *args, **kwargs):
    """numpy.apply_along_axis over an mx.np callback (vmapped by jnp)."""
    fn = _boxing_callback(lambda v: func1d(v, *args, **kwargs))
    return _call(lambda a: jnp.apply_along_axis(fn, axis, a), arr)


def apply_over_axes(func, a, axes):
    """numpy.apply_over_axes; ``func(arr, axis)`` takes/returns mx.np."""
    def fn(arr, axis):
        out = func(NDArray(arr, _skip_device_put=True), axis)
        return out._data if isinstance(out, NDArray) else out
    return _call(lambda x: jnp.apply_over_axes(fn, x, axes), a)


def piecewise(x, condlist, funclist):
    """numpy.piecewise; funclist entries may be scalars or mx.np
    callables."""
    funclist = [f if not callable(f) else _boxing_callback(f)
                for f in funclist]
    return _call(lambda xs, conds: jnp.piecewise(xs, list(conds),
                                                 funclist),
                 x, condlist)


__all__ += ["apply_along_axis", "apply_over_axes", "piecewise"]


def array(obj, dtype=None, ctx=None):
    """mx.np.array — accepts nested lists/numpy/NDArray."""
    if isinstance(obj, NDArray):
        obj = obj._data
    return NDArray(jnp.asarray(obj, dtype=_as_np_dtype(dtype)
                               if dtype else None), ctx=ctx)


asarray = array


# linalg sub-namespace
linalg = types.ModuleType(f"{__name__}.linalg")
sys.modules[linalg.__name__] = linalg
for _name in ["norm", "inv", "det", "slogdet", "cholesky", "qr", "svd",
              "eig", "eigh", "eigvals", "eigvalsh", "solve", "lstsq",
              "matrix_rank", "matrix_power", "pinv", "tensorsolve",
              "tensorinv", "multi_dot"]:
    if hasattr(jnp.linalg, _name):
        setattr(linalg, _name, _make(_name, getattr(jnp.linalg, _name)))

# fft sub-namespace
fft = types.ModuleType(f"{__name__}.fft")
sys.modules[fft.__name__] = fft
for _name in ["fft", "ifft", "rfft", "irfft", "fft2", "ifft2", "fftn",
              "ifftn", "fftfreq", "rfftfreq", "fftshift", "ifftshift"]:
    if hasattr(jnp.fft, _name):
        setattr(fft, _name, _make(_name, getattr(jnp.fft, _name)))


# random sub-namespace: stateful-API facade over jax.random (the eager key
# chain in _rng threads the state, ref: mx.np.random)
random = types.ModuleType(f"{__name__}.random")
sys.modules[random.__name__] = random


def _np_random(name, sampler):
    def wrapper(*args, size=None, dtype=None, ctx=None, **kwargs):
        key = _rng.next_key()
        shape = size if size is not None else ()
        if isinstance(shape, int):
            shape = (shape,)
        out = sampler(key, shape, *args, **kwargs)
        if dtype is not None:
            out = out.astype(_as_np_dtype(dtype))
        return NDArray(out, _skip_device_put=True)
    wrapper.__name__ = name
    return wrapper


random.uniform = _np_random(
    "uniform", lambda key, shape, low=0.0, high=1.0:
    jax.random.uniform(key, shape, minval=low, maxval=high))
random.normal = _np_random(
    "normal", lambda key, shape, loc=0.0, scale=1.0:
    jax.random.normal(key, shape) * scale + loc)
random.randint = _np_random(
    "randint", lambda key, shape, low, high=None:
    jax.random.randint(key, shape, low if high is not None else 0,
                       high if high is not None else low))
random.rand = lambda *shape: random.uniform(size=shape)
random.randn = lambda *shape: random.normal(size=shape)
random.choice = _np_random(
    "choice", lambda key, shape, a, replace=True, p=None:
    jax.random.choice(key, a if not isinstance(a, NDArray) else a._data,
                      shape, replace=replace,
                      p=None if p is None else _unbox(p)))
random.shuffle = lambda x: x._rebind(
    jax.random.permutation(_rng.next_key(), x._data))
random.permutation = _np_random(
    "permutation", lambda key, shape, x:
    jax.random.permutation(key, x if not isinstance(x, NDArray)
                           else x._data))
random.seed = lambda s: __import__(
    "mxnet_tpu.random", fromlist=["seed"]).seed(s)
random.exponential = _np_random(
    "exponential", lambda key, shape, scale=1.0:
    jax.random.exponential(key, shape) * scale)
def _gamma_sampler(key, _size, shape, scale=1.0):
    # the distribution parameter is NAMED 'shape' in numpy's API, so the
    # size-derived arg must not collide with it
    return jax.random.gamma(key, _unbox(shape), _size or None) * scale


random.gamma = _np_random("gamma", _gamma_sampler)
random.beta = _np_random(
    "beta", lambda key, shape, a, b:
    jax.random.beta(key, _unbox(a), _unbox(b), shape or None))
random.dirichlet = _np_random(
    "dirichlet", lambda key, shape, alpha:
    jax.random.dirichlet(key, jnp.asarray(_unbox(alpha), jnp.float32),
                         shape or None))

# round-5 distribution tail — inverse-CDF / mixture forms over the jax
# primitives, numpy-exact parameterizations (support and conventions per
# numpy.random: pareto is Lomax, geometric counts trials >= 1, power is
# U^(1/a) on [0,1])
_EPS = 1e-12


def _u01(key, shape):
    # open interval (0, 1): log(U) and 1/U stay finite
    return jnp.clip(jax.random.uniform(key, shape), _EPS, 1.0 - _EPS)


random.gumbel = _np_random(
    "gumbel", lambda key, shape, loc=0.0, scale=1.0:
    jax.random.gumbel(key, shape) * scale + loc)
random.laplace = _np_random(
    "laplace", lambda key, shape, loc=0.0, scale=1.0:
    jax.random.laplace(key, shape) * scale + loc)
random.logistic = _np_random(
    "logistic", lambda key, shape, loc=0.0, scale=1.0:
    jax.random.logistic(key, shape) * scale + loc)
random.lognormal = _np_random(
    "lognormal", lambda key, shape, mean=0.0, sigma=1.0:
    jnp.exp(jax.random.normal(key, shape) * sigma + mean))
random.poisson = _np_random(
    "poisson", lambda key, shape, lam=1.0:
    jax.random.poisson(key, _unbox(lam), shape or None))
def _eff_int():
    return jnp.int64 if jax.config.x64_enabled else jnp.int32


random.chisquare = _np_random(
    "chisquare", lambda key, shape, df:
    jax.random.chisquare(key, _unbox(df), shape=shape or None))
random.f = _np_random(
    "f", lambda key, shape, dfnum, dfden:
    (jax.random.chisquare(key, _unbox(dfnum), shape=shape or None)
     / jnp.asarray(_unbox(dfnum), jnp.float32))
    / (jax.random.chisquare(jax.random.fold_in(key, 1), _unbox(dfden),
                            shape=shape or None)
       / jnp.asarray(_unbox(dfden), jnp.float32)))
random.geometric = _np_random(
    "geometric", lambda key, shape, p:
    (jnp.floor(jnp.log(_u01(key, shape))
               / jnp.log1p(-jnp.clip(_unbox(p), _EPS, 1.0 - _EPS))) + 1.0)
    .astype(_eff_int()))
random.pareto = _np_random(
    "pareto", lambda key, shape, a:
    jnp.power(_u01(key, shape), -1.0 / jnp.asarray(_unbox(a),
                                                   jnp.float32)) - 1.0)
random.power = _np_random(
    "power", lambda key, shape, a:
    jnp.power(_u01(key, shape), 1.0 / jnp.asarray(_unbox(a),
                                                  jnp.float32)))
random.rayleigh = _np_random(
    "rayleigh", lambda key, shape, scale=1.0:
    scale * jnp.sqrt(-2.0 * jnp.log(_u01(key, shape))))
random.weibull = _np_random(
    "weibull", lambda key, shape, a:
    jnp.power(-jnp.log(_u01(key, shape)),
              1.0 / jnp.asarray(_unbox(a), jnp.float32)))
random.binomial = _np_random(
    "binomial", lambda key, shape, n, p:
    jax.random.binomial(key, _unbox(n), jnp.clip(_unbox(p), 0.0, 1.0),
                        shape=shape or None).astype(_eff_int()))
random.negative_binomial = _np_random(
    "negative_binomial", lambda key, shape, n, p:
    jax.random.poisson(
        jax.random.fold_in(key, 1),
        jax.random.gamma(key, jnp.asarray(_unbox(n), jnp.float32),
                         shape or None)
        * ((1.0 - jnp.asarray(_unbox(p), jnp.float32))
           / jnp.maximum(jnp.asarray(_unbox(p), jnp.float32), _EPS))))
random.multivariate_normal = _np_random(
    "multivariate_normal", lambda key, shape, mean, cov:
    jax.random.multivariate_normal(
        key, jnp.asarray(_unbox(mean), jnp.float32),
        jnp.asarray(_unbox(cov), jnp.float32), shape or None))


def _multinomial(n, pvals, size=None):
    """numpy.random.multinomial: counts over one draw of n trials.
    Counting is a scatter-add over the categorical draws — O(size*k)
    output memory, not the O(size*n*k) a one-hot sum would take."""
    key = _rng.next_key()
    p = jnp.asarray(_unbox(pvals), jnp.float32)
    shape = (size,) if isinstance(size, int) else tuple(size or ())
    k = p.shape[-1]
    draws = jax.random.categorical(key, jnp.log(jnp.maximum(p, _EPS)),
                                   shape=shape + (int(n),))
    flat = draws.reshape(-1, int(n))

    def count_row(row):
        return jnp.zeros((k,), _eff_int()).at[row].add(1)

    counts = jax.vmap(count_row)(flat).reshape(shape + (k,))
    return NDArray(counts, _skip_device_put=True)


random.multinomial = _multinomial

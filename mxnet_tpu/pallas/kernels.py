"""Seed kernels of the guarded Pallas tier (docs/pallas.md).

Three kernels target the two profiled ceilings docs/perf_notes.md ends on:

- ``conv_epilogue`` — the RN50 lever (conv fusions at ~76% of HBM
  bandwidth): scale·y + bias + residual + activation in ONE VMEM pass,
  promoted from ``benchmarks/conv_epilogue_probe.py``'s staged probe into
  the library, wired behind ``ops/nn.py``'s BatchNorm ``act_type`` path,
  the resnet-v1 residual epilogue, and ``nd.contrib.conv_epilogue``.
- ``matmul_epilogue`` — the BERT lever (~56% MFU inside XLA's matmul
  fusions, dropout-mask traffic measured 24% of a step pre-rbg): bias +
  activation + inverted dropout applied in one pass over the matmul
  output, wired behind the Gluon Dense/PositionwiseFFN path. Dropout
  keys follow the PR-1 ``(layer, tick, shard)`` fold discipline via
  :func:`dropout_bits`; mask semantics are bit-identical to
  ``ops/nn.py``'s Dropout (one uint8 per element, keep = bits >= ⌈p·256⌉).
- ``blockwise_attention`` — the existing long-context online-softmax
  kernel (parallel/ring_attention.py), routed through the same registry
  so every custom kernel shares one kill-switch / parity / journal story.

Every kernel registers with its XLA reference and tolerance; gradients of
the Pallas paths are ``custom_vjp`` with the reference's VJP as the
backward (rematerialized — the backward is mathematically the reference's,
so the parity gate bounds the full training step, not just the forward).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..base import MXNetError
from .registry import dispatch, register_kernel

__all__ = ["fused_conv_epilogue", "fused_matmul_epilogue", "dropout_bits",
           "keep_threshold", "EPILOGUE_ACTS"]


def _block(n, cap):
    """Largest divisor of n that is <= cap (the grid must tile n exactly —
    a floor-divided grid would leave the remainder rows unwritten)."""
    for b in range(min(cap, n), 0, -1):
        if n % b == 0:
            return b
    return 1


def _block_pair(r, c, block, row_cap=512, col_cap=256):
    """Resolve the (block_r, block_c) tiling for an (r, c) view. An
    explicit/tuned ``block`` wins when it tiles the view exactly;
    anything else clamps to the default — the epilogues are elementwise
    over the tile grid, so every exact tiling is bit-identical, and a
    non-divisor block (stale tuned table, wrong shape class) must degrade
    to the default rather than leave remainder rows unwritten."""
    if block is not None:
        try:
            br, bc = int(block[0]), int(block[1])
        except (TypeError, ValueError, IndexError):
            br = bc = 0
        if 0 < br <= r and 0 < bc <= c and r % br == 0 and c % bc == 0:
            return br, bc
    return _block(r, row_cap), _block(c, col_cap)


def _act_fn(act_type):
    fns = {
        None: lambda x: x,
        "identity": lambda x: x,
        "relu": lambda x: jnp.maximum(x, 0.0),
        "gelu": lambda x: jax.nn.gelu(x, approximate=False),
        "tanh": jnp.tanh,
        "sigmoid": jax.nn.sigmoid,
    }
    try:
        return fns[act_type]
    except KeyError:
        raise MXNetError(f"pallas epilogue: unknown act_type {act_type!r}; "
                         f"one of {sorted(k for k in fns if k)}") from None


EPILOGUE_ACTS = ("identity", "relu", "gelu", "tanh", "sigmoid")


def _vec_spec(shape, br, bc):
    """BlockSpec for a (1, C) column-broadcast or (R, 1) row-broadcast
    vector riding next to (br, bc) data blocks."""
    from jax.experimental import pallas as pl
    if shape[0] == 1:
        return pl.BlockSpec((1, bc), lambda i, j: (0, j))
    return pl.BlockSpec((br, 1), lambda i, j: (i, 0))


def _check_vec(name, v, y):
    if v.shape not in ((1, y.shape[1]), (y.shape[0], 1)):
        return (f"shape:{name}{v.shape}_vs_y{y.shape} (want (1, C) or "
                f"(R, 1))")
    return None


def _epilogue_tune_key(y, *rest, **params):
    """Shape class of an epilogue dispatch ("RxC") — the tuned-table key
    under which a committed block shape applies to this call."""
    if getattr(y, "ndim", 0) != 2:
        return None
    return f"{y.shape[0]}x{y.shape[1]}"


# ---------------------------------------------------------------------------
# conv epilogue: act(scale * y + bias [+ res]) in one VMEM pass
# ---------------------------------------------------------------------------
def _conv_epilogue_ref(y, scale, bias, res=None, act_type="relu",
                       block=None):
    """The XLA reference (the semantic contract): fp32 accumulation, cast
    back to y's dtype — matching the kernel's internal math. ``block`` is
    the Pallas tier's tiling knob; tiling doesn't change semantics, so
    the reference accepts and ignores it (fallback keeps one signature)."""
    out = (y.astype(jnp.float32) * scale.astype(jnp.float32)
           + bias.astype(jnp.float32))
    if res is not None:
        out = out + res.astype(jnp.float32)
    return _act_fn(act_type)(out).astype(y.dtype)


def _conv_epilogue_call(y, scale, bias, res, act_type, interpret, block):
    from jax.experimental import pallas as pl
    r, c = y.shape
    br, bc = _block_pair(r, c, block)
    act = _act_fn(act_type)
    data = pl.BlockSpec((br, bc), lambda i, j: (i, j))

    def kernel(y_ref, s_ref, b_ref, *rest):
        o_ref = rest[-1]
        out = (y_ref[...].astype(jnp.float32)
               * s_ref[...].astype(jnp.float32)
               + b_ref[...].astype(jnp.float32))
        if len(rest) == 2:
            out = out + rest[0][...].astype(jnp.float32)
        o_ref[...] = act(out).astype(o_ref.dtype)

    in_specs = [data, _vec_spec(scale.shape, br, bc),
                _vec_spec(bias.shape, br, bc)]
    args = [y, scale, bias]
    if res is not None:
        in_specs.append(data)
        args.append(res)
    return pl.pallas_call(
        kernel, grid=(r // br, c // bc), in_specs=in_specs, out_specs=data,
        out_shape=jax.ShapeDtypeStruct((r, c), y.dtype),
        interpret=interpret)(*args)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def _ce_res(act_type, interpret, block, y, scale, bias, res):
    return _conv_epilogue_call(y, scale, bias, res, act_type, interpret,
                               block)


def _ce_res_fwd(act_type, interpret, block, y, scale, bias, res):
    return (_ce_res(act_type, interpret, block, y, scale, bias, res),
            (y, scale, bias, res))


def _ce_res_bwd(act_type, interpret, block, saved, g):
    y, scale, bias, res = saved
    _, vjp = jax.vjp(
        lambda a, s, b, r: _conv_epilogue_ref(a, s, b, r,
                                              act_type=act_type),
        y, scale, bias, res)
    return vjp(g)


_ce_res.defvjp(_ce_res_fwd, _ce_res_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def _ce_nores(act_type, interpret, block, y, scale, bias):
    return _conv_epilogue_call(y, scale, bias, None, act_type, interpret,
                               block)


def _ce_nores_fwd(act_type, interpret, block, y, scale, bias):
    return (_ce_nores(act_type, interpret, block, y, scale, bias),
            (y, scale, bias))


def _ce_nores_bwd(act_type, interpret, block, saved, g):
    y, scale, bias = saved
    _, vjp = jax.vjp(
        lambda a, s, b: _conv_epilogue_ref(a, s, b, act_type=act_type),
        y, scale, bias)
    return vjp(g)


_ce_nores.defvjp(_ce_nores_fwd, _ce_nores_bwd)


def _conv_epilogue_supports(y, scale, bias, res=None, act_type="relu",
                            block=None):
    if y.ndim != 2:
        return f"not_2d:{y.shape}"
    if y.size == 0:
        return "empty"
    if not jnp.issubdtype(y.dtype, jnp.floating):
        return f"dtype:{y.dtype}"
    if y.shape[1] < 8:
        return f"minor_dim_tiny:{y.shape[1]}"
    for name, v in (("scale", scale), ("bias", bias)):
        bad = _check_vec(name, v, y)
        if bad:
            return bad
    if scale.shape != bias.shape:
        return f"shape:scale{scale.shape}_vs_bias{bias.shape}"
    if res is not None and res.shape != y.shape:
        return f"shape:res{res.shape}_vs_y{y.shape}"
    if act_type not in (None,) + EPILOGUE_ACTS:
        return f"act:{act_type}"
    return None


def _conv_epilogue_example():
    rng = np.random.RandomState(0)
    y = jnp.asarray(rng.randn(16, 128), jnp.float32)
    res = jnp.asarray(rng.randn(16, 128), jnp.float32)
    col = (jnp.asarray(rng.rand(1, 128) + 0.5, jnp.float32),
           jnp.asarray(rng.randn(1, 128) * 0.1, jnp.float32))
    row = (jnp.asarray(rng.rand(16, 1) + 0.5, jnp.float32),
           jnp.asarray(rng.randn(16, 1) * 0.1, jnp.float32))
    return [
        ((y, col[0], col[1], res), {"act_type": "relu"}),
        ((y, row[0], row[1], None), {"act_type": "relu"}),
        ((y, col[0], col[1], None), {"act_type": "gelu"}),
    ]


@register_kernel(
    "conv_epilogue", xla_reference=_conv_epilogue_ref, tolerance=1e-5,
    backends=("tpu",), supports=_conv_epilogue_supports,
    example=_conv_epilogue_example,
    doc="act(scale*y + bias [+ res]) over 2D rows in one VMEM pass — the "
        "RN50 conv-fusion bandwidth lever (docs/perf_notes.md; promoted "
        "from benchmarks/conv_epilogue_probe.py). scale/bias broadcast "
        "as (1, C) columns or (R, 1) rows. block=(br, bc) overrides the "
        "default tiling (tuned tables; any exact tiling is bit-identical, "
        "invalid blocks clamp to the default).",
    tune_key=_epilogue_tune_key)
def _conv_epilogue_pallas(y, scale, bias, res=None, interpret=False,
                          act_type="relu", block=None):
    block = None if block is None else (int(block[0]), int(block[1]))
    if res is None:
        return _ce_nores(act_type, bool(interpret), block, y, scale, bias)
    return _ce_res(act_type, bool(interpret), block, y, scale, bias, res)


# ---------------------------------------------------------------------------
# matmul epilogue: dropout(act(y + bias)) in one pass over the matmul output
# ---------------------------------------------------------------------------
def keep_threshold(p):
    """uint8 keep threshold, bit-identical to ops/nn.py Dropout: one
    random byte per element, keep where bits >= threshold."""
    return min(255, int(round(float(p) * 256)))


def dropout_bits(key, shape, layer=0, tick=0, shard=0):
    """Per-call dropout bytes under the PR-1 fold discipline: the
    (layer, tick, shard) identity folds into the key so every layer,
    microbatch/scan tick, and shard draws an independent mask from one
    threaded key (the correlated-mask class fixed in PR 1)."""
    for v in (layer, tick, shard):
        key = jax.random.fold_in(key, v)
    return jax.random.bits(key, tuple(shape), dtype=jnp.uint8)


def _matmul_epilogue_ref(y, bias, bits=None, act_type="gelu", p=0.0,
                         block=None):
    out = _act_fn(act_type)(y.astype(jnp.float32)
                            + bias.astype(jnp.float32))
    if bits is not None and p > 0:
        keep = bits >= jnp.uint8(keep_threshold(p))
        out = jnp.where(keep, out / (1.0 - p), 0.0)
    return out.astype(y.dtype)


def _matmul_epilogue_call(y, bias, bits, act_type, p, interpret, block):
    from jax.experimental import pallas as pl
    r, c = y.shape
    br, bc = _block_pair(r, c, block)
    act = _act_fn(act_type)
    data = pl.BlockSpec((br, bc), lambda i, j: (i, j))
    thresh = keep_threshold(p)
    inv = 1.0 / (1.0 - p) if p < 1.0 else 0.0

    def kernel(y_ref, b_ref, *rest):
        o_ref = rest[-1]
        out = act(y_ref[...].astype(jnp.float32)
                  + b_ref[...].astype(jnp.float32))
        if len(rest) == 2:
            keep = rest[0][...] >= jnp.uint8(thresh)
            out = jnp.where(keep, out * inv, 0.0)
        o_ref[...] = out.astype(o_ref.dtype)

    in_specs = [data, _vec_spec(bias.shape, br, bc)]
    args = [y, bias]
    if bits is not None and p > 0:
        in_specs.append(data)
        args.append(bits)
    return pl.pallas_call(
        kernel, grid=(r // br, c // bc), in_specs=in_specs, out_specs=data,
        out_shape=jax.ShapeDtypeStruct((r, c), y.dtype),
        interpret=interpret)(*args)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3))
def _me_drop(act_type, p, interpret, block, y, bias, bits):
    return _matmul_epilogue_call(y, bias, bits, act_type, p, interpret,
                                 block)


def _me_drop_fwd(act_type, p, interpret, block, y, bias, bits):
    return (_me_drop(act_type, p, interpret, block, y, bias, bits),
            (y, bias, bits))


def _me_drop_bwd(act_type, p, interpret, block, saved, g):
    y, bias, bits = saved
    _, vjp = jax.vjp(
        lambda a, b: _matmul_epilogue_ref(a, b, bits, act_type=act_type,
                                          p=p), y, bias)
    dy, dbias = vjp(g)
    # integer primal: cotangent must be float0, not None
    return dy, dbias, np.zeros(bits.shape, dtype=jax.dtypes.float0)


_me_drop.defvjp(_me_drop_fwd, _me_drop_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def _me_nodrop(act_type, interpret, block, y, bias):
    return _matmul_epilogue_call(y, bias, None, act_type, 0.0, interpret,
                                 block)


def _me_nodrop_fwd(act_type, interpret, block, y, bias):
    return _me_nodrop(act_type, interpret, block, y, bias), (y, bias)


def _me_nodrop_bwd(act_type, interpret, block, saved, g):
    y, bias = saved
    _, vjp = jax.vjp(
        lambda a, b: _matmul_epilogue_ref(a, b, act_type=act_type), y, bias)
    return vjp(g)


_me_nodrop.defvjp(_me_nodrop_fwd, _me_nodrop_bwd)


def _matmul_epilogue_supports(y, bias, bits=None, act_type="gelu", p=0.0,
                              block=None):
    if y.ndim != 2:
        return f"not_2d:{y.shape}"
    if y.size == 0:
        return "empty"
    if not jnp.issubdtype(y.dtype, jnp.floating):
        return f"dtype:{y.dtype}"
    if y.shape[1] < 8:
        return f"minor_dim_tiny:{y.shape[1]}"
    bad = _check_vec("bias", bias, y)
    if bad:
        return bad
    if bits is not None:
        if bits.shape != y.shape:
            return f"shape:bits{bits.shape}_vs_y{y.shape}"
        if bits.dtype != jnp.uint8:
            return f"dtype:bits_{bits.dtype}"
    if act_type not in (None,) + EPILOGUE_ACTS:
        return f"act:{act_type}"
    if not 0.0 <= float(p) < 1.0:
        return f"p:{p}"
    return None


def _matmul_epilogue_example():
    rng = np.random.RandomState(1)
    y = jnp.asarray(rng.randn(32, 128), jnp.float32)
    b = jnp.asarray(rng.randn(1, 128) * 0.1, jnp.float32)
    bits = dropout_bits(  # graftlint: disable=G2 deterministic parity-gate fixture
        jax.random.key(7), (32, 128), layer=1, tick=2)
    return [
        ((y, b, None), {"act_type": "gelu", "p": 0.0}),
        ((y, b, bits), {"act_type": "gelu", "p": 0.3}),
        ((y, b, bits), {"act_type": "identity", "p": 0.5}),
    ]


@register_kernel(
    "matmul_epilogue", xla_reference=_matmul_epilogue_ref, tolerance=1e-5,
    backends=("tpu",), supports=_matmul_epilogue_supports,
    example=_matmul_epilogue_example,
    doc="dropout(act(y + bias)) in one pass over a matmul output — the "
        "BERT MFU lever (docs/perf_notes.md: dropout-in-epilogue, "
        "docs/roadmap.md items 3-4). Mask semantics bit-identical to "
        "ops/nn.py Dropout; bits come from dropout_bits() under the "
        "PR-1 (layer, tick, shard) fold discipline. block=(br, bc) "
        "overrides the default tiling (tuned tables; invalid blocks "
        "clamp to the default).",
    tune_key=_epilogue_tune_key)
def _matmul_epilogue_pallas(y, bias, bits=None, interpret=False,
                            act_type="gelu", p=0.0, block=None):
    block = None if block is None else (int(block[0]), int(block[1]))
    if bits is None or p <= 0:
        return _me_nodrop(act_type, bool(interpret), block, y, bias)
    return _me_drop(act_type, float(p), bool(interpret), block, y, bias,
                    bits)


# ---------------------------------------------------------------------------
# blockwise attention: the existing online-softmax kernel, same guard story
# ---------------------------------------------------------------------------
def _blockwise_ref(q, k, v, block_size=512, causal=False, scale=None,
                   _chunk=2048):
    """Dense-attention reference with the query axis chunked: the same
    math as attention_reference (each chunk sees its exact key prefix,
    so bottom-right causal alignment is preserved), but the score-matrix
    footprint is bounded at chunk×S — the kill switch must not turn a
    long-context run's O(S·block) memory into an O(S²) OOM."""
    from ..parallel.ring_attention import attention_reference
    if scale is None:
        scale = float(q.shape[-1]) ** -0.5
    s_q, s_kv = q.shape[-2], k.shape[-2]
    if s_q <= _chunk:
        return attention_reference(q, k, v, causal=causal, scale=scale)
    outs = []
    for i in range(0, s_q, _chunk):
        qc = q[..., i:i + _chunk, :]
        length = qc.shape[-2]
        if not causal:
            outs.append(attention_reference(qc, k, v, causal=False,
                                            scale=scale))
            continue
        # bottom-right alignment: global row i+r attends keys
        # j <= i + r + (s_kv - s_q). Slicing keys to that chunk's max
        # makes the reference's own (kmax - length) offset land exactly
        # there; a non-positive kmax means every row's set is empty.
        kmax = i + length + s_kv - s_q
        if kmax <= 0:
            outs.append(jnp.zeros(qc.shape, q.dtype))
            continue
        outs.append(attention_reference(
            qc, k[..., :kmax, :], v[..., :kmax, :], causal=True,
            scale=scale))
    return jnp.concatenate(outs, axis=-2)


def _blockwise_supports(q, k, v, block_size=512, causal=False, scale=None):
    if q.shape[-1] != k.shape[-1] or k.shape[:-1] != v.shape[:-1]:
        return f"shape:q{q.shape}_k{k.shape}_v{v.shape}"
    if q.size == 0:
        return "empty"
    return None


def _blockwise_example():
    rng = np.random.RandomState(2)
    q = jnp.asarray(rng.randn(2, 2, 64, 16), jnp.float32)
    k = jnp.asarray(rng.randn(2, 2, 64, 16), jnp.float32)
    v = jnp.asarray(rng.randn(2, 2, 64, 16), jnp.float32)
    return [
        ((q, k, v), {"block_size": 16, "causal": False}),
        ((q, k, v), {"block_size": 16, "causal": True}),
    ]


@register_kernel(
    "blockwise_attention", xla_reference=_blockwise_ref, tolerance=2e-4,
    backends=("tpu", "cpu", "gpu"), supports=_blockwise_supports,
    example=_blockwise_example,
    doc="Memory-efficient online-softmax attention over KV blocks "
        "(parallel/ring_attention.py) — registered so the long-context "
        "kernel shares the tier's kill-switch, parity gate, and journal "
        "story. Portable (lax.scan), so every backend is a first-class "
        "target; the reference materializes the full score matrix.")
def _blockwise_pallas(q, k, v, interpret=False, block_size=512, causal=False,
                      scale=None):
    from ..parallel.ring_attention import _blockwise_impl
    return _blockwise_impl(q, k, v, block_size=block_size, causal=causal,
                           scale=scale)


# ---------------------------------------------------------------------------
# N-D wrappers — the surface ops/ and gluon/ wire against
# ---------------------------------------------------------------------------
def fused_conv_epilogue(x, scale=None, bias=None, res=None, channel_axis=-1,
                        act_type="relu", interpret=False):
    """N-D entry: normalize to the 2D kernel form and dispatch.

    ``scale``/``bias`` are per-channel vectors along ``channel_axis``
    (or None for a pure residual-add epilogue). Channel-last inputs map
    to (1, C) column broadcasts; ``channel_axis=1`` (NCHW) maps to
    (R, 1) row broadcasts over a (N*C, spatial) view — no transpose on
    either layout. Other axes are moved to the minor position first.
    """
    shape = x.shape
    if x.ndim < 2:
        # nothing to tile: the reference IS the op
        s = jnp.ones((1,), x.dtype) if scale is None else scale
        b = jnp.zeros((1,), x.dtype) if bias is None else bias
        return _conv_epilogue_ref(x.reshape(1, -1), s.reshape(1, -1),
                                  b.reshape(1, -1),
                                  None if res is None
                                  else res.reshape(1, -1),
                                  act_type=act_type).reshape(shape)
    ax = channel_axis % x.ndim
    moved = False
    if scale is None and bias is None:
        # no per-channel vectors: any 2D view works — pick the one with
        # the widest well-aligned minor dim for lane utilization
        y2 = _flatten2d(x)
        r2 = None if res is None else res.reshape(y2.shape)
        c = y2.shape[1]
        s2 = jnp.ones((1, c), x.dtype)
        b2 = jnp.zeros((1, c), x.dtype)
    elif ax == x.ndim - 1:
        c = shape[ax]
        y2 = x.reshape(-1, c)
        r2 = None if res is None else res.reshape(-1, c)
        s2 = (jnp.ones((1, c), x.dtype) if scale is None
              else scale.reshape(1, c))
        b2 = (jnp.zeros((1, c), x.dtype) if bias is None
              else bias.reshape(1, c))
    else:
        if ax != 1:
            x = jnp.moveaxis(x, ax, 1)
            res = None if res is None else jnp.moveaxis(res, ax, 1)
            shape = x.shape
            moved = True
        n, c = shape[0], shape[1]
        y2 = x.reshape(n * c, -1)
        r2 = None if res is None else res.reshape(n * c, -1)

        def _rowvec(v, fill):
            if v is None:
                return jnp.full((n * c, 1), fill, x.dtype)
            return jnp.tile(v.reshape(c), n).reshape(n * c, 1)

        s2 = _rowvec(scale, 1)
        b2 = _rowvec(bias, 0)
    out = dispatch("conv_epilogue", y2, s2, b2, r2, act_type=act_type,
                   interpret=interpret)
    out = out.reshape(shape)
    if moved:
        out = jnp.moveaxis(out, 1, ax)
    return out


def _flatten2d(x):
    """2D view of x maximizing a lane-aligned minor dim: the largest
    divisor of x.size that is <= 4096 and a multiple of 128, else the
    natural (…, last) flatten."""
    total = int(x.size)
    for c in range(4096, 127, -128):
        if total % c == 0:
            return x.reshape(total // c, c)
    return x.reshape(-1, x.shape[-1])


def fused_matmul_epilogue(y, bias, act_type=None, p=0.0, rng=None,
                          training=False, layer=0, tick=0, shard=0,
                          interpret=False):
    """N-D entry for the matmul epilogue: dropout(act(y + bias)) with
    ``bias`` along the minor axis. Dropout engages only in training with
    ``p > 0`` and an rng key; bits derive via :func:`dropout_bits` under
    the (layer, tick, shard) fold discipline."""
    shape = y.shape
    c = shape[-1]
    y2 = y.reshape(-1, c)
    b2 = (jnp.zeros((1, c), y.dtype) if bias is None
          else bias.reshape(1, c))
    bits = None
    p = float(p)
    if training and p > 0 and rng is not None:
        bits = dropout_bits(rng, y2.shape, layer=layer, tick=tick,
                            shard=shard)
    out = dispatch("matmul_epilogue", y2, b2, bits, act_type=act_type,
                   p=p if bits is not None else 0.0, interpret=interpret)
    return out.reshape(shape)

"""mxnet_tpu.pallas — the guarded custom-kernel tier (docs/pallas.md).

One registry (``registry.py``) maps op names to (pallas_impl,
xla_reference, tolerance) triples; ``dispatch`` auto-selects the custom
path only where it is verified to run and falls back — journaled, never
silent — to the XLA reference everywhere else (non-TPU backends,
unsupported shapes, ``MXNET_TPU_PALLAS=off``). Every registered kernel is
parity-gated against its reference at test time (tests/test_pallas.py),
so the tier can never silently change numerics, and CI's G10 lint rule
keeps raw ``pl.pallas_call`` out of library code so no kernel can bypass
the guard.

Importing this package registers the seed kernels (``kernels.py``); it
never dials a backend (G1 contract — backend checks happen at dispatch
time).
"""
from __future__ import annotations

from . import kernels as _kernels          # noqa: F401  (registration)
from .kernels import (EPILOGUE_ACTS, dropout_bits, fused_conv_epilogue,
                      fused_matmul_epilogue, keep_threshold)
from .registry import (MODES, KernelSpec, dispatch, get_kernel, kernels,
                       mode, register_kernel, reset_provenance, set_mode,
                       tier_provenance)

__all__ = ["KernelSpec", "MODES", "EPILOGUE_ACTS", "dispatch",
           "dropout_bits", "fused_conv_epilogue", "fused_matmul_epilogue",
           "get_kernel", "keep_threshold", "kernels", "mode",
           "register_kernel", "reset_provenance", "set_mode",
           "tier_provenance"]

"""Guarded custom-kernel registry — the one gate every hand kernel runs
through (ROADMAP item 2; docs/pallas.md).

docs/perf_notes.md ends the XLA-level optimization story at two profiled
ceilings (RN50 conv fusions at ~76% of HBM bandwidth, BERT at ~56% MFU in
XLA's matmul fusions). Hand Pallas kernels are the named lever — but a hand
kernel that silently changes numerics, or silently runs an unverified code
path on a backend it was never tested on, is a worse defect class than the
ceilings it chases. This registry is the guard:

- every kernel registers as a ``(pallas_impl, xla_reference, tolerance)``
  triple; the reference is the *semantic contract* and the tolerance is the
  budget the implementation must meet (enforced by tests/test_pallas.py's
  interpret-mode parity gate over every registered kernel — a kernel
  without a passing parity gate cannot ship);
- dispatch auto-selects the custom path only where it is verified to run
  (``backends``), the shape is supported (``supports``), and the operator
  has not been killed (``MXNET_TPU_PALLAS=off``); everything else falls
  back to the XLA reference — journaled (``pallas_fallback`` records with a
  reason) and counted, never silent;
- per-op tier provenance (:func:`tier_provenance`) is a first-class
  output: ``bench.py --pallas {on,off,auto}`` stamps it into the BENCH
  artifact so an A/B number always says which tier produced it.

The registry — not any one kernel — is the subsystem's deliverable: future
hand kernels (int8 GEMMs, MoE dispatch) register here and inherit the
parity gate, the fallback matrix, and the journal story for free.
"""
from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Callable, Dict, Optional, Sequence, Tuple

from ..base import MXNetError

__all__ = ["KernelSpec", "register_kernel", "get_kernel", "kernels",
           "dispatch", "mode", "set_mode", "tier_provenance",
           "reset_provenance", "MODES"]

MODES = ("auto", "on", "off")

_REGISTRY: Dict[str, "KernelSpec"] = {}
_lock = threading.Lock()
_mode_override: Optional[str] = None
# journal dedupe + provenance: dispatch runs per eager op call (and per
# trace under jit) — one journal line per (kernel, reason) per process,
# with full counts kept in the provenance table instead
_journaled: set = set()
_prov: Dict[str, Dict] = {}


@dataclass
class KernelSpec:
    """One guarded custom kernel: the impl, its semantic contract, and the
    selection gates.

    ``pallas_impl(*args, interpret=False, **params)`` and
    ``xla_reference(*args, **params)`` share one signature; parity within
    ``tolerance`` (max abs error on fp32-cast outputs) is enforced by the
    registration-time test gate over ``example()``'s representative
    arguments, so registering a kernel without a passing gate fails CI,
    and the tier can never silently change numerics."""

    name: str
    pallas_impl: Callable
    xla_reference: Callable
    tolerance: float
    backends: Tuple[str, ...] = ("tpu",)
    supports: Optional[Callable] = None   # (*args, **params) -> None | reason
    example: Optional[Callable] = None    # () -> (args, params) for the gate
    doc: str = ""
    differentiable: bool = True
    # (*args, **params) -> shape-class string ("RxC") | None: the tuned-
    # table key under which an autotuned block shape applies to a call.
    # None = the kernel takes no tuned knobs (autotune never touches it).
    tune_key: Optional[Callable] = None


def register_kernel(name: str, *, xla_reference: Callable, tolerance: float,
                    backends: Sequence[str] = ("tpu",),
                    supports: Optional[Callable] = None,
                    example: Optional[Callable] = None,
                    doc: str = "", differentiable: bool = True,
                    tune_key: Optional[Callable] = None):
    """Decorator registering ``fn`` as the custom impl of kernel ``name``."""
    def deco(fn):
        with _lock:
            if name in _REGISTRY:
                raise MXNetError(f"duplicate pallas kernel registration: "
                                 f"{name!r}")
            _REGISTRY[name] = KernelSpec(
                name=name, pallas_impl=fn, xla_reference=xla_reference,
                tolerance=float(tolerance), backends=tuple(backends),
                supports=supports, example=example,
                doc=doc or (fn.__doc__ or ""),
                differentiable=differentiable, tune_key=tune_key)
        return fn
    return deco


def get_kernel(name: str) -> KernelSpec:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise MXNetError(
            f"pallas kernel {name!r} is not registered "
            f"({sorted(_REGISTRY)} known)") from None


def kernels() -> Dict[str, KernelSpec]:
    """Snapshot of the registry (name -> spec), for the parity gate."""
    from . import kernels as _k   # noqa: F401  (registration side effect)
    with _lock:
        return dict(sorted(_REGISTRY.items()))


# ---------------------------------------------------------------------------
# mode / backend resolution
# ---------------------------------------------------------------------------
def mode() -> str:
    """Effective tier mode: ``set_mode`` override, else the
    ``MXNET_TPU_PALLAS`` env knob, else ``auto``. A malformed knob value
    degrades to ``auto`` (journaled once) — a typo in an env var must
    never flip a training run onto an unverified path OR kill it."""
    if _mode_override is not None:
        return _mode_override
    raw = os.environ.get("MXNET_TPU_PALLAS", "auto").strip().lower()
    if raw in MODES:
        return raw
    _journal_once("__mode__", f"bad_mode:{raw}",
                  detail=f"MXNET_TPU_PALLAS={raw!r} not in {MODES}; "
                         f"using 'auto'")
    return "auto"


def set_mode(value: Optional[str]) -> None:
    """Process-level override of the env knob (``None`` resets). The
    bench A/B flag and tests use this; production selection should use
    the env var so child processes inherit it."""
    global _mode_override
    if value is not None and value not in MODES:
        raise MXNetError(f"pallas mode must be one of {MODES}; "
                         f"got {value!r}")
    _mode_override = value


def _backend() -> str:
    """Call-time backend name. ``jax.default_backend()`` here is a
    call-time dial like ops/contrib.py's — never at import (G1)."""
    import jax
    try:
        return jax.default_backend()
    except RuntimeError:        # backend not initializable: act like CPU
        return "cpu"


# ---------------------------------------------------------------------------
# dispatch
# ---------------------------------------------------------------------------
def _journal_once(kernel: str, reason: str, **fields) -> None:
    key = (kernel, reason)
    with _lock:
        if key in _journaled:
            return
        _journaled.add(key)
    from ..diagnostics import get_journal
    get_journal().event("pallas_fallback", kernel=kernel, reason=reason,
                        **fields)


# tuned-table injection: one journal line per (kernel, shape_class)
# outcome — dispatch is per-op-call hot, the journal is not
_tuned_logged: set = set()


def _tuned_block(spec: "KernelSpec", args, params):
    """The tuned block for this dispatch, or None: consult the active
    tuned table (MXNET_TPU_TUNED_TABLE via autotune.table.tuned_for —
    cached, validated, never raises) at the kernel's shape class.  An
    entry that would not tile the class exactly is refused here with a
    journaled ``tuned_fallback`` (the kernels would clamp it anyway —
    refusing early keeps the journal truthful about what actually ran)."""
    from ..autotune import table as _tt
    doc = _tt.tuned_for("pallas")
    if doc is None:
        return None
    cls = spec.tune_key(*args, **params)
    if not cls:
        return None
    entry = _tt.pallas_entry(doc, spec.name, cls)
    blk = entry.get("block") if isinstance(entry, dict) else None
    if blk is None:
        return None
    log_key = (spec.name, cls)
    try:
        r, c = (int(v) for v in cls.split("x"))
        br, bc = int(blk[0]), int(blk[1])
        ok = 0 < br <= r and 0 < bc <= c and r % br == 0 and c % bc == 0
    except (TypeError, ValueError):
        ok = False
    with _lock:
        first = log_key not in _tuned_logged
        if first:
            _tuned_logged.add(log_key)
    if not ok:
        if first:
            from ..diagnostics import get_journal
            get_journal().event(
                "tuned_fallback", reason="invalid_block", site="pallas",
                kernel=spec.name, shape_class=cls, block=blk,
                fallback="builtin_defaults")
        return None
    if first:
        from ..diagnostics import get_journal
        get_journal().event("tuned_load", site="pallas", kernel=spec.name,
                            shape_class=cls, block=[br, bc])
    return (br, bc)


def _note(kernel: str, tier: str, reason: Optional[str] = None) -> None:
    with _lock:
        rec = _prov.setdefault(kernel, {"pallas": 0, "xla": 0,
                                        "fallback_reasons": {}})
        rec[tier] += 1
        if reason:
            rr = rec["fallback_reasons"]
            rr[reason] = rr.get(reason, 0) + 1


def tier_provenance() -> Dict[str, Dict]:
    """Per-kernel dispatch accounting since process start (or the last
    :func:`reset_provenance`): how many times each tier ran and why the
    XLA tier was chosen. Counts are per *dispatch decision* — once per
    eager op call, once per trace under jit — which is exactly the
    provenance a BENCH artifact needs ("which tier compiled into the
    measured program")."""
    with _lock:
        return {k: {"pallas": v["pallas"], "xla": v["xla"],
                    "fallback_reasons": dict(v["fallback_reasons"])}
                for k, v in sorted(_prov.items())}


def reset_provenance() -> None:
    with _lock:
        _prov.clear()
        _journaled.clear()
        _tuned_logged.clear()


def dispatch(name: str, *args, interpret: bool = False, **params):
    """Run kernel ``name``: the custom tier where it is verified to
    apply, the XLA reference everywhere else.

    Selection order (first hit wins, reason journaled once + counted):

    1. ``mode() == "off"`` — the kill switch beats everything, including
       ``interpret`` (an operator turning the tier off must get the
       reference, period).
    2. ``supports`` rejects the concrete shapes/dtypes — unsupported
       inputs fall back *before* the backend gate so the reason an
       operator sees on any host names the real blocker.
    3. backend not in ``spec.backends`` — unless ``interpret=True``,
       which runs the custom impl in interpret mode (the CPU parity
       gate's path; never the default on any backend).

    ``mode() == "on"`` does not force an unsupported kernel onto the
    hardware — it makes every fallback LOUD (a ``RuntimeWarning`` on top
    of the journal line), for A/B runs that must not quietly measure the
    reference tier.
    """
    spec = get_kernel(name)
    m = mode()
    reason = None
    if m == "off":
        reason = "mode_off"
    if reason is None and spec.supports is not None:
        reason = spec.supports(*args, **params)
    if reason is None and not interpret:
        backend = _backend()
        if backend not in spec.backends:
            reason = f"backend:{backend}"
    # dispatch decisions ride the active trace span (if any): a traced
    # step's span says which kernel tier compiled into it, and why a
    # fallback happened (docs/observability.md)
    from ..observability import trace as _trace
    if reason is None:
        # tuned tiling rides the pallas tier only — an explicit block=
        # always wins, the reference tier never sees injected knobs
        if spec.tune_key is not None and "block" not in params:
            blk = _tuned_block(spec, args, params)
            if blk is not None:
                params = dict(params, block=blk)
        _note(name, "pallas")
        _trace.annotate(**{f"pallas.{name}": "pallas"})
        return spec.pallas_impl(*args, interpret=interpret, **params)
    _note(name, "xla", reason)
    _trace.annotate(**{f"pallas.{name}": f"xla:{reason}"})
    _journal_once(name, reason, mode=m)
    if m == "on" and reason != "mode_off":
        import warnings
        warnings.warn(
            f"pallas kernel {name!r} fell back to the XLA reference "
            f"({reason}) despite MXNET_TPU_PALLAS=on", RuntimeWarning,
            stacklevel=2)
    return spec.xla_reference(*args, **params)

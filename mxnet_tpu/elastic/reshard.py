"""Topology-aware checkpoint reader — restore N_old shard files onto an
N_new mesh.

``parallel/_ckpt.py``'s fast path is layout-locked: per-shard restore
demands a saved piece for EXACTLY each shard index the current mesh
produces (``place_like`` raises "mesh or sharding layout changed since
save"). That is the right contract for a crash-resume on the same
topology — and exactly the wrong one after an elastic resize, where the
survivors' mesh produces different shard indices than the cohort that
wrote the checkpoint.

This module is the slow-but-shape-free lane:

1. **assemble** — read the meta file plus ALL ``.shard0..N_old-1``
   files the manifest's recorded shard set names (never a glob — stale
   files from an older save with a different world would mix in), and
   paste every piece into a full host array per entry. Each piece's
   bytes are CRC-verified by the ``.params`` v3 container on load, the
   file set by the commit manifest before this reader runs. Coverage is
   proven: missing or overlapping pieces raise a structured error
   naming the entry — a half-assembled tensor can never be placed.
2. **place** — re-drop each global array onto the *current* sharding
   via ``jax.make_array_from_callback``: only the shards this process
   addresses are materialized on device, for any N_new (scale-down and
   scale-up alike).

Memory note: assembly materializes one full copy of the tree on the
host (the price of changing topology); the same-topology fast path
keeps its one-host-share bound. The elastic driver uses this lane only
inside a resize.
"""
from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np

from ..base import MXNetError
from ..diagnostics.journal import get_journal
from ..parallel import _ckpt

__all__ = ["assemble_entries", "place_global", "place_named",
           "read_global_entries", "reshard_report"]


def _parse_idx(ik):
    """``"a:b,c:d"`` -> ((a, b), (c, d)); scalar entries have key ""."""
    if not ik:
        return ()
    out = []
    for part in ik.split(","):
        a, b = part.split(":")
        out.append((int(a), int(b)))
    return tuple(out)


def assemble_entries(pieces):
    """``{name: {idxkey: np.ndarray}}`` -> ``{name: np.ndarray}`` full
    host arrays. The global extent of each dim is the max piece stop;
    coverage must be exact (no gaps, no overlaps)."""
    out = {}
    for name, per in pieces.items():
        parsed = [(_parse_idx(ik), arr) for ik, arr in per.items()]
        ndim = len(parsed[0][0])
        if any(len(idx) != ndim for idx, _ in parsed):
            raise MXNetError(f"reshard: {name!r} pieces disagree on rank")
        if ndim == 0:
            out[name] = np.asarray(parsed[0][1]).reshape(())
            continue
        shape = tuple(max(stop for idx, _ in parsed
                          for lo, stop in [idx[d]])
                      for d in range(ndim))
        dtype = np.asarray(parsed[0][1]).dtype
        full = np.empty(shape, dtype)
        covered = 0
        for idx, arr in parsed:
            arr = np.asarray(arr)
            want = tuple(stop - lo for lo, stop in idx)
            if tuple(arr.shape) != want:
                raise MXNetError(
                    f"reshard: {name!r} piece {idx} is shaped "
                    f"{tuple(arr.shape)}, index says {want} — torn or "
                    "mislabeled shard file")
            if arr.dtype != dtype:
                raise MXNetError(f"reshard: {name!r} pieces disagree on "
                                 f"dtype ({arr.dtype} vs {dtype})")
            full[tuple(slice(lo, stop) for lo, stop in idx)] = arr
            covered += arr.size
        if covered != full.size:
            raise MXNetError(
                f"reshard: {name!r} pieces cover {covered} of "
                f"{full.size} elements — the shard set is incomplete "
                "(or overlapping); refusing a partial tensor")
        out[name] = full
    return out


def read_global_entries(fname):
    """(meta, {name: full np.ndarray}) from a sharded-trainer checkpoint
    file — full-file or per-shard, any writer topology."""
    from .. import ndarray as nd
    meta, loaded = _ckpt.read_meta(fname)
    if not meta["per_shard"]:
        return meta, {k: v.asnumpy() for k, v in loaded.items()
                      if k != "__meta__"}
    n_files = int(meta.get("shard_files", 1))
    pieces = {}
    for rank in range(n_files):
        path = f"{fname}.shard{rank}"
        if not os.path.exists(path):
            raise MXNetError(
                f"reshard: per-shard checkpoint incomplete: {path} "
                f"missing (meta says {n_files} shard files)")
        loaded = nd.load(path)
        if not isinstance(loaded, dict):
            # an EMPTY shard container (zero-state optimizer, or a
            # round-robin split that left this rank no pieces) loads as
            # a list — there is just nothing to collect from it
            continue
        for key, arr in loaded.items():
            name, ik = key.rsplit("|", 1)
            prev = pieces.setdefault(name, {})
            if ik not in prev:          # replicas collapse, as on save
                prev[ik] = arr.asnumpy()
    return meta, assemble_entries(pieces)


def place_global(name, cur, host):
    """Drop a full host array onto ``cur``'s exact sharding (shape and
    dtype validated) — only this process's addressable shards touch a
    device."""
    cur = jnp.asarray(cur)
    host = np.asarray(host)
    if tuple(host.shape) != tuple(cur.shape) or \
            jnp.dtype(host.dtype) != cur.dtype:
        raise MXNetError(
            f"reshard: checkpoint entry {name!r} is "
            f"{host.dtype}{tuple(host.shape)}, expected "
            f"{cur.dtype}{tuple(cur.shape)} — architecture or "
            "master_dtype mismatch")
    return jax.make_array_from_callback(cur.shape, cur.sharding,
                                        lambda idx: host[idx])


def place_named(name, mesh, spec, host):
    """Drop a full host array onto ``NamedSharding(mesh, spec)`` — the
    INITIAL placement twin of :func:`place_global` (which needs a live
    array to copy the sharding from).  Same contract: only this
    process's addressable shards touch a device.  The serving shard
    planner (serving/shardplan.py) uses this to land checkpoint weights
    straight onto the serving mesh, exactly how elastic restore places
    assembled entries."""
    from jax.sharding import NamedSharding
    host = np.asarray(host)
    sharding = NamedSharding(mesh, spec)
    try:
        return jax.make_array_from_callback(host.shape, sharding,
                                            lambda idx: host[idx])
    except ValueError as e:
        raise MXNetError(
            f"reshard: entry {name!r} {host.dtype}{tuple(host.shape)} "
            f"cannot be placed as {spec} on mesh "
            f"{dict(zip(mesh.axis_names, mesh.devices.shape))}: {e}") \
            from None


def journal_reshard(root, step, meta, n_new, entries, consumer):
    """One ``reshard_restore`` record per topology-changing restore —
    the journal evidence the chaos tests and ``doctor --journal``
    correlate with ``rank_lost``/``cohort_resize``."""
    n_old = int(meta.get("shard_files", 1)) if meta.get("per_shard") \
        else 1
    get_journal().event(
        "reshard_restore", root=root, step=int(step), n_old=n_old,
        n_new=int(n_new), entries=len(entries),
        bytes=int(sum(np.asarray(v).nbytes for v in entries.values())),
        consumer=consumer)


def reshard_report(fname):
    """Doctor-grade dry run: what would assemble from this checkpoint
    file (entry count, shard files, bytes) without touching a device."""
    meta, entries = read_global_entries(fname)
    return {"per_shard": bool(meta.get("per_shard")),
            "shard_files": int(meta.get("shard_files", 1)),
            "entries": len(entries),
            "bytes": int(sum(v.nbytes for v in entries.values()))}

"""Cohort membership — heartbeat liveness, epoch ledger, deadline barriers.

The multi-host substrate (``jax.distributed`` + GSPMD collectives) is
static: a lost rank turns every subsequent collective into an unbounded
hang, and the only recovery the launcher offers is killing the whole job
(``tools/launch.py --max-restarts``). This module is the elastic tier's
control plane: a *file-based* cohort ledger on a filesystem every rank
shares (the same property the checkpoint commit protocol already
assumes), giving survivors three things a wedged collective cannot:

1. **liveness** — every rank's daemon heartbeat bumps a monotonic
   sequence number in ``hb/rank-<r>.json``; an observer declares a rank
   lost when its *sequence* stops advancing for ``deadline_s`` of the
   observer's own monotonic clock. No cross-host wall-clock comparison
   (NTP steps poison those — the G11 lesson), no coordination-service
   timeout that kills the observer too.
2. **deadline-bounded barriers** — every wait is a poll loop with a hard
   deadline that re-checks liveness as it waits: a dead member surfaces
   as a structured :class:`RankLost` *before* the deadline, never a hang.
3. **an epoch ledger** — cohort shape is decided ONCE per change, by the
   leader (lowest surviving rank), as an atomically-published
   ``epoch/epoch-<k>.json`` record that every member adopts. Membership
   is therefore rank-uniform by construction — the PR-5 lesson that a
   rank-local decision about whether to enter a collective is itself a
   deadlock applies doubly to a decision about who IS in the collective.

Barrier paths embed the epoch, so a rebuilt cohort can never consume a
dead generation's barrier litter. Import-light: stdlib + the journal +
``resilience.atomic`` (whose fault hook also makes the ledger writable
by the chaos harness). No jax — liveness must keep working while the
data plane is wedged.

Knobs (docs/elastic.md): ``MXNET_TPU_ELASTIC_HEARTBEAT_S`` (default 2),
``MXNET_TPU_ELASTIC_DEADLINE_S`` (default 20),
``MXNET_TPU_ELASTIC_BARRIER_S`` (default 120),
``MXNET_TPU_ELASTIC_POLL_S`` (default 0.05).
"""
from __future__ import annotations

import json
import os
import shutil
import threading
import time

from ..base import MXNetError
from ..diagnostics.journal import get_journal
from ..resilience import atomic

__all__ = ["BarrierTimeout", "Cohort", "CohortConfig", "Heartbeat",
           "LivenessReader", "RankLost"]

HEARTBEAT_S = 2.0
DEADLINE_S = 20.0
BARRIER_S = 120.0
POLL_S = 0.05


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(name)
    try:
        return float(v) if v else default
    except ValueError:
        return default


class RankLost(MXNetError):
    """A cohort member stopped heartbeating: raised *instead of* entering
    (or staying in) a collective wait. Carries the evidence so the
    elastic driver can resize without re-deriving it."""

    def __init__(self, lost, survivors, epoch, where=""):
        self.lost = sorted(int(r) for r in lost)
        self.survivors = sorted(int(r) for r in survivors)
        self.epoch = int(epoch)
        self.where = where
        super().__init__(
            f"rank(s) {self.lost} lost (epoch {self.epoch}"
            + (f", at {where}" if where else "")
            + f"); survivors {self.survivors}")


class BarrierTimeout(MXNetError):
    """A cohort barrier expired with every missing member still
    heartbeating — a stall, not a death; the caller's retry/abort
    decision, not a resize trigger."""

    def __init__(self, tag, waiting_for, deadline_s):
        self.tag = tag
        self.waiting_for = sorted(int(r) for r in waiting_for)
        super().__init__(
            f"cohort barrier {tag!r} expired after {deadline_s:g}s still "
            f"waiting for live rank(s) {self.waiting_for}")


class CohortConfig:
    """Resolved knobs; explicit arguments beat the environment."""

    def __init__(self, heartbeat_s=None, deadline_s=None, barrier_s=None,
                 poll_s=None):
        self.heartbeat_s = (float(heartbeat_s) if heartbeat_s is not None
                            else _env_float("MXNET_TPU_ELASTIC_HEARTBEAT_S",
                                            HEARTBEAT_S))
        self.deadline_s = (float(deadline_s) if deadline_s is not None
                           else _env_float("MXNET_TPU_ELASTIC_DEADLINE_S",
                                           DEADLINE_S))
        self.barrier_s = (float(barrier_s) if barrier_s is not None
                          else _env_float("MXNET_TPU_ELASTIC_BARRIER_S",
                                          BARRIER_S))
        self.poll_s = (float(poll_s) if poll_s is not None
                       else _env_float("MXNET_TPU_ELASTIC_POLL_S", POLL_S))
        if self.deadline_s <= self.heartbeat_s:
            raise MXNetError(
                f"elastic deadline_s ({self.deadline_s:g}) must exceed "
                f"heartbeat_s ({self.heartbeat_s:g}) — a deadline inside "
                "one heartbeat interval declares healthy ranks dead")


class Heartbeat:
    """Seq-file heartbeat daemon for ONE member of any cohort-shaped
    group. Training ranks (:class:`Cohort`) and serving replicas
    (``serving.pool``) share this writer: bump a monotonic sequence in
    ``<hb_dir>/<prefix>-<id>.json`` every ``interval_s``, merging the
    optional ``payload()`` dict into each record — the slot a serving
    replica's readiness beacon (queue depth, last-batch age, commit
    step, bound port) rides. Liveness semantics live entirely in
    :class:`LivenessReader`; the payload is advisory state for whoever
    reads the ledger. Written via ``resilience.atomic`` (so the chaos
    harness reaches it — torn-heartbeat injection included) but NOT
    fsynced: a heartbeat is ephemeral evidence, not durable state. A
    transient write failure is swallowed — heartbeating must never kill
    the member it reports on."""

    def __init__(self, hb_dir, member, interval_s, payload=None,
                 prefix="rank"):
        self.hb_dir = str(hb_dir)
        self.member = member
        self.interval_s = float(interval_s)
        self.payload = payload
        self.prefix = prefix
        os.makedirs(self.hb_dir, exist_ok=True)
        self._seq = 0
        self._stop = threading.Event()
        self._thread = None
        # beat() is called by the daemon AND by lifecycle code that
        # wants a state change published immediately (a draining
        # replica).  The lock covers only in-memory state (seq bump,
        # dirty/writer flags) — the ledger write runs OUTSIDE it (G15:
        # no file I/O under a lock).  Publish order still matches beat
        # order because exactly ONE writer is in flight at a time: a
        # beat arriving mid-write marks _dirty and returns, and the
        # in-flight writer loops, re-sampling the payload until the
        # flag stays clear — so the last write always reflects a sample
        # taken at-or-after the last beat() (a racing stale daemon
        # sample can never overwrite a lifecycle not-ready flip)
        self._beat_lock = threading.Lock()
        self._dirty = False
        self._writing = False

    @property
    def path(self) -> str:
        return os.path.join(self.hb_dir,
                            f"{self.prefix}-{self.member}.json")

    def beat(self) -> None:
        """Write one heartbeat now (the daemon calls this on a timer;
        lifecycle code calls it to publish a payload change at once).
        When another thread's write is in flight this returns after
        marking the state dirty — the in-flight writer re-samples and
        republishes, so the caller's change still lands promptly and
        never loses to a stale concurrent sample."""
        with self._beat_lock:
            self._seq += 1
            self._dirty = True
            if self._writing:
                return        # the in-flight writer republishes for us
            self._writing = True
        try:
            while True:
                with self._beat_lock:
                    if not self._dirty:
                        # exit decision + flag clear are ONE critical
                        # section: a beat() landing after this release
                        # sees _writing False and writes itself
                        self._writing = False
                        return
                    self._dirty = False
                    doc = {"member": self.member, "pid": os.getpid(),
                           "seq": self._seq}
                if self.payload is not None:
                    try:
                        doc.update(self.payload())
                    except Exception as e:   # liveness must outlive a
                        doc["payload_error"] = type(e).__name__  # broken
                try:                                          # provider
                    with atomic.atomic_write(self.path, "w",
                                             durable=False) as f:
                        json.dump(doc, f)
                except OSError:
                    pass     # a transient hb write failure must not
        except BaseException:                             # kill us
            with self._beat_lock:     # next beat() becomes the writer
                self._writing = False
            raise

    def start(self) -> "Heartbeat":
        if self._thread is not None:
            return self
        self._stop.clear()
        self.beat()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"mxtpu-hb-{self.prefix}-{self.member}")
        self._thread.start()
        return self

    def _run(self):
        while not self._stop.wait(self.interval_s):
            self.beat()

    def stop(self, resign=False) -> None:
        """Stop heartbeating. ``resign=True`` additionally removes the
        seq file — a graceful leave observers see as loss at their next
        liveness check."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval_s + 1.0)
            self._thread = None
        if resign:
            try:
                os.unlink(self.path)
            except OSError:
                pass


class LivenessReader:
    """Per-member (seq, first-seen-monotonic) tracking over a directory
    of :class:`Heartbeat` seq files. A member is alive while its
    heartbeat sequence keeps advancing; staleness is measured on the
    OBSERVER's monotonic clock from the moment the current seq was
    first observed. A torn/unparsable seq file reads as "no heartbeat"
    — the grace clock runs until a whole record lands, so a wedged or
    half-written beacon degrades to loss, never to a reader crash."""

    def __init__(self, hb_dir, deadline_s, prefix="rank"):
        self.hb_dir = hb_dir
        self.deadline_s = deadline_s
        self.prefix = prefix
        self._seen = {}          # member -> (seq, mono_first_seen)
        self._docs = {}          # member -> last well-formed record

    def _read(self, member):
        try:
            with open(os.path.join(self.hb_dir,
                                   f"{self.prefix}-{member}.json"),
                      encoding="utf-8") as f:
                doc = json.load(f)
            seq = int(doc.get("seq", -1))
        except FileNotFoundError:
            # resigned (graceful leave unlinks the file): there is no
            # beacon to trust anymore — a stale payload must not keep
            # advertising a dead member's port/readiness
            self._docs.pop(member, None)
            return None
        except (OSError, ValueError):
            return None      # torn/unreadable: keep the stale payload
        self._docs[member] = doc
        return seq

    def payload(self, member):
        """The last well-formed heartbeat record observed for
        ``member`` (refreshed by :meth:`observe`), or None before one
        lands — the serving pool reads its readiness beacon here."""
        return self._docs.get(member)

    def members(self) -> list:
        """Member ids with a seq file on the ledger (sorted; numeric ids
        sort numerically)."""
        out = []
        try:
            names = os.listdir(self.hb_dir)
        except OSError:
            return out
        head = f"{self.prefix}-"
        for name in names:
            if name.startswith(head) and name.endswith(".json"):
                raw = name[len(head):-len(".json")]
                out.append(int(raw) if raw.isdigit() else raw)
        # numeric ids sort numerically (2 before 10), strings after
        return sorted(out, key=lambda m: (isinstance(m, str), m))

    def observe(self, member):
        """Refresh this member's record; returns its idle seconds
        (observer clock), or None if it has never heartbeated at all."""
        seq = self._read(member)
        now = time.monotonic()
        if seq is None:
            # no (whole) file yet: start (or keep) the grace clock so a
            # member that never comes up is eventually declared lost,
            # not waited on forever
            prev = self._seen.get(member)
            if prev is None or prev[0] is not None:
                self._seen[member] = (None, now)
                return 0.0
            return now - prev[1]
        prev = self._seen.get(member)
        if prev is None or prev[0] != seq:
            self._seen[member] = (seq, now)
            return 0.0
        return now - prev[1]

    def alive(self, member) -> bool:
        idle = self.observe(member)
        return idle is not None and idle <= self.deadline_s


_Liveness = LivenessReader         # pre-generalization internal name


class Cohort:
    """One rank's handle on the shared cohort ledger under ``root``.

    Lifecycle::

        cohort = Cohort(root, rank=r, config=cfg).start()
        members = cohort.form(world)        # epoch 0, all ranks
        ...
        lost = cohort.check()               # cheap, non-blocking
        cohort.barrier("step-100")          # deadline-bounded sync
        members = cohort.resize(lost)       # leader publishes epoch k+1
        cohort.stop()

    Every blocking wait is deadline-bounded and converts a dead member
    into :class:`RankLost`. Membership decisions come only from the
    epoch ledger, so every member adopts the same cohort shape.
    """

    def __init__(self, root, rank, config=None, journal=None):
        self.root = str(root)
        self.rank = int(rank)
        self.cfg = config or CohortConfig()
        self._journal = journal if journal is not None else get_journal()
        self.hb_dir = os.path.join(self.root, "hb")
        self.epoch_dir = os.path.join(self.root, "epoch")
        self.barrier_dir = os.path.join(self.root, "barrier")
        self.join_dir = os.path.join(self.root, "join")
        for d in (self.hb_dir, self.epoch_dir, self.barrier_dir,
                  self.join_dir):
            os.makedirs(d, exist_ok=True)
        self._live = LivenessReader(self.hb_dir, self.cfg.deadline_s)
        # the generic seq-file writer, with the training-rank payload in
        # the (serving-pool-shared) heartbeat payload slot
        self._hb = Heartbeat(self.hb_dir, self.rank, self.cfg.heartbeat_s,
                             payload=lambda: {"rank": self.rank})
        # per-(epoch, tag) use counter: cohort calls are SPMD (every
        # member runs the same sequence), so the n-th barrier at a tag on
        # one rank pairs with the n-th on every other — a stale file from
        # use n-1 can then never satisfy use n
        self._barrier_counts = {}

    # -- heartbeats ----------------------------------------------------------
    def beat(self) -> None:
        """Write one heartbeat now (the daemon calls this on a timer; an
        rng-less single-threaded test can drive it by hand)."""
        self._hb.beat()

    def start(self) -> "Cohort":
        self._hb.start()
        return self

    def stop(self, resign=False) -> None:
        """Stop heartbeating. ``resign=True`` additionally removes the
        heartbeat file — a graceful leave that peers see as loss at the
        next liveness check (the resize path is the same either way)."""
        self._hb.stop(resign=resign)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- epoch ledger --------------------------------------------------------
    def _epoch_path(self, k):
        return os.path.join(self.epoch_dir, f"epoch-{int(k):06d}.json")

    def read_epoch(self):
        """(epoch, members) from the newest well-formed epoch record, or
        (None, None) before formation. A torn/unparsable newest record is
        skipped (atomic_write makes that near-impossible, but a reader
        must never wedge on half a ledger)."""
        doc = self.read_epoch_doc()
        if doc is None:
            return None, None
        return int(doc["epoch"]), [int(r) for r in doc["members"]]

    def read_epoch_doc(self):
        """The newest well-formed epoch record as a dict (or None):
        beyond (epoch, members) it carries the writer's provenance —
        ``written_by``, ``reason``, and for a resize the leader's
        ``recovery_trace`` id that every survivor's ``elastic_recover``
        span adopts (docs/elastic.md, docs/observability.md)."""
        try:
            names = sorted(os.listdir(self.epoch_dir), reverse=True)
        except OSError:
            return None
        for name in names:
            if not name.startswith("epoch-") or not name.endswith(".json"):
                continue
            try:
                with open(os.path.join(self.epoch_dir, name),
                          encoding="utf-8") as f:
                    doc = json.load(f)
                int(doc["epoch"])
                [int(r) for r in doc["members"]]
                return doc
            except (OSError, ValueError, KeyError, TypeError):
                continue
        return None

    def _write_epoch(self, k, members, reason):
        doc = {"epoch": int(k), "members": sorted(int(r) for r in members),
               "written_by": self.rank, "reason": reason}
        # the leader stamps its active trace into the ledger record so
        # every adopter can join its recovery trace — the ledger is the
        # one channel all survivors already read.  Lazy import: this
        # module stays import-light; observability.trace is stdlib-only
        # and current_ids() is {} with tracing off (schema unchanged)
        from ..observability import trace as _trace
        ids = _trace.current_ids()
        if ids.get("trace_id"):
            doc["recovery_trace"] = ids["trace_id"]
        with atomic.atomic_write(self._epoch_path(k), "w") as f:
            json.dump(doc, f)
        return doc

    def members(self):
        """Current cohort membership (from the ledger)."""
        _, members = self.read_epoch()
        if members is None:
            raise MXNetError(f"cohort under {self.root!r} not formed yet "
                             "(no epoch record) — call form()")
        return members

    @property
    def epoch(self):
        k, _ = self.read_epoch()
        return -1 if k is None else k

    def is_leader(self, members=None):
        """Leader = the lowest-ranked member I still observe alive
        (liveness-filtered so a dead rank 0 cannot stall every
        leadership duty; ties can't happen — ranks are unique)."""
        members = self.members() if members is None else members
        alive = [r for r in members if r == self.rank or
                 self._live.alive(r)]
        return bool(alive) and min(alive) == self.rank

    def form(self, world, deadline_s=None) -> list:
        """Form epoch 0 over ranks ``0..world-1``: rank 0 publishes the
        record, everyone waits for it (deadline-bounded) and barriers so
        no member races ahead before the cohort exists."""
        if self.rank == 0 and self.read_epoch()[0] is None:
            self._write_epoch(0, range(int(world)), "form")
            self._journal.event("cohort_form", root=self.root,
                                world=int(world))
        deadline = deadline_s if deadline_s is not None else \
            self.cfg.barrier_s
        t0 = time.monotonic()
        while self.read_epoch()[0] is None:
            if time.monotonic() - t0 > deadline:
                raise BarrierTimeout("form", [0], deadline)
            time.sleep(self.cfg.poll_s)
        members = self.members()
        self.barrier("form", deadline_s=deadline, members=members)
        return members

    # -- liveness ------------------------------------------------------------
    def check(self, members=None) -> list:
        """Non-blocking liveness sweep: the members of the current epoch
        (minus me) whose heartbeats have gone stale. Cheap enough to run
        every training step."""
        members = self.members() if members is None else members
        return [r for r in members
                if r != self.rank and not self._live.alive(r)]

    def ensure_members(self, where="") -> list:
        """Raise :class:`RankLost` if any cohort member is dead — the
        guard a caller runs BEFORE entering a data-plane collective
        (graftlint G12's dynamic twin)."""
        members = self.members()
        lost = self.check(members)
        if lost:
            raise RankLost(lost, [r for r in members if r not in lost],
                           self.epoch, where=where)
        return members

    # -- barriers ------------------------------------------------------------
    def barrier(self, tag, deadline_s=None, members=None) -> None:
        """Deadline-bounded cohort barrier for the current epoch: every
        member drops ``barrier/e<k>-<tag>/rank-<r>``; the wait re-checks
        liveness, so a member dying inside the barrier raises
        :class:`RankLost` (with survivors) instead of hanging, and a
        stall past the deadline raises :class:`BarrierTimeout`."""
        epoch = self.epoch
        members = self.members() if members is None else members
        deadline = deadline_s if deadline_s is not None else \
            self.cfg.barrier_s
        d = os.path.join(self.barrier_dir, f"e{epoch:06d}-{tag}")
        os.makedirs(d, exist_ok=True)
        count = self._barrier_counts.get((epoch, tag), 0) + 1
        self._barrier_counts[(epoch, tag)] = count
        my = os.path.join(d, f"rank-{self.rank}")
        with atomic.atomic_write(my, "w") as f:
            f.write(str(count))

        def _arrived(r):
            try:
                with open(os.path.join(d, f"rank-{r}"),
                          encoding="utf-8") as f:
                    return int(f.read().strip()) >= count
            except (OSError, ValueError):
                return False

        t0 = time.monotonic()
        while True:
            waiting = [r for r in members if not _arrived(r)]
            if not waiting:
                return
            dead = [r for r in waiting if r != self.rank
                    and not self._live.alive(r)]
            if dead:
                raise RankLost(dead, [r for r in members if r not in dead],
                               epoch, where=f"barrier:{tag}")
            if time.monotonic() - t0 > deadline:
                raise BarrierTimeout(tag, waiting, deadline)
            time.sleep(self.cfg.poll_s)

    # -- resize / join -------------------------------------------------------
    def pending_joiners(self) -> list:
        """Ranks with a join request AND a live heartbeat (a join file
        from a process that died before admission must not be adopted
        into the new epoch)."""
        out = []
        try:
            names = os.listdir(self.join_dir)
        except OSError:
            return out
        for name in names:
            if not name.startswith("rank-"):
                continue
            try:
                r = int(name[len("rank-"):].split(".")[0])
            except ValueError:
                continue
            if self._live.alive(r):
                out.append(r)
        return sorted(out)

    def resize(self, lost=(), deadline_s=None) -> list:
        """Publish (leader) or adopt (everyone else) the next epoch:
        members = current survivors − ``lost`` + live pending joiners.
        Exactly one writer — the lowest *surviving* rank — so the
        decision is made once and shared; every member returns the SAME
        new member list. Admitted joiners' request files are consumed."""
        old_epoch, old_members = self.read_epoch()
        if old_members is None:
            raise MXNetError("resize before form(): no epoch record")
        lost = set(int(r) for r in lost) | set(self.check(old_members))
        survivors = [r for r in old_members if r not in lost]
        if self.rank not in survivors:
            raise MXNetError(f"rank {self.rank} is not a survivor of "
                             f"epoch {old_epoch} — rejoin with join()")
        joiners = [r for r in self.pending_joiners()
                   if r not in survivors]
        new_members = sorted(survivors + joiners)
        if min(survivors) == self.rank:
            self._write_epoch(old_epoch + 1, new_members, "resize")
            for r in joiners:
                try:
                    os.unlink(os.path.join(self.join_dir, f"rank-{r}"))
                except OSError:
                    pass
            self._sweep_dead_epochs(old_epoch)
            self._journal.event(
                "cohort_resize", root=self.root, epoch=old_epoch + 1,
                old_members=sorted(old_members), members=new_members,
                lost=sorted(lost), joined=joiners)
        deadline = deadline_s if deadline_s is not None else \
            self.cfg.barrier_s
        t0 = time.monotonic()
        while True:
            k, members = self.read_epoch()
            if k is not None and k > old_epoch:
                break
            if time.monotonic() - t0 > deadline:
                raise BarrierTimeout("resize", [min(survivors)], deadline)
            time.sleep(self.cfg.poll_s)
        # sync the SURVIVORS (the SPMD participants of this call) only:
        # joiners are admitted through the ledger and synchronize at
        # their join() wait, not here
        self.barrier("resize", deadline_s=deadline, members=survivors)
        return members

    def _sweep_dead_epochs(self, newest_dead) -> None:
        """Leader-side GC at resize: barrier/collective litter of epochs
        ``<= newest_dead - 1`` can never be read again (the new epoch's
        paths embed the new k; the just-ended epoch's dirs are left one
        generation as a race margin). Best-effort — litter must never
        fail a resize."""
        for parent in (self.barrier_dir,
                       os.path.join(self.root, "coll")):
            try:
                names = os.listdir(parent)
            except OSError:
                continue
            for name in names:
                if not name.startswith("e"):
                    continue
                try:
                    k = int(name[1:7])
                except ValueError:
                    continue
                if k < newest_dead:
                    shutil.rmtree(os.path.join(parent, name),
                                  ignore_errors=True)

    def join(self, deadline_s=None) -> list:
        """Scale-up entry for a NEW rank: heartbeat + a join request,
        then wait (deadline-bounded) for an epoch that includes me —
        published by the leader at its next resize."""
        self.start()
        with atomic.atomic_write(
                os.path.join(self.join_dir, f"rank-{self.rank}"),
                "w") as f:
            f.write(str(os.getpid()))
        deadline = deadline_s if deadline_s is not None else \
            self.cfg.barrier_s
        t0 = time.monotonic()
        while True:
            _, members = self.read_epoch()
            if members is not None and self.rank in members:
                self._journal.event("cohort_join", root=self.root,
                                    rank=self.rank, epoch=self.epoch)
                return members
            if time.monotonic() - t0 > deadline:
                raise BarrierTimeout("join", [self.rank], deadline)
            time.sleep(self.cfg.poll_s)

"""Survivor-safe cohort collectives — deadline-bounded, ledger-backed.

The GSPMD data plane's collectives (psum inside the compiled step,
``multihost_utils`` on the host) are *unbounded* waits: one dead rank
wedges every peer until an external timeout kills the job. The elastic
tier cannot use them across cohort boundaries, so the operations the
control plane itself needs — broadcast a small decision, reduce a
parameter tree at a sync point — ride the same shared-filesystem ledger
as membership, with the same contract: every wait has a deadline and
re-checks liveness, so a dead member surfaces as :class:`RankLost`
(from :mod:`.membership`), never a hang.

Pattern (one round-trip per op, leader-reduced)::

    coll/e<epoch>-<tag>-<n>/rank-<r>.npz    every member's contribution
    coll/e<epoch>-<tag>-<n>/result.npz      leader's published result

``<n>`` is the per-(epoch, tag) use counter (SPMD call sequences, as in
``Cohort.barrier``), so repeated sync points never read a predecessor's
files. Contribution and result files land via ``nd.save``-grade
atomicity (``resilience.atomic``), so a reader can only ever see a
complete payload. These ops move small trees (decisions, periodic
parameter syncs) over the shared FS; the per-step gradient path stays
GSPMD/ICI — this is the recovery lane, not the fast lane.
"""
from __future__ import annotations

import io
import json
import os
import shutil
import time

import numpy as np

from ..base import MXNetError
from ..resilience import atomic
from .membership import BarrierTimeout, RankLost

__all__ = ["allreduce_mean", "broadcast", "broadcast_json"]


def _op_dir(cohort, tag):
    # use counter lives ON the cohort handle (one handle per rank), not
    # at module scope: two ranks of one test process must not share it
    epoch = cohort.epoch
    counts = getattr(cohort, "_coll_counts", None)
    if counts is None:
        counts = cohort._coll_counts = {}
    n = counts.get((epoch, tag), 0) + 1
    counts[(epoch, tag)] = n
    d = os.path.join(cohort.root, "coll", f"e{epoch:06d}-{tag}-{n:04d}")
    os.makedirs(d, exist_ok=True)
    if n > 2:
        # GC two-behind: a member only contributes to op n after
        # completing n-1, and n-1's result only publishes once every
        # member contributed — so when ANY member starts n, ALL have
        # finished n-2. Without this, each sync point leaves world+1
        # full-tree .npz copies on the shared FS forever.
        shutil.rmtree(os.path.join(
            cohort.root, "coll", f"e{epoch:06d}-{tag}-{n - 2:04d}"),
            ignore_errors=True)
    return d, epoch


def _write_npz(path, arrays):
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    with atomic.atomic_write(path, "wb") as f:
        f.write(buf.getvalue())


def _read_npz(path):
    with open(path, "rb") as f:
        data = f.read()
    with np.load(io.BytesIO(data)) as z:
        return {k: z[k] for k in z.files}


def _wait_for(cohort, path, owner_ranks, epoch, deadline, what):
    """Poll for ``path``; a dead owner raises RankLost, a live stall
    raises BarrierTimeout. Never an unbounded wait."""
    t0 = time.monotonic()
    while not os.path.exists(path):
        dead = [r for r in owner_ranks if r != cohort.rank
                and not cohort._live.alive(r)]
        if dead:
            members = cohort.members()
            raise RankLost(dead, [r for r in members if r not in dead],
                           epoch, where=what)
        if time.monotonic() - t0 > deadline:
            raise BarrierTimeout(what, owner_ranks, deadline)
        time.sleep(cohort.cfg.poll_s)


def allreduce_mean(cohort, tag, arrays, deadline_s=None):
    """Element-wise mean of ``{name: np.ndarray}`` across the cohort.

    Every member contributes; the leader (lowest member rank) reduces in
    float64 and publishes; everyone returns the identical result dict
    (cast back to each input's dtype). Raises :class:`RankLost` if a
    member dies mid-operation."""
    members = cohort.ensure_members(where=f"allreduce:{tag}")
    deadline = deadline_s if deadline_s is not None else \
        cohort.cfg.barrier_s
    d, epoch = _op_dir(cohort, tag)
    arrays = {k: np.asarray(v) for k, v in arrays.items()}
    _write_npz(os.path.join(d, f"rank-{cohort.rank}.npz"), arrays)
    result_path = os.path.join(d, "result.npz")
    leader = min(members)
    if cohort.rank == leader:
        acc = None
        for r in members:
            p = os.path.join(d, f"rank-{r}.npz")
            _wait_for(cohort, p, [r], epoch, deadline,
                      f"allreduce:{tag}:contrib")
            contrib = _read_npz(p)
            if set(contrib) != set(arrays):
                raise MXNetError(
                    f"allreduce {tag!r}: rank {r} contributed keys "
                    f"{sorted(contrib)} != {sorted(arrays)} — the cohort "
                    "diverged structurally")
            if acc is None:
                acc = {k: v.astype(np.float64) for k, v in contrib.items()}
            else:
                for k, v in contrib.items():
                    acc[k] += v
        out = {k: (acc[k] / len(members)).astype(arrays[k].dtype)
               for k in acc}
        _write_npz(result_path, out)
    else:
        _wait_for(cohort, result_path, [leader], epoch, deadline,
                  f"allreduce:{tag}:result")
        out = _read_npz(result_path)
    return out


def broadcast(cohort, tag, arrays=None, deadline_s=None):
    """Leader's ``{name: np.ndarray}`` adopted by every member. Pass
    ``arrays`` on the leader; other ranks' argument is ignored."""
    members = cohort.ensure_members(where=f"broadcast:{tag}")
    deadline = deadline_s if deadline_s is not None else \
        cohort.cfg.barrier_s
    d, epoch = _op_dir(cohort, tag)
    leader = min(members)
    result_path = os.path.join(d, "result.npz")
    if cohort.rank == leader:
        if arrays is None:
            raise MXNetError(f"broadcast {tag!r}: leader has no payload")
        _write_npz(result_path, {k: np.asarray(v)
                                 for k, v in arrays.items()})
        return {k: np.asarray(v) for k, v in arrays.items()}
    _wait_for(cohort, result_path, [leader], epoch, deadline,
              f"broadcast:{tag}")
    return _read_npz(result_path)


def broadcast_json(cohort, tag, doc=None, deadline_s=None):
    """Leader's small JSON document adopted by every member — the
    rank-uniform decision primitive (which step validated, which step to
    restore): decided once, published once, adopted everywhere."""
    members = cohort.ensure_members(where=f"bcast_json:{tag}")
    deadline = deadline_s if deadline_s is not None else \
        cohort.cfg.barrier_s
    d, epoch = _op_dir(cohort, tag)
    leader = min(members)
    path = os.path.join(d, "doc.json")
    if cohort.rank == leader:
        with atomic.atomic_write(path, "w") as f:
            json.dump(doc, f)
        return doc
    _wait_for(cohort, path, [leader], epoch, deadline,
              f"bcast_json:{tag}")
    with open(path, encoding="utf-8") as f:
        return json.load(f)

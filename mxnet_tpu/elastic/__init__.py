"""mxnet_tpu.elastic — elastic multi-host training (docs/elastic.md).

The resilience tier (PR 3) and guardrails (PR 5) made a *fixed* cohort
crash-safe; this package lets the cohort change shape mid-run. Four
pieces:

* :mod:`.membership` — heartbeat liveness, the epoch ledger, and
  deadline-bounded barriers: a dead rank surfaces as a structured
  :class:`RankLost` instead of a hung collective, and every membership
  decision is published once by the leader and adopted by all (the
  rank-uniform contract graftlint G12 enforces statically).
* :mod:`.collective` — survivor-safe recovery-lane collectives over the
  shared filesystem (broadcast a decision, mean-reduce a state tree);
  every wait is deadline-bounded and liveness-checked.
* :mod:`.reshard` — the topology-free checkpoint reader: assemble the
  global tree from the N_old shard files a different cohort wrote,
  prove coverage, and re-place onto the N_new mesh.
* :mod:`.driver` — the run loop: detect → quiesce → resize → rebuild →
  resharded restore → resume, bounded retries, one trace span per
  recovery so ``rank_lost``/``cohort_resize``/``reshard_restore``
  journal records correlate.

Lazy exports (PEP 562): importing the package — or its stdlib-only
submodules ``membership``/``report`` — touches no jax, so the doctor
can summarize cohort events from a journal while the backend is
wedged.
"""
from __future__ import annotations

import importlib

__all__ = ["BarrierTimeout", "Cohort", "CohortConfig", "CohortGroup",
           "ElasticDriver", "ElasticExhausted", "Heartbeat",
           "LivenessReader", "RankLost", "allreduce_mean",
           "assemble_entries", "broadcast", "broadcast_json",
           "elastic_metadata", "elastic_report", "place_global",
           "place_named", "read_global_entries", "reshard_report"]

_LAZY = {
    "BarrierTimeout": ("membership", "BarrierTimeout"),
    "Cohort": ("membership", "Cohort"),
    "CohortConfig": ("membership", "CohortConfig"),
    "Heartbeat": ("membership", "Heartbeat"),
    "LivenessReader": ("membership", "LivenessReader"),
    "RankLost": ("membership", "RankLost"),
    "allreduce_mean": ("collective", "allreduce_mean"),
    "broadcast": ("collective", "broadcast"),
    "broadcast_json": ("collective", "broadcast_json"),
    "CohortGroup": ("driver", "CohortGroup"),
    "ElasticDriver": ("driver", "ElasticDriver"),
    "ElasticExhausted": ("driver", "ElasticExhausted"),
    "elastic_metadata": ("driver", "elastic_metadata"),
    "assemble_entries": ("reshard", "assemble_entries"),
    "place_global": ("reshard", "place_global"),
    "place_named": ("reshard", "place_named"),
    "read_global_entries": ("reshard", "read_global_entries"),
    "reshard_report": ("reshard", "reshard_report"),
    "elastic_report": ("report", "elastic_report"),
}

_SUBMODULES = ("collective", "driver", "membership", "report", "reshard")


def __getattr__(name):
    if name in _SUBMODULES:
        mod = importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    try:
        mod_name, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}") from None
    mod = importlib.import_module(f".{mod_name}", __name__)
    value = getattr(mod, attr)
    globals()[name] = value          # cache: subsequent lookups are direct
    return value


def __dir__():
    return sorted(set(globals()) | set(__all__) | set(_SUBMODULES))

"""Stdlib-only elastic journal summary (the ``doctor --journal``
cohort-events section, docs/elastic.md).

Reads a JSONL diagnostics journal and summarizes the elastic records —
rank losses, cohort resizes (with the membership trajectory), resharded
restores, retraces — plus the trace linkage between them: records
written inside one ``elastic_recover`` span share a ``trace_id``, so
the report can say "loss of rank 1 at step 6 → epoch 2 (2→1 members) →
restored step 5 resharded 2→1" as one correlated event. No jax, no
runtime package: the report must work from a wedged environment (the
``resilience.commit.doctor_report`` contract)."""
from __future__ import annotations

import json

__all__ = ["elastic_report"]

_KINDS = ("cohort_form", "cohort_resize", "cohort_join", "rank_lost",
          "reshard_restore", "elastic_retrace")


def elastic_report(path: str) -> dict:
    try:
        with open(path, encoding="utf-8") as f:
            lines = f.readlines()
    except OSError as e:
        return {"ok": False, "path": path,
                "error": f"cannot read journal: {e.strerror or e}"}
    counts = {k: 0 for k in _KINDS}
    resizes, restores, losses = [], [], []
    rollback_traces = set()
    by_trace = {}
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue                      # torn tail line from a kill
        if not isinstance(rec, dict):
            continue
        kind = rec.get("kind")
        tid = rec.get("trace_id")
        if kind == "divergence_rollback" and tid:
            rollback_traces.add(tid)
        if kind not in _KINDS:
            continue
        counts[kind] += 1
        if tid:
            by_trace.setdefault(tid, []).append(kind)
        if kind == "rank_lost":
            losses.append({k: rec.get(k) for k in
                           ("lost", "survivors", "epoch", "step",
                            "where", "trace_id")})
        elif kind == "cohort_resize":
            resizes.append({k: rec.get(k) for k in
                            ("epoch", "old_members", "members", "lost",
                             "joined", "trace_id")})
        elif kind == "reshard_restore":
            restores.append({k: rec.get(k) for k in
                             ("step", "n_old", "n_new", "entries",
                              "bytes", "trace_id")})
    # a recovery is "correlated" when loss→resize→restore share a trace
    correlated = sum(
        1 for kinds in by_trace.values()
        if "rank_lost" in kinds and "reshard_restore" in kinds)
    out = {"ok": True, "path": path, "counts": counts,
           "rank_losses": losses,
           "resizes": resizes,
           "reshard_restores": restores,
           "correlated_recoveries": correlated,
           "last_resize": resizes[-1] if resizes else None,
           "rollback_linked": sorted(
               t for t in by_trace if t in rollback_traces)}
    return out

"""The elastic run loop: detect → quiesce → resize → rebuild → restore
→ resume.

Orchestrates a training cohort through membership changes (docs/
elastic.md). The shape of one recovery, all under ONE trace span so the
journal records correlate::

    rank_lost        a member's heartbeat went stale (or a barrier
                     surfaced RankLost) — evidence, step, epoch
    cohort_resize    the leader published epoch k+1 (survivors + any
                     live joiners); every member adopted it
    elastic_retrace  the survivor rebuilt its trainer/mesh — compiled
                     programs dropped, never silently reused
    reshard_restore  the newest committed checkpoint re-placed onto the
                     new topology (N_old shard files → N_new mesh)

Progress model: work since the last committed checkpoint is lost on a
resize — the same contract as a preemption (docs/checkpointing.md);
``checkpoint_every`` bounds the loss window. Recovery attempts are
bounded by ``MXNET_TPU_ELASTIC_MAX_REBUILDS`` (default 3): a cohort
that cannot stabilize surfaces a structured error instead of thrashing.

While the driver runs, checkpoint commits/restores are coordinated by
the cohort (``CohortGroup`` installed into ``parallel._ckpt``): barriers
are deadline-bounded against the membership ledger, shard files are
keyed by cohort rank, and the commit manifest records the cohort shape
— the provenance the resharded reader and ``doctor --journal`` consume.
"""
from __future__ import annotations

import os

import numpy as np

from ..base import MXNetError
from ..diagnostics.journal import get_journal
from ..observability import trace as _trace
from ..parallel import _ckpt
from . import collective
from .membership import Cohort, RankLost  # noqa: F401  (re-export surface)

__all__ = ["CohortGroup", "ElasticDriver", "ElasticExhausted"]

DEFAULT_MAX_REBUILDS = 3


class ElasticExhausted(MXNetError):
    """The rebuild budget ran out — the cohort kept losing members (or
    kept timing out) faster than it could stabilize."""


class CohortGroup:
    """Cohort-backed checkpoint group for ``parallel._ckpt.set_group``:
    rank 0 duties go to the cohort leader, barriers and broadcasts ride
    the deadline-bounded ledger, and per-shard piece ownership is a
    round-robin split over the member list (see ``_ckpt.write_entries``)."""

    kind = "cohort"

    def __init__(self, cohort, members=None):
        self.cohort = cohort
        self.members = list(members if members is not None
                            else cohort.members())
        if cohort.rank not in self.members:
            raise MXNetError(f"rank {cohort.rank} is not a member of "
                             f"{self.members}")

    def index(self):
        return self.members.index(self.cohort.rank)

    def count(self):
        return len(self.members)

    def barrier(self, tag):
        self.cohort.barrier(f"ckpt-{tag}", members=self.members)

    def bcast_int(self, value):
        doc = collective.broadcast_json(self.cohort, "ckpt-int",
                                        {"v": int(value)})
        return int(doc["v"])

    def owns_piece(self, position):
        return position % len(self.members) == self.index()

    def meta(self):
        return {"world": self.count(), "kind": "cohort",
                "cohort_epoch": self.cohort.epoch,
                "cohort_members": list(self.members)}


def _env_int(name, default):
    v = os.environ.get(name)
    try:
        return int(v) if v else int(default)
    except ValueError:
        return int(default)


class ElasticDriver:
    """Run a sharded/pipelined trainer under an elastic cohort.

    ``build(members)`` constructs a FRESH trainer for the given member
    list (choose the mesh/data layout for that world there); the driver
    owns when to call it — at start and after every resize — and always
    follows a rebuild with a resharded restore of the newest committed
    step, so a new trainer never trains from reinitialized weights while
    a checkpoint exists.

    ``data_fn(step, members, index)`` returns the positional batch for
    ``trainer.step`` — derive the rank's shard from ``members``/``index``
    so data re-partitions with the cohort.
    """

    def __init__(self, cohort: Cohort, ckpt_root, build, *,
                 checkpoint_every=10, keep_last=3, sync_every=None,
                 max_rebuilds=None, per_shard=None):
        self.cohort = cohort
        self.ckpt_root = str(ckpt_root)
        self.build = build
        self.checkpoint_every = int(checkpoint_every)
        self.keep_last = keep_last
        self.sync_every = sync_every
        self.per_shard = per_shard
        self.max_rebuilds = (int(max_rebuilds) if max_rebuilds is not None
                             else _env_int("MXNET_TPU_ELASTIC_MAX_REBUILDS",
                                           DEFAULT_MAX_REBUILDS))
        self.rebuilds = 0
        self.restored_step = None
        self._last_committed = None
        # called as on_restore(trainer, step) after every resharded
        # restore — the hook a data pipeline uses to rewind to the
        # restored step (and the chaos tests use to snapshot the
        # just-restored tree)
        self.on_restore = None

    # -- cohort-synchronous state sync ---------------------------------------
    def _entries_host(self, trainer):
        if hasattr(trainer, "_param_entries"):     # ShardedTrainer
            ents = {**trainer._param_entries(),
                    **trainer._state_entries()}
        else:                                      # PipelinedTrainer
            ents = trainer._ckpt_entries()
        return {k: _ckpt.gather_host(v) for k, v in ents.items()}

    def _sync_state(self, trainer, tag):
        """Average the full param/opt-state tree across the cohort (the
        recovery-lane collective: deadline-bounded, RankLost-safe). Run
        before every commit so the cohort's per-rank shard files are
        slices of ONE agreed tree — and at ``sync_every`` as the
        local-SGD sync point."""
        if len(self._members) <= 1:
            return
        reduced = collective.allreduce_mean(self.cohort, tag,
                                            self._entries_host(trainer))
        trainer._adopt_host_entries(reduced)

    # -- checkpoint / restore under the cohort group -------------------------
    def _checkpoint(self, trainer):
        # constant tag: the per-(epoch, tag) use counter disambiguates
        # repeats, and constant tags keep the ledger's directory count
        # bounded (step-embedded tags would grow one dir per sync)
        self._sync_state(trainer, "presync")
        step = trainer.checkpoint(self.ckpt_root, keep_last=self.keep_last,
                                  per_shard=self.per_shard)
        self._last_committed = int(step)
        return step

    def _has_checkpoint(self):
        from ..resilience import commit as _commit
        return bool(_commit.committed_steps(self.ckpt_root))

    def _setup(self, members):
        """Fresh trainer for ``members``, prepared (sharded state
        materialized from an example batch) + resharded restore of the
        newest committed step (when one exists)."""
        self._members = list(members)
        trainer = self.build(list(members))
        if not getattr(trainer, "_prepared", True):
            batch = self._data_fn(int(trainer.num_update), list(members),
                                  members.index(self.cohort.rank))
            trainer.prepare(*batch[:-1])
        if self._has_checkpoint():
            self.restored_step = trainer.restore_resharded(self.ckpt_root)
            self._last_committed = int(self.restored_step)
            if self.on_restore is not None:
                self.on_restore(trainer, self.restored_step)
        return trainer

    # -- recovery ------------------------------------------------------------
    def _recover(self, trainer, err):
        """One bounded recovery: journal the loss, resize, rebuild,
        restore — all under ONE ``elastic_recover`` span so the
        ``rank_lost``/``cohort_resize``/``reshard_restore`` records
        correlate by trace id. A FURTHER loss mid-recovery loops here
        (each attempt spends rebuild budget) instead of escaping."""
        j = get_journal()
        while True:
            self.rebuilds += 1
            if self.rebuilds > self.max_rebuilds:
                raise ElasticExhausted(
                    f"elastic rebuild budget exhausted "
                    f"({self.max_rebuilds}); last failure: {err}") from err
            try:
                with _trace.span("elastic_recover",
                                 epoch=self.cohort.epoch,
                                 attempt=self.rebuilds) as sp:
                    j.event("rank_lost",
                            lost=getattr(err, "lost", []),
                            survivors=getattr(err, "survivors", []),
                            epoch=getattr(err, "epoch", self.cohort.epoch),
                            where=getattr(err, "where", "")
                            or str(err)[:200],
                            step=(int(trainer.num_update)
                                  if trainer is not None else None),
                            attempt=self.rebuilds)
                    # quiesce: the doomed trainer (compiled programs
                    # included) is dropped before the world changes
                    # under it; the leader publishes the new epoch
                    trainer = None
                    members = self.cohort.resize(getattr(err, "lost", []))
                    # join the leader's recovery trace: the epoch record
                    # just adopted carries the leader's trace id (it was
                    # stamped inside ITS elastic_recover span), so every
                    # survivor's subsequent recovery records —
                    # elastic_retrace, reshard_restore, the final span —
                    # correlate under ONE pod-wide trace
                    doc = self.cohort.read_epoch_doc() or {}
                    _trace.adopt_trace(sp, doc.get("recovery_trace"))
                    _ckpt.set_group(CohortGroup(self.cohort, members))
                    j.event("elastic_retrace", reason="cohort_resize",
                            epoch=self.cohort.epoch,
                            members=list(members))
                    trainer = self._setup(members)
                return members, trainer
            except RankLost as e2:
                err = e2

    # -- the loop ------------------------------------------------------------
    def run(self, data_fn, num_steps):
        """Train to ``num_steps`` optimizer updates, surviving membership
        changes. Returns the final trainer (its ``num_update`` ==
        ``num_steps``; a final checkpoint is committed)."""
        self._data_fn = data_fn
        self._last_committed = None
        members = self.cohort.members()
        prev_group = _ckpt.set_group(CohortGroup(self.cohort, members))
        trainer = None
        try:
            while True:
                try:
                    if trainer is None:
                        trainer = self._setup(members)
                    step = int(trainer.num_update)
                    if step >= int(num_steps):
                        # final commit only when the loop didn't already
                        # cover this exact state — and INSIDE the try,
                        # so a rank dying during it still recovers
                        if self._last_committed != step:
                            self._checkpoint(trainer)
                        break
                    lost = self.cohort.check()
                    if lost:
                        raise RankLost(
                            lost, [r for r in members if r not in lost],
                            self.cohort.epoch, where="step_poll")
                    if self.sync_every and step and \
                            step % int(self.sync_every) == 0:
                        self._sync_state(trainer, "sync")
                    batch = data_fn(step, list(members),
                                    members.index(self.cohort.rank))
                    trainer.step(*batch)
                    done = int(trainer.num_update)
                    if done % self.checkpoint_every == 0 or \
                            done >= int(num_steps):
                        self._checkpoint(trainer)
                except RankLost as e:
                    members, trainer = self._recover(trainer, e)
            return trainer
        finally:
            _ckpt.set_group(prev_group)


def elastic_metadata():
    """Cohort/elastic provenance block for bench artifacts
    (benchmarks/scaling.py): the env-wired world plus the installed
    checkpoint group's shape, if any."""
    g = _ckpt.group()
    doc = {"kind": g.kind, "world": int(g.count())}
    if g.kind == "cohort":
        doc.update({"epoch": g.cohort.epoch,
                    "members": list(g.members)})
    for k in ("MXTPU_NUM_PROC", "MXTPU_PROC_ID"):
        if os.environ.get(k):
            doc[k.lower()] = int(os.environ[k])
    return doc


def np_tree_equal(a, b):
    """Bitwise equality of two {name: np.ndarray} trees (test helper for
    the restore bit-exactness proofs)."""
    if set(a) != set(b):
        return False
    return all(np.array_equal(np.asarray(a[k]), np.asarray(b[k]))
               for k in a)

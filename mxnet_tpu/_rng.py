"""Global PRNG state for eager execution.

The reference keeps per-device RNG states in the resource manager
(ref: src/resource.cc ResourceRequest::kRandom, mx.random.seed). JAX RNG is
stateless, so the eager (`mx.nd`) layer keeps ONE root key here and splits a
fresh subkey per sampling op; jitted/hybridized code threads keys explicitly
instead (see gluon.block), which is the TPU-idiomatic path.
"""
from __future__ import annotations

import contextlib
import threading

import jax

_lock = threading.Lock()
_key = jax.random.PRNGKey(0)
_trace = threading.local()


def seed(seed_state: int):
    """ref: mx.random.seed — reseed the global generator."""
    global _key
    with _lock:
        _key = jax.random.PRNGKey(int(seed_state))


def next_key():
    """Split off a fresh subkey for one op invocation.

    Inside a hybridize trace (``trace_key`` scope) the subkey is derived from
    the *traced* key argument via ``fold_in``, so the jitted program takes the
    key as a runtime input — each call of the compiled function sees fresh
    randomness instead of a baked-in constant."""
    stack = getattr(_trace, "stack", None)
    if stack:
        entry = stack[-1]
        entry[1] += 1
        return jax.random.fold_in(entry[0], entry[1])
    global _key
    with _lock:
        _key, sub = jax.random.split(_key)
    return sub


@contextlib.contextmanager
def trace_key(key):
    """Scope used while tracing a hybridized block: route ``next_key`` through
    a traced key argument (the TPU-idiomatic explicit-key threading)."""
    stack = getattr(_trace, "stack", None)
    if stack is None:
        stack = _trace.stack = []
    stack.append([key, 0])
    try:
        yield
    finally:
        stack.pop()


def in_trace() -> bool:
    return bool(getattr(_trace, "stack", None))

"""Global PRNG state for eager execution.

The reference keeps per-device RNG states in the resource manager
(ref: src/resource.cc ResourceRequest::kRandom, mx.random.seed). JAX RNG is
stateless, so the eager (`mx.nd`) layer keeps ONE root key here and splits a
fresh subkey per sampling op; jitted/hybridized code threads keys explicitly
instead (see gluon.block), which is the TPU-idiomatic path.
"""
from __future__ import annotations

import contextlib
import os
import threading

import jax


def _default_impl():
    """PRNG bit-generator implementation.

    threefry (JAX's default) is counter-based and fully reproducible but
    costs real MXU time to generate big masks — measured 32 ms of a
    131 ms BERT-base step (24%!) just making dropout masks
    (docs/perf_notes.md round 3). On TPU the default here is ``rbg``
    (XLA's hardware RngBitGenerator): same stateless key-threading
    semantics, ~free mask generation. Override with MXNET_PRNG_IMPL=
    threefry2x32|rbg (e.g. for bit-exact cross-platform repro); CPU
    keeps threefry so test suites stay deterministic."""
    impl = os.environ.get("MXNET_PRNG_IMPL")
    if impl:
        return impl
    try:
        if jax.default_backend() == "tpu":
            return "rbg"
    except RuntimeError:
        pass
    return "threefry2x32"


def _make_key(seed_val):
    # every key creation is a backend touch (array on device) — route it
    # through the diagnostics guard so the dial is journaled and a wedged
    # tunnel leaves a breadcrumb instead of a silent hang
    from .diagnostics import guard
    guard.ensure_backend(tag="rng-global-key")
    return jax.random.key(int(seed_val), impl=_default_impl())


_lock = threading.Lock()
# LAZY by contract: created on first seed()/key use. Nothing at module
# scope may call jax.default_backend()/jax.random.key — an import-time
# key here dialed the backend on `import mxnet_tpu` and wedged every
# tunnel-pinned process before any wedge-proofing could run (the root
# cause of the round-4/5 RED multichip gates, VERDICT r5; the reference
# builds RNG states lazily in src/resource.cc's ResourceManager).
# tests/test_diagnostics.py pins this with an import-hermeticity test.
_key = None
_trace = threading.local()


def _ensure_key_locked():
    """Create the global key on first use (caller holds ``_lock``)."""
    global _key
    if _key is None:
        _key = _make_key(0)
    return _key


def seed(seed_state: int):
    """ref: mx.random.seed — reseed the global generator."""
    global _key
    with _lock:
        _key = _make_key(int(seed_state))


def next_key():
    """Split off a fresh subkey for one op invocation.

    Inside a hybridize trace (``trace_key`` scope) the subkey is derived from
    the *traced* key argument via ``fold_in``, so the jitted program takes the
    key as a runtime input — each call of the compiled function sees fresh
    randomness instead of a baked-in constant."""
    stack = getattr(_trace, "stack", None)
    if stack:
        entry = stack[-1]
        entry[1] += 1
        return jax.random.fold_in(entry[0], entry[1])
    global _key
    with _lock:
        _key, sub = jax.random.split(_ensure_key_locked())
    return sub


@contextlib.contextmanager
def trace_key(key):
    """Scope used while tracing a hybridized block: route ``next_key`` through
    a traced key argument (the TPU-idiomatic explicit-key threading)."""
    stack = getattr(_trace, "stack", None)
    if stack is None:
        stack = _trace.stack = []
    stack.append([key, 0])
    try:
        yield
    finally:
        stack.pop()


def in_trace() -> bool:
    return bool(getattr(_trace, "stack", None))


def get_state():
    """Snapshot the eager generator: (raw key bits uint32, impl name).
    Together with ``set_state`` this makes checkpoint/resume bit-exact for
    every op that draws from the global key (dropout masks, samplers)."""
    import numpy as np
    with _lock:
        key = _ensure_key_locked()
        return (np.asarray(jax.random.key_data(key)),
                str(jax.random.key_impl(key)))


def set_state(data, impl):
    global _key
    import jax.numpy as jnp
    from .diagnostics import guard
    guard.ensure_backend(tag="rng-set-state")
    with _lock:
        _key = jax.random.wrap_key_data(
            jnp.asarray(data, dtype=jnp.uint32), impl=impl)

"""Global PRNG state for eager execution.

The reference keeps per-device RNG states in the resource manager
(ref: src/resource.cc ResourceRequest::kRandom, mx.random.seed). JAX RNG is
stateless, so the eager (`mx.nd`) layer keeps ONE root key here and splits a
fresh subkey per sampling op; jitted/hybridized code threads keys explicitly
instead (see gluon.block), which is the TPU-idiomatic path.
"""
from __future__ import annotations

import threading

import jax

_lock = threading.Lock()
_key = jax.random.PRNGKey(0)


def seed(seed_state: int):
    """ref: mx.random.seed — reseed the global generator."""
    global _key
    with _lock:
        _key = jax.random.PRNGKey(int(seed_state))


def next_key():
    """Split off a fresh subkey for one op invocation."""
    global _key
    with _lock:
        _key, sub = jax.random.split(_key)
    return sub

"""``mx.npx`` — NumPy-extension namespace (ref: python/mxnet/
numpy_extension/ + the `_npx_*` ops): neural-net operators with NumPy
calling conventions, plus the np-mode switches."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .. import ndarray as nd
from ..numpy import _call
from ..base import MXNetError

__all__ = ["set_np", "reset_np", "is_np_array", "softmax", "log_softmax",
           "relu", "sigmoid", "gelu", "leaky_relu", "batch_norm",
           "layer_norm", "fully_connected", "convolution", "pooling",
           "one_hot", "pick", "topk", "embedding", "dropout", "seed"]

_np_mode = {"array": False, "shape": False}


def set_np(shape=True, array=True):
    """ref: npx.set_np — enables numpy semantics globally. The TPU build's
    nd namespace is already numpy-semantics (jnp), so this toggles only the
    bookkeeping flag for script parity."""
    _np_mode["array"] = array
    _np_mode["shape"] = shape


def reset_np():
    set_np(False, False)
    _np_mode["array"] = False
    _np_mode["shape"] = False


def is_np_array():
    return _np_mode["array"]


def softmax(x, axis=-1):
    return _call(jax.nn.softmax, x, axis=axis)


def log_softmax(x, axis=-1):
    return _call(jax.nn.log_softmax, x, axis=axis)


def relu(x):
    return _call(jax.nn.relu, x)


def sigmoid(x):
    return _call(jax.nn.sigmoid, x)


def gelu(x):
    return _call(jax.nn.gelu, x)


def leaky_relu(x, slope=0.01):
    return _call(lambda a: jax.nn.leaky_relu(a, slope), x)


def one_hot(x, depth, on_value=1.0, off_value=0.0, dtype=None):
    return _call(lambda a: jax.nn.one_hot(a.astype(jnp.int32), depth) *
                 (on_value - off_value) + off_value, x)


def pick(data, index, axis=-1, keepdims=False):
    return nd.pick(data, index, axis=axis, keepdims=keepdims)


def topk(data, k=1, axis=-1, ret_typ="indices", is_ascend=False):
    return nd.topk(data, k=k, axis=axis, ret_typ=ret_typ,
                   is_ascend=is_ascend)


def embedding(data, weight, input_dim=None, output_dim=None, dtype=None):
    return nd.Embedding(data, weight,
                        input_dim=input_dim or weight.shape[0],
                        output_dim=output_dim or weight.shape[1])


def fully_connected(x, weight, bias=None, num_hidden=None, no_bias=False,
                    flatten=True):
    args = [x, weight] + ([] if bias is None else [bias])
    return nd.FullyConnected(*args,
                             num_hidden=num_hidden or weight.shape[0],
                             no_bias=bias is None or no_bias,
                             flatten=flatten)


def convolution(data, weight, bias=None, **kwargs):
    args = [data, weight] + ([] if bias is None else [bias])
    if bias is None:
        kwargs.setdefault("no_bias", True)
    return nd.Convolution(*args, **kwargs)


def pooling(data, **kwargs):
    return nd.Pooling(data, **kwargs)


def batch_norm(x, gamma, beta, running_mean, running_var, eps=1e-3,
               momentum=0.9, fix_gamma=False, use_global_stats=False,
               output_mean_var=False, axis=1):
    return nd.BatchNorm(x, gamma, beta, running_mean, running_var, eps=eps,
                        momentum=momentum, fix_gamma=fix_gamma,
                        use_global_stats=use_global_stats,
                        output_mean_var=output_mean_var, axis=axis)


def layer_norm(x, gamma, beta, axis=-1, eps=1e-5):
    return nd.LayerNorm(x, gamma, beta, axis=axis, eps=eps)


def dropout(x, p=0.5, **kwargs):
    return nd.Dropout(x, p=p, **kwargs)


def seed(s):
    from .. import random as _random
    _random.seed(s)


# the rest of the reference's most-used `_npx_*` family: thin adapters
# over the registry ops (same numerics / autograd as mx.nd)
def batch_dot(a, b, transpose_a=False, transpose_b=False):
    return nd.batch_dot(a, b, transpose_a=transpose_a,
                        transpose_b=transpose_b)


def gather_nd(data, indices):
    return nd.gather_nd(data, indices)


def reshape_like(lhs, rhs):
    return nd.reshape_like(lhs, rhs)


def broadcast_like(lhs, rhs):
    return nd.broadcast_like(lhs, rhs)


def arange_like(data, start=0.0, step=1.0, axis=None):
    return nd.arange_like(data, start=start, step=step, axis=axis)


def sequence_mask(data, sequence_length=None, use_sequence_length=False,
                  value=0.0, axis=0):
    # the flag is authoritative (reference semantics): with
    # use_sequence_length=False the data passes through unmasked even if
    # a sequence_length tensor was supplied; with it True the lengths
    # are REQUIRED (silent pass-through would corrupt attention/losses)
    if use_sequence_length and sequence_length is None:
        raise MXNetError("sequence_mask: use_sequence_length=True "
                         "requires a sequence_length tensor")
    args = [data] + ([sequence_length] if use_sequence_length else [])
    return nd.SequenceMask(*args, use_sequence_length=use_sequence_length,
                           value=value, axis=axis)


def smooth_l1(data, scalar=1.0):
    return nd.smooth_l1(data, scalar=scalar)


def slice(data, begin, end, step=None):        # noqa: A001 (ref name)
    kwargs = {"begin": begin, "end": end}
    if step is not None:
        kwargs["step"] = step
    return nd.slice(data, **kwargs)


def slice_like(data, shape_like, axes=None):
    return nd.slice_like(data, shape_like, axes=axes)


def waitall():
    nd.waitall()


__all__ += ["batch_dot", "gather_nd", "reshape_like", "broadcast_like",
            "arange_like", "sequence_mask", "smooth_l1", "slice",
            "slice_like", "waitall"]


# round-5 tail: the remaining commonly-scripted `_npx_*` entry points —
# same thin-adapter idiom (registry ops carry numerics + autograd)
def activation(data, act_type="relu"):
    return nd.Activation(data, act_type=act_type)


def cast(data, dtype):
    return nd.cast(data, dtype=dtype)


def erf(data):
    return nd.erf(data)


def erfinv(data):
    return nd.erfinv(data)


def gamma(data):
    return nd.gamma(data)


def gammaln(data):
    return nd.gammaln(data)


def deconvolution(data, weight, bias=None, **kwargs):
    args = [data, weight] + ([bias] if bias is not None else [])
    # the op's registered default is no_bias=True — an explicit bias must
    # flip it or it would be silently ignored
    kwargs.setdefault("no_bias", bias is None)
    return nd.Deconvolution(*args, **kwargs)


def ctc_loss(data, label, data_lengths=None, label_lengths=None, **kwargs):
    args = [data, label]
    if data_lengths is not None:
        args.append(data_lengths)
        kwargs.setdefault("use_data_lengths", True)
    if label_lengths is not None:
        args.append(label_lengths)
        kwargs.setdefault("use_label_lengths", True)
    return nd.CTCLoss(*args, **kwargs)


def group_norm(data, gamma, beta, num_groups=1, eps=1e-5):
    return nd.GroupNorm(data, gamma, beta, num_groups=num_groups, eps=eps)


def instance_norm(data, gamma, beta, eps=1e-3):
    # default eps matches the op's (and the reference's) 1e-3
    return nd.InstanceNorm(data, gamma, beta, eps=eps)


def box_nms(data, overlap_thresh=0.5, valid_thresh=0.0, topk=-1,
            coord_start=2, score_index=1, id_index=-1, force_suppress=False,
            in_format="corner", out_format="corner"):
    return nd.contrib.box_nms(
        data, overlap_thresh=overlap_thresh, valid_thresh=valid_thresh,
        topk=topk, coord_start=coord_start, score_index=score_index,
        id_index=id_index, force_suppress=force_suppress,
        in_format=in_format, out_format=out_format)


def rnn(data, parameters, state, state_cell=None, sequence_length=None,
        mode="lstm", state_size=None, num_layers=1, **kwargs):
    args = [data, parameters, state] + \
        ([state_cell] if state_cell is not None else [])
    if sequence_length is not None:
        args.append(sequence_length)
        kwargs.setdefault("use_sequence_length", True)
    return nd.RNN(*args, mode=mode, state_size=state_size,
                  num_layers=num_layers, **kwargs)


__all__ += ["activation", "cast", "erf", "erfinv", "gamma", "gammaln",
            "deconvolution", "ctc_loss", "group_norm", "instance_norm",
            "box_nms", "rnn"]

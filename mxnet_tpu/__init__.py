"""mxnet_tpu — a TPU-native deep-learning framework with the capability
surface of Apache MXNet 1.x (reference: yanghaojin/incubator-mxnet).

Built from scratch on JAX/XLA (+Pallas for custom kernels): XLA replaces the
reference's ThreadedEngine/mshadow/cuDNN stack, ``hybridize()`` lowers Gluon
blocks to jitted XLA computations (the reference's CachedOp), and the KVStore
facade maps onto ``jax.lax.psum`` over a device mesh. See SURVEY.md for the
full reference analysis and design-mapping table.

Usage mirrors the reference::

    import mxnet_tpu as mx
    from mxnet_tpu import nd, autograd, gluon

    x = nd.ones((2, 3), ctx=mx.tpu())
    with autograd.record():
        y = (x * 2).sum()
    y.backward()
"""
from __future__ import annotations

__version__ = "0.1.0"

# Multi-host: when launched by tools/launch.py (MXTPU_* env protocol), the
# coordination service must be joined BEFORE any jax backend touch — do it
# at package import, the earliest point we control (the kvstore would be
# too late: importing this package already initializes devices).
import os as _os

if _os.environ.get("MXTPU_COORD_ADDR"):
    import jax as _jax
    try:
        _jax.distributed.initialize(
            coordinator_address=_os.environ["MXTPU_COORD_ADDR"],
            num_processes=int(_os.environ["MXTPU_NUM_PROC"]),
            process_id=int(_os.environ["MXTPU_PROC_ID"]))
    except RuntimeError:
        pass          # already joined (re-import / interactive)

# fp32 means fp32: JAX's DEFAULT matmul precision lowers fp32 matmul
# inputs to single-pass bf16 multiplies on TPU (~1e-2 relative error —
# measured FAILing the CPU-oracle parity sweep, benchmarks/hw_parity.py),
# while the reference's fp32 GEMMs are true fp32 (cuBLAS). HIGHEST
# restores fp32 accumulation for fp32 inputs and does not touch the bf16
# AMP fast paths (their operands are already bf16). Override with
# MXNET_MATMUL_PRECISION=default|high|highest.
import jax as _jax_cfg

_prec = _os.environ.get("MXNET_MATMUL_PRECISION") or "highest"
if _prec not in ("default", "high", "highest"):
    raise ImportError(
        f"MXNET_MATMUL_PRECISION={_prec!r} is invalid: expected "
        f"'default', 'high' or 'highest'")
_jax_cfg.config.update("jax_default_matmul_precision", _prec)

from .base import MXNetError
from .context import (Context, cpu, cpu_pinned, cpu_shared, current_context,
                      gpu, gpu_memory_info, num_gpus, num_tpus, tpu)
from . import engine
from . import library
from . import ndarray
from . import ndarray as nd
from . import autograd
from . import random
from . import initializer
from . import initializer as init
from . import optimizer
from . import lr_scheduler
from . import metric
from . import metric_det
# detection mAP lives beside the classification metrics (the reference
# ecosystem ships it in gluoncv.utils.metrics; one registry here)
metric.VOCMApMetric = metric_det.VOCMApMetric
metric.VOC07MApMetric = metric_det.VOC07MApMetric
from . import kvstore
from . import kvstore as kv
from . import gluon
from . import parallel
from . import recordio
from . import io
from . import image
from . import symbol
from . import symbol as sym
from . import model
from . import module
from . import module as mod
from . import monitor
from . import monitor as mon
from . import callback
from . import profiler
from . import contrib
from . import numpy as np
from . import numpy_extension as npx
from . import visualization
from . import visualization as viz
from . import test_utils
from . import operator
from . import runtime
from . import diagnostics
from . import observability    # stdlib-only telemetry substrate
from . import guardrails       # import-light root; fused loads lazily
from . import resilience
from . import serving          # lazy package: submodules load on first use
from . import testing
from . import util
from . import rnn
from . import attribute
from .attribute import AttrScope
from . import name

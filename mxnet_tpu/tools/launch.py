#!/usr/bin/env python
"""Multi-host launcher (ref: tools/launch.py + dmlc-core tracker).

The reference launches parameter-server jobs (scheduler + servers +
workers) over ssh/mpi/local with DMLC_* env wiring. The TPU equivalent
launches one worker process per host that calls
``jax.distributed.initialize`` — the JAX coordination service plays the
scheduler; GSPMD over DCN replaces ps-lite (SURVEY §5.8).

  # 4 local processes faking a 4-host job (the reference's `--launcher
  # local` test mode, used by tests/nightly/dist_sync_kvstore.py):
  python tools/launch.py -n 4 --launcher local python train.py

  # ssh to hosts in a hostfile:
  python tools/launch.py -n 2 -H hosts --launcher ssh python train.py

Env protocol handed to each worker (read by mxnet_tpu.kvstore 'dist_*'):
  MXTPU_COORD_ADDR  host:port of process 0 (jax coordinator)
  MXTPU_NUM_PROC    world size
  MXTPU_PROC_ID     rank
The legacy DMLC_* names are also set for script compatibility.
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys


def worker_env(rank, n, coord_addr):
    env = dict(os.environ)
    env.update({
        "MXTPU_COORD_ADDR": coord_addr,
        "MXTPU_NUM_PROC": str(n),
        "MXTPU_PROC_ID": str(rank),
        # legacy names (ref: dmlc tracker env wiring)
        "DMLC_NUM_WORKER": str(n),
        "DMLC_WORKER_ID": str(rank),
        "DMLC_ROLE": "worker",
        "DMLC_PS_ROOT_URI": coord_addr.split(":")[0],
        "DMLC_PS_ROOT_PORT": coord_addr.split(":")[1],
    })
    return env


def launch_local(args, command):
    """Spawn the job; heartbeat-monitor the workers and auto-restart the
    whole job on failure up to --max-restarts (SURVEY §5.3's TPU plan:
    'checkpoint + relaunch; add heartbeat + auto-resume in the launcher'
    — the training script resumes from its own latest checkpoint, like
    the reference's recovery story)."""
    import time
    coord = f"127.0.0.1:{args.port}"
    attempts = 0
    # bounded by the restart budget: the body returns 1 past
    # --max-restarts, so the condition is the loop's honest contract
    while attempts <= args.max_restarts:
        procs = [subprocess.Popen(
            command, env=dict(worker_env(r, args.num_workers, coord),
                              MXTPU_RESTART=str(attempts)))
            for r in range(args.num_workers)]

        def _terminate(signum, frame):
            for p in procs:
                p.terminate()
            sys.exit(1)
        signal.signal(signal.SIGINT, _terminate)
        signal.signal(signal.SIGTERM, _terminate)

        # heartbeat loop: poll liveness; one dead worker fails the job
        # (dist_sync semantics — the reference's dist_sync also cannot
        # survive a lost worker; recovery = relaunch from checkpoint).
        # Bounded by child liveness, not a while-True spin (G13): the
        # loop ends when every worker has exited or the first fails.
        failed = False
        codes = [None] * len(procs)
        while any(c is None for c in codes) and not failed:
            time.sleep(args.heartbeat_interval)
            codes = [p.poll() for p in procs]
            failed = any(c is not None and c != 0 for c in codes)
        if not failed:
            return 0
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            p.wait()
        attempts += 1
        if attempts > args.max_restarts:
            print(f"launch: job failed after {attempts - 1} restarts",
                  file=sys.stderr)
            return 1
        print(f"launch: worker died; restarting job "
              f"(attempt {attempts}/{args.max_restarts}, scripts resume "
              f"from their checkpoints; MXTPU_RESTART={attempts})",
              file=sys.stderr)
    return 1         # --max-restarts < 0: nothing was ever launched


def launch_ssh(args, command):
    if not args.hostfile:
        raise SystemExit("--launcher ssh requires -H/--hostfile")
    with open(args.hostfile) as f:
        hosts = [h.strip() for h in f if h.strip()]
    if len(hosts) < args.num_workers:
        raise SystemExit(f"hostfile has {len(hosts)} hosts < "
                         f"-n {args.num_workers}")
    coord = f"{hosts[0]}:{args.port}"
    procs = []
    for rank in range(args.num_workers):
        env = worker_env(rank, args.num_workers, coord)
        import shlex
        env_str = " ".join(
            f"{k}={shlex.quote(str(v))}" for k, v in env.items()
            if k.startswith(("MXTPU_", "DMLC_")))
        remote = f"cd {shlex.quote(os.getcwd())} && {env_str} " + \
            " ".join(shlex.quote(c) for c in command)
        procs.append(subprocess.Popen(["ssh", "-o",
                                       "StrictHostKeyChecking=no",
                                       hosts[rank], remote]))
    rc = 0
    for p in procs:
        rc = p.wait() or rc
    return rc


def main():
    parser = argparse.ArgumentParser(
        description="Launch a multi-host mxnet_tpu job "
                    "(ref: tools/launch.py)")
    parser.add_argument("-n", "--num-workers", type=int, required=True)
    parser.add_argument("-H", "--hostfile", type=str, default=None)
    parser.add_argument("--launcher", type=str, default="local",
                        choices=["local", "ssh"])
    parser.add_argument("-p", "--port", type=int, default=9099)
    parser.add_argument("--max-restarts", type=int, default=0,
                        help="auto-restart the job this many times when a "
                             "worker dies (local launcher); scripts resume "
                             "from their own checkpoints")
    parser.add_argument("--heartbeat-interval", type=float, default=0.5,
                        help="worker liveness poll interval, seconds")
    parser.add_argument("command", nargs=argparse.REMAINDER)
    args = parser.parse_args()
    if not args.command:
        raise SystemExit("no command given")
    if args.launcher == "local":
        rc = launch_local(args, args.command)
    else:
        rc = launch_ssh(args, args.command)
    sys.exit(rc)


if __name__ == "__main__":
    main()

"""Device context model.

Re-design of the reference's ``Context`` (ref: include/mxnet/base.h struct
Context; python/mxnet/context.py) for TPU: a Context names a logical device
(`cpu`, `gpu`, `tpu`, plus the reference's pinned/shared CPU variants) and
resolves to a concrete ``jax.Device``. Per the north star, ``mx.tpu()`` is a
first-class Context so scripts port by swapping ``ctx=mx.tpu()``.
"""
from __future__ import annotations

import threading

import jax

from .base import MXNetError

__all__ = ["Context", "cpu", "gpu", "tpu", "cpu_pinned", "cpu_shared",
           "current_context", "num_gpus", "num_tpus", "gpu_memory_info"]


class Context:
    """A logical device. Mirrors the reference API: ``Context(kind, device_id)``,
    comparable/hashable, usable as a ``with`` scope to set the default device
    (ref: python/mxnet/context.py Context.__enter__).
    """

    # device type codes keep the reference's numbering, with TPU appended
    # (ref: include/mxnet/base.h Context::DeviceType)
    devtype2str = {1: "cpu", 2: "gpu", 3: "cpu_pinned", 5: "cpu_shared", 6: "tpu"}
    devstr2type = {v: k for k, v in devtype2str.items()}

    _default = threading.local()

    def __init__(self, device_type, device_id: int = 0):
        if isinstance(device_type, Context):
            self.device_typeid = device_type.device_typeid
            self.device_id = device_type.device_id
        elif isinstance(device_type, str):
            if device_type not in Context.devstr2type:
                raise MXNetError(f"unknown device type {device_type!r}")
            self.device_typeid = Context.devstr2type[device_type]
            self.device_id = device_id
        else:
            self.device_typeid = int(device_type)
            self.device_id = device_id

    @property
    def device_type(self) -> str:
        typ = Context.devtype2str[self.device_typeid]
        # pinned/shared CPU collapse onto plain host memory on TPU systems
        return typ

    def __hash__(self):
        return hash((self.device_typeid, self.device_id))

    def __eq__(self, other):
        return (isinstance(other, Context)
                and self.device_typeid == other.device_typeid
                and self.device_id == other.device_id)

    def __str__(self):
        return f"{self.device_type}({self.device_id})"

    __repr__ = __str__

    # -- resolution onto jax ------------------------------------------------
    @property
    def jax_device(self) -> jax.Device:
        """Resolve this Context to a concrete jax.Device."""
        return _resolve_device(self.device_type, self.device_id)

    def __enter__(self):
        if not hasattr(Context._default, "stack"):
            Context._default.stack = []
        Context._default.stack.append(self)
        return self

    def __exit__(self, *exc):
        Context._default.stack.pop()

    def empty_cache(self):
        """Release cached device memory (ref: Storage pool ReleaseAll via
        MXStorageEmptyCache). XLA owns pooling; best-effort no-op."""
        try:
            self.jax_device.client.defragment()  # pragma: no cover
        except Exception:
            pass


def _platform_devices(platform: str):
    """Process-LOCAL devices of a platform: a Context must resolve to an
    addressable device — in multi-process jobs jax.devices() lists the
    whole job's devices but only local ones accept transfers."""
    from .diagnostics import guard
    try:
        return [d for d in guard.devices(local=True)
                if d.platform == platform]
    except RuntimeError:
        return []


_ACCEL_CACHE = {}


def _accelerator_devices():
    """Devices on the default (accelerator) backend that are not plain CPU.

    Under the TPU tunnel the platform may report an experimental name, so we
    detect 'is an accelerator' rather than string-match 'tpu' exclusively.
    """
    if "accel" not in _ACCEL_CACHE:
        from .diagnostics import guard
        devs = [d for d in guard.devices(local=True)
                if d.platform != "cpu"]
        _ACCEL_CACHE["accel"] = devs
    return _ACCEL_CACHE["accel"]


def _resolve_device(device_type: str, device_id: int) -> jax.Device:
    if device_type in ("cpu", "cpu_pinned", "cpu_shared"):
        devs = _platform_devices("cpu")
        if not devs:  # default backend is CPU-less? fall back to any device
            from .diagnostics import guard
            devs = guard.devices(local=True)
        return devs[min(device_id, len(devs) - 1)]
    if device_type == "tpu":
        devs = _platform_devices("tpu") or _accelerator_devices()
        if not devs:
            raise MXNetError("no TPU devices visible to JAX")
        if device_id >= len(devs):
            raise MXNetError(f"tpu({device_id}) out of range: {len(devs)} devices")
        return devs[device_id]
    if device_type == "gpu":
        devs = _platform_devices("gpu") or _platform_devices("cuda")
        if devs:
            return devs[device_id]
        # Compatibility affordance: scripts written for the reference use
        # mx.gpu(i); on a TPU system map them onto accelerators so they run
        # unmodified (documented divergence).
        devs = _accelerator_devices()
        if devs:
            return devs[min(device_id, len(devs) - 1)]
        raise MXNetError("no GPU/accelerator devices visible to JAX")
    raise MXNetError(f"unknown device type {device_type!r}")


def cpu(device_id: int = 0) -> Context:
    return Context("cpu", device_id)


def gpu(device_id: int = 0) -> Context:
    return Context("gpu", device_id)


def tpu(device_id: int = 0) -> Context:
    """TPU context (new in this framework; the north-star API addition)."""
    return Context("tpu", device_id)


def cpu_pinned(device_id: int = 0) -> Context:
    return Context("cpu_pinned", device_id)


def cpu_shared(device_id: int = 0) -> Context:
    return Context("cpu_shared", device_id)


def num_gpus() -> int:
    """ref: mx.context.num_gpus; counts accelerators on TPU systems."""
    devs = _platform_devices("gpu") or _platform_devices("cuda")
    return len(devs)


def num_tpus() -> int:
    return len(_platform_devices("tpu") or _accelerator_devices())


def gpu_memory_info(device_id: int = 0):
    """(free, total) bytes, ref: mx.context.gpu_memory_info."""
    # device-memory queries dial the backend; guard them so the touch is
    # journaled (docs/diagnostics.md)
    from .diagnostics import guard
    guard.ensure_backend(tag="device-memory-info")
    dev = _resolve_device("gpu", device_id)
    stats = getattr(dev, "memory_stats", lambda: None)()
    if stats:
        total = stats.get("bytes_limit", 0)
        used = stats.get("bytes_in_use", 0)
        return (total - used, total)
    return (0, 0)


def current_context() -> Context:
    """The default context (ref: Context::CurrentContext via with-scopes).
    Defaults to cpu(0) like the reference."""
    stack = getattr(Context._default, "stack", None)
    if stack:
        return stack[-1]
    return Context("cpu", 0)


def default_ctx_for_accel() -> Context:
    """Best training context on this host: tpu(0) if present else cpu(0)."""
    return tpu(0) if _accelerator_devices() else cpu(0)

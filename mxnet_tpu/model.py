"""``mx.model`` — checkpoint helpers (ref: python/mxnet/model.py).

Format parity: ``prefix-symbol.json`` (graph) + ``prefix-%04d.params``
(NDArray dict with arg:/aux: prefixes), the same pair every reference-era
deployment pipeline consumes (SURVEY §5.4).

Crash consistency (docs/checkpointing.md): both files are written
through ``resilience.atomic`` (tmp + fsync + rename), the ``.params``
container carries CRC32s, and the resume path
(:func:`load_latest_params` / ``module.fit(resume=True)``) walks epochs
newest-first, *validating* each candidate and journaling a
``ckpt_fallback`` record when a torn/corrupt file is skipped — a
preempted save can cost at most one checkpoint interval, never the run.
"""
from __future__ import annotations

import contextlib
import os
import re

from . import ndarray as nd
from .base import MXNetError
from .diagnostics.journal import get_journal

__all__ = ["save_checkpoint", "load_checkpoint", "load_params",
           "list_checkpoint_epochs", "load_latest_params",
           "gc_checkpoints"]

_EPOCH_RE_T = r"^%s-(\d{4,})\.params$"


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params,
                    remove_amp_cast=True):
    """ref: model.py save_checkpoint. Atomic, and the prefix's directory
    is created if missing (a checkpoint callback must not crash the run
    because the output dir wasn't pre-made)."""
    from .observability import trace as _trace
    with _trace.span("ckpt_commit", prefix=prefix, epoch=int(epoch)):
        d = os.path.dirname(prefix)
        if d:
            os.makedirs(d, exist_ok=True)
        if symbol is not None:
            symbol.save(f"{prefix}-symbol.json")
        save_dict = {f"arg:{k}": v for k, v in arg_params.items()}
        save_dict.update({f"aux:{k}": v for k, v in aux_params.items()})
        nd.save(f"{prefix}-{epoch:04d}.params", save_dict)


def load_params(prefix, epoch):
    """ref: model.py load_params → (arg_params, aux_params)."""
    loaded = nd.load(f"{prefix}-{epoch:04d}.params")
    arg_params, aux_params = {}, {}
    for k, v in loaded.items():
        kind, _, name = k.partition(":")
        if kind == "arg":
            arg_params[name] = v
        elif kind == "aux":
            aux_params[name] = v
        else:
            raise MXNetError(f"invalid param key {k!r} (want arg:/aux:)")
    return arg_params, aux_params


def load_checkpoint(prefix, epoch):
    """ref: model.py load_checkpoint → (symbol, arg_params, aux_params)."""
    from . import symbol as sym_mod
    symbol = sym_mod.load(f"{prefix}-symbol.json")
    arg_params, aux_params = load_params(prefix, epoch)
    return symbol, arg_params, aux_params


def list_checkpoint_epochs(prefix):
    """Epoch numbers of every ``prefix-NNNN.params`` on disk, ascending."""
    d, base = os.path.split(prefix)
    pat = re.compile(_EPOCH_RE_T % re.escape(base))
    try:
        names = os.listdir(d or ".")
    except OSError:
        return []
    return sorted(int(m.group(1)) for n in names
                  for m in [pat.match(n)] if m)


def load_latest_params(prefix):
    """The newest epoch checkpoint that actually loads —
    ``(arg_params, aux_params, epoch)`` — or None when none exists.

    A torn or corrupt candidate (CRC/truncation MXNetError from
    ``nd.load``) is skipped with a journaled ``ckpt_fallback`` record
    and the next-newest tried: resume never dies on — and never
    silently trains from — a bad file."""
    for epoch in reversed(list_checkpoint_epochs(prefix)):
        try:
            arg_params, aux_params = load_params(prefix, epoch)
            return arg_params, aux_params, epoch
        except MXNetError as e:
            get_journal().event(
                "ckpt_fallback", prefix=prefix, epoch=epoch,
                file=f"{prefix}-{epoch:04d}.params",
                error=type(e).__name__, detail=str(e)[:300])
    return None


def gc_checkpoints(prefix, keep_last):
    """Keep-last-k retention over ``prefix-NNNN.params`` (+ their
    ``.states`` companions) and sweep crashed-writer tmp litter next to
    the prefix. The symbol file is shared across epochs and kept."""
    if not keep_last or keep_last < 1:
        return []
    removed = []
    for epoch in list_checkpoint_epochs(prefix)[:-keep_last]:
        for suffix in (".params", ".states"):
            path = f"{prefix}-{epoch:04d}{suffix}"
            with contextlib.suppress(OSError):
                os.remove(path)
                removed.append(path)
    from .resilience.atomic import sweep_tmp
    d, base = os.path.split(prefix)
    sweep_tmp(d or ".", prefix=base)
    return removed

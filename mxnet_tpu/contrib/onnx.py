"""ONNX import/export (ref: python/mxnet/contrib/onnx/).

Gated on the ``onnx`` package, which is not bundled in this environment
(zero egress, no pip). The conversion seams are in place:

- export walks the Symbol DAG (mxnet_tpu.symbol.Symbol._topo) — the same
  node list the reference's MXNetGraph.create_onnx_graph_proto consumes;
- import maps ONNX nodes onto the op registry by name.

When ``onnx`` is installed, ``export_model``/``import_model`` run; without
it they raise this documented gate instead of failing deep inside.
"""
from __future__ import annotations

from ..base import MXNetError

__all__ = ["export_model", "import_model", "get_model_metadata"]

# ONNX op_type → registry op + param adapter, used when onnx is present
_IMPORT_MAP = {
    "Gemm": "FullyConnected",
    "Conv": "Convolution",
    "BatchNormalization": "BatchNorm",
    "Relu": "relu",
    "Sigmoid": "sigmoid",
    "Tanh": "tanh",
    "Softmax": "softmax",
    "MaxPool": "Pooling",
    "AveragePool": "Pooling",
    "Reshape": "reshape",
    "Concat": "Concat",
    "Add": "elemwise_add",
    "Mul": "elemwise_mul",
    "MatMul": "dot",
    "Dropout": "Dropout",
    "Flatten": "Flatten",
}


def _require_onnx():
    try:
        import onnx  # noqa: F401
        return onnx
    except ImportError:
        raise MXNetError(
            "the `onnx` package is not available in this environment "
            "(no network/pip). ONNX interchange is gated on it; the "
            "native checkpoint formats (-symbol.json + .params via "
            "mx.model.save_checkpoint / HybridBlock.export) cover "
            "serialization, and the op mapping table "
            "(mxnet_tpu.contrib.onnx._IMPORT_MAP) is ready for when "
            "onnx is installed.") from None


def export_model(sym, params, input_shape, input_type=None,
                 onnx_file_path="model.onnx", verbose=False):
    """ref: contrib/onnx/mx2onnx export_model."""
    onnx = _require_onnx()
    raise MXNetError("onnx runtime found but the exporter is not complete "
                     "in this round; use -symbol.json/.params checkpoints")


def import_model(model_file):
    """ref: contrib/onnx/onnx2mx import_model."""
    onnx = _require_onnx()
    raise MXNetError("onnx runtime found but the importer is not complete "
                     "in this round")


def get_model_metadata(model_file):
    onnx = _require_onnx()
    model = onnx.load(model_file)
    graph = model.graph
    return {
        "input_tensor_data": [(i.name, tuple(
            d.dim_value for d in i.type.tensor_type.shape.dim))
            for i in graph.input],
        "output_tensor_data": [(o.name, tuple(
            d.dim_value for d in o.type.tensor_type.shape.dim))
            for o in graph.output],
    }

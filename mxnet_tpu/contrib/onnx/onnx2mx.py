"""ONNX graph -> Symbol conversion (ref: python/mxnet/contrib/onnx/
onnx2mx/_op_translations.py). Returns (sym, arg_params, aux_params) like
the reference's import_model; the importer registry is open (@onnx2mx)."""
from __future__ import annotations

import numpy as np

from ...base import MXNetError

_IMPORTERS = {}


def onnx2mx(op_type):
    def deco(fn):
        _IMPORTERS[op_type] = fn
        return fn
    return deco


class _Ctx:
    def __init__(self, use_count=None):
        self.tensors = {}       # tensor name -> Symbol
        self.params = {}        # param name -> np.ndarray
        self.aux_names = set()
        self.use_count = use_count or {}
        # names whose value is a TRUE constant: Constant-node outputs and
        # values folded from them. Graph initializers are deliberately
        # NOT in here — they are the rebindable arg_params (trained
        # weights), and folding through them bakes the ORIGINAL weights
        # into derived constants that a later re-bind silently misses
        # (ADVICE r5); see import_graph's fold gate.
        self.const_names = set()

    def sym(self, name):
        if name not in self.tensors:
            raise MXNetError(f"ONNX import: undefined tensor {name!r} "
                             f"(graph not topologically ordered?)")
        return self.tensors[name]

    def const_value(self, name):
        """The numpy value behind an initializer input (e.g. Reshape's
        shape). Non-destructive: initializers the rebuilt graph no longer
        references are filtered out at the end of import_graph."""
        if name not in self.params:
            raise MXNetError(
                f"ONNX import: input {name!r} must be a constant "
                f"initializer for this op")
        return self.params[name]

    def transform_param(self, name, fn):
        """Apply a value transform (transpose/scale) to an initializer.
        A shared initializer (used by several nodes) is copied under a
        fresh name so other consumers see the original value; returns the
        name to reference."""
        if self.use_count.get(name, 1) > 1:
            new = name
            i = 1
            while new in self.params:
                new = f"{name}__t{i}"
                i += 1
            self.params[new] = fn(self.params[name])
            from ...symbol import var
            self.tensors[new] = var(new)
            return new
        self.params[name] = fn(self.params[name])
        return name


def _sym_mod():
    from ... import symbol
    return symbol


def _sympair(pads, op):
    pads = list(pads or [])
    if not pads:
        return None
    half = len(pads) // 2
    begin, end = pads[:half], pads[half:]
    if begin != end:
        raise MXNetError(f"ONNX import: asymmetric pads {pads} not "
                         f"supported for {op}")
    return tuple(begin)


@onnx2mx("Conv")
def _conv(node, ins, attrs, ctx):
    sym = _sym_mod()
    wname = node["inputs"][1]
    if wname not in ctx.params:
        raise MXNetError("ONNX import: Conv weight must be an initializer")
    wshape = ctx.params[wname].shape
    kernel = tuple(attrs.get("kernel_shape") or wshape[2:])
    return sym.Convolution(
        *ins, kernel=kernel,
        stride=tuple(attrs.get("strides") or (1,) * len(kernel)),
        dilate=tuple(attrs.get("dilations") or (1,) * len(kernel)),
        pad=_sympair(attrs.get("pads"), "Conv") or (0,) * len(kernel),
        num_filter=int(wshape[0]),
        num_group=int(attrs.get("group", 1)),
        no_bias=len(ins) < 3, name=node.get("name") or None)


@onnx2mx("Gemm")
def _gemm(node, ins, attrs, ctx):
    sym = _sym_mod()
    if int(attrs.get("transA", 0)):
        raise MXNetError("ONNX import: Gemm transA=1 unsupported")
    wname = node["inputs"][1]
    if wname not in ctx.params:
        raise MXNetError("ONNX import: Gemm B must be an initializer")
    alpha = float(attrs.get("alpha", 1.0))
    trans_b = int(attrs.get("transB", 0))
    if not trans_b or alpha != 1.0:
        wname = ctx.transform_param(
            wname, lambda w: (w if trans_b
                              else np.ascontiguousarray(w.T)) * alpha)
    w = ctx.params[wname]
    beta = float(attrs.get("beta", 1.0))
    bias = []
    if len(node["inputs"]) > 2 and node["inputs"][2]:
        # C omitted via empty-string input name is legal ONNX
        bname = node["inputs"][2]
        if beta != 1.0 and bname in ctx.params:
            bname = ctx.transform_param(bname, lambda b: b * beta)
        bias = [ctx.sym(bname)]
    return sym.FullyConnected(ins[0], ctx.sym(wname), *bias,
                              num_hidden=int(w.shape[0]),
                              no_bias=not bias, flatten=True,
                              name=node.get("name") or None)


@onnx2mx("MatMul")
def _matmul(node, ins, attrs, ctx):
    sym = _sym_mod()
    wname = node["inputs"][1]
    if wname in ctx.params and ctx.params[wname].ndim == 2:
        wname = ctx.transform_param(
            wname, lambda w: np.ascontiguousarray(w.T))
        return sym.FullyConnected(
            ins[0], ctx.sym(wname),
            num_hidden=int(ctx.params[wname].shape[0]), no_bias=True,
            flatten=False, name=node.get("name") or None)
    # general case: ONNX MatMul is numpy-matmul (batched over leading
    # dims) — batch_dot here is jnp.matmul, the exact semantics
    return sym.batch_dot(*ins, name=node.get("name") or None)


@onnx2mx("BatchNormalization")
def _bn(node, ins, attrs, ctx):
    sym = _sym_mod()
    for nm in node["inputs"][3:5]:
        ctx.aux_names.add(nm)
    return sym.BatchNorm(*ins, eps=float(attrs.get("epsilon", 1e-5)),
                         momentum=float(attrs.get("momentum", 0.9)),
                         fix_gamma=False, use_global_stats=False,
                         name=node.get("name") or None)


for _onnx, _act in [("Relu", "relu"), ("Sigmoid", "sigmoid"),
                    ("Tanh", "tanh"), ("Softplus", "softrelu"),
                    ("Softsign", "softsign")]:
    def _make_act(act_type):
        def conv(node, ins, attrs, ctx):
            return _sym_mod().Activation(ins[0], act_type=act_type,
                                         name=node.get("name") or None)
        return conv
    _IMPORTERS[_onnx] = _make_act(_act)

for _onnx, _mx in [("Exp", "exp"), ("Log", "log"), ("Sqrt", "sqrt"),
                   ("Abs", "abs"), ("Neg", "negative"), ("Erf", "erf"),
                   ("Floor", "floor"), ("Ceil", "ceil")]:
    def _make_unary(mx_name):
        def conv(node, ins, attrs, ctx):
            return getattr(_sym_mod(), mx_name)(
                ins[0], name=node.get("name") or None)
        return conv
    _IMPORTERS[_onnx] = _make_unary(_mx)

for _onnx, _mx in [("Add", "broadcast_add"), ("Sub", "broadcast_sub"),
                   ("Mul", "broadcast_mul"), ("Div", "broadcast_div"),
                   ("Max", "broadcast_maximum"),
                   ("Min", "broadcast_minimum")]:
    def _make_binary(mx_name):
        def conv(node, ins, attrs, ctx):
            return getattr(_sym_mod(), mx_name)(
                ins[0], ins[1], name=node.get("name") or None)
        return conv
    _IMPORTERS[_onnx] = _make_binary(_mx)


def _pool(node, ins, attrs, ctx, ptype, global_pool):
    sym = _sym_mod()
    if global_pool:
        return sym.Pooling(ins[0], kernel=(1, 1), pool_type=ptype,
                           global_pool=True,
                           name=node.get("name") or None)
    kernel = tuple(attrs["kernel_shape"])
    return sym.Pooling(
        ins[0], kernel=kernel, pool_type=ptype,
        stride=tuple(attrs.get("strides") or (1,) * len(kernel)),
        pad=_sympair(attrs.get("pads"), "Pool") or (0,) * len(kernel),
        pooling_convention="full" if int(attrs.get("ceil_mode", 0))
        else "valid",
        count_include_pad=bool(attrs.get("count_include_pad", 0)),
        name=node.get("name") or None)


_IMPORTERS["MaxPool"] = lambda n, i, a, c: _pool(n, i, a, c, "max", False)
_IMPORTERS["AveragePool"] = lambda n, i, a, c: _pool(n, i, a, c, "avg",
                                                     False)
_IMPORTERS["GlobalMaxPool"] = lambda n, i, a, c: _pool(n, i, a, c, "max",
                                                       True)
_IMPORTERS["GlobalAveragePool"] = lambda n, i, a, c: _pool(n, i, a, c,
                                                           "avg", True)


@onnx2mx("Flatten")
def _flatten(node, ins, attrs, ctx):
    axis = int(attrs.get("axis", 1))
    if axis != 1:
        raise MXNetError(f"ONNX import: Flatten axis={axis} unsupported")
    return _sym_mod().Flatten(ins[0], name=node.get("name") or None)


@onnx2mx("Softmax")
def _softmax(node, ins, attrs, ctx):
    return _sym_mod().softmax(ins[0], axis=int(attrs.get("axis", -1)),
                              name=node.get("name") or None)


@onnx2mx("LogSoftmax")
def _log_softmax(node, ins, attrs, ctx):
    return _sym_mod().log_softmax(ins[0], axis=int(attrs.get("axis", -1)),
                                  name=node.get("name") or None)


@onnx2mx("Reshape")
def _reshape(node, ins, attrs, ctx):
    shape = tuple(int(s) for s in ctx.const_value(node["inputs"][1]))
    return _sym_mod().reshape(ins[0], shape=shape,
                              name=node.get("name") or None)


@onnx2mx("Transpose")
def _transpose(node, ins, attrs, ctx):
    return _sym_mod().transpose(ins[0],
                                axes=tuple(attrs.get("perm") or ()),
                                name=node.get("name") or None)


@onnx2mx("Concat")
def _concat(node, ins, attrs, ctx):
    return _sym_mod().Concat(*ins, dim=int(attrs.get("axis", 1)),
                             name=node.get("name") or None)


@onnx2mx("Clip")
def _clip(node, ins, attrs, ctx):
    lo = attrs.get("min")
    hi = attrs.get("max")
    if lo is None and len(node["inputs"]) > 1 and node["inputs"][1]:
        lo = float(ctx.const_value(node["inputs"][1]))
    if hi is None and len(node["inputs"]) > 2 and node["inputs"][2]:
        hi = float(ctx.const_value(node["inputs"][2]))
    # ONNX spec: absent bound means unbounded on that side
    lo = float(lo) if lo is not None else float(np.finfo(np.float32).min)
    hi = float(hi) if hi is not None else float(np.finfo(np.float32).max)
    return _sym_mod().clip(ins[0], a_min=lo, a_max=hi,
                           name=node.get("name") or None)


@onnx2mx("LeakyRelu")
def _leaky(node, ins, attrs, ctx):
    return _sym_mod().LeakyReLU(ins[0], act_type="leaky",
                                slope=float(attrs.get("alpha", 0.01)),
                                name=node.get("name") or None)


@onnx2mx("Elu")
def _elu(node, ins, attrs, ctx):
    return _sym_mod().LeakyReLU(ins[0], act_type="elu",
                                slope=float(attrs.get("alpha", 1.0)),
                                name=node.get("name") or None)


@onnx2mx("ReduceMean")
def _reduce_mean(node, ins, attrs, ctx):
    return _sym_mod().mean(ins[0], axis=tuple(attrs.get("axes") or ()),
                           keepdims=bool(attrs.get("keepdims", 1)),
                           name=node.get("name") or None)


@onnx2mx("Dropout")
def _dropout(node, ins, attrs, ctx):
    return ins[0]                 # inference identity


@onnx2mx("Identity")
def _identity(node, ins, attrs, ctx):
    return ins[0]


# ONNX TensorProto.DataType code -> dtype name (one table for the Cast
# importer and the fold path)
_ONNX_DT = {1: "float32", 6: "int32", 7: "int64", 9: "bool",
            10: "float16", 11: "float64", 16: "bfloat16"}


@onnx2mx("Cast")
def _cast(node, ins, attrs, ctx):
    to = _ONNX_DT.get(int(attrs.get("to", 1)))
    if to is None:
        raise MXNetError(f"ONNX import: Cast to {attrs.get('to')} "
                         f"unsupported")
    # 64-bit requests under default jax resolve at EXECUTION time in the
    # shared Cast op (ops/elemwise.py _effective_dtype) — nothing baked
    # into the imported graph, and x64 runs keep true int64/float64
    return _sym_mod().cast(ins[0], dtype=to,
                           name=node.get("name") or None)


@onnx2mx("Gather")
def _gather(node, ins, attrs, ctx):
    return _sym_mod().take(ins[0], ins[1],
                           axis=int(attrs.get("axis", 0)),
                           name=node.get("name") or None)


@onnx2mx("LayerNormalization")
def _layer_normalization(node, ins, attrs, ctx):
    if len(ins) > 2:
        beta = ins[2]
    else:
        # bias B is optional in ONNX: synthesize zeros shaped like scale
        sname = node["inputs"][1]
        if sname not in ctx.params:
            raise MXNetError("ONNX import: no-bias LayerNormalization "
                             "needs Scale as an initializer to size the "
                             "zero bias")
        bname = f"{node.get('name') or sname}_zero_bias"
        ctx.params[bname] = np.zeros_like(np.asarray(ctx.params[sname]))
        from ...symbol import var
        ctx.tensors[bname] = var(bname)
        beta = ctx.tensors[bname]
    return _sym_mod().LayerNorm(
        ins[0], ins[1], beta, axis=int(attrs.get("axis", -1)),
        eps=float(attrs.get("epsilon", 1e-5)),
        name=node.get("name") or None)


def _axes_arg(node, ins, attrs, ctx, input_idx):
    """opset-13 moved Unsqueeze/Squeeze axes from attr to input."""
    if len(node["inputs"]) > input_idx and node["inputs"][input_idx]:
        return [int(a) for a in
                np.asarray(ctx.const_value(
                    node["inputs"][input_idx])).ravel()]
    a = attrs.get("axes")
    return None if a is None else [int(v) for v in a]


@onnx2mx("Unsqueeze")
def _unsqueeze(node, ins, attrs, ctx):
    axes = _axes_arg(node, ins, attrs, ctx, 1)
    s = ins[0]
    for ax in sorted(axes):
        s = _sym_mod().expand_dims(s, axis=ax)
    return s


@onnx2mx("Squeeze")
def _squeeze(node, ins, attrs, ctx):
    axes = _axes_arg(node, ins, attrs, ctx, 1)
    return _sym_mod().squeeze(
        ins[0], axis=tuple(axes) if axes is not None else None,
        name=node.get("name") or None)


@onnx2mx("Slice")
def _slice(node, ins, attrs, ctx):
    names = node["inputs"]
    if len(names) >= 3:           # opset-10+: starts/ends[/axes] inputs
        starts = [int(v) for v in
                  np.asarray(ctx.const_value(names[1])).ravel()]
        ends = [int(v) for v in
                np.asarray(ctx.const_value(names[2])).ravel()]
        axes = ([int(v) for v in
                 np.asarray(ctx.const_value(names[3])).ravel()]
                if len(names) > 3 and names[3]
                else list(range(len(starts))))
        if len(names) > 4 and names[4]:
            steps = [int(v) for v in
                     np.asarray(ctx.const_value(names[4])).ravel()]
            if any(s != 1 for s in steps):
                # strided slice: representable when axes are the leading
                # dims in order (the form our exporter emits)
                if list(axes) != list(range(len(axes))):
                    raise MXNetError("ONNX import: strided Slice over "
                                     "non-leading axes unsupported")
                big = np.iinfo(np.int64).max
                return _sym_mod().slice(
                    ins[0],
                    begin=tuple(None if abs(b) >= big // 2 else b
                                for b in starts),
                    end=tuple(None if abs(e) >= big // 2 else e
                              for e in ends),
                    step=tuple(steps), name=node.get("name") or None)
    else:                          # opset-1 attrs form
        starts = [int(v) for v in attrs.get("starts", [])]
        ends = [int(v) for v in attrs.get("ends", [])]
        axes = [int(v) for v in
                attrs.get("axes", range(len(starts)))]
    big = np.iinfo(np.int64).max
    s = ins[0]
    for ax, b, e in zip(axes, starts, ends):
        s = _sym_mod().slice_axis(s, axis=ax, begin=b,
                                  end=None if e >= big // 2 else e)
    return s


@onnx2mx("Split")
def _split(node, ins, attrs, ctx):
    names = node["inputs"]
    axis = int(attrs.get("axis", 0))
    if len(names) > 1 and names[1]:
        sizes = [int(v) for v in
                 np.asarray(ctx.const_value(names[1])).ravel()]
    elif attrs.get("split"):
        sizes = [int(v) for v in attrs["split"]]
    else:
        raise MXNetError("ONNX import: Split without sizes needs the "
                         "output count — unsupported")
    outs = []
    off = 0
    for sz in sizes:
        outs.append(_sym_mod().slice_axis(ins[0], axis=axis, begin=off,
                                          end=off + sz))
        off += sz
    return outs


@onnx2mx("Constant")
def _constant(node, ins, attrs, ctx):
    val = attrs.get("value")
    if val is None:
        raise MXNetError("ONNX import: Constant without value")
    name = node["outputs"][0]
    ctx.params[name] = np.asarray(val)
    ctx.const_names.add(name)
    return _sym_mod().var(name)


@onnx2mx("Pow")
def _pow(node, ins, attrs, ctx):
    return _sym_mod().broadcast_power(ins[0], ins[1],
                                      name=node.get("name") or None)


@onnx2mx("Equal")
def _equal(node, ins, attrs, ctx):
    return _sym_mod().broadcast_equal(ins[0], ins[1],
                                      name=node.get("name") or None)


@onnx2mx("Where")
def _where(node, ins, attrs, ctx):
    # ops/tensor.py `where` is jnp.where underneath: 3-operand numpy
    # broadcasting, inf/NaN-safe in the unselected branch (an arithmetic
    # decomposition like c*a+(1-c)*b would turn 0*inf into NaN — the
    # standard ConstantOfShape(-inf) mask pattern)
    return _sym_mod().where(ins[0], ins[1], ins[2],
                            name=node.get("name") or None)


@onnx2mx("ConstantOfShape")
def _constant_of_shape(node, ins, attrs, ctx):
    # a constant shape input is folded before this importer runs (see
    # _FOLDABLE); reaching here means the shape is runtime-computed,
    # which a static-shape XLA graph cannot express
    raise MXNetError(
        "ONNX import: ConstantOfShape with a non-constant shape input "
        f"(node {node.get('name')!r}) — dynamic output shapes are not "
        "representable; re-export with do_constant_folding=True")


@onnx2mx("Expand")
def _expand(node, ins, attrs, ctx):
    shape = tuple(int(v) for v in
                  np.asarray(ctx.const_value(node["inputs"][1])).ravel())
    # ONNX Expand = RIGHT-aligned numpy broadcasting (rank may differ in
    # either direction, target dims of 1 keep the input dim). Multiply by
    # a ones-constant of the target shape — jnp's broadcasting rules do
    # the alignment exactly; float32 ones promote integer inputs, an
    # accepted divergence (integer Expands are shape plumbing and fold).
    ones_name = node["outputs"][0] + "__expand_ones"
    ctx.params[ones_name] = np.ones(shape, np.float32)
    from ...symbol import var
    ctx.tensors[ones_name] = var(ones_name)
    return _sym_mod().broadcast_mul(ins[0], ctx.tensors[ones_name],
                                    name=node.get("name") or None)


# ---------------------------------------------------------------------------
# constant folding: torch exports compute shape/mask helpers with chains of
# small ops over Constant nodes (expand lowers to Where(Equal(size, -1),
# onnx_shape, size) etc.). When EVERY input of a node is a TRUE constant —
# a Constant-node output or a fold product of those, never a graph
# initializer — evaluate it with numpy at import time: the graph the
# executor sees is what do_constant_folding=True would have produced.
# Initializer-rooted chains import as real ops instead: an initializer is
# a rebindable parameter (sym.eval / rebound arg_params may supply NEW
# values), and a fold through it would silently keep the import-time
# weights baked into the derived constant (ADVICE r5).
# ---------------------------------------------------------------------------

def _fold_numpy(op, vals, attrs):
    """Returns the folded numpy value, or None if this op can't fold."""
    if op == "Mul":
        return vals[0] * vals[1]
    if op == "Add":
        return vals[0] + vals[1]
    if op == "Sub":
        return vals[0] - vals[1]
    if op == "Div":
        a, b = np.asarray(vals[0]), np.asarray(vals[1])
        if np.issubdtype(a.dtype, np.integer):
            # ONNX int Div truncates toward zero; stay in integer math
            # (a float64 round trip loses exactness beyond 2**53)
            return (np.sign(a) * np.sign(b)
                    * (np.abs(a) // np.abs(b))).astype(a.dtype)
        return a / b
    if op == "Pow":
        return np.power(vals[0], vals[1])
    if op == "Sqrt":
        return np.sqrt(vals[0])
    if op == "Neg":
        return -vals[0]
    if op == "Equal":
        return np.equal(vals[0], vals[1])
    if op == "Where":
        return np.where(vals[0], vals[1], vals[2])
    if op == "ConstantOfShape":
        fill = attrs.get("value")
        fill = np.asarray(fill).ravel()[0] if fill is not None \
            else np.float32(0)
        return np.full(tuple(int(v) for v in np.ravel(vals[0])), fill)
    if op == "Expand":
        target = tuple(int(v) for v in np.ravel(vals[1]))
        out_shape = np.broadcast_shapes(np.asarray(vals[0]).shape, target)
        return np.broadcast_to(vals[0], out_shape).copy()
    if op == "Cast":
        name = _ONNX_DT.get(int(attrs.get("to", 1)))
        if name is None:
            return None
        try:
            dt = np.dtype(name)
        except TypeError:                    # bfloat16 needs ml_dtypes
            import ml_dtypes
            dt = np.dtype(getattr(ml_dtypes, name))
        return np.asarray(vals[0]).astype(dt)
    if op == "Unsqueeze":
        axes = ([int(v) for v in np.ravel(vals[1])] if len(vals) > 1
                else [int(v) for v in attrs.get("axes", [])])
        out = np.asarray(vals[0])
        for ax in sorted(axes):
            out = np.expand_dims(out, ax)
        return out
    if op == "Squeeze":
        axes = ([int(v) for v in np.ravel(vals[1])] if len(vals) > 1
                else [int(v) for v in attrs.get("axes", [])])
        return np.squeeze(np.asarray(vals[0]),
                          axis=tuple(axes) if axes else None)
    if op == "Concat":
        return np.concatenate(vals, axis=int(attrs.get("axis", 0)))
    if op == "Gather":
        return np.take(vals[0], np.asarray(vals[1]).astype(np.int64),
                       axis=int(attrs.get("axis", 0)))
    if op == "Reshape":
        shp = [int(v) for v in np.ravel(vals[1])]
        src = np.asarray(vals[0])
        shp = [src.shape[i] if d == 0 else d for i, d in enumerate(shp)]
        return src.reshape(shp)
    return None


_FOLDABLE = {"Mul", "Add", "Sub", "Div", "Pow", "Sqrt", "Neg", "Equal",
             "Where", "ConstantOfShape", "Expand", "Cast", "Unsqueeze",
             "Squeeze", "Concat", "Gather", "Reshape"}


def import_graph(model):
    """dict-proto model -> (sym, arg_params {name: np}, aux_params)."""
    from ...symbol import Group, var
    g = model["graph"]
    use_count = {}
    for node in g["nodes"]:
        for n in node["inputs"]:
            use_count[n] = use_count.get(n, 0) + 1
    ctx = _Ctx(use_count)
    for t in g.get("initializers", []):
        ctx.params[t["name"]] = np.asarray(t["data"])
        ctx.tensors[t["name"]] = var(t["name"])
    for vi in g["inputs"]:
        if vi["name"] not in ctx.tensors:
            ctx.tensors[vi["name"]] = var(vi["name"])
    for node in g["nodes"]:
        op_type = node["op_type"]
        in_names = [n for n in node["inputs"] if n]
        if op_type in _FOLDABLE and \
                all(n in ctx.const_names for n in in_names):
            folded = _fold_numpy(op_type, [ctx.params[n] for n in in_names],
                                 node.get("attrs", {}))
            if folded is not None:
                for nm in node["outputs"]:
                    ctx.params[nm] = np.asarray(folded)
                    ctx.const_names.add(nm)
                    ctx.tensors[nm] = var(nm)
                continue
        imp = _IMPORTERS.get(op_type)
        if imp is None:
            raise MXNetError(
                f"ONNX import: no converter for op_type "
                f"{op_type!r} (node {node.get('name')!r}); "
                f"register one with "
                f"@mxnet_tpu.contrib.onnx.onnx2mx.onnx2mx")
        ins = [ctx.sym(n) for n in node["inputs"] if n]
        out_syms = imp(node, ins, node.get("attrs", {}), ctx)
        outs = node["outputs"]
        if not isinstance(out_syms, (list, tuple)):
            out_syms = [out_syms]
        for nm, s in zip(outs, out_syms):
            ctx.tensors[nm] = s
    out_names = [o["name"] for o in g["outputs"]]
    outs = [ctx.sym(n) for n in out_names]
    sym = outs[0] if len(outs) == 1 else Group(outs)
    # split params by BN-aux slots; keep only tensors the rebuilt graph
    # still references (constant-only inputs like Reshape shapes drop out
    # here naturally — they never become graph variables)
    ref_args = set(sym.list_arguments())
    ref_aux = set(sym.list_auxiliary_states())
    arg_params = {k: v for k, v in ctx.params.items()
                  if k in ref_args and k not in ctx.aux_names}
    aux_params = {k: v for k, v in ctx.params.items()
                  if k in ref_aux or (k in ctx.aux_names
                                      and k in ref_aux | ref_args)}
    return sym, arg_params, aux_params

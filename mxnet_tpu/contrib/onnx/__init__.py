"""ONNX import/export (ref: python/mxnet/contrib/onnx/).

Fully self-contained: the protobuf wire codec in ``proto.py`` reads and
writes real ``.onnx`` bytes without the ``onnx`` pip (unavailable in this
environment), so exported files interoperate with the official
onnx/onnxruntime stack and standard ONNX files import. Conversion is a
pure data transform over the dict-proto representation — see
``mx2onnx.export_graph`` / ``onnx2mx.import_graph`` — with open converter
registries like the reference's ``@mx_op.register`` pattern.
"""
from __future__ import annotations

import json

import numpy as np

from ...base import MXNetError
from . import mx2onnx, onnx2mx, proto
from .mx2onnx import export_graph
from .onnx2mx import import_graph

__all__ = ["export_model", "import_model", "get_model_metadata",
           "export_graph", "import_graph", "mx2onnx", "onnx2mx", "proto"]


def _load_sym_params(sym, params):
    from ... import symbol as sym_mod
    from ...ndarray import NDArray
    if isinstance(sym, str):
        sym = sym_mod.load(sym)
    if isinstance(params, str):
        from ...ndarray import load as nd_load
        params = nd_load(params)      # keys keep their arg:/aux: prefixes
    out = {}
    for k, v in (params or {}).items():
        out[k] = v.asnumpy() if isinstance(v, NDArray) else np.asarray(v)
    return sym, out


def export_model(sym, params, input_shape=None, input_type=None,
                 onnx_file_path="model.onnx", verbose=False,
                 opset_version=13):
    """Export a Symbol (or a '-symbol.json' path) + params (dict or a
    '.params' path) to a real ONNX file. Returns the file path.
    (ref: contrib/onnx/mx2onnx/export_model.py export_model)"""
    sym, params = _load_sym_params(sym, params)
    if input_shape is not None and input_shape and \
            not isinstance(input_shape[0], (tuple, list)):
        input_shape = [input_shape]
    in_types = None
    if input_type is not None:
        if not isinstance(input_type, (list, tuple)):
            input_type = [input_type] * len(input_shape or [()])
        in_types = [np.dtype(t).name for t in input_type]
    model = export_graph(sym, params, in_shapes=input_shape,
                         in_types=in_types)
    model["opset"] = opset_version
    buf = proto.encode_model(model)
    from ...resilience.atomic import atomic_write
    with atomic_write(onnx_file_path, "wb") as f:
        f.write(buf)
    if verbose:
        g = model["graph"]
        print(f"ONNX export: {len(g['nodes'])} nodes, "
              f"{len(g['initializers'])} initializers -> {onnx_file_path}")
    return onnx_file_path


def import_model(model_file):
    """ONNX file (or dict-proto) -> (sym, arg_params, aux_params) with
    NDArray params (ref: contrib/onnx/onnx2mx/import_model.py)."""
    from ...ndarray import array
    if isinstance(model_file, dict):
        model = model_file
    else:
        with open(model_file, "rb") as f:
            model = proto.decode_model(f.read())
    sym, arg_np, aux_np = import_graph(model)
    arg_params = {k: array(v) for k, v in arg_np.items()}
    aux_params = {k: array(v) for k, v in aux_np.items()}
    return sym, arg_params, aux_params


def import_to_gluon(model_file, ctx=None):
    """ONNX file -> SymbolBlock ready for inference
    (ref: contrib/onnx/onnx2mx/import_to_gluon.py)."""
    from ...gluon import SymbolBlock
    from ... import symbol as sym_mod
    sym, arg_params, aux_params = import_model(model_file)
    data_names = [n for n in sym.list_arguments() if n not in arg_params]
    inputs = [sym_mod.var(n) for n in data_names]
    from ...context import cpu, current_context
    ctx = ctx if ctx is not None else current_context()
    net = SymbolBlock(sym, inputs)
    params = net.collect_params()
    for name, arr in list(arg_params.items()) + list(aux_params.items()):
        if name in params:
            params[name]._load_init(arr, ctx)
    return net


def get_model_metadata(model_file):
    """Input/output names+shapes of an ONNX file — parsed with the
    built-in codec, no onnx pip needed."""
    with open(model_file, "rb") as f:
        model = proto.decode_model(f.read())
    g = model["graph"]
    init_names = {t["name"] for t in g.get("initializers", [])}
    return {
        "input_tensor_data": [(i["name"], tuple(i.get("shape", ())))
                              for i in g["inputs"]
                              if i["name"] not in init_names],
        "output_tensor_data": [(o["name"], tuple(o.get("shape", ())))
                               for o in g["outputs"]],
    }

"""Symbol-DAG -> ONNX graph conversion (ref: python/mxnet/contrib/onnx/
mx2onnx/_op_translations.py). Each MX op converter returns a list of ONNX
node dicts; the registry is open (@mx2onnx) so new ops slot in the same
way the reference's @mx_op.register does."""
from __future__ import annotations

import numpy as np

from ...base import MXNetError

_EXPORTERS = {}


def mx2onnx(op_name):
    def deco(fn):
        _EXPORTERS[op_name] = fn
        return fn
    return deco


class _Ctx:
    """Per-export state: tensor naming, generated initializers."""

    def __init__(self, params):
        self.params = params
        self.extra_initializers = []
        self.renames = {}        # identity-folded tensors (Dropout, etc.)
        self.shape_of = {}       # tensor name -> static shape (if inferred)
        self._uid = 0

    def tname(self, sym):
        node = sym._node
        if node.op is None:
            name = node.name
        elif node.num_outputs == 1:
            name = node.name
        else:
            name = f"{node.name}_out{sym._index}"
        return self.renames.get(name, name)

    def out_name(self, node, index=0):
        if node.num_outputs == 1:
            return node.name
        return f"{node.name}_out{index}"

    def add_initializer(self, hint, arr):
        self._uid += 1
        name = f"_{hint}_{self._uid}"
        self.extra_initializers.append(
            {"name": name, "data": np.asarray(arr)})
        return name


def _pads(pad):
    pad = tuple(pad or ())
    return list(pad) + list(pad)          # symmetric begin+end


@mx2onnx("Convolution")
def _conv(node, ins, out, attrs, ctx):
    onnx_attrs = {"kernel_shape": list(attrs["kernel"]),
                  "strides": list(attrs.get("stride") or
                                  (1,) * len(attrs["kernel"])),
                  "dilations": list(attrs.get("dilate") or
                                    (1,) * len(attrs["kernel"])),
                  "pads": _pads(attrs.get("pad") or
                                (0,) * len(attrs["kernel"])),
                  "group": int(attrs.get("num_group") or 1)}
    return [{"op_type": "Conv", "name": node.name, "inputs": ins,
             "outputs": [out], "attrs": onnx_attrs}]


@mx2onnx("FullyConnected")
def _fc(node, ins, out, attrs, ctx):
    nodes = []
    data = ins[0]
    if attrs.get("flatten", True):
        flat = f"{node.name}_flat"
        nodes.append({"op_type": "Flatten", "name": flat, "inputs": [data],
                      "outputs": [flat], "attrs": {"axis": 1}})
        data = flat
        gemm_in = [data, ins[1]] + (ins[2:]
                                    if not attrs.get("no_bias") else [])
        nodes.append({"op_type": "Gemm", "name": node.name,
                      "inputs": gemm_in, "outputs": [out],
                      "attrs": {"alpha": 1.0, "beta": 1.0, "transA": 0,
                                "transB": 1}})
        return nodes
    # flatten=False keeps leading dims (possibly rank>2): ONNX Gemm is
    # 2-D-only, so emit MatMul(x, W^T) [+ Add bias] — imports back as
    # FullyConnected(flatten=False) via the MatMul importer
    if ins[1] in ctx.params:
        wt = ctx.add_initializer(
            f"{ins[1]}_T",
            np.ascontiguousarray(np.asarray(ctx.params[ins[1]]).T))
    else:
        wt = f"{node.name}_wT"
        nodes.append({"op_type": "Transpose", "name": wt,
                      "inputs": [ins[1]], "outputs": [wt],
                      "attrs": {"perm": [1, 0]}})
    mm_out = out if attrs.get("no_bias") else f"{node.name}_mm"
    nodes.append({"op_type": "MatMul", "name": f"{node.name}_mm",
                  "inputs": [data, wt], "outputs": [mm_out], "attrs": {}})
    if not attrs.get("no_bias"):
        nodes.append({"op_type": "Add", "name": node.name,
                      "inputs": [mm_out, ins[2]], "outputs": [out],
                      "attrs": {}})
    return nodes


@mx2onnx("BatchNorm")
def _bn(node, ins, out, attrs, ctx):
    if attrs.get("fix_gamma"):
        gname = ins[1]
        if gname in ctx.params:
            ins = list(ins)
            ins[1] = ctx.add_initializer(
                "ones", np.ones_like(np.asarray(ctx.params[gname])))
    act = attrs.get("act_type")
    if act in ("identity", "None"):     # fused no-op epilogue: plain BN
        act = None
    bn_out = f"{node.name}_bn" if act else out
    nodes = [{"op_type": "BatchNormalization", "name": node.name,
              "inputs": list(ins), "outputs": [bn_out],
              "attrs": {"epsilon": float(attrs.get("eps", 1e-3)),
                        "momentum": float(attrs.get("momentum", 0.9))}}]
    if act:
        # fused normalize-epilogue activation (pallas tier) decomposes
        # back to BN + plain activation for ONNX
        nodes += _act_chain(f"{node.name}_act", bn_out, out, act, ctx)
    return nodes


_ACT = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
        "softrelu": "Softplus", "softsign": "Softsign"}


@mx2onnx("Activation")
def _act(node, ins, out, attrs, ctx):
    act = attrs.get("act_type", "relu")
    if act not in _ACT:
        raise MXNetError(f"ONNX export: unsupported activation {act}")
    return [{"op_type": _ACT[act], "name": node.name, "inputs": ins,
             "outputs": [out], "attrs": {}}]


for _mx, _onnx in [("relu", "Relu"), ("sigmoid", "Sigmoid"),
                   ("tanh", "Tanh"), ("exp", "Exp"), ("log", "Log"),
                   ("sqrt", "Sqrt"), ("abs", "Abs"), ("negative", "Neg"),
                   ("erf", "Erf"), ("floor", "Floor"), ("ceil", "Ceil")]:
    def _make_unary(onnx_type):
        def conv(node, ins, out, attrs, ctx):
            return [{"op_type": onnx_type, "name": node.name,
                     "inputs": ins, "outputs": [out], "attrs": {}}]
        return conv
    _EXPORTERS[_mx] = _make_unary(_onnx)


@mx2onnx("Pooling")
def _pool(node, ins, out, attrs, ctx):
    ptype = attrs.get("pool_type", "max")
    if ptype not in ("max", "avg"):
        raise MXNetError(f"ONNX export: unsupported pool_type {ptype}")
    if attrs.get("global_pool"):
        op = "GlobalMaxPool" if ptype == "max" else "GlobalAveragePool"
        return [{"op_type": op, "name": node.name, "inputs": ins,
                 "outputs": [out], "attrs": {}}]
    kernel = attrs["kernel"]
    onnx_attrs = {"kernel_shape": list(kernel),
                  "strides": list(attrs.get("stride") or (1,) * len(kernel)),
                  "pads": _pads(attrs.get("pad") or (0,) * len(kernel)),
                  "ceil_mode": int(attrs.get("pooling_convention",
                                             "valid") == "full")}
    if ptype == "avg":
        onnx_attrs["count_include_pad"] = int(
            bool(attrs.get("count_include_pad", True)))
    op = "MaxPool" if ptype == "max" else "AveragePool"
    return [{"op_type": op, "name": node.name, "inputs": ins,
             "outputs": [out], "attrs": onnx_attrs}]


@mx2onnx("Flatten")
def _flatten(node, ins, out, attrs, ctx):
    return [{"op_type": "Flatten", "name": node.name, "inputs": ins,
             "outputs": [out], "attrs": {"axis": 1}}]


for _mx, _onnx in [("elemwise_add", "Add"), ("broadcast_add", "Add"),
                   ("elemwise_sub", "Sub"), ("broadcast_sub", "Sub"),
                   ("elemwise_mul", "Mul"), ("broadcast_mul", "Mul"),
                   ("elemwise_div", "Div"), ("broadcast_div", "Div"),
                   ("broadcast_maximum", "Max"),
                   ("broadcast_minimum", "Min")]:
    def _make_binary(onnx_type):
        def conv(node, ins, out, attrs, ctx):
            return [{"op_type": onnx_type, "name": node.name,
                     "inputs": ins, "outputs": [out], "attrs": {}}]
        return conv
    _EXPORTERS[_mx] = _make_binary(_onnx)


@mx2onnx("softmax")
def _softmax(node, ins, out, attrs, ctx):
    return [{"op_type": "Softmax", "name": node.name, "inputs": ins[:1],
             "outputs": [out], "attrs": {"axis": int(attrs.get("axis",
                                                               -1))}}]


@mx2onnx("log_softmax")
def _logsoftmax(node, ins, out, attrs, ctx):
    return [{"op_type": "LogSoftmax", "name": node.name, "inputs": ins[:1],
             "outputs": [out], "attrs": {"axis": int(attrs.get("axis",
                                                               -1))}}]


@mx2onnx("SoftmaxOutput")
def _softmax_output(node, ins, out, attrs, ctx):
    # inference export: drop the label input (ref: mx2onnx softmax_output)
    return [{"op_type": "Softmax", "name": node.name, "inputs": ins[:1],
             "outputs": [out], "attrs": {"axis": -1}}]


@mx2onnx("Dropout")
def _dropout(node, ins, out, attrs, ctx):
    ctx.renames[out] = ctx.renames.get(ins[0], ins[0])   # inference no-op
    return []


@mx2onnx("identity")
def _identity(node, ins, out, attrs, ctx):
    ctx.renames[out] = ctx.renames.get(ins[0], ins[0])
    return []


@mx2onnx("reshape")
def _reshape(node, ins, out, attrs, ctx):
    shape = tuple(attrs.get("shape") or ())
    if any(s in (-2, -3, -4) for s in shape):
        raise MXNetError("ONNX export: reshape special codes -2/-3/-4 have "
                         "no ONNX equivalent")
    shape_name = ctx.add_initializer("shape",
                                     np.asarray(shape, dtype=np.int64))
    return [{"op_type": "Reshape", "name": node.name,
             "inputs": [ins[0], shape_name], "outputs": [out], "attrs": {}}]


@mx2onnx("transpose")
def _transpose(node, ins, out, attrs, ctx):
    return [{"op_type": "Transpose", "name": node.name, "inputs": ins,
             "outputs": [out],
             "attrs": {"perm": list(attrs.get("axes") or [])}}]


@mx2onnx("Concat")
def _concat(node, ins, out, attrs, ctx):
    return [{"op_type": "Concat", "name": node.name, "inputs": ins,
             "outputs": [out], "attrs": {"axis": int(attrs.get("dim", 1))}}]


@mx2onnx("clip")
def _clip(node, ins, out, attrs, ctx):
    lo = ctx.add_initializer("min", np.float32(attrs.get("a_min")))
    hi = ctx.add_initializer("max", np.float32(attrs.get("a_max")))
    return [{"op_type": "Clip", "name": node.name,
             "inputs": [ins[0], lo, hi], "outputs": [out], "attrs": {}}]


@mx2onnx("LeakyReLU")
def _leaky(node, ins, out, attrs, ctx):
    act = attrs.get("act_type", "leaky")
    if act == "leaky":
        return [{"op_type": "LeakyRelu", "name": node.name,
                 "inputs": ins[:1], "outputs": [out],
                 "attrs": {"alpha": float(attrs.get("slope", 0.25))}}]
    if act == "elu":
        return [{"op_type": "Elu", "name": node.name, "inputs": ins[:1],
                 "outputs": [out],
                 "attrs": {"alpha": float(attrs.get("slope", 0.25))}}]
    if act == "prelu":
        return [{"op_type": "PRelu", "name": node.name, "inputs": ins[:2],
                 "outputs": [out], "attrs": {}}]
    if act == "gelu":
        # exact erf form, decomposed for broad opset compatibility:
        # 0.5 * x * (1 + erf(x / sqrt(2)))
        n = node.name
        inv_sqrt2 = ctx.add_initializer("inv_sqrt2",
                                        np.float32(0.7071067811865476))
        half = ctx.add_initializer("half", np.float32(0.5))
        one = ctx.add_initializer("one", np.float32(1.0))
        return [
            {"op_type": "Mul", "name": f"{n}_scale",
             "inputs": [ins[0], inv_sqrt2], "outputs": [f"{n}_scaled"],
             "attrs": {}},
            {"op_type": "Erf", "name": f"{n}_erf",
             "inputs": [f"{n}_scaled"], "outputs": [f"{n}_erfv"],
             "attrs": {}},
            {"op_type": "Add", "name": f"{n}_add1",
             "inputs": [f"{n}_erfv", one], "outputs": [f"{n}_1perf"],
             "attrs": {}},
            {"op_type": "Mul", "name": f"{n}_mulx",
             "inputs": [ins[0], f"{n}_1perf"], "outputs": [f"{n}_xe"],
             "attrs": {}},
            {"op_type": "Mul", "name": n,
             "inputs": [f"{n}_xe", half], "outputs": [out], "attrs": {}},
        ]
    raise MXNetError(f"ONNX export: LeakyReLU act_type {act} unsupported")


def _act_chain(name, src, out, act, ctx):
    """ONNX nodes applying activation ``act`` to tensor ``src`` -> ``out``
    (the decomposition target for the fused pallas epilogue ops)."""
    simple = {"relu": "Relu", "tanh": "Tanh", "sigmoid": "Sigmoid"}
    if act in simple:
        return [{"op_type": simple[act], "name": name, "inputs": [src],
                 "outputs": [out], "attrs": {}}]
    if act == "gelu":
        # exact erf form: 0.5 * x * (1 + erf(x / sqrt(2)))
        inv_sqrt2 = ctx.add_initializer("inv_sqrt2",
                                        np.float32(0.7071067811865476))
        half = ctx.add_initializer("half", np.float32(0.5))
        one = ctx.add_initializer("one", np.float32(1.0))
        return [
            {"op_type": "Mul", "name": f"{name}_scale",
             "inputs": [src, inv_sqrt2], "outputs": [f"{name}_scaled"],
             "attrs": {}},
            {"op_type": "Erf", "name": f"{name}_erf",
             "inputs": [f"{name}_scaled"], "outputs": [f"{name}_erfv"],
             "attrs": {}},
            {"op_type": "Add", "name": f"{name}_add1",
             "inputs": [f"{name}_erfv", one], "outputs": [f"{name}_1perf"],
             "attrs": {}},
            {"op_type": "Mul", "name": f"{name}_mulx",
             "inputs": [src, f"{name}_1perf"], "outputs": [f"{name}_xe"],
             "attrs": {}},
            {"op_type": "Mul", "name": name,
             "inputs": [f"{name}_xe", half], "outputs": [out], "attrs": {}},
        ]
    raise MXNetError(f"ONNX export: unsupported fused activation {act!r}")


@mx2onnx("_contrib_conv_epilogue")
def _conv_epilogue_onnx(node, ins, out, attrs, ctx):
    """Fused residual epilogue act(x + res) -> Add + activation."""
    act = attrs.get("act_type", "relu")
    if act in (None, "identity"):
        return [{"op_type": "Add", "name": node.name, "inputs": list(ins),
                 "outputs": [out], "attrs": {}}]
    add_out = f"{node.name}_add"
    nodes = [{"op_type": "Add", "name": f"{node.name}_sum",
              "inputs": list(ins), "outputs": [add_out], "attrs": {}}]
    return nodes + _act_chain(node.name, add_out, out, act, ctx)


@mx2onnx("_contrib_matmul_epilogue")
def _matmul_epilogue_onnx(node, ins, out, attrs, ctx):
    """Fused matmul epilogue dropout(act(y + bias)) -> Add + activation
    (dropout, like the plain Dropout op, is an inference no-op)."""
    act = attrs.get("act_type")
    if act in (None, "identity", "None"):
        return [{"op_type": "Add", "name": node.name, "inputs": list(ins),
                 "outputs": [out], "attrs": {}}]
    add_out = f"{node.name}_add"
    nodes = [{"op_type": "Add", "name": f"{node.name}_sum",
              "inputs": list(ins), "outputs": [add_out], "attrs": {}}]
    return nodes + _act_chain(node.name, add_out, out, act, ctx)


@mx2onnx("Embedding")
def _embedding(node, ins, out, attrs, ctx):
    # Gather(weight, int64(indices)) — the indices arrive float in MXNet
    idx64 = f"{node.name}_idx64"
    return [
        {"op_type": "Cast", "name": f"{node.name}_cast",
         "inputs": [ins[0]], "outputs": [idx64],
         "attrs": {"to": 7}},                      # 7 = INT64
        {"op_type": "Gather", "name": node.name,
         "inputs": [ins[1], idx64], "outputs": [out],
         "attrs": {"axis": 0}},
    ]


@mx2onnx("LayerNorm")
def _layer_norm(node, ins, out, attrs, ctx):
    # opset-17 LayerNormalization (x, scale, bias)
    return [{"op_type": "LayerNormalization", "name": node.name,
             "inputs": ins[:3], "outputs": [out],
             "attrs": {"axis": int(attrs.get("axis", -1)),
                       "epsilon": float(attrs.get("eps", 1e-5))}}]


for _mx, _onnx, _rev in [("_mul_scalar", "Mul", False),
                         ("_plus_scalar", "Add", False),
                         ("_minus_scalar", "Sub", False),
                         ("_rminus_scalar", "Sub", True),
                         ("_div_scalar", "Div", False),
                         ("_rdiv_scalar", "Div", True)]:
    def _make_scalar(onnx_type, reverse):
        def conv(node, ins, out, attrs, ctx):
            s = ctx.add_initializer(
                "scalar", np.float32(attrs.get("scalar", 0.0)))
            inputs = [s, ins[0]] if reverse else [ins[0], s]
            return [{"op_type": onnx_type, "name": node.name,
                     "inputs": inputs, "outputs": [out], "attrs": {}}]
        return conv
    _EXPORTERS[_mx] = _make_scalar(_onnx, _rev)


@mx2onnx("expand_dims")
def _expand_dims(node, ins, out, attrs, ctx):
    axes = ctx.add_initializer(
        "axes", np.asarray([int(attrs.get("axis", 0))], np.int64))
    return [{"op_type": "Unsqueeze", "name": node.name,
             "inputs": [ins[0], axes], "outputs": [out], "attrs": {}}]


@mx2onnx("squeeze")
def _squeeze(node, ins, out, attrs, ctx):
    axis = attrs.get("axis")
    inputs = [ins[0]]
    if axis is not None:
        axes = axis if isinstance(axis, (tuple, list)) else [int(axis)]
        inputs.append(ctx.add_initializer(
            "axes", np.asarray(list(axes), np.int64)))
    return [{"op_type": "Squeeze", "name": node.name, "inputs": inputs,
             "outputs": [out], "attrs": {}}]


_INT_MAX = np.iinfo(np.int64).max


@mx2onnx("slice")
def _slice(node, ins, out, attrs, ctx):
    begin = list(attrs.get("begin") or ())
    end = list(attrs.get("end") or ())
    step = list(attrs.get("step") or ())
    steps = [1 if (i >= len(step) or step[i] is None) else int(step[i])
             for i in range(len(begin))]
    # ONNX Slice default bounds flip for negative steps: start clamps to
    # dim-1 via INT64_MAX, and end INT64_MIN means "through index 0"
    starts = [(_INT_MAX if steps[i] < 0 else 0) if b is None else int(b)
              for i, b in enumerate(begin)]
    ends = [(-_INT_MAX - 1 if steps[i] < 0 else _INT_MAX)
            if e is None else int(e) for i, e in enumerate(end)]
    axes = list(range(len(starts)))
    return [{"op_type": "Slice", "name": node.name,
             "inputs": [ins[0],
                        ctx.add_initializer(
                            "starts", np.asarray(starts, np.int64)),
                        ctx.add_initializer(
                            "ends", np.asarray(ends, np.int64)),
                        ctx.add_initializer(
                            "axes", np.asarray(axes, np.int64)),
                        ctx.add_initializer(
                            "steps", np.asarray(steps, np.int64))],
             "outputs": [out], "attrs": {}}]


@mx2onnx("slice_like")
def _slice_like(node, ins, out, attrs, ctx):
    like_shape = ctx.shape_of.get(ins[1])
    if like_shape is None:
        raise MXNetError(
            "ONNX export: slice_like needs shape inference — pass "
            "in_shapes to export (the 'like' tensor's static shape "
            "becomes the Slice ends)")
    axes = attrs.get("axes")
    x_rank = len(ctx.shape_of.get(ins[0], like_shape))
    if axes is None:
        axes = list(range(min(x_rank, len(like_shape))))
    else:
        axes = [int(a) % x_rank
                for a in (axes if isinstance(axes, (tuple, list))
                          else [axes])]
    starts = [0] * len(axes)
    ends = [int(like_shape[a]) for a in axes]
    return [{"op_type": "Slice", "name": node.name,
             "inputs": [ins[0],
                        ctx.add_initializer(
                            "starts", np.asarray(starts, np.int64)),
                        ctx.add_initializer(
                            "ends", np.asarray(ends, np.int64)),
                        ctx.add_initializer(
                            "axes", np.asarray(axes, np.int64))],
             "outputs": [out], "attrs": {}}]


def _attention_core_nodes(n, ctx, q_name, k_name, v_name, B, Sq, Sk, H, D,
                          causal, out):
    """Shared ONNX attention decomposition: q/k/v are (B,S,C)-shaped
    tensor names; emits reshape→transpose→MatMul→Softmax→MatMul→merge."""
    C = H * D
    nodes = []

    def reshape_t(tag, src, S, perm):
        shp = ctx.add_initializer(
            "shape", np.asarray([B, S, H, D], np.int64))
        nodes.append({"op_type": "Reshape", "name": f"{n}_{tag}r",
                      "inputs": [src, shp], "outputs": [f"{n}_{tag}r"],
                      "attrs": {}})
        nodes.append({"op_type": "Transpose", "name": f"{n}_{tag}t",
                      "inputs": [f"{n}_{tag}r"], "outputs": [f"{n}_{tag}t"],
                      "attrs": {"perm": list(perm)}})
        return f"{n}_{tag}t"

    qt = reshape_t("q", q_name, Sq, (0, 2, 1, 3))      # (B,H,Sq,D)
    kt = reshape_t("k", k_name, Sk, (0, 2, 3, 1))      # (B,H,D,Sk)
    vt = reshape_t("v", v_name, Sk, (0, 2, 1, 3))      # (B,H,Sk,D)
    nodes.append({"op_type": "MatMul", "name": f"{n}_qk",
                  "inputs": [qt, kt], "outputs": [f"{n}_scores"],
                  "attrs": {}})
    scale = ctx.add_initializer("scale", np.float32(D ** -0.5))
    nodes.append({"op_type": "Mul", "name": f"{n}_scl",
                  "inputs": [f"{n}_scores", scale],
                  "outputs": [f"{n}_scaled"], "attrs": {}})
    probs_in = f"{n}_scaled"
    if causal:
        mask = np.triu(np.full((Sq, Sk), -1e9, np.float32), k=1)
        mname = ctx.add_initializer("causal_mask",
                                    mask.reshape(1, 1, Sq, Sk))
        nodes.append({"op_type": "Add", "name": f"{n}_mask",
                      "inputs": [probs_in, mname],
                      "outputs": [f"{n}_masked"], "attrs": {}})
        probs_in = f"{n}_masked"
    nodes.append({"op_type": "Softmax", "name": f"{n}_sm",
                  "inputs": [probs_in], "outputs": [f"{n}_probs"],
                  "attrs": {"axis": -1}})
    nodes.append({"op_type": "MatMul", "name": f"{n}_av",
                  "inputs": [f"{n}_probs", vt], "outputs": [f"{n}_ctxv"],
                  "attrs": {}})
    nodes.append({"op_type": "Transpose", "name": f"{n}_ot",
                  "inputs": [f"{n}_ctxv"], "outputs": [f"{n}_otv"],
                  "attrs": {"perm": [0, 2, 1, 3]}})
    oshp = ctx.add_initializer("shape", np.asarray([B, Sq, C], np.int64))
    nodes.append({"op_type": "Reshape", "name": n,
                  "inputs": [f"{n}_otv", oshp], "outputs": [out],
                  "attrs": {}})
    return nodes


@mx2onnx("_contrib_fused_self_attention")
def _fused_self_attention(node, ins, out, attrs, ctx):
    shape = ctx.shape_of.get(ins[0])
    if shape is None:
        raise MXNetError("ONNX export: fused_self_attention needs shape "
                         "inference — pass in_shapes to export")
    B, S, C3 = shape
    C = C3 // 3
    H = int(attrs["heads"])
    D = C // H
    n = node.name
    # Split (B,S,3C) into q/k/v along the last axis (opset-13 sizes input)
    sizes = ctx.add_initializer("split",
                                np.asarray([C, C, C], np.int64))
    nodes = [{"op_type": "Split", "name": f"{n}_split",
              "inputs": [ins[0], sizes],
              "outputs": [f"{n}_q", f"{n}_k", f"{n}_v"],
              "attrs": {"axis": 2}}]
    nodes += _attention_core_nodes(
        n, ctx, f"{n}_q", f"{n}_k", f"{n}_v", B, S, S, H, D,
        bool(attrs.get("causal")), out)
    return nodes


@mx2onnx("_contrib_fused_cross_attention")
def _fused_cross_attention(node, ins, out, attrs, ctx):
    qshape = ctx.shape_of.get(ins[0])
    kvshape = ctx.shape_of.get(ins[1])
    if qshape is None or kvshape is None:
        raise MXNetError("ONNX export: fused_cross_attention needs shape "
                         "inference — pass in_shapes to export")
    B, Sq, C = qshape
    Sk = kvshape[1]
    H = int(attrs["heads"])
    D = C // H
    n = node.name
    sizes = ctx.add_initializer("split", np.asarray([C, C], np.int64))
    nodes = [{"op_type": "Split", "name": f"{n}_split",
              "inputs": [ins[1], sizes],
              "outputs": [f"{n}_k", f"{n}_v"], "attrs": {"axis": 2}}]
    nodes += _attention_core_nodes(n, ctx, ins[0], f"{n}_k", f"{n}_v",
                                   B, Sq, Sk, H, D, False, out)
    return nodes


@mx2onnx("mean")
def _mean(node, ins, out, attrs, ctx):
    axes = attrs.get("axis")
    a = {"keepdims": int(bool(attrs.get("keepdims", False)))}
    if axes is not None:
        a["axes"] = list(axes) if isinstance(axes, (tuple, list)) \
            else [int(axes)]
    return [{"op_type": "ReduceMean", "name": node.name, "inputs": ins,
             "outputs": [out], "attrs": a}]


def export_graph(sym, params, in_shapes=None, in_types=None,
                 graph_name="mxnet_tpu"):
    """Symbol + params -> dict-proto model (pure data transform, no I/O).

    ``params``: {name: array} — "arg:"/"aux:" prefixes accepted.
    ``in_shapes``/``in_types``: per data input, in list_arguments order.
    """
    params = {(k.split(":", 1)[1] if k.startswith(("arg:", "aux:")) else k):
              np.asarray(getattr(v, "asnumpy", lambda: v)())
              for k, v in (params or {}).items()}
    ctx = _Ctx(params)
    topo = sym._topo()
    out_syms = sym._output_symbols() if hasattr(sym, "_output_symbols") \
        else [sym]

    # static shape map for shape-dependent converters (slice_like, the
    # fused attention decompositions): infer every internal tensor's
    # shape from the declared input shapes + param shapes
    ctx.shape_of = {}
    if in_shapes:
        kw = {k: tuple(v.shape) for k, v in params.items()}
        i_data = 0
        for node in topo:
            if node.op is None and node.name not in params:
                if i_data < len(in_shapes):
                    kw[node.name] = tuple(in_shapes[i_data])
                i_data += 1
        try:
            internals = sym.get_internals()
            _, out_shp, _ = internals.infer_shape(**kw)
            for s, shp in zip(internals, out_shp):
                if shp is not None:
                    ctx.shape_of[ctx.tname(s)] = tuple(shp)
        except Exception:
            pass      # shape-dependent converters will raise with advice

    data_inputs = []
    initializers = [{"name": k, "data": v} for k, v in params.items()]
    nodes = []
    n_data = 0
    for node in topo:
        if node.op is None:
            if node.name not in params:
                shape = tuple(in_shapes[n_data]) if in_shapes else None
                dtype = (in_types[n_data] if in_types else "float32")
                data_inputs.append({"name": node.name,
                                    "dtype": str(np.dtype(dtype)),
                                    "shape": shape})
                n_data += 1
            continue
        if node.op == "_group":
            continue
        conv = _EXPORTERS.get(node.op)
        if conv is None:
            raise MXNetError(
                f"ONNX export: no converter for op {node.op!r} "
                f"(node {node.name!r}); register one with "
                f"@mxnet_tpu.contrib.onnx.mx2onnx.mx2onnx")
        ins = [ctx.tname(s) for s in node.inputs]
        out = ctx.out_name(node)
        nodes.extend(conv(node, ins, out, dict(node.attrs), ctx))
    initializers.extend(ctx.extra_initializers)

    outputs = []
    for s in out_syms:
        nm = ctx.tname(s)
        outputs.append({"name": nm, "dtype": "float32", "shape": None})
    used = set()
    for n in nodes:
        used.update(n["inputs"])
    used.update(o["name"] for o in outputs)
    initializers = [t for t in initializers if t["name"] in used]
    return {"ir_version": 8, "opset": 17, "producer_name": "mxnet_tpu",
            "graph": {"name": graph_name, "nodes": nodes,
                      "initializers": initializers,
                      "inputs": data_inputs, "outputs": outputs}}

"""Symbol-DAG -> ONNX graph conversion (ref: python/mxnet/contrib/onnx/
mx2onnx/_op_translations.py). Each MX op converter returns a list of ONNX
node dicts; the registry is open (@mx2onnx) so new ops slot in the same
way the reference's @mx_op.register does."""
from __future__ import annotations

import numpy as np

from ...base import MXNetError

_EXPORTERS = {}


def mx2onnx(op_name):
    def deco(fn):
        _EXPORTERS[op_name] = fn
        return fn
    return deco


class _Ctx:
    """Per-export state: tensor naming, generated initializers."""

    def __init__(self, params):
        self.params = params
        self.extra_initializers = []
        self.renames = {}        # identity-folded tensors (Dropout, etc.)
        self._uid = 0

    def tname(self, sym):
        node = sym._node
        if node.op is None:
            name = node.name
        elif node.num_outputs == 1:
            name = node.name
        else:
            name = f"{node.name}_out{sym._index}"
        return self.renames.get(name, name)

    def out_name(self, node, index=0):
        if node.num_outputs == 1:
            return node.name
        return f"{node.name}_out{index}"

    def add_initializer(self, hint, arr):
        self._uid += 1
        name = f"_{hint}_{self._uid}"
        self.extra_initializers.append(
            {"name": name, "data": np.asarray(arr)})
        return name


def _pads(pad):
    pad = tuple(pad or ())
    return list(pad) + list(pad)          # symmetric begin+end


@mx2onnx("Convolution")
def _conv(node, ins, out, attrs, ctx):
    onnx_attrs = {"kernel_shape": list(attrs["kernel"]),
                  "strides": list(attrs.get("stride") or
                                  (1,) * len(attrs["kernel"])),
                  "dilations": list(attrs.get("dilate") or
                                    (1,) * len(attrs["kernel"])),
                  "pads": _pads(attrs.get("pad") or
                                (0,) * len(attrs["kernel"])),
                  "group": int(attrs.get("num_group") or 1)}
    return [{"op_type": "Conv", "name": node.name, "inputs": ins,
             "outputs": [out], "attrs": onnx_attrs}]


@mx2onnx("FullyConnected")
def _fc(node, ins, out, attrs, ctx):
    nodes = []
    data = ins[0]
    if attrs.get("flatten", True):
        flat = f"{node.name}_flat"
        nodes.append({"op_type": "Flatten", "name": flat, "inputs": [data],
                      "outputs": [flat], "attrs": {"axis": 1}})
        data = flat
    gemm_in = [data, ins[1]] + (ins[2:] if not attrs.get("no_bias") else [])
    nodes.append({"op_type": "Gemm", "name": node.name, "inputs": gemm_in,
                  "outputs": [out],
                  "attrs": {"alpha": 1.0, "beta": 1.0, "transA": 0,
                            "transB": 1}})
    return nodes


@mx2onnx("BatchNorm")
def _bn(node, ins, out, attrs, ctx):
    if attrs.get("fix_gamma"):
        gname = ins[1]
        if gname in ctx.params:
            ins = list(ins)
            ins[1] = ctx.add_initializer(
                "ones", np.ones_like(np.asarray(ctx.params[gname])))
    return [{"op_type": "BatchNormalization", "name": node.name,
             "inputs": list(ins), "outputs": [out],
             "attrs": {"epsilon": float(attrs.get("eps", 1e-3)),
                       "momentum": float(attrs.get("momentum", 0.9))}}]


_ACT = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
        "softrelu": "Softplus", "softsign": "Softsign"}


@mx2onnx("Activation")
def _act(node, ins, out, attrs, ctx):
    act = attrs.get("act_type", "relu")
    if act not in _ACT:
        raise MXNetError(f"ONNX export: unsupported activation {act}")
    return [{"op_type": _ACT[act], "name": node.name, "inputs": ins,
             "outputs": [out], "attrs": {}}]


for _mx, _onnx in [("relu", "Relu"), ("sigmoid", "Sigmoid"),
                   ("tanh", "Tanh"), ("exp", "Exp"), ("log", "Log"),
                   ("sqrt", "Sqrt"), ("abs", "Abs"), ("negative", "Neg"),
                   ("erf", "Erf"), ("floor", "Floor"), ("ceil", "Ceil")]:
    def _make_unary(onnx_type):
        def conv(node, ins, out, attrs, ctx):
            return [{"op_type": onnx_type, "name": node.name,
                     "inputs": ins, "outputs": [out], "attrs": {}}]
        return conv
    _EXPORTERS[_mx] = _make_unary(_onnx)


@mx2onnx("Pooling")
def _pool(node, ins, out, attrs, ctx):
    ptype = attrs.get("pool_type", "max")
    if ptype not in ("max", "avg"):
        raise MXNetError(f"ONNX export: unsupported pool_type {ptype}")
    if attrs.get("global_pool"):
        op = "GlobalMaxPool" if ptype == "max" else "GlobalAveragePool"
        return [{"op_type": op, "name": node.name, "inputs": ins,
                 "outputs": [out], "attrs": {}}]
    kernel = attrs["kernel"]
    onnx_attrs = {"kernel_shape": list(kernel),
                  "strides": list(attrs.get("stride") or (1,) * len(kernel)),
                  "pads": _pads(attrs.get("pad") or (0,) * len(kernel)),
                  "ceil_mode": int(attrs.get("pooling_convention",
                                             "valid") == "full")}
    if ptype == "avg":
        onnx_attrs["count_include_pad"] = int(
            bool(attrs.get("count_include_pad", True)))
    op = "MaxPool" if ptype == "max" else "AveragePool"
    return [{"op_type": op, "name": node.name, "inputs": ins,
             "outputs": [out], "attrs": onnx_attrs}]


@mx2onnx("Flatten")
def _flatten(node, ins, out, attrs, ctx):
    return [{"op_type": "Flatten", "name": node.name, "inputs": ins,
             "outputs": [out], "attrs": {"axis": 1}}]


for _mx, _onnx in [("elemwise_add", "Add"), ("broadcast_add", "Add"),
                   ("elemwise_sub", "Sub"), ("broadcast_sub", "Sub"),
                   ("elemwise_mul", "Mul"), ("broadcast_mul", "Mul"),
                   ("elemwise_div", "Div"), ("broadcast_div", "Div"),
                   ("broadcast_maximum", "Max"),
                   ("broadcast_minimum", "Min")]:
    def _make_binary(onnx_type):
        def conv(node, ins, out, attrs, ctx):
            return [{"op_type": onnx_type, "name": node.name,
                     "inputs": ins, "outputs": [out], "attrs": {}}]
        return conv
    _EXPORTERS[_mx] = _make_binary(_onnx)


@mx2onnx("softmax")
def _softmax(node, ins, out, attrs, ctx):
    return [{"op_type": "Softmax", "name": node.name, "inputs": ins[:1],
             "outputs": [out], "attrs": {"axis": int(attrs.get("axis",
                                                               -1))}}]


@mx2onnx("log_softmax")
def _logsoftmax(node, ins, out, attrs, ctx):
    return [{"op_type": "LogSoftmax", "name": node.name, "inputs": ins[:1],
             "outputs": [out], "attrs": {"axis": int(attrs.get("axis",
                                                               -1))}}]


@mx2onnx("SoftmaxOutput")
def _softmax_output(node, ins, out, attrs, ctx):
    # inference export: drop the label input (ref: mx2onnx softmax_output)
    return [{"op_type": "Softmax", "name": node.name, "inputs": ins[:1],
             "outputs": [out], "attrs": {"axis": -1}}]


@mx2onnx("Dropout")
def _dropout(node, ins, out, attrs, ctx):
    ctx.renames[out] = ctx.renames.get(ins[0], ins[0])   # inference no-op
    return []


@mx2onnx("identity")
def _identity(node, ins, out, attrs, ctx):
    ctx.renames[out] = ctx.renames.get(ins[0], ins[0])
    return []


@mx2onnx("reshape")
def _reshape(node, ins, out, attrs, ctx):
    shape = tuple(attrs.get("shape") or ())
    if any(s in (-2, -3, -4) for s in shape):
        raise MXNetError("ONNX export: reshape special codes -2/-3/-4 have "
                         "no ONNX equivalent")
    shape_name = ctx.add_initializer("shape",
                                     np.asarray(shape, dtype=np.int64))
    return [{"op_type": "Reshape", "name": node.name,
             "inputs": [ins[0], shape_name], "outputs": [out], "attrs": {}}]


@mx2onnx("transpose")
def _transpose(node, ins, out, attrs, ctx):
    return [{"op_type": "Transpose", "name": node.name, "inputs": ins,
             "outputs": [out],
             "attrs": {"perm": list(attrs.get("axes") or [])}}]


@mx2onnx("Concat")
def _concat(node, ins, out, attrs, ctx):
    return [{"op_type": "Concat", "name": node.name, "inputs": ins,
             "outputs": [out], "attrs": {"axis": int(attrs.get("dim", 1))}}]


@mx2onnx("clip")
def _clip(node, ins, out, attrs, ctx):
    lo = ctx.add_initializer("min", np.float32(attrs.get("a_min")))
    hi = ctx.add_initializer("max", np.float32(attrs.get("a_max")))
    return [{"op_type": "Clip", "name": node.name,
             "inputs": [ins[0], lo, hi], "outputs": [out], "attrs": {}}]


@mx2onnx("LeakyReLU")
def _leaky(node, ins, out, attrs, ctx):
    act = attrs.get("act_type", "leaky")
    if act == "leaky":
        return [{"op_type": "LeakyRelu", "name": node.name,
                 "inputs": ins[:1], "outputs": [out],
                 "attrs": {"alpha": float(attrs.get("slope", 0.25))}}]
    if act == "elu":
        return [{"op_type": "Elu", "name": node.name, "inputs": ins[:1],
                 "outputs": [out],
                 "attrs": {"alpha": float(attrs.get("slope", 0.25))}}]
    if act == "prelu":
        return [{"op_type": "PRelu", "name": node.name, "inputs": ins[:2],
                 "outputs": [out], "attrs": {}}]
    raise MXNetError(f"ONNX export: LeakyReLU act_type {act} unsupported")


@mx2onnx("mean")
def _mean(node, ins, out, attrs, ctx):
    axes = attrs.get("axis")
    a = {"keepdims": int(bool(attrs.get("keepdims", False)))}
    if axes is not None:
        a["axes"] = list(axes) if isinstance(axes, (tuple, list)) \
            else [int(axes)]
    return [{"op_type": "ReduceMean", "name": node.name, "inputs": ins,
             "outputs": [out], "attrs": a}]


def export_graph(sym, params, in_shapes=None, in_types=None,
                 graph_name="mxnet_tpu"):
    """Symbol + params -> dict-proto model (pure data transform, no I/O).

    ``params``: {name: array} — "arg:"/"aux:" prefixes accepted.
    ``in_shapes``/``in_types``: per data input, in list_arguments order.
    """
    params = {(k.split(":", 1)[1] if k.startswith(("arg:", "aux:")) else k):
              np.asarray(getattr(v, "asnumpy", lambda: v)())
              for k, v in (params or {}).items()}
    ctx = _Ctx(params)
    topo = sym._topo()
    out_syms = sym._output_symbols() if hasattr(sym, "_output_symbols") \
        else [sym]

    data_inputs = []
    initializers = [{"name": k, "data": v} for k, v in params.items()]
    nodes = []
    n_data = 0
    for node in topo:
        if node.op is None:
            if node.name not in params:
                shape = tuple(in_shapes[n_data]) if in_shapes else None
                dtype = (in_types[n_data] if in_types else "float32")
                data_inputs.append({"name": node.name,
                                    "dtype": str(np.dtype(dtype)),
                                    "shape": shape})
                n_data += 1
            continue
        if node.op == "_group":
            continue
        conv = _EXPORTERS.get(node.op)
        if conv is None:
            raise MXNetError(
                f"ONNX export: no converter for op {node.op!r} "
                f"(node {node.name!r}); register one with "
                f"@mxnet_tpu.contrib.onnx.mx2onnx.mx2onnx")
        ins = [ctx.tname(s) for s in node.inputs]
        out = ctx.out_name(node)
        nodes.extend(conv(node, ins, out, dict(node.attrs), ctx))
    initializers.extend(ctx.extra_initializers)

    outputs = []
    for s in out_syms:
        nm = ctx.tname(s)
        outputs.append({"name": nm, "dtype": "float32", "shape": None})
    used = set()
    for n in nodes:
        used.update(n["inputs"])
    used.update(o["name"] for o in outputs)
    initializers = [t for t in initializers if t["name"] in used]
    return {"ir_version": 8, "opset": 13, "producer_name": "mxnet_tpu",
            "graph": {"name": graph_name, "nodes": nodes,
                      "initializers": initializers,
                      "inputs": data_inputs, "outputs": outputs}}

"""AMP — automatic mixed precision (ref: python/mxnet/contrib/amp/amp.py).

The reference monkey-patches the op namespaces to insert ``amp_cast`` pairs
from fp16 allow/deny lists and wraps the Trainer with a dynamic loss scaler.
TPU-native translation (SURVEY §2.6 #50):

- the natural target dtype is **bfloat16** (MXU-native, fp32 dynamic range
  ⇒ no loss scaling needed);
- casting happens at the compiled-step boundary: ``amp.init()`` sets the
  process-wide compute dtype that ``parallel.ShardedTrainer`` (and bench)
  pick up — one cast into the program, fp32 master weights, fp32 loss math,
  which is exactly where the reference's graph-pass lands after XLA fusion;
- fp16 parity keeps the reference's ``DynamicLossScaler`` (skip-step on
  overflow, ref: amp.py DynamicLossScaler) for scripts that ask for fp16.
"""
from __future__ import annotations

import numpy as np

from ...base import MXNetError

__all__ = ["init", "reset", "init_trainer", "scale_loss", "unscale",
           "convert_hybrid_block", "DynamicLossScaler", "amp_dtype"]

_state = {"initialized": False, "dtype": None, "lists": None}

# Ops that stay fp32 regardless of the blanket compute dtype when the
# per-op policy is active — the reference's FP32_FUNCS core (reductions,
# losses, norms, exp/log families; ref: amp/lists/symbol_fp16.py
# FP32_FUNCS). The policy only engages when init() receives op lists;
# the default TPU path remains the single cast at the step boundary.
_DEFAULT_FP32_OPS = (
    "softmax", "log_softmax", "SoftmaxOutput", "SoftmaxActivation",
    "norm", "mean", "sum", "exp", "log", "log2", "log10", "expm1",
    "log1p", "erf", "erfinv", "logsumexp", "smooth_l1", "MakeLoss",
    "LinearRegressionOutput", "LogisticRegressionOutput",
    "MAERegressionOutput",
)


class _OpCastPolicy:
    """Dispatch-level realization of the reference's amp_cast graph pass
    (ref: python/mxnet/contrib/amp/amp.py _get_fun_to_wrap +
    lists/symbol_fp16.py): inputs of listed ops are recast on the way in.
    Works on eager arrays and tracers (so it holds inside jit programs)."""

    def __init__(self, target_dtype, target_precision_ops,
                 conditional_fp32_ops, fp32_ops):
        import jax.numpy as jnp
        self._target = jnp.dtype(target_dtype)
        self._target_ops = frozenset(target_precision_ops or ())
        self._fp32_ops = frozenset(fp32_ops or ()) | \
            frozenset(_DEFAULT_FP32_OPS)
        # [(op_name, param_name, [values])] → {op: [(param, {values})]}
        cond = {}
        for op_name, param, values in (conditional_fp32_ops or ()):
            vals = values if isinstance(values, (list, tuple, set)) \
                else [values]
            cond.setdefault(op_name, []).append((param, set(vals)))
        self._conditional = cond

    def _cast_all(self, datas, dtype):
        import jax.numpy as jnp
        return [d.astype(dtype)
                if hasattr(d, "dtype") and jnp.issubdtype(d.dtype,
                                                          jnp.floating)
                and d.dtype != dtype else d
                for d in datas]

    def __call__(self, op_name, datas, params):
        import jax.numpy as jnp
        if op_name in self._fp32_ops:
            return self._cast_all(datas, jnp.float32)
        for param, vals in self._conditional.get(op_name, ()):
            if str(params.get(param)) in vals or params.get(param) in vals:
                return self._cast_all(datas, jnp.float32)
        if op_name in self._target_ops:
            return self._cast_all(datas, self._target)
        return datas


def init(target_dtype="bfloat16", target_precision_ops=None,
         conditional_fp32_ops=None, fp32_ops=None):
    """ref: amp.init — enable mixed precision process-wide.

    Without op lists, AMP is one cast at the compiled-step boundary (the
    idiomatic TPU form — XLA keeps fp32 accumulation where it matters).
    With any of ``target_precision_ops`` / ``conditional_fp32_ops`` /
    ``fp32_ops`` given, a per-op cast policy engages at dispatch: listed
    ops force their floating inputs to the listed precision, mirroring
    the reference's allow/deny-list graph pass."""
    target_dtype = str(np.dtype(target_dtype)) if target_dtype != "bfloat16" \
        else "bfloat16"
    if target_dtype not in ("float16", "bfloat16"):
        raise MXNetError("AMP target_dtype must be float16 or bfloat16 "
                         "(bfloat16 recommended on TPU)")
    _state["initialized"] = True
    _state["dtype"] = target_dtype
    from ... import _dispatch
    if target_precision_ops or conditional_fp32_ops or fp32_ops:
        from ...ops.registry import get as get_op
        for name in list(target_precision_ops or []) + \
                [c[0] for c in (conditional_fp32_ops or [])] + \
                list(fp32_ops or []):
            get_op(name)     # unknown op names fail loudly, not silently
        policy = _OpCastPolicy(target_dtype, target_precision_ops,
                               conditional_fp32_ops, fp32_ops)
        _state["lists"] = policy
        _dispatch.set_amp_cast_hook(policy)
    else:
        # re-init without lists must drop any previously installed policy
        # (a stale hook would keep casting to the OLD target dtype)
        _state["lists"] = None
        _dispatch.set_amp_cast_hook(None)


def reset():
    """Disable AMP (test helper; the reference has no uninit)."""
    from ... import _dispatch
    _state.update(initialized=False, dtype=None, lists=None)
    _dispatch.set_amp_cast_hook(None)


def amp_dtype():
    """The active AMP compute dtype, or None (read by ShardedTrainer)."""
    return _state["dtype"] if _state["initialized"] else None


class DynamicLossScaler:
    """ref: amp.py DynamicLossScaler — grow scale on stability, halve and
    skip the step on overflow. bf16 does not need it; kept for fp16.

    The overflow signal now rides the fused guardrail flag
    (docs/guardrails.md): the fused trainers return it as a step output
    (zero extra host reads) and the eager Trainer checks gradients with
    its own fused pass on both step() paths. ``has_overflow`` has no
    in-repo callers anymore — it is kept, on the same fused chokepoint,
    for external/back-compat callers only."""

    def __init__(self, init_scale=2 ** 16, scale_factor=2.0,
                 scale_window=2000, tolerance=0.0):
        self.loss_scale = init_scale
        self._scale_factor = scale_factor
        self._scale_window = scale_window
        self._unskipped = 0

    def has_overflow(self, params):
        """One fused device-side finiteness reduction over every gradient
        of every replica (guardrails.fused.guard_stats), one host sync
        total — not a per-parameter download (the tunnel costs ~90 ms
        per round-trip)."""
        from ...guardrails import fused
        grads = [g._data for p in params
                 for g in (getattr(p, "_grad", None) or ()) if g is not None]
        if not grads:
            return False
        finite, _ = fused.guard_stats(grads)
        return not fused.host_fetch(finite)[0]

    def update_scale(self, overflow):
        if overflow:
            self.loss_scale = max(1.0, self.loss_scale / self._scale_factor)
            self._unskipped = 0
        else:
            self._unskipped += 1
            if self._unskipped >= self._scale_window:
                self.loss_scale *= self._scale_factor
                self._unskipped = 0


def init_trainer(trainer):
    """ref: amp.init_trainer — attach a loss scaler to a gluon Trainer."""
    if not _state["initialized"]:
        raise MXNetError("call amp.init() before amp.init_trainer()")
    trainer._amp_loss_scaler = DynamicLossScaler()
    return trainer


class _ScaledLoss:
    def __init__(self, loss, scaler):
        self._loss = loss
        self._scaler = scaler

    def __enter__(self):
        s = self._scaler.loss_scale
        if isinstance(self._loss, (list, tuple)):
            return [l * s for l in self._loss]
        return self._loss * s

    def __exit__(self, *exc):
        return False


def scale_loss(loss, trainer):
    """``with amp.scale_loss(loss, trainer) as L: L.backward()``
    (ref: amp.scale_loss). The matching unscale happens in unscale()."""
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None:
        raise MXNetError("trainer was not passed through amp.init_trainer")
    # Trainer.step uses rescale_grad = _scale / batch_size, so dividing
    # the scale back out happens there (ref: Trainer._amp integration)
    trainer._scale = 1.0 / scaler.loss_scale
    return _ScaledLoss(loss, scaler)


def unscale(trainer):
    """Divide accumulated gradients by the current loss scale."""
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None:
        raise MXNetError("trainer was not passed through amp.init_trainer")
    inv = 1.0 / scaler.loss_scale
    for p in trainer._params:
        if p.grad_req != "null" and p._grad is not None:
            for g in p._grad:
                g._rebind((g * inv)._data)
    trainer._scale = 1.0


def convert_hybrid_block(block, target_dtype="bfloat16", ctx=None):
    """Cast a block's parameters for low-precision inference
    (ref: amp.convert_hybrid_block)."""
    block.cast(target_dtype)
    return block

"""AMP — automatic mixed precision (ref: python/mxnet/contrib/amp/amp.py).

The reference monkey-patches the op namespaces to insert ``amp_cast`` pairs
from fp16 allow/deny lists and wraps the Trainer with a dynamic loss scaler.
TPU-native translation (SURVEY §2.6 #50):

- the natural target dtype is **bfloat16** (MXU-native, fp32 dynamic range
  ⇒ no loss scaling needed);
- casting happens at the compiled-step boundary: ``amp.init()`` sets the
  process-wide compute dtype that ``parallel.ShardedTrainer`` (and bench)
  pick up — one cast into the program, fp32 master weights, fp32 loss math,
  which is exactly where the reference's graph-pass lands after XLA fusion;
- fp16 parity keeps the reference's ``DynamicLossScaler`` (skip-step on
  overflow, ref: amp.py DynamicLossScaler) for scripts that ask for fp16.
"""
from __future__ import annotations

import numpy as np

from ...base import MXNetError

__all__ = ["init", "init_trainer", "scale_loss", "unscale",
           "convert_hybrid_block", "DynamicLossScaler", "amp_dtype"]

_state = {"initialized": False, "dtype": None}


def init(target_dtype="bfloat16", target_precision_ops=None,
         conditional_fp32_ops=None, fp32_ops=None):
    """ref: amp.init — enable mixed precision process-wide."""
    target_dtype = str(np.dtype(target_dtype)) if target_dtype != "bfloat16" \
        else "bfloat16"
    if target_dtype not in ("float16", "bfloat16"):
        raise MXNetError("AMP target_dtype must be float16 or bfloat16 "
                         "(bfloat16 recommended on TPU)")
    _state["initialized"] = True
    _state["dtype"] = target_dtype


def amp_dtype():
    """The active AMP compute dtype, or None (read by ShardedTrainer)."""
    return _state["dtype"] if _state["initialized"] else None


class DynamicLossScaler:
    """ref: amp.py DynamicLossScaler — grow scale on stability, halve and
    skip the step on overflow. bf16 does not need it; kept for fp16."""

    def __init__(self, init_scale=2 ** 16, scale_factor=2.0,
                 scale_window=2000, tolerance=0.0):
        self.loss_scale = init_scale
        self._scale_factor = scale_factor
        self._scale_window = scale_window
        self._unskipped = 0

    def has_overflow(self, params):
        for p in params:
            g = p._grad[0] if getattr(p, "_grad", None) else None
            if g is None:
                continue
            a = g.asnumpy()
            if not np.isfinite(a).all():
                return True
        return False

    def update_scale(self, overflow):
        if overflow:
            self.loss_scale = max(1.0, self.loss_scale / self._scale_factor)
            self._unskipped = 0
        else:
            self._unskipped += 1
            if self._unskipped >= self._scale_window:
                self.loss_scale *= self._scale_factor
                self._unskipped = 0


def init_trainer(trainer):
    """ref: amp.init_trainer — attach a loss scaler to a gluon Trainer."""
    if not _state["initialized"]:
        raise MXNetError("call amp.init() before amp.init_trainer()")
    trainer._amp_loss_scaler = DynamicLossScaler()
    return trainer


class _ScaledLoss:
    def __init__(self, loss, scaler):
        self._loss = loss
        self._scaler = scaler

    def __enter__(self):
        s = self._scaler.loss_scale
        if isinstance(self._loss, (list, tuple)):
            return [l * s for l in self._loss]
        return self._loss * s

    def __exit__(self, *exc):
        return False


def scale_loss(loss, trainer):
    """``with amp.scale_loss(loss, trainer) as L: L.backward()``
    (ref: amp.scale_loss). The matching unscale happens in unscale()."""
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None:
        raise MXNetError("trainer was not passed through amp.init_trainer")
    # Trainer.step uses rescale_grad = _scale / batch_size, so dividing
    # the scale back out happens there (ref: Trainer._amp integration)
    trainer._scale = 1.0 / scaler.loss_scale
    return _ScaledLoss(loss, scaler)


def unscale(trainer):
    """Divide accumulated gradients by the current loss scale."""
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None:
        raise MXNetError("trainer was not passed through amp.init_trainer")
    inv = 1.0 / scaler.loss_scale
    for p in trainer._params:
        if p.grad_req != "null" and p._grad is not None:
            for g in p._grad:
                g._rebind((g * inv)._data)
    trainer._scale = 1.0


def convert_hybrid_block(block, target_dtype="bfloat16", ctx=None):
    """Cast a block's parameters for low-precision inference
    (ref: amp.convert_hybrid_block)."""
    block.cast(target_dtype)
    return block

"""INT8 quantization (ref: python/mxnet/contrib/quantization.py).

The reference's calibration flow (entropy/minmax thresholds feeding
quantized_conv/fc kernels, SURVEY §2 #19) targets INT8 GEMMs. On TPU the
idiomatic equivalent is AQT-style quantized XLA matmuls; this round ships
calibration utilities and documents the kernel gap explicitly rather than
pretending parity.
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError

__all__ = ["quantize_model", "calib_thresholds_minmax",
           "calib_thresholds_entropy"]


def calib_thresholds_minmax(arrays):
    """Per-tensor min/max calibration (ref: quantization.py _LayerOutput
    MinMaxCollector)."""
    out = {}
    for name, arr in arrays.items():
        a = arr.asnumpy() if hasattr(arr, "asnumpy") else np.asarray(arr)
        out[name] = (float(a.min()), float(a.max()))
    return out


def calib_thresholds_entropy(arrays, num_bins=8001, num_quantized_bins=255):
    """KL-divergence threshold search (ref: quantization.py
    _get_optimal_threshold)."""
    out = {}
    for name, arr in arrays.items():
        a = np.abs(np.asarray(arr.asnumpy() if hasattr(arr, "asnumpy")
                              else arr)).ravel()
        amax = a.max() if a.size else 0.0
        if amax == 0:
            out[name] = (0.0, 0.0)
            continue
        hist, edges = np.histogram(a, bins=num_bins, range=(0, amax))
        best_kl, best_t = np.inf, amax
        for i in range(num_quantized_bins, num_bins,
                       max(1, num_bins // 64)):
            p = hist[:i].astype(np.float64).copy()
            p[-1] += hist[i:].sum()
            if p.sum() == 0:
                continue
            factor = i / num_quantized_bins
            q = np.repeat(
                np.add.reduceat(p, np.arange(0, i,
                                             max(1, int(factor)))),
                max(1, int(factor)))[:i]
            p /= p.sum()
            q = q / q.sum()
            mask = p > 0
            kl = float(np.sum(p[mask] * np.log(p[mask]
                                               / np.maximum(q[mask], 1e-12))))
            if kl < best_kl:
                best_kl, best_t = kl, edges[i]
        out[name] = (-best_t, best_t)
    return out


def quantize_model(*args, **kwargs):
    raise MXNetError(
        "INT8 quantized inference kernels are not implemented in the TPU "
        "build yet (reference: src/operator/quantization/). The TPU path "
        "is AQT-style int8 XLA matmuls; bf16 inference via "
        "amp.convert_hybrid_block covers most deployment cases today. "
        "Calibration utilities (calib_thresholds_*) are available.")

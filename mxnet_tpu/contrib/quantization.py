"""INT8 quantization (ref: python/mxnet/contrib/quantization.py).

The reference's calibration flow (entropy/minmax thresholds feeding
quantized_conv/fc kernels, SURVEY §2 #19) targets INT8 GEMMs. On TPU the
idiomatic equivalent is AQT-style quantized XLA matmuls; this round ships
calibration utilities and documents the kernel gap explicitly rather than
pretending parity.
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError

__all__ = ["quantize_model", "quantize_net", "calib_thresholds_minmax",
           "calib_thresholds_entropy"]


def calib_thresholds_minmax(arrays):
    """Per-tensor min/max calibration (ref: quantization.py _LayerOutput
    MinMaxCollector)."""
    out = {}
    for name, arr in arrays.items():
        a = arr.asnumpy() if hasattr(arr, "asnumpy") else np.asarray(arr)
        out[name] = (float(a.min()), float(a.max()))
    return out


def _smooth(p, eps=0.0001):
    """ref: quantization.py _smooth_distribution — move eps mass onto
    zero bins so KL is defined."""
    is_zero = p == 0
    n_zero = is_zero.sum()
    n_nonzero = p.size - n_zero
    if n_nonzero == 0:
        return None
    eps1 = eps * n_zero / n_nonzero
    out = p.astype(np.float64).copy()
    out[is_zero] = eps
    out[~is_zero] -= eps1
    if (out[~is_zero] <= 0).any():
        return None
    return out


def _optimal_threshold(a, num_bins=2001, num_quantized_bins=255):
    """KL-divergence threshold search over the |activation| histogram
    (ref: quantization.py _get_optimal_threshold). Clipped distribution p
    (outlier mass saturated into the last bin) is compared against its
    255-level quantization q, with q's per-group mass redistributed over
    the group's nonzero bins like the reference does."""
    amax = float(a.max()) if a.size else 0.0
    if amax == 0:
        return 0.0
    hist, edges = np.histogram(a, bins=num_bins, range=(0, amax))
    best_kl, best_t = np.inf, amax
    step = max(1, (num_bins - num_quantized_bins) // 256)
    for i in range(num_quantized_bins, num_bins + 1, step):
        p = hist[:i].astype(np.float64).copy()
        p[-1] += hist[i:].sum()
        if p.sum() == 0:
            continue
        nonzero = (p != 0)
        # quantize the i bins into num_quantized_bins groups
        group = (np.arange(i) * num_quantized_bins) // i
        sums = np.bincount(group, weights=hist[:i].astype(np.float64),
                           minlength=num_quantized_bins)
        counts = np.bincount(group, weights=nonzero.astype(np.float64),
                             minlength=num_quantized_bins)
        q = np.zeros(i)
        with np.errstate(divide="ignore", invalid="ignore"):
            per_bin = np.where(counts > 0, sums / np.maximum(counts, 1),
                               0.0)
        q[nonzero] = per_bin[group[nonzero]]
        # smooth the raw count vectors (reference order: smooth, then the
        # KL normalizes) — smoothing after normalization would drive small
        # bins negative and skip valid candidates
        ps = _smooth(p)
        qs = _smooth(q) if q.sum() else None
        if ps is None or qs is None:
            continue
        ps = ps / ps.sum()
        qs = qs / qs.sum()
        kl = float(np.sum(ps * np.log(ps / qs)))
        if kl < best_kl:
            best_kl, best_t = kl, edges[i]
    return best_t


def calib_thresholds_entropy(arrays, num_bins=2001, num_quantized_bins=255):
    """KL-divergence calibration per tensor (ref: quantization.py
    _get_optimal_thresholds)."""
    out = {}
    for name, arr in arrays.items():
        a = np.abs(np.asarray(arr.asnumpy() if hasattr(arr, "asnumpy")
                              else arr)).ravel()
        t = _optimal_threshold(a, num_bins=num_bins,
                               num_quantized_bins=num_quantized_bins)
        out[name] = (-t, t)
    return out


def _collect_layer_inputs(sym, arg_params, aux_params, calib_data,
                          data_names, tensor_names, max_batches):
    """Run calib batches through the graph internals and collect the
    fp32 values of ``tensor_names`` (the inputs of to-be-quantized ops)
    (ref: quantization.py _collect_layer_statistics)."""
    from .. import ndarray as nd
    from ..context import current_context
    internals = sym.get_internals()
    by_name = {}
    for s in internals:
        by_name.setdefault(s.name, s)
    wanted = [n for n in tensor_names if n in by_name]
    if not wanted:
        return {}
    from ..symbol import Group
    group = Group([by_name[n] for n in wanted])
    collected = {n: [] for n in wanted}
    # convert params once, outside the per-batch loop
    args_nd = {k: v if isinstance(v, nd.NDArray) else nd.array(v)
               for k, v in arg_params.items()}
    aux_nd = {k: v if isinstance(v, nd.NDArray) else nd.array(v)
              for k, v in aux_params.items()}
    n_done = 0
    for batch in calib_data:
        datas = batch if isinstance(batch, (list, tuple)) else [batch]
        binds = dict(zip(data_names, [nd.array(d) for d in datas]))
        binds.update(args_nd)
        ex = group.bind(current_context(), binds, aux_states=aux_nd)
        outs = ex.forward()
        for n, o in zip(wanted, outs):
            collected[n].append(o.asnumpy())
        n_done += 1
        if max_batches is not None and n_done >= max_batches:
            break
    return {n: np.concatenate([a.ravel() for a in arrs])
            for n, arrs in collected.items() if arrs}


_QUANTIZABLE = ("Convolution", "FullyConnected")


def quantize_model(sym, arg_params, aux_params, data_names=("data",),
                   excluded_sym_names=(), calib_mode="none",
                   calib_data=None, num_calib_examples=None,
                   quantized_dtype="int8", ctx=None, logger=None):
    """Rewrite Convolution/FullyConnected nodes to int8 compute
    (ref: python/mxnet/contrib/quantization.py quantize_model).

    Returns (qsym, qarg_params, aux_params). Weights are pre-quantized
    per-output-channel; activations quantize at runtime with a static
    scale when calibrated (``calib_mode`` 'naive'/'entropy') or a dynamic
    per-batch scale (``calib_mode='none'``). Compute is a real int8
    GEMM/conv accumulated in int32 (ops/quantization.py).
    """
    from ..symbol.symbol import Symbol, _create, var
    if quantized_dtype != "int8":
        raise MXNetError(f"quantized_dtype {quantized_dtype!r}: only "
                         f"'int8' is supported (symmetric)")
    excluded = set(excluded_sym_names or ())
    arg_np = {k: (v.asnumpy() if hasattr(v, "asnumpy") else np.asarray(v))
              for k, v in arg_params.items()}

    topo = sym._topo()
    # which tensors need activation calibration: data inputs of q-ops
    def _tensor_name(s):
        return s.name

    calib_tensors = []
    for node in topo:
        if node.op in _QUANTIZABLE and node.name not in excluded:
            calib_tensors.append(_tensor_name(node.inputs[0]))
    thresholds = {}
    if calib_mode in ("naive", "entropy"):
        if calib_data is None:
            raise MXNetError(f"calib_mode={calib_mode!r} needs calib_data")
        arrays = _collect_layer_inputs(
            sym, arg_params, aux_params, calib_data, list(data_names),
            calib_tensors, num_calib_examples)
        calib_fn = (calib_thresholds_minmax if calib_mode == "naive"
                    else calib_thresholds_entropy)
        thresholds = calib_fn(arrays)

    qargs = {}
    new_of = {}                 # id(old node) -> list[Symbol] outputs

    def mapped(s):
        node = s._node
        if node.op is None:
            return Symbol(node, s._index)
        return new_of[id(node)][s._index]

    for node in topo:
        if node.op is None or node.op == "_group":
            continue
        ins = [mapped(s) for s in node.inputs]
        if node.op in _QUANTIZABLE and node.name not in excluded \
                and node.inputs[1]._node.op is None \
                and node.inputs[1]._node.name in arg_np:
            wname = node.inputs[1]._node.name
            # don't pop: another (e.g. excluded or weight-sharing) layer
            # may still reference the fp32 weight; unreferenced originals
            # are dropped against the rebuilt graph at the end
            w = arg_np[wname]
            if wname + "_quantized" not in qargs:
                from ..ops.quantization import quantize_array
                wq, wscale = quantize_array(w, channel_axis=0)
                qargs[wname + "_quantized"] = np.asarray(wq)
                qargs[wname + "_scale"] = np.asarray(wscale)
            wq_sym = var(wname + "_quantized")
            ws_sym = var(wname + "_scale")
            in_name = _tensor_name(node.inputs[0])
            qkw = {}
            if in_name in thresholds:
                lo, hi = thresholds[in_name]
                qkw = {"min_calib_range": float(lo),
                       "max_calib_range": float(hi)}
            xq_pair = _create("_contrib_quantize_v2", [ins[0]], qkw,
                              name=f"{node.name}_x_quantize")
            xq, xscale = xq_pair[0], xq_pair[1]
            bias_ins = ins[2:] if not node.attrs.get("no_bias") else []
            if node.op == "FullyConnected":
                out = _create(
                    "_contrib_quantized_fully_connected",
                    [xq, wq_sym, xscale, ws_sym] + bias_ins,
                    {"num_hidden": node.attrs["num_hidden"],
                     "no_bias": node.attrs.get("no_bias", False),
                     "flatten": node.attrs.get("flatten", True)},
                    name=f"{node.name}_quantized")
            else:
                out = _create(
                    "_contrib_quantized_conv",
                    [xq, wq_sym, xscale, ws_sym] + bias_ins,
                    {"kernel": node.attrs["kernel"],
                     "stride": node.attrs.get("stride"),
                     "dilate": node.attrs.get("dilate"),
                     "pad": node.attrs.get("pad"),
                     "num_filter": node.attrs["num_filter"],
                     "num_group": node.attrs.get("num_group", 1),
                     "no_bias": node.attrs.get("no_bias", False)},
                    name=f"{node.name}_quantized")
            new_of[id(node)] = [out]
        else:
            # scoped attrs (__ctx_group__ etc.) aren't op params; re-add
            # them after creation like symbol.load_json does
            plain = {k: v for k, v in node.attrs.items()
                     if not k.startswith("__")}
            scoped = {k: v for k, v in node.attrs.items()
                      if k.startswith("__")}
            out = _create(node.op, ins, plain, name=node.name)
            out._node.attrs.update(scoped)
            new_of[id(node)] = [Symbol(out._node, i)
                                for i in range(node.num_outputs)]

    out_syms = sym._output_symbols() if hasattr(sym, "_output_symbols") \
        else [sym]
    mapped_outs = [mapped(s) for s in out_syms]
    from ..symbol import Group
    qsym = mapped_outs[0] if len(mapped_outs) == 1 else Group(mapped_outs)
    from .. import ndarray as nd
    still_referenced = set(qsym.list_arguments()) \
        | set(qsym.list_auxiliary_states())
    qarg_params = {k: nd.array(v) for k, v in arg_np.items()
                   if k in still_referenced}
    qarg_params.update({k: nd.array(v) for k, v in qargs.items()})
    return qsym, qarg_params, dict(aux_params)


def quantize_net(network, calib_data=None, calib_mode="none",
                 data_shapes=None, excluded_sym_names=(),
                 num_calib_examples=None):
    """Gluon route: HybridBlock -> int8 SymbolBlock
    (ref: quantization.py quantize_net). ``data_shapes`` is required when
    ``calib_data`` is None (to trace the network)."""
    import tempfile

    from .. import ndarray as nd
    from .. import symbol as sym_mod
    from ..gluon import SymbolBlock
    from ..model import load_checkpoint

    if calib_data is not None:
        first = calib_data[0] if isinstance(calib_data, (list, tuple)) \
            else calib_data
        example = first if not isinstance(first, (list, tuple)) else \
            first[0]
        x = nd.array(example)
    elif data_shapes:
        x = nd.zeros(data_shapes[0])
    else:
        raise MXNetError("quantize_net needs calib_data or data_shapes")
    network.hybridize()
    network(x)
    with tempfile.TemporaryDirectory() as td:
        prefix = f"{td}/net"
        network.export(prefix)
        sym, arg_params, aux_params = load_checkpoint(prefix, 0)
    batches = None
    if calib_data is not None:
        batches = calib_data if isinstance(calib_data, (list, tuple)) \
            else [calib_data]
    data_name = [n for n in sym.list_arguments()
                 if n not in arg_params
                 and n not in sym.list_auxiliary_states()]
    qsym, qarg, qaux = quantize_model(
        sym, arg_params, aux_params, data_names=data_name,
        excluded_sym_names=excluded_sym_names, calib_mode=calib_mode,
        calib_data=batches, num_calib_examples=num_calib_examples)
    inputs = [sym_mod.var(n) for n in data_name]
    net = SymbolBlock(qsym, inputs)
    params = net.collect_params()
    from ..context import current_context
    ctx = current_context()
    for name, arr in list(qarg.items()) + list(qaux.items()):
        if name in params:
            # int8 weights / fp32 scales must keep their dtype — the
            # SymbolBlock default (fp32) would silently turn the int8
            # GEMM into an fp32 one
            params[name].dtype = arr.asnumpy().dtype \
                if hasattr(arr, "asnumpy") else np.asarray(arr).dtype
            params[name]._load_init(arr, ctx)
    return net

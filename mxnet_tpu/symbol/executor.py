"""Executor — the bound, compiled form of a Symbol.

ref: src/executor/graph_executor.cc GraphExecutor (Bind/SimpleBind,
Forward/Backward, memory planning passes). Here binding compiles the DAG to
one jitted XLA program per (train/infer) mode; XLA's buffer assignment IS
the PlanMemory pass, its fusion the op bulking, and jax.vjp supplies the
backward graph the reference builds with nnvm::pass::Gradient.
"""
from __future__ import annotations

import jax

from .. import _rng
from ..base import MXNetError
from ..context import current_context

__all__ = ["Executor"]


class Executor:
    def __init__(self, symbol, ctx, args, args_grad=None, grad_req="write",
                 aux_states=None):
        from .. import ndarray as nd
        from .passes import apply_env_passes
        symbol = apply_env_passes(symbol)   # MXNET_SUBGRAPH_BACKEND hook
        self._symbol = symbol
        self._ctx = ctx or current_context()
        self.arg_dict = dict(args)
        self.aux_dict = dict(aux_states or {})
        arg_names = symbol.list_arguments()
        if isinstance(grad_req, str):
            grad_req = {n: grad_req for n in arg_names}
        elif isinstance(grad_req, (list, tuple)):
            grad_req = dict(zip(arg_names, grad_req))
        self._grad_req = grad_req
        if args_grad is None:
            args_grad = {n: nd.zeros(self.arg_dict[n].shape, ctx=self._ctx)
                         for n in arg_names
                         if grad_req.get(n, "null") != "null"
                         and n in self.arg_dict}
        self.grad_dict = dict(args_grad)
        self.outputs = []
        self._fns = {}
        self._vjp = None
        self._fwd_values = None
        self._monitor = None

    def install_monitor(self, monitor):
        """ref: Executor SetMonitorCallback via python/mxnet/monitor.py
        Monitor.install — here monitored intermediates come back as extra
        program outputs instead of engine callbacks."""
        self._monitor = monitor

    @property
    def arg_arrays(self):
        return [self.arg_dict[n] for n in self._symbol.list_arguments()]

    @property
    def grad_arrays(self):
        return [self.grad_dict.get(n)
                for n in self._symbol.list_arguments()]

    @property
    def aux_arrays(self):
        return [self.aux_dict[n]
                for n in self._symbol.list_auxiliary_states()]

    def forward(self, is_train=False, **kwargs):
        """ref: Executor::Forward — optionally override inputs by name."""
        from .. import ndarray as nd
        for k, v in kwargs.items():
            if k not in self.arg_dict:
                raise MXNetError(f"executor has no argument {k!r}")
            self.arg_dict[k]._rebind(
                v._data if isinstance(v, nd.NDArray)
                else nd.array(v)._data)
        values = {k: v._data for k, v in self.arg_dict.items()}
        values.update({k: v._data for k, v in self.aux_dict.items()})
        capture_re = (self._monitor._pattern_re
                      if self._monitor is not None
                      and self._monitor.activated else None)
        run = self._symbol._make_eval_fn(training=is_train,
                                         capture_re=capture_re)

        grad_names = [n for n in self._symbol.list_arguments()
                      if self._grad_req.get(n, "null") != "null"]
        if is_train and grad_names:
            others = {k: v for k, v in values.items() if k not in grad_names}

            def fn(grad_values):
                outs, aux_updates = run({**others, **grad_values})
                return outs, aux_updates
            grad_values = {n: values[n] for n in grad_names}
            outs, vjp_fn, aux_updates = jax.vjp(fn, grad_values,
                                                has_aux=True)
            self._vjp = (vjp_fn, grad_names)
        else:
            outs, aux_updates = run(values)
            self._vjp = None
        for name, val in aux_updates.items():
            if name.startswith("__monitor__:"):
                self._monitor._collect(name[len("__monitor__:"):], val)
                continue
            if name in self.aux_dict:
                self.aux_dict[name]._rebind(val)
        self.outputs = [nd.NDArray(o, ctx=self._ctx, _skip_device_put=True)
                        for o in outs]
        return self.outputs

    def backward(self, out_grads=None):
        """ref: Executor::Backward — accumulate per grad_req."""
        from .. import ndarray as nd
        import jax.numpy as jnp
        if self._vjp is None:
            raise MXNetError("backward() requires forward(is_train=True)")
        vjp_fn, grad_names = self._vjp
        if out_grads is None:
            cts = [jnp.ones(o.shape, o.dtype) for o in self.outputs]
        else:
            if not isinstance(out_grads, (list, tuple)):
                out_grads = [out_grads]
            cts = [g._data if isinstance(g, nd.NDArray) else jnp.asarray(g)
                   for g in out_grads]
        grads = vjp_fn(cts)[0]
        for name in grad_names:
            req = self._grad_req.get(name, "write")
            if name not in self.grad_dict:
                self.grad_dict[name] = nd.zeros(self.arg_dict[name].shape,
                                                ctx=self._ctx)
            g = grads[name]
            if req == "add":
                self.grad_dict[name]._rebind(self.grad_dict[name]._data + g)
            else:
                self.grad_dict[name]._rebind(g)

    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        """ref: Executor::CopyParamsFrom."""
        from .. import ndarray as nd
        for k, v in (arg_params or {}).items():
            if k in self.arg_dict:
                self.arg_dict[k]._rebind(nd.array(v)._data)
            elif not allow_extra_params:
                raise MXNetError(f"unknown argument {k!r}")
        for k, v in (aux_params or {}).items():
            if k in self.aux_dict:
                self.aux_dict[k]._rebind(nd.array(v)._data)
            elif not allow_extra_params:
                raise MXNetError(f"unknown aux state {k!r}")

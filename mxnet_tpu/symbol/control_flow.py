"""Symbolic control-flow operators — subgraph nodes over lax primitives
(ref: src/operator/control_flow.cc _foreach/_while_loop/_cond;
python/mxnet/symbol/contrib.py foreach/while_loop/cond).

The reference stores the body as an NNVM subgraph attribute on a special
node and executes it with a subgraph executor per iteration. Here the
node's attrs hold a sub-``Symbol``; execution compiles the subgraph's
eval function into ``lax.scan`` (foreach, while_loop with a done-mask)
or a both-branches ``jnp.where`` select (cond — XLA predicates small
branches on TPU anyway, and lax.cond does not compile inside
differentiated scans on some TPU runtimes).

Free variables of the body subgraph (the user's weight symbols) become
ordinary inputs of the control-flow node, so ``list_arguments``/binding
see them exactly like any other op input.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from ..base import MXNetError

CONTROL_FLOW_OPS = {"_foreach", "_while_loop", "_cond"}

__all__ = ["foreach", "while_loop", "cond", "CONTROL_FLOW_OPS",
           "control_flow_fn"]


def _as_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _sym_mod():
    from . import symbol as S
    return S


def _free_variables(graph, exclude_names):
    """Leaf variable nodes of ``graph`` not in exclude_names, topo order."""
    out = []
    for node in graph._topo():
        if node.op is None and node.name not in exclude_names:
            out.append(node)
    return out


def foreach(body, data, init_states, name=None):
    """Symbolic scan (ref: symbol/contrib.py foreach). ``body`` receives
    placeholder symbols for one data slice and the states and must return
    (outputs, new_states) of symbols."""
    S = _sym_mod()
    name = name or S._NameManager.next_name("foreach")
    data_list = _as_list(data)
    states = _as_list(init_states)
    single_data = not isinstance(data, (list, tuple))
    single_state = not isinstance(init_states, (list, tuple))

    data_vars = [S.var(f"{name}_data{i}") for i in range(len(data_list))]
    state_vars = [S.var(f"{name}_state{i}") for i in range(len(states))]
    outs, new_states = body(data_vars[0] if single_data else data_vars,
                            state_vars[0] if single_state else state_vars)
    single_out = not isinstance(outs, (list, tuple))
    outs, new_states = _as_list(outs), _as_list(new_states)
    if len(new_states) != len(states):
        raise MXNetError(f"foreach: body returned {len(new_states)} states "
                         f"for {len(states)} init_states")
    subgraph = S.Group(outs + new_states)
    ph_names = {v.name for v in data_vars + state_vars}
    closure_nodes = _free_variables(subgraph, ph_names)

    node = S._Node("_foreach", name,
                   list(data_list) + list(states) +
                   [S.Symbol(n) for n in closure_nodes],
                   {"__subgraph__": subgraph,
                    "__data_vars__": [v.name for v in data_vars],
                    "__state_vars__": [v.name for v in state_vars],
                    "__closure_vars__": [n.name for n in closure_nodes],
                    "__num_outputs__": len(outs)},
                   num_outputs=len(outs) + len(new_states))
    out_syms = [S.Symbol(node, i) for i in range(len(outs))]
    st_syms = [S.Symbol(node, len(outs) + i) for i in range(len(new_states))]
    outs_r = out_syms[0] if (single_out and len(out_syms) == 1) else out_syms
    sts_r = st_syms[0] if (single_state and len(st_syms) == 1) else st_syms
    return outs_r, sts_r


def while_loop(cond, func, loop_vars, max_iterations=None, name=None):
    """Symbolic bounded while (ref: symbol/contrib.py while_loop).
    Outputs are stacked to axis-0 length ``max_iterations``; rows past
    the executed steps are zeros (the reference's padding)."""
    S = _sym_mod()
    if max_iterations is None:
        raise MXNetError("while_loop: max_iterations is required "
                         "(static shapes; the reference requires it too)")
    name = name or S._NameManager.next_name("while_loop")
    lvs = _as_list(loop_vars)
    single = not isinstance(loop_vars, (list, tuple))

    lv_vars = [S.var(f"{name}_loopvar{i}") for i in range(len(lvs))]
    cond_out = cond(*lv_vars)
    outs, new_lvs = func(*lv_vars)
    single_out = not isinstance(outs, (list, tuple))
    outs, new_lvs = _as_list(outs), _as_list(new_lvs)
    if len(new_lvs) != len(lvs):
        raise MXNetError(f"while_loop: func returned {len(new_lvs)} loop "
                         f"vars for {len(lvs)}")
    body_graph = S.Group(outs + new_lvs)
    ph_names = {v.name for v in lv_vars}
    closure_nodes = _free_variables(S.Group([cond_out] + outs + new_lvs),
                                    ph_names)
    node = S._Node("_while_loop", name,
                   list(lvs) + [S.Symbol(n) for n in closure_nodes],
                   {"__cond_graph__": cond_out,
                    "__body_graph__": body_graph,
                    "__loop_vars__": [v.name for v in lv_vars],
                    "__closure_vars__": [n.name for n in closure_nodes],
                    "__num_outputs__": len(outs),
                    "__max_iterations__": int(max_iterations)},
                   num_outputs=len(outs) + len(new_lvs))
    out_syms = [S.Symbol(node, i) for i in range(len(outs))]
    st_syms = [S.Symbol(node, len(outs) + i) for i in range(len(new_lvs))]
    outs_r = out_syms[0] if (single_out and len(out_syms) == 1) else out_syms
    sts_r = st_syms[0] if (single and len(st_syms) == 1) else st_syms
    return outs_r, sts_r


def cond(pred, then_func, else_func, name=None):
    """Symbolic branch (ref: symbol/contrib.py cond): ``pred`` is a
    scalar Symbol; the thunks return same-shaped symbols."""
    S = _sym_mod()
    name = name or S._NameManager.next_name("cond")
    then_out = _as_list(then_func())
    else_out = _as_list(else_func())
    single_out = len(then_out) == 1
    if len(then_out) != len(else_out):
        raise MXNetError("cond: branches must return the same number of "
                         "outputs")
    then_graph = S.Group(then_out)
    else_graph = S.Group(else_out)
    closure_nodes = _free_variables(S.Group(then_out + else_out), set())
    node = S._Node("_cond", name,
                   [pred] + [S.Symbol(n) for n in closure_nodes],
                   {"__then_graph__": then_graph,
                    "__else_graph__": else_graph,
                    "__closure_vars__": [n.name for n in closure_nodes],
                    "__num_outputs__": len(then_out)},
                   num_outputs=len(then_out))
    outs = [S.Symbol(node, i) for i in range(len(then_out))]
    return outs[0] if single_out else outs


# ---------------------------------------------------------------------------
# execution — shared by Symbol._make_eval_fn (real arrays) and
# Symbol.infer_shape (jax.eval_shape over the same function)
# ---------------------------------------------------------------------------

def control_flow_fn(node, training):
    """Pure jax function ``fn(*input_arrays) -> tuple(outputs)`` for a
    control-flow node. Aux-state updates inside scanned subgraphs
    (BatchNorm EMA in a loop body) are dropped — a documented divergence;
    hoist the norm out of the loop or use use_global_stats."""
    a = node.attrs
    if node.op == "_foreach":
        sub_run = a["__subgraph__"]._make_eval_fn(training=training)
        d_names, s_names = a["__data_vars__"], a["__state_vars__"]
        c_names = a["__closure_vars__"]
        n_out = a["__num_outputs__"]

        def fn(*arrays):
            nd_, ns_ = len(d_names), len(s_names)
            datas = arrays[:nd_]
            init = tuple(arrays[nd_:nd_ + ns_])
            closure = dict(zip(c_names, arrays[nd_ + ns_:]))

            def step(carry, xs):
                vals = dict(closure)
                vals.update(zip(d_names, xs))
                vals.update(zip(s_names, carry))
                outs, _aux = sub_run(vals)
                return tuple(outs[n_out:]), tuple(outs[:n_out])

            final, stacked = lax.scan(step, init, tuple(datas))
            return tuple(stacked) + tuple(final)
        return fn

    if node.op == "_while_loop":
        cond_run = a["__cond_graph__"]._make_eval_fn(training=training)
        body_run = a["__body_graph__"]._make_eval_fn(training=training)
        lv_names, c_names = a["__loop_vars__"], a["__closure_vars__"]
        n_out = a["__num_outputs__"]
        max_it = a["__max_iterations__"]

        def fn(*arrays):
            nlv = len(lv_names)
            init = tuple(arrays[:nlv])
            closure = dict(zip(c_names, arrays[nlv:]))

            def step(carry, _):
                done, cur = carry
                vals = dict(closure)
                vals.update(zip(lv_names, cur))
                (c,), _ = cond_run(vals)
                keep = jnp.logical_and(jnp.logical_not(done),
                                       jnp.reshape(c, ()).astype(bool))
                outs, _aux = body_run(vals)
                new = tuple(jnp.where(keep, n, o)
                            for n, o in zip(outs[n_out:], cur))
                masked = tuple(jnp.where(keep, o, jnp.zeros_like(o))
                               for o in outs[:n_out])
                return (jnp.logical_not(keep) | done, new), masked

            (_, final), stacked = lax.scan(
                step, (jnp.bool_(False), init), None, length=max_it)
            return tuple(stacked) + tuple(final)
        return fn

    if node.op == "_cond":
        then_run = a["__then_graph__"]._make_eval_fn(training=training)
        else_run = a["__else_graph__"]._make_eval_fn(training=training)
        c_names = a["__closure_vars__"]

        def fn(pred, *arrays):
            vals = dict(zip(c_names, arrays))
            t_outs, _ = then_run(vals)
            e_outs, _ = else_run(vals)
            p = jnp.reshape(pred, ()).astype(bool)
            return tuple(jnp.where(p, t, e)
                         for t, e in zip(t_outs, e_outs))
        return fn

    raise MXNetError(f"not a control-flow node: {node.op}")


# -- serialization -----------------------------------------------------------

_GRAPH_KEYS = ("__subgraph__", "__cond_graph__", "__body_graph__",
               "__then_graph__", "__else_graph__")
_LIST_KEYS = ("__data_vars__", "__state_vars__", "__loop_vars__",
              "__closure_vars__")
_INT_KEYS = ("__num_outputs__", "__max_iterations__")


def serialize_attrs(attrs):
    """attrs -> json-safe strings (called from Symbol.tojson)."""
    out = {}
    for k, v in attrs.items():
        out[k] = v.tojson() if k in _GRAPH_KEYS else str(v)
    return out


def deserialize_attrs(raw, op):
    """Rebuild live attrs from loaded json strings."""
    import ast

    from . import symbol as S
    attrs = {}
    for k, v in raw.items():
        if k in _GRAPH_KEYS:
            attrs[k] = S.load_json(v)
        elif k in _LIST_KEYS:
            attrs[k] = list(ast.literal_eval(v))
        elif k in _INT_KEYS:
            attrs[k] = int(v)
        else:
            attrs[k] = v
    return attrs


def num_outputs_of_node(op, attrs):
    if op == "_foreach":
        return attrs["__num_outputs__"] + len(attrs["__state_vars__"])
    if op == "_while_loop":
        return attrs["__num_outputs__"] + len(attrs["__loop_vars__"])
    return attrs["__num_outputs__"]

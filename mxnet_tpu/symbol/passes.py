"""Graph passes — the NNVM pass machinery + subgraph-hook analog
(ref: nnvm::ApplyPass / src/operator/subgraph/ SubgraphProperty,
env MXNET_SUBGRAPH_BACKEND; SURVEY §2.2 #12).

XLA already does the heavy rewriting (fusion, layout, CSE *within* a
compiled program); these passes operate on the Symbol DAG *before* bind,
where graph-level decisions live — dedup of repeated subgraphs across the
Python-built DAG, pattern substitutions toward custom kernels, etc.
Custom backends register passes and are selected with
``MXNET_SUBGRAPH_BACKEND=<name>[,<name>…]`` exactly like the reference's
subgraph-backend hook.
"""
from __future__ import annotations

import warnings

from ..base import MXNetError, getenv
from ..ops import registry as _registry
from .symbol import Symbol, _Node

__all__ = ["register_pass", "apply_pass", "apply_env_passes", "list_passes"]

_PASSES = {}


def register_pass(name):
    """Decorator: register ``fn(Symbol) -> Symbol`` as a named pass."""
    def deco(fn):
        _PASSES[name] = fn
        return fn
    return deco


def list_passes():
    return sorted(_PASSES)


def apply_pass(sym: Symbol, name: str) -> Symbol:
    """ref: nnvm::ApplyPass."""
    if name not in _PASSES:
        raise MXNetError(f"unknown graph pass {name!r}; "
                         f"known: {list_passes()}")
    return _PASSES[name](sym)


def apply_env_passes(sym: Symbol) -> Symbol:
    """Apply the passes selected by MXNET_SUBGRAPH_BACKEND (comma list) —
    the reference's subgraph-backend activation point (bind time)."""
    backends = getenv("MXNET_SUBGRAPH_BACKEND", "")
    for name in filter(None, (b.strip() for b in backends.split(","))):
        if name in _PASSES:
            sym = _PASSES[name](sym)
        else:                  # lenient like the reference, but visible
            warnings.warn(f"MXNET_SUBGRAPH_BACKEND: unknown pass {name!r} "
                          f"ignored (known: {list_passes()})")
    return sym


@register_pass("CSE")
def common_subexpression_elimination(sym: Symbol) -> Symbol:
    """Merge structurally identical nodes (same op, same attrs, same
    inputs) so duplicated Python-built subgraphs compile & execute once
    (ref: nnvm pass 'CommonSubexprElim' era; XLA CSEs *within* a program,
    this dedups at the graph level so shared work is traced once)."""
    canon = {}      # signature -> canonical _Node
    rebuilt = {}    # id(old node) -> new _Node

    def key_of(node, new_inputs):
        # op node signature: names intentionally excluded — structurally
        # identical ops are the same computation regardless of name
        attrs = tuple(sorted((k, str(v)) for k, v in node.attrs.items()))
        ins = tuple((id(s._node), s._index) for s in new_inputs)
        return (node.op, attrs, ins)

    def _mergeable(node):
        if node.op is None or node.op == "_group":
            return False
        try:
            op = _registry.get(node.op)
        except MXNetError:
            return False
        # stochastic ops draw a fresh PRNG key per node — merging them
        # would collapse independent random draws into one shared draw
        return not op.needs_rng

    def rebuild(node):
        if id(node) in rebuilt:
            return rebuilt[id(node)]
        new_inputs = [Symbol(rebuild(s._node), s._index)
                      for s in node.inputs]
        # variables unify by NAME (two auto-created `fc_weight` vars are
        # one argument — binding is name-keyed); ops unify structurally
        if node.op is None:
            sig = ("var", node.name)
        elif _mergeable(node):
            sig = key_of(node, new_inputs)
        else:
            sig = ("unique", id(node))
        if sig in canon:
            new = canon[sig]
        else:
            new = _Node(node.op, node.name, new_inputs, dict(node.attrs),
                        num_outputs=node.num_outputs)
            canon[sig] = new
        rebuilt[id(node)] = new
        return new

    return Symbol(rebuild(sym._node), sym._index)

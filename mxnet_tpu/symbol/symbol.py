"""Symbol — the lazy graph-composition API (TF1-style world).

TPU-native re-design of the reference's symbolic layer
(ref: python/mxnet/symbol/symbol.py Symbol; nnvm::Symbol/Graph under
src/c_api/c_api_symbolic.cc). Design:

- a Symbol is an output slot of a small immutable node (op name, input
  symbols, hyperparameters) — the same DAG the reference builds via NNVM;
- executing/binding compiles the DAG into ONE jitted XLA program (the
  GraphExecutor's memory planning, op fusion and scheduling are XLA's job —
  SURVEY §2.2 #11 translation row);
- ``infer_shape``/``infer_type`` run ``jax.eval_shape`` over the traced
  program: no per-op inference rules, yet partial inference works because
  tracing is abstract (no FLOPs run);
- auto-created parameter variables follow the reference's naming exactly
  (``fullyconnected0_weight`` …) so `list_arguments` orders match and
  checkpoints interoperate.
"""
from __future__ import annotations

import json
import threading

import jax
import numpy as np

from .. import _rng
from ..base import MXNetError, _as_np_dtype
from ..context import current_context
from ..ops import registry as _registry
from . import control_flow as _cflow

__all__ = ["Symbol", "var", "Variable", "Group", "load", "load_json",
           "zeros", "ones", "arange"]

# input-slot names + aux split per layer op (the reference records these in
# each op's FListInputNames/FMutateInputs; ref: src/operator/nn/*.cc)
_OP_INPUTS = {
    "FullyConnected": (["data", "weight", "bias"], 0),
    "Convolution": (["data", "weight", "bias"], 0),
    "Deconvolution": (["data", "weight", "bias"], 0),
    "BatchNorm": (["data", "gamma", "beta", "moving_mean", "moving_var"], 2),
    "LayerNorm": (["data", "gamma", "beta"], 0),
    "GroupNorm": (["data", "gamma", "beta"], 0),
    "InstanceNorm": (["data", "gamma", "beta"], 0),
    "Embedding": (["data", "weight"], 0),
    "_contrib_DeformableConvolution": (
        ["data", "offset", "weight", "bias"], 0),
    "_contrib_ModulatedDeformableConvolution": (
        ["data", "offset", "mask", "weight", "bias"], 0),
    "RNN": (["data", "parameters", "state", "state_cell"], 0),
    "LeakyReLU": (["data", "gamma"], 0),
    "SoftmaxOutput": (["data", "label"], 0),
    "LinearRegressionOutput": (["data", "label"], 0),
    "MAERegressionOutput": (["data", "label"], 0),
    "LogisticRegressionOutput": (["data", "label"], 0),
}
# params that suppress trailing inputs (no_bias ⇒ drop bias)
_SUPPRESS = {"no_bias": "bias"}


def _infer_param_shapes(opname, attrs, data_shape):
    """Backward shape rules: parameter shapes implied by the data shape —
    what each reference op's InferShape does (ref: src/operator/nn/
    fully_connected.cc FullyConnectedShape, convolution.cc ConvolutionShape,
    batch_norm.cc BatchNormShape, rnn.cc RNNShape, …)."""
    out = {}
    if data_shape is None:
        return out
    d = tuple(data_shape)
    if opname == "FullyConnected":
        flatten = attrs.get("flatten", True)
        in_dim = int(np.prod(d[1:])) if flatten else d[-1]
        out["weight"] = (attrs["num_hidden"], in_dim)
        out["bias"] = (attrs["num_hidden"],)
    elif opname == "Convolution":
        kernel = tuple(attrs["kernel"])
        ng = attrs.get("num_group", 1) or 1
        out["weight"] = (attrs["num_filter"], d[1] // ng) + kernel
        out["bias"] = (attrs["num_filter"],)
    elif opname == "Deconvolution":
        kernel = tuple(attrs["kernel"])
        ng = attrs.get("num_group", 1) or 1
        out["weight"] = (d[1], attrs["num_filter"] // ng) + kernel
        out["bias"] = (attrs["num_filter"],)
    elif opname == "BatchNorm":
        c = d[attrs.get("axis", 1)]
        for s in ("gamma", "beta", "moving_mean", "moving_var"):
            out[s] = (c,)
    elif opname == "LayerNorm":
        c = d[attrs.get("axis", -1)]
        out["gamma"] = (c,)
        out["beta"] = (c,)
    elif opname in ("GroupNorm", "InstanceNorm"):
        out["gamma"] = (d[1],)
        out["beta"] = (d[1],)
    elif opname == "Embedding":
        out["weight"] = (attrs["input_dim"], attrs["output_dim"])
    elif opname == "SoftmaxOutput":
        if attrs.get("multi_output"):
            out["label"] = (d[0],) + d[2:]
        else:
            out["label"] = (d[0],)
    elif opname.endswith("RegressionOutput"):
        out["label"] = d
    elif opname == "LeakyReLU" and attrs.get("act_type") == "prelu":
        out["gamma"] = (d[1],)
    elif opname == "RNN":
        h = attrs["state_size"]
        nl = attrs["num_layers"]
        ndir = 2 if attrs.get("bidirectional") else 1
        g = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}[
            attrs.get("mode", "lstm")]
        size = 0
        for layer in range(nl):
            in_sz = d[-1] if layer == 0 else h * ndir
            size += ndir * (g * h * in_sz + g * h * h + 2 * g * h)
        out["parameters"] = (size,)
        out["state"] = (nl * ndir, d[1], h)
        out["state_cell"] = (nl * ndir, d[1], h)
    return out

_name_lock = threading.Lock()


class _NameManager:
    _counts = {}

    @classmethod
    def next_name(cls, hint):
        with _name_lock:
            idx = cls._counts.get(hint, 0)
            cls._counts[hint] = idx + 1
        return f"{hint}{idx}"


class _Node:
    __slots__ = ("op", "name", "inputs", "attrs", "num_outputs")

    def __init__(self, op, name, inputs, attrs, num_outputs=1):
        self.op = op              # None for variables
        self.name = name
        self.inputs = inputs      # list[Symbol]
        self.attrs = attrs        # coerced op params
        self.num_outputs = num_outputs


class Symbol:
    """One output of a graph node (ref: symbol.py Symbol)."""

    def __init__(self, node, index=0):
        self._node = node
        self._index = index

    # -- identity ------------------------------------------------------------
    @property
    def name(self):
        n = self._node
        if n.num_outputs > 1 and n.op is not None:
            return f"{n.name}_output{self._index}"
        return n.name

    def __repr__(self):
        return f"<Symbol {self.name}>"

    def attr(self, key):
        return self._node.attrs.get(key)

    def list_attr(self):
        return {k: str(v) for k, v in self._node.attrs.items()}

    # -- graph walks ---------------------------------------------------------
    def _topo(self):
        """Topological order of nodes reachable from this output."""
        seen = {}
        order = []

        def visit(node):
            if id(node) in seen:
                return
            seen[id(node)] = node
            for s in node.inputs:
                visit(s._node)
            order.append(node)
        visit(self._node)
        return order

    def list_arguments(self):
        """Variable names in topo order, aux excluded (ref: list_arguments)."""
        args = []
        aux = set(self.list_auxiliary_states())
        for node in self._topo():
            if node.op is None and node.name not in aux:
                args.append(node.name)
        return args

    def list_auxiliary_states(self):
        """ref: list_auxiliary_states — inputs mutated by the op (BatchNorm
        running stats), recognized by input-slot position."""
        aux = []
        for node in self._topo():
            if node.op is None:
                continue
            names, n_aux = _OP_INPUTS.get(node.op, (None, 0))
            if n_aux:
                for s in node.inputs[len(names) - n_aux:]:
                    if s._node.op is None and s._node.name not in aux:
                        aux.append(s._node.name)
        return aux

    def _output_name(self):
        n = self._node
        if n.op is None:
            return n.name
        if n.num_outputs > 1:
            return f"{n.name}_output{self._index}"
        return f"{n.name}_output"

    def list_outputs(self):
        n = self._node
        if n.op == "_group":
            return [s._output_name() for s in n.inputs]
        return [Symbol(n, i)._output_name() for i in range(n.num_outputs)] \
            if n.op is not None else [n.name]

    def get_internals(self):
        """ref: Symbol.get_internals — every node output as a Group."""
        outs = []
        for node in self._topo():
            for i in range(node.num_outputs):
                outs.append(Symbol(node, i))
        return Group(outs)

    def __iter__(self):
        """Iterate over this node's outputs (lets ``a, b = F.split(...)``
        style unpacking work identically to the nd namespace)."""
        if self._node.op == "_group":
            return iter(self._node.inputs)
        return (Symbol(self._node, i)
                for i in range(self._node.num_outputs))

    def __len__(self):
        if self._node.op == "_group":
            return len(self._node.inputs)
        return self._node.num_outputs

    def __getitem__(self, index):
        if isinstance(index, str):
            for i, name in enumerate(self.list_outputs()):
                if name == index:
                    index = i
                    break
            else:
                raise MXNetError(f"no output named {index!r}")
        if self._node.op == "_group":
            return self._node.inputs[index]
        return Symbol(self._node, index)

    # -- evaluation ----------------------------------------------------------
    def _output_symbols(self):
        if self._node.op == "_group":
            return list(self._node.inputs)
        return [self]

    def _make_eval_fn(self, training=False, capture_re=None):
        """Compile the DAG into fn(var_dict) -> (outputs, aux_updates).
        ``capture_re``: compiled regex — matching op outputs (named
        '<node>_output' like the reference Monitor) are added to
        aux_updates under '__monitor__:' keys."""
        out_syms = self._output_symbols()

        def run(values):
            cache = {}
            aux_updates = {}

            def compute(node):
                if id(node) in cache:
                    return cache[id(node)]
                if node.op is None:
                    try:
                        res = [values[node.name]]
                    except KeyError:
                        raise MXNetError(
                            f"symbol variable {node.name!r} was not bound")
                elif node.op == "_group":
                    res = [compute(s._node)[s._index] for s in node.inputs]
                elif node.op in _cflow.CONTROL_FLOW_OPS:
                    arrays = [compute(s._node)[s._index]
                              for s in node.inputs]
                    res = list(_cflow.control_flow_fn(node, training)
                               (*arrays))
                else:
                    op = _registry.get(node.op)
                    arrays = [compute(s._node)[s._index]
                              for s in node.inputs]
                    kwargs = {k: v for k, v in node.attrs.items()
                              if not k.startswith("__")}
                    if op.needs_rng:
                        kwargs["rng"] = _rng.next_key()
                    if op.needs_mode:
                        kwargs["training"] = training
                    out = op.fn(*arrays, **kwargs)
                    res = list(out) if isinstance(out, tuple) else [out]
                    # BatchNorm running-stat EMA: outputs 1/2 are the batch
                    # mean/var; in training they update the moving_* aux
                    # vars (ref: src/operator/nn/batch_norm.cc Forward)
                    if node.op == "BatchNorm" and training and \
                            not node.attrs.get("use_global_stats"):
                        mom = node.attrs.get("momentum", 0.9)
                        for s, stat in ((node.inputs[3], res[1]),
                                        (node.inputs[4], res[2])):
                            if s._node.op is None:
                                old = values[s._node.name]
                                aux_updates[s._node.name] = \
                                    mom * old + (1 - mom) * stat
                cache[id(node)] = res
                if capture_re is not None and node.op is not None and \
                        node.op != "_group":
                    # monitored intermediates ride back as EXTRA outputs
                    # (reserved-prefix aux entries) — the jit-friendly way
                    # to observe inside a compiled program; the reference's
                    # Monitor instead hooks the engine's NDArray callbacks
                    # (ref: python/mxnet/monitor.py install -> MXExecutor
                    # SetMonitorCallback)
                    mon_name = f"{node.name}_output"
                    if capture_re.match(mon_name):
                        aux_updates[f"__monitor__:{mon_name}"] = res[0]
                return res
            outs = [compute(s._node)[s._index] for s in out_syms]
            return outs, aux_updates
        return run

    def eval(self, ctx=None, **kwargs):
        """ref: Symbol.eval — eager evaluation with named inputs."""
        from .. import ndarray as nd
        values = {k: (v._data if isinstance(v, nd.NDArray)
                      else np.asarray(v)) for k, v in kwargs.items()}
        outs, _ = self._make_eval_fn(training=False)(values)
        return [nd.NDArray(o, _skip_device_put=True) for o in outs]

    # -- inference -----------------------------------------------------------
    def infer_shape(self, *args, **kwargs):
        """ref: Symbol.infer_shape → (arg_shapes, out_shapes, aux_shapes).
        Unknown arguments are inferred where possible by abstract tracing
        with placeholder dims; None for those that cannot be."""
        arg_names = self.list_arguments()
        aux_names = self.list_auxiliary_states()
        known = {}
        if args:
            for name, shape in zip(arg_names, args):
                if shape is not None:
                    known[name] = tuple(shape)
        known.update({k: tuple(v) for k, v in kwargs.items()
                      if v is not None})
        # partial inference: walk the graph propagating shapes via
        # jax.eval_shape node by node
        shapes = dict(known)
        dtypes = {}

        def node_shape(node):
            if node.op is None:
                if node.name in shapes:
                    return [jax.ShapeDtypeStruct(shapes[node.name],
                                                 np.float32)]
                # try declared shape attr (var(shape=...))
                shp = node.attrs.get("__shape__")
                if shp:
                    shapes[node.name] = tuple(shp)
                    return [jax.ShapeDtypeStruct(tuple(shp), np.float32)]
                return None
            if node.op == "_group":
                rs = [cached_node_shape(s._node) for s in node.inputs]
                if any(r is None for r in rs):
                    return None
                return [r[s._index] for r, s in zip(rs, node.inputs)]
            if node.op in _cflow.CONTROL_FLOW_OPS:
                in_shapes2 = []
                for s in node.inputs:
                    r = cached_node_shape(s._node)
                    if r is None:
                        return None
                    in_shapes2.append(r[s._index])
                try:
                    out = jax.eval_shape(
                        _cflow.control_flow_fn(node, False), *in_shapes2)
                except Exception:
                    return None
                return list(out)
            # backward parameter-shape rules: data shape ⇒ weight shapes
            if node.inputs:
                data_r = cached_node_shape(node.inputs[0]._node)
                data_shape = tuple(data_r[node.inputs[0]._index].shape) \
                    if data_r is not None else None
                rules = _infer_param_shapes(node.op, node.attrs, data_shape)
                names, _ = _OP_INPUTS.get(node.op, (None, 0))
                if rules and names:
                    for slot, s in zip(names, node.inputs):
                        if s._node.op is None and \
                                s._node.name not in shapes and \
                                slot in rules:
                            shapes[s._node.name] = rules[slot]
            in_shapes = []
            for s in node.inputs:
                r = cached_node_shape(s._node)
                if r is None:
                    return None
                in_shapes.append(r[s._index])
            op = _registry.get(node.op)
            kwargs2 = {k: v for k, v in node.attrs.items()
                       if not k.startswith("__")}
            if op.needs_mode:
                kwargs2["training"] = False
            # the key rides as an ABSTRACT eval_shape argument (legacy
            # uint32[2] layout): a concrete PRNGKey here would dial the
            # backend during shape inference — the G1/G2 import-wedge
            # class, and infer_shape must stay backend-free
            key_arg = (jax.ShapeDtypeStruct((2,), np.uint32),) \
                if op.needs_rng else ()

            def fn(*arrs):
                kk = dict(kwargs2)
                if op.needs_rng:
                    kk["rng"] = arrs[0]
                    arrs = arrs[1:]
                out = op.fn(*arrs, **kk)
                return out
            try:
                out = jax.eval_shape(fn, *key_arg, *in_shapes)
            except Exception:
                return None
            outs = list(out) if isinstance(out, (tuple, list)) else [out]
            return outs

        memo = {}

        def cached_node_shape(node):
            if node.op is None:     # vars re-read `shapes` (rules fill it)
                return node_shape(node)
            if id(node) not in memo:
                memo[id(node)] = node_shape(node)
            return memo[id(node)]

        # layer-op parameter inference (deferred shapes): walk nodes; when a
        # layer op's data shape is known but its weights are variables with
        # unknown shape, try candidate shapes via the op's shape rule — the
        # reference does this in each op's InferShape. Here we instead derive
        # them from the op registry's eval when possible; if not, leave None.
        out_shapes = []
        res = cached_node_shape(self._node)
        if res is not None:
            if self._node.op == "_group":
                out_shapes = [tuple(r.shape) for r in res]
            else:
                out_shapes = [tuple(res[s._index].shape)
                              for s in self._output_symbols()]
        else:
            out_shapes = None
        arg_shapes = [shapes.get(n) for n in arg_names]
        aux_shapes = [shapes.get(n) for n in aux_names]
        return arg_shapes, out_shapes, aux_shapes

    def infer_type(self, *args, **kwargs):
        arg_names = self.list_arguments()
        return ([np.float32] * len(arg_names), [np.float32],
                [np.float32] * len(self.list_auxiliary_states()))

    # -- serialization (ref: Symbol.tojson / save) ---------------------------
    def tojson(self):
        nodes = []
        index = {}
        topo = self._topo()
        for node in topo:
            index[id(node)] = len(nodes)
            entry = {
                "op": "null" if node.op is None else node.op,
                "name": node.name,
                "inputs": [[index[id(s._node)], s._index, 0]
                           for s in node.inputs],
            }
            if node.op in _cflow.CONTROL_FLOW_OPS:
                attrs = _cflow.serialize_attrs(node.attrs)
            else:
                attrs = {k: str(v) for k, v in node.attrs.items()
                         if v is not None}
            if attrs:
                entry["attrs"] = attrs
            nodes.append(entry)
        arg_nodes = [i for i, n in enumerate(topo) if n.op is None]
        heads = [[index[id(s._node)], s._index, 0]
                 for s in self._output_symbols()]
        return json.dumps({"nodes": nodes, "arg_nodes": arg_nodes,
                           "heads": heads,
                           "attrs": {"mxnet_version": ["int", 10700]}},
                          indent=2)

    def save(self, fname):
        from ..resilience.atomic import atomic_write
        with atomic_write(fname, "w") as f:
            f.write(self.tojson())

    # -- binding (ref: simple_bind/bind → GraphExecutor) ---------------------
    def simple_bind(self, ctx=None, grad_req="write", **kwargs):
        from .executor import Executor
        from .. import ndarray as nd
        ctx = ctx or current_context()
        arg_shapes, _, aux_shapes = self.infer_shape(**kwargs)
        arg_names = self.list_arguments()
        aux_names = self.list_auxiliary_states()
        args = {}
        for name, shape in zip(arg_names, arg_shapes):
            if shape is None:
                raise MXNetError(f"simple_bind: could not infer shape of "
                                 f"{name!r}; pass it explicitly")
            args[name] = nd.zeros(shape, ctx=ctx)
        aux = {}
        for name, shape in zip(aux_names, aux_shapes):
            if shape is None:
                raise MXNetError(f"simple_bind: could not infer shape of "
                                 f"aux {name!r}")
            aux[name] = nd.zeros(shape, ctx=ctx)
        return Executor(self, ctx, args, grad_req=grad_req, aux_states=aux)

    def bind(self, ctx, args, args_grad=None, grad_req="write",
             aux_states=None, shared_exec=None):
        from .executor import Executor
        arg_names = self.list_arguments()
        if isinstance(args, (list, tuple)):
            args = dict(zip(arg_names, args))
        if isinstance(aux_states, (list, tuple)):
            aux_states = dict(zip(self.list_auxiliary_states(), aux_states))
        if isinstance(args_grad, (list, tuple)):
            args_grad = dict(zip(arg_names, args_grad))
        return Executor(self, ctx, args, args_grad=args_grad,
                        grad_req=grad_req, aux_states=aux_states or {})

    # -- operators -----------------------------------------------------------
    def _binop(self, other, opname, reverse=False):
        if isinstance(other, Symbol):
            a, b = (other, self) if reverse else (self, other)
            return _create(opname, [a, b], {})
        scalar_op = {"elemwise_add": "_plus_scalar",
                     "elemwise_sub": "_rminus_scalar" if reverse
                     else "_minus_scalar",
                     "elemwise_mul": "_mul_scalar",
                     "elemwise_div": "_rdiv_scalar" if reverse
                     else "_div_scalar",
                     "_power": "_rpower_scalar" if reverse
                     else "_power_scalar"}[opname]
        return _create(scalar_op, [self], {"scalar": float(other)})

    def __add__(self, other):
        return self._binop(other, "elemwise_add")
    __radd__ = __add__

    def __sub__(self, other):
        return self._binop(other, "elemwise_sub")

    def __rsub__(self, other):
        return self._binop(other, "elemwise_sub", reverse=True)

    def __mul__(self, other):
        return self._binop(other, "elemwise_mul")
    __rmul__ = __mul__

    def __truediv__(self, other):
        return self._binop(other, "elemwise_div")

    def __rtruediv__(self, other):
        return self._binop(other, "elemwise_div", reverse=True)

    def __pow__(self, other):
        return self._binop(other, "_power")

    def __neg__(self):
        return self._binop(-1.0, "elemwise_mul")

    # comparisons build graph nodes like NDArray's (ref: symbol.py
    # __eq__ et al. delegate to broadcast_* / *_scalar ops)
    def _cmpop(self, other, broadcast_name, scalar_name):
        if isinstance(other, Symbol):
            return _create(broadcast_name, [self, other], {})
        return _create(scalar_name, [self], {"scalar": float(other)})

    def __eq__(self, other):
        return self._cmpop(other, "broadcast_equal", "_equal_scalar")

    def __ne__(self, other):
        return self._cmpop(other, "broadcast_not_equal",
                           "_not_equal_scalar")

    # __eq__ builds a graph node, so identity hashing must be kept:
    # Symbols live in dicts/sets throughout the composer
    __hash__ = object.__hash__

    def __lt__(self, other):
        return self._cmpop(other, "broadcast_lesser", "_lesser_scalar")

    def __le__(self, other):
        return self._cmpop(other, "broadcast_lesser_equal",
                           "_lesser_equal_scalar")

    def __gt__(self, other):
        return self._cmpop(other, "broadcast_greater", "_greater_scalar")

    def __ge__(self, other):
        return self._cmpop(other, "broadcast_greater_equal",
                           "_greater_equal_scalar")


def _auto_var(name, attrs=None):
    return Symbol(_Node(None, name, [], attrs or {}))


def var(name, attr=None, shape=None, lr_mult=None, wd_mult=None, dtype=None,
        init=None, stype=None, **kwargs):
    """ref: symbol.py var/Variable."""
    attrs = dict(attr or {})
    if shape is not None:
        attrs["__shape__"] = tuple(shape)
    if lr_mult is not None:
        attrs["__lr_mult__"] = lr_mult
    if wd_mult is not None:
        attrs["__wd_mult__"] = wd_mult
    if dtype is not None:
        attrs["__dtype__"] = _as_np_dtype(dtype)
    if init is not None:
        attrs["__init__"] = init
    return _auto_var(name, attrs)


Variable = var


def Group(symbols):
    """ref: symbol.py Group — multi-output symbol."""
    symbols = list(symbols)
    if not symbols:
        raise MXNetError("Group needs at least one symbol")
    node = _Node("_group", _NameManager.next_name("group"), symbols, {},
                 num_outputs=len(symbols))
    return Symbol(node)


def _num_outputs_of(op, attrs):
    n = op.num_outputs
    return n(attrs) if callable(n) else n


def _create(opname, input_syms, kwargs, name=None):
    """Create an op node (the generated mx.sym.<op> wrappers call this)."""
    from .. import attribute as _attr_mod
    from .. import name as _name_mod
    op = _registry.get(opname)
    attrs = op.coerce_params(kwargs)
    hint = opname.lower().lstrip("_")
    scoped = _name_mod.current()
    if name is None and type(scoped) is not _name_mod.NameManager:
        name = scoped.get(None, hint)       # Prefix or custom manager
    name = name or _NameManager.next_name(hint)
    # scoped attrs (ctx_group & friends, ref: AttrScope.get)
    scope_attrs = _attr_mod.current().get()
    for k, v in scope_attrs.items():
        attrs.setdefault(f"__{k}__" if not k.startswith("__") else k, v)
    # auto-create missing parameter variables with reference naming
    names, n_aux = _OP_INPUTS.get(opname, (None, 0))
    if names is not None:
        syms = list(input_syms)
        want = list(names)
        for pkey, drop in _SUPPRESS.items():
            if attrs.get(pkey) and drop in want:
                want.remove(drop)
        if opname == "RNN" and attrs.get("mode") != "lstm" and \
                "state_cell" in want:
            want.remove("state_cell")
        if opname == "LeakyReLU" and "gamma" in want and \
                str(attrs.get("act_type", "leaky")) != "prelu":
            want.remove("gamma")    # only prelu carries a learned slope
        while len(syms) < len(want):
            syms.append(_auto_var(f"{name}_{want[len(syms)]}"))
        input_syms = syms
    n_out = _num_outputs_of(op, attrs)
    # declared outputs only; aux-update extras are consumed by the executor
    node = _Node(opname, name, list(input_syms), attrs, num_outputs=n_out)
    return Symbol(node)


# -- creation helpers mirroring mx.sym namespace -----------------------------
def zeros(shape, dtype=None, **kwargs):
    return _create("_zeros", [], {"shape": shape, "dtype": dtype or "float32"})


def ones(shape, dtype=None, **kwargs):
    return _create("_ones", [], {"shape": shape, "dtype": dtype or "float32"})


def arange(start, stop=None, step=1.0, **kwargs):
    return _create("_arange", [], {"start": start, "stop": stop,
                                   "step": step})


def load_json(json_str):
    """Rebuild a Symbol from the serialized graph (ref: sym.load_json)."""
    graph = json.loads(json_str)
    nodes = graph["nodes"]
    built = []
    for entry in nodes:
        inputs = [Symbol(built[i], oi) for i, oi, _ in entry.get("inputs", [])]
        if entry["op"] == "null":
            attrs = entry.get("attrs", {})
            parsed = {}
            for k, v in attrs.items():
                if k == "__shape__":
                    import ast
                    parsed[k] = tuple(ast.literal_eval(v))
                else:
                    parsed[k] = v
            node = _Node(None, entry["name"], [], parsed)
        elif entry["op"] == "_group":
            node = _Node("_group", entry["name"], inputs, {},
                         num_outputs=len(inputs))
        elif entry["op"] in _cflow.CONTROL_FLOW_OPS:
            attrs = _cflow.deserialize_attrs(entry.get("attrs", {}),
                                             entry["op"])
            node = _Node(entry["op"], entry["name"], inputs, attrs,
                         num_outputs=_cflow.num_outputs_of_node(
                             entry["op"], attrs))
        else:
            op = _registry.get(entry["op"])
            raw = entry.get("attrs", {})
            extra = {k: v for k, v in raw.items() if k.startswith("__")}
            attrs = op.coerce_params({k: v for k, v in raw.items()
                                      if not k.startswith("__")})
            attrs.update(extra)
            node = _Node(entry["op"], entry["name"], inputs, attrs,
                         num_outputs=_num_outputs_of(op, attrs))
        built.append(node)
    heads = graph["heads"]
    if len(heads) == 1:
        return Symbol(built[heads[0][0]], heads[0][1])
    return Group([Symbol(built[i], oi) for i, oi, _ in heads])


def load(fname):
    with open(fname) as f:
        return load_json(f.read())

"""``mx.sym`` — the symbolic operator namespace.

Generated from the same op registry as ``mx.nd`` (the reference generates
both from MXSymbolListAtomicSymbolCreators; ref:
python/mxnet/symbol/register.py), so every operator composes lazily into a
Symbol graph with identical semantics to its eager twin.
"""
from __future__ import annotations

import sys
import types

from ..ops import registry as _registry
from .executor import Executor
from .symbol import (Group, Symbol, Variable, arange, load, load_json, ones,
                     var, zeros)
from . import passes
from .passes import apply_pass, list_passes, register_pass

__all__ = ["Symbol", "var", "Variable", "Group", "load", "load_json",
           "zeros", "ones", "arange", "Executor", "eval_symbol",
           "passes", "apply_pass", "register_pass", "list_passes"]


def _make_wrapper(opname, op):
    param_order = [p.name for p in op.params]

    def wrapper(*args, name=None, attr=None, **kwargs):
        from .symbol import _create
        args = list(args)
        inputs = []
        while args and isinstance(args[0], Symbol):
            inputs.append(args.pop(0))
        # named-input kwargs (data=..., weight=...) like the reference
        from .symbol import _OP_INPUTS
        names, _ = _OP_INPUTS.get(opname, (["data"], 0))
        if not inputs:
            present = [n for n in names if n in kwargs]
            if present:
                for n in names:
                    if n in kwargs:
                        inputs.append(kwargs.pop(n))
                    else:
                        break
        for val, pname in zip(args, param_order):
            kwargs[pname] = val
        return _create(opname, inputs, kwargs, name=name)

    wrapper.__name__ = opname
    wrapper.__doc__ = op.signature_doc()
    return wrapper


def _new_module(name):
    mod = types.ModuleType(f"{__name__}.{name}")
    sys.modules[mod.__name__] = mod
    return mod


random = _new_module("random")
linalg = _new_module("linalg")
contrib = _new_module("contrib")
op = _new_module("op")
_internal = _new_module("_internal")

_this = sys.modules[__name__]


def _expose():
    for opname in _registry.list_ops():
        operator = _registry.get(opname)
        fn = _make_wrapper(opname, operator)
        if opname.startswith("_contrib_"):
            setattr(contrib, opname[len("_contrib_"):], fn)
        elif opname.startswith("_random_"):
            setattr(random, opname[len("_random_"):], fn)
        elif opname.startswith("_sample_"):
            setattr(random, opname[1:], fn)
        elif opname.startswith("_linalg_"):
            setattr(linalg, opname[len("_linalg_"):], fn)
        elif opname.startswith("_"):
            setattr(_internal, opname, fn)
        else:
            if opname in ("BilinearResize2D", "AdaptiveAvgPooling2D",
                          "ROIAlign", "MultiBoxPrior", "box_iou", "box_nms"):
                setattr(contrib, opname, fn)
            else:
                if not hasattr(_this, opname):
                    setattr(_this, opname, fn)
                setattr(op, opname, fn)


_expose()
_registry.install_binary_helpers(_this)

# control-flow ops take Python callables — they bypass the registry
# (ref: python/mxnet/symbol/contrib.py foreach/while_loop/cond)
from .control_flow import foreach as _cf_foreach  # noqa: E402
from .control_flow import while_loop as _cf_while_loop  # noqa: E402
from .control_flow import cond as _cf_cond  # noqa: E402

contrib.foreach = _cf_foreach
contrib.while_loop = _cf_while_loop
contrib.cond = _cf_cond


def eval_symbol(outputs, inputs, args, params):
    """Execute a symbol for SymbolBlock.forward: bind ``inputs`` (Symbols)
    to ``args`` (NDArrays) and parameter variables to ``params``."""
    from .. import ndarray as nd
    values = {}
    for sym, arr in zip(inputs, args):
        values[sym.name] = arr._data if isinstance(arr, nd.NDArray) \
            else nd.array(arr)._data
    for name, p in params.items():
        values[name] = p.data()._data
    run = outputs._make_eval_fn(training=False)
    outs, _ = run(values)
    res = [nd.NDArray(o, _skip_device_put=True) for o in outs]
    return res[0] if len(res) == 1 else res

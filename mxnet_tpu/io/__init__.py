"""``mx.io`` — data iterators (ref: python/mxnet/io/io.py, src/io/).

The reference's C++ iterator pipeline (parser → augmenter → batcher →
prefetcher, src/io/iter_image_recordio_2.cc) maps to Python iterators with a
background prefetch thread staging batches while the TPU step runs — the
double-buffering that hides input latency under compute (SURVEY §2.5 #34).
"""
from __future__ import annotations

import gzip
import os
import queue
import struct
import threading
from collections import OrderedDict, deque, namedtuple

import numpy as np

from .. import ndarray as nd
from ..base import MXNetError

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "ResizeIter",
           "PrefetchingIter", "CSVIter", "MNISTIter", "ImageRecordIter"]


class DataDesc(namedtuple("DataDesc", ["name", "shape"])):
    """ref: io.py DataDesc — name/shape/dtype/layout of one input."""

    def __new__(cls, name, shape, dtype=np.float32, layout="NCHW"):
        ret = super().__new__(cls, name, shape)
        ret.dtype = dtype
        ret.layout = layout
        return ret

    @staticmethod
    def get_batch_axis(layout):
        return 0 if layout is None else layout.find("N")


class DataBatch:
    """ref: io.py DataBatch."""

    def __init__(self, data, label=None, pad=None, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        if data is not None and not isinstance(data, (list, tuple)):
            data = [data]
        if label is not None and not isinstance(label, (list, tuple)):
            label = [label]
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label


class DataIter:
    """ref: io.py DataIter — the iterator protocol all trainers consume."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        raise NotImplementedError


def _init_data(data, allow_empty, default_name):
    """ref: io.py _init_data — normalize array/list/dict to [(name, array)]."""
    if data is None:
        if not allow_empty:
            raise MXNetError("data cannot be None")
        return []
    if isinstance(data, (np.ndarray, nd.NDArray)):
        data = [data]
    if isinstance(data, (list, tuple)):
        if len(data) == 1:
            data = OrderedDict([(default_name, data[0])])
        else:
            data = OrderedDict([(f"_{i}_{default_name}", d)
                                for i, d in enumerate(data)])
    if not isinstance(data, dict):
        raise MXNetError("data must be array, list of arrays, or dict")
    return [(k, v if isinstance(v, np.ndarray) else v.asnumpy())
            for k, v in data.items()]


def _resolve_part(num_parts, part_index):
    """Distributed read sharding (ref: src/io/iter_image_recordio_2.cc
    ``num_parts``/``part_index`` kwargs backed by dmlc InputSplit): each
    worker reads a disjoint part of the input so multi-host data-parallel
    training never consumes duplicate records. ``None`` wires to the
    launcher environment (tools/launch.py exports MXTPU_NUM_PROC /
    MXTPU_PROC_ID), so ``launch.py -n 8 train.py`` shards reads with no
    code change; single-process runs resolve to (1, 0)."""
    if num_parts is None:
        num_parts = int(os.environ.get("MXTPU_NUM_PROC", "1") or 1)
    if part_index is None:
        part_index = int(os.environ.get("MXTPU_PROC_ID", "0") or 0)
    num_parts, part_index = int(num_parts), int(part_index)
    if num_parts < 1 or not 0 <= part_index < num_parts:
        raise MXNetError(f"part_index {part_index} out of range for "
                         f"num_parts {num_parts}")
    return num_parts, part_index


def _part_bounds(n, num_parts, part_index):
    """Contiguous split [start, stop): every record lands in exactly one
    part, remainder spread over the first parts (dmlc InputSplit
    semantics — parts differ in size by at most 1)."""
    base, rem = divmod(n, num_parts)
    start = part_index * base + min(part_index, rem)
    return start, start + base + (1 if part_index < rem else 0)


class NDArrayIter(DataIter):
    """Batches over in-memory arrays (ref: io.py NDArrayIter): shuffle,
    last_batch_handle pad/discard/roll_over; ``num_parts``/``part_index``
    restrict the iterator to a contiguous shard for distributed reads."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label", num_parts=None,
                 part_index=None):
        super().__init__(batch_size)
        self.data = _init_data(data, False, data_name)
        self.label = _init_data(label, True, label_name)
        self.num_data = self.data[0][1].shape[0]
        for k, v in self.data + self.label:
            if v.shape[0] != self.num_data:
                raise MXNetError(f"{k}: all arrays must share dim 0")
        num_parts, part_index = _resolve_part(num_parts, part_index)
        if num_parts > 1:
            lo, hi = _part_bounds(self.num_data, num_parts, part_index)
            self.data = [(k, v[lo:hi]) for k, v in self.data]
            self.label = [(k, v[lo:hi]) for k, v in self.label]
            self.num_data = hi - lo
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        if last_batch_handle == "discard":
            self.num_batches = self.num_data // batch_size
        else:
            self.num_batches = (self.num_data + batch_size - 1) // batch_size
        self._order = np.arange(self.num_data)
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.label]

    def reset(self):
        if self.shuffle:
            np.random.shuffle(self._order)
        # roll_over: keep leftover rows at the front of the next epoch
        if self.last_batch_handle == "roll_over" and \
                getattr(self, "_leftover", None) is not None:
            self._order = np.concatenate([self._leftover, self._order])
            self._leftover = None
        self._cursor = 0

    def iter_next(self):
        return self._cursor < self.num_batches * self.batch_size and \
            self._cursor < self.num_data

    def next(self):
        if not self.iter_next():
            if self.last_batch_handle == "roll_over":
                start = (self.num_data // self.batch_size) * self.batch_size
                if start < self.num_data:
                    self._leftover = self._order[start:]
            raise StopIteration
        start = self._cursor
        stop = min(start + self.batch_size, self.num_data)
        idx = self._order[start:stop]
        pad = 0
        if stop - start < self.batch_size:  # pad from the beginning
            pad = self.batch_size - (stop - start)
            idx = np.concatenate([idx, self._order[:pad]])
        self._cursor += self.batch_size
        data = [nd.array(v[idx]) for _, v in self.data]
        label = [nd.array(v[idx]) for _, v in self.label]
        return DataBatch(data=data, label=label, pad=pad, index=idx,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)

    def getpad(self):
        return 0


class ResizeIter(DataIter):
    """Resize an iterator to a fixed number of batches (ref: ResizeIter)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None

    @property
    def provide_data(self):
        return self.data_iter.provide_data

    @property
    def provide_label(self):
        return self.data_iter.provide_label

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        return self.cur < self.size

    def next(self):
        if self.cur >= self.size:
            raise StopIteration
        try:
            batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            batch = self.data_iter.next()
        self.cur += 1
        return batch


class PrefetchingIter(DataIter):
    """Background-thread prefetch (ref: io.py PrefetchingIter /
    src/io/iter_prefetcher.h): the host prepares batch N+1 while the device
    runs batch N."""

    def __init__(self, iters, rename_data=None, rename_label=None,
                 prefetch_depth=2):
        if isinstance(iters, (list, tuple)):
            if len(iters) != 1:
                raise MXNetError("multi-iter PrefetchingIter is not "
                                 "supported; compose datasets instead")
            iters = iters[0]
        super().__init__(iters.batch_size)
        self.iter = iters
        self._depth = prefetch_depth
        self._queue = None
        self._thread = None
        self._start()

    @property
    def provide_data(self):
        return self.iter.provide_data

    @property
    def provide_label(self):
        return self.iter.provide_label

    def _start(self):
        self._queue = queue.Queue(maxsize=self._depth)

        def worker():
            try:
                for batch in self.iter:
                    self._queue.put(batch)
            finally:
                self._queue.put(None)
        self._thread = threading.Thread(target=worker, daemon=True)
        self._thread.start()

    def _get(self):
        """Bounded dequeue: re-arm a short timeout while the producer is
        alive; a worker that died without delivering its sentinel raises
        a structured error instead of hanging the consumer forever."""
        while True:
            try:
                return self._queue.get(timeout=1.0)
            except queue.Empty:
                if not self._thread.is_alive():
                    raise MXNetError(
                        "prefetch worker died without delivering a batch "
                        "or its end sentinel") from None

    def reset(self):
        if self._thread is not None and self._thread.is_alive():
            while self._get() is not None:
                pass
            self._thread.join(timeout=30.0)
            if self._thread.is_alive():
                raise MXNetError("prefetch worker did not exit within "
                                 "30s after draining")
        self.iter.reset()
        self._start()

    def next(self):
        batch = self._get()
        if batch is None:
            raise StopIteration
        return batch

    def iter_next(self):
        raise MXNetError("PrefetchingIter supports only next()/iteration")


class CSVIter(DataIter):
    """ref: src/io/iter_csv.cc — streams batches out of CSV files."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=1, round_batch=True, num_parts=None,
                 part_index=None, **kwargs):
        super().__init__(batch_size)
        data = np.loadtxt(data_csv, delimiter=",", dtype=np.float32,
                          ndmin=2).reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = np.loadtxt(label_csv, delimiter=",", dtype=np.float32,
                               ndmin=2).reshape((-1,) + tuple(label_shape))
            if label.shape[-1] == 1:
                label = label.reshape(label.shape[0])
        else:
            label = np.zeros((data.shape[0],), dtype=np.float32)
        self._inner = NDArrayIter(data, label, batch_size=batch_size,
                                  last_batch_handle="pad"
                                  if round_batch else "discard",
                                  num_parts=num_parts,
                                  part_index=part_index)

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()


def _read_idx_file(path):
    """MNIST idx format (also handles .gz)."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        zero, dtype_code, ndim = struct.unpack(">HBB", f.read(4))
        shape = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        dt = {8: np.uint8, 9: np.int8, 11: np.int16, 12: np.int32,
              13: np.float32, 14: np.float64}[dtype_code]
        return np.frombuffer(f.read(), dtype=dt).reshape(shape)


class MNISTIter(DataIter):
    """ref: src/io/iter_mnist.cc — reads the raw MNIST ubyte files."""

    def __init__(self, image, label, batch_size=128, shuffle=True, flat=False,
                 silent=False, seed=0, num_parts=None, part_index=None,
                 **kwargs):
        super().__init__(batch_size)
        imgs = _read_idx_file(image).astype(np.float32) / 255.0
        lbls = _read_idx_file(label).astype(np.float32)
        if flat:
            imgs = imgs.reshape(imgs.shape[0], -1)
        else:
            imgs = imgs.reshape(imgs.shape[0], 1, imgs.shape[1],
                                imgs.shape[2])
        self._inner = NDArrayIter(imgs, lbls, batch_size=batch_size,
                                  shuffle=shuffle,
                                  last_batch_handle="discard",
                                  num_parts=num_parts,
                                  part_index=part_index)

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()


class ImageRecordIter(DataIter):
    """ref: src/io/iter_image_recordio_2.cc ImageRecordIter — multithreaded
    decode+augment over an indexed RecordIO pack, with prefetch.

    Supported params mirror the reference's hot subset: path_imgrec/
    path_imgidx, data_shape (C,H,W), batch_size, shuffle, rand_crop,
    rand_mirror, resize, mean_{r,g,b}, std_{r,g,b}, scale.

    ``preprocess_threads`` sizes the decode+augment thread pool — the
    analog of the reference's parser→augmenter worker threads. Raw record
    reads stay serial (cheap, preserves order); JPEG decode and
    augmentation (cv2 — releases the GIL) run on the pool with up to
    ``2 * preprocess_threads + batch_size`` records in flight, results
    collected in submission order so the output stream is deterministic.
    ``preprocess_threads <= 1`` keeps the fully serial path.
    """

    def __init__(self, path_imgrec, data_shape, batch_size,
                 path_imgidx=None, shuffle=False, rand_crop=False,
                 rand_mirror=False, resize=-1, mean_r=0.0, mean_g=0.0,
                 mean_b=0.0, std_r=1.0, std_g=1.0, std_b=1.0, scale=1.0,
                 label_width=1, preprocess_threads=4, seed=0,
                 num_parts=None, part_index=None, **kwargs):
        super().__init__(batch_size)
        from .. import recordio
        self._data_shape = tuple(data_shape)
        self._num_parts, self._part_index = _resolve_part(num_parts,
                                                          part_index)
        if path_imgidx and os.path.exists(path_imgidx):
            self._rec = recordio.MXIndexedRecordIO(path_imgidx, path_imgrec,
                                                   "r")
            self._keys = list(self._rec.keys)
            if self._num_parts > 1:
                # indexed pack: contiguous key range, dmlc InputSplit shape
                lo, hi = _part_bounds(len(self._keys), self._num_parts,
                                      self._part_index)
                self._keys = self._keys[lo:hi]
        else:
            self._rec = recordio.MXRecordIO(path_imgrec, "r")
            self._keys = None
        self._shuffle = shuffle
        self._rand_crop = rand_crop
        self._rand_mirror = rand_mirror
        self._resize = resize
        self._mean = np.array([mean_r, mean_g, mean_b],
                              dtype=np.float32).reshape(3, 1, 1)
        self._std = np.array([std_r, std_g, std_b],
                             dtype=np.float32).reshape(3, 1, 1)
        self._scale = scale
        self._label_width = label_width
        self._seed = seed
        self._rng = np.random.RandomState(seed)
        self._threads = int(preprocess_threads)
        self._pool = None
        self._pending = None
        self._record_counter = 0
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size,) + self._data_shape)]

    @property
    def provide_label(self):
        shape = (self.batch_size,) if self._label_width == 1 else \
            (self.batch_size, self._label_width)
        return [DataDesc("softmax_label", shape)]

    def reset(self):
        if self._pending:
            for fut in self._pending:
                fut.cancel()
        self._pending = deque()
        self._record_counter = 0
        self._stream_pos = 0   # global stream position (round-robin split)
        # epoch counter folds into the per-record augment seed so each
        # epoch draws fresh crops/mirrors (position-keyed seeding alone
        # would replay epoch 1's augmentations forever)
        self._epoch = getattr(self, "_epoch", -1) + 1
        self._exhausted = False
        if self._keys is not None:
            self._order = list(self._keys)
            if self._shuffle:
                self._rng.shuffle(self._order)
            self._pos = 0
        else:
            self._rec.reset()

    def _next_raw(self):
        """Serial record fetch — raw packed bytes, decode deferred. In an
        un-indexed pack there is no key range to slice, so distributed
        sharding falls back to round-robin record assignment (stream
        position modulo num_parts — still a disjoint, exhaustive split)."""
        if self._keys is not None:
            if self._pos >= len(self._order):
                return None
            s = self._rec.read_idx(self._order[self._pos])
            self._pos += 1
        else:
            while True:
                s = self._rec.read()
                if s is None or self._num_parts == 1:
                    break
                here = self._stream_pos
                self._stream_pos += 1
                if here % self._num_parts == self._part_index:
                    break
        return s

    def _decode_augment(self, s, record_idx):
        """Worker body: unpack + JPEG decode + augment one record.
        Augmentation randomness is derived from (seed, record index) so the
        stream is reproducible regardless of pool size or thread timing."""
        from .. import recordio
        header, img = recordio.unpack_img(s, iscolor=1)
        rng = np.random.RandomState(
            ((self._seed * 1000003 + self._epoch) * 1000003 + record_idx)
            & 0x7FFFFFFF) \
            if (self._rand_crop or self._rand_mirror) else None
        chw, mirrored = self._augment(img, rng)
        return self._transform_label(header.label, mirrored), chw

    def _augment(self, img, rng):
        """Returns (CHW float image, mirrored flag) — the flag lets the
        detection subclass apply the SAME flip to its box labels."""
        import cv2
        c, h, w = self._data_shape
        if self._resize > 0:
            short = min(img.shape[:2])
            ratio = self._resize / short
            img = cv2.resize(img, (int(round(img.shape[1] * ratio)),
                                   int(round(img.shape[0] * ratio))))
        ih, iw = img.shape[:2]
        if ih < h or iw < w:
            img = cv2.resize(img, (max(w, iw), max(h, ih)))
            ih, iw = img.shape[:2]
        if self._rand_crop:
            y = rng.randint(0, ih - h + 1)
            x = rng.randint(0, iw - w + 1)
        else:
            y, x = (ih - h) // 2, (iw - w) // 2
        img = img[y:y + h, x:x + w]
        return self._finalize(img, rng)

    def _finalize(self, img, rng):
        """Shared augment tail: mirror draw, BGR→RGB, CHW, normalize —
        one definition for the classification and detection paths."""
        mirrored = bool(self._rand_mirror and rng.rand() < 0.5)
        if mirrored:
            img = img[:, ::-1]
        img = img[:, :, ::-1]  # BGR (cv2) → RGB, like the reference
        chw = img.transpose(2, 0, 1).astype(np.float32)
        chw = (chw - self._mean) / self._std * self._scale
        return chw, mirrored

    def _transform_label(self, label, mirrored):
        """Classification packs: labels are geometry-free — identity.
        The detection subclass flips box coordinates with the image."""
        return label

    def _fill_pending(self):
        """Keep the decode pool fed: submit raw records until the in-flight
        window (2×threads + batch) is full or the pack is exhausted."""
        if self._pool is None:
            from concurrent.futures import ThreadPoolExecutor
            self._pool = ThreadPoolExecutor(
                max_workers=self._threads,
                thread_name_prefix="mx-imgrec-decode")
        window = 2 * self._threads + self.batch_size
        while not self._exhausted and len(self._pending) < window:
            s = self._next_raw()
            if s is None:
                self._exhausted = True
                break
            self._pending.append(self._pool.submit(
                self._decode_augment, s, self._record_counter))
            self._record_counter += 1

    def _next_decoded(self):
        """(label, augmented CHW image) in record order, or None at end."""
        if self._threads <= 1:
            s = self._next_raw()
            if s is None:
                return None
            idx = self._record_counter
            self._record_counter += 1
            return self._decode_augment(s, idx)
        self._fill_pending()
        if not self._pending:
            return None
        return self._pending.popleft().result()

    def next(self):
        datas, labels = [], []
        while len(datas) < self.batch_size:
            rec = self._next_decoded()
            if rec is None:
                break
            label, img = rec
            datas.append(img)
            vals = np.asarray(label, dtype=np.float32).reshape(-1)
            # pad ragged label rows (variable object counts in detection
            # packs) to label_width so the batch stacks
            row = np.full(self._label_width,
                          getattr(self, "_pad_value", 0.0), np.float32)
            n = min(len(vals), self._label_width)
            row[:n] = vals[:n]
            labels.append(row)
        if not datas:
            raise StopIteration
        pad = self.batch_size - len(datas)
        while len(datas) < self.batch_size:
            datas.append(datas[-1])
            labels.append(labels[-1])
        label_arr = np.stack(labels)
        if self._label_width == 1:
            label_arr = label_arr.reshape(-1)
        return DataBatch(data=[nd.array(np.stack(datas))],
                         label=[nd.array(label_arr)], pad=pad,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)


class ImageDetRecordIter(ImageRecordIter):
    """Detection variant (ref: src/io/iter_image_det_recordio.cc): labels
    are variable-length [header_width, obj_width, cls, x0, y0, x1, y1, ...]
    padded to label_width per image; this build reads the same packs with
    label_width = label_pad_width boxes."""

    def __init__(self, path_imgrec, data_shape, batch_size,
                 label_pad_width=35, label_pad_value=-1.0, **kwargs):
        if kwargs.get("rand_crop"):
            raise MXNetError(
                "ImageDetRecordIter does not support rand_crop: cropping "
                "must resample/clip boxes (the reference uses dedicated "
                "rand_crop_prob/min_object_covered parameters) — crop in "
                "a custom transform that adjusts the labels")
        kwargs.setdefault("label_width", label_pad_width)
        super().__init__(path_imgrec, data_shape, batch_size, **kwargs)
        self._pad_value = label_pad_value

    def _augment(self, img, rng):
        """Detection geometry: RESIZE the full frame to data_shape
        (normalized box coords are invariant under pure resize) — the
        base class's center-crop would silently invalidate boxes for any
        size-mismatched pack. Optional mirror flips boxes via
        _transform_label."""
        import cv2
        c, h, w = self._data_shape
        if img.shape[0] != h or img.shape[1] != w:
            img = cv2.resize(img, (w, h))
        return self._finalize(img, rng)

    def _transform_label(self, label, mirrored):
        """Horizontal flip moves the boxes too: x0' = 1-x1, x1' = 1-x0
        (normalized corner coords; ref: src/io/image_det_aug_default.cc
        DefaultImageDetAugmenter mirror handling). Label layout:
        [header_width, obj_width, <header...>, boxes×obj_width] with box
        rows [cls, x0, y0, x1, y1, ...]."""
        if not mirrored:
            return label
        lab = np.array(label, dtype=np.float32).ravel()   # owns its data
        if lab.size < 2:
            return lab
        hw = int(lab[0])
        ow = int(lab[1])
        if hw < 2 or ow < 5 or lab.size <= hw:
            return lab             # not the det header layout: untouched
        n = (lab.size - hw) // ow
        boxes = lab[hw:hw + n * ow].reshape(n, ow)   # view: mutates lab
        x0 = boxes[:, 1].copy()
        boxes[:, 1] = 1.0 - boxes[:, 3]
        boxes[:, 3] = 1.0 - x0
        return lab

__all__.append("ImageDetRecordIter")


class LibSVMIter(DataIter):
    """LibSVM text-format iterator (ref: src/io/iter_libsvm.cc LibSVMIter):
    lines of ``label idx:val idx:val ...`` (indices 0-based like the
    reference's default). Data batches are CSRNDArray (the reference
    yields csr storage); labels are dense. Optional ``label_libsvm``
    holds multi-dim labels in the same format."""

    def __init__(self, data_libsvm, data_shape, batch_size,
                 label_libsvm=None, label_shape=None, num_parts=None,
                 part_index=None, **kwargs):
        super().__init__(batch_size)
        self._data_shape = tuple(data_shape) if not isinstance(
            data_shape, int) else (data_shape,)
        self._label_shape = (tuple(label_shape) if not isinstance(
            label_shape, int) else (label_shape,)) if label_shape else None
        self._rows = self._parse(data_libsvm, want_label=True)
        self._labels_ext = None
        if label_libsvm:
            if not self._label_shape:
                raise MXNetError(
                    "LibSVMIter: label_libsvm requires label_shape (the "
                    "dense label dimension to densify indices into)")
            self._labels_ext = self._parse(label_libsvm, want_label=False)
            if len(self._labels_ext) != len(self._rows):
                raise MXNetError(
                    f"LibSVMIter: label file has {len(self._labels_ext)} "
                    f"rows, data file {len(self._rows)}")
        num_parts, part_index = _resolve_part(num_parts, part_index)
        if num_parts > 1:
            lo, hi = _part_bounds(len(self._rows), num_parts, part_index)
            self._rows = self._rows[lo:hi]
            if self._labels_ext is not None:
                self._labels_ext = self._labels_ext[lo:hi]
        self._pos = 0

    @staticmethod
    def _parse(path, want_label):
        rows = []
        with open(path) as f:
            for line in f:
                parts = line.split()
                if not parts:
                    continue
                if want_label:
                    label = float(parts[0])
                    feats = parts[1:]
                else:
                    label = None
                    feats = parts
                idx, val = [], []
                for tok in feats:
                    i, v = tok.split(":")
                    idx.append(int(i))
                    val.append(float(v))
                rows.append((label, idx, val))
        return rows

    @property
    def provide_data(self):
        return [DataDesc("data",
                         (self.batch_size,) + self._data_shape)]

    @property
    def provide_label(self):
        if self._label_shape:
            return [DataDesc("softmax_label",
                             (self.batch_size,) + self._label_shape)]
        return [DataDesc("softmax_label", (self.batch_size,))]

    def reset(self):
        self._pos = 0

    def next(self):
        from ..ndarray.sparse import CSRNDArray
        if self._pos + self.batch_size > len(self._rows):
            raise StopIteration
        dim = self._data_shape[0]
        data, indices, indptr = [], [], [0]
        labels = []
        for j in range(self.batch_size):
            row = self._pos + j
            label, idx, val = self._rows[row]
            indices.extend(idx)
            data.extend(val)
            indptr.append(len(indices))
            if self._labels_ext is not None:
                # separate label file: each row is idx:val pairs densified
                # over label_shape (ref: iter_libsvm.cc label_libsvm)
                ldim = self._label_shape[0] if self._label_shape else 1
                lrow = np.zeros(ldim, np.float32)
                _, lidx, lval = self._labels_ext[row]
                lrow[np.asarray(lidx, np.int64)] = lval
                labels.append(lrow if ldim > 1 else float(lrow[0]))
            else:
                labels.append(label if label is not None else 0.0)
        self._pos += self.batch_size
        csr = CSRNDArray(np.asarray(data, np.float32),
                         np.asarray(indices, np.int64),
                         np.asarray(indptr, np.int64),
                         (self.batch_size, dim))
        return DataBatch(data=[csr],
                         label=[nd.array(np.asarray(labels, np.float32))])
__all__.append("LibSVMIter")

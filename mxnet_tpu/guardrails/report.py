"""Stdlib-only guardrails journal summary (``doctor --journal``).

Reads a JSONL diagnostics journal (``MXNET_TPU_JOURNAL=<file>``) and
summarizes the training-anomaly records — how many steps were skipped,
the worst consecutive run, every divergence rollback, and any
``TrainingDiverged`` crash — without importing jax or the runtime
package, so the report works from a wedged environment (the same
contract as ``resilience.commit.doctor_report``)."""
from __future__ import annotations

import json

__all__ = ["guard_report"]


def guard_report(path: str) -> dict:
    try:
        with open(path, encoding="utf-8") as f:
            lines = f.readlines()
    except OSError as e:
        return {"ok": False, "path": path,
                "error": f"cannot read journal: {e.strerror or e}"}
    records = 0
    skips = []
    spikes = 0
    rollbacks = []
    diverged = []
    worst_consecutive = 0
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue                      # torn tail line from a kill
        if not isinstance(rec, dict):
            continue
        records += 1
        kind = rec.get("kind")
        if kind == "nonfinite_grad":
            skips.append(rec)
            worst_consecutive = max(worst_consecutive,
                                    int(rec.get("consecutive", 0) or 0))
        elif kind == "loss_spike":
            spikes += 1
        elif kind == "divergence_rollback":
            rollbacks.append({k: rec.get(k) for k in
                              ("step", "restored_step", "reason",
                               "lr_backoff", "rollback", "consumer")})
        elif kind == "crash" and rec.get("error") == "TrainingDiverged":
            diverged.append({"detail": rec.get("detail"),
                             "phase": rec.get("phase")})
    out = {"ok": True, "path": path, "records": records,
           "skipped_steps": len(skips),
           "worst_consecutive_skips": worst_consecutive,
           "loss_spikes": spikes,
           "rollbacks": rollbacks,
           "diverged_errors": diverged}
    if skips:
        out["first_skip_step"] = skips[0].get("step")
        out["last_skip_step"] = skips[-1].get("step")
        consumers = {}
        for rec in skips:
            c = rec.get("consumer") or "?"
            consumers[c] = consumers.get(c, 0) + 1
        out["skips_by_consumer"] = consumers
    return out

"""Host-side anomaly accounting: skip budget, divergence detection,
rollback orchestration.

The fused guard (:mod:`mxnet_tpu.guardrails.fused`) decides *this step*
in-program; this module owns the *trajectory*: how many steps have been
skipped in a row, whether the loss is running away even while finite,
and what to do when the anomaly budget is exhausted — roll back to the
newest CRC-valid committed checkpoint with a learning-rate backoff
(bounded retries), or surface a structured :class:`TrainingDiverged`.

Import-light by design (numpy + the diagnostics journal, no jax): the
monitor must be constructible before any backend dial, and the
``doctor`` CLI reads its journal records from contexts where the
runtime may be broken.

Journal records (docs/guardrails.md has the full schema):

- ``nonfinite_grad``   one per skipped step: step, grad_norm, loss,
  consecutive-skip count, consumer (which trainer path).
- ``loss_spike``       one per sustained-spike observation window.
- ``divergence_rollback``  step, restored_step, reason, lr_backoff,
  rollback ordinal.

Knobs (all overridable per-:class:`GuardConfig`):

- ``MXNET_TPU_GUARD_MAX_SKIPS``     consecutive non-finite steps before
  the run is declared divergent (default 4).
- ``MXNET_TPU_GUARD_SPIKE_FACTOR``  finite-loss spike threshold as a
  multiple of the rolling median (default 10).
- ``MXNET_TPU_GUARD_WINDOW``        rolling loss window length
  (default 50).
- ``MXNET_TPU_GUARD_SPIKE_STEPS``   consecutive spiking steps before
  divergence (default 5).
- ``MXNET_TPU_GUARD_LR_BACKOFF``    learning-rate factor applied at
  each rollback (default 0.5).
- ``MXNET_TPU_GUARD_MAX_ROLLBACKS`` rollback budget before
  :class:`TrainingDiverged` escapes (default 2).
"""
from __future__ import annotations

import collections

import numpy as np

from ..base import MXNetError
from ..diagnostics.journal import get_journal
from ..resilience.retry import _env_float, _env_int

__all__ = ["AnomalyMonitor", "GuardConfig", "TrainingDiverged",
           "handle_divergence", "set_cumulative_lr_backoff",
           "stale_scale_runs"]


def stale_scale_runs(finites):
    """Per-step collapse mask for a scanned fp16 window: ``True`` marks
    a follow-on overflow of a consecutive run — every step after the
    run's first overflow re-decided under the same frozen loss scale,
    so only the first one feeds the scaler and the skip budget. THE
    single definition of the run boundary, shared by
    :meth:`AnomalyMonitor.observe_window` and the trainers' scaler
    feed (``GuardedTrainerMixin._after_run_steps``)."""
    mask, prev_bad = [], False
    for f in finites:
        bad = not bool(f)
        mask.append(bad and prev_bad)
        prev_bad = bad
    return mask


class GuardConfig:
    """Anomaly-guardrail policy for one trainer.

    ``mode="step"`` (default) fetches the step's (flag, loss, norm)
    outputs each step — one ``host_fetch`` of already-computed outputs,
    the same cost as reading the loss for logging — enabling per-step
    journaling, divergence detection and rollback. ``mode="deferred"``
    does ZERO per-step host reads: skip counters accumulate in-program
    and ``trainer.guard_poll()`` fetches them on demand (fp16 dynamic
    loss scaling still needs ``"step"`` — the scale is a host-side
    input).

    ``ckpt_root`` names a ``resilience.commit`` checkpoint root (the
    trainers' ``checkpoint()/restore()`` format); with it set, a
    divergence triggers restore-newest-valid + LR backoff instead of
    raising (until ``max_rollbacks`` is spent). Exception:
    ``module.fit`` checkpoints are EPOCH files, so there ``ckpt_root``
    must be an epoch-file prefix — or left unset to use
    ``checkpoint_prefix``. ``clip_norm`` enables global-norm gradient
    clipping off the guard's already-computed norm.
    """

    def __init__(self, max_consecutive_skips=None, spike_factor=None,
                 spike_window=None, spike_steps=None, lr_backoff=None,
                 max_rollbacks=None, ckpt_root=None, clip_norm=None,
                 mode="step"):
        self.max_consecutive_skips = int(
            max_consecutive_skips if max_consecutive_skips is not None
            else _env_int("MXNET_TPU_GUARD_MAX_SKIPS", 4))
        self.spike_factor = float(
            spike_factor if spike_factor is not None
            else _env_float("MXNET_TPU_GUARD_SPIKE_FACTOR", 10.0))
        self.spike_window = int(
            spike_window if spike_window is not None
            else _env_int("MXNET_TPU_GUARD_WINDOW", 50))
        self.spike_steps = int(
            spike_steps if spike_steps is not None
            else _env_int("MXNET_TPU_GUARD_SPIKE_STEPS", 5))
        self.lr_backoff = float(
            lr_backoff if lr_backoff is not None
            else _env_float("MXNET_TPU_GUARD_LR_BACKOFF", 0.5))
        self.max_rollbacks = int(
            max_rollbacks if max_rollbacks is not None
            else _env_int("MXNET_TPU_GUARD_MAX_ROLLBACKS", 2))
        self.ckpt_root = ckpt_root
        self.clip_norm = float(clip_norm) if clip_norm is not None else None
        if mode not in ("step", "deferred"):
            raise MXNetError(f"GuardConfig mode {mode!r}: expected 'step' "
                             "or 'deferred'")
        if self.max_consecutive_skips < 1:
            raise MXNetError("GuardConfig.max_consecutive_skips must be >= 1")
        if self.spike_window < 1:
            raise MXNetError("GuardConfig.spike_window must be >= 1")
        self.mode = mode

    @classmethod
    def coerce(cls, guard):
        """``None``/``False`` | ``True`` | GuardConfig → GuardConfig |
        None (the trainer-constructor convenience — ``False`` disables
        like ``None`` so a config-driven bool plumbs straight through)."""
        if guard is None or guard is False:
            return None
        if isinstance(guard, cls):
            return guard
        if guard is True:
            return cls()
        raise MXNetError(f"guard must be None, False, True or a "
                         f"GuardConfig, got {type(guard).__name__}")

    def copy(self):
        """Per-field copy. A trainer that adapts a config in place —
        e.g. ``fit()`` pointing ``ckpt_root`` at its
        ``checkpoint_prefix`` — must copy first so the caller's object
        (possibly shared with another trainer) stays untouched."""
        import copy as _copy
        return _copy.copy(self)


class TrainingDiverged(MXNetError):
    """Structured divergence error: the anomaly budget is spent and no
    rollback (or no further rollback) is available. Carries the step,
    the triggering reason, and the skip/rollback counts so drivers can
    journal/report without parsing the message."""

    def __init__(self, step, reason, consecutive_skips=0, rollbacks=0):
        super().__init__(
            f"training diverged at step {step}: {reason} "
            f"(consecutive_skips={consecutive_skips}, "
            f"rollbacks_used={rollbacks})")
        self.step = int(step)
        self.reason = reason
        self.consecutive_skips = int(consecutive_skips)
        self.rollbacks = int(rollbacks)


class AnomalyMonitor:
    """Rolling trajectory statistics + the anomaly budget.

    ``observe(step, finite, loss, grad_norm)`` returns one of
    ``"ok"`` / ``"skip"`` / ``"diverged"`` and journals every skip as a
    structured ``nonfinite_grad`` record. Divergence fires on either
    budget: ``max_consecutive_skips`` non-finite steps in a row, or a
    finite loss above ``spike_factor ×`` the rolling median for
    ``spike_steps`` consecutive observations (the silent-divergence
    class a finiteness check alone cannot see)."""

    def __init__(self, config=None, journal=None, consumer="trainer"):
        self.cfg = config or GuardConfig()
        self._journal = journal
        self.consumer = consumer
        self.total_skips = 0
        self.consecutive_skips = 0
        self.rollbacks = 0
        self.reason = None
        self._losses = collections.deque(maxlen=self.cfg.spike_window)
        self._spike_run = 0

    @property
    def journal(self):
        return self._journal if self._journal is not None else get_journal()

    # -- per-step observation ------------------------------------------------
    def observe(self, step, finite, loss=None, grad_norm=None):
        if not finite:
            self.total_skips += 1
            self.consecutive_skips += 1
            self.journal.event(
                "nonfinite_grad", step=int(step),
                grad_norm=_jsonable(grad_norm), loss=_jsonable(loss),
                consecutive=self.consecutive_skips,
                total_skips=self.total_skips, consumer=self.consumer)
            if self.consecutive_skips >= self.cfg.max_consecutive_skips:
                self.reason = (f"{self.consecutive_skips} consecutive "
                               "non-finite gradient steps")
                return "diverged"
            return "skip"
        self.consecutive_skips = 0
        if loss is not None and np.isfinite(loss):
            verdict = self._observe_loss(step, float(loss))
            if verdict is not None:
                return verdict
        return "ok"

    def _observe_loss(self, step, loss):
        # the window only accumulates NON-spiking losses: a runaway loss
        # must not drag the median up under itself and mute the alarm.
        # the arming threshold is capped at the window itself — the
        # deque can never hold more than spike_window entries, so an
        # uncapped >= 8 gate would silently disarm tiny windows
        if len(self._losses) >= min(self.cfg.spike_window,
                                    max(8, self.cfg.spike_window // 4)):
            median = float(np.median(self._losses))
            if abs(loss) > self.cfg.spike_factor * max(abs(median), 1e-12):
                self._spike_run += 1
                self.journal.event(
                    "loss_spike", step=int(step), loss=loss,
                    rolling_median=median, run=self._spike_run,
                    consumer=self.consumer)
                if self._spike_run >= self.cfg.spike_steps:
                    self.reason = (f"loss {loss:g} above "
                                   f"{self.cfg.spike_factor:g}x rolling "
                                   f"median {median:g} for "
                                   f"{self._spike_run} consecutive steps")
                    return "diverged"
                return "ok"     # spiking: counted, excluded from window
        self._spike_run = 0
        self._losses.append(loss)
        return None

    def observe_window(self, start_step, finites, losses=None, norms=None,
                       collapse_runs=False):
        """Fold a ``run_steps`` window (per-step arrays) into the monitor
        sequentially. Returns the first non-"ok" verdict with its step,
        or ``("ok", last_step)``.

        ``collapse_runs=True`` is the fp16 multi-step contract: the loss
        scale is one traced input frozen for the whole scanned window,
        so every step after the first overflow of a run re-decided
        under a scale the scaler never got to halve. Such a run counts
        ONCE against the consecutive-skip budget; its follow-on steps
        are still journaled (``stale_scale: true`` — they really were
        skipped in-program, and ``doctor --journal`` counts records)
        but cannot stack up to a spurious :class:`TrainingDiverged`
        that the per-step path would have self-healed with one or two
        halvings."""
        finites = list(finites)
        verdict, at = "ok", int(start_step) + len(finites) - 1
        stale = (stale_scale_runs(finites) if collapse_runs
                 else [False] * len(finites))
        run_pos = 0     # in-program position within the current skip run
        for i, f in enumerate(finites):
            step = int(start_step) + i
            bad = not bool(f)
            if stale[i]:
                run_pos += 1
                self.total_skips += 1
                self.journal.event(
                    "nonfinite_grad", step=step,
                    grad_norm=None if norms is None
                    else _jsonable(norms[i]),
                    loss=None if losses is None else _jsonable(losses[i]),
                    # the run's true in-program length, NOT the collapsed
                    # budget counter — doctor's worst-consecutive-skips
                    # reads this field
                    consecutive=run_pos, total_skips=self.total_skips,
                    stale_scale=True, consumer=self.consumer)
                if verdict == "ok":
                    verdict, at = "skip", step
                continue
            run_pos = 1 if bad else 0
            v = self.observe(
                step, bool(f),
                loss=None if losses is None else float(losses[i]),
                grad_norm=None if norms is None else float(norms[i]))
            if v == "diverged":
                return "diverged", step
            if v == "skip" and verdict == "ok":
                verdict, at = "skip", step
        return verdict, at

    def reset_stats(self):
        """Clear trajectory state (post-rollback: the restored world has
        a different loss scale/landscape). The rollback counter is NOT
        reset — it is the bounded-retry budget."""
        self.consecutive_skips = 0
        self._losses.clear()
        self._spike_run = 0
        self.reason = None


def _jsonable(v):
    if v is None:
        return None
    f = float(v)
    return f if np.isfinite(f) else repr(f)


def journal_scaler_only_skip(step, grad_norm, loss, consumer,
                             total_skips=None):
    """The ONE builder of the fp16-only skip record (scaler active, no
    :class:`GuardConfig`): doctor's skip accounting must not depend on
    opting into budgets/rollback, and the record schema must not fork
    across the trainer paths that emit it. ``total_skips`` is optional —
    the fused trainers carry their total in-program and won't pay a
    fetch just to journal it."""
    from ..diagnostics.journal import get_journal
    rec = {"step": int(step), "grad_norm": _jsonable(grad_norm),
           "loss": _jsonable(loss), "scaler_only": True,
           "consumer": consumer}
    if total_skips is not None:
        rec["total_skips"] = int(total_skips)
    get_journal().event("nonfinite_grad", **rec)


class _BackoffScheduler:
    """LR-scheduler wrapper applying the rollback backoff factor on top
    of the wrapped schedule (set_learning_rate is refused when a
    scheduler is installed, so the wrap is the only safe hook)."""

    def __init__(self, base, factor):
        self.base = base
        self.factor = float(factor)
        # mirror the attribute optimizer.__init__ maintains on schedulers
        self.base_lr = getattr(base, "base_lr", None)

    def __call__(self, num_update):
        return self.base(num_update) * self.factor


def set_cumulative_lr_backoff(optimizer, cumulative):
    """Bring the optimizer's effective LR to ``cumulative ×`` its
    checkpoint baseline, regardless of what the restore did to the
    optimizer object.

    The two trainer families differ here: the fused trainers' optimizer
    object SURVIVES a restore (any earlier backoff is still in force),
    while the gluon ``Trainer.load_states`` REPLACES the optimizer with
    the checkpoint's pickled copy — a fresh object at the checkpoint's
    LR, which would silently erase rollback #1's backoff when rollback
    #2 applies its single factor. The carried marker
    (``_guard_lr_backoff``, pickled with the optimizer so it always
    describes the LR it travels with) records how much backoff the
    CURRENT object already carries; applying ``cumulative / carried``
    lands both families on the same compounded trajectory."""
    if optimizer.lr_scheduler is not None:
        sched = optimizer.lr_scheduler
        if isinstance(sched, _BackoffScheduler):
            sched.factor = float(cumulative)
        else:
            optimizer.lr_scheduler = _BackoffScheduler(sched, cumulative)
        return float(cumulative)
    carried = getattr(optimizer, "_guard_lr_backoff", 1.0)
    optimizer.set_learning_rate(
        optimizer.learning_rate * float(cumulative) / carried)
    optimizer._guard_lr_backoff = float(cumulative)
    return float(cumulative)


def handle_divergence(monitor, step, restore_fn, optimizer,
                      on_restored=None):
    """The rollback protocol, shared by every trainer path.

    With a checkpoint root configured and budget left: restore the
    newest CRC-valid committed step (``restore_fn`` — the trainer's own
    ``restore``), apply the LR backoff, journal a structured
    ``divergence_rollback``, reset the monitor's trajectory stats, and
    return the restored step so training resumes. Otherwise raise
    :class:`TrainingDiverged`. A restore that itself fails (no valid
    checkpoint) chains into the divergence error — the caller must
    never silently keep training on garbage."""
    cfg = monitor.cfg
    reason = monitor.reason or "anomaly budget exhausted"
    if cfg.ckpt_root is None or monitor.rollbacks >= cfg.max_rollbacks:
        raise TrainingDiverged(step, reason,
                               consecutive_skips=monitor.consecutive_skips,
                               rollbacks=monitor.rollbacks)
    try:
        restored = restore_fn()
    except MXNetError as e:
        raise TrainingDiverged(
            step, f"{reason}; rollback failed: {e}",
            consecutive_skips=monitor.consecutive_skips,
            rollbacks=monitor.rollbacks) from e
    monitor.rollbacks += 1
    # ``optimizer`` may be a zero-arg callable: a restore can REPLACE the
    # trainer's optimizer object (gluon Trainer.load_states does), and
    # the backoff must land on the restored one — compounded across
    # rollbacks even when the restore reset it (set_cumulative_lr_backoff
    # has the full story). A list/tuple backs off every member
    # (SequentialModule chains modules with separate optimizers).
    opt = optimizer() if callable(optimizer) else optimizer
    opts = list(opt) if isinstance(opt, (list, tuple)) else [opt]
    backoff = None
    for o in opts:
        if o is None:
            continue
        b = set_cumulative_lr_backoff(o, cfg.lr_backoff ** monitor.rollbacks)
        backoff = b if backoff is None else backoff
    monitor.journal.event(
        "divergence_rollback", step=int(step),
        restored_step=int(restored) if restored is not None else None,
        reason=reason, lr_backoff=backoff, rollback=monitor.rollbacks,
        max_rollbacks=cfg.max_rollbacks, consumer=monitor.consumer)
    monitor.reset_stats()
    if on_restored is not None:
        on_restored(restored)
    return restored

"""Shared host-side guard bookkeeping for the fused trainers.

ShardedTrainer and PipelinedTrainer carry identical guard plumbing —
per-step scaler/monitor feeding, scanned-window aftermath (including
the stale-scale run collapse), divergence rollback, and the in-program
skip counters. One copy lives here; a trainer supplies only what
genuinely differs: its consumer tag (``_guard_consumer``) and how a
replicated guard-state scalar is placed on its mesh
(``_reinit_guard_state``).

Host attributes the mixin expects: ``_scaler``, ``_guard_cfg``,
``_monitor``, ``_guard_state``, ``_skipped_offset``, ``_optimizer``,
``_num_update``, and ``restore(ckpt_dir)``.
"""
from __future__ import annotations

from . import fused
from .monitor import handle_divergence, stale_scale_runs

__all__ = ["GuardedTrainerMixin"]


class GuardedTrainerMixin:
    """Guard bookkeeping shared by the fused (jit/pjit) trainers."""

    _guard_consumer = "trainer"

    def _reinit_guard_state(self):
        """Fresh replicated in-program counters on this trainer's mesh."""
        raise NotImplementedError

    def _validate_guard_mode(self):
        """Reject ``mode="deferred"`` + fp16 scaler at construction: the
        loss scale is a host-side input updated from every step's flag,
        so per-step fetches would happen regardless (breaking deferred's
        zero-read contract) while the monitor is never fed (breaking
        journaling/rollback) — neither promise survives, so fail
        structurally instead of silently doing neither."""
        cfg = self._guard_cfg
        if (cfg is not None and cfg.mode == "deferred"
                and self._scaler is not None):
            from ..base import MXNetError
            raise MXNetError(
                "GuardConfig(mode='deferred') cannot be combined with "
                "fp16 dynamic loss scaling — the scale update needs "
                "every step's flag on the host; use mode='step' "
                "(docs/guardrails.md)")

    # -- per-step -------------------------------------------------------------
    def _after_step(self, t, loss, finite, gnorm):
        """Per-step host half of the guardrails: feed the scaler and the
        monitor from the step's OWN outputs. One ``host_fetch`` — the
        same cost as reading the loss for logging. In ``deferred`` mode
        (and with no guard/scaler at all) this does nothing: skip counts
        accumulate in-program and ``guard_poll`` reads them on demand."""
        cfg = self._guard_cfg
        eager = (self._scaler is not None
                 or (cfg is not None and cfg.mode == "step"))
        if not eager:
            return
        ok, loss_v, gn = fused.host_fetch(finite, loss, gnorm)
        if self._scaler is not None:
            self._scaler.update_scale(not ok)
        if cfg is not None and cfg.mode == "step":
            verdict = self._monitor.observe(t, bool(ok), loss=loss_v,
                                            grad_norm=gn)
            if verdict == "diverged":
                self._handle_divergence(t)
        elif not ok:
            self._journal_scaler_only_skip(t, loss_v, gn)

    # -- scanned windows ------------------------------------------------------
    def _after_run_steps(self, start_t, losses, fins, gns):
        """Window-granular guard bookkeeping for run_steps: one fetch of
        the per-step (loss, flag, norm) arrays, fed to the scaler and
        monitor in step order. With an fp16 scaler the scale was FROZEN
        for the whole scanned window, so a run of consecutive overflows
        all re-decided under the same stale scale: halve once per run
        (not once per step — ``scale / 2**num_steps`` would be a
        spurious collapse) and charge the budget once per run
        (``AnomalyMonitor.observe_window(collapse_runs=True)``)."""
        cfg = self._guard_cfg
        eager = (self._scaler is not None
                 or (cfg is not None and cfg.mode == "step"))
        if not eager:
            return
        loss_a, fin_a, gn_a = fused.host_fetch(losses, fins, gns)
        if self._scaler is not None:
            for f, stale in zip(fin_a, stale_scale_runs(fin_a)):
                if not stale:
                    self._scaler.update_scale(not bool(f))
        if cfg is not None and cfg.mode == "step":
            verdict, at = self._monitor.observe_window(
                start_t, fin_a, losses=loss_a, norms=gn_a,
                collapse_runs=self._scaler is not None)
            if verdict == "diverged":
                self._handle_divergence(at)
        else:
            for i, f in enumerate(fin_a):
                if not bool(f):
                    self._journal_scaler_only_skip(
                        int(start_t) + i, loss_a[i], gn_a[i])

    def _journal_scaler_only_skip(self, t, loss_v, gn):
        from .monitor import journal_scaler_only_skip
        journal_scaler_only_skip(t, gn, loss_v, self._guard_consumer)

    # -- divergence -----------------------------------------------------------
    def _handle_divergence(self, t):
        restored = handle_divergence(
            self._monitor, t,
            restore_fn=lambda: self.restore(self._guard_cfg.ckpt_root),
            optimizer=self._optimizer)
        # restore() rewound params/state/num_update; the in-program skip
        # counters belong to the abandoned trajectory — bank the total
        # (skipped_steps stays cumulative) and start fresh counters
        self._skipped_offset += int(fused.host_fetch(
            self._guard_state[0])[0])
        self._guard_state = self._reinit_guard_state()
        return restored

    # -- counters -------------------------------------------------------------
    @property
    def skipped_steps(self):
        """Total non-finite (skipped) steps so far. Reading syncs on the
        in-program counter — one fetch, intended for reports (bench.py
        emits it), not per-step polling."""
        if self._guard_state is None:
            return self._skipped_offset
        return self._skipped_offset + int(
            fused.host_fetch(self._guard_state[0])[0])

    def guard_poll(self):
        """Deferred-mode poll: fetch the in-program counters once and
        return ``(total_skips, consecutive_skips)``. Journals a
        ``guard_poll`` record so long gaps between polls still leave a
        breadcrumb trail."""
        if self._guard_state is None:
            return (self._skipped_offset, 0)
        total, consec = fused.host_fetch(*self._guard_state)
        total = int(total) + self._skipped_offset
        from ..diagnostics.journal import get_journal
        get_journal().event("guard_poll", step=int(self._num_update),
                            total_skips=total, consecutive=int(consec),
                            consumer=self._guard_consumer)
        return (total, int(consec))

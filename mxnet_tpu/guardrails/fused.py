"""In-program guard math — every function here composes under jit/pjit.

The defense against bad numerics has to live *inside* the compiled step:
a host-side ``np.isfinite`` over pulled gradients costs a device→host
round trip per step (the tunnel charges ~90 ms each), and on multi-host
an early return taken by one rank while its peers enter the gradient
all-reduce hangs the collective. Everything in this module is therefore
expressed as traced jnp ops:

- :func:`guard_stats` folds ONE squared-sum reduction over every
  gradient leaf into the step. The sum serves double duty: its square
  root is the global gradient norm (so global-norm clipping costs no
  second pass — :func:`clip_scale`), and a NaN/Inf anywhere in any leaf
  poisons the sum, so ``isfinite(sum)`` is the fused non-finite flag.
  Under GSPMD the gradients the update sees are already psum-reduced
  across the mesh, which makes the flag *globally agreed by
  construction*: a NaN on one shard poisons the reduction on every
  rank, and no rank can branch out of a collective because the skip is
  data-flow (:func:`select`), not control flow.
- :func:`select` realizes skip-step semantics under jit: the updated
  and previous values both exist in-program, and a ``jnp.where`` on the
  flag picks per leaf — a skipped step is bit-identical to not having
  run the optimizer at all (params, optimizer state, AND auxiliary
  state such as BatchNorm running stats).
- guard *state* (total skips, consecutive skips) is carried through the
  step as two traced i32 scalars (:func:`init_guard_state` /
  :func:`update_guard_state`) so counting skips costs zero extra host
  reads — ``lax.scan`` multi-step programs thread it for free.
- :func:`host_fetch` is the ONE sanctioned device→host read for guard
  values: a single ``jax.device_get`` of already-computed step outputs,
  never a mid-step sync. graftlint G9 flags ad-hoc host finiteness
  checks in training modules and points here.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["clip_scale", "guard_stats", "host_fetch", "init_guard_state",
           "select", "update_guard_state"]


def guard_stats(grads, loss=None):
    """One fused reduction over every gradient leaf.

    Returns ``(finite, global_norm)``: a traced bool scalar that is True
    iff every element of every leaf (and ``loss``, when given) is
    finite, and the fp32 global L2 norm. The norm's squared-sum is the
    finiteness evidence — NaN/Inf propagate through the sum — so the
    guard costs exactly one all-reduce, shared with clipping.

    A finite gradient whose *square* overflows fp32 (elements beyond
    ~1.8e19) also reads as non-finite; a step with a 1e19 gradient norm
    is divergence by any definition, so the false positive is the right
    answer.
    """
    total = jnp.zeros((), jnp.float32)
    for g in jax.tree_util.tree_leaves(grads):
        g32 = jnp.asarray(g).astype(jnp.float32)
        total = total + jnp.sum(g32 * g32)
    finite = jnp.isfinite(total)
    if loss is not None:
        finite = jnp.logical_and(
            finite, jnp.isfinite(jnp.asarray(loss).astype(jnp.float32)))
    return finite, jnp.sqrt(total)


def clip_scale(global_norm, clip_norm, eps=1e-8):
    """Global-norm clip factor ``min(1, clip/(norm+eps))`` from the
    guard's already-computed norm (no second reduction pass). A
    non-finite norm yields 1.0 — the skip path owns that case, and
    scaling garbage by a NaN factor would only launder it."""
    s = jnp.minimum(clip_norm / (global_norm + eps), 1.0)
    return jnp.where(jnp.isfinite(global_norm), s, jnp.float32(1.0))


def select(finite, new, old):
    """Skip-step selection: per-leaf ``where(finite, new, old)`` over two
    matching pytrees. Works under jit/pjit/scan — the skip is data flow,
    so every rank of a collective program takes the same path."""
    return jax.tree_util.tree_map(
        lambda a, b: jnp.where(finite, a, b), new, old)


def init_guard_state():
    """Fresh in-program guard counters: (total_skips, consecutive_skips)
    as replicated i32 scalars."""
    return (jnp.zeros((), jnp.int32), jnp.zeros((), jnp.int32))


def update_guard_state(gstate, finite):
    """Fold one step's flag into the carried counters (traced)."""
    skips, consec = gstate
    bad = jnp.where(finite, 0, 1).astype(jnp.int32)
    return (skips + bad,
            jnp.where(finite, 0, consec + 1).astype(jnp.int32))


def host_fetch(*vals):
    """THE sanctioned device→host fetch for guard values.

    One ``jax.device_get`` over all requested scalars/arrays (a single
    transfer of already-materialized step outputs, never a mid-program
    sync), returned as plain Python scalars — scalar ndarrays are
    ``.item()``-ed so callers never need their own ``float()``/``bool()``
    host syncs (which graftlint G9 would rightly flag)."""
    out = []
    for v in jax.device_get(vals):
        a = np.asarray(v)
        out.append(a.item() if a.ndim == 0 else a)
    return out

"""Training anomaly guardrails — fused non-finite detection, skip-step
semantics, and divergence rollback to the last good checkpoint
(docs/guardrails.md).

Three layers, used by all four training paths (``gluon.Trainer``,
``module.fit``, ``parallel.ShardedTrainer``, ``parallel
.PipelinedTrainer``):

1. a **fused finiteness/global-norm guard** computed inside the
   compiled step (:mod:`.fused`) — one squared-sum all-reduce over the
   gradients, returning a flag + norm alongside the loss with zero
   extra host round trips;
2. **skip-step semantics** — a non-finite flag makes the update a
   data-flow no-op (``jnp.where``), journaled as a structured
   ``nonfinite_grad`` record, with the fp16 ``DynamicLossScaler``
   riding the same flag;
3. a **divergence monitor + rollback** (:mod:`.monitor`) — bounded
   anomaly budget; on exhaustion, restore the newest CRC-valid
   ``resilience.commit`` step with an LR backoff, or raise a
   structured :class:`TrainingDiverged`.

This package root is import-light (no jax): trainers import
:mod:`.fused` lazily at trace time.
"""
from .monitor import (AnomalyMonitor, GuardConfig, TrainingDiverged,
                      handle_divergence)
from .report import guard_report

__all__ = ["AnomalyMonitor", "GuardConfig", "TrainingDiverged",
           "guard_report", "handle_divergence"]

"""Per-module call graph — the skeleton under the interprocedural rules.

The hardest defects of the serving/elastic PRs were *cross-function*
concurrency mistakes (ledger I/O reached through a helper while a router
lock was held; a probe slot latched because the release lived in a
function the exception path never called). Per-function AST walking
structurally cannot see them. This module gives the analyzer the missing
edge set: for one parsed file it indexes every function/method (nested
defs included), resolves the calls between them (``self.m()`` through
the class — and through same-module base classes — ``name()`` to the
module function, ``Cls.m()`` explicitly), and tracks the receiver kinds
the concurrency rules care about: lock objects, queues/threads/events,
sockets, subprocess handles.

Known limits (documented in docs/static_analysis.md): dynamic dispatch
(``getattr``/callbacks), decorators that swap the callee, cross-module
calls (summaries are per-module; repo-internal blocking APIs —
``atomic_write``, journal ``event`` — are classified by resolved dotted
name instead), and aliased bound methods (``f = self.m; f()``).

Stdlib-only, like every analysis module: reasons about source, never
imports the runtime.
"""
from __future__ import annotations

import ast
import re

__all__ = ["ModuleIndex", "FunctionInfo", "build_index", "lock_key",
           "classify_blocking", "resolve_callee", "resolve_func_ref",
           "module_imports"]

# ---------------------------------------------------------------------------
# receiver vocabularies
# ---------------------------------------------------------------------------

LOCK_MAKERS = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "threading.Semaphore", "threading.BoundedSemaphore",
    "multiprocessing.Lock", "multiprocessing.RLock",
    "multiprocessing.Semaphore", "multiprocessing.BoundedSemaphore",
}
# name heuristic for lock-shaped receivers constructed elsewhere (an
# inherited `self._lock`, a lock handed in as an argument): the leaf
# identifier reads like a lock
_LOCKISH_RE = re.compile(r"(?:lock|mutex|semaphore|sem)s?$", re.IGNORECASE)
# condition variables get their own vocabulary on top of the lock one:
# G25 cares that `.wait()` sits in a predicate loop, which only makes
# sense for Condition receivers (an Event.wait is level-triggered)
COND_MAKERS = {"threading.Condition"}
_CONDISH_RE = re.compile(r"(?:cond|cv|condition)s?$", re.IGNORECASE)

QUEUE_MAKERS = {"queue.Queue", "queue.LifoQueue", "queue.PriorityQueue",
                "queue.SimpleQueue", "multiprocessing.Queue",
                "multiprocessing.JoinableQueue"}
THREAD_MAKERS = {"threading.Thread", "threading.Timer",
                 "multiprocessing.Process"}
EVENT_MAKERS = {"threading.Event", "threading.Barrier"}
SOCKET_MAKERS = {"socket.socket", "socket.create_connection"}
PROC_MAKERS = {"subprocess.Popen"}

# blocking by resolved dotted name, with the kind each one carries
_SLEEP_CALLS = {"time.sleep"}
_FILE_CALLS = {
    "open", "io.open", "os.replace", "os.rename", "os.listdir",
    "os.scandir", "os.makedirs", "os.mkdir", "os.unlink", "os.remove",
    "os.rmdir", "os.fsync", "os.stat", "shutil.rmtree", "shutil.copy",
    "shutil.copy2", "shutil.copytree", "shutil.move",
    "tempfile.mkstemp", "tempfile.NamedTemporaryFile",
}
_SUBPROCESS_CALLS = {"subprocess.run", "subprocess.call",
                     "subprocess.check_call", "subprocess.check_output",
                     "subprocess.Popen"}
_SOCKET_CALLS = {"socket.create_connection", "socket.getaddrinfo",
                 "urllib.request.urlopen"}
# repo-internal file APIs, matched on the resolved leaf so both the
# relative-import and absolute spellings classify (docs/checkpointing.md:
# these all end in fsync/replace — real file I/O wherever they run)
_REPO_FILE_LEAVES = {"atomic_write", "fsync_dir", "sweep_tmp"}
# blocking waits on tracked receivers, by attribute
_WAIT_ATTRS = {
    "queue": {"get", "put", "join"},
    "thread": {"join"},
    "event": {"wait"},
    "socket": {"recv", "recv_into", "accept", "connect", "sendall",
               "send", "makefile"},
    "proc": {"communicate", "wait"},
}
_JOURNAL_ATTRS = {"event", "crash", "set_phase"}


def _dotted(node):
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class FunctionInfo:
    """One function/method in the module index."""

    __slots__ = ("key", "name", "cls", "node", "line", "public")

    def __init__(self, key, name, cls, node):
        self.key = key
        self.name = name
        self.cls = cls
        self.node = node
        self.line = node.lineno
        self.public = not name.startswith("_")


class ModuleIndex:
    """Functions, classes (with same-module base chains), and tracked
    receivers of one parsed module."""

    def __init__(self, ctx):
        self.ctx = ctx
        self.functions: dict[str, FunctionInfo] = {}
        self.classes: dict[str, list] = {}      # class -> same-module bases
        self._methods: dict[str, set] = {}      # class -> method names
        self.receivers: dict[str, str] = {}     # dotted recv -> kind
        self.lock_recvs: set = set()            # dotted recvs made from
        self.cond_recvs: set = set()            # LOCK_MAKERS / COND_MAKERS
        self._thread_cls = None                 # memo: thread_classes()
        self._collect(ctx.tree)

    # -- construction -------------------------------------------------------
    def _collect(self, tree):
        def visit(node, cls, prefix):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    bases = [b for b in
                             (_dotted(e) for e in child.bases) if b]
                    self.classes[child.name] = bases
                    self._methods.setdefault(child.name, set())
                    visit(child, child.name, "")
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    key = (f"{cls}.{child.name}" if cls
                           else f"{prefix}{child.name}")
                    # first definition wins (a repeated def is a W-tier
                    # problem, not ours)
                    self.functions.setdefault(
                        key, FunctionInfo(key, child.name, cls, child))
                    if cls:
                        self._methods[cls].add(child.name)
                    visit(child, None, key + ".")
                else:
                    visit(child, cls, prefix)

        visit(tree, None, "")
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign):
                value, targets = node.value, node.targets
            elif isinstance(node, (ast.AnnAssign, ast.NamedExpr)) \
                    and node.value is not None:
                value, targets = node.value, [node.target]
            else:
                continue
            if not isinstance(value, ast.Call):
                continue
            name = self.ctx.resolve(value.func)
            if name in LOCK_MAKERS:
                pool = "lock"
            elif name in QUEUE_MAKERS:
                pool = "queue"
            elif name in THREAD_MAKERS:
                pool = "thread"
            elif name in EVENT_MAKERS:
                pool = "event"
            elif name in SOCKET_MAKERS:
                pool = "socket"
            elif name in PROC_MAKERS:
                pool = "proc"
            else:
                continue
            for t in targets:
                dotted = _dotted(t)
                if not dotted:
                    continue
                if pool == "lock":
                    self.lock_recvs.add(dotted)
                    if name in COND_MAKERS:
                        self.cond_recvs.add(dotted)
                else:
                    self.receivers[dotted] = pool

    # -- method resolution through same-module base chains ------------------
    def method_owner(self, cls, name, _seen=None):
        """The class (this one or a same-module ancestor) defining
        ``name``, or None."""
        if cls not in self._methods:
            return None
        _seen = _seen or set()
        if cls in _seen:
            return None                  # cyclic bases: malformed input
        _seen.add(cls)
        if name in self._methods[cls]:
            return cls
        for base in self.classes.get(cls, ()):
            owner = self.method_owner(base.split(".")[-1], name, _seen)
            if owner:
                return owner
        return None

    # -- thread-subclass detection (their run() is a thread root) -----------
    def thread_classes(self) -> set:
        """Class names whose base chain (following same-module links)
        reaches ``threading.Thread`` / ``multiprocessing.Process`` —
        their ``run`` methods execute on the spawned thread."""
        if self._thread_cls is not None:
            return self._thread_cls

        def resolve_base(dotted):
            parts = dotted.split(".")
            expansion = self.ctx.aliases.get(parts[0])
            if expansion:
                parts = expansion.split(".") + parts[1:]
            return ".".join(parts)

        def escapes(cls, seen):
            if cls in seen:
                return False             # cyclic bases: malformed input
            seen.add(cls)
            for base in self.classes.get(cls, ()):
                if resolve_base(base) in THREAD_MAKERS:
                    return True
                leaf = base.split(".")[-1]
                if leaf in self.classes and escapes(leaf, seen):
                    return True
            return False

        self._thread_cls = {c for c in self.classes if escapes(c, set())}
        return self._thread_cls


def build_index(ctx) -> ModuleIndex:
    return ModuleIndex(ctx)


def _site_class(index: ModuleIndex, cls, fnkey):
    """The class ``self`` refers to at a site: the enclosing method's
    class, or — inside a nested def of a method, whose FunctionInfo
    carries no class — the class named by the key prefix (a closure's
    ``self`` is the method's)."""
    if cls:
        return cls
    if fnkey and "." in fnkey:
        head = fnkey.split(".", 1)[0]
        if head in index.classes:
            return head
    return None


def resolve_callee(index: ModuleIndex, call: ast.Call, cls, fnkey):
    """Same-module function key a call targets, or None (external /
    dynamic). ``cls`` / ``fnkey`` locate the call site for ``self.m()``
    and nested-def resolution."""
    func = call.func
    if isinstance(func, ast.Name):
        nested = f"{fnkey}.{func.id}" if fnkey else None
        if nested and nested in index.functions:
            return nested
        if func.id in index.functions:
            return func.id
        if func.id in index.classes:     # constructor: Cls() runs __init__
            owner = index.method_owner(func.id, "__init__")
            if owner:
                return f"{owner}.__init__"
        return None
    if isinstance(func, ast.Attribute) and isinstance(func.value, ast.Name):
        recv = func.value.id
        if recv in ("self", "cls"):
            site_cls = _site_class(index, cls, fnkey)
            if site_cls:
                owner = index.method_owner(site_cls, func.attr)
                if owner:
                    return f"{owner}.{func.attr}"
            return None
        if recv in index.classes:
            owner = index.method_owner(recv, func.attr)
            if owner:
                return f"{owner}.{func.attr}"
    return None


def resolve_func_ref(index: ModuleIndex, node, cls, fnkey):
    """Same-module function key a *function reference* (not a call)
    points at — ``self._run`` passed as a Thread target, a nested
    ``worker`` handed to a pool, a SIBLING nested def spawned from a
    launcher closure — or None. The thread-escape analysis uses this
    to turn spawn sites into call-graph roots."""
    if isinstance(node, ast.Name):
        scope = fnkey or ""
        while scope:                 # enclosing scopes, innermost first
            # a class prefix is a namespace, not a lexical scope — a
            # bare name never resolves to an unqualified method
            if scope == fnkey or scope in index.functions:
                cand = f"{scope}.{node.id}"
                if cand in index.functions:
                    return cand
            scope = scope.rsplit(".", 1)[0] if "." in scope else ""
        if node.id in index.functions:
            return node.id
        return None
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
        recv = node.value.id
        if recv in ("self", "cls"):
            site_cls = _site_class(index, cls, fnkey)
            if site_cls:
                owner = index.method_owner(site_cls, node.attr)
                if owner:
                    return f"{owner}.{node.attr}"
        elif recv in index.classes:
            owner = index.method_owner(recv, node.attr)
            if owner:
                return f"{owner}.{node.attr}"
    return None


# ---------------------------------------------------------------------------
# lock identity
# ---------------------------------------------------------------------------

def lock_key(index: ModuleIndex, expr, cls, fnkey):
    """Canonical key for a lock-shaped expression, or None.

    An expression is a lock when its dotted receiver was constructed
    from a lock maker anywhere in the module, or (heuristic — inherited
    or injected locks have no same-module construction) its leaf
    identifier reads like one (``_lock``, ``_beat_lock``, ``sem``).
    Keys are scoped so two classes' ``self._lock`` never alias:
    ``Cls::self._lock`` / ``<module>::NAME`` / ``fn::local``."""
    dotted = _dotted(expr)
    if dotted is None:
        return None
    leaf = dotted.rsplit(".", 1)[-1]
    if dotted not in index.lock_recvs and not _LOCKISH_RE.search(leaf):
        return None
    if dotted.startswith("self.") or dotted.startswith("cls."):
        scope = cls or fnkey or "<module>"
        return f"{scope}::self.{dotted.split('.', 1)[1]}"
    if "." not in dotted and dotted in index.lock_recvs:
        return f"<module>::{dotted}"
    if "." not in dotted:
        # bare lockish name: module global if assigned at module scope
        # from a maker was handled above; otherwise a local
        return f"{fnkey or '<module>'}::{dotted}"
    return f"{fnkey or cls or '<module>'}::{dotted}"


def lock_display(key: str) -> str:
    return key.split("::", 1)[-1]


# ---------------------------------------------------------------------------
# blocking-call classification
# ---------------------------------------------------------------------------

def _has_timeout(call: ast.Call) -> bool:
    kw = {k.arg for k in call.keywords}
    if None in kw:                       # **kwargs: trust the caller
        return True
    return "timeout" in kw or "deadline_s" in kw or "deadline_ms" in kw


def _journal_write(ctx, call: ast.Call) -> bool:
    """True for journal-append calls: ``get_journal().event(...)``,
    ``self._journal.event(...)``, ``journal.event(...)`` — the ledger
    class of file I/O the PR-9/PR-10 lock audits were about."""
    func = call.func
    if not isinstance(func, ast.Attribute) or func.attr not in _JOURNAL_ATTRS:
        return False
    value = func.value
    if isinstance(value, ast.Call):
        name = ctx.resolve(value.func) or ""
        return name.rsplit(".", 1)[-1] == "get_journal"
    dotted = _dotted(value) or ""
    resolved = ctx.resolve(value) or dotted
    leaf = dotted.rsplit(".", 1)[-1].lower()
    return "journal" in leaf or resolved.endswith(".journal")


def classify_blocking(index: ModuleIndex, call: ast.Call):
    """``(kind, what, deadlined)`` for a blocking call, else None.

    Kinds: ``sleep`` | ``file`` | ``journal`` | ``socket`` | ``wait`` |
    ``subprocess``. ``deadlined`` reports whether a timeout/deadline
    argument is present — a deadlined wait is still a wait (holding a
    lock across it stalls every peer for the full budget), so G15 keeps
    flagging it; G19 uses the distinction the other way around."""
    ctx = index.ctx
    name = ctx.resolve(call.func)
    if name in _SLEEP_CALLS:
        return "sleep", name, False
    if name in _FILE_CALLS:
        return "file", name, False
    if name in _SUBPROCESS_CALLS:
        return "subprocess", name, _has_timeout(call)
    if name in _SOCKET_CALLS:
        return "socket", name, _has_timeout(call)
    if name and name.rsplit(".", 1)[-1] in _REPO_FILE_LEAVES:
        return "file", name.rsplit(".", 1)[-1], False
    if _journal_write(ctx, call):
        return "journal", "journal write", False
    func = call.func
    if isinstance(func, ast.Attribute):
        recv = _dotted(func.value)
        kind = index.receivers.get(recv) if recv else None
        if kind and func.attr in _WAIT_ATTRS.get(kind, ()):
            if kind == "queue" and func.attr in ("get", "put"):
                # non-blocking forms (block=False / get_nowait-style
                # positional False) are not waits
                blk = call.args[0] if call.args and func.attr == "get" \
                    else None
                for k in call.keywords:
                    if k.arg == "block":
                        blk = k.value
                if isinstance(blk, ast.Constant) and blk.value is False:
                    return None
            return "wait", f"{recv}.{func.attr}", _has_timeout(call)
    return None


# ---------------------------------------------------------------------------
# import graph (for --changed-only reverse dependents)
# ---------------------------------------------------------------------------

_IMPORT_RE = re.compile(
    r"^\s*(?:from\s+([.\w]+)\s+import\b|import\s+([\w.]+(?:\s*,\s*[\w.]+)*))")


def module_imports(path_rel: str, src: str) -> set:
    """Dotted modules this file imports (cheap line scan — the
    changed-only selector must not pay a full parse per candidate).
    Relative imports resolve against the file's package."""
    pkg = path_rel.replace("\\", "/").rsplit("/", 1)[0].replace("/", ".") \
        if "/" in path_rel else ""
    out = set()
    for line in src.splitlines():
        m = _IMPORT_RE.match(line)
        if not m:
            continue
        if m.group(1):
            mod = m.group(1)
            if mod.startswith("."):
                level = len(mod) - len(mod.lstrip("."))
                rest = mod.lstrip(".")
                parts = pkg.split(".") if pkg else []
                if level - 1 <= len(parts):
                    base = parts[:len(parts) - (level - 1)]
                    mod = ".".join(base + ([rest] if rest else []))
                else:
                    continue
            out.add(mod)
        else:
            for piece in m.group(2).split(","):
                out.add(piece.strip().split(" ")[0])
    return out

"""Findings baseline — pre-existing findings don't block CI, new ones do.

``ci/lint_baseline.json`` commits the accepted debt: each entry pins one
finding by a *content* fingerprint (file + rule + normalized flagged-line
text), so unrelated edits that shift line numbers don't invalidate it,
while touching the flagged line itself re-opens the finding. Identical
lines in one file share a fingerprint; the baseline therefore matches by
count (two identical accepted findings = two entries).

Workflow: ``python -m mxnet_tpu.analysis --write-baseline`` regenerates
the file from the current findings, preserving the ``justification``
strings of entries that persist. Entries whose finding disappeared are
dropped automatically — the baseline only ever shrinks by fixing code.
"""
from __future__ import annotations

import collections
import json
import os

__all__ = ["load_baseline", "partition", "write_baseline",
           "DEFAULT_BASELINE"]

DEFAULT_BASELINE = os.path.join("ci", "lint_baseline.json")


def load_baseline(path):
    """Return the entry list (possibly empty) from a baseline file.
    Raises ValueError (not a raw JSONDecodeError) on a malformed file
    so the CLI can turn it into a usage error with a recovery hint."""
    if not path or not os.path.exists(path):
        return []
    with open(path, encoding="utf-8") as f:
        try:
            data = json.load(f)
        except json.JSONDecodeError as e:
            raise ValueError(
                f"baseline {path} is not valid JSON ({e}); regenerate it "
                f"with --write-baseline") from e
    entries = data.get("entries", []) if isinstance(data, dict) else None
    if not isinstance(entries, list):
        raise ValueError(
            f"baseline {path} is not a graftlint baseline object; "
            f"regenerate it with --write-baseline")
    return list(entries)


def partition(findings, entries):
    """Split findings into (new, baselined) against the entry list.
    Matching is by fingerprint with multiset counting; the excess
    occurrences (later in file order) are the new ones."""
    budget = collections.Counter(e.get("fingerprint") for e in entries)
    new, baselined = [], []
    for f in findings:
        if budget[f.fingerprint] > 0:
            budget[f.fingerprint] -= 1
            baselined.append(f)
        else:
            new.append(f)
    return new, baselined


def write_baseline(path, findings, keep_justifications=True):
    """Regenerate the baseline from the current findings. Justifications
    of surviving fingerprints carry over; fresh entries get an empty
    string for a human to fill in."""
    old_just = {}
    if keep_justifications:
        try:
            entries = load_baseline(path)
        except ValueError:
            entries = []    # regenerating anyway: a broken file self-heals
        for e in entries:
            if e.get("justification"):
                old_just.setdefault(e["fingerprint"], e["justification"])
    entries = [{"rule": f.code, "path": f.path, "line": f.line,
                "fingerprint": f.fingerprint,
                "message": f.message,
                "justification": old_just.get(f.fingerprint, "")}
               for f in findings]
    payload = {"tool": "graftlint", "version": 1, "entries": entries}
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2, sort_keys=False)
        f.write("\n")
    os.replace(tmp, path)
    return entries

"""graftlint core — the one AST walker behind every static-analysis tier.

The seed's ``ci/lint.py`` was a flat list of generic checks; the round-4/5
wedge (a module-scope backend dial in ``_rng.py``) proved that the hazards
that actually cost benchmark windows are *semantic* and project-specific.
This module is the shared substrate: file iteration, import-alias
resolution, a rule registry keyed by code (``G*`` JAX-hazard rules,
``W*``/``E*`` generic rules), and one suppression syntax.

Dependency-free by the same contract as the old lint tier: stdlib only,
and importable without touching jax (rules reason about *source*, never
the runtime).

Suppressions (one syntax for every rule)::

    x = jax.devices()   # graftlint: disable=G4 reason for the exception
    # graftlint: disable=G1,G2 applies to the NEXT line when alone
    y = probe()

Legacy ``# noqa`` (any code, that line only) is still honored so the
pre-framework annotations keep working; new code should use the
``graftlint`` form, which is per-code and carries a reason.

A file whose first lines contain ``# graftlint: scope=library`` is held
to library-code rules (G2/G4) even outside ``mxnet_tpu/`` — the hook the
rule fixtures under ``tests/data/graftlint/`` use.
"""
from __future__ import annotations

import ast
import hashlib
import io
import os
import re
import time
import tokenize
from dataclasses import dataclass

__all__ = ["Finding", "Rule", "FileContext", "register", "all_rules",
           "load_rules", "lint_file", "run", "iter_py",
           "DEFAULT_PATHS", "DEFAULT_EXCLUDES"]

# same surface the old lint tier scanned, plus setup.py
DEFAULT_PATHS = ["mxnet_tpu", "tools", "examples", "benchmarks", "tests",
                 "ci", "bench.py", "__graft_entry__.py", "setup.py"]
# seeded-violation fixtures must never count against the repo
DEFAULT_EXCLUDES = ("tests/data",)

_DISABLE_RE = re.compile(
    r"#\s*graftlint:\s*disable="
    r"([A-Za-z0-9]+(?:\s*,\s*[A-Za-z0-9]+)*)(?:\s+(?P<reason>.*))?")
_SCOPE_RE = re.compile(r"#\s*graftlint:\s*scope=library\b")
_ALL = "__all_codes__"


@dataclass
class Finding:
    """One diagnostic: a rule code anchored to a repo-relative line."""
    path: str
    line: int
    code: str
    message: str
    severity: str = "warning"
    fingerprint: str = ""

    def sort_key(self):
        return (self.path, self.line, self.code, self.message)

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.code} {self.message}"


class Rule:
    """Base class: subclasses set ``code``/``name``/``severity``/``doc``
    and yield :class:`Finding` from ``check(ctx)``. ``doc`` is the rule
    catalog entry (docs/static_analysis.md + SARIF rule metadata)."""

    code = ""
    name = ""
    severity = "warning"
    doc = ""

    def check(self, ctx: "FileContext"):
        raise NotImplementedError

    def finding(self, ctx, line, message) -> Finding:
        return Finding(ctx.path, line, self.code, message, self.severity)


_RULES: dict[str, Rule] = {}


def register(cls):
    """Class decorator: instantiate and index the rule by its code."""
    inst = cls()
    if not inst.code or inst.code in _RULES:
        # not an assert: must survive python -O, or a duplicate code
        # silently shadows an existing rule
        raise ValueError(f"duplicate or empty rule code: {inst.code!r}")
    _RULES[inst.code] = inst
    return cls


def load_rules() -> dict[str, Rule]:
    """Import the rule modules (idempotent) and return the registry."""
    from . import rules_generic, rules_jax   # noqa  (registration side effect)
    from . import rules_concurrency          # noqa  (registration side effect)
    from . import rules_races                # noqa  (registration side effect)
    return dict(sorted(_RULES.items()))


def all_rules() -> list[Rule]:
    return list(load_rules().values())


# per-rule wall-time accumulation (doctor --lint): None = off. The
# first rule to touch a file's summaries pays the shared extraction
# walk, so interprocedural timing concentrates on the lowest-numbered
# G15+ rule — documented in docs/static_analysis.md.
_rule_timings: dict | None = None


def collect_rule_timings(enabled=True) -> None:
    """Turn per-rule timing on/off (process-wide; forked ``--jobs``
    children inherit the setting and drain their share back)."""
    global _rule_timings
    _rule_timings = {} if enabled else None


def drain_rule_timings() -> dict:
    """``{code: [wall_s, raw_finding_count]}`` accumulated since the
    last drain; resets the accumulator (stays enabled)."""
    global _rule_timings
    if _rule_timings is None:
        return {}
    out, _rule_timings = _rule_timings, {}
    return out


def merge_rule_timings(delta) -> None:
    if _rule_timings is None or not delta:
        return
    for code, (wall, count) in delta.items():
        rec = _rule_timings.setdefault(code, [0.0, 0])
        rec[0] += wall
        rec[1] += count


def _dotted_parts(node):
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return list(reversed(parts))
    return None


class FileContext:
    """Per-file analysis state shared by every rule: source, AST, and the
    import-alias map that lets rules resolve ``jnp.asarray`` →
    ``jax.numpy.asarray`` without executing anything."""

    def __init__(self, path: str, src: str, tree: ast.AST):
        self.path = path.replace(os.sep, "/")
        self.src = src
        self.lines = src.splitlines()
        self.tree = tree
        self.aliases = self._import_aliases(tree)
        head = "\n".join(self.lines[:5])
        self._library = (self.path.startswith("mxnet_tpu/")
                         or bool(_SCOPE_RE.search(head)))

    @property
    def package(self) -> str:
        """Dotted package of this file derived from its repo-relative
        path (``mxnet_tpu/serving/router.py`` → ``mxnet_tpu.serving``)
        — the base relative imports resolve against."""
        parts = self.path.split("/")
        if parts and parts[-1].endswith(".py"):
            parts = parts[:-1]          # __init__.py and modules alike
        return ".".join(p for p in parts if p)

    def _import_aliases(self, tree) -> dict[str, str]:
        aliases = {}
        pkg_parts = self.package.split(".") if self.package else []
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        aliases[a.asname] = a.name
                    else:
                        root = a.name.split(".")[0]
                        aliases.setdefault(root, root)
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    # relative import: resolve against the file's package
                    # so `from ..diagnostics.journal import get_journal`
                    # in mxnet_tpu/serving/ becomes the full dotted name
                    # (the interprocedural rules classify repo-internal
                    # APIs — journal writes, atomic_write — by it)
                    if node.level > len(pkg_parts):
                        continue
                    base = pkg_parts[:len(pkg_parts) - (node.level - 1)]
                    mod = ".".join(base + ([node.module] if node.module
                                           else []))
                elif node.module:
                    mod = node.module
                else:
                    continue
                for a in node.names:
                    if a.name == "*":
                        continue
                    aliases[a.asname or a.name] = f"{mod}.{a.name}"
        return aliases

    def is_library(self) -> bool:
        """True for framework code held to the stricter G2/G4 scope."""
        return self._library

    def resolve(self, node) -> str | None:
        """Dotted name of a Name/Attribute with the import aliases
        expanded, e.g. ``jr.split`` → ``jax.random.split``. None for
        anything not a plain dotted chain."""
        parts = _dotted_parts(node)
        if not parts:
            return None
        expansion = self.aliases.get(parts[0])
        if expansion:
            parts = expansion.split(".") + parts[1:]
        return ".".join(parts)

    def resolve_call(self, call: ast.Call) -> str | None:
        return self.resolve(call.func)

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""


def _suppressions(lines) -> dict[int, set[str]]:
    """line -> set of suppressed codes (``_ALL`` = every code).

    Tokenize-based: only REAL comments count, so a string literal that
    merely quotes the suppression syntax (help text, error messages)
    never masks a co-located finding. Falls back to a plain line scan
    only if tokenization fails (it shouldn't: callers parsed the file)."""
    sup: dict[int, set[str]] = {}

    def apply(i, text):
        m = _DISABLE_RE.search(text)
        if m:
            codes = {c.strip() for c in m.group(1).split(",") if c.strip()}
            # a comment-only disable line covers the next line
            line = lines[i - 1] if 1 <= i <= len(lines) else ""
            target = i + 1 if line.strip().startswith("#") else i
            sup.setdefault(target, set()).update(codes)
        if "# noqa" in text:                     # legacy, that line only
            sup.setdefault(i, set()).add(_ALL)

    src = "\n".join(lines) + "\n"
    try:
        for tok in tokenize.generate_tokens(io.StringIO(src).readline):
            if tok.type == tokenize.COMMENT:
                apply(tok.start[0], tok.string)
    except (tokenize.TokenError, IndentationError, SyntaxError):
        for i, line in enumerate(lines, 1):
            apply(i, line)
    return sup


def _fingerprint(path: str, code: str, line_text: str) -> str:
    """Content-based identity for baseline matching: stable across
    unrelated edits that only shift line numbers."""
    norm = "".join(line_text.split())
    raw = f"{path}|{code}|{norm}".encode("utf-8", "replace")
    return hashlib.sha1(raw).hexdigest()[:12]


def lint_file(path: str, rules=None, root: str | None = None):
    """Run every rule over one file; returns suppression-filtered,
    fingerprinted, sorted findings."""
    rules = rules if rules is not None else all_rules()
    rel = path
    if root:
        ap = os.path.abspath(path)
        aroot = os.path.abspath(root)
        if ap.startswith(aroot + os.sep):
            rel = os.path.relpath(ap, aroot)
    rel = rel.replace(os.sep, "/")
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        lines = src.splitlines()
        ln = e.lineno or 0
        text = lines[ln - 1] if 1 <= ln <= len(lines) else ""
        f = Finding(rel, ln, "E1", f"syntax error: {e.msg}", "error")
        # fingerprinted like every finding — a baselined E1 in one file
        # must never absorb a fresh syntax error in another
        f.fingerprint = _fingerprint(rel, "E1", text)
        return [f]
    ctx = FileContext(rel, src, tree)
    findings = []
    for rule in rules:
        if _rule_timings is None:
            findings.extend(rule.check(ctx))
        else:
            t0 = time.perf_counter()
            fnd = list(rule.check(ctx))
            rec = _rule_timings.setdefault(rule.code, [0.0, 0])
            rec[0] += time.perf_counter() - t0
            rec[1] += len(fnd)
            findings.extend(fnd)
    if not findings:
        return []       # clean file: skip the suppression/span passes
    sup = _suppressions(ctx.lines)
    # a disable anywhere on a multi-line SIMPLE statement covers the
    # whole statement — the natural comment spot is the closing line,
    # while findings anchor to the opening one
    spans = []
    for n in ast.walk(tree):
        if not isinstance(n, ast.stmt):
            continue
        body = getattr(n, "body", None)
        cases = getattr(n, "cases", None)
        if isinstance(body, list) and body:
            # compound statement: span only its multi-line HEADER (the
            # test/subject up to the first inner line), never the body
            end = body[0].lineno - 1
        elif cases:
            end = cases[0].pattern.lineno - 1   # match_case has no lineno
        else:
            end = getattr(n, "end_lineno", n.lineno)
        if end > n.lineno:
            spans.append((n.lineno, end))

    def codes_for(line):
        codes = set(sup.get(line, ()))
        for s, e in spans:
            if s <= line <= e:
                for ln in range(s, e + 1):
                    if ln != line:
                        # legacy `# noqa` (_ALL) stays line-only by
                        # contract; graftlint codes cover the statement
                        codes |= sup.get(ln, set()) - {_ALL}
        return codes

    out = []
    for f in findings:
        codes = codes_for(f.line)
        if f.code in codes or _ALL in codes:
            continue
        f.fingerprint = _fingerprint(f.path, f.code, ctx.line_text(f.line))
        out.append(f)
    out.sort(key=Finding.sort_key)
    return out


def iter_py(paths, excludes=DEFAULT_EXCLUDES, root="."):
    """Yield .py files under ``paths`` (relative to ``root``) exactly
    once each (overlapping paths dedup). ``excludes`` prefixes are
    skipped during directory walks — but a path the caller names that
    is *itself* at/under an exclude is an explicit opt-in and scans
    fully (how the fixture tests lint the fixture corpus)."""

    def excluded(rel):
        rel = rel.replace(os.sep, "/")
        return any(rel == e or rel.startswith(e + "/") for e in excludes)

    seen = set()

    def fresh(fp):
        ap = os.path.abspath(fp)
        if ap in seen:
            return False
        seen.add(ap)
        return True

    for p in paths:
        full = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isfile(full) and full.endswith(".py"):
            if fresh(full):
                yield full
        elif os.path.isdir(full):
            opted_in = excluded(os.path.relpath(full, root))
            for dirpath, _dirs, files in os.walk(full):
                for fname in sorted(files):
                    if not fname.endswith(".py"):
                        continue
                    fp = os.path.join(dirpath, fname)
                    if opted_in or not excluded(os.path.relpath(fp, root)):
                        if fresh(fp):
                            yield fp


def missing_paths(paths, excludes=DEFAULT_EXCLUDES, root="."):
    """The subset of ``paths`` yielding no .py file at all — a typo'd
    path in a scan list must not read as a clean pass."""
    return [p for p in paths
            if next(iter_py([p], excludes=excludes, root=root), None)
            is None]


def _lint_one(args):
    """Worker body for the ``--jobs`` pool: lint one file by rule CODES
    (rule instances don't cross process boundaries; the registry in the
    forked child resolves them) and drain the child's summary-cache
    delta so the parent can merge + persist it."""
    fp, codes, root = args
    from . import summaries as _summaries
    registry = load_rules()
    rules = [registry[c] for c in codes if c in registry]
    findings = lint_file(fp, rules=rules, root=root)
    return findings, _summaries.drain_active_cache(), drain_rule_timings()


def run(paths=None, rules=None, excludes=DEFAULT_EXCLUDES, root=".",
        jobs=1):
    """Lint ``paths`` (default: the repo surface). Returns
    ``(findings, n_files)``. See :func:`iter_py` for how excludes
    interact with explicitly named paths. ``jobs > 1`` fans files out
    over a fork-based process pool (0 = one per CPU, capped); platforms
    without fork fall back to serial — parallelism is a speedup, never
    a behavior change."""
    from . import summaries as _summaries
    paths = paths or DEFAULT_PATHS
    rules = rules if rules is not None else all_rules()
    files = list(iter_py(paths, excludes=excludes, root=root))
    if jobs == 0:
        jobs = min(os.cpu_count() or 1, 8)
    jobs = min(jobs, max(len(files), 1))
    findings = []
    if jobs > 1:
        try:
            import multiprocessing as mp
            codes = [r.code for r in rules]
            with mp.get_context("fork").Pool(jobs) as pool:
                for fnd, delta, timings in pool.imap_unordered(
                        _lint_one, [(fp, codes, root) for fp in files],
                        chunksize=4):
                    findings.extend(fnd)
                    _summaries.merge_cache_delta(delta)
                    merge_rule_timings(timings)
            findings.sort(key=Finding.sort_key)
            return findings, len(files)
        except (ImportError, ValueError, OSError):
            findings = []        # no fork on this platform: run serial
    for fp in files:
        findings.extend(lint_file(fp, rules=rules, root=root))
    findings.sort(key=Finding.sort_key)
    return findings, len(files)

"""Generic Python hygiene rules — the old ``ci/lint.py`` W-tier, ported
onto the graftlint framework so there is one walker, one suppression
syntax, and one baseline for both the generic and the JAX-hazard tiers.

Semantics are kept bit-compatible with the seed's lint so the repo stays
clean through the refactor: imports inside ``try`` are feature probes
(the import IS the use), ``__init__.py`` re-exports don't count as
unused, ``__all__`` strings count as uses.
"""
from __future__ import annotations

import ast
import os

from .core import Rule, register

MAX_LINE = 100


@register
class SyntaxErrorRule(Rule):
    """E1 is emitted by the runner (a file that does not parse runs no
    other rule); registered here so it has catalog + SARIF metadata."""

    code = "E1"
    name = "syntax-error"
    severity = "error"
    doc = "File does not compile under the current Python."

    def check(self, ctx):
        return ()


class _ImportTracker(ast.NodeVisitor):
    """Imported names vs referenced names (see module docstring for the
    deliberate exemptions)."""

    def __init__(self):
        self.imports = {}       # name -> lineno
        self.used = set()
        self._try_depth = 0

    def visit_Try(self, node):
        self._try_depth += 1
        self.generic_visit(node)
        self._try_depth -= 1

    def visit_Import(self, node):
        if self._try_depth:
            return
        for a in node.names:
            name = (a.asname or a.name).split(".")[0]
            self.imports.setdefault(name, node.lineno)

    def visit_ImportFrom(self, node):
        if self._try_depth or node.module == "__future__":
            return
        for a in node.names:
            if a.name == "*":
                continue
            self.imports.setdefault(a.asname or a.name, node.lineno)

    def visit_Name(self, node):
        self.used.add(node.id)

    def visit_Attribute(self, node):
        self.generic_visit(node)


@register
class UnusedImport(Rule):
    code = "W1"
    name = "unused-import"
    doc = ("Imported name never referenced. Imports inside try/except "
           "(feature probes), `__all__`-exported names, `_`-prefixed "
           "names, and `__init__.py` re-exports are exempt.")

    def check(self, ctx):
        if os.path.basename(ctx.path) == "__init__.py":
            return
        tracker = _ImportTracker()
        tracker.visit(ctx.tree)
        exported = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id == "__all__" and \
                            isinstance(node.value, (ast.List, ast.Tuple)):
                        for elt in node.value.elts:
                            if isinstance(elt, ast.Constant):
                                exported.add(str(elt.value))
        for name, lineno in tracker.imports.items():
            if name.startswith("_"):
                continue
            if name not in tracker.used and name not in exported:
                yield self.finding(ctx, lineno, f"unused import {name!r}")


@register
class BareExcept(Rule):
    code = "W2"
    name = "bare-except"
    doc = "`except:` with no exception type catches SystemExit/KeyboardInterrupt."

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ExceptHandler) and node.type is None:
                yield self.finding(ctx, node.lineno, "bare except:")


@register
class MutableDefault(Rule):
    code = "W3"
    name = "mutable-default-argument"
    doc = "list/dict/set literal default is shared across calls."

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for d in node.args.defaults + node.args.kw_defaults:
                    if isinstance(d, (ast.List, ast.Dict, ast.Set)):
                        yield self.finding(ctx, d.lineno,
                                           "mutable default argument")


@register
class PointlessFString(Rule):
    code = "W4"
    name = "f-string-without-placeholders"
    doc = "f-string with no {placeholders} — the prefix is a no-op."

    def check(self, ctx):
        format_specs = {id(n.format_spec) for n in ast.walk(ctx.tree)
                        if isinstance(n, ast.FormattedValue)
                        and n.format_spec is not None}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.JoinedStr):
                # skip format-spec JoinedStrs nested inside FormattedValue
                # (e.g. the ':8.1f' in f"{x:8.1f}" parses as a JoinedStr)
                if id(node) in format_specs:
                    continue
                if not any(isinstance(v, ast.FormattedValue)
                           for v in node.values):
                    yield self.finding(ctx, node.lineno,
                                       "f-string without placeholders")


@register
class Whitespace(Rule):
    code = "W5"
    name = "whitespace"
    doc = "Trailing whitespace or tab indentation."

    def check(self, ctx):
        for i, line in enumerate(ctx.lines, 1):
            if line != line.rstrip():
                yield self.finding(ctx, i, "trailing whitespace")
            if line.startswith("\t") or (
                    line[:1] == " " and
                    "\t" in line[:len(line) - len(line.lstrip())]):
                yield self.finding(ctx, i, "tab indentation")


@register
class LineLength(Rule):
    code = "W6"
    name = "line-too-long"
    doc = f"Line longer than {MAX_LINE} columns."

    def check(self, ctx):
        for i, line in enumerate(ctx.lines, 1):
            if len(line) > MAX_LINE:
                yield self.finding(
                    ctx, i, f"line too long ({len(line)} > {MAX_LINE})")

"""Output emitters: human text, machine JSON, and SARIF 2.1.0 (the
interchange format code-review UIs ingest — GitHub code scanning,
VS Code SARIF viewer)."""
from __future__ import annotations

import json

__all__ = ["emit_text", "to_json", "to_sarif", "dump_json"]

_SARIF_LEVEL = {"error": "error", "warning": "warning", "note": "note"}


def emit_text(new, baselined, n_files, stream, verbose_baselined=False):
    """The classic ``path:line: CODE message`` listing plus a summary
    line. Only NEW findings print by default — baselined debt is a
    count, not noise."""
    for f in new:
        stream.write(f.render() + "\n")
    if verbose_baselined:
        for f in baselined:
            stream.write(f.render() + "  [baselined]\n")
    stream.write(
        f"graftlint: {n_files} files, {len(new) + len(baselined)} findings "
        f"({len(new)} new, {len(baselined)} baselined)\n")


def _finding_dict(f):
    return {"path": f.path, "line": f.line, "rule": f.code,
            "severity": f.severity, "message": f.message,
            "fingerprint": f.fingerprint}


def to_json(new, baselined, n_files):
    return {
        "tool": "graftlint",
        "files": n_files,
        "new": [_finding_dict(f) for f in new],
        "baselined": [_finding_dict(f) for f in baselined],
    }


def to_sarif(new, baselined, rules):
    """Minimal-but-valid SARIF 2.1.0 run. Baselined findings ride along
    with ``baselineState: unchanged`` so viewers can filter them; new
    ones carry ``baselineState: new``."""

    def result(f, state):
        return {
            "ruleId": f.code,
            "level": _SARIF_LEVEL.get(f.severity, "warning"),
            "message": {"text": f.message},
            "baselineState": state,
            "partialFingerprints": {"graftlint/v1": f.fingerprint},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path,
                                         "uriBaseId": "SRCROOT"},
                    "region": {"startLine": max(f.line, 1)},
                }
            }],
        }

    return {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "graftlint",
                "fullName": "graftlint (rule catalog: "
                            "docs/static_analysis.md)",
                "rules": [{
                    "id": r.code,
                    "name": r.name,
                    "defaultConfiguration":
                        {"level": _SARIF_LEVEL.get(r.severity, "warning")},
                    "shortDescription": {"text": r.name},
                    "fullDescription": {"text": r.doc},
                } for r in rules],
            }},
            "results": [result(f, "new") for f in new] +
                       [result(f, "unchanged") for f in baselined],
        }],
    }


def dump_json(obj, stream):
    json.dump(obj, stream, indent=2)
    stream.write("\n")

"""Data-race rules (G22-G25) — static thread-escape + lockset analysis.

G15-G19 check lock *discipline* (what happens while a lock is held);
nothing checked lock *consistency*: that a shared field is protected by
the SAME lock everywhere, or by any lock at all. That gap is exactly
where the serving stack's worst shipped bugs lived — the PR-9
latched-probe TOCTOU and the PR-11 ``Heartbeat.beat()`` stale-overwrite
were both fields whose sites disagreed about the protecting lock.

This family runs a static variant of the Eraser lockset algorithm on
the schema-v2 summaries:

- **thread escape** (:class:`~.summaries.ModuleSummaries`
  ``thread_roots`` / ``thread_reachable``): spawn targets
  (``Thread(target=...)``, ``Timer`` callbacks, ``*callback*``
  registrations) and Thread-subclass ``run()`` methods seed a forward
  reachability pass, so every function knows whether it can run
  concurrently with the object's other methods;
- **per-class locksets**: every ``self._x`` site carries the locks
  lexically held there, widened by ``entry_locks`` (a private helper
  only ever called under ``self._lock`` inherits it). An attribute is
  *thread-shared* when it is touched from a thread-reachable function
  and from at least one other function.

Deliberate asymmetries (FP control, documented in
docs/static_analysis.md): unlocked READS of an otherwise-locked field
are tolerated (single-reader snapshots, monitoring counters — G24
covers the read-then-act case that actually corrupts state), and
``__init__`` writes are ignored (Eraser's init refinement: the object
is not published yet). Scope: mxnet_tpu/ library code.
"""
from __future__ import annotations

from . import callgraph as cg
from . import summaries as sm
from .core import Rule, register

__all__ = ["race_model"]


class _Site:
    __slots__ = ("mode", "fn", "method", "line", "locks", "treach")

    def __init__(self, mode, fn, method, line, locks, treach):
        self.mode = mode          # "r" | "w" | "c"
        self.fn = fn              # full function key
        self.method = method      # method name (init refinement)
        self.line = line
        self.locks = locks        # frozenset of effective lock keys
        self.treach = treach      # on a thread-reachable path


def _locks_str(locks) -> str:
    return ", ".join(sorted(cg.lock_display(k) for k in locks)) \
        or "no lock"


def race_model(ctx):
    """``{(cls, attr): [Site, ...]}`` for every class attribute of the
    file, memoized per FileContext — the four race rules share one
    pass. Sites carry EFFECTIVE locksets (lexically held + guaranteed
    on entry) and the thread-reachability of their function."""
    model = getattr(ctx, "_race_model", None)
    if model is not None:
        return model
    ms = sm.for_context(ctx)
    model = {}
    for key, s in ms.functions.items():
        if "." not in key:
            continue
        head, rest = key.split(".", 1)
        if head in ms.functions:
            continue              # nested def in a module function
        method = rest.split(".", 1)[0]
        entry = ms.entry_locks.get(key, frozenset())
        treach = key in ms.thread_reachable
        for attr, mode, line, held in s.attrs:
            site = _Site(mode, key, method, line,
                         frozenset(held) | entry, treach)
            model.setdefault((head, attr), []).append(site)
    for sites in model.values():
        sites.sort(key=lambda st: (st.line, st.fn))
    ctx._race_model = model
    return model


def _live(sites):
    """Init-refined accesses: ``__init__`` runs before the object is
    published, so its writes don't participate in the lockset."""
    return [st for st in sites if st.method != "__init__"]


def _thread_shared(live) -> bool:
    return any(st.treach for st in live) and len({st.fn for st in live}) > 1


@register
class UnlockedSharedMutation(Rule):
    code = "G22"
    name = "unlocked-shared-mutation"
    severity = "error"
    doc = ("A class attribute is mutated with NO lock held while other "
           "sites of the same attribute take a lock for it — on a "
           "class whose methods run concurrently (the module spawns a "
           "thread that reaches them). The locked sites prove the "
           "author considered the field shared; the unlocked write is "
           "then a torn update waiting for load (Eraser's core "
           "signal: the candidate lockset intersects to empty with a "
           "non-trivial starting set). Effective locksets include "
           "entry locks — a helper only ever called under the lock "
           "does NOT flag. Unlocked reads are deliberately tolerated "
           "(snapshot/monitoring patterns); `__init__` writes are "
           "pre-publication and ignored. Fix: take the same lock the "
           "other sites take, or — for genuine single-writer fields — "
           "document the ownership with an inline disable. Scope: "
           "mxnet_tpu/ library code.")

    def check(self, ctx):
        if not ctx.is_library():
            return
        ms = sm.for_context(ctx)
        if not ms.thread_roots:
            return
        for (cls, attr), sites in sorted(race_model(ctx).items()):
            live = _live(sites)
            if not _thread_shared(live):
                continue
            locked = [st for st in live if st.locks]
            if not locked:
                continue
            bare = [st for st in live if st.mode == "w" and not st.locks]
            if not bare:
                continue
            guard = _locks_str(set().union(*(st.locks for st in locked)))
            for st in bare:
                yield self.finding(
                    ctx, st.line,
                    f"`self.{attr}` mutated with no lock on a "
                    f"thread-shared path, but other sites guard it "
                    f"with {guard} (e.g. line {locked[0].line}) — a "
                    f"concurrent peer can interleave mid-update; take "
                    f"the same lock here")


@register
class InconsistentLockset(Rule):
    code = "G23"
    name = "inconsistent-lockset"
    severity = "error"
    doc = ("Two sites protect the SAME class attribute with DISJOINT "
           "locks on a class whose methods run concurrently — each "
           "site is individually 'locked' but no common lock orders "
           "the two accesses, so they interleave exactly as if "
           "unlocked. This is the shape of the PR-11 "
           "`Heartbeat.beat()` stale-overwrite bug (the daemon and the "
           "caller each took their own lock around the shared ledger "
           "state; the pre-fix shape is the "
           "tests/data/graftlint/hist_heartbeat_overwrite.py "
           "fixture). Only pairs with at least one WRITE flag "
           "(read/read needs no ordering); attributes with an "
           "unlocked write are G22's territory, not double-reported "
           "here. Fix: pick ONE lock for the field and use it at "
           "every site. Scope: mxnet_tpu/ library code.")

    def check(self, ctx):
        if not ctx.is_library():
            return
        ms = sm.for_context(ctx)
        if not ms.thread_roots:
            return
        for (cls, attr), sites in sorted(race_model(ctx).items()):
            live = _live(sites)
            if not _thread_shared(live):
                continue
            writes = [st for st in live if st.mode == "w"]
            if not writes or any(not st.locks for st in writes):
                continue              # no writes / G22's case
            flagged = False
            for w in writes:
                for other in live:
                    if other is w or not other.locks:
                        continue
                    if w.locks & other.locks:
                        continue
                    yield self.finding(
                        ctx, max(w.line, other.line),
                        f"`self.{attr}` written under "
                        f"{_locks_str(w.locks)} (line {w.line}) but "
                        f"accessed under disjoint "
                        f"{_locks_str(other.locks)} (line "
                        f"{other.line}) — no common lock orders the "
                        f"two, so they interleave as if unlocked; "
                        f"protect the field with ONE lock everywhere")
                    flagged = True
                    break
                if flagged:
                    break             # one finding per attribute


@register
class CheckThenActRace(Rule):
    code = "G24"
    name = "check-then-act-race"
    severity = "error"
    doc = ("A membership test over a shared dict/set (`if k not in "
           "self._x:`) guards a mutation of the same attribute, but no "
           "single lock spans BOTH the check and the act — between "
           "them a concurrent peer can invalidate the answer, so two "
           "threads both pass the test and both mutate (TOCTOU). This "
           "is the shape behind the PR-9 latched half-open probe "
           "(membership checked during enumeration, slot claimed "
           "later; the pre-fix shape is the "
           "tests/data/graftlint/hist_latched_probe_toctou.py "
           "fixture). Flags only attributes that are thread-shared "
           "(touched from a thread-reachable function and at least "
           "one other); a `with lock:` enclosing both check and act — "
           "including via entry locks — is the fix and silences it. "
           "Scope: mxnet_tpu/ library code.")

    def check(self, ctx):
        if not ctx.is_library():
            return
        ms = sm.for_context(ctx)
        if not ms.thread_roots:
            return
        model = race_model(ctx)
        for key, s in sorted(ms.functions.items()):
            if "." not in key:
                continue
            head = key.split(".", 1)[0]
            if head in ms.functions:
                continue
            entry = ms.entry_locks.get(key, frozenset())
            for attr, t_line, t_locks, a_line, a_locks in s.toctou:
                live = _live(model.get((head, attr), ()))
                if not _thread_shared(live):
                    continue
                eff_t = frozenset(t_locks) | entry
                eff_a = frozenset(a_locks) | entry
                if eff_t & eff_a:
                    continue          # one lock spans check AND act
                yield self.finding(
                    ctx, a_line,
                    f"`self.{attr}` mutated based on a membership "
                    f"test at line {t_line}, but no lock spans both "
                    f"(check under {_locks_str(eff_t)}, act under "
                    f"{_locks_str(eff_a)}) — the answer can go stale "
                    f"between them and two threads both act; hold one "
                    f"lock across the check and the mutation")


@register
class CondWaitWithoutPredicateLoop(Rule):
    code = "G25"
    name = "cond-wait-without-predicate-loop"
    severity = "error"
    doc = ("`Condition.wait()` outside a `while` predicate loop. "
           "Condition waits are edge-triggered and legally subject to "
           "spurious wakeups, and with multiple waiters a single "
           "notify can wake the wrong one after the predicate was "
           "consumed — an `if`-guarded (or unguarded) wait then "
           "proceeds on a false premise. Python's own docs mandate "
           "the loop; `wait_for(pred)` embeds it and is the "
           "recommended spelling. Receivers count as conditions when "
           "constructed from `threading.Condition` in this module or "
           "when the name reads like one (`_cv`, `_cond`); "
           "`Event.wait()` is level-triggered and exempt. Scope: "
           "mxnet_tpu/ library code.")

    def check(self, ctx):
        if not ctx.is_library():
            return
        ms = sm.for_context(ctx)
        for _key, s in sorted(ms.functions.items()):
            for recv, line, in_loop in s.cond_waits:
                if in_loop:
                    continue
                yield self.finding(
                    ctx, line,
                    f"`{recv}.wait()` is not re-checked in a `while` "
                    f"predicate loop — spurious wakeups and consumed "
                    f"notifies resume with the predicate false; use "
                    f"`while not pred: {recv}.wait()` or "
                    f"`{recv}.wait_for(pred)`")

"""graftlint — the repo's AST-based static-analysis tier.

One framework behind both the generic hygiene rules (``W*``, the old
``ci/lint.py`` tier) and the project-specific JAX-hazard rules (``G*``:
import-time backend dials, PRNG discipline, host syncs in traced code,
undeadlined subprocesses, silent device-failure swallows). See
``docs/static_analysis.md`` for the rule catalog and workflow; the
runtime half of the same defense lives in ``mxnet_tpu/diagnostics``.

CLI: ``python -m mxnet_tpu.analysis [paths] [--format=text|json|sarif]
[--write-baseline] [--rules=...]``.
"""
from .core import (Finding, Rule, FileContext, all_rules, load_rules,
                   lint_file, run, DEFAULT_PATHS, DEFAULT_EXCLUDES)
from .baseline import load_baseline, partition, write_baseline
from .cli import main, repo_root

__all__ = ["Finding", "Rule", "FileContext", "all_rules", "load_rules",
           "lint_file", "run", "DEFAULT_PATHS", "DEFAULT_EXCLUDES",
           "load_baseline", "partition", "write_baseline", "main",
           "repo_root"]

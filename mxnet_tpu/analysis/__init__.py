"""graftlint — the repo's AST-based static-analysis tier.

One framework behind both the generic hygiene rules (``W*``, the old
``ci/lint.py`` tier) and the project-specific JAX-hazard rules (``G*``:
import-time backend dials, PRNG discipline, host syncs in traced code,
undeadlined subprocesses, silent device-failure swallows). See
``docs/static_analysis.md`` for the rule catalog and workflow; the
runtime half of the same defense lives in ``mxnet_tpu/diagnostics``.

CLI: ``python -m mxnet_tpu.analysis [paths] [--format=text|json|sarif]
[--write-baseline] [--rules=...] [--jobs N] [--changed-only REF]``.

The interprocedural tier (G15-G19: lock discipline, rank uniformity
through helpers, dropped deadlines) runs on the per-module call-graph +
function-summary engine in :mod:`.callgraph` / :mod:`.summaries` —
cycle-safe fixpoint propagation, per-file summary cache keyed by
content fingerprint.
"""
from .core import (Finding, Rule, FileContext, all_rules, load_rules,
                   lint_file, run, DEFAULT_PATHS, DEFAULT_EXCLUDES)
from .baseline import load_baseline, partition, write_baseline
from .cli import changed_only_paths, main, repo_root
from .summaries import ModuleSummaries, SummaryCache, module_summaries

__all__ = ["Finding", "Rule", "FileContext", "all_rules", "load_rules",
           "lint_file", "run", "DEFAULT_PATHS", "DEFAULT_EXCLUDES",
           "load_baseline", "partition", "write_baseline", "main",
           "repo_root", "changed_only_paths", "ModuleSummaries",
           "SummaryCache", "module_summaries"]

"""doctor-facing lint summary — ``python -m mxnet_tpu.diagnostics
doctor --lint <repo-root>``.

One in-process graftlint run over the checkout, reduced to the numbers
an operator triages by: file count, new-vs-baselined split, per-rule
finding counts, summary-cache hit rate, and wall clock. Rides the
diagnostics ``_REPORT_TABLE`` like every other report surface.
"""
from __future__ import annotations

import os
import sys
import time

from . import baseline as _baseline
from . import core
from . import summaries as _summaries
from .cli import repo_root

__all__ = ["lint_report"]


def lint_report(root=None) -> dict:
    """Run graftlint over ``root`` (default: this checkout) and return
    the doctor summary dict. Never raises — a broken checkout reports
    ``ok: False`` with the reason, like every doctor section."""
    root = os.path.abspath(root) if root else repo_root()
    t0 = time.perf_counter()
    cache = None
    cpath = os.path.join(root, _summaries.DEFAULT_CACHE)
    if os.path.isdir(os.path.dirname(cpath)):
        cache = _summaries.SummaryCache.load(cpath)
    prev = _summaries.set_active_cache(cache)
    # fork-based --jobs is unsafe once jax's own threads exist in this
    # process (doctor imports the runtime); serial + warm cache is fast
    # enough, and a wedged doctor would be the worst possible irony
    jobs = 1 if "jax" in sys.modules else 0
    core.collect_rule_timings(True)
    try:
        findings, n_files = core.run(root=root, jobs=jobs)
    except (OSError, SyntaxError) as e:
        return {"ok": False, "error": type(e).__name__,
                "detail": str(e)[:300], "root": root}
    finally:
        timings = core.drain_rule_timings()
        core.collect_rule_timings(False)
        _summaries.set_active_cache(prev)
        if cache is not None:
            try:
                cache.save(keep=4096)
            except OSError:
                pass
    if n_files == 0:
        return {"ok": False, "error": "no_files",
                "detail": f"no .py files under {root}", "root": root}
    try:
        entries = _baseline.load_baseline(
            os.path.join(root, _baseline.DEFAULT_BASELINE))
    except ValueError as e:
        return {"ok": False, "error": "bad_baseline",
                "detail": str(e)[:300], "root": root}
    new, based = _baseline.partition(findings, entries)
    rules: dict = {}
    for f in new:
        rules[f.code] = rules.get(f.code, 0) + 1
    # per-rule cost/yield: wall-clock spent inside each rule's check()
    # and the RAW sites it flagged (before suppressions/baseline —
    # inline-disabled sites still cost their detection time). The
    # first interprocedural rule per file pays the shared summary
    # extraction, so its wall time reads high by design.
    rule_stats = {
        code: {"wall_ms": round(wall * 1000.0, 2), "findings": count}
        for code, (wall, count) in sorted(timings.items())}
    return {"ok": True, "root": root, "files": n_files,
            "new": len(new), "baselined": len(based), "rules": rules,
            "rule_stats": rule_stats,
            "cache": cache.stats() if cache is not None else None,
            "wall_s": round(time.perf_counter() - t0, 2)}

"""JAX-hazard rules (G-codes) — project-specific semantics grounded in
defects this repo actually shipped:

- G1: the round-4/5 wedge class itself — ``_rng.py`` dialed the backend
  at module scope, so ``import mxnet_tpu`` in a tunnel-pinned process
  hung before any wedge-proofing could run (VERDICT r5).
- G4/G6: ``engine.waitall`` probed devices directly and swallowed every
  failure silently (the anti-pattern the diagnostics journal exists to
  kill).
- G5: the PR-1 deadline lesson — every undeadlined subprocess is a
  future rc:124 with no artifact.

Each rule resolves names through the file's import aliases
(``jnp.asarray`` → ``jax.numpy.asarray``); none of them import jax.
"""
from __future__ import annotations

import ast
import re

from .core import Rule, register

# calls that initialize (or require) a live backend client — including
# jax.numpy array CREATION: the first concrete array is a backend touch
# (guard.py's docstring names it), so a module-scope jnp constant wedges
# importers exactly like a module-scope jax.devices()
BACKEND_DIAL = {
    "jax.devices", "jax.local_devices", "jax.device_count",
    "jax.local_device_count", "jax.device_put", "jax.device_get",
    "jax.default_backend", "jax.process_index", "jax.process_count",
    "jax.block_until_ready", "jax.random.PRNGKey", "jax.random.key",
} | {"jax.numpy." + f for f in (
    "array", "asarray", "zeros", "ones", "full", "empty", "arange",
    "linspace", "eye", "identity", "zeros_like", "ones_like",
    "full_like")}

DEVICE_PROBES = {"jax.devices", "jax.local_devices"}

KEY_MAKERS = {"jax.random.PRNGKey", "jax.random.key"}

# jax.random draws that consume a key (split/fold_in deliberately absent)
SAMPLERS = {
    "uniform", "normal", "bernoulli", "bits", "randint", "permutation",
    "shuffle", "categorical", "gamma", "beta", "exponential", "poisson",
    "truncated_normal", "gumbel", "laplace", "cauchy", "choice",
    "dirichlet", "multivariate_normal", "rademacher", "t", "logistic",
}

JIT_WRAPPERS = {"jax.jit", "jax.pjit", "jax.experimental.pjit.pjit"}
PARTIALS = {"functools.partial", "partial"}

# (callable, indices of function-valued args) for traced-body detection
TRACED_ARG_CALLS = {
    "jax.lax.scan": (0,),
    "jax.lax.map": (0,),
    "jax.lax.fori_loop": (2,),
    "jax.lax.while_loop": (0, 1),
    "jax.lax.cond": (1, 2),
    "jax.lax.switch": (1,),
    "jax.lax.associative_scan": (0,),
    "jax.checkpoint": (0,),
    "jax.remat": (0,),
}

HOST_SYNC_ATTRS = {"item", "tolist", "block_until_ready"}
HOST_SYNC_CALLS = {"numpy.asarray", "numpy.array", "jax.device_get",
                   "jax.block_until_ready"}


def _is_main_guard(test) -> bool:
    """True for the ``__name__ == "__main__"`` comparison (either
    operand order) — that body runs as a script, never at import."""
    if not (isinstance(test, ast.Compare) and len(test.ops) == 1
            and isinstance(test.ops[0], ast.Eq)):
        return False
    operands = [test.left] + test.comparators
    names = {o.id for o in operands if isinstance(o, ast.Name)}
    consts = {o.value for o in operands if isinstance(o, ast.Constant)}
    return "__name__" in names and "__main__" in consts


def _walk_import_time(tree):
    """Yield (node, import_time) for the whole module: a node is
    import-time iff no function/lambda/genexp body (or ``__main__``
    guard) encloses it. Decorators, default argument values, class
    bodies — and annotations, unless ``from __future__ import
    annotations`` defers them — DO run at import."""
    out = []
    lazy_annotations = any(
        isinstance(n, ast.ImportFrom) and n.module == "__future__"
        and any(a.name == "annotations" for a in n.names)
        for n in tree.body)

    def visit_annotation(ann, import_time):
        if ann is not None and not lazy_annotations:
            visit(ann, import_time)

    def visit(node, import_time):
        out.append((node, import_time))
        if isinstance(node, ast.If) and _is_main_guard(node.test):
            visit(node.test, import_time)
            for child in node.body:
                visit(child, False)
            for child in node.orelse:
                visit(child, import_time)
            return
        if isinstance(node, ast.AnnAssign):
            visit_annotation(node.annotation, import_time)
            visit(node.target, import_time)
            if node.value is not None:
                visit(node.value, import_time)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for d in node.decorator_list:
                visit(d, import_time)
            for d in node.args.defaults:
                visit(d, import_time)
            for d in node.args.kw_defaults:
                if d is not None:
                    visit(d, import_time)
            a = node.args
            for arg in (a.posonlyargs + a.args + a.kwonlyargs
                        + [a.vararg, a.kwarg]):
                if arg is not None:
                    visit_annotation(arg.annotation, import_time)
            visit_annotation(node.returns, import_time)
            for child in node.body:
                visit(child, False)
            return
        if isinstance(node, ast.GeneratorExp):
            # building a genexp evaluates ONLY the first iterable; the
            # body is deferred until iteration
            visit(node.generators[0].iter, import_time)
            for i, gen in enumerate(node.generators):
                visit(gen.target, False)
                if i > 0:
                    visit(gen.iter, False)
                for cond in gen.ifs:
                    visit(cond, False)
            visit(node.elt, False)
            return
        if isinstance(node, ast.Lambda):
            # lambda DEFAULTS evaluate when the expression does (maybe
            # at import); only the body is deferred
            for d in node.args.defaults:
                visit(d, import_time)
            for d in node.args.kw_defaults:
                if d is not None:
                    visit(d, import_time)
            visit(node.body, False)
            return
        for child in ast.iter_child_nodes(node):
            visit(child, import_time)

    visit(tree, True)
    return out


@register
class ModuleScopeBackendDial(Rule):
    code = "G1"
    name = "module-scope-backend-dial"
    severity = "error"
    doc = ("Backend-dialing call (jax.devices/device_put/PRNGKey/...) "
           "reachable at import time — module scope, class body, "
           "decorator, or default argument. An import-time dial hangs "
           "every process that imports the module when the TPU tunnel "
           "is wedged (the round-4/5 rc:124 root cause). Defer the "
           "touch into a function and route it through "
           "mxnet_tpu.diagnostics.guard.")

    def check(self, ctx):
        for node, import_time in _walk_import_time(ctx.tree):
            if not import_time or not isinstance(node, ast.Call):
                continue
            name = ctx.resolve_call(node)
            if name in BACKEND_DIAL:
                yield self.finding(
                    ctx, node.lineno,
                    f"module-scope backend dial: {name}() runs at import "
                    f"time; defer it into a function (guarded by "
                    f"diagnostics.guard)")


@register
class PrngDiscipline(Rule):
    code = "G2"
    name = "prng-discipline"
    doc = ("Library code must not bake constant PRNG keys "
           "(jax.random.PRNGKey(0) gives every caller the same stream "
           "and dials the backend wherever it runs), and must not feed "
           "the same key to two draws without an intervening "
           "split/fold_in (identical randomness — the correlated-"
           "dropout-mask class fixed in PR 1). Scope: mxnet_tpu/ "
           "library code.")

    def check(self, ctx):
        if not ctx.is_library():
            return
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) \
                    and ctx.resolve_call(node) in KEY_MAKERS \
                    and ((node.args
                          and isinstance(node.args[0], ast.Constant))
                         or any(kw.arg == "seed"
                                and isinstance(kw.value, ast.Constant)
                                for kw in node.keywords)):
                yield self.finding(
                    ctx, node.lineno,
                    "constant PRNG key in library code: every caller "
                    "draws the identical stream (thread a key in, or use "
                    "_rng.next_key())")
        for fn in ast.walk(ctx.tree):
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._check_reuse(ctx, fn)

    def _check_reuse(self, ctx, fn):
        out = []
        self._scan_block(ctx, fn.body, set(), out)
        return out

    def _scan_block(self, ctx, stmts, drawn, out):
        """Key-lifetime scan, branch-aware: mutually exclusive branches
        each fork the drawn-set (one draw per if/else arm is NOT reuse);
        afterwards the union flows on (a draw in any arm plus a later
        draw of the same key IS)."""
        for stmt in stmts:
            if isinstance(stmt, ast.If):
                self._apply_events(ctx, [stmt.test], drawn, out)
                forks = []
                for block in (stmt.body, stmt.orelse):
                    d = set(drawn)
                    self._scan_block(ctx, block, d, out)
                    # a terminating arm (guard clause) never rejoins the
                    # fall-through flow — its draws don't leak forward
                    if not self._terminates(block):
                        forks.append(d)
                drawn.update(*forks)
            elif isinstance(stmt, ast.Try):
                self._scan_block(ctx, stmt.body, drawn, out)
                # handlers and the else-block are mutually exclusive
                # alternatives after the body
                base = set(drawn)
                forks = []
                blocks = [h.body for h in stmt.handlers]
                if stmt.orelse:
                    blocks.append(stmt.orelse)
                for block in blocks:
                    d = set(base)
                    self._scan_block(ctx, block, d, out)
                    if not self._terminates(block):
                        forks.append(d)
                drawn.update(*forks)
                self._scan_block(ctx, stmt.finalbody, drawn, out)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._apply_events(ctx, [stmt.iter], drawn, out)
                # the loop target rebinds EVERY iteration — a fresh key
                # per pass (`for k in jax.random.split(key, n):`)
                targets = [sub.id for sub in ast.walk(stmt.target)
                           if isinstance(sub, ast.Name)]
                self._scan_loop_body(ctx, stmt.body, drawn, out,
                                     refresh=targets)
                self._scan_block(ctx, stmt.orelse, drawn, out)
            elif isinstance(stmt, ast.While):
                self._apply_events(ctx, [stmt.test], drawn, out)
                self._scan_loop_body(ctx, stmt.body, drawn, out)
                self._scan_block(ctx, stmt.orelse, drawn, out)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                self._apply_events(
                    ctx, [i.context_expr for i in stmt.items], drawn, out)
                for item in stmt.items:     # `as key:` rebinds
                    if item.optional_vars is not None:
                        for sub in ast.walk(item.optional_vars):
                            if isinstance(sub, ast.Name):
                                drawn.discard(sub.id)
                self._scan_block(ctx, stmt.body, drawn, out)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef)):
                pass            # separate scope, scanned on its own
            elif isinstance(stmt, ast.Match):
                # match arms are mutually exclusive, like if/else
                self._apply_events(ctx, [stmt.subject], drawn, out)
                forks = []
                for case in stmt.cases:
                    d = set(drawn)
                    self._scan_block(ctx, case.body, d, out)
                    if not self._terminates(case.body):
                        forks.append(d)
                drawn.update(*forks)
            else:
                self._apply_events(ctx, [stmt], drawn, out)

    @staticmethod
    def _terminates(stmts) -> bool:
        """True when a block's flow cannot rejoin the statement after
        its parent (guard clauses: return/raise/break/continue last)."""
        return bool(stmts) and isinstance(
            stmts[-1], (ast.Return, ast.Raise, ast.Break, ast.Continue))

    def _scan_loop_body(self, ctx, stmts, drawn, out, refresh=()):
        """Loop bodies run repeatedly: a second pass seeded with the
        first pass's drawn-set catches a same-key draw repeated across
        iterations (the correlated-mask-per-tick class from PR 1) while
        a per-iteration split/fold_in still clears it. ``refresh``
        names (the for-loop target) rebind before every pass."""
        for var in refresh:
            drawn.discard(var)
        self._scan_block(ctx, stmts, drawn, out)
        for var in refresh:
            drawn.discard(var)
        second = []
        self._scan_block(ctx, stmts, drawn, second)
        seen = {(f.line, f.message) for f in out}
        out.extend(f for f in second if (f.line, f.message) not in seen)

    def _apply_events(self, ctx, nodes, drawn, out):
        for node in nodes:
            self._apply_node(ctx, node, drawn, out)

    def _apply_node(self, ctx, node, drawn, out):
        """Fold one node's draw/refresh events into the drawn-set in
        evaluation order, forking at expression-level branches (IfExp,
        short-circuiting BoolOp) exactly like _scan_block forks at
        statement-level if/match. Nested defs/lambdas are own scopes."""
        if node is None or isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return
        if isinstance(node, ast.IfExp):
            self._apply_node(ctx, node.test, drawn, out)
            forks = []
            for arm in (node.body, node.orelse):
                d = set(drawn)
                self._apply_node(ctx, arm, d, out)
                forks.append(d)
            drawn.update(*forks)
            return
        if isinstance(node, ast.BoolOp):
            # operands after the first may be short-circuited away
            self._apply_node(ctx, node.values[0], drawn, out)
            forks = []
            for v in node.values[1:]:
                d = set(drawn)
                self._apply_node(ctx, v, d, out)
                forks.append(d)
            drawn.update(*forks)
            return
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.NamedExpr,
                             ast.AnnAssign)):
            # value evaluates first; binding the targets then REFRESHES
            # them (k, sub = split(k) never reads stale state); walrus
            # and annotated rebinds count too
            if isinstance(node, ast.AnnAssign) and node.value is None:
                return              # bare annotation: nothing binds
            self._apply_node(ctx, node.value, drawn, out)
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                for sub in ast.walk(t):
                    if isinstance(sub, ast.Name):
                        drawn.discard(sub.id)
            return
        if isinstance(node, ast.Call):
            for child in ast.iter_child_nodes(node):
                self._apply_node(ctx, child, drawn, out)
            name = ctx.resolve_call(node) or ""
            if name.startswith("jax.random.") and \
                    name.rsplit(".", 1)[-1] in SAMPLERS and \
                    node.args and isinstance(node.args[0], ast.Name):
                # a refresh happens only when the split/fold_in RESULT is
                # bound (the Assign-target discard) — `split(key)` with
                # the result dropped does not freshen `key`
                var = node.args[0].id
                if var in drawn:
                    out.append(self.finding(
                        ctx, node.lineno,
                        f"PRNG key {var!r} fed to a second draw with no "
                        f"split/fold_in between — identical random bits"))
                else:
                    drawn.add(var)
            return
        for child in ast.iter_child_nodes(node):
            self._apply_node(ctx, child, drawn, out)


def _static_under_trace(arg) -> bool:
    """True when the expression reads tracer METADATA (.shape/.ndim/
    .size/.dtype, len()) — static Python values during tracing, so
    int()/float() over them is trace-safe, not a host sync."""
    for n in ast.walk(arg):
        if isinstance(n, ast.Attribute) and n.attr in (
                "shape", "ndim", "size", "dtype"):
            return True
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Name) \
                and n.func.id == "len":
            return True
    return False


def _traced_functions(ctx):
    """FunctionDef/Lambda nodes whose bodies run under trace: jit/pjit-
    decorated defs, plus functions handed to lax control-flow combinators
    (scan/while/cond/...) by name or inline lambda."""
    by_name = {}
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            by_name.setdefault(node.name, []).append(node)
    traced = []
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                name = ctx.resolve(target)
                if name in JIT_WRAPPERS:
                    traced.append(node)
                elif isinstance(dec, ast.Call) and name in PARTIALS and \
                        any(ctx.resolve(a) in JIT_WRAPPERS
                            for a in dec.args):
                    traced.append(node)
        elif isinstance(node, ast.Call):
            name = ctx.resolve_call(node)
            arg_idx = ()
            if name in TRACED_ARG_CALLS:
                arg_idx = TRACED_ARG_CALLS[name]
            elif name in JIT_WRAPPERS:
                arg_idx = (0,)
            for i in arg_idx:
                if i < len(node.args):
                    a = node.args[i]
                    if isinstance(a, ast.Name):
                        traced.extend(by_name.get(a.id, ()))
                    elif isinstance(a, ast.Lambda):
                        traced.append(a)
    return traced


@register
class HostSyncInTracedCode(Rule):
    code = "G3"
    name = "host-sync-in-traced-code"
    severity = "error"
    doc = ("Host synchronization (.item()/.tolist()/float()/np.asarray/"
           "block_until_ready) inside jit/pjit-decorated functions or "
           "lax.scan/while/cond bodies. Under trace these either fail "
           "(ConcretizationTypeError) or silently force a device→host "
           "round trip per step, serializing the TPU pipeline.")

    def check(self, ctx):
        seen = set()
        for fn in _traced_functions(ctx):
            body = fn.body if isinstance(fn.body, list) else [fn.body]
            # nested defs/lambdas are separate scopes (pure_callback
            # host helpers legitimately sync); a nested fn that IS
            # traced (e.g. named in lax.scan) is collected above
            stack = list(body)
            while stack:
                node = stack.pop()
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.Lambda)):
                    continue
                stack.extend(ast.iter_child_nodes(node))
                if not isinstance(node, ast.Call) or id(node) in seen:
                    continue
                hit = self._host_sync_hit(ctx, node)
                if hit:
                    seen.add(id(node))
                    yield self.finding(
                        ctx, node.lineno,
                        f"host sync {hit} inside traced code — fails or "
                        f"forces a device round trip under jit/scan")

    @staticmethod
    def _host_sync_hit(ctx, node):
        func = node.func
        if isinstance(func, ast.Attribute) and func.attr in HOST_SYNC_ATTRS:
            return f".{func.attr}()"
        name = ctx.resolve(func)
        if name in HOST_SYNC_CALLS:
            return f"{name}()"
        if isinstance(func, ast.Name) and func.id in ("float", "int") \
                and len(node.args) == 1 \
                and not isinstance(node.args[0], ast.Constant) \
                and not _static_under_trace(node.args[0]):
            return f"{func.id}()"
        return None


@register
class UnguardedDeviceProbe(Rule):
    code = "G4"
    name = "unguarded-device-probe"
    severity = "error"
    doc = ("Direct jax.devices()/jax.local_devices() in library code. "
           "A wedged tunnel hangs the caller indefinitely; "
           "diagnostics.guard.devices() / ensure_backend() is the one "
           "sanctioned dial (journaled, deadline-guarded, cached). "
           "Scope: mxnet_tpu/ library code.")

    def check(self, ctx):
        if not ctx.is_library():
            return
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) and \
                    ctx.resolve_call(node) in DEVICE_PROBES:
                yield self.finding(
                    ctx, node.lineno,
                    "direct device probe in library code — use "
                    "diagnostics.guard.devices() (deadline-guarded, "
                    "journaled) instead of jax.devices()")


@register
class UndeadlinedSubprocess(Rule):
    code = "G5"
    name = "subprocess-without-timeout"
    doc = ("Blocking subprocess call (run/call/check_call/check_output) "
           "without timeout=. A child that dials a wedged backend hangs "
           "the parent for the driver's whole window — every such wait "
           "needs a deadline (the PR-1 lesson; guard.probe_backend is "
           "the model).")

    BLOCKING = {"subprocess.run", "subprocess.call",
                "subprocess.check_call", "subprocess.check_output"}

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = ctx.resolve_call(node)
            if name not in self.BLOCKING:
                continue
            kw_names = {kw.arg for kw in node.keywords}
            if "timeout" in kw_names or None in kw_names:  # **kwargs: unknown
                continue
            yield self.finding(
                ctx, node.lineno,
                f"{name}() without timeout= — an undeadlined child "
                f"hang becomes an information-free rc:124")


QUEUE_MAKERS = {"queue.Queue", "queue.LifoQueue", "queue.PriorityQueue"}
ALWAYS_UNBOUNDED_MAKERS = {"queue.SimpleQueue"}
THREAD_MAKERS = {"threading.Thread", "threading.Timer"}


@register
class UnboundedQueueDiscipline(Rule):
    code = "G8"
    name = "unbounded-queue"
    doc = ("Unbounded ``queue.Queue()`` construction, or a blocking "
           "``.get()``/``.join()`` on a queue/thread without "
           "``timeout=``, in library code. An unbounded queue turns "
           "overload into unbounded latency + memory (the serving "
           "subsystem's admission contract: shed with ServerOverloaded "
           "instead — docs/serving.md), and an undeadlined get/join is "
           "the in-process twin of G5's subprocess hang: one wedged "
           "producer thread and the caller blocks for the driver's "
           "whole window. ``queue.Queue.join()`` accepts no timeout at "
           "all — restructure around bounded waits. Scope: mxnet_tpu/ "
           "library code.")

    @staticmethod
    def _const_int(node):
        if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub) \
                and isinstance(node.operand, ast.Constant) \
                and isinstance(node.operand.value, int):
            return -node.operand.value
        if isinstance(node, ast.Constant) and isinstance(node.value, int) \
                and not isinstance(node.value, bool):
            return node.value
        return None

    def _unbounded_construction(self, call):
        kw = {k.arg: k.value for k in call.keywords}
        if None in kw:                       # **kwargs: unknown, trust it
            return False
        maxsize = call.args[0] if call.args else kw.get("maxsize")
        if maxsize is None:
            return True                      # default maxsize=0: unbounded
        c = self._const_int(maxsize)
        return c is not None and c <= 0      # explicit 0/negative

    @staticmethod
    def _receivers(ctx):
        """Dotted receiver names bound to queue / thread constructions
        anywhere in the file ('q', 'self._queue', ...)."""
        queues, threads = set(), set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign):
                value, targets = node.value, node.targets
            elif isinstance(node, (ast.AnnAssign, ast.NamedExpr)) \
                    and node.value is not None:
                value, targets = node.value, [node.target]
            else:
                continue
            if not isinstance(value, ast.Call):
                continue
            name = ctx.resolve_call(value)
            if name in QUEUE_MAKERS | ALWAYS_UNBOUNDED_MAKERS:
                pool = queues
            elif name in THREAD_MAKERS:
                pool = threads
            else:
                continue
            for t in targets:
                dotted = ctx.resolve(t)
                if dotted:
                    pool.add(dotted)
        return queues, threads

    def check(self, ctx):
        if not ctx.is_library():
            return
        queues, threads = self._receivers(ctx)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = ctx.resolve_call(node)
            if name in ALWAYS_UNBOUNDED_MAKERS:
                yield self.finding(
                    ctx, node.lineno,
                    f"{name}() is unbounded by construction — overload "
                    "becomes unbounded memory/latency; use a bounded "
                    "queue.Queue(maxsize=N) and shed on Full")
                continue
            if name in QUEUE_MAKERS and self._unbounded_construction(node):
                yield self.finding(
                    ctx, node.lineno,
                    f"unbounded {name}() in library code — pass "
                    "maxsize=N and shed on queue.Full (the serving "
                    "admission-control contract)")
                continue
            func = node.func
            if not isinstance(func, ast.Attribute):
                continue
            recv = ctx.resolve(func.value)
            if recv is None:
                continue
            kw_names = {k.arg for k in node.keywords}
            if None in kw_names:             # **kwargs: unknown
                continue
            if func.attr == "get" and recv in queues:
                if "timeout" in kw_names or len(node.args) >= 2:
                    continue
                blk = node.args[0] if node.args else None
                for k in node.keywords:
                    if k.arg == "block":
                        blk = k.value
                if isinstance(blk, ast.Constant) and blk.value is False:
                    continue                 # non-blocking get
                yield self.finding(
                    ctx, node.lineno,
                    f"{recv}.get() without timeout= — a wedged producer "
                    "hangs the consumer for the driver's whole window "
                    "(the G5 lesson, in-process)")
            elif func.attr == "join":
                if recv in queues:
                    yield self.finding(
                        ctx, node.lineno,
                        f"{recv}.join(): queue.Queue.join() accepts no "
                        "timeout — restructure around bounded waits "
                        "(task counting + Event.wait(timeout=))")
                elif recv in threads and "timeout" not in kw_names \
                        and not node.args:
                    yield self.finding(
                        ctx, node.lineno,
                        f"{recv}.join() without timeout= — a wedged "
                        "worker thread hangs shutdown forever; join "
                        "with a deadline and report the stall")


ARTIFACT_SUFFIXES = (".params", ".states", ".pstate", ".json", ".onnx")
_SAVE_FN_RE = re.compile(r"save|checkpoint|export|dump", re.IGNORECASE)


def _functions_with_calls(tree):
    """Yield (call_node, enclosing_function_name_or_None) for every Call
    in the module (innermost function wins)."""
    out = []

    def visit(node, fn_name):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fn_name = node.name
        if isinstance(node, ast.Call):
            out.append((node, fn_name))
        for child in ast.iter_child_nodes(node):
            visit(child, fn_name)

    visit(tree, None)
    return out


@register
class NonAtomicDurableWrite(Rule):
    code = "G7"
    name = "non-atomic-durable-write"
    doc = ("Durable artifact (.params/.states/.json/...) opened with a "
           "direct open(path, 'w'/'wb') in library code: a preemption "
           "mid-write leaves a torn file the loader misparses (the "
           "crash class docs/checkpointing.md exists for). Route the "
           "write through mxnet_tpu.resilience.atomic.atomic_write "
           "(tmp + fsync + os.replace). Flagged on artifact-suffix "
           "evidence in the path expression, or a bare path variable "
           "inside a save/checkpoint/export/dump-named function. "
           "Scope: mxnet_tpu/ library code.")

    @staticmethod
    def _write_mode(node) -> bool:
        mode = None
        if len(node.args) >= 2:
            mode = node.args[1]
        for kw in node.keywords:
            if kw.arg == "mode":
                mode = kw.value
        return (isinstance(mode, ast.Constant)
                and isinstance(mode.value, str)
                and mode.value.startswith("w"))

    @staticmethod
    def _suffix_evidence(path_arg):
        for sub in ast.walk(path_arg):
            if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
                if sub.value.endswith(ARTIFACT_SUFFIXES):
                    return sub.value
        return None

    def check(self, ctx):
        if not ctx.is_library():
            return
        for node, fn_name in _functions_with_calls(ctx.tree):
            if ctx.resolve_call(node) not in ("open", "io.open"):
                continue
            if not node.args or not self._write_mode(node):
                continue
            path_arg = node.args[0]
            suffix = self._suffix_evidence(path_arg)
            named_save = (isinstance(path_arg, (ast.Name, ast.Attribute))
                          and fn_name is not None
                          and _SAVE_FN_RE.search(fn_name))
            if suffix:
                yield self.finding(
                    ctx, node.lineno,
                    f"direct write to durable artifact ({suffix!r}) — a "
                    "crash mid-write leaves a torn file; use "
                    "resilience.atomic.atomic_write")
            elif named_save:
                yield self.finding(
                    ctx, node.lineno,
                    f"open(..., 'w') inside {fn_name}(): checkpoint-"
                    "shaped writers must be atomic — use "
                    "resilience.atomic.atomic_write (tmp + fsync + "
                    "os.replace)")


# -- G9: host-synced finiteness checks in training-loop code -----------------

# the modules that sit on the per-step hot path: a host-synced finiteness
# check here costs a device→host round trip EVERY step (the defect class
# the fused guard replaced — gluon/utils.py's old per-array asscalar()
# loop and amp's per-step has_overflow pull)
TRAINING_PATH_RE = re.compile(
    r"(^|/)mxnet_tpu/(gluon/(trainer|utils)\.py|module/[^/]+\.py|"
    r"parallel/[^/]+\.py|contrib/amp/[^/]+\.py|optimizer/[^/]+\.py)$")
_SCOPE_TRAINING_RE = re.compile(r"#\s*graftlint:\s*scope=training\b")

HOST_FINITENESS = {"numpy.isfinite", "numpy.isnan", "numpy.isinf"}
DEVICE_FINITENESS = {"jax.numpy.isfinite", "jax.numpy.isnan",
                     "jax.numpy.isinf"} | HOST_FINITENESS
# identifiers that smell like per-step training values; float()/.item()/
# .asscalar() over them in a training module is a per-step host sync
GUARD_VALUE_RE = re.compile(r"grad|loss|norm|overflow|finite", re.I)
HOST_PULL_ATTRS = ("item", "asscalar")
SANCTIONED_FETCH = "host_fetch"     # guardrails.fused.host_fetch


@register
class HostSyncedFinitenessCheck(Rule):
    code = "G9"
    name = "host-synced-finiteness-check"
    doc = ("Per-step host-synced finiteness check in training-loop "
           "modules: np.isfinite/np.isnan over step values, or "
           "float()/bool()/.item()/.asscalar() on gradient/loss/norm "
           "values (including values derived from a device-side "
           "isfinite). Each one is a device->host round trip per step "
           "— and on multi-host, a per-rank early return out of a "
           "collective. Use the fused in-program guard "
           "(mxnet_tpu.guardrails.fused.guard_stats) and read its step "
           "outputs through guardrails.fused.host_fetch. Scope: "
           "training-loop library modules (gluon trainer/utils, "
           "module/, parallel/, contrib/amp, optimizer/).")

    def _in_scope(self, ctx) -> bool:
        if TRAINING_PATH_RE.search("/" + ctx.path):
            return True
        return bool(_SCOPE_TRAINING_RE.search("\n".join(ctx.lines[:5])))

    @staticmethod
    def _sanctioned(node) -> bool:
        """True when the expression routes through the one sanctioned
        chokepoint (guardrails.fused.host_fetch) — the fetch is the
        API, not an ad-hoc sync."""
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call) \
                    and isinstance(sub.func, ast.Attribute) \
                    and sub.func.attr == SANCTIONED_FETCH:
                return True
        return False

    @classmethod
    def _assign_pairs(cls, targets, value):
        """Decompose an assignment into (targets, value) taint units:
        tuple unpacking propagates element-wise so in
        `flag, n = jnp.isfinite(g).all(), step` only `flag` is dirtied
        — tainting `n` too would flag a later benign `int(n)`.
        Shape-mismatched or starred unpacking falls back to the whole
        value (conservative)."""
        for t in targets:
            if isinstance(t, (ast.Tuple, ast.List)) \
                    and isinstance(value, (ast.Tuple, ast.List)) \
                    and len(t.elts) == len(value.elts) \
                    and not any(isinstance(e, ast.Starred)
                                for e in t.elts):
                for te, ve in zip(t.elts, value.elts):
                    yield from cls._assign_pairs([te], ve)
            else:
                yield [t], value

    @staticmethod
    def _scope_map(tree):
        """node → innermost enclosing function (None = module scope).
        Name-set analysis must be per-scope: a `norm` blessed inside one
        function must not exempt a different function's `norm`."""
        scopes = {}

        def visit(node, scope):
            for child in ast.iter_child_nodes(node):
                scopes[child] = scope
                visit(child,
                      child if isinstance(child, (ast.FunctionDef,
                                                  ast.AsyncFunctionDef))
                      else scope)

        visit(tree, None)
        return scopes

    def _tainted_names(self, ctx, scopes):
        """Per-scope name sets from a fixpoint over each scope's
        assignments — returns ``{scope: (tainted, blessed)}``:

        - **tainted** — assigned (transitively) from expressions
          containing a finiteness call: `ok = jnp.all(jnp.isfinite(g))`
          taints `ok`, `flag = ok` taints `flag`;
        - **blessed** — assigned from expressions routing through the
          sanctioned chokepoint: `norm = fused.host_fetch(norm_dev)[0]`
          is already a host value, so a later `np.isfinite(norm)` /
          `float(norm)` costs no device sync and must NOT be flagged
          (it is the exact pattern this rule recommends). Blessing wins
          over taint — `ok, gn = fused.host_fetch(finite, gnorm)`
          blesses `ok` even though `finite` is tainted."""
        per_scope: dict = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign):
                pairs = self._assign_pairs(node.targets, node.value)
            elif isinstance(node, (ast.AnnAssign, ast.NamedExpr)) \
                    and node.value is not None:
                pairs = self._assign_pairs([node.target], node.value)
            else:
                continue
            per_scope.setdefault(scopes.get(node), []).extend(pairs)
        out = {}
        for scope, assigns in per_scope.items():
            taint: set[str] = set()
            blessed: set[str] = set()
            changed = True
            while changed:
                changed = False
                for targets, value in assigns:
                    if self._sanctioned(value):
                        dest = blessed
                    else:
                        dirty = False
                        for sub in ast.walk(value):
                            if isinstance(sub, ast.Call) \
                                    and ctx.resolve_call(sub) \
                                    in DEVICE_FINITENESS:
                                dirty = True
                            elif isinstance(sub, ast.Name) \
                                    and sub.id in taint \
                                    and sub.id not in blessed:
                                dirty = True
                        if not dirty:
                            continue
                        dest = taint
                    for t in targets:
                        for sub in ast.walk(t):
                            if isinstance(sub, ast.Name) \
                                    and sub.id not in dest:
                                dest.add(sub.id)
                                changed = True
            out[scope] = (taint, blessed)
        return out

    @staticmethod
    def _matches_guard_value(node, taint) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and (
                    GUARD_VALUE_RE.search(sub.id) or sub.id in taint):
                return True
            if isinstance(sub, ast.Attribute) \
                    and GUARD_VALUE_RE.search(sub.attr):
                return True
        return False

    @staticmethod
    def _all_names_blessed(node, blessed) -> bool:
        """Every Name in the expression is a host_fetch result (and
        there is at least one): checking/converting it is host-local."""
        names = [s.id for s in ast.walk(node)
                 if isinstance(s, ast.Name)]
        return bool(names) and all(n in blessed for n in names)

    def check(self, ctx):
        if not ctx.is_library() or not self._in_scope(ctx):
            return
        scopes = self._scope_map(ctx.tree)
        per_scope = self._tainted_names(ctx, scopes)
        empty: tuple = (frozenset(), frozenset())
        # _sanctioned walks the whole call subtree — run it only on
        # candidates that already matched the cheap name/taint checks,
        # not on every Call in the file
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            taint, blessed = per_scope.get(scopes.get(node), empty)
            name = ctx.resolve_call(node)
            if name in HOST_FINITENESS:
                if self._sanctioned(node) or (
                        node.args and all(self._all_names_blessed(a,
                                                                  blessed)
                                          for a in node.args)):
                    continue
                yield self.finding(
                    ctx, node.lineno,
                    f"host {name}() in a training-loop module — a "
                    "device->host sync per step; fold the check into "
                    "the compiled step (guardrails.fused.guard_stats)")
                continue
            func = node.func
            if isinstance(func, ast.Name) and func.id in ("float", "bool",
                                                          "int") \
                    and len(node.args) == 1 \
                    and self._matches_guard_value(node.args[0], taint) \
                    and not self._all_names_blessed(node.args[0],
                                                    blessed) \
                    and not self._sanctioned(node):
                yield self.finding(
                    ctx, node.lineno,
                    f"{func.id}() host-syncs a per-step training value "
                    "— return it from the compiled step and read it via "
                    "guardrails.fused.host_fetch")
            elif isinstance(func, ast.Attribute) \
                    and func.attr in HOST_PULL_ATTRS \
                    and self._matches_guard_value(func.value, taint) \
                    and not self._all_names_blessed(func.value, blessed) \
                    and not self._sanctioned(node):
                yield self.finding(
                    ctx, node.lineno,
                    f".{func.attr}() host-syncs a per-step training "
                    "value — use the fused guard's step outputs "
                    "(guardrails.fused.host_fetch)")


@register
class SilentDeviceExceptionSwallow(Rule):
    code = "G6"
    name = "silent-device-exception-swallow"
    doc = ("`except Exception: pass` (or bare) around backend-touching "
           "code. A dead device path that vanishes silently is "
           "undebuggable — journal it via diagnostics.journal (the "
           "engine.waitall lesson) or narrow the catch.")

    BROAD = {"Exception", "BaseException"}

    def _touches_device(self, ctx, try_node):
        # only the PROTECTED code counts (body + else) — a jax call in a
        # sibling handler doesn't make an unrelated handler a G6
        for top in list(try_node.body) + list(try_node.orelse):
            for node in ast.walk(top):
                if isinstance(node, ast.Call):
                    name = ctx.resolve_call(node) or ""
                    if name.startswith("jax."):
                        return True
                    func = node.func
                    if isinstance(func, ast.Attribute) and func.attr in (
                            "block_until_ready", "device_put", "devices"):
                        return True
        return False

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Try):
                continue
            for handler in node.handlers:
                t = handler.type
                broad = t is None or \
                    (isinstance(t, ast.Name) and t.id in self.BROAD) or \
                    (isinstance(t, ast.Tuple)
                     and any(isinstance(e, ast.Name) and e.id in self.BROAD
                             for e in t.elts))
                swallows = len(handler.body) == 1 and (
                    isinstance(handler.body[0], ast.Pass)
                    or (isinstance(handler.body[0], ast.Expr)
                        and isinstance(handler.body[0].value, ast.Constant)))
                if broad and swallows and self._touches_device(ctx, node):
                    yield self.finding(
                        ctx, handler.lineno,
                        "device/runtime failure swallowed silently — "
                        "journal it (diagnostics.journal) or narrow the "
                        "except")


@register
class DirectPallasCall(Rule):
    code = "G10"
    name = "direct-pallas-call"
    severity = "error"
    doc = ("Direct `pl.pallas_call` in library code outside "
           "mxnet_tpu/pallas/. A raw kernel bypasses the registry's "
           "parity gate, backend/shape fallback, and journaled "
           "provenance (docs/pallas.md) — an unverified kernel can then "
           "silently change numerics or run on a backend it was never "
           "tested on. Register it (pallas.register_kernel) and route "
           "callers through pallas.dispatch. "
           "Scope: mxnet_tpu/ library code; mxnet_tpu/pallas/ is the "
           "sanctioned home.")

    PALLAS_CALLS = {"jax.experimental.pallas.pallas_call"}

    def check(self, ctx):
        if not ctx.is_library() or ctx.path.startswith("mxnet_tpu/pallas/"):
            return
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) and \
                    ctx.resolve_call(node) in self.PALLAS_CALLS:
                yield self.finding(
                    ctx, node.lineno,
                    "raw pl.pallas_call in library code bypasses the "
                    "kernel tier's parity/fallback guard — register the "
                    "kernel in mxnet_tpu/pallas/ and dispatch through "
                    "the registry")


@register
class WallclockDuration(Rule):
    code = "G11"
    name = "wallclock-duration"
    severity = "error"
    doc = ("`time.time()` used in duration arithmetic in library code. "
           "The wall clock steps under NTP adjustment, so a "
           "`time.time() - t0` duration can go NEGATIVE (or jump hours) "
           "mid-run — poisoning journal durations, latency summaries "
           "and Time-cost logs. Durations must come from "
           "`time.monotonic()` / `time.perf_counter()`; wall clock is "
           "only for timestamps (a bare `time.time()` with no "
           "subtraction is fine). Per-function scope: a name assigned "
           "from time.time() taints subtractions in the same scope. "
           "Scope: mxnet_tpu/ library code.")

    WALL = "time.time"

    def _scopes(self, tree):
        """(scope_body_nodes) per function/module, nested functions
        excluded from their parent (their taint is their own)."""
        scopes = [tree]
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                scopes.append(node)
        return scopes

    def _walk_scope(self, scope):
        """Nodes belonging to this scope only (stop at nested function
        boundaries)."""
        stack = list(ast.iter_child_nodes(scope))
        while stack:
            node = stack.pop()
            yield node
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.Lambda)):
                stack.extend(ast.iter_child_nodes(node))

    def _is_wall_call(self, ctx, node):
        return isinstance(node, ast.Call) and \
            ctx.resolve_call(node) == self.WALL

    def check(self, ctx):
        if not ctx.is_library():
            return
        for scope in self._scopes(ctx.tree):
            # line-ordered taint flow: an assignment from time.time()
            # taints its name, a later reassignment from anything else
            # clears it — so rebinding a variable to monotonic doesn't
            # keep a stale error on correct code
            events = []     # (lineno, order, kind, payload)
            for node in self._walk_scope(scope):
                if isinstance(node, ast.Assign):
                    wall = self._is_wall_call(ctx, node.value)
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            events.append((node.lineno, 1,
                                           "taint" if wall else "clear",
                                           tgt.id))
                elif isinstance(node, ast.BinOp) and \
                        isinstance(node.op, ast.Sub):
                    events.append((node.lineno, 0, "sub", node))
            tainted = set()
            for _ln, _order, kind, payload in sorted(
                    events, key=lambda e: (e[0], e[1])):
                if kind == "taint":
                    tainted.add(payload)
                    continue
                if kind == "clear":
                    tainted.discard(payload)
                    continue
                node = payload
                for side in (node.left, node.right):
                    if self._is_wall_call(ctx, side) or \
                            (isinstance(side, ast.Name)
                             and side.id in tainted):
                        yield self.finding(
                            ctx, node.lineno,
                            "duration computed from time.time() — the "
                            "wall clock steps under NTP; use "
                            "time.monotonic()/perf_counter() for "
                            "durations (time.time() is for timestamps "
                            "only)")
                        break


@register
class UnboundedPollLoop(Rule):
    code = "G13"
    name = "unbounded-poll-loop"
    severity = "error"
    doc = ("`while True:` poll loop containing time.sleep() with no "
           "deadline/budget check inside the loop, in library code. "
           "The router/breaker/drain wait-loop hazard class: the "
           "condition being polled for can simply never come (dead "
           "replica, wedged worker, stuck flag) and the thread spins "
           "for the driver's whole window — an information-free rc:124, "
           "in-process. Bound every poll loop: compare a monotonic "
           "clock against a deadline inside the loop "
           "(elastic.membership.Cohort.barrier is the model) or "
           "restructure onto a bounded condition / Event.wait(timeout=). "
           "Scope: mxnet_tpu/ library code.")

    CLOCKS = {"time.monotonic", "time.perf_counter", "time.time",
              "time.monotonic_ns", "time.perf_counter_ns", "time.time_ns"}
    SLEEP = "time.sleep"

    @staticmethod
    def _const_true(test) -> bool:
        return isinstance(test, ast.Constant) and bool(test.value)

    def _scopes(self, tree):
        scopes = [tree]
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                scopes.append(node)
        return scopes

    def _walk_scope(self, scope):
        stack = list(ast.iter_child_nodes(scope))
        while stack:
            node = stack.pop()
            yield node
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.Lambda)):
                stack.extend(ast.iter_child_nodes(node))

    def _is_clock_call(self, ctx, node) -> bool:
        return isinstance(node, ast.Call) and \
            ctx.resolve_call(node) in self.CLOCKS

    def _clock_tainted(self, ctx, scope) -> set:
        """Names assigned (anywhere in this scope) from an expression
        containing a monotonic/wall clock call — deadline variables
        (`deadline = time.monotonic() + x`, `t0 = time.monotonic()`)."""
        tainted = set()
        for node in self._walk_scope(scope):
            if isinstance(node, ast.Assign) and any(
                    self._is_clock_call(ctx, s)
                    for s in ast.walk(node.value)):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        tainted.add(tgt.id)
        return tainted

    def _loop_bounded(self, ctx, loop, tainted) -> bool:
        """A loop is budget-bounded when some Compare inside it reads a
        clock (directly or through a deadline name) — the
        `if time.monotonic() - t0 > deadline: raise` shape."""
        for node in self._loop_body(loop):
            if not isinstance(node, ast.Compare):
                continue
            for sub in ast.walk(node):
                if self._is_clock_call(ctx, sub):
                    return True
                if isinstance(sub, ast.Name) and sub.id in tainted:
                    return True
        return False

    def _loop_body(self, loop):
        """Nodes inside the loop, stopping at nested functions (their
        sleeps and their budgets are their own)."""
        stack = list(loop.body) + list(loop.orelse)
        while stack:
            node = stack.pop()
            yield node
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.Lambda)):
                stack.extend(ast.iter_child_nodes(node))

    def check(self, ctx):
        if not ctx.is_library():
            return
        for scope in self._scopes(ctx.tree):
            tainted = None          # computed lazily per scope
            for node in self._walk_scope(scope):
                if not (isinstance(node, ast.While)
                        and self._const_true(node.test)):
                    continue
                has_sleep = any(
                    isinstance(sub, ast.Call)
                    and ctx.resolve_call(sub) == self.SLEEP
                    for sub in self._loop_body(node))
                if not has_sleep:
                    continue
                if tainted is None:
                    tainted = self._clock_tainted(ctx, scope)
                if self._loop_bounded(ctx, node, tainted):
                    continue
                yield self.finding(
                    ctx, node.lineno,
                    "unbounded poll loop: while True + time.sleep with "
                    "no deadline/budget check — a condition that never "
                    "comes wedges this thread forever; compare a "
                    "monotonic clock against a deadline inside the loop")


@register
class RankDependentCollectiveEntry(Rule):
    code = "G12"
    name = "rank-dependent-collective-entry"
    severity = "error"
    doc = ("Host-level collective entered under a rank-local condition. "
           "A call like multihost_utils.sync_global_devices / "
           "process_allgather / broadcast_one_to_all guarded by "
           "`if jax.process_index() == 0:` (or a name derived from it) "
           "means SOME ranks enter the collective and others don't — "
           "the guarded ranks wait forever for peers that never arrive. "
           "This is the deadlock class elastic training cannot tolerate "
           "(docs/elastic.md): the PR-5 lesson that a rank-dependent "
           "decision to enter a collective is itself a deadlock. Make "
           "entry unconditional and rank-uniform; decide once on one "
           "rank and share the verdict through a broadcast "
           "(parallel._ckpt group bcast_int / elastic.broadcast_json). "
           "World-SIZE conditionals (`if jax.process_count() == 1:`) "
           "are rank-uniform and fine. Scope: mxnet_tpu/ library code.")

    COLLECTIVES = {
        "jax.experimental.multihost_utils.sync_global_devices",
        "jax.experimental.multihost_utils.process_allgather",
        "jax.experimental.multihost_utils.broadcast_one_to_all",
        "jax.experimental.multihost_utils.assert_equal",
    }
    RANK_SOURCES = {"jax.process_index"}

    def _scopes(self, tree):
        scopes = [tree]
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                scopes.append(node)
        return scopes

    def _scope_children(self, scope):
        """Direct body of this scope, stopping at nested functions
        (each nested scope carries its own taint and guards)."""
        stack = list(ast.iter_child_nodes(scope))
        while stack:
            node = stack.pop()
            yield node
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.Lambda)):
                stack.extend(ast.iter_child_nodes(node))

    def _is_rank_call(self, ctx, node):
        return isinstance(node, ast.Call) and \
            ctx.resolve_call(node) in self.RANK_SOURCES

    def _mentions_rank(self, ctx, node, tainted):
        for sub in ast.walk(node):
            if self._is_rank_call(ctx, sub):
                return True
            if isinstance(sub, ast.Name) and sub.id in tainted:
                return True
        return False

    def check(self, ctx):
        if not ctx.is_library():
            return
        for scope in self._scopes(ctx.tree):
            # pass 1: names assigned from expressions containing a
            # process_index() call ("rank = jax.process_index()",
            # "is_main = jax.process_index() == 0")
            tainted = set()
            for node in self._scope_children(scope):
                if isinstance(node, ast.Assign) and any(
                        self._is_rank_call(ctx, s)
                        for s in ast.walk(node.value)):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            tainted.add(tgt.id)
            # pass 2: descend tracking whether we are under a
            # rank-dependent condition; flag collectives there
            yield from self._descend(ctx, scope, tainted, False)

    def _descend(self, ctx, node, tainted, guarded):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue            # its own scope handles it
            if isinstance(child, (ast.If, ast.While)):
                rank_test = self._mentions_rank(ctx, child.test, tainted)
                yield from self._descend(ctx, child.test, tainted,
                                         guarded)
                for part in child.body + child.orelse:
                    yield from self._walk_stmt(ctx, part, tainted,
                                               guarded or rank_test)
                continue
            if isinstance(child, ast.IfExp):
                rank_test = self._mentions_rank(ctx, child.test, tainted)
                yield from self._descend(ctx, child.test, tainted,
                                         guarded)
                for part in (child.body, child.orelse):
                    yield from self._walk_stmt(ctx, part, tainted,
                                               guarded or rank_test)
                continue
            if isinstance(child, ast.BoolOp):
                # short-circuit entry: `rank == 0 and allgather(...)`
                seen_rank = False
                for operand in child.values:
                    yield from self._walk_stmt(ctx, operand, tainted,
                                               guarded or seen_rank)
                    seen_rank = seen_rank or \
                        self._mentions_rank(ctx, operand, tainted)
                continue
            if guarded and isinstance(child, ast.Call) and \
                    ctx.resolve_call(child) in self.COLLECTIVES:
                yield self.finding(
                    ctx, child.lineno,
                    "collective entered under a rank-dependent "
                    "condition — guarded ranks wait forever for peers "
                    "that never arrive; make entry unconditional and "
                    "share the one-rank decision via a broadcast "
                    "(docs/elastic.md)")
                # still descend: nested collectives get their own lines
            yield from self._descend(ctx, child, tainted, guarded)

    def _walk_stmt(self, ctx, node, tainted, guarded):
        """Flag a collective at ``node`` itself, then descend."""
        if guarded and isinstance(node, ast.Call) and \
                ctx.resolve_call(node) in self.COLLECTIVES:
            yield self.finding(
                ctx, node.lineno,
                "collective entered under a rank-dependent "
                "condition — guarded ranks wait forever for peers "
                "that never arrive; make entry unconditional and "
                "share the one-rank decision via a broadcast "
                "(docs/elastic.md)")
        yield from self._descend(ctx, node, tainted, guarded)


@register
class UnboundedKeyedRegistry(Rule):
    code = "G14"
    name = "unbounded-keyed-registry"
    severity = "error"
    doc = ("Dict/set attribute in library-code classes indexed by "
           "externally-supplied keys — the key expression names a "
           "request-shaped identifier (tenant, request/req id, step, "
           "path/file name, session/client/user/token, trace/span id) "
           "and the insert sits in a PUBLIC method — with inserts but "
           "no eviction/cap on any path in the class. A long-lived "
           "server then grows host memory one entry per novel key "
           "forever: the ParamStore bad-step-set hazard class "
           "(churning commit root), the per-tenant counter-table "
           "class, the Prometheus label-cardinality class. Bound it: "
           "LRU-cap with popitem/pop, prune against `len(...)` "
           "compares, or reset the container on a lifecycle path. "
           "Containers whose inserts only happen in underscore-private "
           "methods are out of scope (the caller owns the key space), "
           "as are key names outside the vocabulary (operator-bounded "
           "registries). Scope: mxnet_tpu/ library classes.")

    # request-shaped identifier vocabulary: a key built from one of
    # these tokens is presumed externally supplied (request fields,
    # tenant ids, file/step names) rather than operator-configured
    VOCAB = {"tenant", "tenants", "step", "steps", "request", "req",
             "path", "paths", "file", "files", "fname", "filename",
             "client", "session", "user", "token", "trace", "span"}

    CONTAINERS = {"dict", "set", "collections.OrderedDict",
                  "collections.defaultdict", "OrderedDict",
                  "defaultdict"}
    EVICTORS = {"pop", "popitem", "clear", "discard", "remove"}

    @staticmethod
    def _self_attr(node):
        """'x' for a `self.x` attribute expression, else None."""
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and \
                node.value.id == "self":
            return node.attr
        return None

    def _container_attrs(self, ctx, cls) -> set:
        """Attrs assigned a fresh dict/set/OrderedDict/defaultdict
        anywhere in the class (the `self._seen = {}` shape)."""
        out = set()
        for node in ast.walk(cls):
            if not isinstance(node, ast.Assign):
                continue
            v = node.value
            fresh = isinstance(v, (ast.Dict, ast.Set)) or (
                isinstance(v, ast.Call)
                and ctx.resolve_call(v) in self.CONTAINERS)
            if not fresh:
                continue
            for tgt in node.targets:
                attr = self._self_attr(tgt)
                if attr:
                    out.add(attr)
        return out

    def _evicted_attrs(self, ctx, cls, attrs) -> set:
        """Attrs with eviction/cap evidence on ANY path: an evictor
        method call, `del self.x[...]`, a `len(self.x)` inside a
        Compare (the `while len(...) > cap: popitem()` shape), or a
        reset-reassignment outside __init__."""
        out = set()
        init = next((n for n in cls.body
                     if isinstance(n, ast.FunctionDef)
                     and n.name == "__init__"), None)
        init_nodes = set(ast.walk(init)) if init is not None else set()
        for node in ast.walk(cls):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in self.EVICTORS:
                attr = self._self_attr(node.func.value)
                if attr in attrs:
                    out.add(attr)
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    if isinstance(t, ast.Subscript):
                        attr = self._self_attr(t.value)
                        if attr in attrs:
                            out.add(attr)
            elif isinstance(node, ast.Compare):
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Call) and \
                            isinstance(sub.func, ast.Name) and \
                            sub.func.id == "len" and sub.args:
                        attr = self._self_attr(sub.args[0])
                        if attr in attrs:
                            out.add(attr)
            elif isinstance(node, ast.Assign) and node not in init_nodes:
                for tgt in node.targets:
                    attr = self._self_attr(tgt)
                    if attr in attrs:
                        out.add(attr)       # lifecycle reset path
        return out

    def _key_is_external(self, key_expr) -> bool:
        """True when a Name in the key expression carries a
        vocabulary token (`request_id`, `step`, `fname`, ...)."""
        for sub in ast.walk(key_expr):
            if isinstance(sub, ast.Name):
                tokens = sub.id.lower().split("_")
                if any(t in self.VOCAB for t in tokens):
                    return True
        return False

    def _inserts(self, method):
        """(line, attr, key_expr) for each insert in one method:
        `self.x[k] = v`, `self.x.add(k)`, `self.x.setdefault(k, ...)`."""
        for node in ast.walk(method):
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for tgt in targets:
                    if isinstance(tgt, ast.Subscript):
                        attr = self._self_attr(tgt.value)
                        if attr:
                            yield node.lineno, attr, tgt.slice
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in ("add", "setdefault") and node.args:
                attr = self._self_attr(node.func.value)
                if attr:
                    yield node.lineno, attr, node.args[0]

    def check(self, ctx):
        if not ctx.is_library():
            return
        for cls in ast.walk(ctx.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            attrs = self._container_attrs(ctx, cls)
            if not attrs:
                continue
            evicted = self._evicted_attrs(ctx, cls, attrs)
            for method in cls.body:
                if not isinstance(method, ast.FunctionDef):
                    continue
                if method.name.startswith("_"):
                    continue           # private: caller owns the keys
                for line, attr, key_expr in self._inserts(method):
                    if attr not in attrs or attr in evicted:
                        continue
                    if not self._key_is_external(key_expr):
                        continue
                    yield self.finding(
                        ctx, line,
                        f"unbounded keyed registry: `self.{attr}` is "
                        "inserted with an externally-supplied key in a "
                        "public method but nothing in the class ever "
                        "evicts or caps it — a long-lived server grows "
                        "one entry per novel key forever; add an LRU "
                        "cap/pruning (ParamStore's bad-step LRU is the "
                        "model)")


@register
class UnvalidatedCacheDeserialize(Rule):
    code = "G21"
    name = "unvalidated-cache-deserialize"
    severity = "error"
    doc = ("Deserializing a persisted executable/pickle without a "
           "version-envelope or CRC check on the read path: a function "
           "that both reads file bytes AND hands them to an unguarded "
           "deserializer (pickle.load/loads, marshal, an Unpickler, "
           "jax.export.deserialize, serialize_executable."
           "deserialize_and_load) will happily load a torn write, a "
           "bit-flipped sector, or a stale-toolchain artifact as live "
           "state — the failure is wrong NUMERICS or a segfaulting "
           "executable, not a clean error.  The AOT compile cache "
           "(serving/aotcache.py) is the model read path: magic + "
           "bounds + CRC32 + a jax/jaxlib/backend envelope are all "
           "verified (serving/aot_report.read_entry) before any byte "
           "reaches the deserializer.  Evidence that satisfies the "
           "rule, anywhere in the same function: a zlib/binascii CRC "
           "or hashlib digest call, or identifiers carrying "
           "crc/checksum/magic/envelope/sha tokens (a delegated "
           "validate helper names itself).  Deserializing bytes the "
           "caller passed in (no file read in the function) is out of "
           "scope — the reader that pulled them off disk owns the "
           "check.  Scope: mxnet_tpu/ library code.")

    # unguarded deserializers of attacker/corruption-visible bytes
    # (pickle.Unpickler itself is NOT here: the constructor only wraps
    # the stream — the .load() call is the deserialize, matched below)
    DESERIALIZERS = {"pickle.load", "pickle.loads",
                     "marshal.load", "marshal.loads",
                     "jax.export.deserialize"}
    DESER_SUFFIX = ("deserialize_and_load",)
    # file-read shapes: open() in the function, or .read()/.read_bytes()
    READ_ATTRS = {"read", "read_bytes"}
    # validation evidence: digest calls or validation-named identifiers
    EVIDENCE_CALLS = {"zlib.crc32", "binascii.crc32"}
    EVIDENCE_PREFIX = ("hashlib.",)
    EVIDENCE_TOKENS = {"crc", "crc32", "checksum", "magic", "envelope",
                       "sha1", "sha256", "digest"}

    @staticmethod
    def _scope_nodes(scope):
        """Nodes of this function only — nested defs/lambdas are their
        own read paths and carry their own evidence."""
        stack = list(ast.iter_child_nodes(scope))
        while stack:
            node = stack.pop()
            yield node
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.Lambda)):
                stack.extend(ast.iter_child_nodes(node))

    def _is_deserializer(self, ctx, call) -> bool:
        name = ctx.resolve_call(call)
        if name:
            if name in self.DESERIALIZERS:
                return True
            if name.endswith(self.DESER_SUFFIX):
                return True
        # method spelling: anything.load() on an Unpickler instance is
        # out of reach without types; catch the documented pattern
        # Unpickler(...).load() in one expression
        f = call.func
        if isinstance(f, ast.Attribute) and f.attr == "load" and \
                isinstance(f.value, ast.Call):
            inner = ctx.resolve_call(f.value)
            if inner and inner.endswith("Unpickler"):
                return True
        return False

    def _reads_file(self, ctx, fn) -> bool:
        for node in self._scope_nodes(fn):
            if not isinstance(node, ast.Call):
                continue
            name = ctx.resolve_call(node)
            if name == "open":
                return True
            f = node.func
            if isinstance(f, ast.Attribute) and \
                    f.attr in self.READ_ATTRS:
                return True
        return False

    def _has_evidence(self, ctx, fn) -> bool:
        for node in self._scope_nodes(fn):
            if isinstance(node, ast.Call):
                name = ctx.resolve_call(node)
                if name and (name in self.EVIDENCE_CALLS or
                             name.startswith(self.EVIDENCE_PREFIX)):
                    return True
            ident = None
            if isinstance(node, ast.Name):
                ident = node.id
            elif isinstance(node, ast.Attribute):
                ident = node.attr
            if ident:
                tokens = ident.lower().split("_")
                if any(t in self.EVIDENCE_TOKENS for t in tokens):
                    return True
        return False

    def check(self, ctx):
        if not ctx.is_library():
            return
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            deser_lines = [
                n.lineno for n in self._scope_nodes(fn)
                if isinstance(n, ast.Call)
                and self._is_deserializer(ctx, n)]
            if not deser_lines:
                continue
            if not self._reads_file(ctx, fn):
                continue            # caller-supplied bytes: reader owns it
            if self._has_evidence(ctx, fn):
                continue
            for line in deser_lines:
                yield self.finding(
                    ctx, line,
                    "unvalidated cache deserialize: this function reads "
                    "persisted bytes and hands them to a deserializer "
                    "with no CRC/version-envelope check in sight — a "
                    "torn or stale entry becomes wrong numerics instead "
                    "of a clean fallback; validate first "
                    "(serving/aot_report.read_entry is the model) or "
                    "route through a checked reader")

"""graftlint CLI — ``python -m mxnet_tpu.analysis``.

Exit codes: 0 = no new findings (baselined debt allowed), 1 = new
findings (or any finding with ``--no-baseline``), 2 = usage error.
"""
from __future__ import annotations

import argparse
import os
import sys

from . import baseline as _baseline
from . import core, emitters

__all__ = ["main", "repo_root"]


def repo_root() -> str:
    """The repo checkout this package lives in (two levels above the
    package directory) — the anchor for default paths and the baseline."""
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def _build_parser():
    p = argparse.ArgumentParser(
        prog="python -m mxnet_tpu.analysis",
        description="graftlint: JAX-hazard + generic static analysis "
                    "(see docs/static_analysis.md)")
    p.add_argument("paths", nargs="*",
                   help="files/dirs to lint, repo-relative "
                        "(default: the whole repo surface)")
    p.add_argument("--format", choices=("text", "json", "sarif"),
                   default="text", help="output format (default: text)")
    p.add_argument("--baseline", default=None, metavar="FILE",
                   help="baseline file (default: ci/lint_baseline.json)")
    p.add_argument("--no-baseline", action="store_true",
                   help="report every finding as new (audit mode)")
    p.add_argument("--write-baseline", action="store_true",
                   help="regenerate the baseline from current findings "
                        "(preserves surviving justifications) and exit 0")
    p.add_argument("--rules", default=None, metavar="CODES",
                   help="comma-separated rule codes to run "
                        "(e.g. W1,W2,G1); default: all")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    p.add_argument("--show-baselined", action="store_true",
                   help="also print baselined findings (text format)")
    return p


def main(argv=None) -> int:
    args = _build_parser().parse_args(argv)
    root = repo_root()

    registry = core.load_rules()
    if args.list_rules:
        for rule in registry.values():
            print(f"{rule.code:4} {rule.severity:8} {rule.name}")
            print(f"     {rule.doc}")
        return 0

    rules = list(registry.values())
    if args.rules:
        wanted = [c.strip() for c in args.rules.split(",") if c.strip()]
        unknown = [c for c in wanted if c not in registry]
        if unknown:
            print(f"unknown rule code(s): {', '.join(unknown)}",
                  file=sys.stderr)
            return 2
        rules = [registry[c] for c in wanted]

    if args.write_baseline and (args.paths or args.rules) \
            and not args.baseline:
        # a narrowed scan regenerating the COMMITTED baseline would
        # silently drop every out-of-scope entry
        print("--write-baseline with paths/--rules would clobber the "
              "default baseline with a partial scan; pass an explicit "
              "--baseline FILE or run unfiltered", file=sys.stderr)
        return 2

    if args.paths:
        # EVERY named path must resolve — a typo'd path among valid
        # ones must not read as a clean pass
        miss = core.missing_paths(args.paths, root=root)
        if miss:
            print(f"no .py files found under: {' '.join(miss)}",
                  file=sys.stderr)
            return 2
    findings, n_files = core.run(args.paths or None, rules=rules, root=root)
    if n_files == 0:
        # the default scan finding nothing means repo_root() is not a
        # checkout (e.g. an installed wheel) — not a clean pass
        print(f"no .py files found under {root} — not a repo checkout?",
              file=sys.stderr)
        return 2

    # a relative --baseline resolves against the repo root, like the scan
    # paths and the default baseline — never against the process cwd
    bl_path = args.baseline or _baseline.DEFAULT_BASELINE
    if not os.path.isabs(bl_path):
        bl_path = os.path.join(root, bl_path)
    if args.write_baseline:
        entries = _baseline.write_baseline(bl_path, findings)
        print(f"graftlint: wrote {len(entries)} entries to "
              f"{os.path.relpath(bl_path, root)}")
        return 0

    try:
        entries = [] if args.no_baseline else \
            _baseline.load_baseline(bl_path)
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2
    new, baselined = _baseline.partition(findings, entries)

    if args.format == "text":
        emitters.emit_text(new, baselined, n_files, sys.stdout,
                           verbose_baselined=args.show_baselined)
    elif args.format == "json":
        emitters.dump_json(emitters.to_json(new, baselined, n_files),
                           sys.stdout)
    else:
        emitters.dump_json(emitters.to_sarif(new, baselined,
                                             list(registry.values())),
                           sys.stdout)
    return 1 if new else 0

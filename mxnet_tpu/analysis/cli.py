"""graftlint CLI — ``python -m mxnet_tpu.analysis``.

Exit codes: 0 = no new findings (baselined debt allowed), 1 = new
findings (or any finding with ``--no-baseline``), 2 = usage error.
"""
from __future__ import annotations

import argparse
import os
import subprocess
import sys

from . import baseline as _baseline
from . import callgraph as _callgraph
from . import core, emitters
from . import summaries as _summaries

__all__ = ["main", "repo_root", "changed_only_paths"]


def repo_root() -> str:
    """The repo checkout this package lives in (two levels above the
    package directory) — the anchor for default paths and the baseline."""
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def _build_parser():
    p = argparse.ArgumentParser(
        prog="python -m mxnet_tpu.analysis",
        description="graftlint: JAX-hazard + generic static analysis "
                    "(see docs/static_analysis.md)")
    p.add_argument("paths", nargs="*",
                   help="files/dirs to lint, repo-relative "
                        "(default: the whole repo surface)")
    p.add_argument("--format", choices=("text", "json", "sarif"),
                   default="text", help="output format (default: text)")
    p.add_argument("--baseline", default=None, metavar="FILE",
                   help="baseline file (default: ci/lint_baseline.json)")
    p.add_argument("--no-baseline", action="store_true",
                   help="report every finding as new (audit mode)")
    p.add_argument("--write-baseline", action="store_true",
                   help="regenerate the baseline from current findings "
                        "(preserves surviving justifications) and exit 0")
    p.add_argument("--rules", default=None, metavar="CODES",
                   help="comma-separated rule codes to run "
                        "(e.g. W1,W2,G1); default: all")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    p.add_argument("--show-baselined", action="store_true",
                   help="also print baselined findings (text format)")
    p.add_argument("--jobs", type=int, default=1, metavar="N",
                   help="lint files on N processes (0 = one per CPU, "
                        "capped; default 1). Fork-based; platforms "
                        "without fork fall back to serial")
    p.add_argument("--changed-only", default=None, metavar="REF",
                   help="lint only files changed vs this git ref (plus "
                        "untracked), AND their reverse import-graph "
                        "dependents — so interprocedural findings don't "
                        "go stale. The pre-commit hook's mode")
    p.add_argument("--no-cache", action="store_true",
                   help="skip the per-file summary cache "
                        "(ci/lint_summary_cache.json)")
    return p


def _git_lines(root, args_):
    out = subprocess.run(["git"] + args_, cwd=root, capture_output=True,
                         text=True, timeout=30)
    if out.returncode != 0:
        raise RuntimeError(out.stderr.strip() or "git failed")
    return [ln for ln in out.stdout.splitlines() if ln.strip()]


def _depends_on(imports: set, mod: str) -> bool:
    """Does a file with these imported modules depend on ``mod``?
    Exact import, an import of any submodule of it, or the
    ``from <parent-pkg> import <leaf>`` shape (one level)."""
    for i in imports:
        if i == mod or i.startswith(mod + "."):
            return True
        if mod.startswith(i + ".") and mod.count(".") == i.count(".") + 1:
            return True
    return False


def changed_only_paths(root, ref, surface=None) -> list:
    """Repo-relative .py paths to lint for ``--changed-only REF``: the
    files changed vs the ref (plus untracked), intersected with the
    default scan surface (fixture dirs stay excluded), plus the
    TRANSITIVE reverse import-graph dependents — a caller of an edited
    helper can gain or lose an interprocedural finding without itself
    changing, so dependents must re-lint or G15-G19 results go stale.
    Deeper-than-one-level package re-exports are a documented limit
    (docs/static_analysis.md)."""
    changed = {c.replace(os.sep, "/")
               for c in _git_lines(root, ["diff", "--name-only", ref,
                                          "--"])}
    changed |= {c.replace(os.sep, "/")
                for c in _git_lines(root, ["ls-files", "--others",
                                           "--exclude-standard"])}
    changed = {c for c in changed if c.endswith(".py")}
    if surface is None:
        surface = {os.path.relpath(fp, root).replace(os.sep, "/")
                   for fp in core.iter_py(core.DEFAULT_PATHS, root=root)}
    selected = changed & surface
    if not selected:
        return []
    mod_of, imports = {}, {}
    for rel in surface:
        mod = rel[:-3].replace("/", ".")
        if mod.endswith(".__init__"):
            mod = mod[:-len(".__init__")]
        mod_of[rel] = mod
        try:
            with open(os.path.join(root, rel), encoding="utf-8") as f:
                imports[rel] = _callgraph.module_imports(rel, f.read())
        except OSError:
            imports[rel] = set()
    grew = True
    while grew:
        grew = False
        mods = {mod_of[r] for r in selected}
        for rel, imps in imports.items():
            if rel in selected:
                continue
            if any(_depends_on(imps, m) for m in mods):
                selected.add(rel)
                grew = True
    return sorted(selected)


def main(argv=None) -> int:
    args = _build_parser().parse_args(argv)
    root = repo_root()

    registry = core.load_rules()
    if args.list_rules:
        for rule in registry.values():
            print(f"{rule.code:4} {rule.severity:8} {rule.name}")
            print(f"     {rule.doc}")
        return 0

    rules = list(registry.values())
    if args.rules:
        wanted = [c.strip() for c in args.rules.split(",") if c.strip()]
        unknown = [c for c in wanted if c not in registry]
        if unknown:
            print(f"unknown rule code(s): {', '.join(unknown)}",
                  file=sys.stderr)
            return 2
        rules = [registry[c] for c in wanted]

    if args.write_baseline \
            and (args.paths or args.rules or args.changed_only) \
            and not args.baseline:
        # a narrowed scan regenerating the COMMITTED baseline would
        # silently drop every out-of-scope entry
        print("--write-baseline with paths/--rules would clobber the "
              "default baseline with a partial scan; pass an explicit "
              "--baseline FILE or run unfiltered", file=sys.stderr)
        return 2

    if args.paths:
        # EVERY named path must resolve — a typo'd path among valid
        # ones must not read as a clean pass
        miss = core.missing_paths(args.paths, root=root)
        if miss:
            print(f"no .py files found under: {' '.join(miss)}",
                  file=sys.stderr)
            return 2
    paths = args.paths or None
    if args.changed_only:
        if args.paths:
            print("--changed-only computes its own path set; drop the "
                  "explicit paths", file=sys.stderr)
            return 2
        try:
            paths = changed_only_paths(root, args.changed_only)
        except (RuntimeError, OSError, subprocess.SubprocessError) as e:
            print(f"--changed-only {args.changed_only}: {e}",
                  file=sys.stderr)
            return 2
        if not paths:
            print(f"graftlint: no changed .py files vs "
                  f"{args.changed_only}")
            return 0

    cache = None
    if not args.no_cache and not args.list_rules:
        cpath = os.path.join(root, _summaries.DEFAULT_CACHE)
        if os.path.isdir(os.path.dirname(cpath)):
            cache = _summaries.SummaryCache.load(cpath)
    prev_cache = _summaries.set_active_cache(cache)
    try:
        findings, n_files = core.run(paths, rules=rules, root=root,
                                     jobs=args.jobs)
    finally:
        _summaries.set_active_cache(prev_cache)
        if cache is not None:
            try:
                cache.save(keep=4096)
            except OSError:
                pass             # a read-only checkout still lints fine
    if n_files == 0:
        # the default scan finding nothing means repo_root() is not a
        # checkout (e.g. an installed wheel) — not a clean pass
        print(f"no .py files found under {root} — not a repo checkout?",
              file=sys.stderr)
        return 2

    # a relative --baseline resolves against the repo root, like the scan
    # paths and the default baseline — never against the process cwd
    bl_path = args.baseline or _baseline.DEFAULT_BASELINE
    if not os.path.isabs(bl_path):
        bl_path = os.path.join(root, bl_path)
    if args.write_baseline:
        entries = _baseline.write_baseline(bl_path, findings)
        print(f"graftlint: wrote {len(entries)} entries to "
              f"{os.path.relpath(bl_path, root)}")
        return 0

    try:
        entries = [] if args.no_baseline else \
            _baseline.load_baseline(bl_path)
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2
    new, baselined = _baseline.partition(findings, entries)

    if args.format == "text":
        emitters.emit_text(new, baselined, n_files, sys.stdout,
                           verbose_baselined=args.show_baselined)
    elif args.format == "json":
        emitters.dump_json(emitters.to_json(new, baselined, n_files),
                           sys.stdout)
    else:
        emitters.dump_json(emitters.to_sarif(new, baselined,
                                             list(registry.values())),
                           sys.stdout)
    return 1 if new else 0

"""Function summaries + cycle-safe fixpoint — graftlint's interprocedural
memory.

For every function in a module this computes one summary:

- **blocks** — the blocking operations it performs directly (file /
  socket / journal I/O, ``time.sleep``, tracked-receiver ``get/join/
  wait``, subprocess), each annotated with the locks held at the site;
- **locks** — which lock keys it acquires (``with`` and explicit
  ``.acquire()``) and releases, and whether a release sits on an
  exception-safe path (a ``finally`` body);
- **rank taint** — whether its return value derives from
  ``jax.process_index()`` (directly or through another tainted
  same-module function);
- **deadline** — which ``deadline``/``timeout`` parameters it accepts
  and whether each is ever read (threads toward a wait) at all.

Direct facts propagate transitively over the call graph by fixpoint
iteration (monotone set joins, so recursion/cycles converge instead of
recursing forever), giving the G15-G19 rules answers like "does this
``with self._lock:`` body *reach* file I/O through any chain of
helpers".

Summaries are cached per file, keyed by a content fingerprint
(sha1 of source + engine schema version), in
``ci/lint_summary_cache.json`` next to the baseline — re-runs and CI
skip the extraction walk for unchanged files; the fixpoint re-runs from
the cached direct facts (cheap, and identical by construction since the
fingerprint pins the whole module text, line numbers included).
"""
from __future__ import annotations

import ast
import hashlib
import json
import os
import re

from . import callgraph as cg

__all__ = ["FunctionSummary", "ModuleSummaries", "SummaryCache",
           "module_summaries", "for_context", "set_active_cache",
           "drain_active_cache", "merge_cache_delta", "active_cache"]

# v2: attribute accesses with held locksets, thread-spawn targets,
# Condition waits — the G22-G25 race family. Bumping the version
# changes every fingerprint, so a pre-G22 cache cold-starts cleanly
# instead of serving summaries without the new fields.
_SCHEMA_VERSION = 2
DEFAULT_CACHE = os.path.join("ci", "lint_summary_cache.json")

_RANK_SOURCES = {"jax.process_index"}
_DEADLINE_PARAM_RE = re.compile(r"deadline|timeout", re.IGNORECASE)


class FunctionSummary:
    """Direct (non-transitive) facts of one function; plain-data so it
    round-trips through the JSON cache."""

    __slots__ = ("key", "line", "public", "blocks", "calls", "acq_with",
                 "acq_exp", "releases", "rank_direct", "rank_calls",
                 "deadline_params", "deadline_read", "attrs", "toctou",
                 "cond_waits", "spawns", "thread_run")

    def __init__(self, key, line, public):
        self.key = key
        self.line = line
        self.public = public
        self.blocks = []      # (kind, what, line, (locks...), deadlined)
        self.calls = []       # (callee_key, line, (locks...), in_finally)
        self.acq_with = []    # (lock_key, line, (locks_held_before...))
        self.acq_exp = []     # (lock_key, line, in_finally)
        self.releases = []    # (lock_key, line, in_finally)
        self.rank_direct = False
        self.rank_calls = []  # same-module callees feeding the return
        self.deadline_params = []
        self.deadline_read = []
        # race-family facts (schema v2)
        self.attrs = []       # (attr, "r"|"w"|"c", line, (locks...))
        self.toctou = []      # (attr, test_line, (test_locks...),
        #                        act_line, (act_locks...)) — a write to
        #                        `self.attr` guarded by a membership
        #                        test of the same attr
        self.cond_waits = []  # (recv, line, in_while_loop)
        self.spawns = []      # same-module fn keys passed as thread
        #                       targets / callbacks — thread roots
        self.thread_run = False  # run() of a Thread subclass

    def to_dict(self):
        return {"line": self.line, "public": self.public,
                "blocks": [list(b) for b in self.blocks],
                "calls": [list(c) for c in self.calls],
                "acq_with": [list(a) for a in self.acq_with],
                "acq_exp": [list(a) for a in self.acq_exp],
                "releases": [list(r) for r in self.releases],
                "rank_direct": self.rank_direct,
                "rank_calls": list(self.rank_calls),
                "deadline_params": list(self.deadline_params),
                "deadline_read": list(self.deadline_read),
                "attrs": [list(a) for a in self.attrs],
                "toctou": [[t[0], t[1], list(t[2]), t[3], list(t[4])]
                           for t in self.toctou],
                "cond_waits": [list(c) for c in self.cond_waits],
                "spawns": list(self.spawns),
                "thread_run": self.thread_run}

    @classmethod
    def from_dict(cls, key, d):
        s = cls(key, int(d["line"]), bool(d["public"]))
        s.blocks = [(b[0], b[1], int(b[2]), tuple(b[3]), bool(b[4]))
                    for b in d["blocks"]]
        s.calls = [(c[0], int(c[1]), tuple(c[2]), bool(c[3]))
                   for c in d["calls"]]
        s.acq_with = [(a[0], int(a[1]), tuple(a[2]))
                      for a in d["acq_with"]]
        s.acq_exp = [(a[0], int(a[1]), bool(a[2])) for a in d["acq_exp"]]
        s.releases = [(r[0], int(r[1]), bool(r[2]))
                      for r in d["releases"]]
        s.rank_direct = bool(d["rank_direct"])
        s.rank_calls = list(d["rank_calls"])
        s.deadline_params = list(d["deadline_params"])
        s.deadline_read = list(d["deadline_read"])
        s.attrs = [(a[0], a[1], int(a[2]), tuple(a[3]))
                   for a in d["attrs"]]
        s.toctou = [(t[0], int(t[1]), tuple(t[2]), int(t[3]), tuple(t[4]))
                    for t in d["toctou"]]
        s.cond_waits = [(c[0], int(c[1]), bool(c[2]))
                        for c in d["cond_waits"]]
        s.spawns = list(d["spawns"])
        s.thread_run = bool(d["thread_run"])
        return s


# ---------------------------------------------------------------------------
# extraction
# ---------------------------------------------------------------------------

# container methods that mutate the receiver in place — a call through
# `self._x.append(...)` is a WRITE of `self._x` for lockset purposes
_MUTATORS = {"append", "appendleft", "add", "insert", "extend", "pop",
             "popitem", "popleft", "remove", "discard", "clear",
             "update", "setdefault", "sort", "reverse"}
# thread-target parameter names (Thread(target=...), Timer(t, function=...))
_TARGET_KWARGS = {"target", "function"}


def _self_attr(node):
    """Bare attribute name for a one-level ``self.X`` / ``cls.X``
    access, else None."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and \
            node.value.id in ("self", "cls"):
        return node.attr
    return None


def _extract_function(index, info):
    """One function's direct facts: a structure-aware walk tracking the
    held-lock set through ``with`` nesting and the in-``finally`` flag
    through try statements. Nested defs/lambdas are separate scopes —
    code inside them does not run when this function does.

    Schema v2 also records, per ``self._x`` site, the lockset held
    there (the raw material of the G22-G25 Eraser-style analysis),
    membership-test guards over later writes of the same attribute
    (G24's check-then-act pairs), ``Condition.wait()`` sites with their
    enclosing-``while`` flag (G25), and thread-spawn targets (the
    thread-escape roots)."""
    s = FunctionSummary(info.key, info.line, info.public)
    cls, fnkey = info.cls, info.key
    if info.name == "run" and cls and cls in index.thread_classes():
        s.thread_run = True

    def tracked(attr):
        # lock/queue/event/... receivers are synchronization objects,
        # not shared data; method names are class namespace, not state
        dotted = f"self.{attr}"
        if dotted in index.lock_recvs or dotted in index.receivers:
            return False
        if cg._LOCKISH_RE.search(attr):
            return False
        if cls and index.method_owner(cls, attr):
            return False
        return True

    def record(attr, mode, line, held, guards):
        if not tracked(attr):
            return
        s.attrs.append((attr, mode, line, tuple(held)))
        if mode == "w":
            for g_attr, g_line, g_locks in guards:
                if g_attr == attr:
                    s.toctou.append((attr, g_line, tuple(g_locks),
                                     line, tuple(held)))

    def record_target(t, held, fin, loop, guards):
        """Assignment/delete target: classify ``self.X``-rooted stores
        as writes, walk everything else (slices, chained receivers)
        for the reads they contain."""
        if isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                record_target(e, held, fin, loop, guards)
            return
        if isinstance(t, ast.Starred):
            record_target(t.value, held, fin, loop, guards)
            return
        attr = _self_attr(t)
        if attr:                                   # self.x = ...
            record(attr, "w", t.lineno, held, guards)
            return
        if isinstance(t, ast.Subscript):
            attr = _self_attr(t.value)
            if attr:                               # self.x[k] = ...
                record(attr, "w", t.lineno, held, guards)
            else:
                walk(t.value, held, fin, loop, guards)
            walk(t.slice, held, fin, loop, guards)
            return
        if isinstance(t, ast.Attribute):
            attr = _self_attr(t.value)
            if attr:                               # self.x.field = ...
                record(attr, "w", t.lineno, held, guards)
            else:
                walk(t.value, held, fin, loop, guards)
            return
        walk(t, held, fin, loop, guards)

    def walk(node, held, fin, loop, guards):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            new = list(held)
            for item in node.items:
                lk = cg.lock_key(index, item.context_expr, cls, fnkey)
                if lk:
                    s.acq_with.append((lk, item.context_expr.lineno,
                                       tuple(new)))
                    new.append(lk)
                else:
                    walk(item.context_expr, tuple(new), fin, loop, guards)
                if item.optional_vars is not None:
                    walk(item.optional_vars, tuple(new), fin, loop, guards)
            for st in node.body:
                walk(st, tuple(new), fin, loop, guards)
            return
        if isinstance(node, ast.Try):
            for st in node.body:
                walk(st, held, fin, loop, guards)
            for h in node.handlers:
                if h.type is not None:
                    walk(h.type, held, fin, loop, guards)
                for st in h.body:
                    walk(st, held, fin, loop, guards)
            for st in node.orelse:
                walk(st, held, fin, loop, guards)
            for st in node.finalbody:
                walk(st, held, True, loop, guards)
            return
        if isinstance(node, ast.While):
            walk(node.test, held, fin, loop, guards)
            for st in node.body:
                walk(st, held, fin, True, guards)
            for st in node.orelse:
                walk(st, held, fin, loop, guards)
            return
        if isinstance(node, ast.If):
            # a membership test over `self.X` guards BOTH branches (In
            # conditions the hit path, NotIn the miss path — either way
            # a mutation below depends on the possibly-stale answer)
            new_guards = guards
            for sub in ast.walk(node.test):
                if isinstance(sub, ast.Compare):
                    for op, cmp_ in zip(sub.ops, sub.comparators):
                        if not isinstance(op, (ast.In, ast.NotIn)):
                            continue
                        attr = _self_attr(cmp_)
                        if attr:
                            new_guards = new_guards + (
                                (attr, sub.lineno, tuple(held)),)
            walk(node.test, held, fin, loop, guards)
            for st in node.body:
                walk(st, held, fin, loop, new_guards)
            for st in node.orelse:
                walk(st, held, fin, loop, new_guards)
            return
        if isinstance(node, ast.Assign):
            for t in node.targets:
                record_target(t, held, fin, loop, guards)
            walk(node.value, held, fin, loop, guards)
            return
        if isinstance(node, ast.AnnAssign):
            record_target(node.target, held, fin, loop, guards)
            if node.value is not None:
                walk(node.value, held, fin, loop, guards)
            return
        if isinstance(node, ast.AugAssign):
            record_target(node.target, held, fin, loop, guards)
            walk(node.value, held, fin, loop, guards)
            return
        if isinstance(node, ast.Delete):
            for t in node.targets:
                record_target(t, held, fin, loop, guards)
            return
        if isinstance(node, ast.Call):
            b = cg.classify_blocking(index, node)
            if b:
                kind, what, deadlined = b
                s.blocks.append((kind, what, node.lineno, held, deadlined))
            func = node.func
            name = index.ctx.resolve(func)
            skip_func = False
            if name in cg.THREAD_MAKERS:
                cands = [kw.value for kw in node.keywords
                         if kw.arg in _TARGET_KWARGS]
                cands += node.args[1:2]     # Thread(group, target) /
                for c in cands:             # Timer(interval, function)
                    ref = cg.resolve_func_ref(index, c, cls, fnkey)
                    if ref:
                        s.spawns.append(ref)
            elif isinstance(func, ast.Attribute) and \
                    "callback" in func.attr:
                # registration APIs (add_stall_callback, ...): the
                # registered function runs on someone else's thread
                for c in list(node.args) + [k.value for k in
                                            node.keywords]:
                    ref = cg.resolve_func_ref(index, c, cls, fnkey)
                    if ref:
                        s.spawns.append(ref)
            if isinstance(func, ast.Attribute):
                if func.attr in ("acquire", "release"):
                    lk = cg.lock_key(index, func.value, cls, fnkey)
                    if lk:
                        if func.attr == "acquire":
                            s.acq_exp.append((lk, node.lineno, fin))
                        else:
                            s.releases.append((lk, node.lineno, fin))
                inner = _self_attr(func.value)
                if inner is not None and func.attr in _MUTATORS:
                    record(inner, "w", node.lineno, held, guards)
                    skip_func = True    # don't double-record the read
                if func.attr == "wait":
                    recv = cg._dotted(func.value)
                    if recv is not None and (
                            recv in index.cond_recvs or
                            (cg._CONDISH_RE.search(
                                recv.rsplit(".", 1)[-1]) and
                             index.receivers.get(recv) != "event")):
                        s.cond_waits.append((recv, node.lineno, loop))
            callee = cg.resolve_callee(index, node, cls, fnkey)
            if callee:
                s.calls.append((callee, node.lineno, held, fin))
            for child in ast.iter_child_nodes(node):
                if skip_func and child is func:
                    continue
                walk(child, held, fin, loop, guards)
            return
        if isinstance(node, ast.Compare):
            checked = []
            for op, cmp_ in zip(node.ops, node.comparators):
                if isinstance(op, (ast.In, ast.NotIn)):
                    attr = _self_attr(cmp_)
                    if attr:
                        record(attr, "c", cmp_.lineno, held, guards)
                        checked.append(cmp_)
            for child in ast.iter_child_nodes(node):
                if any(child is c for c in checked):
                    continue            # already recorded as a check
                walk(child, held, fin, loop, guards)
            return
        if isinstance(node, ast.Attribute):
            attr = _self_attr(node)
            if attr and isinstance(node.ctx, ast.Load):
                record(attr, "r", node.lineno, held, guards)
            for child in ast.iter_child_nodes(node):
                walk(child, held, fin, loop, guards)
            return
        for child in ast.iter_child_nodes(node):
            walk(child, held, fin, loop, guards)

    for st in info.node.body:
        walk(st, (), False, False, ())
    _extract_rank(index, info, s)
    _extract_deadline(info, s)
    return s


def _is_rank_call(ctx, node) -> bool:
    return isinstance(node, ast.Call) and \
        ctx.resolve(node.func) in _RANK_SOURCES


def _scope_walk(fn_node):
    """This function's own nodes — stops at nested def/lambda
    boundaries (their assignments and returns are their own)."""
    stack = list(ast.iter_child_nodes(fn_node))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
            stack.extend(ast.iter_child_nodes(node))


def _extract_rank(index, info, s):
    """Return-value rank taint: does this function's return derive from
    ``jax.process_index()`` — directly, through a local name, or through
    a same-module call (resolved later by the fixpoint)?"""
    ctx = index.ctx
    tainted: set = set()
    name_keys: dict = {}            # name -> same-module callee keys
    assigns = []
    for node in _scope_walk(info.node):
        if isinstance(node, ast.Assign):
            assigns.append((node.targets, node.value))
        elif isinstance(node, (ast.AnnAssign, ast.NamedExpr)) \
                and node.value is not None:
            assigns.append(([node.target], node.value))
    changed = True
    while changed:                  # local two-level flows: a = pi(); b = a
        changed = False
        for targets, value in assigns:
            dirty = False
            keys = set()
            for sub in ast.walk(value):
                if _is_rank_call(ctx, sub):
                    dirty = True
                elif isinstance(sub, ast.Name):
                    if sub.id in tainted:
                        dirty = True
                    keys |= name_keys.get(sub.id, set())
                elif isinstance(sub, ast.Call):
                    callee = cg.resolve_callee(index, sub, info.cls,
                                               info.key)
                    if callee:
                        keys.add(callee)
            if not dirty and not keys:
                continue
            for t in targets:
                for sub in ast.walk(t):
                    if not isinstance(sub, ast.Name):
                        continue
                    if dirty and sub.id not in tainted:
                        tainted.add(sub.id)
                        changed = True
                    if keys - name_keys.get(sub.id, set()):
                        name_keys[sub.id] = \
                            name_keys.get(sub.id, set()) | keys
                        changed = True
    rank_calls: set = set()
    for node in _scope_walk(info.node):
        if not isinstance(node, ast.Return) or node.value is None:
            continue
        for sub in ast.walk(node.value):
            if _is_rank_call(ctx, sub):
                s.rank_direct = True
            elif isinstance(sub, ast.Name):
                if sub.id in tainted:
                    s.rank_direct = True
                rank_calls |= name_keys.get(sub.id, set())
            elif isinstance(sub, ast.Call):
                callee = cg.resolve_callee(index, sub, info.cls, info.key)
                if callee:
                    rank_calls.add(callee)
    s.rank_calls = sorted(rank_calls)


def _extract_deadline(info, s):
    a = info.node.args
    params = [p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)
              if _DEADLINE_PARAM_RE.search(p.arg)]
    if not params:
        return
    read = set()
    # whole-subtree walk deliberately: a nested closure capturing the
    # deadline param (a hedge thread's run()) IS threading it
    for node in ast.walk(info.node):
        if isinstance(node, ast.Name) and node.id in params \
                and isinstance(node.ctx, (ast.Load, ast.Del)):
            read.add(node.id)
    s.deadline_params = params
    s.deadline_read = sorted(read)


# ---------------------------------------------------------------------------
# module summaries + fixpoint
# ---------------------------------------------------------------------------

class ModuleSummaries:
    """All function summaries of one module plus the transitive facts
    computed by fixpoint over the call graph."""

    def __init__(self, ctx, functions):
        self.ctx = ctx
        self.functions = functions            # key -> FunctionSummary
        self._index = None
        edges = {k: [c for (c, _l, _h, _f) in s.calls if c in functions]
                 for k, s in functions.items()}
        self.edges = edges
        # transitive blocking ops: {key: {(kind, what)}}
        self.reach = self._fixpoint(
            {k: {(b[0], b[1]) for b in s.blocks}
             for k, s in functions.items()}, edges)
        # transitive lock acquisitions
        self.trans_acquires = self._fixpoint(
            {k: {a[0] for a in s.acq_with} | {a[0] for a in s.acq_exp}
             for k, s in functions.items()}, edges)
        # transitive releases (for exception-path analysis: a helper
        # called from a finally that releases the slot counts)
        self.trans_releases = self._fixpoint(
            {k: {r[0] for r in s.releases} for k, s in functions.items()},
            edges)
        # rank taint: boolean fixpoint over return-flow edges
        taint = {k: s.rank_direct for k, s in functions.items()}
        changed = True
        while changed:
            changed = False
            for k, s in functions.items():
                if taint[k]:
                    continue
                if any(taint.get(c, False) for c in s.rank_calls):
                    taint[k] = True
                    changed = True
        self.rank_taint = taint
        # thread escape: forward reachability from spawn targets and
        # Thread-subclass run() methods — a function in this set can
        # run concurrently with the object's other methods
        roots = {c for s in functions.values() for c in s.spawns
                 if c in functions}
        roots |= {k for k, s in functions.items() if s.thread_run}
        self.thread_roots = roots
        reach_t = set(roots)
        frontier = list(roots)
        while frontier:
            k = frontier.pop()
            for c in edges.get(k, ()):
                if c not in reach_t:
                    reach_t.add(c)
                    frontier.append(c)
        self.thread_reachable = reach_t
        self.entry_locks = self._entry_locks(functions, roots)

    @staticmethod
    def _entry_locks(functions, roots):
        """Locks guaranteed held on ENTRY to each function: the
        intersection, over every same-module call site, of the locks
        the caller holds there plus the caller's own entry set. Public
        functions, thread roots, and functions with no same-module
        caller start open (anyone may call them with nothing held); a
        private helper only ever invoked as ``with self._lock:
        self._helper()`` inherits the lock — so its attribute writes
        don't read as unlocked to the G22/G23 lockset analysis.
        Decreasing intersection fixpoint from the full lock universe;
        cycle-safe because the sets only shrink."""
        callers: dict = {}
        for k, s in functions.items():
            for c, _l, held, _f in s.calls:
                if c in functions:
                    callers.setdefault(c, []).append((k, held))
        universe = frozenset(
            a[0] for s in functions.values()
            for a in list(s.acq_with) + list(s.acq_exp))
        entry = {}
        for k, s in functions.items():
            # nested defs (key prefix is itself a function) are only
            # reachable through their parent — never externally public
            nested = "." in k and k.rsplit(".", 1)[0] in functions
            open_entry = (k in roots or not callers.get(k)
                          or (s.public and not nested))
            entry[k] = frozenset() if open_entry else universe
        changed = True
        while changed:
            changed = False
            for k in entry:
                if not entry[k]:
                    continue
                new = None
                for caller, held in callers.get(k, ()):
                    site = entry[caller] | set(held)
                    new = site if new is None else (new & site)
                new = frozenset(new or ())
                if new != entry[k]:
                    entry[k] = new
                    changed = True
        return entry

    @staticmethod
    def _fixpoint(direct, edges):
        """Monotone set join to a fixed point — cycle-safe by
        construction (the sets only grow and are bounded by the union
        of all direct facts)."""
        reach = {k: set(v) for k, v in direct.items()}
        changed = True
        while changed:
            changed = False
            for k, callees in edges.items():
                r = reach[k]
                n = len(r)
                for c in callees:
                    if c != k and c in reach:
                        r |= reach[c]
                if len(r) != n:
                    changed = True
        return reach

    @property
    def index(self) -> "cg.ModuleIndex":
        """The (lazily built) AST-side module index — rules that walk
        the tree (G18) use it; cache hits that don't never pay for it."""
        if self._index is None:
            self._index = cg.build_index(self.ctx)
        return self._index

    def chain(self, start, kind_what):
        """Shortest call chain (list of function keys) from ``start`` to
        a function whose DIRECT blocks contain ``kind_what``, plus the
        op line in that function — for human-readable findings."""
        target = None
        frontier = [(start, [start])]
        seen = {start}
        while frontier:
            nxt = []
            for key, path in frontier:
                s = self.functions.get(key)
                if s is None:
                    continue
                for b in s.blocks:
                    if (b[0], b[1]) == kind_what:
                        return path, b[2]
                for c, _l, _h, _f in s.calls:
                    if c in self.functions and c not in seen:
                        seen.add(c)
                        nxt.append((c, path + [c]))
            frontier = nxt
        return target, None


# ---------------------------------------------------------------------------
# cache
# ---------------------------------------------------------------------------

class SummaryCache:
    """Fingerprint-keyed per-file summary store. ``new`` entries are
    kept apart from the loaded ones so a forked ``--jobs`` worker can
    drain its delta back to the parent, which merges and persists."""

    def __init__(self, path=None):
        self.path = path
        self._data: dict = {}
        self.new: dict = {}
        self.hits = 0
        self.misses = 0

    @classmethod
    def load(cls, path):
        c = cls(path)
        try:
            with open(path, encoding="utf-8") as f:
                data = json.load(f)
            if isinstance(data, dict) and \
                    data.get("version") == _SCHEMA_VERSION:
                c._data = data.get("entries", {})
        except (OSError, ValueError):
            pass                     # unreadable cache: rebuild silently
        return c

    def get(self, fp):
        entry = self.new.get(fp) or self._data.get(fp)
        if entry is not None:
            self.hits += 1
        else:
            self.misses += 1
        return entry

    def put(self, fp, entry):
        self.new[fp] = entry

    def save(self, keep=None):
        """Persist (atomically: tmp + replace — the lint tier practices
        what it lints). ``keep`` bounds the entry count; stale entries
        (files since edited) are the ones dropped first."""
        if not self.path:
            return
        entries = {**self._data, **self.new}
        if keep is not None and len(entries) > keep:
            fresh = set(self.new)
            for fp in list(entries):
                if len(entries) <= keep:
                    break
                if fp not in fresh:
                    del entries[fp]
        payload = {"version": _SCHEMA_VERSION, "entries": entries}
        # pid-unique staging: a pre-commit hook and a manual run saving
        # concurrently must not interleave into one tmp (the shared
        # temp-file class atomic_write solves with per-call suffixes;
        # analysis stays runtime-free — ci/lint.py path-loads it — so
        # the tmp+replace pattern is by hand, not atomic_write)
        tmp = f"{self.path}.tmp.{os.getpid()}"
        try:
            # graftlint: disable=G7 hand-rolled tmp + os.replace below
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(payload, f)
                f.write("\n")
            os.replace(tmp, self.path)
        except OSError:
            try:                   # the cache is an optimization: a
                os.unlink(tmp)     # failed save must not fail the lint
            except OSError:
                pass

    def stats(self) -> dict:
        total = self.hits + self.misses
        return {"hits": self.hits, "misses": self.misses,
                "hit_rate": round(self.hits / total, 3) if total else None}


_active_cache: SummaryCache | None = None


def set_active_cache(cache) -> SummaryCache | None:
    """Install (or, with None, remove) the process-wide cache; returns
    the previous one so callers can nest/restore."""
    global _active_cache
    prev, _active_cache = _active_cache, cache
    return prev


def active_cache():
    return _active_cache


def drain_active_cache():
    """(new_entries, hits, misses) accumulated since the last drain —
    the ``--jobs`` worker's return payload."""
    c = _active_cache
    if c is None:
        return {}, 0, 0
    delta = (dict(c.new), c.hits, c.misses)
    c.new.clear()
    c.hits = c.misses = 0
    return delta


def merge_cache_delta(delta) -> None:
    """Fold a worker's drained delta into the parent's active cache."""
    c = _active_cache
    if c is None:
        return
    new, hits, misses = delta
    c.new.update(new)
    c.hits += hits
    c.misses += misses


def fingerprint(src: str) -> str:
    raw = f"{src}\x00schema{_SCHEMA_VERSION}".encode("utf-8", "replace")
    return hashlib.sha1(raw).hexdigest()


def module_summaries(ctx, cache=None) -> ModuleSummaries:
    """Summaries for one :class:`~.core.FileContext`, through the cache
    when one is active (content fingerprint pins the whole file text,
    so cached line numbers are exact by construction)."""
    cache = cache if cache is not None else _active_cache
    fp = fingerprint(ctx.src)
    if cache is not None:
        entry = cache.get(fp)
        if entry is not None:
            funcs = {k: FunctionSummary.from_dict(k, d)
                     for k, d in entry.items()}
            return ModuleSummaries(ctx, funcs)
    index = cg.build_index(ctx)
    funcs = {key: _extract_function(index, info)
             for key, info in index.functions.items()}
    if cache is not None:
        cache.put(fp, {k: s.to_dict() for k, s in funcs.items()})
    ms = ModuleSummaries(ctx, funcs)
    ms._index = index               # already built: share it
    return ms


def for_context(ctx) -> ModuleSummaries:
    """Memoized per-FileContext accessor — every G15-G19 rule shares ONE
    summary computation per file (the shared-AST contract)."""
    ms = getattr(ctx, "_mod_summaries", None)
    if ms is None:
        ms = module_summaries(ctx)
        ctx._mod_summaries = ms
    return ms

"""Concurrency / lock-discipline rules (G15-G20, G26) — the
interprocedural tier, built on :mod:`.callgraph` + :mod:`.summaries`.

Every rule here is grounded in a cross-function defect this repo
actually shipped and then paid to find dynamically (chaos tests, hand
archaeology — CHANGES.md PRs 9-10):

- the router held its placement lock across ledger file I/O until a
  code comment (not a tool) moved the read outside;
- breaker/quarantine transitions journaled (file I/O) from inside
  counter critical sections in both the router and the tenant fleet;
- the half-open probe slot latched forever when an exception path
  skipped its release;
- the heartbeat ``beat()`` staged its atomic write under a lock whose
  only job was papering over a shared temp-file race;
- rank-dependent collective entry hid behind helper functions where the
  per-function G12 could not see it.

The per-function rules (G1-G14) reason about one scope at a time; these
five reason about what a function *reaches*. All five scope to
``mxnet_tpu/`` library code, like G4/G8.
"""
from __future__ import annotations

import ast

from . import callgraph as cg
from . import summaries as sm
from .core import Rule, register
from .rules_jax import RankDependentCollectiveEntry

_BLOCK_NOUN = {"sleep": "a sleep", "file": "file I/O",
               "journal": "a journal write", "socket": "socket I/O",
               "wait": "a blocking wait", "subprocess": "a subprocess"}

# kinds that constitute a *wait* for G19's purposes (file I/O completes
# on its own; a wait can be indefinite without a deadline)
_WAIT_KINDS = ("wait", "sleep", "subprocess", "socket")


def _chain_str(path) -> str:
    return " -> ".join(k.split(".")[-1] + "()" for k in path)


@register
class BlockingCallUnderLock(Rule):
    code = "G15"
    name = "blocking-call-under-lock"
    severity = "error"
    doc = ("A lock-holding region (`with self._lock:` or any tracked "
           "lock) reaches a blocking operation — file/socket I/O, a "
           "journal write, time.sleep, a queue/thread/event wait, a "
           "subprocess — directly or TRANSITIVELY through any chain of "
           "same-module calls (the summary engine's reach set). Every "
           "thread that touches the lock then stalls behind one slow "
           "write or wedged wait; on a slow shared filesystem that is "
           "the whole front door. Move the I/O outside the critical "
           "section: mutate state under the lock, collect the payload, "
           "emit after release. Regression notes — the PR-9 router "
           "held its placement lock across ledger reads (fixed by a "
           "comment then, enforced here now; the pre-fix shape is the "
           "tests/data/graftlint/hist_lock_held_ledger_io.py fixture); "
           "this PR's audit moved the router/fleet breaker-transition "
           "journal writes (serving/router.py `_transition`, "
           "serving/fleet.py `_transition`/`_admit_tenant`) and the "
           "heartbeat's staged atomic write "
           "(elastic/membership.py `Heartbeat.beat`) outside their "
           "locks. A deadlined wait under a lock still counts: peers "
           "stall for the full budget. Held-region tracking is "
           "`with`-based — blocking work between an explicit "
           ".acquire()/.release() straddle is not attributed to the "
           "lock (G17's territory; docs/static_analysis.md known "
           "limits). Scope: mxnet_tpu/ library code.")

    def check(self, ctx):
        if not ctx.is_library():
            return
        ms = sm.for_context(ctx)
        seen = set()
        for key, s in ms.functions.items():
            for kind, what, line, held, _deadlined in s.blocks:
                if not held or (line, what) in seen:
                    continue
                seen.add((line, what))
                locks = ", ".join(sorted(
                    {cg.lock_display(h) for h in held}))
                yield self.finding(
                    ctx, line,
                    f"{_BLOCK_NOUN[kind]} ({what}) while holding "
                    f"{locks} — every thread touching the lock stalls "
                    f"behind it; mutate under the lock, do the "
                    f"{_BLOCK_NOUN[kind].split()[-1]} after release")
            for callee, line, held, _fin in s.calls:
                if not held or callee not in ms.reach:
                    continue
                reached = ms.reach[callee]
                if not reached:
                    continue
                if (line, callee) in seen:
                    continue
                seen.add((line, callee))
                kind, what = sorted(reached)[0]
                path, op_line = ms.chain(callee, (kind, what))
                via = _chain_str(path) if path else callee
                locks = ", ".join(sorted(
                    {cg.lock_display(h) for h in held}))
                yield self.finding(
                    ctx, line,
                    f"call under {locks} reaches {_BLOCK_NOUN[kind]} "
                    f"({what} via {via}, line {op_line}) — the lock is "
                    f"held across it on every path through the chain; "
                    f"hoist the blocking step out of the critical "
                    f"section")


@register
class LockOrderCycle(Rule):
    code = "G16"
    name = "lock-order-cycle"
    severity = "error"
    doc = ("Two locks acquired in opposite orders somewhere in the same "
           "module — A then B on one path (nested `with`, or a call "
           "under A into a function that takes B), B then A on another. "
           "Two threads each holding their first lock deadlock forever, "
           "and nothing times out because locks have no deadline. "
           "Pick one global order (document it where the locks are "
           "constructed) or collapse the sections onto one lock. "
           "Reentrant same-lock nesting (RLock) is not a cycle and is "
           "not flagged. Scope: mxnet_tpu/ library code.")

    def check(self, ctx):
        if not ctx.is_library():
            return
        ms = sm.for_context(ctx)
        orders: dict = {}         # (outer, inner) -> (line, via)
        for key, s in ms.functions.items():
            for lk, line, held in s.acq_with:
                for h in held:
                    if h != lk:
                        orders.setdefault((h, lk), (line, None))
            for callee, line, held, _fin in s.calls:
                if callee not in ms.trans_acquires:
                    continue
                for h in held:
                    for lk in ms.trans_acquires[callee]:
                        if lk != h:
                            orders.setdefault((h, lk), (line, callee))
        reported = set()
        for (a, b), (line, via) in sorted(orders.items(),
                                          key=lambda kv: kv[1][0]):
            if (b, a) not in orders or frozenset((a, b)) in reported:
                continue
            reported.add(frozenset((a, b)))
            other_line = orders[(b, a)][0]
            da, db = cg.lock_display(a), cg.lock_display(b)
            suffix = f" (via {via.split('.')[-1]}())" if via else ""
            yield self.finding(
                ctx, line,
                f"lock-order cycle: {da} -> {db} here{suffix}, but "
                f"{db} -> {da} at line {other_line} — two threads each "
                f"holding their first lock deadlock with no timeout; "
                f"pick one global order or merge the critical sections")


@register
class LeakedAcquire(Rule):
    code = "G17"
    name = "leaked-acquire"
    severity = "error"
    doc = ("Explicit `.acquire()` on a lock/semaphore with no "
           "exception-safe release: no `.release()` in a `finally:` of "
           "the same function, and no `finally:`-called helper that "
           "transitively releases it (the summary engine checks the "
           "callees too). The first exception between acquire and the "
           "straight-line release latches the slot forever — every "
           "later waiter queues behind a resource nobody holds. This "
           "is the PR-9 latched-probe class: the half-open breaker's "
           "one probe slot was claimed at placement and an exception "
           "path skipped the release, silently keeping the replica out "
           "of rotation until restart (pre-fix shape: "
           "tests/data/graftlint/hist_latched_probe.py). Prefer "
           "`with lock:`; when acquire/release must straddle "
           "statements, release in a `finally:` (directly or via a "
           "cleanup helper). Scope: mxnet_tpu/ library code.")

    def check(self, ctx):
        if not ctx.is_library():
            return
        ms = sm.for_context(ctx)
        for key, s in ms.functions.items():
            safe = {lk for lk, _line, fin in s.releases if fin}
            for callee, _line, _held, fin in s.calls:
                if fin and callee in ms.trans_releases:
                    safe |= ms.trans_releases[callee]
            for lk, line, fin in s.acq_exp:
                if fin or lk in safe:
                    continue
                yield self.finding(
                    ctx, line,
                    f"{cg.lock_display(lk)}.acquire() with no release "
                    f"on the exception path — the first raise between "
                    f"acquire and release latches the slot forever "
                    f"(the latched-probe class); use `with`, or "
                    f"release in a finally: (a finally-called cleanup "
                    f"helper counts)")


@register
class InterprocRankUniformity(Rule):
    code = "G18"
    name = "interprocedural-rank-uniformity"
    severity = "error"
    doc = ("G12 extended through helpers: a host-level collective "
           "(multihost_utils.sync_global_devices / process_allgather / "
           "broadcast_one_to_all / assert_equal) entered under a "
           "condition whose value flows from jax.process_index() VIA A "
           "FUNCTION RETURN — `if self._is_leader():` where _is_leader "
           "returns a process_index comparison, or a name assigned "
           "from such a call. Some ranks enter the collective, others "
           "don't, and the entered ranks wait forever (docs/"
           "elastic.md). The rank-taint summary propagates through "
           "same-module call chains with cycle-safe fixpoint, so "
           "burying the rank check N helpers deep no longer hides it. "
           "Direct `process_index()` guards stay G12's findings; this "
           "rule fires only on helper-returned taint. Make entry "
           "unconditional; decide on one rank and share the verdict "
           "via a broadcast. Scope: mxnet_tpu/ library code.")

    COLLECTIVES = RankDependentCollectiveEntry.COLLECTIVES
    RANK_SOURCES = RankDependentCollectiveEntry.RANK_SOURCES

    def check(self, ctx):
        if not ctx.is_library() or "multihost_utils" not in ctx.src:
            return
        ms = sm.for_context(ctx)
        if not any(ms.rank_taint.values()):
            return
        index = ms.index
        for info in index.functions.values():
            yield from self._check_fn(ctx, ms, index, info)

    # -- helper-taint plumbing ------------------------------------------
    def _tainted_call(self, ms, index, node, cls, fnkey) -> bool:
        if not isinstance(node, ast.Call):
            return False
        callee = cg.resolve_callee(index, node, cls, fnkey)
        return bool(callee) and ms.rank_taint.get(callee, False)

    def _local_taint(self, ctx, ms, index, info) -> set:
        tainted: set = set()
        changed = True
        while changed:
            changed = False
            for node in sm._scope_walk(info.node):
                if isinstance(node, ast.Assign):
                    targets, value = node.targets, node.value
                elif isinstance(node, (ast.AnnAssign, ast.NamedExpr)) \
                        and node.value is not None:
                    targets, value = [node.target], node.value
                else:
                    continue
                dirty = any(
                    self._tainted_call(ms, index, sub, info.cls, info.key)
                    or (isinstance(sub, ast.Name) and sub.id in tainted)
                    for sub in ast.walk(value))
                if not dirty:
                    continue
                for t in targets:
                    for sub in ast.walk(t):
                        if isinstance(sub, ast.Name) \
                                and sub.id not in tainted:
                            tainted.add(sub.id)
                            changed = True
        return tainted

    def _mentions_helper_rank(self, ctx, ms, index, info, node,
                              tainted) -> bool:
        """True when the condition's taint arrives through a helper
        return (and NOT directly from process_index — that is G12's
        finding, not ours)."""
        direct = helper = False
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call) and \
                    ctx.resolve(sub.func) in self.RANK_SOURCES:
                direct = True
            elif self._tainted_call(ms, index, sub, info.cls, info.key):
                helper = True
            elif isinstance(sub, ast.Name) and sub.id in tainted:
                helper = True
        return helper and not direct

    # -- guarded descent (G12's shape, helper-taint flavored) -----------
    def _check_fn(self, ctx, ms, index, info):
        tainted = self._local_taint(ctx, ms, index, info)

        def mentions(node):
            return self._mentions_helper_rank(ctx, ms, index, info,
                                              node, tainted)

        def descend(node, guarded):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                    continue          # separate scope, visited on its own
                if isinstance(child, (ast.If, ast.While)):
                    rank_test = mentions(child.test)
                    yield from descend(child.test, guarded)
                    for part in child.body + child.orelse:
                        yield from walk_stmt(part, guarded or rank_test)
                    continue
                if isinstance(child, ast.IfExp):
                    rank_test = mentions(child.test)
                    yield from descend(child.test, guarded)
                    for part in (child.body, child.orelse):
                        yield from walk_stmt(part, guarded or rank_test)
                    continue
                if isinstance(child, ast.BoolOp):
                    seen_rank = False
                    for operand in child.values:
                        yield from walk_stmt(operand,
                                             guarded or seen_rank)
                        seen_rank = seen_rank or mentions(operand)
                    continue
                if guarded and isinstance(child, ast.Call) and \
                        ctx.resolve(child.func) in self.COLLECTIVES:
                    yield self._flag(ctx, child)
                yield from descend(child, guarded)

        def walk_stmt(node, guarded):
            if guarded and isinstance(node, ast.Call) and \
                    ctx.resolve(node.func) in self.COLLECTIVES:
                yield self._flag(ctx, node)
            yield from descend(node, guarded)

        yield from descend(info.node, False)

    def _flag(self, ctx, node):
        return self.finding(
            ctx, node.lineno,
            "collective guarded by a condition whose rank-taint flows "
            "through a helper return (process_index via a function) — "
            "guarded ranks wait forever for peers that never arrive; "
            "make entry unconditional and broadcast the one-rank "
            "decision (docs/elastic.md)")


@register
class LeakedOpenSpan(Rule):
    code = "G20"
    name = "leaked-open-span"
    severity = "error"
    doc = ("A manually-managed trace span (`sp = trace.start_span(...)`)"
           " whose `.end()` is not reached on an exception path: no "
           "`with sp:` use, no `.end()` in a `finally:` of the same "
           "function, and no `finally:`-called same-module helper that "
           "ends the span passed to it (the summary engine maps "
           "argument positions through the call graph, the G17 "
           "leaked-acquire shape applied to spans). The first raise "
           "between the open and the straight-line `.end()` leaks the "
           "span: it never reaches the ring/journal, its children "
           "dangle, and the request it represents vanishes from every "
           "assembled timeline — the invisible twin of the latched "
           "probe slot. Ownership transfer is not a leak and is not "
           "flagged: a span stored on an object/container, returned, "
           "yielded, aliased, or handed to a callee that does not end "
           "it is ended by whoever owns it now (the serving request "
           "root's cross-thread lifecycle) — a resolved callee that "
           "DOES end the passed span is treated like a direct .end() "
           "at the call site, so a straight-line helper close is still "
           "a leak. Regression note: the first repo "
           "audit caught the router's hedge-arm span "
           "(serving/router.py) ending in try AND except but never in "
           "finally — restructured onto `with` in the same PR. Scope: "
           "mxnet_tpu/ library code.")

    _OPEN_LEAF = ".start_span"

    def _is_open(self, ctx, node) -> bool:
        if not isinstance(node, ast.Call):
            return False
        name = ctx.resolve(node.func)
        return bool(name) and (name == "start_span"
                               or name.endswith(self._OPEN_LEAF))

    # -- interproc half: which params does a function end? ---------------
    def _param_ends(self, index) -> dict:
        """``{fn_key: {param position}}`` on which ``.end()`` is called
        — directly, or by forwarding the param to a same-module callee
        that (transitively) ends it; monotone fixpoint, cycle-safe."""
        params = {k: [a.arg for a in (info.node.args.posonlyargs
                                      + info.node.args.args)]
                  for k, info in index.functions.items()}
        ends: dict = {k: set() for k in index.functions}
        changed = True
        while changed:
            changed = False
            for key, info in index.functions.items():
                names = params[key]
                if not names:
                    continue
                for node in sm._scope_walk(info.node):
                    if not isinstance(node, ast.Call):
                        continue
                    f = node.func
                    if isinstance(f, ast.Attribute) and f.attr == "end" \
                            and isinstance(f.value, ast.Name) \
                            and f.value.id in names:
                        i = names.index(f.value.id)
                        if i not in ends[key]:
                            ends[key].add(i)
                            changed = True
                    callee = cg.resolve_callee(index, node, info.cls, key)
                    if not callee or callee not in ends:
                        continue
                    cparams = params.get(callee, [])
                    off = 1 if cparams[:1] in (["self"], ["cls"]) \
                        and isinstance(f, ast.Attribute) else 0
                    for j, arg in enumerate(node.args):
                        if isinstance(arg, ast.Name) and arg.id in names \
                                and (j + off) in ends[callee]:
                            i = names.index(arg.id)
                            if i not in ends[key]:
                                ends[key].add(i)
                                changed = True
                    for kw in node.keywords:
                        if kw.arg and isinstance(kw.value, ast.Name) \
                                and kw.value.id in names \
                                and kw.arg in cparams \
                                and cparams.index(kw.arg) in ends[callee]:
                            i = names.index(kw.value.id)
                            if i not in ends[key]:
                                ends[key].add(i)
                                changed = True
        return ends

    # -- per-function analysis -------------------------------------------
    def check(self, ctx):
        if not ctx.is_library() or "start_span" not in ctx.src:
            return
        ms = sm.for_context(ctx)
        index = ms.index
        ends = self._param_ends(index)
        for info in index.functions.values():
            yield from self._check_fn(ctx, index, info, ends)

    def _check_fn(self, ctx, index, info, ends):
        opens: dict = {}       # name -> open line
        safe: set = set()      # exception-safe end / with-managed
        escaped: set = set()   # ownership transferred: not ours to end
        has_end: set = set()   # any .end() at all (message precision)

        def note_call(node, fin):
            """An ``x.end()`` / helper-forwarding call; returns the
            span names this call uses so the walker skips re-escaping
            them."""
            used: set = set()
            f = node.func
            if isinstance(f, ast.Attribute) and isinstance(f.value,
                                                           ast.Name):
                nm = f.value.id
                if nm in opens:
                    used.add(nm)
                    if f.attr == "end":
                        has_end.add(nm)
                        if fin:
                            safe.add(nm)
                    elif f.attr not in ("set_attrs", "context"):
                        escaped.add(nm)   # unknown method: hand off
            callee = cg.resolve_callee(index, node, info.cls, info.key)
            cparams = ([a.arg for a in
                        (index.functions[callee].node.args.posonlyargs
                         + index.functions[callee].node.args.args)]
                       if callee in index.functions else [])
            off = 1 if cparams[:1] in (["self"], ["cls"]) \
                and isinstance(f, ast.Attribute) else 0
            for j, arg in enumerate(node.args):
                if isinstance(arg, ast.Name) and arg.id in opens:
                    used.add(arg.id)
                    if callee and (j + off) in ends.get(callee, ()):
                        has_end.add(arg.id)
                        if fin:
                            safe.add(arg.id)
                    else:
                        escaped.add(arg.id)   # handed to an opaque callee
            for kw in node.keywords:
                if isinstance(kw.value, ast.Name) and kw.value.id in opens:
                    used.add(kw.value.id)
                    if callee and kw.arg and kw.arg in cparams \
                            and cparams.index(kw.arg) in ends.get(
                                callee, ()):
                        has_end.add(kw.value.id)
                        if fin:
                            safe.add(kw.value.id)
                    else:
                        escaped.add(kw.value.id)
            return used

        def walk(node, fin):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                return                    # separate scope
            if isinstance(node, ast.Try):
                for st in node.body:
                    walk(st, fin)
                for h in node.handlers:
                    for st in h.body:
                        walk(st, fin)
                for st in node.orelse:
                    walk(st, fin)
                for st in node.finalbody:
                    walk(st, True)
                return
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    ce = item.context_expr
                    if isinstance(ce, ast.Name) and ce.id in opens:
                        safe.add(ce.id)   # __exit__ ends it
                    elif self._is_open(ctx, ce):
                        pass              # `with start_span(...)`: safe
                    else:
                        walk(ce, fin)
                    if item.optional_vars is not None and \
                            self._is_open(ctx, ce):
                        ov = item.optional_vars
                        if isinstance(ov, ast.Name):
                            opens.setdefault(ov.id, ce.lineno)
                            safe.add(ov.id)
                for st in node.body:
                    walk(st, fin)
                return
            if isinstance(node, ast.Assign) and \
                    self._is_open(ctx, node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        opens.setdefault(t.id, node.value.lineno)
                    # an attribute/subscript target is ownership
                    # transfer at birth (the request object owns it)
                walk(node.value, fin)
                return
            if isinstance(node, ast.Call):
                used = note_call(node, fin)
                f = node.func
                # don't re-visit the receiver/arg Names note_call
                # already classified (the receiver Name nests inside
                # an Attribute — skipping the whole func node there)
                if not (isinstance(f, ast.Attribute)
                        and isinstance(f.value, ast.Name)
                        and f.value.id in used):
                    walk(f, fin)
                for arg in node.args:
                    if isinstance(arg, ast.Name) and arg.id in used:
                        continue
                    walk(arg, fin)
                for kw in node.keywords:
                    if isinstance(kw.value, ast.Name) \
                            and kw.value.id in used:
                        continue
                    walk(kw.value, fin)
                return
            if isinstance(node, ast.Name) and node.id in opens:
                # any other use — returned, yielded, stored, aliased,
                # in a container — transfers ownership
                escaped.add(node.id)
                return
            for child in ast.iter_child_nodes(node):
                walk(child, fin)

        for st in info.node.body:
            walk(st, False)
        for name, line in sorted(opens.items(), key=lambda kv: kv[1]):
            if name in safe or name in escaped:
                continue
            how = ("its .end() is never on a finally: path"
                   if name in has_end else "it is never .end()ed")
            yield self.finding(
                ctx, line,
                f"start_span() result {name!r} leaks on the exception "
                f"path — {how}, so the first raise loses the span (and "
                f"every child) from the assembled timeline; use "
                f"`with`, or end it in a finally: (a finally-called "
                f"helper that ends the passed span counts)")


@register
class SwallowedDurableWriteError(Rule):
    code = "G26"
    name = "swallowed-durable-write-error"
    severity = "error"
    doc = ("A broad exception handler (bare `except:`, `except "
           "Exception:`, `except BaseException:`) wrapped around a "
           "durable-write call chain — the protected code reaches a "
           "commit point (atomic_write, os.replace/os.rename, "
           "os.fsync, fsync_dir) directly or TRANSITIVELY through "
           "same-module helpers (the summary engine's reach set) — "
           "and the handler neither re-raises nor journals. The write "
           "that was supposed to outlive the process failed, and the "
           "process carried on as if it had landed: the checkpoint "
           "loader restores a step that was never committed, the "
           "heartbeat reader trusts a beat that never hit disk. The "
           "chaos tier's disk_full/io_error faults exist precisely to "
           "drive these paths — a swallowing handler turns every one "
           "of those injections into a silent no-op instead of a "
           "journaled degrade. A handler is fine if it re-raises "
           "(bare `raise` or `raise X`) or records the failure "
           "through the journal surface (`.event()`, `.crash()`, "
           "`.set_phase()`, `note_disk_full()`); a TYPED handler "
           "(`except OSError:`) is not flagged — naming the type is "
           "the visible contract G26 wants (resilience.retry's "
           "ENOSPC fail-fast is exactly that shape). Scope: "
           "mxnet_tpu/ library code.")

    BROAD = {"Exception", "BaseException"}
    # (kind, what) block facts that constitute a durability commit
    # point — plain open/read I/O stays G6/G21 territory
    DURABLE = {("file", w) for w in (
        "os.replace", "os.rename", "os.fsync",
        "atomic_write", "fsync_dir")}
    _JOURNAL_ATTRS = {"event", "crash", "set_phase"}

    def _is_broad(self, handler) -> bool:
        t = handler.type
        if t is None:
            return True
        names = t.elts if isinstance(t, ast.Tuple) else [t]
        return any(isinstance(e, ast.Name) and e.id in self.BROAD
                   for e in names)

    def _handler_recovers(self, handler) -> bool:
        """Re-raise or journal anywhere in the handler body (nested
        defs excluded — code in them does not run on this path)."""
        stack = list(handler.body)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            if isinstance(node, ast.Raise):
                return True
            if isinstance(node, ast.Call):
                f = node.func
                leaf = f.attr if isinstance(f, ast.Attribute) \
                    else (f.id if isinstance(f, ast.Name) else None)
                if leaf in self._JOURNAL_ATTRS \
                        or leaf == "note_disk_full":
                    return True
            stack.extend(ast.iter_child_nodes(node))
        return False

    def _durable_site(self, ctx, ms, index, info, stmts):
        """First durable write the protected statements reach:
        ``(line, what, via, op_line)`` — direct commit-point calls
        first, then same-module callees whose transitive reach set
        contains one."""
        transitive = None
        stack = list(stmts)
        while stack:
            node = stack.pop(0)
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            if isinstance(node, ast.Call):
                b = cg.classify_blocking(index, node)
                if b and (b[0], b[1]) in self.DURABLE:
                    return node.lineno, b[1], None, node.lineno
                callee = cg.resolve_callee(index, node, info.cls,
                                           info.key)
                if transitive is None and callee:
                    hits = sorted(self.DURABLE
                                  & set(ms.reach.get(callee, ())))
                    if hits:
                        path, op_line = ms.chain(callee, hits[0])
                        transitive = (node.lineno, hits[0][1],
                                      _chain_str(path) if path
                                      else callee, op_line)
            stack.extend(ast.iter_child_nodes(node))
        return transitive

    def check(self, ctx):
        if not ctx.is_library():
            return
        ms = sm.for_context(ctx)
        index = ms.index
        for info in index.functions.values():
            for node in sm._scope_walk(info.node):
                if not isinstance(node, ast.Try):
                    continue
                site = None
                for handler in node.handlers:
                    if not self._is_broad(handler) \
                            or self._handler_recovers(handler):
                        continue
                    if site is None:
                        # only the PROTECTED code counts (body + else)
                        site = self._durable_site(
                            ctx, ms, index, info,
                            list(node.body) + list(node.orelse))
                        if site is None:
                            break
                    _line, what, via, op_line = site
                    reach = f"{what} via {via}, line {op_line}" \
                        if via else what
                    yield self.finding(
                        ctx, handler.lineno,
                        f"broad except swallows a durable-write "
                        f"failure (the try body reaches {reach}) — "
                        f"the commit never landed and nothing will "
                        f"ever say so; narrow the catch to the "
                        f"expected type, re-raise, or journal the "
                        f"failure (.event/.crash/note_disk_full) "
                        f"before degrading")


@register
class DeadlineDropped(Rule):
    code = "G19"
    name = "deadline-dropped"
    severity = "warning"
    doc = ("A PUBLIC function accepts a deadline/timeout parameter but "
           "never reads it, while transitively reaching a blocking "
           "wait (sleep, tracked get/join/wait, socket, subprocess) "
           "through the call graph. The API *promises* a bounded wait "
           "and silently delivers an unbounded one — the caller's "
           "budget never reaches the thing that actually blocks, so a "
           "wedged dependency produces the same information-free hang "
           "the deadline existed to prevent (the G5/G13 class, hidden "
           "behind a signature). Thread the parameter through to every "
           "transitive wait (pass it down, or convert it to a "
           "monotonic deadline compared inside the loop); reads "
           "inside nested closures count. Regression note: this rule's "
           "first repo audit caught serving/pool.py "
           "ProcReplica.restart(deadline_s=...) accepting a deadline "
           "and running its whole stop ladder (socket roundtrip + "
           "three subprocess waits) on fixed constants — fixed in the "
           "same PR by threading the budget through every wait. "
           "Scope: mxnet_tpu/ library code.")

    def check(self, ctx):
        if not ctx.is_library():
            return
        ms = sm.for_context(ctx)
        for key, s in ms.functions.items():
            if not s.public or not s.deadline_params:
                continue
            unread = [p for p in s.deadline_params
                      if p not in s.deadline_read]
            if not unread:
                continue
            reached = ms.reach.get(key, ())
            waits = sorted(w for w in reached if w[0] in _WAIT_KINDS)
            if not waits:
                continue
            kind, what = waits[0]
            path, op_line = ms.chain(key, (kind, what))
            via = f" (reaches {what}, line {op_line}" + \
                (f", via {_chain_str(path)}" if path and len(path) > 1
                 else "") + ")"
            names = ", ".join(repr(p) for p in unread)
            yield self.finding(
                ctx, s.line,
                f"deadline parameter {names} accepted but never read "
                f"while the function transitively blocks{via} — the "
                f"caller's budget never reaches the wait; thread it "
                f"through or drop it from the signature")

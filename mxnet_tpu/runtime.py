"""``mx.runtime`` — build/runtime feature detection
(ref: python/mxnet/runtime.py Features/feature_list over libinfo.cc)."""
from __future__ import annotations

__all__ = ["Feature", "Features", "feature_list"]


class Feature:
    def __init__(self, name, enabled):
        self.name = name
        self.enabled = enabled

    def __repr__(self):
        return f"[{'✔' if self.enabled else '✖'} {self.name}]"


def _detect():
    from .diagnostics import guard
    feats = {}
    platforms = {d.platform for d in guard.devices()}
    # "axon" is the TPU tunnel platform name in this environment
    feats["TPU"] = bool(platforms & {"tpu", "axon"})
    feats["CUDA"] = bool(platforms & {"gpu", "cuda"})
    feats["CPU"] = True
    feats["BLAS_OPEN"] = True              # via XLA's host backend
    feats["F16C"] = True                   # bf16/fp16 via XLA
    try:
        import cv2  # noqa: F401
        feats["OPENCV"] = True
    except ImportError:
        feats["OPENCV"] = False
    try:
        from . import _native
        feats["NATIVE_IO"] = _native.get_lib() is not None
    except Exception:
        feats["NATIVE_IO"] = False
    feats["DIST_KVSTORE"] = True           # jax.distributed path
    try:
        from jax.experimental.pallas.ops.tpu import flash_attention  # noqa
        feats["PALLAS_FLASH_ATTENTION"] = True
    except ImportError:
        feats["PALLAS_FLASH_ATTENTION"] = False
    try:
        import onnx  # noqa: F401
        feats["ONNX"] = True
    except ImportError:
        feats["ONNX"] = False
    feats["INT8_QUANTIZATION"] = False     # calibration only this round
    return feats


class Features(dict):
    """ref: runtime.Features — dict of Feature with is_enabled()."""

    def __init__(self):
        super().__init__({name: Feature(name, on)
                          for name, on in _detect().items()})

    def is_enabled(self, name):
        name = name.upper()
        return name in self and self[name].enabled

    def __repr__(self):
        return "[" + ", ".join(repr(f) for f in self.values()) + "]"


def feature_list():
    return list(Features().values())

"""Gluon Block / HybridBlock.

TPU-native re-design of the reference's module system
(ref: python/mxnet/gluon/block.py — Block, HybridBlock, SymbolBlock).

The reference's ``hybridize()`` traces a block into an NNVM graph executed by
``CachedOp`` (ref: src/imperative/cached_op.cc). Here ``hybridize()`` lowers
the block to **one jitted XLA program** via ``jax.jit`` — the mapping SURVEY
§7 calls the most natural in the whole port. Details of the design:

- the traced function takes ``(rng_key, trainable_params, aux_params,
  *inputs)`` so randomness is threaded explicitly (TPU-idiomatic) and XLA
  sees parameters as runtime arguments (no retrace when values change);
- auxiliary state updated during forward (BatchNorm running stats) is
  returned as extra outputs and written back after the call — mutation is
  hoisted out of the pure program;
- under ``autograd.record()`` the whole jitted program records ONE tape node
  whose pullback is the XLA-compiled transpose, so backward is compiled too;
- ``static_alloc``/``static_shape`` flags are accepted for API compatibility
  (XLA's jit cache + buffer assignment already provide both).
"""
from __future__ import annotations

import re
import threading
from collections import OrderedDict

import jax
import numpy as np

from .. import _rng, autograd
from .. import ndarray as nd
from ..base import MXNetError, _as_np_dtype
from ..context import Context, current_context
from .parameter import (DeferredInitializationError, Parameter, ParameterDict)

__all__ = ["Block", "HybridBlock", "SymbolBlock", "functional_apply"]


def functional_apply(block, key, tr_datas, aux_datas, input_datas,
                     training=True, ctx=None):
    """Run a Gluon block as a pure function of its parameter arrays.

    This is the predictor-extraction primitive — the bridge between the
    mutable Gluon world and functional XLA shared by the sharded/pipelined
    trainers (``parallel/``) and the serving predictor cache
    (``serving/cache.py``): parameter handles are temporarily rebound to
    the traced arrays, the block runs eagerly (every op dispatches to jnp
    on tracers), and the handles are restored. Returns ``(out_datas,
    out_treedef, aux_new_datas)``; auxiliary state (BatchNorm running
    stats) is captured from the rebound handles — mutation hoisted into
    explicit outputs.
    """
    trainable, aux = block._param_split()
    if ctx is None:
        ctx = current_context()
    saved = []
    temps = {}
    for param, data in list(zip(trainable, tr_datas)) + \
            list(zip(aux, aux_datas)):
        saved.append((param, param._data))
        arr = nd.NDArray(data, ctx=ctx, _skip_device_put=True)
        temps[id(param)] = arr
        param._data = [arr] * len(param._ctx_list or [ctx])
    try:
        # trace with recording OFF — a jitted program is differentiated
        # as one unit from outside, never via the eager tape
        with _rng.trace_key(key), autograd.pause(train_mode=training):
            out = Block.__call__(block, *[
                nd.NDArray(d, ctx=ctx, _skip_device_put=True)
                if not isinstance(d, nd.NDArray) else d
                for d in input_datas])
        out_flat, treedef = jax.tree_util.tree_flatten(
            out, is_leaf=lambda x: isinstance(x, nd.NDArray))
        out_datas = [o._data if isinstance(o, nd.NDArray) else o
                     for o in out_flat]
        aux_new = [temps[id(p)]._data for p in aux]
    finally:
        for param, data in saved:
            param._data = data
    return out_datas, treedef, aux_new

_naming = threading.local()


def _counters():
    if not hasattr(_naming, "counts"):
        _naming.counts = {}
    return _naming.counts


class _BlockScope:
    """Auto prefix generation (ref: gluon/block.py _BlockScope)."""
    _current = threading.local()

    def __init__(self, block):
        self._block = block
        self._counter = {}
        self._old_scope = None

    @staticmethod
    def create(prefix, params, hint):
        current = getattr(_BlockScope._current, "value", None)
        if current is None:
            if prefix is None:
                counts = _counters()
                idx = counts.get(hint, 0)
                counts[hint] = idx + 1
                prefix = f"{hint}{idx}_"
            if params is None:
                params = ParameterDict(prefix)
            else:
                params = ParameterDict(params.prefix, shared=params)
            return prefix, params
        if prefix is None:
            idx = current._counter.get(hint, 0)
            current._counter[hint] = idx + 1
            prefix = f"{hint}{idx}_"
        if params is None:
            parent = current._block.params
            params = ParameterDict(parent.prefix + prefix, shared=parent._shared)
        else:
            params = ParameterDict(params.prefix, shared=params)
        return current._block.prefix + prefix, params

    def __enter__(self):
        if self._block._empty_prefix:
            return self
        self._old_scope = getattr(_BlockScope._current, "value", None)
        _BlockScope._current.value = self
        return self

    def __exit__(self, *exc):
        if self._block._empty_prefix:
            return
        _BlockScope._current.value = self._old_scope


class Block:
    """Base class of all layers and models (ref: gluon/block.py Block)."""

    def __init__(self, prefix=None, params=None):
        self._empty_prefix = prefix == ""
        self._prefix, self._params = _BlockScope.create(
            prefix, params, self._alias())
        self._name = self._prefix[:-1] if self._prefix.endswith("_") \
            else self._prefix
        self._scope = _BlockScope(self)
        self._children = OrderedDict()
        self._reg_params = {}
        self._forward_hooks = []
        self._forward_pre_hooks = []

    def _alias(self):
        return self.__class__.__name__.lower()

    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._name

    @property
    def params(self) -> ParameterDict:
        return self._params

    def name_scope(self):
        return self._scope

    def __repr__(self):
        lines = [f"{self.__class__.__name__}("]
        for key, child in self._children.items():
            child_repr = repr(child).replace("\n", "\n  ")
            lines.append(f"  ({key}): {child_repr}")
        lines.append(")")
        return "\n".join(lines)

    def __setattr__(self, name, value):
        if isinstance(value, Block):
            existing = getattr(self, name, None)
            if isinstance(existing, Block):
                self._children.pop(name, None)
            self.register_child(value, name)
        elif isinstance(value, Parameter):
            self._reg_params[name] = value
        super().__setattr__(name, value)

    def register_child(self, block, name=None):
        if name is None:
            name = str(len(self._children))
        self._children[name] = block

    def register_forward_hook(self, hook):
        self._forward_hooks.append(hook)
        return hook

    def register_forward_pre_hook(self, hook):
        self._forward_pre_hooks.append(hook)
        return hook

    def apply(self, fn):
        for child in self._children.values():
            child.apply(fn)
        fn(self)
        return self

    def collect_params(self, select=None) -> ParameterDict:
        """All parameters of self + descendants (ref: Block.collect_params)."""
        ret = ParameterDict(self._params.prefix)
        if select is None:
            ret.update({p.name: p for p in self._reg_params.values()})
        else:
            pattern = re.compile(select)
            ret.update({p.name: p for p in self._reg_params.values()
                        if pattern.match(p.name)})
        for child in self._children.values():
            ret.update(child.collect_params(select))
        # include params registered directly on self.params (name_scope usage)
        if select is None:
            ret.update({name: p for name, p in self._params.items()})
        else:
            pattern = re.compile(select)
            ret.update({name: p for name, p in self._params.items()
                        if pattern.match(name)})
        return ret

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        self.collect_params().initialize(init, ctx, verbose, force_reinit)

    def cast(self, dtype):
        for child in self._children.values():
            child.cast(dtype)
        for param in self._reg_params.values():
            param.cast(dtype)
        self._on_cast(dtype)

    def _on_cast(self, dtype):
        pass

    def zero_grad(self):
        self.collect_params().zero_grad()

    # -- checkpointing (ref: Block.save_parameters / load_parameters) --------
    def _structural_names(self, prefix=""):
        """name → Parameter keyed by *structural* path ('0.weight'), the
        reference's load-anywhere format (ref: block.py
        _collect_params_with_prefix)."""
        out = OrderedDict()
        for attr, param in self._reg_params.items():
            out[prefix + attr] = param
        for name, p in self._params.items():
            # params registered directly on self.params inside name_scope
            key = name[len(self._params.prefix):] \
                if name.startswith(self._params.prefix) else name
            out.setdefault(prefix + key, p)
        for name, child in self._children.items():
            out.update(child._structural_names(prefix + name + "."))
        return out

    def save_parameters(self, filename, deduplicate=False):
        arg_dict = {}
        for key, param in self._structural_names().items():
            arg_dict[key] = param.data(param.list_ctx()[0])
        nd.save(filename, arg_dict)

    def load_parameters(self, filename, ctx=None, allow_missing=False,
                        ignore_extra=False, cast_dtype=False,
                        dtype_source="current"):
        loaded = nd.load(filename)
        if not isinstance(loaded, dict):
            raise MXNetError(f"{filename} is not a parameter dict file")
        self.load_dict(loaded, ctx=ctx, allow_missing=allow_missing,
                       ignore_extra=ignore_extra, cast_dtype=cast_dtype,
                       dtype_source=dtype_source, source=filename)

    def load_dict(self, loaded, ctx=None, allow_missing=False,
                  ignore_extra=False, cast_dtype=False,
                  dtype_source="current", source="<param dict>"):
        """Load parameters from an already-loaded name→NDArray dict (ref:
        gluon Block.load_dict). The in-memory half of ``load_parameters``
        — the serving hot-reload path applies checkpoint dicts through
        here so a swap needs no extra disk round trip. ``arg:``/``aux:``
        prefixes from ``HybridBlock.export`` artifacts are stripped."""
        if any(k.partition(":")[0] in ("arg", "aux") and ":" in k
               for k in loaded):
            loaded = {k.partition(":")[2] if ":" in k and
                      k.partition(":")[0] in ("arg", "aux") else k: v
                      for k, v in loaded.items()}
        params = self._structural_names()
        if ctx is None:
            ctx = [current_context()]
        if isinstance(ctx, Context):
            ctx = [ctx]
        for key, param in params.items():
            if key not in loaded:
                if not allow_missing:
                    raise MXNetError(f"parameter {key} missing from {source}")
                continue
            value = loaded[key]
            if cast_dtype and dtype_source == "current" and \
                    param.dtype is not None:
                value = nd.NDArray(value._data, ctx=value.ctx,
                                   dtype=param.dtype)
            param._load_init(value, ctx)
        if not ignore_extra:
            extra = set(loaded) - set(params)
            if extra:
                raise MXNetError(f"{source} has extra parameters "
                                 f"{sorted(extra)}; pass ignore_extra=True")

    save_params = save_parameters          # deprecated aliases kept
    load_params = load_parameters

    # -- execution -----------------------------------------------------------
    def __call__(self, *args, **kwargs):
        for hook in self._forward_pre_hooks:
            hook(self, args)
        out = self.forward(*args, **kwargs)
        for hook in self._forward_hooks:
            hook(self, args, out)
        return out

    def forward(self, *args, **kwargs):
        raise NotImplementedError

    def hybridize(self, active=True, **kwargs):
        """No-op on plain Blocks; recurses so nested HybridBlocks engage."""
        for child in self._children.values():
            child.hybridize(active, **kwargs)

    def _param_split(self):
        params = [p for p in self.collect_params().values()]
        trainable = [p for p in params if p.grad_req != "null"]
        aux = [p for p in params if p.grad_req == "null"]
        return trainable, aux

    def summary(self, *inputs):
        """Print a per-layer summary (ref: Block.summary), minimal edition."""
        lines = [f"{'Layer':<40}{'Output':<24}{'Params':<12}"]
        total = 0
        for name, param in self.collect_params().items():
            if param.shape and not param._shape_incomplete():
                count = int(np.prod(param.shape))
                total += count
                lines.append(f"{name:<40}{str(param.shape):<24}{count:<12}")
        lines.append(f"Total params: {total}")
        print("\n".join(lines))


class HybridBlock(Block):
    """A Block that can be lowered to one compiled XLA program
    (ref: gluon/block.py HybridBlock; CachedOp ≡ jax.jit per SURVEY §7)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._active = False
        self._cached_fns = {}
        self._flags = {}
        self._out_treedef = None

    def hybridize(self, active=True, static_alloc=False, static_shape=False,
                  inline_limit=2, forward_bulk_size=None,
                  backward_bulk_size=None):
        self._active = active
        self._flags = {"static_alloc": static_alloc,
                       "static_shape": static_shape}
        self._cached_fns = {}
        for child in self._children.values():
            child.hybridize(active, static_alloc=static_alloc,
                            static_shape=static_shape)

    def _clear_cached_op(self):
        self._cached_fns = {}

    def infer_shape(self, *args):
        """Set shapes of this block's deferred params from input shapes.
        Leaf layers override; containers resolve via their children."""
        if self._reg_params and any(
                p._deferred_init for p in self._reg_params.values()):
            raise MXNetError(
                f"{self.__class__.__name__} has deferred-init parameters but "
                f"does not implement infer_shape()")

    def _deferred_pending(self):
        return any(p._deferred_init for p in self._reg_params.values())

    def _finish_deferred(self, *args):
        self.infer_shape(*args)
        for param in self._reg_params.values():
            param._finish_deferred_init()

    def forward(self, *args, **kwargs):
        """Gather this block's registered params and run ``hybrid_forward``.
        Symbol inputs trace symbolically (F = mx.sym, params become
        variables) — the reference's dual-world dispatch."""
        from .. import symbol as sym_mod
        if args and isinstance(args[0], sym_mod.Symbol):
            params = {name: p.var()
                      for name, p in self._reg_params.items()}
            return self.hybrid_forward(sym_mod, *args, **kwargs, **params)
        if self._deferred_pending():
            self._finish_deferred(*args)
        ctx = None
        for a in args:
            if isinstance(a, nd.NDArray):
                ctx = a.ctx
                break
        try:
            params = {name: p.data(ctx)
                      for name, p in self._reg_params.items()}
        except DeferredInitializationError:
            self._finish_deferred(*args)
            params = {name: p.data(ctx)
                      for name, p in self._reg_params.items()}
        return self.hybrid_forward(nd, *args, **kwargs, **params)

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError

    # -- the CachedOp equivalent ---------------------------------------------
    def __call__(self, *args, **kwargs):
        from .. import symbol as sym_mod
        if args and isinstance(args[0], sym_mod.Symbol):
            return super().__call__(*args, **kwargs)   # symbolic trace
        if args:
            self._num_inputs = len(args)
        if self._active and not _rng.in_trace():
            return self._call_cached(*args)
        return super().__call__(*args, **kwargs)

    def _ensure_ready(self, args):
        """Resolve every descendant's deferred init by a one-time eager pass."""
        pending = any(p._data is None
                      for p in self.collect_params().values())
        if pending:
            with autograd.pause():
                super().__call__(*args)

    def _build_fn(self, training, n_args, ctx):
        self_block = self

        def fn(rng_key, trainable_datas, aux_datas, *input_datas):
            out_datas, treedef, aux_new = functional_apply(
                self_block, rng_key, trainable_datas, aux_datas,
                list(input_datas), training=training, ctx=ctx)
            self_block._out_treedef = treedef
            return tuple(out_datas) + tuple(aux_new)
        return jax.jit(fn)

    def _call_cached(self, *args):
        self._ensure_ready(args)
        ctx = None
        for a in args:
            if isinstance(a, nd.NDArray):
                ctx = a.ctx
                break
        if ctx is None:
            ctx = current_context()
        training = autograd.is_training()
        from .. import _dispatch
        key = (training, len(args), str(ctx), _dispatch.amp_epoch())
        jitted = self._cached_fns.get(key)
        if jitted is None:
            jitted = self._build_fn(training, len(args), ctx)
            self._cached_fns[key] = jitted

        trainable, aux = self._param_split()
        idx = 0  # hybridized execution uses the primary context replica
        tr_datas = [p._data[idx]._data for p in trainable]
        aux_datas = [p._data[idx]._data for p in aux]
        in_datas = [a._data if isinstance(a, nd.NDArray) else
                    np.asarray(a) for a in args]
        rng_key = _rng.next_key()

        recording = autograd.is_recording() and (
            trainable or any(isinstance(a, nd.NDArray) and
                             (a._tape_node is not None or a._grad is not None)
                             for a in args))
        n_tr = len(tr_datas)
        if recording:
            def wrapped(*xs):
                res = jitted(rng_key, list(xs[:n_tr]), aux_datas,
                             *xs[n_tr:])
                # singleton outputs unpack so the TapeNode cotangent
                # convention (scalar ct for 1 output) matches the vjp tree
                return res[0] if len(res) == 1 else res
            out_all, vjp_fn = jax.vjp(wrapped, *(tr_datas + in_datas))
            if not isinstance(out_all, tuple):
                out_all = (out_all,)
            parents = [(None, 0, p._data[idx]) for p in trainable]
            for a in args:
                if isinstance(a, nd.NDArray) and a._grad is not None:
                    parents.append((None, 0, a))
                elif isinstance(a, nd.NDArray) and a._tape_node is not None:
                    parents.append((a._tape_node, a._tape_out_idx, None))
                else:
                    parents.append((None, 0, None))
            avals = [jax.ShapeDtypeStruct(o.shape, o.dtype) for o in out_all]
            fwd_inputs = [p._data[idx] for p in trainable] + [
                a if isinstance(a, nd.NDArray) else d
                for a, d in zip(args, in_datas)]
            node = autograd.TapeNode(vjp_fn, parents, avals,
                                     fwd_fn=wrapped, fwd_inputs=fwd_inputs)
        else:
            out_all = jitted(rng_key, tr_datas, aux_datas, *in_datas)
            node = None

        n_aux = len(aux)
        n_out = len(out_all) - n_aux
        out_datas = out_all[:n_out]
        aux_new = out_all[n_out:]
        for param, new in zip(aux, aux_new):
            param._data[idx]._rebind(new)

        outs = []
        for i, data in enumerate(out_datas):
            arr = nd.NDArray(data, ctx=ctx, _skip_device_put=True)
            if node is not None:
                arr._tape_node = node
                arr._tape_out_idx = i
            outs.append(arr)
        if self._out_treedef is not None:
            return jax.tree_util.tree_unflatten(self._out_treedef, outs)
        return outs[0] if len(outs) == 1 else tuple(outs)

    # -- deployment (ref: HybridBlock.export → -symbol.json + .params) -------
    def export(self, path, epoch=0, remove_amp_cast=True):
        """Serialize for deployment: trace the block symbolically into a
        real ``path-symbol.json`` graph (loadable by SymbolBlock.imports /
        mx.sym.load — the reference's deployment contract, SURVEY §3.5) +
        ``path-%04d.params`` weights with arg:/aux: keys."""
        from .. import symbol as sym_mod
        n = getattr(self, "_num_inputs", 1)
        names = ["data"] if n == 1 else [f"data{i}" for i in range(n)]
        out = self(*[sym_mod.var(nm) for nm in names])
        if isinstance(out, (list, tuple)):
            out = sym_mod.Group(list(out))
        out.save(f"{path}-symbol.json")
        params = {}
        for name, param in self.collect_params().items():
            params[("arg:" if param.grad_req != "null" else "aux:") + name] = \
                param.data(param.list_ctx()[0])
        nd.save(f"{path}-{epoch:04d}.params", params)
        return f"{path}-symbol.json", f"{path}-{epoch:04d}.params"


class SymbolBlock(HybridBlock):
    """Runs a loaded Symbol graph as a Gluon block (ref: gluon
    SymbolBlock): the deployment path for ``HybridBlock.export`` /
    ``mx.model.save_checkpoint`` artifacts."""

    def __init__(self, outputs, inputs, params=None):
        super().__init__(prefix="", params=params)
        if isinstance(outputs, (list, tuple)):
            from .. import symbol as sym_mod
            outputs = sym_mod.Group(list(outputs))
        self._outputs = outputs
        self._inputs = inputs
        input_names = {s.name for s in inputs}
        aux = set(outputs.list_auxiliary_states())
        for name in (outputs.list_arguments()
                     + outputs.list_auxiliary_states()):
            if name in input_names or name in self._params:
                continue
            self.params.get(name, grad_req="null" if name in aux
                            else "write", allow_deferred_init=True)

    @staticmethod
    def imports(symbol_file, input_names, param_file=None, ctx=None):
        from .. import symbol as sym_mod
        symbol = sym_mod.load(symbol_file)
        if isinstance(input_names, str):
            input_names = [input_names]
        inputs = [sym_mod.var(n) for n in input_names]
        block = SymbolBlock(symbol, inputs)
        if param_file:
            block.collect_params().load(param_file, ctx=ctx,
                                        allow_missing=False,
                                        ignore_extra=True)
        return block

    def forward(self, *args):
        from .. import symbol as sym_mod
        return sym_mod.eval_symbol(self._outputs, self._inputs, args,
                                   self.collect_params())

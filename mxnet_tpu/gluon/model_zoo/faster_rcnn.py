"""Faster R-CNN (driver config #5's second family; ref ecosystem:
gluoncv model_zoo faster_rcnn + the reference's example/rcnn pipeline:
src/operator/contrib/proposal.cc, roi_align.cc; rcnn/core targets).

TPU-first composition out of the contrib op set that already exists:
anchors + RPN head → ``F.contrib.Proposal`` (decode/filter/NMS, static
shapes, vmapped) → ``F.contrib.ROIAlign`` over fixed-topN RoIs → the
box head. Target assignment for BOTH stages reuses the tested
``F.contrib.MultiBoxTarget`` matcher (IoU matching + variance-encoded
box regression — the same math the reference's rcnn sample_rois /
assign_anchor do, SSD-style batched instead of per-image loops).
Everything is static-shape: padded proposals carry batch_idx -1 and are
masked out of the loss.
"""
from __future__ import annotations

import jax
import numpy as np

from ...base import MXNetError
from .. import nn
from ..block import HybridBlock
from ..loss import Loss

__all__ = ["FasterRCNN", "FasterRCNNLoss", "rpn_anchors",
           "faster_rcnn_resnet"]


def rpn_anchors(height, width, feature_stride=16,
                scales=(8.0, 16.0, 32.0), ratios=(0.5, 1.0, 2.0)):
    """All RPN anchors for an (height, width) feature map, PIXEL corner
    coords (A*H*W, 4) — bit-identical to the Proposal op's generation
    (ref: proposal.cc GenerateAnchors, legacy (w-1)/2 extents), so loss
    targets and proposal decode see the SAME anchors."""
    base = []
    c = (feature_stride - 1) / 2.0
    base_size = float(feature_stride)
    for r in ratios:
        size = base_size * base_size / r
        ws = np.sqrt(size)
        hs = ws * r
        for s in scales:
            bw, bh = ws * s, hs * s
            base.append([c - (bw - 1) / 2, c - (bh - 1) / 2,
                         c + (bw - 1) / 2, c + (bh - 1) / 2])
    base = np.asarray(base, np.float32)                    # (A, 4)
    sx = np.arange(width, dtype=np.float32) * feature_stride
    sy = np.arange(height, dtype=np.float32) * feature_stride
    shift = np.stack(np.meshgrid(sx, sy), axis=-1).reshape(-1, 2)
    shifts = np.concatenate([shift, shift], axis=1)        # (H*W, 4)
    all_anchors = (shifts[:, None, :] + base[None, :, :])
    return all_anchors.reshape(-1, 4)


class RPNHead(HybridBlock):
    """3x3 conv + twin 1x1 heads (ref: rcnn symbol rpn_conv/rpn_cls)."""

    def __init__(self, num_anchors, channels=256, **kwargs):
        super().__init__(**kwargs)
        self._a = num_anchors
        with self.name_scope():
            self.conv = nn.Conv2D(channels, 3, padding=1,
                                  activation="relu")
            self.cls = nn.Conv2D(2 * num_anchors, 1)
            self.bbox = nn.Conv2D(4 * num_anchors, 1)

    def hybrid_forward(self, F, x):
        t = self.conv(x)
        raw = self.cls(t)                    # (N, 2A, H, W)
        n, _, h, w = raw.shape
        # softmax over the bg/fg pair per anchor (reference reshapes to
        # (N, 2, A*H, W) and softmaxes the channel pair)
        prob = F.softmax(F.reshape(raw, (n, 2, -1)), axis=1)
        prob = F.reshape(prob, (n, 2 * self._a, h, w))
        return raw, prob, self.bbox(t)


class FasterRCNN(HybridBlock):
    """Two-stage detector over a feature backbone.

    forward(x, im_info) → (rois (N*topN, 5), cls_logits (N*topN, C+1),
    bbox_deltas (N*topN, 4), rpn_cls_raw, rpn_bbox_pred). Padded RoIs
    have batch_idx -1.
    """

    def __init__(self, features, classes, feature_stride=16,
                 scales=(8.0, 16.0, 32.0), ratios=(0.5, 1.0, 2.0),
                 roi_size=(7, 7), rpn_pre_nms_top_n=400,
                 rpn_post_nms_top_n=64, rpn_min_size=4,
                 head_units=256, **kwargs):
        super().__init__(**kwargs)
        self._classes = classes
        self._stride = feature_stride
        self._scales = tuple(float(s) for s in scales)
        self._ratios = tuple(float(r) for r in ratios)
        self._roi_size = tuple(roi_size)
        self._pre = rpn_pre_nms_top_n
        self._post = rpn_post_nms_top_n
        self._min_size = rpn_min_size
        a = len(scales) * len(ratios)
        with self.name_scope():
            self.features = features
            self.rpn = RPNHead(a, prefix="rpn_")
            self.head1 = nn.Dense(head_units, activation="relu",
                                  prefix="head1_")
            self.head2 = nn.Dense(head_units, activation="relu",
                                  prefix="head2_")
            self.cls_pred = nn.Dense(classes + 1, prefix="cls_")
            self.bbox_pred = nn.Dense(4, prefix="bbox_")

    def hybrid_forward(self, F, x, im_info):
        feat = self.features(x)
        rpn_raw, rpn_prob, rpn_bbox = self.rpn(feat)
        rois = F.contrib.Proposal(
            rpn_prob, rpn_bbox, im_info,
            rpn_pre_nms_top_n=self._pre, rpn_post_nms_top_n=self._post,
            rpn_min_size=self._min_size, scales=self._scales,
            ratios=self._ratios, feature_stride=self._stride)
        rois = F.stop_gradient(rois)     # proposals are fixed boxes
        pooled = F.contrib.ROIAlign(
            feat, rois, pooled_size=self._roi_size,
            spatial_scale=1.0 / self._stride)
        flat = F.Flatten(pooled)
        h = self.head2(self.head1(flat))
        return (rois, self.cls_pred(h), self.bbox_pred(h),
                rpn_raw, rpn_bbox)


class FasterRCNNLoss(Loss):
    """Joint RPN + RCNN loss (ref: rcnn multi-task loss — rpn softmax CE +
    rpn smooth-L1 + rcnn softmax CE + rcnn smooth-L1).

    ``forward(outputs, gt_label, im_shape)`` where outputs is
    FasterRCNN's tuple and gt_label is (N, M, 5) rows [cls, x0, y0, x1,
    y1] in PIXELS, padded with cls=-1.
    """

    def __init__(self, model, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._m = model
        self._anchor_cache = {}

    def hybrid_forward(self, F, outputs, gt_label, im_shape):
        rois, cls_logits, bbox_deltas, rpn_raw, rpn_bbox = outputs
        # im_shape must be STATIC (a plain (h, w) tuple): it sizes the
        # anchor constants. Everything downstream is F ops — the loss
        # traces under hybridize()/jit (round-4: divergence #12 closed;
        # the reference runs this matching in the MXProposalTarget C++ op,
        # src/operator/contrib/proposal_target.cc).
        n, _, fh, fw = rpn_raw.shape
        try:
            ih, iw = float(im_shape[0]), float(im_shape[1])
        except (TypeError, jax.errors.TracerArrayConversionError,
                jax.errors.ConcretizationTypeError):
            raise MXNetError(
                "FasterRCNNLoss: pass im_shape as a static (h, w) tuple "
                "— it parameterizes the anchor grid, which must be a "
                "trace-time constant") from None
        a = len(self._m._scales) * len(self._m._ratios)

        # ---- RPN targets: anchors vs gt (class-agnostic objectness).
        # Corners are extended by +1 before normalizing: MultiBoxTarget
        # encodes with corner widths (x2-x0) while the Proposal op
        # decodes with the legacy +1 widths — with BOTH anchors and gt
        # extended, the matcher's encoding becomes the exact inverse of
        # the decode (the +0.5 center shifts cancel). Cache is bounded:
        # keyed by feature shape, a handful of entries per model.
        key = (fh, fw, ih, iw)
        if key not in self._anchor_cache:
            if len(self._anchor_cache) >= 16:
                self._anchor_cache.pop(next(iter(self._anchor_cache)))
            anchors = rpn_anchors(fh, fw, self._m._stride,
                                  self._m._scales, self._m._ratios)
            norm_np = np.array([iw, ih, iw, ih], np.float32)
            ext = anchors + np.array([0, 0, 1, 1], np.float32)
            # cache device arrays: eager steps reuse them without a
            # re-upload; under jit they embed as constants
            self._anchor_cache[key] = (anchors.shape[0],
                                       F.array((ext / norm_np)[None]),
                                       F.array(norm_np))
        num_anchors, anc_norm, norm = self._anchor_cache[key]
        # gt preprocessing in-graph: objectness labels (0 for every real
        # box, -1 padding), legacy +1 extents, pixel → normalized coords
        gt_cls = F.slice_axis(gt_label, axis=-1, begin=0, end=1)
        gt_box = F.slice_axis(gt_label, axis=-1, begin=1, end=5)
        obj_cls = F.where(gt_cls >= 0, F.zeros_like(gt_cls),
                          -F.ones_like(gt_cls))
        ext_box = F.broadcast_add(
            gt_box, F.array(np.array([0, 0, 1, 1], np.float32)))
        gt_obj = F.concat(obj_cls, F.broadcast_div(ext_box, norm), dim=-1)
        # dummy cls_preds (N, A, 2) just threads through the matcher
        dummy = F.zeros((n, num_anchors, 2))
        # variances (1,1,1,1): the Proposal op decodes RAW deltas
        # (NonLinearTransformInv has no variance factor), so the targets
        # the RPN regresses toward must be unscaled
        rpn_loc_t, rpn_loc_m, rpn_cls_t = F.contrib.MultiBoxTarget(
            anc_norm, gt_obj, dummy,
            overlap_threshold=0.7, negative_mining_ratio=3.0,
            variances=(1.0, 1.0, 1.0, 1.0))
        # rpn_raw (N, 2A, H, W): per-anchor pair logits → (N, A*H*W, 2)
        rpn_logits = F.transpose(
            F.reshape(rpn_raw, (n, 2, a, fh * fw)), axes=(0, 3, 2, 1))
        rpn_logits = F.reshape(rpn_logits, (n, -1, 2))
        # MultiBoxTarget anchor order is (H*W, A); match it
        cls_t = rpn_cls_t
        ce = F.log_softmax(rpn_logits, axis=-1)
        picked = F.pick(ce, F.relu(cls_t), axis=-1)
        mask = (cls_t >= 0)
        rpn_cls_loss = -F.sum(picked * mask) / F.broadcast_maximum(
            F.sum(mask), F.ones((1,)))
        # Proposal reads bbox channels ANCHOR-major (channel c = a*4 +
        # coord, transpose(1,2,0).reshape(-1,4)); flatten identically so
        # the loss trains the layout the decoder consumes
        rpn_bbox_flat = F.reshape(F.transpose(
            rpn_bbox, axes=(0, 2, 3, 1)), (n, -1))
        rpn_loc_loss = F.sum(
            F.smooth_l1((rpn_bbox_flat - rpn_loc_t) * rpn_loc_m,
                        scalar=3.0)) / F.broadcast_maximum(
            F.sum(rpn_loc_m) / 4.0, F.ones((1,)))

        # ---- RCNN targets: proposals vs gt (per-class), fully in-graph —
        # per-image anchor sets via the batched MultiBoxTarget extension
        # (vmapped over rois AND gt; replaces the round-3 host loop)
        per = F.reshape(rois, (n, -1, 5))
        topn = per.shape[1]
        valid = F.cast(F.slice_axis(per, axis=-1, begin=0, end=1) >= 0,
                       "float32")
        valid = F.reshape(valid, (n, topn))               # (N, topn)
        roi_norm = F.broadcast_div(
            F.slice_axis(per, axis=-1, begin=1, end=5), norm)
        gt_n = F.concat(gt_cls, F.broadcast_div(gt_box, norm), dim=-1)
        logits = F.reshape(cls_logits, (n, topn, -1))
        deltas = F.reshape(bbox_deltas, (n, topn, 4))
        dummy2 = F.zeros((n, topn, self._m._classes + 1))
        loc_t, loc_m, cls_t2 = F.contrib.MultiBoxTarget(
            roi_norm, gt_n, dummy2,
            overlap_threshold=0.5, negative_mining_ratio=-1.0)
        ce2 = F.log_softmax(logits, axis=-1)              # (N, topn, C+1)
        cls_sel = F.pick(ce2, F.broadcast_maximum(
            cls_t2, F.zeros((1, 1))), axis=-1)            # (N, topn)
        nvalid = F.broadcast_maximum(F.sum(valid, axis=1), F.ones((1,)))
        rcnn_cls_loss = F.mean(-F.sum(cls_sel * valid, axis=1) / nvalid)
        lm = F.reshape(loc_m, (n, topn, 4)) * F.reshape(valid,
                                                        (n, topn, 1))
        lt = F.reshape(loc_t, (n, topn, 4))
        box_num = F.broadcast_maximum(
            F.sum(F.reshape(lm, (n, -1)), axis=1) / 4.0, F.ones((1,)))
        rcnn_box_loss = F.mean(F.sum(F.reshape(
            F.smooth_l1((deltas - lt) * lm, scalar=1.0),
            (n, -1)), axis=1) / box_num)
        return (rpn_cls_loss + rpn_loc_loss + rcnn_cls_loss
                + rcnn_box_loss)


def faster_rcnn_resnet(classes=20, **kwargs):
    """Small ResNet-backboned Faster R-CNN (thumbnail backbone truncated
    before global pooling; stride 16 at stage 3)."""
    from .vision import resnet18_v1
    backbone = resnet18_v1(classes=10)
    feat = nn.HybridSequential(prefix="backbone_")
    # features: [conv, bn, relu?, stages...]; keep through stage 3
    children = list(backbone.features._children.values())
    with feat.name_scope():
        for layer in children[:-2]:        # drop last stage + global pool
            feat.add(layer)
    return FasterRCNN(feat, classes, **kwargs)

"""BERT model family (GluonNLP parity: the reference ecosystem's
gluonnlp.model.bert — BERTEncoder/BERTModel and the bert_12_768_12 /
bert_24_1024_16 configurations that drive the driver's config #3).

TPU-first choices: attention runs through the blockwise flash-attention op
(ops/contrib.py _contrib_flash_attention) so long sequences stream through
VMEM; under a mesh the same model trains sequence-parallel via
mxnet_tpu.parallel.ring_attention; GELU/LayerNorm/Dense all lower to fused
XLA ops on the MXU.
"""
from __future__ import annotations


from ...base import MXNetError
from .. import nn
from ..block import HybridBlock

__all__ = ["MultiHeadAttention", "PositionwiseFFN", "TransformerEncoderCell",
           "BERTEncoder", "BERTModel", "bert_12_768_12", "bert_24_1024_16",
           "get_bert_model"]


class MultiHeadAttention(HybridBlock):
    """Self-attention with fused QKV projection (the reference ecosystem
    fuses via _contrib_interleaved_matmul_selfatt_*; here one Dense + the
    flash-attention op)."""

    def __init__(self, units, num_heads, dropout=0.0, use_bias=True,
                 causal=False, attention_block_size=512, seq_parallel=False,
                 **kwargs):
        super().__init__(**kwargs)
        if units % num_heads:
            raise MXNetError(f"units {units} not divisible by num_heads "
                             f"{num_heads}")
        self._units = units
        self._num_heads = num_heads
        self._causal = causal
        self._block = attention_block_size
        if seq_parallel not in (False, True, "ring", "ulysses"):
            raise MXNetError(
                f"seq_parallel must be False, True/'ring', or 'ulysses'; "
                f"got {seq_parallel!r}")
        self._seq_parallel = seq_parallel
        with self.name_scope():
            self.qkv = nn.Dense(3 * units, flatten=False, use_bias=use_bias,
                                prefix="qkv_")
            self.proj = nn.Dense(units, flatten=False, use_bias=use_bias,
                                 prefix="proj_")
            self.dropout = nn.Dropout(dropout) if dropout else None

    def hybrid_forward(self, F, x, mask=None):
        # x: (B, S, C)
        h = self._num_heads
        d = self._units // h
        qkv = self.qkv(x)                                  # (B, S, 3C)
        if not self._seq_parallel:
            # single-program path: attention straight off the fused QKV in
            # (B, S, H, D) einsum layout — no permute copies (the
            # (3,B,H,S,D) chain cost ~6 GB/step, docs/perf_notes.md).
            # Shape-free (the op clamps block_size to the concrete S at
            # trace time) so the block exports symbolically.
            out = F.contrib.fused_self_attention(
                qkv, heads=h, causal=self._causal, block_size=self._block)
            out = self.proj(out)
            if self.dropout is not None:
                out = self.dropout(out)
            return out
        b, s, c = x.shape
        qkv = F.reshape(qkv, (b, s, 3, h, d))
        qkv = F.transpose(qkv, axes=(2, 0, 3, 1, 4))       # (3, B, H, S, D)
        q, k, v = qkv[0], qkv[1], qkv[2]
        # seq_parallel=True/'ring' → ring attention; 'ulysses' → the
        # all-to-all head-scatter variant (better when heads ≥ shards)
        if self._seq_parallel == "ulysses":
            out = F.contrib.ulysses_attention(q, k, v,
                                              causal=self._causal)
        else:
            out = F.contrib.ring_attention(q, k, v, causal=self._causal)
        out = F.transpose(out, axes=(0, 2, 1, 3))          # (B, S, H, D)
        out = F.reshape(out, (b, s, self._units))
        out = self.proj(out)
        if self.dropout is not None:
            out = self.dropout(out)
        return out


class PositionwiseFFN(HybridBlock):
    """ref ecosystem: gluonnlp PositionwiseFFN (GELU for BERT).

    Both halves ride the guarded pallas matmul-epilogue tier
    (docs/pallas.md): ffn_1's bias+gelu and ffn_2's bias+dropout each run
    as ONE pass over the matmul output (dropout-in-epilogue — the BERT
    MFU lever, docs/roadmap.md items 3-4) instead of separate bias /
    activation / mask ops. Same params, same math; non-fusable
    activations keep the classic layout."""

    def __init__(self, units, hidden_size, dropout=0.0, activation="gelu",
                 **kwargs):
        super().__init__(**kwargs)
        from ..nn.basic_layers import _EPILOGUE_ACTS
        fused_act = activation if activation in _EPILOGUE_ACTS else None
        with self.name_scope():
            self.ffn_1 = nn.Dense(hidden_size, flatten=False, prefix="ffn1_",
                                  activation=fused_act)
            if fused_act is not None:
                self.activation = None
            else:
                self.activation = nn.GELU() if activation == "gelu" else \
                    nn.Activation(activation)
            self.ffn_2 = nn.Dense(units, flatten=False, prefix="ffn2_",
                                  epilogue_dropout=dropout)

    def hybrid_forward(self, F, x):
        out = self.ffn_1(x)
        if self.activation is not None:
            out = self.activation(out)
        # dropout is folded into ffn_2's epilogue (epilogue_dropout=)
        return self.ffn_2(out)


class TransformerEncoderCell(HybridBlock):
    """Post-LayerNorm transformer cell (BERT arrangement)."""

    def __init__(self, units, hidden_size, num_heads, dropout=0.0,
                 causal=False, seq_parallel=False, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.attention = MultiHeadAttention(units, num_heads,
                                                dropout=dropout,
                                                causal=causal,
                                                seq_parallel=seq_parallel,
                                                prefix="attn_")
            self.ln1 = nn.LayerNorm(epsilon=1e-12, prefix="ln1_")
            self.ffn = PositionwiseFFN(units, hidden_size, dropout=dropout,
                                       prefix="ffn_")
            self.ln2 = nn.LayerNorm(epsilon=1e-12, prefix="ln2_")
            self.dropout = nn.Dropout(dropout) if dropout else None

    def hybrid_forward(self, F, x):
        att = self.attention(x)
        if self.dropout is not None:
            att = self.dropout(att)
        x = self.ln1(x + att)
        x = self.ln2(x + self.ffn(x))
        return x


class BERTEncoder(HybridBlock):
    """Stack of transformer cells (gluonnlp BERTEncoder parity)."""

    def __init__(self, num_layers, units, hidden_size, num_heads,
                 dropout=0.0, seq_parallel=False, **kwargs):
        super().__init__(**kwargs)
        self._num_layers = num_layers
        with self.name_scope():
            self.transformer_cells = nn.HybridSequential(prefix="cells_")
            with self.transformer_cells.name_scope():
                for _ in range(num_layers):
                    self.transformer_cells.add(TransformerEncoderCell(
                        units, hidden_size, num_heads, dropout=dropout,
                        seq_parallel=seq_parallel))

    def hybrid_forward(self, F, x):
        return self.transformer_cells(x)


class BERTModel(HybridBlock):
    """gluonnlp BERTModel parity: embeddings → encoder → (pooler, MLM,
    NSP) heads. forward(inputs, token_types) → (sequence_out, pooled_out)
    or with masked_positions → MLM scores."""

    def __init__(self, num_layers=12, units=768, hidden_size=3072,
                 num_heads=12, max_length=512, vocab_size=30522,
                 token_type_vocab_size=2, dropout=0.1,
                 use_pooler=True, use_decoder=True, use_classifier=True,
                 seq_parallel=False, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._use_pooler = use_pooler
        self._use_decoder = use_decoder
        self._use_classifier = use_classifier
        with self.name_scope():
            self.word_embed = nn.Embedding(vocab_size, units,
                                           prefix="word_embed_")
            self.token_type_embed = nn.Embedding(token_type_vocab_size,
                                                 units,
                                                 prefix="token_type_embed_")
            self.position_weight = self.params.get(
                "position_embed", shape=(max_length, units))
            self.embed_layer_norm = nn.LayerNorm(epsilon=1e-12,
                                                 prefix="embed_ln_")
            self.embed_dropout = nn.Dropout(dropout) if dropout else None
            self.encoder = BERTEncoder(num_layers, units, hidden_size,
                                       num_heads, dropout=dropout,
                                       seq_parallel=seq_parallel,
                                       prefix="encoder_")
            if use_pooler:
                self.pooler = nn.Dense(units, activation="tanh",
                                       flatten=False, prefix="pooler_")
            if use_decoder:
                self.decoder = nn.HybridSequential(prefix="decoder_")
                with self.decoder.name_scope():
                    self.decoder.add(nn.Dense(units, flatten=False,
                                              activation=None))
                    self.decoder.add(nn.GELU())
                    self.decoder.add(nn.LayerNorm(epsilon=1e-12))
                    self.decoder.add(nn.Dense(vocab_size, flatten=False))
            if use_classifier:
                self.classifier = nn.Dense(2, prefix="nsp_")

    def hybrid_forward(self, F, inputs, token_types=None,
                       masked_positions=None, position_weight=None):
        x = self.word_embed(inputs)
        if token_types is not None:
            x = x + self.token_type_embed(token_types)
        # shape-free position add (exports symbolically): slice the
        # (1, max_len, U) table along the sequence axis like x (B, S, U)
        pos = F.slice_like(F.expand_dims(position_weight, axis=0), x,
                           axes=(1,))
        x = F.broadcast_add(x, pos)
        x = self.embed_layer_norm(x)
        if self.embed_dropout is not None:
            x = self.embed_dropout(x)
        seq_out = self.encoder(x)
        outputs = [seq_out]
        if self._use_pooler:
            cls = F.squeeze(F.slice(seq_out, begin=(None, 0, None),
                                    end=(None, 1, None)), axis=1)
            pooled = self.pooler(cls)
            outputs.append(pooled)
            if self._use_classifier:
                outputs.append(self.classifier(pooled))
        if self._use_decoder:
            if masked_positions is not None:
                # per-row gather: picked[b, m] = seq_out[b, pos[b, m]];
                # batch indices built shape-free via arange_like so the
                # masked path also exports symbolically
                batch_idx = F.broadcast_like(
                    F.reshape(F.arange_like(masked_positions, axis=0),
                              (-1, 1)),
                    masked_positions)
                idx = F.stack(batch_idx, masked_positions, axis=0)
                picked = F.gather_nd(seq_out, idx)
                outputs.append(self.decoder(picked))
            else:
                outputs.append(self.decoder(seq_out))
        return tuple(outputs) if len(outputs) > 1 else outputs[0]


_bert_configs = {
    "bert_12_768_12": dict(num_layers=12, units=768, hidden_size=3072,
                           num_heads=12),
    "bert_24_1024_16": dict(num_layers=24, units=1024, hidden_size=4096,
                            num_heads=16),
}


def get_bert_model(model_name="bert_12_768_12", vocab_size=30522,
                   max_length=512, dropout=0.1, **kwargs):
    if model_name not in _bert_configs:
        raise MXNetError(f"unknown BERT config {model_name!r}; "
                         f"options: {sorted(_bert_configs)}")
    cfg = dict(_bert_configs[model_name])
    cfg.update(kwargs)
    return BERTModel(vocab_size=vocab_size, max_length=max_length,
                     dropout=dropout, **cfg)


def bert_12_768_12(**kwargs):
    return get_bert_model("bert_12_768_12", **kwargs)


def bert_24_1024_16(**kwargs):
    return get_bert_model("bert_24_1024_16", **kwargs)

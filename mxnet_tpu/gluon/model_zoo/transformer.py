"""Transformer encoder-decoder for NMT (Sockeye parity — the reference
ecosystem's sockeye.transformer drives driver config #4; MXNet 1.x itself
ships the fused attention ops it uses, src/operator/contrib/transformer.cc).

TPU-first: self/cross attention run through the blockwise flash-attention
op; the decoder trains teacher-forced with causal masking in ONE jitted
step (no BucketingModule needed — but Module+bucketing works too via the
shape-keyed jit cache); greedy decode keeps static shapes by scanning to
max_length.
"""
from __future__ import annotations

import math

import numpy as np

from .. import nn
from ..block import HybridBlock
from .bert import MultiHeadAttention, PositionwiseFFN

__all__ = ["TransformerEncoder", "TransformerDecoder", "TransformerModel",
           "transformer_base", "CrossAttention"]


class CrossAttention(HybridBlock):
    """Attention with separate query and memory inputs (decoder→encoder)."""

    def __init__(self, units, num_heads, dropout=0.0, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._heads = num_heads
        with self.name_scope():
            self.q_proj = nn.Dense(units, flatten=False, prefix="q_")
            self.kv_proj = nn.Dense(2 * units, flatten=False, prefix="kv_")
            self.proj = nn.Dense(units, flatten=False, prefix="out_")
            self.dropout = nn.Dropout(dropout) if dropout else None

    def hybrid_forward(self, F, x, mem):
        # shape-free (exports symbolically): the fused op splits heads and
        # K/V internally off the concrete trace shapes
        out = F.contrib.fused_cross_attention(
            self.q_proj(x), self.kv_proj(mem), heads=self._heads)
        out = self.proj(out)
        if self.dropout is not None:
            out = self.dropout(out)
        return out


class _EncoderCell(HybridBlock):
    def __init__(self, units, hidden_size, num_heads, dropout, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.attn = MultiHeadAttention(units, num_heads, dropout=dropout,
                                           prefix="attn_")
            self.ln1 = nn.LayerNorm(prefix="ln1_")
            self.ffn = PositionwiseFFN(units, hidden_size, dropout=dropout,
                                       activation="relu", prefix="ffn_")
            self.ln2 = nn.LayerNorm(prefix="ln2_")

    def hybrid_forward(self, F, x):
        x = self.ln1(x + self.attn(x))
        return self.ln2(x + self.ffn(x))


class _DecoderCell(HybridBlock):
    def __init__(self, units, hidden_size, num_heads, dropout, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.self_attn = MultiHeadAttention(units, num_heads,
                                                dropout=dropout, causal=True,
                                                prefix="self_")
            self.ln1 = nn.LayerNorm(prefix="ln1_")
            self.cross_attn = CrossAttention(units, num_heads,
                                             dropout=dropout,
                                             prefix="cross_")
            self.ln2 = nn.LayerNorm(prefix="ln2_")
            self.ffn = PositionwiseFFN(units, hidden_size, dropout=dropout,
                                       activation="relu", prefix="ffn_")
            self.ln3 = nn.LayerNorm(prefix="ln3_")

    def hybrid_forward(self, F, x, mem):
        x = self.ln1(x + self.self_attn(x))
        x = self.ln2(x + self.cross_attn(x, mem))
        return self.ln3(x + self.ffn(x))


def _positions(max_length, units):
    pos = np.arange(max_length)[:, None]
    dim = np.arange(0, units, 2)[None, :]
    angle = pos / np.power(10000.0, dim / units)
    enc = np.zeros((max_length, units), dtype=np.float32)
    enc[:, 0::2] = np.sin(angle)
    enc[:, 1::2] = np.cos(angle)
    return enc


class TransformerEncoder(HybridBlock):
    def __init__(self, num_layers, units, hidden_size, num_heads, dropout,
                 **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.cells = nn.HybridSequential(prefix="cells_")
            with self.cells.name_scope():
                for _ in range(num_layers):
                    self.cells.add(_EncoderCell(units, hidden_size,
                                                num_heads, dropout))

    def hybrid_forward(self, F, x):
        return self.cells(x)


class TransformerDecoder(HybridBlock):
    def __init__(self, num_layers, units, hidden_size, num_heads, dropout,
                 **kwargs):
        super().__init__(**kwargs)
        self._cells = []
        with self.name_scope():
            for i in range(num_layers):
                cell = _DecoderCell(units, hidden_size, num_heads, dropout,
                                    prefix=f"cell{i}_")
                self.register_child(cell, f"cell{i}")
                self._cells.append(cell)

    def hybrid_forward(self, F, x, mem):
        for cell in self._cells:
            x = cell(x, mem)
        return x


class TransformerModel(HybridBlock):
    """Sockeye-parity seq2seq transformer: forward(src, tgt) → logits
    (teacher forcing); ``translate`` runs greedy decode."""

    def __init__(self, src_vocab, tgt_vocab, num_layers=6, units=512,
                 hidden_size=2048, num_heads=8, max_length=512,
                 dropout=0.1, tie_weights=False, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._max_length = max_length
        with self.name_scope():
            self.src_embed = nn.Embedding(src_vocab, units,
                                          prefix="src_embed_")
            self.tgt_embed = nn.Embedding(tgt_vocab, units,
                                          prefix="tgt_embed_")
            self.encoder = TransformerEncoder(num_layers, units, hidden_size,
                                              num_heads, dropout,
                                              prefix="enc_")
            self.decoder = TransformerDecoder(num_layers, units, hidden_size,
                                              num_heads, dropout,
                                              prefix="dec_")
            self.output = nn.Dense(tgt_vocab, flatten=False, prefix="out_")
            self.dropout = nn.Dropout(dropout) if dropout else None
            # sinusoidal table as a Constant parameter: exports with the
            # model and keeps the embed path shape-free (slice_like)
            self.pos_weight = self.params.get_constant(
                "pos_embed", _positions(max_length, units))

    def _embed(self, F, tokens, embed, pos_weight):
        x = embed(tokens) * math.sqrt(self._units)
        pos = F.slice_like(F.expand_dims(pos_weight, axis=0), x, axes=(1,))
        x = F.broadcast_add(x, pos)
        if self.dropout is not None:
            x = self.dropout(x)
        return x

    def encode(self, src):
        from ... import ndarray as F
        return self.encoder(self._embed(F, src, self.src_embed,
                                        self.pos_weight.data()))

    def hybrid_forward(self, F, src, tgt, pos_weight=None):
        pos = pos_weight if pos_weight is not None else \
            self.pos_weight.data()
        mem = self.encoder(self._embed(F, src, self.src_embed, pos))
        dec = self.decoder(self._embed(F, tgt, self.tgt_embed, pos), mem)
        return self.output(dec)

    def translate(self, src, bos_id=1, eos_id=2, max_steps=None,
                  beam_size=1, length_penalty=1.0):
        """Greedy (``beam_size=1``) or beam-search decode (the Sockeye
        inference mode, ref ecosystem: sockeye.beam_search). Host-driven
        loop over eager decoder calls with static shapes per step.
        ``length_penalty`` is the (5+len)^a/(5+1)^a GNMT normalization
        exponent, applied at the FINAL best-hypothesis selection only —
        per-step pruning compares raw cumulative log-probs (a documented
        simplification vs Sockeye's normalized in-search ranking)."""
        from ... import ndarray as nd
        import numpy as onp
        max_steps = max_steps or min(self._max_length, 64)
        mem = self.encode(src)
        b = src.shape[0]
        if beam_size <= 1:
            # greedy decode as a contrib.while_loop over a FIXED (B, L)
            # token buffer (ref: the control-flow op rewrite directed by
            # src/operator/control_flow.cc parity): every step runs the
            # decoder at ONE static shape — a single XLA program instead
            # of max_steps growing-prefix compilations — and the causal
            # decoder mask makes position `step` independent of the
            # padding beyond it. Early exit when every row emitted EOS
            # is the loop condition, like the reference's imperative path.
            # the fixed buffer is embedded whole every step, so it must
            # fit the positional table: cap the decode length at
            # max_length rows (the growing-prefix loop hit the same
            # ceiling one token later)
            length = min(max_steps + 1, self._max_length)
            max_steps = length - 1
            tokens0 = nd.concat(
                nd.full((b, 1), bos_id, dtype="int32"),
                nd.zeros((b, max_steps), dtype="int32"), dim=1)
            step0 = nd.zeros((1,))
            finished0 = nd.zeros((b,))

            def decode_cond(step, tokens, finished):
                return (step < max_steps) * (finished.sum() < b)

            def decode_step(step, tokens, finished):
                dec = self.decoder(
                    self._embed(nd, tokens, self.tgt_embed,
                                self.pos_weight.data()), mem)
                # project only the current position (O(V) not O(L·V))
                dec_t = nd.take(dec, step.astype("int32"), axis=1)
                logits = self.output(dec_t)              # (B, 1, V)
                nxt = logits.reshape(b, -1).argmax(axis=-1)
                nxt = nd.where(finished, nd.full((b,), eos_id), nxt)
                col = nd.one_hot(step.astype("int32") + 1, depth=length)
                tokens = (tokens * (1 - col) +
                          nd.broadcast_mul(nxt.reshape(b, 1), col)) \
                    .astype("int32")
                finished = nd.broadcast_maximum(
                    finished, (nxt == eos_id).astype("float32"))
                return [], [step + 1, tokens, finished]

            _, (steps, tokens, _fin) = nd.contrib.while_loop(
                decode_cond, decode_step, [step0, tokens0, finished0],
                max_iterations=max_steps)
            return tokens.asnumpy()[:, 1:1 + int(steps.asnumpy()[0])]

        # beam search: expand memory to (B*K, Sk, C), track per-beam
        # cumulative log-probs; finished beams only extend with EOS at
        # zero added score
        k = int(beam_size)
        mem_k = mem.repeat(k, axis=0)       # on-device beam expansion
        tokens = onp.full((b * k, 1), bos_id, dtype=onp.int32)
        scores = onp.full((b, k), -onp.inf, onp.float64)
        scores[:, 0] = 0.0                    # first step: only beam 0 live
        finished = onp.zeros((b, k), bool)

        def lp(length):
            return ((5.0 + length) ** length_penalty) / \
                (6.0 ** length_penalty)

        for step in range(max_steps):
            tgt = nd.array(tokens)
            dec = self.decoder(self._embed(nd, tgt, self.tgt_embed,
                                           self.pos_weight.data()), mem_k)
            # last timestep only, sliced ON DEVICE: projecting and
            # log-softmaxing all t positions then shipping (B*K, t, V)
            # to host would be O(T²V) transfer for an O(TV) need
            dec_last = nd.slice_axis(dec, axis=1, begin=-1, end=None)
            logp = nd.log_softmax(self.output(dec_last),
                                  axis=-1).asnumpy()[:, 0]    # (B*K, V)
            v = logp.shape[-1]
            logp = logp.reshape(b, k, v)
            # finished beams: only EOS continuation, at no added cost
            fin_row = onp.full((v,), -onp.inf)
            fin_row[eos_id] = 0.0
            logp = onp.where(finished[:, :, None], fin_row[None, None],
                             logp)
            cand = scores[:, :, None] + logp               # (B, K, V)
            flat = cand.reshape(b, k * v)
            top = onp.argpartition(-flat, k, axis=1)[:, :k]
            beam_idx, tok_idx = top // v, top % v
            scores = onp.take_along_axis(flat, top, axis=1)
            # reorder histories and append the chosen tokens
            rows = (onp.arange(b)[:, None] * k + beam_idx).reshape(-1)
            tokens = onp.concatenate(
                [tokens[rows],
                 tok_idx.reshape(-1, 1).astype(onp.int32)], axis=1)
            finished = onp.take_along_axis(finished, beam_idx, axis=1) \
                | (tok_idx == eos_id)
            if finished.all():
                break
        # pick the best beam per sentence under the length penalty;
        # length = tokens up to and including the first EOS (full length
        # when no EOS was emitted — argmin alone conflates the two)
        gen = tokens.reshape(b, k, -1)[:, :, 1:]
        has_eos = (gen == eos_id).any(axis=2)
        first_eos = (gen == eos_id).argmax(axis=2)
        lengths = onp.where(has_eos, first_eos + 1, gen.shape[2])
        normed = scores / lp(onp.maximum(lengths, 1))
        best = normed.argmax(axis=1)
        out = tokens.reshape(b, k, -1)[onp.arange(b), best, 1:]
        return out


def transformer_base(src_vocab, tgt_vocab, **kwargs):
    """The Sockeye/`Attention is All You Need` base config."""
    cfg = dict(num_layers=6, units=512, hidden_size=2048, num_heads=8,
               dropout=0.1)
    cfg.update(kwargs)
    return TransformerModel(src_vocab, tgt_vocab, **cfg)

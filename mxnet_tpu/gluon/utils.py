"""Gluon utilities (ref: python/mxnet/gluon/utils.py)."""
from __future__ import annotations

import numpy as np

from .. import ndarray as nd
from ..base import MXNetError

__all__ = ["split_data", "split_and_load", "clip_global_norm", "check_sha1",
           "download"]


def split_data(data, num_slice, batch_axis=0, even_split=True):
    """Split one batch along ``batch_axis`` into ``num_slice`` pieces
    (ref: gluon/utils.py split_data). On TPU, prefer a sharded batch on a
    Mesh (mxnet_tpu.parallel) over per-device slices — this exists for
    script compatibility."""
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise MXNetError(
            f"batch size {size} not divisible by {num_slice} slices; pass "
            f"even_split=False")
    step = size // num_slice
    slices = []
    for i in range(num_slice):
        begin = i * step
        end = (i + 1) * step if i < num_slice - 1 else size
        slices.append(nd.slice_axis(data, axis=batch_axis, begin=begin,
                                    end=end))
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """Split a batch and load each slice onto one context
    (ref: gluon/utils.py split_and_load)."""
    if not isinstance(data, nd.NDArray):
        data = nd.array(data)
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [piece.as_in_context(ctx) for piece, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm, check_isfinite=True,
                     global_norm=None):
    """Rescale arrays so their joint L2 norm ≤ max_norm
    (ref: gluon/utils.py clip_global_norm).

    Device-side (docs/guardrails.md): the squared-sum reduction is ONE
    fused device computation — no per-array ``asscalar()`` pull.
    ``check_isfinite=True`` (the reference default) costs one scalar
    fetch of that norm — the API returns a float and warns on
    non-finite — and, since the norm is on the host anyway, arrays are
    only touched when clipping actually applies (scale < 1): the
    steady-state unclipped step costs zero array ops.
    ``check_isfinite=False`` is fully lazy for dense arrays — zero host
    syncs; the clip factor and the non-finite handling stay device-side
    (a non-finite norm scales by 1.0 via ``where`` — unclipped — since
    the caller's guard/skip path owns non-finite steps), the multiply
    is unconditional (no host branch exists to skip it), and the
    returned norm is a scalar NDArray. Row-sparse entries are the
    exception: their ``.data`` is host-resident, so scaling them
    necessarily fetches the scale once.

    ``global_norm`` feeds an already-computed global norm (e.g. the
    fused guard's step output) so clipping costs no extra reduction
    pass over the gradients."""
    import jax.numpy as jnp

    from ..guardrails import fused
    from ..ndarray.sparse import RowSparseNDArray
    if not arrays:
        raise MXNetError("clip_global_norm: empty array list")
    if global_norm is not None:
        norm_dev = jnp.asarray(
            global_norm._data if isinstance(global_norm, nd.NDArray)
            else global_norm).astype(jnp.float32)
    else:
        total = jnp.zeros((), jnp.float32)
        for arr in arrays:
            # row-sparse grads: only stored rows contribute (ref:
            # gluon/utils.py supports row_sparse grad clipping)
            data = arr.data if isinstance(arr, RowSparseNDArray) \
                else arr._data
            d32 = jnp.asarray(data).astype(jnp.float32)
            total = total + jnp.sum(d32 * d32)
        norm_dev = jnp.sqrt(total)
    if not check_isfinite:
        scale = fused.clip_scale(norm_dev, jnp.float32(max_norm))
        for arr in arrays:
            if isinstance(arr, RowSparseNDArray):
                data = np.asarray(arr.data)
                arr.data = data * np.asarray(scale).astype(data.dtype)
            else:
                arr *= nd.NDArray(scale.astype(arr._data.dtype),
                                  _skip_device_put=True)
        return nd.NDArray(norm_dev, _skip_device_put=True)
    norm = fused.host_fetch(norm_dev)[0]
    # a host float via the sanctioned chokepoint — G9 blesses it
    if not np.isfinite(norm):
        import warnings
        warnings.warn("clip_global_norm: non-finite gradient norm — "
                      "arrays left unclipped (enable guardrails to "
                      "skip-step instead; docs/guardrails.md)")
        return norm
    scale = max_norm / (norm + 1e-8)
    if scale < 1.0:
        for arr in arrays:
            if isinstance(arr, RowSparseNDArray):
                data = np.asarray(arr.data)
                arr.data = data * np.asarray(scale, data.dtype)
            else:
                arr *= scale
    return norm


def check_sha1(filename, sha1_hash):
    import hashlib
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                break
            sha1.update(chunk)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None, retries=5,
             verify_ssl=True):
    raise MXNetError("download() requires network access, which this "
                     "environment does not provide; place files locally and "
                     "load them directly")

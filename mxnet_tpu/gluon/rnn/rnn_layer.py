"""Fused recurrent layers (ref: python/mxnet/gluon/rnn/rnn_layer.py).

Parameters are stored per-layer/per-direction with the reference's names
(``l0_i2h_weight``, ``r0_h2h_bias``, …) so checkpoints port; the forward
packs them into the cuDNN-layout flat vector and calls the fused ``RNN`` op
(ops/nn.py — ``lax.scan`` over time, ref: src/operator/rnn.cc).
"""
from __future__ import annotations

from ...base import MXNetError
from ..block import HybridBlock

__all__ = ["RNN", "LSTM", "GRU"]


class _RNNLayer(HybridBlock):
    def __init__(self, hidden_size, num_layers, layout, dropout,
                 bidirectional, input_size, mode, gates,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(**kwargs)
        if layout not in ("TNC", "NTC"):
            raise MXNetError(f"invalid layout {layout!r}; must be TNC or NTC")
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._mode = mode
        self._gates = gates
        ng, ni, nh = gates, input_size, hidden_size
        with self.name_scope():
            for i in range(num_layers):
                for j in (["l", "r"] if bidirectional else ["l"]):
                    self._register_param(
                        f"{j}{i}_i2h_weight",
                        (ng * nh, ni if i == 0 else nh * self._dir),
                        i2h_weight_initializer)
                    self._register_param(f"{j}{i}_h2h_weight", (ng * nh, nh),
                                         h2h_weight_initializer)
                    self._register_param(f"{j}{i}_i2h_bias", (ng * nh,),
                                         i2h_bias_initializer)
                    self._register_param(f"{j}{i}_h2h_bias", (ng * nh,),
                                         h2h_bias_initializer)

    def _register_param(self, name, shape, init):
        p = self.params.get(name, shape=shape, init=init,
                            allow_deferred_init=True)
        self._reg_params[name] = p
        setattr(self, name, p)

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def infer_shape(self, x, *args):
        ni = x.shape[2] if self._layout == "TNC" else x.shape[-1]
        ng, nh = self._gates, self._hidden_size
        for j in (["l", "r"] if self._dir == 2 else ["l"]):
            getattr(self, f"{j}0_i2h_weight")._set_shape((ng * nh, ni))

    def begin_state(self, batch_size=0, func=None, **kwargs):
        from ... import ndarray as F
        if func is None:
            func = F.zeros
        states = []
        for info in self.state_info(batch_size):
            info = dict(info)
            shape = info.pop("shape")
            states.append(func(shape, **kwargs))
        return states

    def __call__(self, inputs, states=None, **kwargs):
        if states is None:
            batch = inputs.shape[self._layout.find("N")]
            states = self.begin_state(batch, ctx=inputs.ctx,
                                      dtype=inputs.dtype)
            skip_states = True
        else:
            skip_states = False
        if not isinstance(states, (list, tuple)):
            states = [states]
        # states unpack to separate positional args: the cached-op jit
        # boundary handles NDArray args, not python lists of them
        out = super().__call__(inputs, *states)
        if skip_states:
            return out[0]
        return out

    def hybrid_forward(self, F, inputs, *states, **params):
        if self._layout == "NTC":
            inputs = F.swapaxes(inputs, 0, 1)
        # pack cuDNN-layout flat vector: weights (layer-major, dir
        # interleaved, i2h then h2h), then biases in the same order
        dirs = ["l", "r"] if self._dir == 2 else ["l"]
        pieces = []
        for i in range(self._num_layers):
            for j in dirs:
                pieces.append(F.reshape(params[f"{j}{i}_i2h_weight"], (-1,)))
                pieces.append(F.reshape(params[f"{j}{i}_h2h_weight"], (-1,)))
        for i in range(self._num_layers):
            for j in dirs:
                pieces.append(params[f"{j}{i}_i2h_bias"])
                pieces.append(params[f"{j}{i}_h2h_bias"])
        flat = F.concat(*pieces, dim=0)
        rnn_args = [inputs, flat] + list(states)
        outs = F.RNN(*rnn_args, state_size=self._hidden_size,
                     num_layers=self._num_layers, mode=self._mode,
                     bidirectional=self._dir == 2, p=self._dropout,
                     state_outputs=True)
        out = outs[0]
        if self._layout == "NTC":
            out = F.swapaxes(out, 0, 1)
        return out, list(outs[1:])


class RNN(_RNNLayer):
    """Multi-layer Elman RNN (ref: rnn_layer.py RNN)."""

    def __init__(self, hidden_size, num_layers=1, activation="relu",
                 layout="TNC", dropout=0, bidirectional=False, input_size=0,
                 **kwargs):
        self._activation = activation
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size,
                         "rnn_relu" if activation == "relu" else "rnn_tanh",
                         1, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size)}]


class LSTM(_RNNLayer):
    """Multi-layer LSTM (ref: rnn_layer.py LSTM)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, "lstm", 4, **kwargs)

    def state_info(self, batch_size=0):
        shape = (self._num_layers * self._dir, batch_size, self._hidden_size)
        return [{"shape": shape}, {"shape": shape}]


class GRU(_RNNLayer):
    """Multi-layer GRU (ref: rnn_layer.py GRU)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, "gru", 3, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size)}]

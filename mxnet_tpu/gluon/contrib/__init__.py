"""gluon.contrib (ref: python/mxnet/gluon/contrib/__init__.py)."""
from . import nn
from . import estimator
from .nn import Concurrent, HybridConcurrent, Identity

__all__ = ["nn", "estimator", "Concurrent", "HybridConcurrent", "Identity"]

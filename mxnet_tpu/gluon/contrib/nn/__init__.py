"""gluon.contrib.nn (ref: python/mxnet/gluon/contrib/nn/basic_layers.py)."""
from __future__ import annotations

import numpy as np

from ....base import MXNetError
from ...block import HybridBlock
from ...nn import HybridSequential, Sequential, SyncBatchNorm

__all__ = ["Concurrent", "HybridConcurrent", "Identity", "MoEFFN",
           "SyncBatchNorm"]


class HybridConcurrent(HybridSequential):
    """Parallel children concatenated on ``axis``
    (ref: contrib/nn HybridConcurrent — Inception-style branches)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def hybrid_forward(self, F, x):
        return F.concat(*[block(x) for block in self._children.values()],
                        dim=self.axis)

    def forward(self, x):
        from ... import nn as _nn  # noqa: F401
        from .... import ndarray as F
        return F.concat(*[block(x) for block in self._children.values()],
                        dim=self.axis)


class Concurrent(Sequential):
    """Eager variant (ref: contrib/nn Concurrent)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def forward(self, x):
        from .... import ndarray as F
        return F.concat(*[block(x) for block in self._children.values()],
                        dim=self.axis)


class Identity(HybridBlock):
    """ref: contrib/nn Identity."""

    def hybrid_forward(self, F, x):
        return x


class MoEFFN(HybridBlock):
    """Top-k routed mixture-of-experts FFN as a drop-in Gluon layer
    (net-new TPU capability — the reference has no MoE layer; routing
    follows GShard/Switch, SURVEY §2.4 #32 expert-parallel row).

    Drop it where a ``PositionwiseFFN`` would go::

        ffn = gluon.contrib.nn.MoEFFN(units=512, hidden_size=2048,
                                      num_experts=8, k=2)
        net = ... ffn(x) ...                      # x: (B, T, units)
        mesh = parallel.make_mesh({"data": 1, "expert": 8})
        trainer = parallel.ShardedTrainer(net, loss, "adam", ...,
            mesh=mesh,
            param_rules=[(r".*expert_.*", PartitionSpec("expert"))])

    Under a mesh whose ``expert`` axis matches ``num_experts`` the forward
    dispatches tokens with two ``all_to_all``s and runs ONLY the local
    expert per device at ``capacity_factor`` buffer size
    (parallel.moe_apply_topk — per-device compute O(k·tokens/E)); on any
    other mesh (or eagerly on one device) it falls back to the dense
    formulation: every expert over every token, gate-weighted — same
    math except no capacity dropping, so tiny-scale runs are exact.

    Inside a ShardedTrainer step the Switch load-balancing loss is added
    to the training objective automatically (``aux_loss_weight`` times
    it; perfect balance ⇒ aux = k). Eager forwards additionally expose
    the concrete value as ``_last_aux_loss`` for logging — traced steps
    do NOT update it (a traced value would be a leaked tracer).
    """

    def __init__(self, units, hidden_size, num_experts, k=2,
                 capacity_factor=1.5, activation="gelu",
                 aux_loss_weight=0.01, expert_axis="expert", **kwargs):
        super().__init__(**kwargs)
        self._units, self._hidden = int(units), int(hidden_size)
        self._ne, self._k = int(num_experts), int(k)
        self._cf = float(capacity_factor)
        self._act = activation
        self.aux_loss_weight = float(aux_loss_weight)
        self._expert_axis = expert_axis
        self._last_aux_loss = None
        e, u, h = self._ne, self._units, self._hidden
        with self.name_scope():
            self.gate_weight = self.params.get("gate_weight", shape=(e, u))
            self.expert_w1 = self.params.get("expert_w1", shape=(e, u, h))
            self.expert_b1 = self.params.get("expert_b1", shape=(e, h),
                                             init="zeros")
            self.expert_w2 = self.params.get("expert_w2", shape=(e, h, u))
            self.expert_b2 = self.params.get("expert_b2", shape=(e, u),
                                             init="zeros")

    def _activate(self, h):
        import jax
        fns = {"gelu": jax.nn.gelu, "relu": jax.nn.relu,
               "tanh": jax.numpy.tanh}
        try:
            return fns[self._act](h)
        except KeyError:
            raise MXNetError(f"MoEFFN: unknown activation {self._act!r}; "
                             f"one of {sorted(fns)}")

    def hybrid_forward(self, F, x, gate_weight, expert_w1, expert_b1,
                       expert_w2, expert_b2):
        import jax
        import jax.numpy as jnp
        from .... import ndarray as nd_mod
        from ....parallel.mesh import current_mesh
        from ....parallel.moe import moe_apply_topk

        xd = x._data if isinstance(x, nd_mod.NDArray) else jnp.asarray(x)
        gw, w1, b1, w2, b2 = (a._data if isinstance(a, nd_mod.NDArray)
                              else jnp.asarray(a)
                              for a in (gate_weight, expert_w1, expert_b1,
                                        expert_w2, expert_b2))
        shape = xd.shape
        tok = xd.reshape(-1, shape[-1])
        gates = tok.astype(jnp.float32) @ gw.astype(jnp.float32).T  # (N, E)

        mesh = current_mesh()
        n_tok = tok.shape[0]
        axis_configured = self._expert_axis in mesh.axis_names
        size_ok = (axis_configured
                   and int(mesh.shape[self._expert_axis]) == self._ne)
        tokens_ok = n_tok % self._ne == 0
        use_a2a = size_ok and tokens_ok
        if axis_configured and not use_a2a:
            # the mesh asked for expert parallelism but the a2a path is
            # rejected: going dense silently would lose expert
            # parallelism AND change training numerics (no capacity
            # dropping) with no signal — the misconfiguration class
            # ADVICE r5 flags and elastic training (ROADMAP items 4/5)
            # cannot tolerate. Warn loudly; the forward still runs.
            import warnings
            if not size_ok:
                why = (f"mesh axis {self._expert_axis!r} has size "
                       f"{int(mesh.shape[self._expert_axis])} but "
                       f"num_experts={self._ne}")
            else:
                why = (f"token count {n_tok} is not divisible by "
                       f"num_experts={self._ne}")
            warnings.warn(
                f"MoEFFN: expert-parallel all-to-all path rejected "
                f"({why}); falling back to the DENSE formulation — "
                f"O(E·tokens) compute and different numerics (no "
                f"capacity dropping). Fix the mesh/batch shape, or use "
                f"a mesh without the {self._expert_axis!r} axis to "
                f"silence this.", RuntimeWarning, stacklevel=2)
        if use_a2a:
            def expert_fn(params_e, t):
                ew1, eb1, ew2, eb2 = params_e
                h = self._activate(t.astype(jnp.float32) @ ew1 + eb1)
                return h @ ew2 + eb2
            if not isinstance(xd, jax.core.Tracer):
                # eager call: stage operands onto the mesh (replicated) so
                # the shard_map sees mesh-addressable arrays; inside a
                # ShardedTrainer trace GSPMD handles placement instead
                from jax.sharding import NamedSharding, PartitionSpec
                rep = NamedSharding(mesh, PartitionSpec())
                tok, gates, w1, b1, w2, b2 = (
                    jax.device_put(a, rep)
                    for a in (tok, gates, w1, b1, w2, b2))
            y, aux, _ = moe_apply_topk(
                expert_fn, (w1, b1, w2, b2), gates, tok, k=self._k,
                capacity_factor=self._cf, mesh=mesh,
                axis_name=self._expert_axis)
            if not isinstance(xd, jax.core.Tracer):
                # bring the eager result home so downstream single-device
                # eager math doesn't mix committed device sets
                y = jax.device_put(np.asarray(y))
                aux = jax.device_put(np.asarray(aux))
        else:
            # dense fallback: every expert over every token, gate-weighted
            probs = jax.nn.softmax(gates, axis=-1)
            top_p, top_e = jax.lax.top_k(probs, self._k)
            if self._k > 1:
                top_p = top_p / jnp.maximum(
                    top_p.sum(-1, keepdims=True), 1e-9)
            onehot = jax.nn.one_hot(top_e, self._ne, dtype=jnp.float32)
            wgt = (onehot * top_p[..., None]).sum(1)        # (N, E)
            h = self._activate(jnp.einsum(
                "nd,edh->neh", tok.astype(jnp.float32), w1) + b1)
            ye = jnp.einsum("neh,ehd->ned", h, w2) + b2
            y = ((ye * wgt[..., None]).sum(1)).astype(xd.dtype)
            load = onehot.sum(1).mean(0)                     # (E,)
            importance = probs.mean(0)
            aux = self._ne * jnp.sum(load * importance)
        # trace channel for ShardedTrainer's objective (read-and-cleared by
        # _collect_aux_losses so no tracer outlives its trace); the public
        # _last_aux_loss only ever holds concrete values (eager forwards)
        self._trace_aux_loss = aux
        if not isinstance(aux, jax.core.Tracer):
            self._last_aux_loss = aux
        y = y.astype(xd.dtype).reshape(shape[:-1] + (self._units,))
        return nd_mod.NDArray(y, _skip_device_put=True)

"""``gluon.contrib.estimator`` — the high-level fit API (ref:
python/mxnet/gluon/contrib/estimator/estimator.py + event_handler.py):
``Estimator(net, loss, train_metrics, trainer).fit(train_data, val_data,
epochs)`` with the reference's event-handler protocol (TrainBegin /
EpochBegin / BatchBegin / BatchEnd / EpochEnd / TrainEnd) and its stock
handlers (logging, checkpoint, early stopping).

TPU-first: the step itself is the same autograd.record + Trainer.step
fused program every other trainer here uses; hybridize the net and each
bucket shape compiles once.
"""
from __future__ import annotations

import logging
import time

from ... import metric as _metric
from ...base import MXNetError
from ..trainer import Trainer
from .. import loss as gloss
from ..utils import split_and_load  # noqa: F401  (re-export parity)

__all__ = ["Estimator", "TrainBegin", "TrainEnd", "EpochBegin",
           "EpochEnd", "BatchBegin", "BatchEnd", "LoggingHandler",
           "CheckpointHandler", "EarlyStoppingHandler", "StopTraining"]


class StopTraining(Exception):
    """Raised by a handler to stop fit() (ref: event_handler.py)."""


class TrainBegin:
    def train_begin(self, estimator, *args, **kwargs):
        pass


class TrainEnd:
    def train_end(self, estimator, *args, **kwargs):
        pass


class EpochBegin:
    def epoch_begin(self, estimator, *args, **kwargs):
        pass


class EpochEnd:
    def epoch_end(self, estimator, *args, **kwargs):
        pass


class BatchBegin:
    def batch_begin(self, estimator, *args, **kwargs):
        pass


class BatchEnd:
    def batch_end(self, estimator, *args, **kwargs):
        pass


class LoggingHandler(TrainBegin, BatchEnd, EpochEnd, TrainEnd):
    """Periodic metric logging (ref: event_handler.py LoggingHandler)."""

    def __init__(self, log_interval=50):
        self.log_interval = log_interval
        self._batches = 0
        self._tic = None

    def train_begin(self, estimator, *args, **kwargs):
        self._tic = time.monotonic()
        logging.info("Training begin")

    def batch_end(self, estimator, *args, **kwargs):
        self._batches += 1
        if self.log_interval and self._batches % self.log_interval == 0:
            msg = " ".join(f"{n}={v:.4f}" for n, v in
                           (m.get() for m in estimator.train_metrics))
            logging.info("[batch %d] %s", self._batches, msg)

    def epoch_end(self, estimator, epoch=None, **kwargs):
        msg = " ".join(f"{n}={v:.4f}" for n, v in
                       (m.get() for m in estimator.train_metrics))
        val = " ".join(f"val_{n}={v:.4f}" for n, v in
                       (m.get() for m in estimator.val_metrics))
        logging.info("Epoch[%s] %s %s", epoch, msg, val)

    def train_end(self, estimator, *args, **kwargs):
        logging.info("Training end (%.1fs)", time.monotonic() - self._tic)


class CheckpointHandler(EpochEnd, TrainEnd):
    """Save params each epoch, track the best by a monitored metric
    (ref: event_handler.py CheckpointHandler)."""

    def __init__(self, model_dir, model_prefix="model", monitor=None,
                 mode="min", save_best=False):
        import os
        os.makedirs(model_dir, exist_ok=True)
        self.prefix = os.path.join(model_dir, model_prefix)
        self.monitor = monitor
        self.save_best = save_best
        if mode not in ("min", "max"):
            raise MXNetError(f"mode must be min/max, got {mode!r}")
        self._sign = 1.0 if mode == "min" else -1.0
        self._best = None

    def epoch_end(self, estimator, epoch=None, **kwargs):
        estimator.net.save_parameters(
            f"{self.prefix}-epoch{epoch}.params")
        if self.save_best and self.monitor is not None:
            name, value = self.monitor.get()
            score = self._sign * value
            if self._best is None or score < self._best:
                self._best = score
                estimator.net.save_parameters(f"{self.prefix}-best.params")

    def train_end(self, estimator, *args, **kwargs):
        estimator.net.save_parameters(f"{self.prefix}-final.params")


class EarlyStoppingHandler(EpochEnd):
    """Stop when the monitored metric stops improving (ref:
    event_handler.py EarlyStoppingHandler)."""

    def __init__(self, monitor, mode="min", patience=3, min_delta=0.0):
        self.monitor = monitor
        self.patience = patience
        self.min_delta = min_delta
        self._sign = 1.0 if mode == "min" else -1.0
        self._best = None
        self._bad = 0

    def epoch_end(self, estimator, epoch=None, **kwargs):
        import math
        name, value = self.monitor.get()
        if isinstance(value, float) and math.isnan(value):
            # monitor never updated (e.g. no val_data): no signal — do
            # not count it as "no improvement"
            logging.warning("EarlyStoppingHandler: monitor %r is NaN "
                            "(was it ever updated?); skipping", name)
            return
        score = self._sign * value
        if self._best is None or score < self._best - self.min_delta:
            self._best = score
            self._bad = 0
        else:
            self._bad += 1
            if self._bad > self.patience:
                raise StopTraining(
                    f"{name} stopped improving for {self._bad} epochs")


def _as_metrics(metrics):
    if metrics is None:
        return []
    if isinstance(metrics, _metric.EvalMetric):
        metrics = [metrics]
    return list(metrics)


class Estimator:
    """High-level train loop (ref: estimator.py Estimator): one batch =
    record → loss → backward → Trainer.step; metrics update per batch;
    handlers observe the reference's event points."""

    def __init__(self, net, loss, train_metrics=None, trainer=None,
                 val_metrics=None, val_loss=None):
        self.net = net
        if not isinstance(loss, gloss.Loss):
            raise MXNetError("loss must be a gluon Loss")
        self.loss = loss
        self.val_loss = val_loss or loss
        self.train_metrics = _as_metrics(train_metrics) or \
            [_metric.Accuracy()]
        import copy
        self.val_metrics = _as_metrics(val_metrics) or \
            [copy.deepcopy(m) for m in self.train_metrics]
        for m in self.val_metrics:
            m.reset()
        # validation loss is a first-class metric (the reference reports
        # it and early-stops on it); evaluate() feeds it from val_loss
        self._val_loss_metric = _metric.Loss(name="loss")
        self.val_metrics.append(self._val_loss_metric)
        self.trainer = trainer or Trainer(
            net.collect_params(), "adam", {"learning_rate": 1e-3})

    # -- internals ---------------------------------------------------------
    def _call(self, handlers, event, *args, **kwargs):
        for h in handlers:
            fn = getattr(h, event, None)
            if fn is not None:
                fn(self, *args, **kwargs)

    def _batch(self, batch):
        data, label = batch.data[0], batch.label[0]
        from ... import autograd
        with autograd.record():
            out = self.net(data)
            loss = self.loss(out, label)
        loss.backward()
        self.trainer.step(data.shape[0])
        for m in self.train_metrics:
            m.update([label], [out])
        return loss

    def evaluate(self, val_data, metrics=None):
        """ref: estimator.py evaluate — run val_data through the net,
        update ``metrics`` (default: self.val_metrics)."""
        metrics = _as_metrics(metrics) or self.val_metrics
        for m in metrics:
            m.reset()
        val_data.reset()
        for batch in val_data:
            out = self.net(batch.data[0])
            loss = self.val_loss(out, batch.label[0])
            for m in metrics:
                if m is self._val_loss_metric:
                    m.update(None, [loss])
                else:
                    m.update([batch.label[0]], [out])
        return [m.get() for m in metrics]

    def fit(self, train_data, val_data=None, epochs=1,
            event_handlers=None, batches=None):
        """ref: estimator.py fit(train_data, val_data, epochs) —
        ``batches`` caps steps per epoch (the reference's ``batches``
        argument for partial epochs)."""
        handlers = list(event_handlers or [])
        if not any(isinstance(h, LoggingHandler) for h in handlers):
            handlers.append(LoggingHandler())
        self._call(handlers, "train_begin")
        try:
            for epoch in range(epochs):
                for m in self.train_metrics:
                    m.reset()
                train_data.reset()
                self._call(handlers, "epoch_begin", epoch=epoch)
                for i, batch in enumerate(train_data):
                    if batches is not None and i >= batches:
                        break
                    self._call(handlers, "batch_begin", batch=batch)
                    loss = self._batch(batch)
                    self._call(handlers, "batch_end", batch=batch,
                               loss=loss)
                if val_data is not None:
                    self.evaluate(val_data)
                # every handler's epoch_end runs even when one asks to
                # stop (the reference's stop_training-flag protocol:
                # checkpoints/logs of the stopping epoch still happen)
                stop = None
                for h in handlers:
                    fn = getattr(h, "epoch_end", None)
                    if fn is None:
                        continue
                    try:
                        fn(self, epoch=epoch)
                    except StopTraining as e:
                        stop = e
                if stop is not None:
                    raise stop
        except StopTraining as e:
            logging.info("Stop training: %s", e)
        self._call(handlers, "train_end")
        return self

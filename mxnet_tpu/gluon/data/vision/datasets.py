"""Vision datasets (ref: python/mxnet/gluon/data/vision/datasets.py).

Download is unavailable in this environment (zero egress): every dataset
reads the standard files from a local ``root`` directory and raises a clear
error when absent.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np

from ....base import MXNetError
from ... import nn  # noqa: F401  (parity import)
from .. import dataset

__all__ = ["MNIST", "FashionMNIST", "CIFAR10", "CIFAR100",
           "ImageRecordDataset", "ImageFolderDataset"]


def _read_idx(path):
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        _, dtype_code, ndim = struct.unpack(">HBB", f.read(4))
        shape = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        dt = {8: np.uint8, 9: np.int8, 11: np.int16, 12: np.int32,
              13: np.float32, 14: np.float64}[dtype_code]
        return np.frombuffer(f.read(), dtype=dt).reshape(shape)


class _DownloadedDataset(dataset.Dataset):
    def __init__(self, root, transform):
        self._root = os.path.expanduser(root)
        self._transform = transform
        self._data = None
        self._label = None
        if not os.path.isdir(self._root):
            raise MXNetError(
                f"dataset root {self._root} does not exist; downloads are "
                f"disabled in this environment — place the standard files "
                f"there manually")
        self._get_data()

    def __getitem__(self, idx):
        from ... import ndarray as _nd_unused  # noqa: F401
        from .... import ndarray as nd
        x = nd.array(self._data[idx])
        y = self._label[idx]
        if self._transform is not None:
            return self._transform(x, y)
        return x, y

    def __len__(self):
        return len(self._label)

    def _get_data(self):
        raise NotImplementedError


class MNIST(_DownloadedDataset):
    """ref: datasets.py MNIST — reads train-images-idx3-ubyte(.gz) etc."""

    _files = {True: ("train-images-idx3-ubyte", "train-labels-idx1-ubyte"),
              False: ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte")}

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "mnist"),
                 train=True, transform=None):
        self._train = train
        super().__init__(root, transform)

    def _get_data(self):
        img_name, lbl_name = self._files[self._train]
        img_path = os.path.join(self._root, img_name)
        lbl_path = os.path.join(self._root, lbl_name)
        for p in (img_path, lbl_path):
            if not os.path.exists(p) and not os.path.exists(p + ".gz"):
                raise MXNetError(f"missing MNIST file {p}(.gz)")
        img_path = img_path if os.path.exists(img_path) else img_path + ".gz"
        lbl_path = lbl_path if os.path.exists(lbl_path) else lbl_path + ".gz"
        images = _read_idx(img_path)
        self._data = images.reshape(images.shape[0], images.shape[1],
                                    images.shape[2], 1)
        self._label = _read_idx(lbl_path).astype(np.int32)


class FashionMNIST(MNIST):
    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "fashion-mnist"),
                 train=True, transform=None):
        super().__init__(root=root, train=train, transform=transform)


class CIFAR10(_DownloadedDataset):
    """ref: datasets.py CIFAR10 — reads the python-pickle batches or the
    binary .bin format."""

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "cifar10"),
                 train=True, transform=None):
        self._train = train
        super().__init__(root, transform)

    def _batches(self):
        if self._train:
            return [f"data_batch_{i}" for i in range(1, 6)]
        return ["test_batch"]

    def _get_data(self):
        # accept either the pickled python version or raw .bin files
        pickle_dir = os.path.join(self._root, "cifar-10-batches-py")
        bin_dir = os.path.join(self._root, "cifar-10-batches-bin")
        tar = os.path.join(self._root, "cifar-10-python.tar.gz")
        if not os.path.isdir(pickle_dir) and os.path.exists(tar):
            with tarfile.open(tar) as tf:
                tf.extractall(self._root)
        datas, labels = [], []
        if os.path.isdir(pickle_dir):
            for name in self._batches():
                with open(os.path.join(pickle_dir, name), "rb") as f:
                    # graftlint: disable=G21 operator-placed standard dataset file
                    entry = pickle.load(f, encoding="latin1")
                datas.append(np.asarray(entry["data"], dtype=np.uint8)
                             .reshape(-1, 3, 32, 32))
                labels.append(np.asarray(entry["labels"], dtype=np.int32))
        elif os.path.isdir(bin_dir):
            names = [f"{b}.bin" for b in self._batches()]
            for name in names:
                raw = np.fromfile(os.path.join(bin_dir, name),
                                  dtype=np.uint8).reshape(-1, 3073)
                labels.append(raw[:, 0].astype(np.int32))
                datas.append(raw[:, 1:].reshape(-1, 3, 32, 32))
        else:
            raise MXNetError(f"no CIFAR-10 files found under {self._root}")
        self._data = np.concatenate(datas).transpose(0, 2, 3, 1)
        self._label = np.concatenate(labels)


class CIFAR100(_DownloadedDataset):
    """ref: datasets.py CIFAR100."""

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "cifar100"),
                 fine_label=False, train=True, transform=None):
        self._train = train
        self._fine = fine_label
        super().__init__(root, transform)

    def _get_data(self):
        pickle_dir = os.path.join(self._root, "cifar-100-python")
        name = "train" if self._train else "test"
        path = os.path.join(pickle_dir, name)
        if not os.path.exists(path):
            raise MXNetError(f"no CIFAR-100 files found under {self._root}")
        with open(path, "rb") as f:
            # graftlint: disable=G21 operator-placed standard dataset file
            entry = pickle.load(f, encoding="latin1")
        self._data = np.asarray(entry["data"], dtype=np.uint8) \
            .reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        key = "fine_labels" if self._fine else "coarse_labels"
        self._label = np.asarray(entry[key], dtype=np.int32)


class ImageRecordDataset(dataset.RecordFileDataset):
    """ref: datasets.py ImageRecordDataset — .rec of packed images."""

    def __init__(self, filename, flag=1, transform=None):
        super().__init__(filename)
        self._flag = flag
        self._transform = transform

    def __getitem__(self, idx):
        from .... import recordio
        from .... import ndarray as nd
        record = super().__getitem__(idx)
        header, img = recordio.unpack_img(record, self._flag)
        x = nd.array(img)
        label = header.label
        if self._transform is not None:
            return self._transform(x, label)
        return x, label


class ImageFolderDataset(dataset.Dataset):
    """ref: datasets.py ImageFolderDataset — root/class_x/xxx.jpg layout."""

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self._exts = [".jpg", ".jpeg", ".png"]
        self._list_images(self._root)

    def _list_images(self, root):
        self.synsets = []
        self.items = []
        for folder in sorted(os.listdir(root)):
            path = os.path.join(root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for filename in sorted(os.listdir(path)):
                if os.path.splitext(filename)[1].lower() in self._exts:
                    self.items.append((os.path.join(path, filename), label))

    def __getitem__(self, idx):
        import cv2
        from .... import ndarray as nd
        path, label = self.items[idx]
        img = cv2.imread(path, cv2.IMREAD_COLOR if self._flag
                         else cv2.IMREAD_GRAYSCALE)
        if img is None:
            raise MXNetError(f"failed to read image {path}")
        if self._flag:
            img = img[:, :, ::-1].copy()  # BGR→RGB
        x = nd.array(img)
        if self._transform is not None:
            return self._transform(x, label)
        return x, label

    def __len__(self):
        return len(self.items)

"""DataLoader (ref: python/mxnet/gluon/data/dataloader.py).

The reference forks worker *processes* that return batches through
shared-memory NDArrays (``kCPUShared``). On the TPU build workers are
*threads*: the heavy per-sample work (JPEG decode via cv2, numpy augment)
releases the GIL, batches assemble into pinned host numpy buffers, and the
device transfer happens once per batch (then overlapped by the prefetching
trainer). This is the idiomatic single-host TPU input pipeline; the
process-pool design would only re-buy what jax.device_put already gives.
"""
from __future__ import annotations

import queue
import threading

import numpy as np

from ...base import MXNetError
from ... import ndarray as nd
from .sampler import BatchSampler, RandomSampler, SequentialSampler

__all__ = ["DataLoader", "default_batchify_fn"]


def default_batchify_fn(data):
    """Stack samples into a batch (ref: dataloader.py default_batchify_fn)."""
    if isinstance(data[0], nd.NDArray):
        return nd.stack(*data, axis=0)
    if isinstance(data[0], (tuple, list)):
        return tuple(default_batchify_fn(list(x)) for x in zip(*data))
    arr = np.asarray(data)
    return nd.array(arr)


class DataLoader:
    """ref: dataloader.py DataLoader — same signature; thread workers."""

    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, prefetch=None,
                 thread_pool=True, timeout=120):
        self._dataset = dataset
        if batch_sampler is None:
            if batch_size is None:
                raise MXNetError("batch_size is required when batch_sampler "
                                 "is not given")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle else \
                    SequentialSampler(len(dataset))
            elif shuffle:
                raise MXNetError("shuffle must be False with custom sampler")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        elif batch_size is not None or shuffle or sampler is not None or \
                last_batch is not None:
            raise MXNetError("batch_size/shuffle/sampler/last_batch must not "
                             "be given with batch_sampler")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._num_workers = max(0, num_workers)
        self._prefetch = max(0, prefetch if prefetch is not None
                             else 2 * self._num_workers)

    def __len__(self):
        return len(self._batch_sampler)

    def _load_batch(self, indices):
        return self._batchify_fn([self._dataset[i] for i in indices])

    def __iter__(self):
        if self._num_workers == 0:
            for indices in self._batch_sampler:
                yield self._load_batch(indices)
            return
        # thread pool: batches computed ahead, delivered IN ORDER
        batches = list(self._batch_sampler)
        results = [None] * len(batches)
        done = [threading.Event() for _ in batches]
        task_q = queue.Queue(maxsize=max(len(batches), 1))
        for i, b in enumerate(batches):
            task_q.put((i, b))

        def worker():
            while True:
                try:
                    i, b = task_q.get_nowait()
                except queue.Empty:
                    return
                try:
                    results[i] = self._load_batch(b)
                except Exception as e:     # surface in consumer
                    results[i] = e
                done[i].set()

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(self._num_workers)]
        for t in threads:
            t.start()
        for i in range(len(batches)):
            done[i].wait()
            out = results[i]
            results[i] = None
            if isinstance(out, Exception):
                raise out
            yield out

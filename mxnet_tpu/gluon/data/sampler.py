"""Samplers (ref: python/mxnet/gluon/data/sampler.py)."""
from __future__ import annotations

import numpy as np

from ...base import MXNetError

__all__ = ["Sampler", "SequentialSampler", "RandomSampler", "BatchSampler",
           "SplitSampler"]


class Sampler:
    def __len__(self):
        raise NotImplementedError

    def __iter__(self):
        raise NotImplementedError


class SequentialSampler(Sampler):
    def __init__(self, length, start=0):
        self._length = length
        self._start = start

    def __iter__(self):
        return iter(range(self._start, self._start + self._length))

    def __len__(self):
        return self._length


class RandomSampler(Sampler):
    def __init__(self, length):
        self._length = length

    def __iter__(self):
        indices = np.arange(self._length)
        np.random.shuffle(indices)
        return iter(indices.tolist())

    def __len__(self):
        return self._length


class SplitSampler(Sampler):
    """Rank-sharded sampler: yields this worker's disjoint part of
    ``[0, length)`` — the DataLoader-side analog of the iterators'
    ``num_parts``/``part_index`` distributed read sharding (ref:
    src/io/iter_image_recordio_2.cc kwargs over dmlc InputSplit; the
    upstream example-zoo's SplitSampler idiom).

    With ``shuffle=True`` every rank permutes the FULL index space with a
    common (seed, epoch)-derived generator and then takes its contiguous
    slice — so each epoch's global order is one shared permutation,
    partitioned disjointly and exhaustively across ranks. ``num_parts``/
    ``part_index`` default to the launcher env (MXTPU_NUM_PROC /
    MXTPU_PROC_ID), so single-process runs see the whole dataset."""

    def __init__(self, length, num_parts=None, part_index=None,
                 shuffle=False, seed=0):
        from ...io import _part_bounds, _resolve_part
        self._length = int(length)
        self._num_parts, self._part_index = _resolve_part(num_parts,
                                                          part_index)
        self._shuffle = shuffle
        self._seed = int(seed)
        self._epoch = 0
        self._bounds = _part_bounds(self._length, self._num_parts,
                                    self._part_index)

    def set_epoch(self, epoch):
        """Pin the permutation epoch explicitly (DistributedSampler
        convention) — call it at the top of each epoch. The permutation
        seed derives ONLY from this explicitly tracked epoch: ``__iter__``
        deliberately does NOT auto-advance it, because any
        rank-asymmetric extra sweep (a batch-count pre-pass, an eval over
        train data, ``len(list(sampler))``) would silently desynchronize
        the shared permutation across ranks — duplicated and missing
        records with no signal (ADVICE r5; the exact divergence class
        elastic multi-host training cannot tolerate, ROADMAP item 4). A
        missed ``set_epoch`` now degrades to a repeated-but-consistent
        order instead of silent cross-rank desync."""
        self._epoch = int(epoch)

    def __iter__(self):
        if self._shuffle:
            rng = np.random.RandomState(
                (self._seed * 1000003 + self._epoch) & 0x7FFFFFFF)
            order = rng.permutation(self._length)
        else:
            order = np.arange(self._length)
        lo, hi = self._bounds
        return iter(order[lo:hi].tolist())

    def __len__(self):
        lo, hi = self._bounds
        return hi - lo


class BatchSampler(Sampler):
    """Groups a sampler into batches; last_batch keep/discard/rollover
    (ref: sampler.py BatchSampler)."""

    def __init__(self, sampler, batch_size, last_batch="keep"):
        self._sampler = sampler
        self._batch_size = batch_size
        self._last_batch = last_batch
        self._prev = []
        if last_batch not in ("keep", "discard", "rollover"):
            raise MXNetError(f"invalid last_batch {last_batch!r}")

    def __iter__(self):
        batch, self._prev = self._prev, []
        for i in self._sampler:
            batch.append(i)
            if len(batch) == self._batch_size:
                yield batch
                batch = []
        if batch:
            if self._last_batch == "keep":
                yield batch
            elif self._last_batch == "rollover":
                self._prev = batch

    def __len__(self):
        if self._last_batch == "keep":
            return (len(self._sampler) + self._batch_size - 1) \
                // self._batch_size
        if self._last_batch == "discard":
            return len(self._sampler) // self._batch_size
        return (len(self._sampler) + len(self._prev)) // self._batch_size

"""Gluon losses (ref: python/mxnet/gluon/loss.py).

Every loss is a HybridBlock so it fuses into the jitted training step."""
from __future__ import annotations


from ..base import MXNetError
from .block import HybridBlock

__all__ = ["Loss", "L2Loss", "L1Loss", "SigmoidBinaryCrossEntropyLoss",
           "SigmoidBCELoss", "SoftmaxCrossEntropyLoss", "SoftmaxCELoss",
           "KLDivLoss", "HuberLoss", "HingeLoss", "SquaredHingeLoss",
           "LogisticLoss", "TripletLoss", "CTCLoss", "CosineEmbeddingLoss"]


def _apply_weighting(F, loss, weight=None, sample_weight=None):
    """ref: gluon/loss.py _apply_weighting."""
    if sample_weight is not None:
        loss = F.broadcast_mul(loss, sample_weight)
    if weight is not None:
        loss = loss * weight
    return loss


def _reshape_like(F, x, y):
    return F.reshape_like(x, y) if x.shape != y.shape else x


class Loss(HybridBlock):
    """Base loss (ref: gluon/loss.py Loss)."""

    def __init__(self, weight, batch_axis, **kwargs):
        super().__init__(**kwargs)
        self._weight = weight
        self._batch_axis = batch_axis

    def __repr__(self):
        return (f"{self.__class__.__name__}(batch_axis={self._batch_axis}, "
                f"w={self._weight})")

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError

    def _mean_over_nonbatch(self, F, loss):
        axes = [a for a in range(loss.ndim) if a != self._batch_axis]
        return F.mean(loss, axis=tuple(axes)) if axes else loss


class L2Loss(Loss):
    """0.5 * (pred - label)^2 (ref: loss.py L2Loss)."""

    def __init__(self, weight=1.0, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.square(label - pred)
        loss = _apply_weighting(F, loss, self._weight / 2, sample_weight)
        return self._mean_over_nonbatch(F, loss)


class L1Loss(Loss):
    """|pred - label| (ref: loss.py L1Loss)."""

    def __init__(self, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.abs(label - pred)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_over_nonbatch(F, loss)


class SigmoidBinaryCrossEntropyLoss(Loss):
    """BCE with optional logits input (ref: loss.py SigmoidBCELoss)."""

    def __init__(self, from_sigmoid=False, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_sigmoid = from_sigmoid

    def hybrid_forward(self, F, pred, label, sample_weight=None,
                       pos_weight=None):
        label = _reshape_like(F, label, pred)
        if not self._from_sigmoid:
            if pos_weight is None:
                loss = F.relu(pred) - pred * label + \
                    F.Activation(-F.abs(pred), act_type="softrelu")
            else:
                log_weight = 1 + F.broadcast_mul(pos_weight - 1, label)
                loss = F.relu(pred) - pred * label + log_weight * \
                    (F.Activation(-F.abs(pred), act_type="softrelu") +
                     F.relu(-pred))
        else:
            eps = 1e-12
            if pos_weight is None:
                loss = -(F.log(pred + eps) * label
                         + F.log(1. - pred + eps) * (1. - label))
            else:
                loss = -(F.broadcast_mul(F.log(pred + eps) * label, pos_weight)
                         + F.log(1. - pred + eps) * (1. - label))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_over_nonbatch(F, loss)


SigmoidBCELoss = SigmoidBinaryCrossEntropyLoss


class SoftmaxCrossEntropyLoss(Loss):
    """Softmax + CE in one numerically-stable op; the single most common
    loss in reference training scripts (ref: loss.py SoftmaxCrossEntropyLoss).
    """

    def __init__(self, axis=-1, sparse_label=True, from_logits=False,
                 weight=None, batch_axis=0, label_smoothing=0.0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._axis = axis
        self._sparse_label = sparse_label
        self._from_logits = from_logits
        # Sockeye-style smoothed CE (ref ecosystem: sockeye.loss
        # CrossEntropyLoss(label_smoothing=...)): target mass (1-eps) on
        # the label, eps spread uniformly. Fused into the sparse path as
        # lse - (1-eps)·pred[y] - eps·mean(pred) — still no [.., C]
        # log-prob materialization.
        self._smoothing = float(label_smoothing)
        if self._smoothing and not sparse_label:
            raise MXNetError("label_smoothing requires sparse_label=True "
                             "(smooth dense label distributions yourself)")

    @property
    def amp_safe(self):
        """True when this loss does its own fp32-accumulated reductions on
        reduced-precision inputs, so callers (ShardedTrainer) may skip the
        fp32 pre-cast of model outputs. Only the fused sparse path
        qualifies; the generic paths do elementwise math in the input
        dtype and want fp32 inputs under AMP."""
        return self._sparse_label and not self._from_logits

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if self._sparse_label and not self._from_logits:
            # fused path: loss = lse(pred) - pred[label]. Never materializes
            # the [.., C] log-prob tensor — under bf16 AMP with a large
            # vocabulary the log_softmax intermediate dominates HBM traffic
            # (docs/perf_notes.md); the backward is softmax - onehot, fused
            # the same way (ref: src/operator/softmax_output.cc backward).
            lse = F.logsumexp(pred, axis=self._axis, keepdims=True)
            picked = F.pick(pred, label, axis=self._axis, keepdims=True)
            target = F.cast(picked, "float32")
            if self._smoothing:
                eps = self._smoothing
                # mean accumulates in fp32 (amp_safe contract: bf16 AMP
                # feeds reduced-precision logits straight in; XLA fuses
                # the cast into the reduction, nothing materializes)
                target = target * (1.0 - eps) + F.mean(
                    F.cast(pred, "float32"), axis=self._axis,
                    keepdims=True) * eps
            loss = lse - target
            loss = _apply_weighting(F, loss, self._weight, sample_weight)
            return self._mean_over_nonbatch(F, loss)
        if not self._from_logits:
            pred = F.log_softmax(pred, axis=self._axis)
        if self._sparse_label:
            loss = -F.pick(pred, label, axis=self._axis, keepdims=True)
            if self._smoothing:
                eps = self._smoothing
                loss = loss * (1.0 - eps) - F.mean(
                    pred, axis=self._axis, keepdims=True) * eps
        else:
            label = _reshape_like(F, label, pred)
            loss = -F.sum(pred * label, axis=self._axis, keepdims=True)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_over_nonbatch(F, loss)


SoftmaxCELoss = SoftmaxCrossEntropyLoss


class KLDivLoss(Loss):
    """ref: loss.py KLDivLoss."""

    def __init__(self, from_logits=True, axis=-1, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._axis = axis

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = F.log_softmax(pred, axis=self._axis)
        loss = label * (F.log(label + 1e-12) - pred)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_over_nonbatch(F, loss)


class HuberLoss(Loss):
    """Smooth L1 above rho (ref: loss.py HuberLoss)."""

    def __init__(self, rho=1.0, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._rho = rho

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.abs(label - pred)
        loss = F.where(loss > self._rho,
                       loss - 0.5 * self._rho,
                       (0.5 / self._rho) * F.square(loss))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_over_nonbatch(F, loss)


class HingeLoss(Loss):
    """max(0, 1 - pred*label) (ref: loss.py HingeLoss)."""

    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.relu(self._margin - pred * label)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_over_nonbatch(F, loss)


class SquaredHingeLoss(Loss):
    """ref: loss.py SquaredHingeLoss."""

    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.square(F.relu(self._margin - pred * label))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_over_nonbatch(F, loss)


class LogisticLoss(Loss):
    """ref: loss.py LogisticLoss."""

    def __init__(self, weight=None, batch_axis=0, label_format="signed",
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        if label_format not in ("signed", "binary"):
            raise MXNetError(f"bad label_format {label_format!r}")
        self._label_format = label_format

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        if self._label_format == "signed":
            label = (label + 1.0) / 2.0
        loss = F.relu(pred) - pred * label + \
            F.Activation(-F.abs(pred), act_type="softrelu")
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_over_nonbatch(F, loss)


class TripletLoss(Loss):
    """ref: loss.py TripletLoss."""

    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, positive, negative, sample_weight=None):
        positive = _reshape_like(F, positive, pred)
        negative = _reshape_like(F, negative, pred)
        axes = tuple(range(1, pred.ndim))
        loss = F.sum(F.square(positive - pred) - F.square(negative - pred),
                     axis=axes)
        loss = F.relu(loss + self._margin)
        return _apply_weighting(F, loss, self._weight, sample_weight)


class CTCLoss(Loss):
    """Connectionist temporal classification (ref: loss.py CTCLoss →
    src/operator/contrib/ctc_loss.cc). Layout TNC like the reference default.
    """

    def __init__(self, layout="NTC", label_layout="NT", weight=None, **kwargs):
        if layout not in ("NTC", "TNC"):
            raise MXNetError(f"bad layout {layout!r}")
        super().__init__(weight, 0, **kwargs)
        self._layout = layout
        self._label_layout = label_layout

    def hybrid_forward(self, F, pred, label, pred_lengths=None,
                       label_lengths=None, sample_weight=None):
        if self._layout == "NTC":
            pred = F.swapaxes(pred, 0, 1)
        if self._label_layout == "TN":
            label = F.swapaxes(label, 0, 1)
        loss = F.CTCLoss(pred, label,
                         use_data_lengths=pred_lengths is not None,
                         use_label_lengths=label_lengths is not None,
                         data_lengths=pred_lengths,
                         label_lengths=label_lengths)
        return _apply_weighting(F, loss, self._weight, sample_weight)


class CosineEmbeddingLoss(Loss):
    """ref: loss.py CosineEmbeddingLoss."""

    def __init__(self, weight=None, batch_axis=0, margin=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, input1, input2, label, sample_weight=None):
        input1 = _reshape_like(F, input1, input2)
        cos = F.sum(input1 * input2, axis=-1) / (
            F.norm(input1, axis=-1) * F.norm(input2, axis=-1) + 1e-12)
        label = label.reshape((-1,))
        loss = F.where(label == 1, 1.0 - cos,
                       F.relu(cos - self._margin))
        return _apply_weighting(F, loss, self._weight, sample_weight)
